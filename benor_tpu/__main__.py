"""CLI driver — the reference's `yarn start` demo plus the science harness.

`python -m benor_tpu` reproduces src/start.ts:6-43: launch 10 nodes with 4
faulty, all-1 inputs, run consensus, print each node's final state.

Subcommands:
  demo   [--backend tpu|express] [-n N] [-f F] ...   the start.ts demo
  sweep  --n N --f-values 0,100,...                  rounds-vs-f curve;
         [--batched --journal J --resume]            with --batched the
         [--trace-out t.json --manifest-out m.json]  sweepscope plane
                                                     adds the durable
                                                     resumable bucket
                                                     journal, Perfetto
                                                     bucket-lifecycle
                                                     spans and the
                                                     kind: sweep_manifest
                                                     document
  coins  --n N --f F                                 private vs common coin
  trace  --n N --f F --out trace.json                flight-recorder round
                                                     history as a Chrome-
                                                     trace/Perfetto file
  audit  --n N --f F [--witness-trials 0,1]          run one witnessed
         [--witness-nodes k] [--audit-out b.json]    config, machine-check
                                                     the Ben-Or invariants
                                                     (benor_tpu/audit.py),
                                                     dump the bundle
  scale  --mesh 1,2,4 [--mode weak|strong]           weak/strong scaling
         [--profile-out scaling.json]                ladders across mesh
                                                     shapes -> pinned-
                                                     schema scaling
                                                     manifest + baseline
                                                     gate (benor_tpu/
                                                     meshscope); exit 2
                                                     on regression
  watch  PATH [--poll 0.2] [--timeout 60]            tail a running
                                                     run's JSON-lines
                                                     file: heartbeats,
                                                     sweep-journal
                                                     bucket records, or
                                                     both interleaved
                                                     (kind-dispatched
                                                     lines, unknown
                                                     kinds passed raw);
                                                     no backend touched
  serve  [--port 8400] [--max-batch-jobs 32]         the async multi-
         [--trace-out trace.json]                    tenant request
                                                     plane (benor_tpu/
                                                     serve): HTTP+SSE
                                                     job API over the
                                                     warm batched
                                                     executor pool;
                                                     --trace-out arms
                                                     servescope spans
  load   [--clients 1000] [--url http://...]         drive concurrent
         [--profile-out serve.json]                  SSE clients against
         [--trace-out trace.json]                    the serve plane ->
                                                     pinned-schema serve
                                                     manifest (v2: per-
                                                     stage p50/p99 +
                                                     attribution) +
                                                     baseline gate (SERVE_
                                                     BASELINE.json);
                                                     exit 2 on
                                                     regression
  preset NAME                                        a BASELINE.json config
  lint   [--format json|text] [--root DIR]           benorlint static
                                                     analysis over the
                                                     package tree
                                                     (benor_tpu/analysis);
                                                     exit 2 on findings

Observability: `--record` (sweep) fills the on-device flight recorder;
`--metrics-out PATH` (sweep/coins/trace/audit/lint) dumps the unified
metrics registry (JSON-lines, or Prometheus textfile with a .prom
extension).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def _mesh_sizes(spec: str):
    """argparse type= for --mesh: '1,2,4' -> [1, 2, 4], rejecting
    malformed rungs with a usage error instead of a raw ValueError
    traceback (the value is consumed twice: the pre-dispatch
    device-count widening in main() and the ladder itself in _scale)."""
    import argparse
    try:
        sizes = [int(x) for x in spec.split(",")]
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--mesh expects comma-separated device counts, got {spec!r}")
    if not sizes or any(s < 1 for s in sizes):
        raise argparse.ArgumentTypeError(
            f"--mesh rungs must be >= 1, got {spec!r}")
    return sizes


def _honor_platform_env() -> None:
    """Make ``JAX_PLATFORMS=cpu python -m benor_tpu ...`` actually work:
    the axon TPU plugin overrides the env var at import time (and then
    hangs if the chip is unreachable), so re-assert the user's explicit
    choice via the config API, which wins."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


#: Set when the accelerator was unreachable and the run fell back to CPU;
#: subcommands weave it into their own output (a bare stdout line here
#: would corrupt machine-readable outputs like _preset's pure JSON).
FELL_BACK = False


def _ensure_live_backend(retries: int = 2, timeout_s: float = 120.0) -> None:
    """Never hang a CLI run on an unreachable chip.

    The AXON plugin's specific failure mode is an INDEFINITE hang at
    backend init — so the guard engages only when that plugin is selected
    (any other platform, including a plain TPU machine or an explicit cpu
    pin, skips the probe and pays zero overhead).  Probes via the shared
    helper (the same machinery bench.py's acquire_platform uses, with a
    shorter interactive budget — 2 x 120 s covers the known slow-init
    window) and falls back to CPU if the chip never comes up."""
    global FELL_BACK
    from .utils.backend import probe_with_retries

    plat_env = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if "axon" not in plat_env:
        if plat_env:
            return              # an explicit non-axon pin (cpu, tpu, ...)
        # Env unset: the axon plugin self-registers as the ambient default
        # backend when installed, so the hang-at-init risk is identical to
        # an explicit JAX_PLATFORMS=axon.  find_spec does not import the
        # plugin (importing is what can hang).
        import importlib.util
        if importlib.util.find_spec("axon") is None:
            return
    plat = probe_with_retries(
        retries, timeout_s, backoff_s=10.0,
        log=lambda s: print(f"probe: {s}", file=sys.stderr, flush=True))
    if plat:
        return                          # backend is live
    print("warning: accelerator backend unreachable; falling back to CPU",
          file=sys.stderr, flush=True)
    FELL_BACK = True
    import jax
    jax.config.update("jax_platforms", "cpu")


def _demo(args) -> int:
    from .api import get_nodes_state, launch_network, start_consensus
    n, f = args.n, args.f
    # start.ts:25-29 — the reference refuses F > N/2 in the demo driver
    if f > n / 2:
        print("Too many faulty nodes", file=sys.stderr)
        return 1
    initial = [1] * n                      # start.ts:9-20: all-1 inputs
    faulty = [True] * f + [False] * (n - f)
    net = launch_network(n, f, initial, faulty, backend=args.backend,
                         max_rounds=args.max_rounds, seed=args.seed)
    start_consensus(net)
    for i, st in enumerate(get_nodes_state(net)):
        print(f"node {i}: {st}")
    return 0


def _add_pallas_arg(sub) -> None:
    """ONE definition of the --pallas option for every subparser that
    runs the compute path (sweep, coins) — mirrors FLAGSHIP_FLAGS'
    single-definition rationale."""
    sub.add_argument("--pallas", choices=("auto", "on", "off"),
                     default="auto",
                     help="fused pallas flagship path (auto: on for "
                          "accelerator backends, off on CPU)")


def _add_obs_args(sub, record: bool = True) -> None:
    """ONE definition of the observability options (flight recorder +
    metrics export) for every compute subcommand."""
    if record:
        sub.add_argument("--record", action="store_true",
                         help="fill the on-device flight recorder "
                              "(SimConfig.record): per-round "
                              "decided/killed/value-histogram/coin/"
                              "margin telemetry with no demotion of the "
                              "fused pallas path (unlike debug=True)")
    sub.add_argument("--metrics-out", metavar="PATH",
                     help="write the unified metrics registry "
                          "(utils/metrics.py: timers, compile and probe "
                          "counters) as JSON-lines on exit; .prom "
                          "extension switches to Prometheus textfile "
                          "format")


def _export_metrics(path) -> None:
    if not path:
        return
    from .utils import metrics
    if str(path).endswith(".prom"):
        n = metrics.export_prometheus(path)
    else:
        n = metrics.export_jsonl(path)
    print(f"wrote {n} metrics records to {path}", file=sys.stderr,
          flush=True)


def _pallas_flags(choice: str) -> dict:
    """--pallas plumbing: 'auto' engages the fused flagship path exactly
    when results.py's accelerator-scale studies do (on for accelerator
    backends, off on CPU where interpret-mode pallas would dominate);
    'on' forces it (CPU runs use the interpreter — correct, slow); 'off'
    pins the plain XLA path.  Ineligible configs (biased scheduler, the
    exact-table regime) ignore the flags silently, like everywhere else.
    """
    from .results import FLAGSHIP_FLAGS, _flagship_flags
    if choice == "on":
        return dict(FLAGSHIP_FLAGS)
    if choice == "off":
        return {}
    return _flagship_flags()


def _sweep(args) -> int:
    from .config import SimConfig
    from .sweep import rounds_vs_f, run_point, save_points
    f_values = [int(x) for x in args.f_values.split(",")]
    flags = _pallas_flags(args.pallas)
    cfg = SimConfig(n_nodes=args.n, n_faulty=0, trials=args.trials,
                    max_rounds=args.max_rounds, delivery="quorum",
                    scheduler=args.scheduler, coin_mode=args.coin,
                    fault_model=args.fault_model, seed=args.seed,
                    record=args.record,
                    heartbeat_rounds=args.heartbeat_rounds, **flags)
    if args.heartbeat_rounds and not args.batched:
        # the per-point path runs each point as one uninterrupted
        # compiled loop — there is no boundary to beat at; a silent
        # no-op would fake live progress (the house rule)
        print("warning: --heartbeat-rounds only publishes on the "
              "batched engine (per bucket); add --batched, or use "
              "`trace`/poll_rounds for per-round liveness",
              file=sys.stderr)
    if not args.batched and (args.journal or args.resume
                             or args.trace_out or args.manifest_out
                             or args.pipeline):
        # sweepscope instruments the BUCKET lifecycle; the per-point
        # path has no buckets — a silent no-op would fake durability/
        # tracing (the same house rule as --heartbeat-rounds)
        print("warning: --journal/--resume/--trace-out/--manifest-out/"
              "--pipeline instrument the batched engine's buckets; "
              "add --batched", file=sys.stderr)
    if args.resume and not args.journal:
        print("sweep: --resume requires --journal (the journal is the "
              "resume substrate)", file=sys.stderr)
        return 1
    if args.trace_out and args.batched:
        from .utils.metrics import SPANS
        SPANS.enable()
    journal_kw = dict(journal_path=args.journal, resume=args.resume,
                      pipeline=args.pipeline)
    mode = "balanced/no-crash" if args.balanced else "iid/crash"
    fb = " [cpu fallback]" if FELL_BACK else ""
    # banner reports the compute path actually taken, not the request:
    # ineligible configs (sub-CF-regime quorums, biased scheduler)
    # silently ignore the flags.  Evaluated PER f VALUE — the pallas
    # predicates gate on the quorum N - f, so a sweep can cross the
    # CF-regime boundary mid-curve (larger f => smaller quorum) and a
    # single n_faulty=0 probe would over-claim for those points.
    from .ops.tally import pallas_round_active, pallas_stream_active

    def _engaged(c):
        return pallas_round_active(c) or pallas_stream_active(c)

    eng = [_engaged(cfg.replace(n_faulty=int(f))) for f in f_values]
    pallas_note = (", pallas" if eng and all(eng)
                   else ", pallas (where eligible)" if any(eng) else "")
    print(f"rounds-vs-f sweep: N={args.n}, trials={args.trials}, "
          f"scheduler={args.scheduler}, coin={args.coin}, "
          f"faults={args.fault_model}, inputs={mode}"
          f"{pallas_note}{fb}")
    t0 = time.perf_counter()
    if args.balanced:
        # the science regime: balanced inputs, F purely a protocol
        # parameter (crash-pinned faults make every tally the deterministic
        # full-population draw and the curve degenerates — see RESULTS.md).
        # Under 'byzantine'/'equivocate' the F lanes are LIVE adversaries,
        # so they are marked (not crashed) rather than zeroed.
        from .state import FaultSpec
        from .sweep import balanced_inputs, run_curve_batched
        bal = balanced_inputs(args.trials, args.n)

        def faults_for(c):
            if c.fault_model in ("byzantine", "equivocate"):
                return FaultSpec.first_f(c)
            return FaultSpec.none(args.trials, args.n)

        if args.batched:
            cb = run_curve_batched(cfg, f_values, initial_values=bal,
                                   faults_for=faults_for, verbose=True,
                                   heartbeat_path=args.heartbeat_out,
                                   **journal_kw)
            points = cb.points
        else:
            points = []
            for f in f_values:
                cfg_f = cfg.replace(n_faulty=int(f))
                points.append(run_point(cfg_f, initial_values=bal,
                                        faults=faults_for(cfg_f)))
        for pt in points:
            print(f"  f={pt.n_faulty}: mean_k={pt.mean_k:.2f} "
                  f"decided={pt.decided_frac:.3f} "
                  f"disagree={pt.disagree_frac:.3f} "
                  f"{pt.trials_per_sec:.1f} trials/s", flush=True)
    elif args.batched:
        from .sweep import run_curve_batched
        cb = run_curve_batched(cfg, f_values, verbose=True,
                               heartbeat_path=args.heartbeat_out,
                               **journal_kw)
        points = cb.points
        for pt in points:
            print(f"  f={pt.n_faulty}: mean_k={pt.mean_k:.2f} "
                  f"decided={pt.decided_frac:.3f} "
                  f"{pt.trials_per_sec:.1f} trials/s", flush=True)
    else:
        points = rounds_vs_f(cfg, f_values)
    from .utils.metrics import REGISTRY
    REGISTRY.timer("cli.sweep").record(time.perf_counter() - t0)
    if args.batched and args.manifest_out:
        from .sweepscope import build_sweep_manifest, save_sweep_manifest
        try:
            save_sweep_manifest(args.manifest_out,
                                build_sweep_manifest(cb, cfg))
            print(f"wrote sweep manifest to {args.manifest_out}",
                  file=sys.stderr)
        except ValueError as e:
            # a resumed curve's stage clocks price the original run —
            # the builder refuses; say so instead of writing a lie
            print(f"sweep: no manifest written: {e}", file=sys.stderr)
    if args.batched and args.trace_out:
        from .utils.metrics import export_chrome_trace
        n_ev = export_chrome_trace(args.trace_out, spans=True)
        print(f"wrote {n_ev} trace events to {args.trace_out} "
              f"(open in ui.perfetto.dev)", file=sys.stderr)
    if args.record:
        # recorder-derived per-point science: round history is in each
        # point (SweepPoint.round_history; --out JSON carries the rows)
        from .utils.metrics import round_history_summary
        for pt in points:
            s = round_history_summary(pt.round_history)
            print(f"  f={pt.n_faulty}: quiescence_round="
                  f"{s['rounds_to_quiescence']} "
                  f"decide_velocity={s['decide_velocity']}", flush=True)
    if args.out:
        save_points(args.out, points)
        print(f"wrote {args.out}")
    _export_metrics(args.metrics_out)
    return 0


def _trace(args) -> int:
    """Run ONE recorded config and export a Chrome-trace/Perfetto file:
    every protocol round as a trace slice (its telemetry row in args)
    alongside the registry's host-side timer spans."""
    from .config import SimConfig
    from .state import FaultSpec
    from .sweep import balanced_inputs, run_point
    from .utils import metrics
    from .utils.tracing import timed

    cfg = SimConfig(n_nodes=args.n, n_faulty=args.f, trials=args.trials,
                    max_rounds=args.max_rounds, delivery="quorum",
                    scheduler=args.scheduler, coin_mode=args.coin,
                    fault_model=args.fault_model, seed=args.seed,
                    record=True, **_pallas_flags(args.pallas))
    with timed("trace.run"):
        if args.balanced:
            faults = (FaultSpec.first_f(cfg)
                      if cfg.fault_model in ("byzantine", "equivocate")
                      else FaultSpec.none(args.trials, args.n))
            pt = run_point(cfg, initial_values=balanced_inputs(
                args.trials, args.n), faults=faults)
        else:
            pt = run_point(cfg)
    summ = metrics.round_history_summary(pt.round_history)
    n_ev = metrics.export_chrome_trace(
        args.out, round_history=pt.round_history,
        rounds_label=f"benor N={args.n} f={args.f}")
    fb = " [cpu fallback]" if FELL_BACK else ""
    print(f"rounds={pt.rounds_executed} decided={pt.decided_frac:.3f} "
          f"mean_k={pt.mean_k:.2f} "
          f"quiescence_round={summ['rounds_to_quiescence']}{fb}")
    print(f"wrote {n_ev} trace events to {args.out} "
          f"(open in https://ui.perfetto.dev or chrome://tracing)")
    _export_metrics(args.metrics_out)
    return 0


def _audit(args) -> int:
    """Run ONE witnessed config and machine-check the Ben-Or invariants:
    prints the audit verdict (pinpointed violations with trial/round/node
    ids and tallies), optionally dumps the JSON witness bundle, and feeds
    the audit.* counters of the unified metrics registry.  Exit code 0 =
    clean, 2 = violations found (so CI can gate on it)."""
    from .audit import audit_point, default_witness_overrides, save_bundle
    from .config import SimConfig
    from .state import FaultSpec
    from .sweep import balanced_inputs

    dflt = default_witness_overrides(args.trials, args.n)
    wt = (tuple(int(x) for x in args.witness_trials.split(","))
          if args.witness_trials else dflt["witness_trials"])
    wk = args.witness_nodes or dflt["witness_nodes"]
    cfg = SimConfig(n_nodes=args.n, n_faulty=args.f, trials=args.trials,
                    max_rounds=args.max_rounds, delivery="quorum",
                    scheduler=args.scheduler, coin_mode=args.coin,
                    fault_model=args.fault_model, seed=args.seed,
                    witness_trials=wt, witness_nodes=wk,
                    **_pallas_flags(args.pallas))
    initial = faults = unanimous = None
    if args.balanced:
        initial = balanced_inputs(args.trials, args.n)
        if cfg.fault_model not in ("byzantine", "equivocate"):
            faults = FaultSpec.none(args.trials, args.n)
    if args.unanimous is not None:
        initial = np.full((args.trials, args.n), args.unanimous, np.int8)
        unanimous = args.unanimous
    report, bundle = audit_point(cfg, initial_values=initial,
                                 faults=faults, unanimous=unanimous,
                                 label=f"cli N={args.n} f={args.f}")
    fb = " [cpu fallback]" if FELL_BACK else ""
    print(f"watched trials={[int(t) for t in bundle.trial_ids]} "
          f"nodes={[int(i) for i in bundle.node_ids]}{fb}")
    print(report.summary())
    for v in report.violations[:args.max_violations]:
        print(f"  [{v.invariant}] {v.message}")
    if len(report.violations) > args.max_violations:
        print(f"  ... {len(report.violations) - args.max_violations} more "
              f"(see --audit-out)")
    if args.audit_out:
        save_bundle(args.audit_out, bundle, report)
        print(f"wrote witness bundle to {args.audit_out}")
    _export_metrics(args.metrics_out)
    return 0 if report.ok else 2


def _coins(args) -> int:
    from .config import SimConfig
    from .state import FaultSpec
    from .sweep import balanced_inputs, coin_comparison, run_point
    cfg = SimConfig(n_nodes=args.n, n_faulty=args.f, trials=args.trials,
                    max_rounds=args.max_rounds, seed=args.seed,
                    **_pallas_flags(args.pallas))
    res = coin_comparison(cfg)
    for mode, pts in res.items():
        p = pts[0]
        print(f"{mode}: decided={p.decided_frac:.3f} mean_k={p.mean_k:.2f}")
    for eps in (args.eps or []):
        wcfg = cfg.replace(coin_mode="weak_common", coin_eps=eps,
                           scheduler="adversarial", delivery="quorum")
        p = run_point(wcfg, initial_values=balanced_inputs(args.trials,
                                                           args.n),
                      faults=FaultSpec.none(args.trials, args.n))
        print(f"weak_common(eps={eps}): decided={p.decided_frac:.3f} "
              f"mean_k={p.mean_k:.2f}")
    _export_metrics(args.metrics_out)
    return 0


def _results(args) -> int:
    from .results import generate
    from .utils.backend import default_scale
    n, trials = args.n, args.trials
    if n is None or trials is None:
        # mirror bench.py's platform-aware defaults (shared constants in
        # utils/backend.py): the full N=1M x 32-trial study set is a TPU
        # workload; a CPU run (explicit pin or unreachable-accelerator
        # fallback) gets the same studies at smoke scale
        import jax
        on_cpu = FELL_BACK or jax.default_backend() == "cpu"
        dn, dt = default_scale(on_cpu)
        n = dn if n is None else n
        trials = dt if trials is None else trials
        if on_cpu:
            print(f"results: CPU backend — defaulting to N={dn:,}, "
                  f"trials={dt} (pass --n/--trials to override)",
                  flush=True)
    generate(out_dir=args.out, n_large=n, trials_large=trials,
             seed=args.seed, presets=not args.no_presets)
    return 0


def _lint(args) -> int:
    """benorlint over the package tree: tracer hygiene, kernel column
    layouts, five-regime config parity (benor_tpu/analysis).  Exit 0 =
    clean, 2 = findings — same CI-gateable convention as `audit`."""
    from .analysis.cli import main as lint_main
    return lint_main(args)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _profile_kernels(args) -> int:
    """kernelscope capture (`profile --kernels`): arm the in-kernel
    stage counters on both fused dispatches (the single-pass kernel and
    the two-kernel plane pipeline), assemble the per-stage/per-tile
    attribution report with the layout-derived predicted bytes
    telescoped against the executables' cost model, emit the
    pinned-schema ``kind: kernel_manifest`` and gate it against the
    committed KERNEL_BASELINE.json (tools/check_kernel_regression.py's
    exact comparator): exit 2 on a kernel-plane regression, 3 never
    (an incomparable baseline is reported and skipped, like the perf
    gate), 0 otherwise."""
    from .kernelscope import (IncomparableKernels, capture_kernels,
                              compare_kernels, load_kernel_manifest,
                              save_kernel_manifest)

    manifest = capture_kernels(n_nodes=args.n, trials=args.trials,
                               max_rounds=args.max_rounds,
                               seed=args.seed,
                               telemetry_path=args.telemetry_out)
    fb = " [cpu fallback]" if FELL_BACK else ""
    if args.format == "json":
        print(json.dumps(manifest, indent=1))
    else:
        sc = manifest["scale"]
        mode = "interpret" if manifest["interpret"] else "compiled"
        print(f"kernelscope: {manifest['platform']} "
              f"({manifest['device_kind']}, {mode}), scale "
              f"N={sc['n_nodes']} T={sc['trials']} "
              f"R<={sc['max_rounds']} seed={sc['seed']}{fb}")
        for name, rep in manifest["kernels"].items():
            pred = rep["predicted_bytes_per_round"]
            print(f"  {name} [{rep['dispatch']}/{rep['counts_mode']}]: "
                  f"rounds={rep['rounds_executed']} "
                  f"pad_waste={rep['pad_waste_frac']} "
                  f"hops/round={rep['plane_hops_per_round']} "
                  f"predicted={pred['total']}B/round "
                  f"measured={rep['measured_bytes_per_round']} "
                  f"ratio={rep['byte_ratio']} "
                  f"bit_equal={rep['bit_equal_off_on']}")
            for stage, blk in rep["stages"].items():
                print(f"    {stage}: {blk['counters']}")
        fvx = manifest.get("fused_vs_xla")
        if fvx:
            print(f"  fused_vs_xla: gap={fvx['gap_bytes']}B "
                  f"(fused {fvx['fused_run_bytes']} vs xla "
                  f"{fvx['xla_run_bytes']}), stage shares "
                  f"{fvx['stage_attribution']}, "
                  f"bit_equal={fvx['bit_equal']}")
    if args.profile_out:
        save_kernel_manifest(args.profile_out, manifest)
        print(f"wrote kernel manifest to {args.profile_out}",
              file=sys.stderr)
    _export_metrics(args.metrics_out)

    baseline_path = args.baseline or os.path.join(_repo_root(),
                                                  "KERNEL_BASELINE.json")
    if args.update_baseline:
        save_kernel_manifest(baseline_path, manifest)
        print(f"re-baselined {baseline_path}", file=sys.stderr)
        return 0
    if not os.path.exists(baseline_path):
        print(f"no baseline at {baseline_path} — capture-only run "
              f"(--update-baseline to create one)", file=sys.stderr)
        return 0
    try:
        findings = compare_kernels(manifest,
                                   load_kernel_manifest(baseline_path))
    except (IncomparableKernels, ValueError) as e:
        print(f"baseline {baseline_path} not comparable: {e}",
              file=sys.stderr)
        return 0
    for f in findings:
        print(f"REGRESSION [{f.kind}]: {f.message}", file=sys.stderr)
    if findings:
        return 2
    print(f"kernel gate: in-band vs {baseline_path}", file=sys.stderr)
    return 0


def _profile(args) -> int:
    """AOT cost/memory observatory (benor_tpu/perfscope): stage-timed
    capture of the five compiled regimes — trace/lower, backend compile,
    first execute, steady-state execute, plus the XLA cost model and
    memory footprint per executable, placed on the device roofline.
    Emits the pinned-schema manifest (--profile-out / --format json),
    optionally wraps the capture in a jax.profiler Perfetto trace
    (--trace-dir, with the metrics registry's counter tracks exported
    next to it), and gates against a committed baseline: exit 2 on an
    out-of-band structural metric, 0 otherwise."""
    if args.kernels:
        return _profile_kernels(args)
    from .perfscope import (IncomparableManifests, build_manifest,
                            capture_all, compare_manifests, load_manifest,
                            missing_regimes, save_manifest)
    from .perfscope.regimes import REGIME_NAMES, default_profile_scale

    scale = default_profile_scale()
    for k, v in (("n_nodes", args.n), ("trials", args.trials),
                 ("max_rounds", args.max_rounds)):
        if v is not None:
            scale[k] = v
    scale["seed"] = args.seed
    regimes = args.regimes.split(",") if args.regimes else None
    if regimes:
        unknown = sorted(set(regimes) - set(REGIME_NAMES))
        if unknown:
            print(f"unknown regimes {unknown}; choose from "
                  f"{list(REGIME_NAMES)}", file=sys.stderr)
            return 1

    import contextlib
    trace_cm = contextlib.nullcontext()
    if args.trace_dir:
        from .utils.tracing import profile_trace
        trace_cm = profile_trace(args.trace_dir)
    with trace_cm as trace_path:
        reports = capture_all(regimes=regimes,
                              steady_reps=args.steady_reps, **scale)
        fvx = None
        if regimes is None:
            # the paired fused-vs-XLA measurement (PR 8) rides every FULL
            # capture; a --regimes subset records an explicit null so the
            # gate sees "not measured", never a stale pass
            from .perfscope.regimes import capture_fused_vs_xla
            fvx = capture_fused_vs_xla(steady_reps=args.steady_reps,
                                       **scale)
    manifest = build_manifest(reports, scale, fused_vs_xla=fvx)
    if args.trace_dir:
        # the XLA trace and the registry's counter tracks side by side:
        # load both files into ui.perfetto.dev for one merged timeline
        from .utils import metrics
        counters = os.path.join(args.trace_dir,
                                "perfscope_counters.trace.json")
        n_ev = metrics.export_chrome_trace(counters)
        print(f"jax.profiler trace in {trace_path} "
              f"(+{n_ev} counter events in {counters})", file=sys.stderr)

    fb = " [cpu fallback]" if FELL_BACK else ""
    if args.format == "json":
        print(json.dumps(manifest, indent=1))
    else:
        print(f"perfscope: {manifest['platform']} "
              f"({manifest['device_kind']}), scale "
              f"N={scale['n_nodes']} T={scale['trials']} "
              f"R<={scale['max_rounds']} seed={scale['seed']}{fb}")
        for r in reports:
            roof = (f"AI={r.arithmetic_intensity} flop/B"
                    if r.arithmetic_intensity is not None else "AI=n/a")
            if r.bound is not None:
                roof += (f", {r.achieved_gbps} GB/s of "
                         f"{r.hbm_peak_gbps} GB/s peak "
                         f"(util {r.hbm_util}) -> {r.bound}-bound")
            print(f"  {r.regime}: lower {r.trace_lower_s * 1e3:.0f}ms "
                  f"compile {r.compile_s * 1e3:.0f}ms "
                  f"first {r.first_execute_s * 1e3:.0f}ms "
                  f"steady {r.steady_execute_s * 1e3:.1f}ms | "
                  f"rounds={r.rounds_executed} "
                  f"flops={r.flops:.3g} bytes={r.bytes_accessed:.3g} "
                  f"peakHBM={r.peak_bytes:,}B | {roof}")
    if args.profile_out:
        save_manifest(args.profile_out, manifest)
        print(f"wrote perf manifest to {args.profile_out}",
              file=sys.stderr)
    _export_metrics(args.metrics_out)

    baseline_path = args.baseline or os.path.join(_repo_root(),
                                                  "PERF_BASELINE.json")
    missing = missing_regimes(manifest)
    if args.update_baseline:
        if missing:
            # a partial baseline would make every later gate pass
            # vacuously: compare_manifests only walks baseline regimes
            print(f"refusing to write a partial baseline (missing "
                  f"{missing}) — a baseline must cover all of "
                  f"{list(REGIME_NAMES)}", file=sys.stderr)
            return 1
        save_manifest(baseline_path, manifest)
        print(f"re-baselined {baseline_path}", file=sys.stderr)
        return 0
    if not os.path.exists(baseline_path):
        print(f"no baseline at {baseline_path} — capture-only run "
              f"(--update-baseline to create one)", file=sys.stderr)
        return 0
    if regimes and missing:
        print(f"partial capture ({sorted(set(regimes))}) — baseline gate "
              f"skipped (a full manifest covers {list(REGIME_NAMES)})",
              file=sys.stderr)
        return 0
    try:
        regressions = compare_manifests(manifest,
                                        load_manifest(baseline_path),
                                        timing_band=args.timing_band)
    except (IncomparableManifests, ValueError) as e:
        print(f"baseline {baseline_path} not comparable: {e}",
              file=sys.stderr)
        return 0
    for reg in regressions:
        print(f"REGRESSION: {reg.message}", file=sys.stderr)
    if regressions:
        return 2
    print(f"perf gate: in-band vs {baseline_path}", file=sys.stderr)
    return 0


def _scale(args) -> int:
    """Scaling-efficiency capture (benor_tpu/meshscope/scaling.py): run
    weak-/strong-scaling ladders of the sharded regime across mesh
    shapes, emit the pinned-schema ``kind: scaling_manifest`` document
    (tools/scaling_manifest_schema.json) with per-shape throughput,
    efficiency vs the 1-device rung and the straggler ratio, and gate it
    against the committed SCALING_BASELINE.json
    (meshscope/scalegate.py): exit 2 on a scaling regression or
    straggler trip, 0 otherwise."""
    from .meshscope import (IncomparableScaling, build_scaling_manifest,
                            compare_scaling, load_scaling_manifest,
                            run_scaling_ladder, save_scaling_manifest)
    from .meshscope.scaling import parse_mesh_2d

    sizes = args.mesh
    try:
        shapes_2d = [parse_mesh_2d(s) for s in (args.mesh_2d or [])]
    except ValueError as e:
        print(f"scale: {e}", file=sys.stderr)
        return 1
    need = max([max(sizes)] + [t * n for t, n in shapes_2d])
    import jax
    have = len(jax.devices())
    if need > have:
        print(f"mesh ladder needs {need} devices, have {have} — "
              f"on CPU set XLA_FLAGS=--xla_force_host_platform_"
              f"device_count={need} (before jax initializes)",
              file=sys.stderr)
        return 1
    rows, scale = run_scaling_ladder(
        sizes, mode=args.mode, axis=args.axis, n_nodes=args.n,
        trials=args.trials, max_rounds=args.max_rounds, seed=args.seed,
        reps=args.reps, verbose=args.format == "text",
        mesh_2d=shapes_2d)
    manifest = build_scaling_manifest(rows, args.mode, args.axis, scale)
    fb = " [cpu fallback]" if FELL_BACK else ""
    if args.format == "json":
        print(json.dumps(manifest, indent=1))
    else:
        print(f"meshscope scale: {manifest['platform']} "
              f"({manifest['device_kind']}), {args.mode} ladder on the "
              f"{args.axis} axis, rungs {sizes}{fb}")
        for r in rows:
            ts, ns = r["mesh_shape"]
            print(f"  mesh=({ts},{ns}) d={r['devices']}: "
                  f"N={r['n_nodes']} "
                  f"T={r['trials']} rounds={r['rounds']} "
                  f"{r['node_rounds_per_sec']:.4g} node-rounds/s "
                  f"efficiency={r['efficiency']} "
                  f"straggler={r['straggler_ratio']:.2f}")
    if args.profile_out:
        save_scaling_manifest(args.profile_out, manifest)
        print(f"wrote scaling manifest to {args.profile_out}",
              file=sys.stderr)
    _export_metrics(args.metrics_out)

    baseline_path = args.baseline or os.path.join(_repo_root(),
                                                  "SCALING_BASELINE.json")
    if args.update_baseline:
        save_scaling_manifest(baseline_path, manifest)
        print(f"re-baselined {baseline_path}", file=sys.stderr)
        return 0
    if not os.path.exists(baseline_path):
        print(f"no baseline at {baseline_path} — capture-only run "
              f"(--update-baseline to create one)", file=sys.stderr)
        return 0
    try:
        findings = compare_scaling(manifest,
                                   load_scaling_manifest(baseline_path))
    except (IncomparableScaling, ValueError) as e:
        print(f"baseline {baseline_path} not comparable: {e}",
              file=sys.stderr)
        return 0
    for f in findings:
        print(f"REGRESSION: {f.message}", file=sys.stderr)
    if findings:
        return 2
    print(f"scaling gate: in-band vs {baseline_path}", file=sys.stderr)
    return 0


def _serve(args) -> int:
    """The benor-serve request plane (benor_tpu/serve/server.py): accept
    concurrent simulate/sweep/trajectory/audit jobs over HTTP, coalesce
    them into continuous batches on the warm AOT executor pool, stream
    round-history/witness rows back as server-sent events.  Runs until
    interrupted."""
    from .serve import run_server
    return run_server(host=args.host, port=args.port,
                      max_batch_jobs=args.max_batch_jobs,
                      trace_out=args.trace_out)


def _load(args) -> int:
    """Load-test the serve plane (benor_tpu/serve/loadgen.py): drive
    --clients concurrent SSE clients (against --url, or an in-process
    server when omitted), print the pinned-schema serve manifest
    (p50/p99 latency, saturation throughput, jobs-per-launch
    coalescing) and gate it against the committed SERVE_BASELINE.json
    (serve/gate.py): exit 2 on a serving regression, 0 otherwise."""
    from .serve import IncomparableServe, compare_serve, run_load

    job = None
    if args.job:
        job = json.loads(args.job)
    if args.trace_out:
        from .utils.metrics import SPANS
        SPANS.enable()
    manifest = run_load(url=args.url, clients=args.clients, job=job,
                        timeout=args.timeout, ramp_s=args.ramp,
                        max_batch_jobs=args.max_batch_jobs)
    if args.trace_out:
        from .utils.metrics import export_chrome_trace
        n = export_chrome_trace(args.trace_out, spans=True)
        print(f"wrote {n} trace events to {args.trace_out} "
              f"(open in ui.perfetto.dev)", file=sys.stderr)
    fb = " [cpu fallback]" if FELL_BACK else ""
    if args.format == "json":
        print(json.dumps(manifest, indent=1))
    else:
        lat = manifest["latency_ms"]
        attr = manifest["attribution"]
        print(f"benor-serve load: {manifest['platform']} "
              f"({manifest['device_kind']}), {manifest['clients']} "
              f"concurrent clients{fb}")
        print(f"  jobs {manifest['jobs_completed']}"
              f"/{manifest['jobs_submitted']} "
              f"(errors {manifest['errors']}) in "
              f"{manifest['duration_s']:.2f}s = "
              f"{manifest['throughput_jobs_per_sec']:.1f} jobs/s")
        print(f"  latency p50={lat['p50']:.0f}ms p99={lat['p99']:.0f}ms; "
              f"coalescing {manifest['jobs_per_launch']:.1f} "
              f"jobs/launch over {manifest['launches']} launches")
        stages = manifest["stages"]
        print("  stages p99 (ms): "
              + " ".join(f"{s}={stages[s]['p99']:.0f}"
                         for s in ("queue_wait", "batch_assemble",
                                   "launch", "stream_out")))
        print(f"  attribution: {attr['stage_mean_sum_ms']:.0f}ms of "
              f"{attr['client_mean_ms']:.0f}ms client mean attributed "
              f"(coverage {attr['coverage']:.2f}, "
              f"{'ok' if attr['ok'] else 'INCOMPLETE'})")
    if args.profile_out:
        with open(args.profile_out, "w") as fh:
            json.dump(manifest, fh, indent=1)
        print(f"wrote serve manifest to {args.profile_out}",
              file=sys.stderr)
    _export_metrics(args.metrics_out)

    baseline_path = args.baseline or os.path.join(_repo_root(),
                                                  "SERVE_BASELINE.json")
    if args.update_baseline:
        with open(baseline_path, "w") as fh:
            json.dump(manifest, fh, indent=1)
        print(f"re-baselined {baseline_path}", file=sys.stderr)
        return 0
    if not os.path.exists(baseline_path):
        print(f"no baseline at {baseline_path} — capture-only run "
              f"(--update-baseline to create one)", file=sys.stderr)
        return 0
    try:
        with open(baseline_path) as fh:
            base = json.load(fh)
        findings = compare_serve(manifest, base,
                                 timing_band=args.timing_band)
    except (IncomparableServe, ValueError) as e:
        print(f"baseline {baseline_path} not comparable: {e}",
              file=sys.stderr)
        return 0
    for f in findings:
        print(f"REGRESSION: {f.message}", file=sys.stderr)
    if findings:
        return 2
    print(f"serve gate: in-band vs {baseline_path}", file=sys.stderr)
    return 0


def _format_heartbeat(rec) -> str:
    bits = [f"[{rec.get('label', '?')}]"]
    if rec.get("round") is not None:
        bits.append(f"round={rec['round']}/{rec.get('max_rounds')}")
    if rec.get("points_done") is not None:
        bits.append(f"points={rec['points_done']}"
                    f"/{rec.get('points_total')}")
    if rec.get("rounds_per_sec") is not None:
        bits.append(f"{rec['rounds_per_sec']:.3g} rounds/s")
    if rec.get("decided_frac") is not None:
        bits.append(f"decided={rec['decided_frac']:.3f}")
    if rec.get("eta_s") is not None:
        bits.append(f"eta={rec['eta_s']:.1f}s")
    if rec.get("progress") is not None:
        bits.append(f"{100 * rec['progress']:.0f}%")
    if rec.get("done"):
        bits.append("DONE")
    return " ".join(bits)


def _format_sweep_bucket(rec) -> str:
    """One sweep-journal bucket record (sweepscope/journal.py) as a
    watch line: which bucket landed, its stage wall clocks, its
    compile count."""
    idx = rec.get("point_indices") or []
    bits = [f"[{rec.get('label', 'sweep')}-journal]",
            f"bucket {rec.get('bucket_index')}",
            f"({rec.get('bucket_kind')}, {len(idx)} pt"
            f"{'s' if len(idx) != 1 else ''})"]
    for stage in ("prepare_s", "compile_s", "run_s", "fetch_s"):
        v = rec.get(stage)
        if isinstance(v, (int, float)):
            bits.append(f"{stage[:-2]}={v:.2f}s")
    if rec.get("compile_count") is not None:
        bits.append(f"compiles={rec['compile_count']}")
    return " ".join(bits)


def _format_kernel_telem(rec) -> str:
    """One kernelscope telemetry record (kernelscope/report.py) as a
    watch line: which kernel, its round count, the pad-waste fraction
    and the per-stage counter totals — compact; the per-tile detail
    lives in the kernel manifest."""
    bits = [f"[{rec.get('label', 'kernelscope')}]",
            f"kernel={rec.get('kernel')}",
            f"rounds={rec.get('rounds')}"]
    if rec.get("pad_waste_frac") is not None:
        bits.append(f"pad_waste={rec['pad_waste_frac']:.3f}")
    totals = rec.get("stage_totals") or {}
    for stage in sorted(totals):
        c = totals[stage]
        bits.append(f"{stage}(hist={c.get('hist_visits')} "
                    f"quorum={c.get('quorum_passes')} "
                    f"coins={c.get('coin_draws')} "
                    f"hops={c.get('plane_hops')})")
    return " ".join(bits)


def _format_sweep_done(rec) -> str:
    bits = [f"[{rec.get('label', 'sweep')}-journal]",
            f"sweep complete: {rec.get('points_total')} points / "
            f"{rec.get('n_buckets')} buckets"]
    if rec.get("buckets_reused"):
        bits.append(f"({rec['buckets_reused']} journal-restored)")
    if rec.get("overlap_headroom_s") is not None:
        bits.append(f"overlap_headroom={rec['overlap_headroom_s']:.2f}s")
    bits.append("DONE")
    return " ".join(bits)


def _format_atlas_probe(rec) -> str:
    """One atlas search probe (atlas/search.py) as a watch line: which
    axis and generation, the probed value and its verdict."""
    bits = [f"[atlas:{rec.get('axis')}]",
            f"gen={rec.get('generation')}",
            f"{rec.get('axis')}={rec.get('value')}",
            f"verdict={rec.get('verdict')}"]
    if isinstance(rec.get("stall_frac"), (int, float)):
        bits.append(f"stall={rec['stall_frac']:.3f}")
    if rec.get("rounds_executed") is not None:
        bits.append(f"rounds={rec['rounds_executed']}")
    return " ".join(bits)


def _format_atlas_cliff(rec) -> str:
    """One cliff-refinement step: the bracketing interval after this
    generation's bisection, flagged when at the pinned tolerance."""
    bits = [f"[atlas:{rec.get('axis')}]"]
    if rec.get("generation") is not None:
        bits.append(f"gen={rec['generation']}")
    bits.append(f"cliff [{rec.get('lo')}, {rec.get('hi')}]")
    if isinstance(rec.get("width"), (int, float)):
        bits.append(f"width={rec['width']:g}")
    bits.append(f"{rec.get('lo_verdict')}->{rec.get('hi_verdict')}")
    if rec.get("converged"):
        bits.append("CONVERGED")
    return " ".join(bits)


def _format_atlas_heatmap(rec) -> str:
    """One 2D-slice heatmap document, rendered with the backend-free
    shade grid from benor_tpu/atlas/__init__.py."""
    from .atlas import render_heatmap
    try:
        return render_heatmap(rec)
    except (KeyError, TypeError, ValueError):
        # a torn/foreign heatmap record: surface it raw, never crash
        # the tail
        return json.dumps(rec, sort_keys=True)


def _watch(args) -> int:
    """Tail a running run's JSON-lines progress file (heartbeats from
    meshscope, sweep-journal bucket records from sweepscope, or one
    file carrying both interleaved): print each new record as it is
    appended — kind-dispatched formatting, unknown kinds passed through
    raw (never dropped, never a crash — a partial trailing line is
    simply re-read on the next poll), stopping on any ``done: true``
    record, on --no-follow after one pass, or after --timeout seconds
    of silence.  Pure host-side tail: never touches a JAX backend.
    Exit 0 once at least one record was seen, 1 on a silent timeout
    (nothing to watch)."""
    import json as _json

    from .atlas import CLIFF_KIND, HEATMAP_KIND, PROBE_KIND
    from .kernelscope.report import KERNEL_TELEM_KIND
    from .meshscope.heartbeat import HEARTBEAT_KIND, tail_records
    from .sweepscope.journal import BUCKET_KIND, DONE_KIND

    formatters = {HEARTBEAT_KIND: _format_heartbeat,
                  BUCKET_KIND: _format_sweep_bucket,
                  DONE_KIND: _format_sweep_done,
                  KERNEL_TELEM_KIND: _format_kernel_telem,
                  PROBE_KIND: _format_atlas_probe,
                  CLIFF_KIND: _format_atlas_cliff,
                  HEATMAP_KIND: _format_atlas_heatmap}
    seen = 0
    for rec in tail_records(args.path, poll_s=args.poll,
                            timeout_s=args.timeout,
                            follow=not args.no_follow,
                            stop_when_done=not args.keep_going):
        seen += 1
        fmt = formatters.get(rec.get("kind"))
        if fmt is not None:
            print(fmt(rec), flush=True)
        else:
            # unknown kind: pass the record through raw — a new
            # producer's records surface verbatim instead of vanishing
            print(_json.dumps(rec.get("raw", rec), sort_keys=True),
                  flush=True)
        if args.max_updates and seen >= args.max_updates:
            break
    if not seen:
        print(f"watch: no records in {args.path} within "
              f"{args.timeout}s (is the run armed with a heartbeat/"
              f"journal path?)",
              file=sys.stderr)
        return 1
    return 0


def _atlas(args) -> int:
    """The phase-boundary observatory (benor_tpu/atlas): adaptive cliff
    search over the scenario grid -> pinned-schema atlas manifest +
    cliff-drift gate vs the committed ATLAS_BASELINE.json.  Exit 2 on
    drift findings; an incomparable baseline (platform/scale mismatch)
    is a printed note, not a failure — recapture or re-baseline."""
    from .atlas import gate as agate
    from .atlas import manifest as amanifest
    from .atlas import render_heatmap
    from .atlas import search as asearch

    verbose = args.format == "text"
    if args.out_dir:
        os.makedirs(args.out_dir, exist_ok=True)

    if args.heatmap:
        from .config import SimConfig
        spec_a, spec_b = args.heatmap.split(",", 1)
        cfg = SimConfig(n_nodes=args.n, n_faulty=args.f,
                        trials=args.trials, max_rounds=args.max_rounds,
                        delivery="all", path="histogram",
                        seed=args.seed)
        doc = asearch.heatmap_slice(cfg, spec_a, spec_b,
                                    na=args.coarse, nb=args.coarse,
                                    journal_path=args.journal,
                                    verbose=verbose)
        asearch.export_heatmap(doc, json_path=args.profile_out,
                               trace_path=args.trace_out)
        if args.format == "json":
            print(json.dumps(doc, indent=1, sort_keys=True))
        else:
            print(render_heatmap(doc))
            print(f"  {len(doc['rows'])} probes in {doc['n_buckets']} "
                  f"bucket(s), {doc['compile_count']} compile(s)")
        return 0

    if args.axis:
        from .config import SimConfig
        cfg = SimConfig(n_nodes=args.n, n_faulty=args.f,
                        trials=args.trials, max_rounds=args.max_rounds,
                        delivery="all", path="histogram",
                        seed=args.seed)
        docs = []
        for i, spec in enumerate(args.axis):
            res = asearch.find_cliffs(
                cfg, spec, coarse=args.coarse,
                journal_path=args.journal,
                resume=args.resume or i > 0,
                forensics=not args.no_forensics,
                out_dir=args.out_dir, verbose=verbose)
            d = res.to_dict()
            d["name"] = f"axis{i}"
            docs.append(d)
        manifest = amanifest.build_manifest(docs, scale=args.scale)
    else:
        searches = tuple(s for s in args.searches.split(",") if s)
        manifest = amanifest.capture_atlas(
            searches=searches, scale=args.scale,
            forensics=not args.no_forensics,
            journal_path=args.journal, resume=args.resume,
            out_dir=args.out_dir, verbose=verbose)

    baseline_path = args.baseline or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ATLAS_BASELINE.json")
    if args.update_baseline:
        amanifest.save_manifest(baseline_path, manifest)
        print(f"baseline updated: {baseline_path} "
              f"({manifest['cliff_count']} cliffs, "
              f"{manifest['probe_count']} probes)")
        return 0
    if args.profile_out:
        amanifest.save_manifest(args.profile_out, manifest)
    if args.format == "json":
        print(json.dumps(manifest, indent=1, sort_keys=True))
    else:
        for s in manifest["searches"]:
            print(f"[{s['name']}] {s['spec']}: {s['probe_count']} "
                  f"probes / {len(s['generations'])} generations / "
                  f"{s['compile_count']} compiles")
            for c in s["cliffs"]:
                extra = ""
                if c.get("safety"):
                    extra += (" audit_ok" if c["safety"]["audit_ok"]
                              else f" VIOLATIONS="
                                   f"{c['safety']['n_violations']}")
                if c.get("repro_reproduced") is not None:
                    extra += (" repro_ok" if c["repro_reproduced"]
                              else " REPRO-STALE")
                print(f"  cliff {c['axis']}={c['point']:g} bracket "
                      f"[{c['lo']:g}, {c['hi']:g}] "
                      f"{c['lo_verdict']}->{c['hi_verdict']}{extra}")
    if os.path.exists(baseline_path):
        try:
            findings = agate.compare_atlas(
                manifest, amanifest.load_manifest(baseline_path))
        except (agate.IncomparableAtlas, ValueError) as e:
            print(f"atlas: baseline not comparable ({e}) — skipping "
                  f"the drift gate", file=sys.stderr)
            return 0
        for f in findings:
            print(f"REGRESSION: [{f.metric}] {f.message}")
        if findings:
            return 2
        print(f"atlas: in-band vs {os.path.basename(baseline_path)}")
    return 0


def _replay(args) -> int:
    """Re-execute a ``kind: atlas_repro`` document and pin it
    bit-identically: exit 0 reproduced, 2 verdict/digest mismatch, 1
    unreadable input."""
    from .atlas import repro as arepro

    try:
        doc = arepro.load_repro(args.path)
    except (OSError, ValueError) as e:
        print(f"replay: unreadable repro: {e}", file=sys.stderr)
        return 1
    res = arepro.replay_repro(doc)
    if args.format == "json":
        print(json.dumps(res, indent=1, sort_keys=True))
    else:
        v, e = res["verdict"], res["expected"]
        print(f"replay {os.path.basename(args.path)} "
              f"[{doc.get('label') or 'unlabeled'}]: "
              f"digest {'ok' if res['digest_ok'] else 'MISMATCH'}, "
              f"verdict {v['verdict']} (recorded {e.get('verdict')}) "
              f"rounds={v['rounds_executed']} "
              f"decided={v['decided_frac']:g} -> "
              f"{'REPRODUCED' if res['ok'] else 'NOT REPRODUCED'}")
    return 0 if res["ok"] else 2


def _preset(args) -> int:
    from .sweep import baseline_configs, run_point
    cfgs = baseline_configs()
    if args.name not in cfgs:
        print(f"unknown preset {args.name!r}; choose from "
              f"{sorted(cfgs)}", file=sys.stderr)
        return 1
    pt = run_point(cfgs[args.name])
    d = pt.to_dict()
    if FELL_BACK:
        d["platform_fallback"] = "cpu"   # keep the JSON honest AND valid
    print(json.dumps(d, indent=1))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="benor_tpu")
    sub = ap.add_subparsers(dest="cmd")

    d = sub.add_parser("demo", help="the reference start.ts demo")
    d.add_argument("-n", type=int, default=10)        # start.ts:7
    d.add_argument("-f", type=int, default=4)         # start.ts:8
    d.add_argument("--backend", choices=("tpu", "express", "native"),
                   default="tpu")
    d.add_argument("--max-rounds", type=int, default=32)
    d.add_argument("--seed", type=int, default=0)

    s = sub.add_parser("sweep", help="rounds-vs-f curve")
    s.add_argument("--n", type=int, required=True)
    s.add_argument("--f-values", required=True,
                   help="comma-separated fault counts")
    s.add_argument("--trials", type=int, default=256)
    s.add_argument("--max-rounds", type=int, default=64)
    s.add_argument("--scheduler",
                   choices=("uniform", "biased", "adversarial", "targeted"),
                   default="uniform")
    s.add_argument("--coin", choices=("private", "common"), default="private")
    s.add_argument("--fault-model",
                   choices=("crash", "byzantine", "equivocate"),
                   default="crash")
    s.add_argument("--seed", type=int, default=0)
    _add_pallas_arg(s)
    _add_obs_args(s)
    s.add_argument("--balanced", action="store_true",
                   help="balanced inputs + zero crashes (the multi-round "
                        "science regime; default is the reference-style "
                        "iid-inputs/crash-faults workload)")
    s.add_argument("--batched", action="store_true",
                   help="run the curve through the batched dynamic-F "
                        "engine: one XLA compile per static-shape bucket "
                        "instead of one per f value (bit-identical "
                        "summaries; see sweep.run_curve_batched)")
    s.add_argument("--pipeline", action="store_true",
                   help="with --batched: compile-ahead/execute-behind "
                        "scheduler — bucket k+1's prepare + AOT "
                        "compile overlaps bucket k's device execute "
                        "on a host thread (bit-identical results and "
                        "per-bucket compile counts; the manifest's "
                        "pipeline block reports the headroom "
                        "reclaimed vs the serial overlap model)")
    s.add_argument("--out", help="write points to this JSON file")
    s.add_argument("--heartbeat-out", metavar="PATH",
                   help="with --batched and a heartbeat cadence "
                        "(SimConfig.heartbeat_rounds via "
                        "--heartbeat-rounds), append live-progress "
                        "records here for `python -m benor_tpu watch`")
    s.add_argument("--heartbeat-rounds", type=int, default=0,
                   help="arm the live progress plane at this round "
                        "cadence (0 = off); the batched engine beats "
                        "per bucket")
    s.add_argument("--journal", metavar="PATH",
                   help="with --batched: append one durable JSON-lines "
                        "record per completed bucket (input "
                        "fingerprint, stage wall clocks, per-point "
                        "payloads) — the sweepscope journal `watch` "
                        "tails and --resume restarts from")
    s.add_argument("--resume", action="store_true",
                   help="with --journal: skip every bucket whose "
                        "fingerprint matches a journal record and "
                        "reassemble its points bit-identically from "
                        "disk; only unfinished buckets recompile "
                        "(tampered records rerun, never reuse)")
    s.add_argument("--trace-out", metavar="PATH",
                   help="with --batched: arm sweepscope span tracing "
                        "and write the Perfetto trace (per-bucket "
                        "prepare/compile/execute/fetch stage spans, "
                        "flow-linked to the points each bucket "
                        "carried) here")
    s.add_argument("--manifest-out", metavar="PATH",
                   help="with --batched: write the pinned-schema "
                        "kind: sweep_manifest document (per-bucket "
                        "stage clocks + overlap-headroom attribution; "
                        "tools/sweep_manifest_schema.json, gated by "
                        "tools/check_sweep_regression.py)")

    c = sub.add_parser("coins", help="private vs common coin, adversarial")
    c.add_argument("--n", type=int, default=100)
    c.add_argument("--f", type=int, default=40)  # need F >> sqrt(N)
    c.add_argument("--trials", type=int, default=128)
    c.add_argument("--max-rounds", type=int, default=48)
    c.add_argument("--seed", type=int, default=0)
    _add_pallas_arg(c)
    c.add_argument("--eps", type=float, nargs="*",
                   help="also run weak_common coins at these deviation "
                        "probabilities (0 ~ common, 1 ~ private; the "
                        "termination transition sits at 1 - F/N)")
    _add_obs_args(c, record=False)

    t = sub.add_parser("trace",
                       help="run one recorded config, export a Chrome-"
                            "trace/Perfetto file of its round history")
    t.add_argument("--n", type=int, default=1000)
    t.add_argument("--f", type=int, default=250)
    t.add_argument("--trials", type=int, default=64)
    t.add_argument("--max-rounds", type=int, default=64)
    t.add_argument("--scheduler",
                   choices=("uniform", "biased", "adversarial", "targeted"),
                   default="uniform")
    t.add_argument("--coin", choices=("private", "common", "weak_common"),
                   default="private")
    t.add_argument("--fault-model",
                   choices=("crash", "byzantine", "equivocate"),
                   default="crash")
    t.add_argument("--balanced", action="store_true",
                   help="balanced inputs + zero crashes (live marked "
                        "faults under byzantine/equivocate) — the "
                        "multi-round science regime")
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--out", default="benor_trace.json",
                   help="Chrome-trace output path (default "
                        "benor_trace.json)")
    _add_pallas_arg(t)
    _add_obs_args(t, record=False)   # trace implies --record

    a = sub.add_parser("audit",
                       help="run one witnessed config and machine-check "
                            "the Ben-Or invariants (benor_tpu/audit.py)")
    a.add_argument("--n", type=int, default=100)
    a.add_argument("--f", type=int, default=25)
    a.add_argument("--trials", type=int, default=16)
    a.add_argument("--max-rounds", type=int, default=32)
    a.add_argument("--scheduler",
                   choices=("uniform", "biased", "adversarial", "targeted"),
                   default="uniform")
    a.add_argument("--coin", choices=("private", "common"),
                   default="private")
    a.add_argument("--fault-model",
                   choices=("crash", "byzantine", "equivocate"),
                   default="crash")
    a.add_argument("--balanced", action="store_true",
                   help="balanced inputs + zero crashes (live marked "
                        "faults under byzantine/equivocate) — the regime "
                        "where the safety adversaries bite")
    a.add_argument("--unanimous", type=int, choices=(0, 1), default=None,
                   help="run all-<v> inputs and arm the VALIDITY check "
                        "(any decision != v is a violation)")
    a.add_argument("--seed", type=int, default=0)
    a.add_argument("--witness-trials", default=None,
                   help="comma-separated global trial ids to watch "
                        "(default: the first min(trials, 4))")
    a.add_argument("--witness-nodes", type=int, default=None,
                   help="how many nodes to watch — the first ceil(k/2) + "
                        "last floor(k/2) global ids (default: "
                        "min(n, 16))")
    a.add_argument("--audit-out", metavar="PATH",
                   help="write the witness bundle + audit verdict as one "
                        "JSON document (re-auditable offline via "
                        "audit.load_bundle)")
    a.add_argument("--max-violations", type=int, default=5,
                   help="violations printed before truncating (all land "
                        "in --audit-out)")
    _add_pallas_arg(a)
    _add_obs_args(a, record=False)

    p = sub.add_parser("preset", help="run a BASELINE.json preset config")
    p.add_argument("name")

    li = sub.add_parser("lint",
                        help="benorlint static analysis (tracer hygiene, "
                             "kernel column layouts, five-regime config "
                             "parity); exit 2 on findings")
    li.add_argument("--root", default=None,
                    help="package root to lint (default: the benor_tpu "
                         "package directory)")
    li.add_argument("--format", choices=("text", "json"), default="text",
                    help="report format (json is schema-pinned by "
                         "tools/check_metrics_schema.py)")
    li.add_argument("--out", metavar="PATH",
                    help="write the report to this file instead of stdout")
    _add_obs_args(li, record=False)

    pf = sub.add_parser("profile",
                        help="AOT cost/memory observatory: stage-timed "
                             "capture of the five compiled regimes + "
                             "roofline placement + baseline perf gate "
                             "(benor_tpu/perfscope); exit 2 on "
                             "regression")
    pf.add_argument("--n", type=int, default=None,
                    help="nodes (default: the profile scale — 256 on "
                         "CPU, the bench scale on accelerators)")
    pf.add_argument("--trials", type=int, default=None)
    pf.add_argument("--max-rounds", type=int, default=None)
    pf.add_argument("--seed", type=int, default=0)
    pf.add_argument("--regimes", default=None,
                    help="comma-separated subset of "
                         "traced,fused_pallas,sliced,batched_sweep,"
                         "sharded (default: all five; a subset skips "
                         "the baseline gate)")
    pf.add_argument("--steady-reps", type=int, default=2,
                    help="post-warm-up executions averaged into the "
                         "steady-state timing (default 2)")
    pf.add_argument("--format", choices=("text", "json"), default="text",
                    help="stdout format; json = the pinned-schema "
                         "manifest (tools/perf_report_schema.json)")
    pf.add_argument("--profile-out", metavar="PATH",
                    help="write the manifest to this JSON file")
    pf.add_argument("--baseline", metavar="PATH", default=None,
                    help="baseline manifest to gate against (default: "
                         "the committed PERF_BASELINE.json)")
    pf.add_argument("--update-baseline", action="store_true",
                    help="write this capture as the new baseline "
                         "instead of gating against it")
    pf.add_argument("--timing-band", type=float, default=None,
                    help="also gate the machine-sensitive stage timings "
                         "at this ratio band (off by default; see "
                         "perfscope/baseline.py)")
    pf.add_argument("--trace-dir", metavar="DIR", default=None,
                    help="wrap the capture in a jax.profiler trace "
                         "(TensorBoard/Perfetto) and export the metrics "
                         "registry's counter tracks next to it")
    pf.add_argument("--kernels", action="store_true",
                    help="kernelscope capture instead of the perfscope "
                         "regimes: in-kernel stage counters + "
                         "layout-derived HBM traffic attribution for "
                         "the fused pallas dispatches -> pinned-schema "
                         "kind:kernel_manifest, gated against "
                         "KERNEL_BASELINE.json (exit 2 on regression); "
                         "--baseline/--update-baseline/--profile-out "
                         "apply to the kernel manifest")
    pf.add_argument("--telemetry-out", metavar="PATH", default=None,
                    help="with --kernels: append live kind:"
                         "kernel_telemetry JSON-lines records here "
                         "(`python -m benor_tpu watch` renders them)")
    _add_obs_args(pf, record=False)

    sc = sub.add_parser("scale",
                        help="weak/strong scaling ladders across mesh "
                             "shapes -> pinned-schema scaling manifest "
                             "+ baseline gate (benor_tpu/meshscope); "
                             "exit 2 on scaling regression")
    sc.add_argument("--mesh", default="1,2,4", type=_mesh_sizes,
                    help="comma-separated device counts, one ladder "
                         "rung each; MUST include 1 (efficiency is "
                         "measured vs the single-device rung)")
    sc.add_argument("--mesh-2d", action="append", default=None,
                    metavar="T,N",
                    help="append an explicit 2D (trial_shards, "
                         "node_shards) rung after the 1D ladder, e.g. "
                         "--mesh-2d 2,2 --mesh-2d 2,4; weak mode "
                         "grows BOTH problem axes with their shard "
                         "counts (constant per-shard slab)")
    sc.add_argument("--mode", choices=("weak", "strong"), default="weak",
                    help="weak: the sharded axis's problem size grows "
                         "with the rung; strong: fixed problem spread "
                         "thinner")
    sc.add_argument("--axis", choices=("nodes", "trials"),
                    default="nodes",
                    help="which mesh axis the ladder grows (nodes = "
                         "the ICI psum leg, trials = data parallel)")
    sc.add_argument("--n", type=int, default=None,
                    help="base nodes per rung (default: the CPU-smoke "
                         "scale in meshscope/scaling.py)")
    sc.add_argument("--trials", type=int, default=None)
    sc.add_argument("--max-rounds", type=int, default=None)
    sc.add_argument("--seed", type=int, default=0)
    sc.add_argument("--reps", type=int, default=2,
                    help="steady-state executions averaged per rung")
    sc.add_argument("--format", choices=("text", "json"), default="text")
    sc.add_argument("--profile-out", metavar="PATH",
                    help="write the scaling manifest to this JSON file "
                         "(kind: scaling_manifest, schema-pinned by "
                         "tools/scaling_manifest_schema.json)")
    sc.add_argument("--baseline", metavar="PATH", default=None,
                    help="baseline manifest to gate against (default: "
                         "the committed SCALING_BASELINE.json)")
    sc.add_argument("--update-baseline", action="store_true",
                    help="write this capture as the new baseline "
                         "instead of gating against it")
    _add_obs_args(sc, record=False)

    sv = sub.add_parser("serve",
                        help="the async multi-tenant request plane: "
                             "HTTP+SSE job API coalescing concurrent "
                             "client jobs onto the warm batched "
                             "executor pool (benor_tpu/serve)")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8400,
                    help="listen port (default 8400; 0 = ephemeral)")
    sv.add_argument("--max-batch-jobs", type=int, default=None,
                    help="coalescing ceiling: jobs per executable "
                         "launch (default serve.MAX_BATCH_JOBS, "
                         "rounded up to a power of two)")
    sv.add_argument("--trace-out", metavar="PATH", default=None,
                    help="arm servescope span tracing and write the "
                         "Perfetto trace (request/batch/job stage "
                         "spans, flow-linked) here on shutdown")

    ld = sub.add_parser("load",
                        help="load-test the serve plane: concurrent "
                             "SSE clients -> pinned-schema serve "
                             "manifest + baseline gate "
                             "(SERVE_BASELINE.json); exit 2 on "
                             "regression")
    ld.add_argument("--clients", type=int, default=1000,
                    help="concurrent clients (default 1000 — the "
                         "acceptance scale)")
    ld.add_argument("--url", default=None,
                    help="target a running `benor_tpu serve` instance "
                         "(default: spin an in-process server on an "
                         "ephemeral port for the run)")
    ld.add_argument("--job", default=None,
                    help="JSON JobSpec each client submits (default: "
                         "serve.loadgen.DEFAULT_JOB, a dyn-bucket "
                         "simulate; clients get distinct seeds)")
    ld.add_argument("--timeout", type=float, default=120.0,
                    help="per-client completion deadline in seconds")
    ld.add_argument("--ramp", type=float, default=0.0,
                    help="spread connection setup across this many "
                         "seconds (0 = thundering herd)")
    ld.add_argument("--max-batch-jobs", type=int, default=None,
                    help="coalescing ceiling of the in-process server "
                         "(ignored with --url)")
    ld.add_argument("--format", choices=("text", "json"), default="text",
                    help="stdout format; json = the pinned-schema "
                         "manifest (tools/serve_manifest_schema.json)")
    ld.add_argument("--profile-out", metavar="PATH",
                    help="write the serve manifest to this JSON file")
    ld.add_argument("--baseline", metavar="PATH", default=None,
                    help="baseline manifest to gate against (default: "
                         "the committed SERVE_BASELINE.json)")
    ld.add_argument("--update-baseline", action="store_true",
                    help="write this capture as the new baseline "
                         "instead of gating against it")
    ld.add_argument("--timing-band", type=float, default=None,
                    help="also gate the machine-sensitive throughput/"
                         "p99 numbers at this ratio band (off by "
                         "default; see serve/gate.py)")
    ld.add_argument("--trace-out", metavar="PATH", default=None,
                    help="arm servescope span tracing for the run and "
                         "write the Perfetto trace (request/batch/job "
                         "stage spans, flow-linked) here")
    _add_obs_args(ld, record=False)

    w = sub.add_parser("watch",
                       help="tail a running run's JSON-lines progress "
                            "file: heartbeats (rounds/sec, decided "
                            "fraction, ETA) and/or sweep-journal "
                            "bucket records, kind-dispatched; no JAX "
                            "backend touched")
    w.add_argument("path", help="JSON-lines file (sweep "
                                "--heartbeat-out / --journal / "
                                "TpuNetwork.heartbeat_path; mixed "
                                "kinds interleave freely)")
    w.add_argument("--poll", type=float, default=0.2,
                   help="poll interval in seconds (default 0.2)")
    w.add_argument("--timeout", type=float, default=60.0,
                   help="give up after this many seconds without a new "
                        "record (default 60)")
    w.add_argument("--max-updates", type=int, default=0,
                   help="stop after printing this many records "
                        "(0 = until done/timeout)")
    w.add_argument("--no-follow", action="store_true",
                   help="print what is in the file now and exit "
                        "instead of tailing")
    w.add_argument("--keep-going", action="store_true",
                   help="do not stop at done: true records — an atlas "
                        "search journal carries one sweep_done per "
                        "refinement generation, with probe/cliff "
                        "records interleaving after each")

    at = sub.add_parser(
        "atlas",
        help="phase-boundary observatory: adaptive cliff search over "
             "the scenario grid (benor_tpu/atlas) -> pinned-schema "
             "kind:atlas_manifest + cliff-drift gate vs "
             "ATLAS_BASELINE.json; exit 2 on drift")
    at.add_argument("--searches", default="omission,partition,quorum",
                    help="comma-separated shipped searches to run "
                         "(default: all three — the omission stall "
                         "cliff, the partition liveness boundary, the "
                         "F >= N/2 quorum cliff)")
    at.add_argument("--axis", action="append", default=None,
                    metavar="SPEC",
                    help="instead of the shipped searches, hunt cliffs "
                         "on this '<name>:<lo>:<hi>[:<tol>]' axis over "
                         "the --n/--f/--trials/--max-rounds base "
                         "config (repeatable; see "
                         "atlas/scenario.AXIS_KINDS)")
    at.add_argument("--n", type=int, default=64,
                    help="base nodes for --axis/--heatmap searches")
    at.add_argument("--f", type=int, default=16)
    at.add_argument("--trials", type=int, default=8)
    at.add_argument("--max-rounds", type=int, default=16)
    at.add_argument("--seed", type=int, default=0)
    at.add_argument("--coarse", type=int, default=4,
                    help="coarse seeding-grid intervals per axis "
                         "(default 4 -> 5 grid points)")
    at.add_argument("--scale", type=float, default=1.0,
                    help="trial-count multiplier for the shipped "
                         "searches (cliff LOCATIONS are scale-free; "
                         "the gate refuses cross-scale compares)")
    at.add_argument("--no-forensics", action="store_true",
                    help="skip the per-cliff witness-armed audit and "
                         "minimal-repro emission")
    at.add_argument("--journal", metavar="PATH",
                    help="append atlas_probe/atlas_cliff records plus "
                         "the underlying sweep-journal bucket records "
                         "here (`python -m benor_tpu watch` renders "
                         "them; --resume restarts from it)")
    at.add_argument("--resume", action="store_true",
                    help="with --journal: restore every completed "
                         "generation's buckets bit-identically from "
                         "the journal (0 compiles) and run only the "
                         "remainder")
    at.add_argument("--out-dir", metavar="DIR",
                    help="dump witness bundles + repro JSONs here")
    at.add_argument("--heatmap", metavar="SPEC_A,SPEC_B",
                    help="instead of a search: evaluate the 2D "
                         "axis_a x axis_b slice in ONE batched call "
                         "and render the stall/rounds heatmap "
                         "(--profile-out JSON rows, --trace-out "
                         "Perfetto counter tracks)")
    at.add_argument("--trace-out", metavar="PATH",
                    help="with --heatmap: write Perfetto counter "
                         "tracks (one per axis_b row) here")
    at.add_argument("--format", choices=("text", "json"),
                    default="text")
    at.add_argument("--profile-out", metavar="PATH",
                    help="write the manifest (or --heatmap document) "
                         "to this JSON file")
    at.add_argument("--baseline", metavar="PATH", default=None,
                    help="baseline manifest to gate against (default: "
                         "the committed ATLAS_BASELINE.json)")
    at.add_argument("--update-baseline", action="store_true",
                    help="write this capture as the new baseline "
                         "instead of gating against it")
    _add_obs_args(at, record=False)

    rp = sub.add_parser(
        "replay",
        help="re-execute a kind:atlas_repro document bit-identically "
             "(digest + verdict pinned); exit 0 reproduced, 2 "
             "mismatch, 1 unreadable")
    rp.add_argument("path", help="repro JSON (atlas --out-dir emission "
                                 "or a manifest cliff's repro block "
                                 "saved to a file)")
    rp.add_argument("--format", choices=("text", "json"),
                    default="text")

    r = sub.add_parser("results",
                       help="generate RESULTS/ (curves + presets artifact)")
    r.add_argument("--out", default="RESULTS")
    r.add_argument("--n", type=int, default=None,
                   help="study size (default: 1M on accelerator, 50k on "
                        "CPU so a fallback run stays tractable)")
    r.add_argument("--trials", type=int, default=None,
                   help="MC trials (default: 32 on accelerator, 8 on CPU)")
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--no-presets", action="store_true",
                   help="skip the BASELINE presets (quick smoke)")

    argv = list(sys.argv[1:] if argv is None else argv)
    # bare `python -m benor_tpu [-n N -f F ...]` == the start.ts demo
    if not argv or argv[0] not in ("demo", "sweep", "coins", "preset",
                                   "results", "trace", "audit", "lint",
                                   "profile", "scale", "watch", "serve",
                                   "load", "atlas", "replay",
                                   "-h", "--help"):
        argv = ["demo"] + argv
    args = ap.parse_args(argv)
    if args.cmd == "scale":
        # a CPU mesh ladder needs max(--mesh) virtual devices; the
        # host-platform device count is honored until the CPU backend
        # first INITIALIZES (importing jax is fine — nothing before this
        # point touches a device), so widen it here when the operator
        # has not already pinned it
        want = max(args.mesh)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{max(want, 1)}").strip()
    _honor_platform_env()
    if getattr(args, "metrics_out", None) and args.cmd != "lint":
        # feed the unified registry's compile counters from the first
        # compile on (the jax.monitoring listener must precede them).
        # lint is exempt: a pure-AST pass compiles nothing, and the
        # analyzer's no-jax contract must hold with --metrics-out too.
        from .utils.compile_counter import install
        install()
    # the event-loop oracle backends, the (pure-AST) linter and the
    # (pure-tail) watcher never touch a JAX backend — don't spend a
    # probe (or a fallback) on them
    if not (args.cmd in ("lint", "watch") or
            (args.cmd == "demo" and args.backend in ("express", "native"))):
        _ensure_live_backend()
    return {"demo": _demo, "sweep": _sweep, "coins": _coins,
            "preset": _preset, "results": _results,
            "trace": _trace, "audit": _audit, "lint": _lint,
            "profile": _profile, "scale": _scale,
            "watch": _watch, "serve": _serve, "load": _load,
            "atlas": _atlas, "replay": _replay}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
