"""Simulation driver: the whole network run as one compiled while-loop.

The reference's "run" is an emergent property of the Node.js event loop —
rounds race as fast as O(N^2) localhost fetches resolve (SURVEY §3.3-3.4).
Here the run is a single ``lax.while_loop`` whose body is one Ben-Or round;
termination is ``all(decided | killed)`` or the round cap.  Decided lanes are
frozen via masking (quirk 5 handled in models/benor.py).

``k`` observability matches the reference's update points exactly:
k=0 at init (node.ts:25), k=1 at /start (node.ts:172), k=r+1 after a lane
completes round r (node.ts:147).
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .config import SimConfig
from .models.benor import all_settled, benor_round
from .state import (FaultSpec, NetState, init_state, new_recorder,
                    new_witness)

#: One warning per process for the debug-demotes-pallas perf cliff.
_debug_demotion_warned = False

#: One warning per process for the structured-delivery pallas demotion.
_structured_demotion_warned = False

#: One warning per process for the faultlab (omission/partition) pallas
#: demotion.
_faults_demotion_warned = False


def delivery_plane(cfg: SimConfig) -> str:
    """Which delivery plane serves this config: 'topology'
    (adjacency-structured neighbor fan-in, benor_tpu/topo/deliver.py),
    'committee' (per-round sampled committees,
    benor_tpu/topo/committees.py) or 'complete' (the paper's implicit
    all-to-all graph — every pre-PR-12 config).  The driver-level
    dispatch fact the regimes share: structured planes run the shared
    round kernel's gather/scatter tallies on the traced XLA loop in
    every regime; the fused pallas kernels only ever serve 'complete'
    (see warn_structured_demotes_pallas)."""
    if cfg.topology is not None:
        return "topology"
    if cfg.committee_cap:
        return "committee"
    return "complete"


def injection_plane(cfg: SimConfig) -> tuple:
    """Which DYNAMIC fault families (benor_tpu/faults, PR 15) this
    config arms, as a tuple of names in fixed order: 'crash_recover'
    (per-node down-intervals — cfg.fault_model + the cfg.recovery
    schedule spec), 'omission' (per-edge iid drops, cfg.drop_prob) and
    'partition' (epoch-structured group masks, cfg.partition).  Empty =
    the static pre-faultlab fault plane, whose executables are
    bit-identical in results AND compile counts to a build without the
    feature (the house rule tests/test_faults.py pins).  The
    driver-level dispatch fact the regimes share: crash_recover runs in
    EVERY regime including the fused pallas kernels (which re-derive
    liveness from the round bounds in-kernel); omission and partitions
    live on the delivery='all' plane, which the fused kernels never
    serve (warn_faults_demote_pallas announces that structural
    demotion, like the topo twin)."""
    fams = []
    if cfg.fault_model == "crash_recover" or cfg.recovery is not None:
        fams.append("crash_recover")
    if cfg.drop_prob:
        fams.append("omission")
    if cfg.partition is not None:
        fams.append("partition")
    return tuple(fams)


def warn_faults_demote_pallas(cfg: SimConfig) -> None:
    """The faultlab sibling of warn_structured_demotes_pallas: omission
    (cfg.drop_prob) and partitions (cfg.partition) require
    delivery='all', which every pallas gate in ops/tally.py rejects —
    so a use_pallas_round/use_pallas_hist config with either armed runs
    the per-round XLA loop.  Structural (the kernels implement lossless
    quorum delivery only), but silent flag-swallowing is how perf
    cliffs hide: announce once per process and tick the
    ``sim.demotion.faults`` counter on every call (one tick = one
    traced demoted executable build, the PR 14 discipline)."""
    from .utils.metrics import REGISTRY
    REGISTRY.counter("sim.demotion.faults").inc()
    global _faults_demotion_warned
    if _faults_demotion_warned:
        return
    _faults_demotion_warned = True
    warnings.warn(
        "SimConfig(use_pallas_round/use_pallas_hist) has no effect with "
        f"the {'/'.join(injection_plane(cfg))} fault plane armed: the "
        "fused kernels implement lossless complete-graph delivery only, "
        "so this run takes the per-round XLA loop.  Results are exactly "
        "the armed plane's semantics; only the kernel-speed expectation "
        "is off.  (crash_recover alone does NOT demote — the kernels "
        "re-derive down-intervals in-register.)",
        stacklevel=3)


def warn_structured_demotes_pallas(cfg: SimConfig) -> None:
    """A structured delivery plane (cfg.topology / cfg.committee_cap)
    never engages the fused pallas kernels: structured delivery requires
    delivery='all', which pallas_round_active / pallas_stream_active
    already reject, so a use_pallas_round/use_pallas_hist config runs
    the per-round XLA loop instead.  That demotion is STRUCTURAL (the
    kernels implement the complete graph only) — but silent flag-
    swallowing is how perf cliffs hide, so announce it once per
    process, the debug-demotion policy's sibling.

    Tooling visibility (PR 14): the one-shot warning is invisible to
    anything but a human tail of stderr, so every CALL of this
    announcer also ticks the ``sim.demotion.structured`` counter in
    the unified metrics registry.  Callers sit inside jitted entry
    points, so one tick = one TRACED demoted executable build — a warm
    jit cache re-runs the executable without re-entering this Python
    body, so the counter counts distinct demoted builds, not executions
    (tests/test_kernelscope.py pins both halves).  bench.py surfaces
    the family in its topo blob."""
    from .utils.metrics import REGISTRY
    REGISTRY.counter("sim.demotion.structured").inc()
    global _structured_demotion_warned
    if _structured_demotion_warned:
        return
    _structured_demotion_warned = True
    warnings.warn(
        "SimConfig(use_pallas_round/use_pallas_hist) has no effect under "
        f"the {delivery_plane(cfg)!r} delivery plane: the fused kernels "
        "implement the complete graph only, so this run takes the "
        "per-round XLA loop (the topo gather/scatter tallies).  Results "
        "are exactly the structured plane's semantics; only the "
        "kernel-speed expectation is off.",
        stacklevel=3)


def warn_debug_demotes_pallas(cfg: SimConfig) -> None:
    """cfg.debug silently routes a fused-pallas-eligible config onto the
    per-round XLA loop (the host-callback escape hatch cannot live inside
    the packed kernels) — observing the run CHANGES the code that
    executes.  Emit one loud process-wide warning the first time a
    pallas-eligible config is demoted, so 'zero-cost tracing' is never
    read as covering the fused regime.  cfg.record is the
    non-perturbing alternative (the flight recorder runs INSIDE the
    fused loop).

    Every call of this announcer ticks ``sim.demotion.debug`` in the
    metrics registry (the warning itself fires once per process).  As
    with the structured twin, callers are jitted entry points: one tick
    = one traced demoted executable build, not one execution."""
    from .utils.metrics import REGISTRY
    REGISTRY.counter("sim.demotion.debug").inc()
    global _debug_demotion_warned
    if _debug_demotion_warned:
        return
    _debug_demotion_warned = True
    warnings.warn(
        "SimConfig(debug=True) demotes this fused-pallas-eligible config "
        "to the per-round XLA loop (host debug callbacks cannot run "
        "inside the packed kernels): results are bit-identical via the "
        "XLA samplers' own streams only where the paths share streams, "
        "and the run is substantially slower.  For non-perturbing "
        "per-round telemetry use SimConfig(record=True) — the flight "
        "recorder fills on-device inside the fused loop.",
        stacklevel=3)


def heartbeat_due(cfg: SimConfig, prev_round, next_round) -> bool:
    """True iff the live-progress heartbeat (cfg.heartbeat_rounds;
    benor_tpu/meshscope/heartbeat.py) should fire for a round cursor
    that moved prev_round -> next_round: the cursor crossed a multiple
    of the cadence.  HOST-side only — every consumer (TpuNetwork.start's
    poll loop, the sharded/multihost slice wrappers) calls this between
    compiled slices, never inside one, so the knob cannot perturb a
    trace.  The single source of truth for the cadence, so every regime
    beats at the same rounds."""
    h = cfg.heartbeat_rounds
    if h <= 0:
        return False
    return (int(next_round) // h) > (int(prev_round) // h)


def start_state(cfg: SimConfig, state: NetState) -> NetState:
    """The /start transition: live lanes set k=1 (node.ts:167-188)."""
    k = jnp.where(~state.killed, jnp.int32(1), state.k)
    return NetState(x=state.x, decided=state.decided, k=k, killed=state.killed)


def _carry_extras(cfg: SimConfig, carry, offset: int = 2):
    """Split a loop carry's optional tail — (recorder?, witness?) in that
    fixed order, present iff the matching flag is set — into named slots.
    ``offset`` is where the tail starts (after the mandatory entries)."""
    recorder = witness = None
    i = offset
    if cfg.record:
        recorder = carry[i]
        i += 1
    if cfg.witness:
        witness = carry[i]
    return recorder, witness


def _run_body(cfg: SimConfig, faults: FaultSpec, base_key: jax.Array, carry,
              dyn=None, ctx=None):
    """One while-loop iteration.  ``carry`` is (r, state) plus the
    optional observability tail — the flight-recorder buffer when
    cfg.record, then the witness buffer when cfg.witness — riding the
    carry so every executed round writes its row(s) on device.
    ``ctx`` (ShardCtx or None=single-device) is threaded into the round
    kernel AND the debug callback, so a shard_map'd caller of
    run_consensus_traced gets one psum-globalized event per round instead
    of per-shard duplicates."""
    from .ops.collectives import SINGLE
    ctx = SINGLE if ctx is None else ctx
    r, state = carry[0], carry[1]
    recorder, witness = _carry_extras(cfg, carry)
    out = benor_round(cfg, state, faults, base_key, r, ctx, dyn=dyn,
                      recorder=recorder, witness=witness)
    if cfg.record or cfg.witness:
        state, *extras = out
    else:
        state, extras = out, []
    if cfg.debug:  # per-round host callback (SURVEY §5.1); zero cost if off
        from .utils.tracing import emit_round_event
        emit_round_event(state, ctx if ctx is not SINGLE else None)
    return (r + 1, state, *extras)


def _run_cond(cfg: SimConfig, carry, ctx=None):
    from .ops.collectives import SINGLE
    r, state = carry[0], carry[1]
    return (r <= cfg.max_rounds) & ~all_settled(state, SINGLE if ctx is None
                                                else ctx)


# benorlint: allow-donate-argnums — run_point's compile-then-time double
# call and every parity oracle re-invoke with the SAME state buffers
@functools.partial(jax.jit, static_argnums=0)
def run_consensus(cfg: SimConfig, state: NetState, faults: FaultSpec,
                  base_key: jax.Array):
    """Run from /start to termination or round cap.

    Returns (rounds_executed, final_state) — plus the filled
    flight-recorder buffer when ``cfg.record`` is set, plus the filled
    witness buffer when ``cfg.witness`` is set (in that order).
    jit-compiled once per config (SimConfig is static/hashable); the loop
    is on-device, zero host round trips per round.  In the fused-kernel
    regime (tally.pallas_round_active) the loop carries the BIT-PLANE
    packed state stack (state.PACK_LAYOUT: ~6 + k_bits bits per node at
    32 nodes per uint32 word) instead of NetState — pack/unpack and
    every per-lane XLA op run once per RUN, not per round, and on a
    single device the whole round is ONE kernel pass
    (pallas_round.fused_round_pallas) — with bit-identical results (the
    kernels share the unfused path's exact random streams).

    PERF CLIFF — ``cfg.debug`` is NOT zero-cost in the fused regime: the
    per-round host callbacks cannot run inside the packed kernels, so a
    pallas-round-eligible config with debug=True is silently DEMOTED to
    the per-round XLA loop (a one-time warning fires;
    warn_debug_demotes_pallas).  Off the fused regime debug=True traces
    in one callback per round and debug=False costs nothing, as before.
    ``cfg.record`` (the flight recorder) is the observation mechanism
    that does NOT change which code runs.
    """
    from .ops.tally import pallas_round_active

    # NOTE: the structured-plane demotion is announced (and counted —
    # sim.demotion.structured) by run_consensus_traced, which every
    # structured config reaches below (the pallas gates reject them);
    # announcing here too would double-tick the counter per run
    if pallas_round_active(cfg):
        if cfg.debug:
            warn_debug_demotes_pallas(cfg)
        else:
            from .ops.pallas_round import run_packed
            return run_packed(cfg, state, faults, base_key)
    return run_consensus_traced(cfg, state, faults, base_key, None)


def run_consensus_traced(cfg: SimConfig, state: NetState, faults: FaultSpec,
                         base_key: jax.Array,
                         dyn=None, ctx=None):
    """The round loop as a plain traceable function with a DYNAMIC fault
    parameter — the building block of the batched dynamic-F sweep engine
    (sweep.run_curve_batched), which vmaps it over a [B] batch of
    per-point (state, faults, dyn) triples inside ONE jit so an entire
    rounds-vs-f curve costs one XLA compile.

    ``dyn`` (state.DynParams or None) carries F/quorum as traced scalars;
    ``cfg`` keeps every static shape/mode decision and must agree with
    dyn's values on all of them (sweep.quorum_specialized defines when it
    can't — exact-table, dense and pallas regimes reject tracing).  With
    dyn=None this IS run_consensus's XLA loop, bit-for-bit.  Not jitted:
    callers embed it in their own jit (run_consensus above, or the
    batched engine's bucket executable).

    ``ctx`` (ShardCtx or None) names the mesh axes when this loop is
    embedded under shard_map: tallies, the termination predicate AND the
    cfg.debug round events then psum-globalize instead of emitting
    per-shard duplicates.  Returns (rounds, state), with the filled
    flight recorder appended when cfg.record and the filled witness
    buffer when cfg.witness (recorder first when both).
    """
    from .ops.tally import pallas_requested, pallas_round_active

    if dyn is not None and pallas_round_active(cfg):
        raise ValueError(
            "dynamic-F tracing cannot drive the fused pallas round; "
            "bucket such configs statically (sweep.quorum_specialized)")
    # structured configs are never quorum-specialized, so the batched
    # engine (and the serve dyn runner) reach THIS entry point directly
    # — announce the structural pallas demotion here too, or a
    # use_pallas_* sweep would silently swallow the flag (the exact
    # cliff the one-shot path warns about in run_consensus)
    if pallas_requested(cfg) and delivery_plane(cfg) != "complete":
        warn_structured_demotes_pallas(cfg)
    # same announce-don't-swallow policy for the faultlab delivery
    # planes: omission/partition force delivery='all', so the pallas
    # gates reject them structurally (crash_recover does NOT demote —
    # the kernels serve it)
    if pallas_requested(cfg) and not pallas_round_active(cfg) and \
            (cfg.drop_prob or cfg.partition is not None):
        warn_faults_demote_pallas(cfg)
    state = start_state(cfg, state)
    carry = (jnp.int32(1), state)
    if cfg.record:
        carry = carry + (new_recorder(cfg, state, ctx),)
    if cfg.witness:
        carry = carry + (new_witness(cfg, state, ctx),)
    out = jax.lax.while_loop(
        functools.partial(_run_cond, cfg, ctx=ctx),
        functools.partial(_run_body, cfg, faults, base_key, dyn=dyn,
                          ctx=ctx),
        carry)
    return (out[0] - 1, *out[1:])


def resume_consensus(cfg: SimConfig, state: NetState, faults: FaultSpec,
                     base_key: jax.Array, from_round: int, recorder=None,
                     witness=None):
    """Re-enter the round loop from a checkpointed round index (SURVEY §5.4).

    With cfg.record, pass the checkpointed run's ``recorder`` to keep
    filling it (None starts a fresh buffer whose rows before
    ``from_round`` stay zero except the re-entry snapshot in row 0) and
    the return gains the recorder as a third element.  cfg.witness
    threads ``witness`` the same way (appended after the recorder when
    both are on)."""
    from .ops.tally import pallas_round_active

    pallas = pallas_round_active(cfg)
    if pallas and cfg.debug:
        warn_debug_demotes_pallas(cfg)
    if pallas and not cfg.debug:
        # same fused dispatch as run_consensus: the packed loop serves
        # resume too (randomness keys on (key, round), never loop entry)
        from .ops.pallas_round import run_packed_slice
        out = run_packed_slice(cfg, state, faults, base_key,
                               jnp.int32(from_round),
                               jnp.int32(cfg.max_rounds + 2),
                               recorder=recorder, witness=witness)
        return (out[0] - 1, *out[1:])
    carry = (jnp.int32(from_round), state)
    if cfg.record:
        carry = carry + (new_recorder(cfg, state) if recorder is None
                         else recorder,)
    if cfg.witness:
        carry = carry + (new_witness(cfg, state) if witness is None
                         else witness,)
    out = jax.lax.while_loop(
        functools.partial(_run_cond, cfg),
        functools.partial(_run_body, cfg, faults, base_key),
        carry)
    return (out[0] - 1, *out[1:])


# benorlint: allow-donate-argnums — poll loops re-pass the carried
# recorder/witness buffers and backends snapshot the input state between
# slices; donation would invalidate those caller-held arrays
@functools.partial(jax.jit, static_argnums=0)
def run_consensus_slice(cfg: SimConfig, state: NetState, faults: FaultSpec,
                        base_key: jax.Array, from_round: jax.Array,
                        until_round: jax.Array, recorder=None,
                        witness=None):
    """At most ``until_round - from_round`` rounds of the compiled loop.

    The slice primitive behind mid-run observability (cfg.poll_rounds):
    the round body is a pure function of (round index, state) with all
    randomness keyed on (seed, round, phase, trial, node) — never on how
    the loop was entered — so running the network in slices is bit-identical
    to the one-shot ``run_consensus`` (tests/test_http_api.py pins this).
    Both round bounds are TRACED scalars: every slice of every chunk size
    shares one compiled executable per config.

    Returns (next_round, state); ``next_round == from_round`` means no
    progress was possible (already settled or past the round cap).

    In the fused-round regime the slice runs the packed loop
    (run_packed_slice — the same dispatch run_consensus and the sharded
    runner make), with bit-identical results.

    With cfg.record, ``recorder`` threads the flight-recorder buffer
    across slices (None builds a fresh one, row 0 snapshotting ``state``)
    and the filled buffer is appended to the return — slice-by-slice
    filling is bit-identical to the one-shot run's recorder.  cfg.witness
    threads ``witness`` identically (appended last when both are on).
    """
    from .ops.tally import pallas_round_active

    pallas = pallas_round_active(cfg)
    if pallas and cfg.debug:
        warn_debug_demotes_pallas(cfg)
    if pallas and not cfg.debug:
        from .ops.pallas_round import run_packed_slice
        return run_packed_slice(cfg, state, faults, base_key,
                                from_round, until_round, recorder=recorder,
                                witness=witness)
    carry = (jnp.int32(from_round), state)
    if cfg.record:
        carry = carry + (new_recorder(cfg, state) if recorder is None
                         else recorder,)
    if cfg.witness:
        carry = carry + (new_witness(cfg, state) if witness is None
                         else witness,)

    def cond(carry):
        return _run_cond(cfg, carry) & (carry[0] < until_round)

    return jax.lax.while_loop(
        cond, functools.partial(_run_body, cfg, faults, base_key), carry)


def simulate(cfg: SimConfig, initial_values, faulty_list=None,
             faults: Optional[FaultSpec] = None, crash_rounds=None):
    """Convenience one-shot: build state, run, return (rounds, state, faults).

    ``faulty_list`` is the reference's launch-time fault vector
    (launchNodes.ts:8); ``crash_rounds`` is required for
    fault_model='crash_at_round'; pass ``faults`` directly for fully
    per-trial specs.  With cfg.record the filled flight recorder is
    appended: (rounds, state, faults, recorder); with cfg.witness the
    filled witness buffer is appended after it.
    """
    if faults is None:
        if faulty_list is None:
            faulty_list = [False] * cfg.n_nodes
        faults = FaultSpec.from_faulty_list(cfg, faulty_list, crash_rounds)
    state = init_state(cfg, initial_values, faults)
    base_key = jax.random.key(cfg.seed)
    out = run_consensus(cfg, state, faults, base_key)
    return (out[0], out[1], faults, *out[2:])
