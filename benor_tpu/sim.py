"""Simulation driver: the whole network run as one compiled while-loop.

The reference's "run" is an emergent property of the Node.js event loop —
rounds race as fast as O(N^2) localhost fetches resolve (SURVEY §3.3-3.4).
Here the run is a single ``lax.while_loop`` whose body is one Ben-Or round;
termination is ``all(decided | killed)`` or the round cap.  Decided lanes are
frozen via masking (quirk 5 handled in models/benor.py).

``k`` observability matches the reference's update points exactly:
k=0 at init (node.ts:25), k=1 at /start (node.ts:172), k=r+1 after a lane
completes round r (node.ts:147).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .config import SimConfig
from .models.benor import all_settled, benor_round
from .state import FaultSpec, NetState, init_state


def start_state(cfg: SimConfig, state: NetState) -> NetState:
    """The /start transition: live lanes set k=1 (node.ts:167-188)."""
    k = jnp.where(~state.killed, jnp.int32(1), state.k)
    return NetState(x=state.x, decided=state.decided, k=k, killed=state.killed)


def _run_body(cfg: SimConfig, faults: FaultSpec, base_key: jax.Array, carry,
              dyn=None):
    r, state = carry
    state = benor_round(cfg, state, faults, base_key, r, dyn=dyn)
    if cfg.debug:  # per-round host callback (SURVEY §5.1); zero cost if off
        from .utils.tracing import emit_round_event
        emit_round_event(state)
    return (r + 1, state)


def _run_cond(cfg: SimConfig, carry):
    r, state = carry
    return (r <= cfg.max_rounds) & ~all_settled(state)


@functools.partial(jax.jit, static_argnums=0)
def run_consensus(cfg: SimConfig, state: NetState, faults: FaultSpec,
                  base_key: jax.Array) -> Tuple[jax.Array, NetState]:
    """Run from /start to termination or round cap.

    Returns (rounds_executed, final_state).  jit-compiled once per config
    (SimConfig is static/hashable); the loop is on-device, zero host round
    trips per round.  In the fused-kernel regime
    (tally.pallas_round_active) the loop carries the PACKED per-lane state
    word instead of NetState — pack/unpack and every per-lane XLA op run
    once per RUN, not per round — with bit-identical results (the kernels
    share the unfused path's exact random streams).
    """
    from .ops.tally import pallas_round_active

    if pallas_round_active(cfg) and not cfg.debug:
        from .ops.pallas_round import run_packed
        return run_packed(cfg, state, faults, base_key)
    return run_consensus_traced(cfg, state, faults, base_key, None)


def run_consensus_traced(cfg: SimConfig, state: NetState, faults: FaultSpec,
                         base_key: jax.Array,
                         dyn=None) -> Tuple[jax.Array, NetState]:
    """The round loop as a plain traceable function with a DYNAMIC fault
    parameter — the building block of the batched dynamic-F sweep engine
    (sweep.run_curve_batched), which vmaps it over a [B] batch of
    per-point (state, faults, dyn) triples inside ONE jit so an entire
    rounds-vs-f curve costs one XLA compile.

    ``dyn`` (state.DynParams or None) carries F/quorum as traced scalars;
    ``cfg`` keeps every static shape/mode decision and must agree with
    dyn's values on all of them (sweep.quorum_specialized defines when it
    can't — exact-table, dense and pallas regimes reject tracing).  With
    dyn=None this IS run_consensus's XLA loop, bit-for-bit.  Not jitted:
    callers embed it in their own jit (run_consensus above, or the
    batched engine's bucket executable).
    """
    from .ops.tally import pallas_round_active

    if dyn is not None and pallas_round_active(cfg):
        raise ValueError(
            "dynamic-F tracing cannot drive the fused pallas round; "
            "bucket such configs statically (sweep.quorum_specialized)")
    state = start_state(cfg, state)
    carry = (jnp.int32(1), state)
    r, state = jax.lax.while_loop(
        functools.partial(_run_cond, cfg),
        functools.partial(_run_body, cfg, faults, base_key, dyn=dyn),
        carry)
    return r - 1, state


def resume_consensus(cfg: SimConfig, state: NetState, faults: FaultSpec,
                     base_key: jax.Array, from_round: int):
    """Re-enter the round loop from a checkpointed round index (SURVEY §5.4)."""
    from .ops.tally import pallas_round_active

    if pallas_round_active(cfg) and not cfg.debug:
        # same fused dispatch as run_consensus: the packed loop serves
        # resume too (randomness keys on (key, round), never loop entry)
        from .ops.pallas_round import run_packed_slice
        r, state = run_packed_slice(cfg, state, faults, base_key,
                                    jnp.int32(from_round),
                                    jnp.int32(cfg.max_rounds + 2))
        return r - 1, state
    carry = (jnp.int32(from_round), state)
    r, state = jax.lax.while_loop(
        functools.partial(_run_cond, cfg),
        functools.partial(_run_body, cfg, faults, base_key),
        carry)
    return r - 1, state


@functools.partial(jax.jit, static_argnums=0)
def run_consensus_slice(cfg: SimConfig, state: NetState, faults: FaultSpec,
                        base_key: jax.Array, from_round: jax.Array,
                        until_round: jax.Array):
    """At most ``until_round - from_round`` rounds of the compiled loop.

    The slice primitive behind mid-run observability (cfg.poll_rounds):
    the round body is a pure function of (round index, state) with all
    randomness keyed on (seed, round, phase, trial, node) — never on how
    the loop was entered — so running the network in slices is bit-identical
    to the one-shot ``run_consensus`` (tests/test_http_api.py pins this).
    Both round bounds are TRACED scalars: every slice of every chunk size
    shares one compiled executable per config.

    Returns (next_round, state); ``next_round == from_round`` means no
    progress was possible (already settled or past the round cap).

    In the fused-round regime the slice runs the packed loop
    (run_packed_slice — the same dispatch run_consensus and the sharded
    runner make), with bit-identical results.
    """
    from .ops.tally import pallas_round_active

    if pallas_round_active(cfg) and not cfg.debug:
        from .ops.pallas_round import run_packed_slice
        return run_packed_slice(cfg, state, faults, base_key,
                                from_round, until_round)
    carry = (jnp.int32(from_round), state)

    def cond(carry):
        r, st = carry
        return _run_cond(cfg, carry) & (r < until_round)

    r, state = jax.lax.while_loop(
        cond, functools.partial(_run_body, cfg, faults, base_key), carry)
    return r, state


def simulate(cfg: SimConfig, initial_values, faulty_list=None,
             faults: Optional[FaultSpec] = None, crash_rounds=None):
    """Convenience one-shot: build state, run, return (rounds, state, faults).

    ``faulty_list`` is the reference's launch-time fault vector
    (launchNodes.ts:8); ``crash_rounds`` is required for
    fault_model='crash_at_round'; pass ``faults`` directly for fully
    per-trial specs.
    """
    if faults is None:
        if faulty_list is None:
            faulty_list = [False] * cfg.n_nodes
        faults = FaultSpec.from_faulty_list(cfg, faulty_list, crash_rounds)
    state = init_state(cfg, initial_values, faults)
    base_key = jax.random.key(cfg.seed)
    rounds, final = run_consensus(cfg, state, faults, base_key)
    return rounds, final, faults
