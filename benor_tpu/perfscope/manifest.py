"""Perf manifest: the pinned-schema JSON document a profile run emits.

One manifest = one capture session: platform/device identity, the
profile scale, and one PerfReport per regime (regimes.REGIME_NAMES).
The schema is checked in at ``tools/perf_report_schema.json`` and
validated by ``tools/check_metrics_schema.py`` (auto-detected by the
``kind`` key) — the same contract discipline as the bench detail record
and the witness bundle, so a renamed metric breaks tier-1 before it
breaks the regression gate or a dashboard.

``tools/check_perf_regression.py`` compares a manifest against the
committed ``PERF_BASELINE.json`` (same document format) with per-metric
tolerance bands (perfscope/baseline.py), exit 2 on regression.
"""

from __future__ import annotations

import json
import time
from typing import List, Sequence

from .capture import REPORT_VERSION, PerfReport
from .regimes import REGIME_NAMES

#: The manifest's auto-detection tag (tools/check_metrics_schema.py).
MANIFEST_KIND = "perf_manifest"


def build_manifest(reports: Sequence[PerfReport], scale: dict,
                   fused_vs_xla: dict = None) -> dict:
    """Assemble the manifest document from a capture session's reports.

    ``fused_vs_xla`` (regimes.capture_fused_vs_xla) is the PR-8 paired
    fused-vs-XLA measurement + the layout-derived packing cost model;
    None (a --regimes-subset capture that skipped the pair) records an
    explicit null, which the regression gate treats as "nothing to
    gate" rather than a pass."""
    import jax

    dev = jax.devices()[0]
    return {
        "kind": MANIFEST_KIND,
        "schema_version": REPORT_VERSION,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "jax_version": jax.__version__,
        "created_unix": round(time.time(), 3),
        "scale": {k: int(scale[k])
                  for k in ("n_nodes", "trials", "max_rounds", "seed")},
        "regimes": {r.regime: r.to_dict() for r in reports},
        "fused_vs_xla": fused_vs_xla,
    }


def missing_regimes(manifest: dict) -> List[str]:
    """Regime keys a complete manifest must carry but this one lacks."""
    return [r for r in REGIME_NAMES
            if r not in manifest.get("regimes", {})]


def save_manifest(path: str, manifest: dict) -> None:
    with open(path, "w") as fh:
        json.dump(manifest, fh, indent=1)
        fh.write("\n")


def load_manifest(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("kind") != MANIFEST_KIND:
        raise ValueError(
            f"{path}: not a perf manifest (kind={doc.get('kind')!r})")
    return doc
