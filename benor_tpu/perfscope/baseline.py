"""Perf regression detection: manifest-vs-baseline tolerance bands.

Deliberately jax-free (stdlib only): ``tools/check_perf_regression.py``
must be able to gate a CI run — or an operator's laptop — without
initializing any backend.  A "regression" is a STRUCTURAL drift: the
cost model's FLOPs / bytes / memory footprint moving outside a
per-metric band, a regime disappearing, or the deterministic round
count changing.  Wall-clock stages are machine-sensitive and are NOT
gated by default (pass ``timing_band`` to opt in); they are still
carried in every manifest for trend reading.

Bands gate BOTH directions: a 10x drop in bytes accessed is either a
real optimization (re-baseline with ``--update-baseline`` /
``python -m benor_tpu profile --update-baseline``) or a silently
degenerated capture (a regime that stopped iterating), and the gate
cannot tell which — a human re-baselining can.

``check_bench_trajectory`` reads the committed BENCH_r01..r05 headline
series and flags same-platform throughput collapses, so the round-over-
round artifacts participate in the same gate.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

#: metric -> max allowed new/old ratio (and 1/band on the way down).
#: Structural cost-model and footprint metrics only; see module
#: docstring for why timings are opt-in.
STRUCTURAL_BANDS: Dict[str, float] = {
    "flops": 1.25,
    "bytes_accessed": 1.25,
    "transcendentals": 1.5,
    "argument_bytes": 1.25,
    "output_bytes": 1.25,
    "temp_bytes": 1.5,
    "peak_bytes": 1.5,
}

#: Stage-timing metrics (gated only when ``timing_band`` is passed).
TIMING_KEYS = ("trace_lower_s", "compile_s", "first_execute_s",
               "steady_execute_s")


class IncomparableManifests(ValueError):
    """Raised when manifest and baseline describe different experiments
    (platform / scale / schema mismatch) — comparing them would produce
    confident nonsense, so the gate refuses instead."""


@dataclasses.dataclass
class Regression:
    """One out-of-band metric."""

    regime: str
    metric: str
    new: Optional[float]
    old: Optional[float]
    ratio: Optional[float]
    band: Optional[float]
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _require_comparable(new: dict, base: dict) -> None:
    for key in ("kind", "schema_version", "platform"):
        if new.get(key) != base.get(key):
            raise IncomparableManifests(
                f"{key}: manifest has {new.get(key)!r}, baseline has "
                f"{base.get(key)!r}")
    if new.get("scale") != base.get("scale"):
        raise IncomparableManifests(
            f"scale: manifest {new.get('scale')} vs baseline "
            f"{base.get('scale')} — recapture at the baseline scale or "
            f"re-baseline")


def _band_check(regime: str, metric: str, new_v, old_v, band: float,
                out: List[Regression]) -> None:
    if old_v in (None, 0) or new_v is None:
        # a metric the baseline's backend could not produce (or a zero
        # denominator) cannot band-compare; only flag a new zero where
        # the baseline had substance
        if old_v and not new_v:
            out.append(Regression(
                regime, metric, new_v, old_v, 0.0, band,
                f"{regime}.{metric}: went to zero (baseline {old_v}) — "
                f"the capture likely degenerated"))
        return
    ratio = float(new_v) / float(old_v)
    if ratio > band:
        out.append(Regression(
            regime, metric, float(new_v), float(old_v), round(ratio, 4),
            band,
            f"{regime}.{metric}: {new_v} vs baseline {old_v} "
            f"({ratio:.2f}x > band {band}x) — regression"))
    elif ratio < 1.0 / band:
        out.append(Regression(
            regime, metric, float(new_v), float(old_v), round(ratio, 4),
            band,
            f"{regime}.{metric}: {new_v} vs baseline {old_v} "
            f"({ratio:.2f}x < band 1/{band}x) — improvement or "
            f"degenerated capture; re-baseline if intended"))


def compare_manifests(new: dict, base: dict,
                      timing_band: Optional[float] = None
                      ) -> List[Regression]:
    """All out-of-band metrics of ``new`` vs ``base`` (empty = gate
    passes).  Raises IncomparableManifests when the two documents do not
    describe the same experiment."""
    _require_comparable(new, base)
    out: List[Regression] = []
    for regime, old_rep in base.get("regimes", {}).items():
        new_rep = new.get("regimes", {}).get(regime)
        if new_rep is None:
            out.append(Regression(
                regime, "regime", None, None, None, None,
                f"{regime}: present in baseline but missing from the "
                f"manifest — a compiled regime disappeared"))
            continue
        if new_rep.get("rounds_executed") != old_rep.get("rounds_executed"):
            out.append(Regression(
                regime, "rounds_executed",
                new_rep.get("rounds_executed"),
                old_rep.get("rounds_executed"), None, None,
                f"{regime}.rounds_executed: "
                f"{new_rep.get('rounds_executed')} vs baseline "
                f"{old_rep.get('rounds_executed')} — same seed + scale "
                f"must execute the same rounds (determinism drift)"))
        for metric, band in STRUCTURAL_BANDS.items():
            _band_check(regime, metric, new_rep.get(metric),
                        old_rep.get(metric), band, out)
        if timing_band:
            for metric in TIMING_KEYS:
                _band_check(regime, metric, new_rep.get(metric),
                            old_rep.get(metric), timing_band, out)
    return out


def check_bench_trajectory(paths: Sequence[str],
                           collapse_ratio: float = 3.0) -> List[str]:
    """Same-platform throughput collapses along a BENCH_r*.json series.

    Compares each record's ``node_rounds_per_sec`` (the workload-
    invariant throughput number; ``value`` = trials/s is NOT comparable
    across regime-set changes — bench.py documents why) against the best
    earlier same-platform record; a drop past ``collapse_ratio`` is a
    finding.  Records that failed to parse, carried an error, or predate
    the metric are skipped with a note."""
    findings: List[str] = []
    best: Dict[str, tuple] = {}              # platform -> (value, path)
    for path in paths:
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            findings.append(f"note: {path}: unreadable ({e})")
            continue
        if not isinstance(rec, dict) or rec.get("error"):
            findings.append(f"note: {path}: error record, skipped")
            continue
        plat = rec.get("platform")
        nrps = rec.get("node_rounds_per_sec")
        if not plat or nrps is None:
            # ABSENT metric = pre-metric capture; a present 0.0 is the
            # worst possible collapse and must flow into the comparison
            findings.append(
                f"note: {path}: no node_rounds_per_sec (pre-metric "
                f"capture), skipped")
            continue
        prev = best.get(plat)
        if prev and nrps * collapse_ratio < prev[0]:
            findings.append(
                f"REGRESSION: {path}: node_rounds_per_sec {nrps:.3g} is "
                f">{collapse_ratio}x below the {plat} best {prev[0]:.3g} "
                f"({prev[1]})")
        if prev is None or nrps > prev[0]:
            best[plat] = (nrps, path)
    return findings


def check_multichip_trajectory(paths: Sequence[str],
                               collapse_ratio: float = 3.0) -> List[str]:
    """Scaling-efficiency collapses along the MULTICHIP_r*.json series.

    The multichip round captures carry the "near-linear scaling"
    evidence the pod-scale arc rests on; once a record publishes a
    ``scaling_efficiency`` (meshscope-era captures do — the best
    same-device-count efficiency of their scaling manifest), later
    records must not collapse below it.  Mirrors the bench-series
    ``node_rounds_per_sec=0.0`` rule, one notch stricter: a MISSING or
    zero scaling_efficiency on an otherwise-ok record is treated as the
    WORST collapse (efficiency 0.0) and flows into the comparison
    instead of being skipped — a capture that stopped reporting the
    metric must not read as healthy.  Records that failed (``ok``
    false), were skipped, or are unreadable are noted and skipped, like
    error records in the bench walk.  Comparisons key on ``n_devices``
    (efficiency at 2 chips and at 8 are different experiments)."""
    findings: List[str] = []
    best: Dict[object, tuple] = {}      # n_devices -> (efficiency, path)
    for path in paths:
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            findings.append(f"note: {path}: unreadable ({e})")
            continue
        if not isinstance(rec, dict) or rec.get("skipped") \
                or not rec.get("ok"):
            findings.append(f"note: {path}: skipped/failed capture, "
                            f"not compared")
            continue
        eff = rec.get("scaling_efficiency")
        if not eff:
            # missing or zero = the worst possible collapse; it still
            # participates (flagged iff an earlier record set a bar)
            findings.append(
                f"note: {path}: no scaling_efficiency — treated as 0.0 "
                f"(the worst collapse), not skipped")
            eff = 0.0
        key = rec.get("n_devices")
        prev = best.get(key)
        if prev and eff * collapse_ratio < prev[0]:
            findings.append(
                f"REGRESSION: {path}: scaling_efficiency {eff:.3g} is "
                f">{collapse_ratio}x below the n_devices={key} best "
                f"{prev[0]:.3g} ({prev[1]})")
        if eff and (prev is None or eff > prev[0]):
            best[key] = (eff, path)
    return findings
