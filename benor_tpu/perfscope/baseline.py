"""Perf regression detection: manifest-vs-baseline tolerance bands.

Deliberately jax-free (stdlib only): ``tools/check_perf_regression.py``
must be able to gate a CI run — or an operator's laptop — without
initializing any backend.  A "regression" is a STRUCTURAL drift: the
cost model's FLOPs / bytes / memory footprint moving outside a
per-metric band, a regime disappearing, or the deterministic round
count changing.  Wall-clock stages are machine-sensitive and are NOT
gated by default (pass ``timing_band`` to opt in); they are still
carried in every manifest for trend reading.

Bands gate BOTH directions: a 10x drop in bytes accessed is either a
real optimization (re-baseline with ``--update-baseline`` /
``python -m benor_tpu profile --update-baseline``) or a silently
degenerated capture (a regime that stopped iterating), and the gate
cannot tell which — a human re-baselining can.

``check_bench_trajectory`` reads the committed BENCH_r01..r05 headline
series and flags same-platform throughput collapses, so the round-over-
round artifacts participate in the same gate.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

#: metric -> max allowed new/old ratio (and 1/band on the way down).
#: Structural cost-model and footprint metrics only; see module
#: docstring for why timings are opt-in.
STRUCTURAL_BANDS: Dict[str, float] = {
    "flops": 1.25,
    "bytes_accessed": 1.25,
    "transcendentals": 1.5,
    "argument_bytes": 1.25,
    "output_bytes": 1.25,
    "temp_bytes": 1.5,
    "peak_bytes": 1.5,
}

#: Stage-timing metrics (gated only when ``timing_band`` is passed).
TIMING_KEYS = ("trace_lower_s", "compile_s", "first_execute_s",
               "steady_execute_s")


class IncomparableManifests(ValueError):
    """Raised when manifest and baseline describe different experiments
    (platform / scale / schema mismatch) — comparing them would produce
    confident nonsense, so the gate refuses instead."""


@dataclasses.dataclass
class Regression:
    """One out-of-band metric."""

    regime: str
    metric: str
    new: Optional[float]
    old: Optional[float]
    ratio: Optional[float]
    band: Optional[float]
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _require_comparable(new: dict, base: dict) -> None:
    for key in ("kind", "schema_version", "platform"):
        if new.get(key) != base.get(key):
            raise IncomparableManifests(
                f"{key}: manifest has {new.get(key)!r}, baseline has "
                f"{base.get(key)!r}")
    if new.get("scale") != base.get("scale"):
        raise IncomparableManifests(
            f"scale: manifest {new.get('scale')} vs baseline "
            f"{base.get('scale')} — recapture at the baseline scale or "
            f"re-baseline")


def _band_check(regime: str, metric: str, new_v, old_v, band: float,
                out: List[Regression]) -> None:
    if old_v in (None, 0) or new_v is None:
        # a metric the baseline's backend could not produce (or a zero
        # denominator) cannot band-compare; only flag a new zero where
        # the baseline had substance
        if old_v and not new_v:
            out.append(Regression(
                regime, metric, new_v, old_v, 0.0, band,
                f"{regime}.{metric}: went to zero (baseline {old_v}) — "
                f"the capture likely degenerated"))
        return
    ratio = float(new_v) / float(old_v)
    if ratio > band:
        out.append(Regression(
            regime, metric, float(new_v), float(old_v), round(ratio, 4),
            band,
            f"{regime}.{metric}: {new_v} vs baseline {old_v} "
            f"({ratio:.2f}x > band {band}x) — regression"))
    elif ratio < 1.0 / band:
        out.append(Regression(
            regime, metric, float(new_v), float(old_v), round(ratio, 4),
            band,
            f"{regime}.{metric}: {new_v} vs baseline {old_v} "
            f"({ratio:.2f}x < band 1/{band}x) — improvement or "
            f"degenerated capture; re-baseline if intended"))


def compare_manifests(new: dict, base: dict,
                      timing_band: Optional[float] = None
                      ) -> List[Regression]:
    """All out-of-band metrics of ``new`` vs ``base`` (empty = gate
    passes).  Raises IncomparableManifests when the two documents do not
    describe the same experiment."""
    _require_comparable(new, base)
    out: List[Regression] = []
    for regime, old_rep in base.get("regimes", {}).items():
        new_rep = new.get("regimes", {}).get(regime)
        if new_rep is None:
            out.append(Regression(
                regime, "regime", None, None, None, None,
                f"{regime}: present in baseline but missing from the "
                f"manifest — a compiled regime disappeared"))
            continue
        if new_rep.get("rounds_executed") != old_rep.get("rounds_executed"):
            out.append(Regression(
                regime, "rounds_executed",
                new_rep.get("rounds_executed"),
                old_rep.get("rounds_executed"), None, None,
                f"{regime}.rounds_executed: "
                f"{new_rep.get('rounds_executed')} vs baseline "
                f"{old_rep.get('rounds_executed')} — same seed + scale "
                f"must execute the same rounds (determinism drift)"))
        for metric, band in STRUCTURAL_BANDS.items():
            _band_check(regime, metric, new_rep.get(metric),
                        old_rep.get(metric), band, out)
        if timing_band:
            for metric in TIMING_KEYS:
                _band_check(regime, metric, new_rep.get(metric),
                            old_rep.get(metric), timing_band, out)
    return out


def _headline(rec: dict) -> dict:
    """Unwrap a committed bench record to its headline dict.

    The driver commits BENCH_r*.json as a wrapper ({cmd, rc, tail,
    parsed}) whose ``parsed`` key holds the stdout headline; a raw
    headline (bench.py stdout piped straight to a file) is its own
    record.  The trajectory walkers accept both — before PR 8 the
    wrapper records silently read as pre-metric captures and the whole
    committed series was skipped."""
    parsed = rec.get("parsed")
    if isinstance(parsed, dict):
        return parsed
    return rec


#: The PR-8 acceptance bound: the bit-plane relayout must cut per-node
#: round traffic at least this much AT THE BENCH GEOMETRY
#: (max_rounds = PACKED_RATIO_REF_MAX_ROUNDS).  The raw
#: packed_traffic_ratio in a manifest is a pure function of the capture's
#: max_rounds (more rounds = more k planes), so check_fused_vs_xla
#: NORMALIZES it to the reference geometry before gating — a
#: --max-rounds 64 capture must not read as a layout regression, and a
#: widened PACK_LAYOUT field must not hide behind a small capture.
PACKED_TRAFFIC_MIN_RATIO = 4.0
PACKED_RATIO_REF_MAX_ROUNDS = 12


def _k_planes(max_rounds: int) -> int:
    """state.pack_k_bits_for, stdlib twin (this module must not import
    jax-bearing modules — the NO-JAX gate contract)."""
    return max(int(max_rounds + 1).bit_length(), 1)


def normalized_traffic_ratio(fvx: dict):
    """The capture's layout re-priced at the reference bench geometry:
    old-layout bytes over new-layout bytes per node per round with the k
    field resized to PACKED_RATIO_REF_MAX_ROUNDS.  None when the block
    lacks the packing fields (schema drift — the schema gate owns
    that)."""
    bits = fvx.get("packed_bits_per_node")
    old_bytes = fvx.get("unpacked_round_bytes_per_node")
    mr = fvx.get("max_rounds")
    if bits is None or not old_bytes or mr is None:
        return None
    static_bits = bits - _k_planes(mr)
    ref_bits = static_bits + _k_planes(PACKED_RATIO_REF_MAX_ROUNDS)
    if ref_bits <= 0:
        return None
    return old_bytes / (2.0 * ref_bits / 8.0)


def check_fused_vs_xla(manifest: dict) -> List[str]:
    """The fused-beats-XLA acceptance gate over a manifest's
    ``fused_vs_xla`` block (PR 8) — "REGRESSION: ..." strings drive exit
    2, "note: ..." strings are informational.

    On a real backend the fused round kernel must BEAT the plain XLA
    loop (speedup > 1.0) — the committed-bench era where the flagship
    fast path lost to XLA (BENCH_r05 pallas_speedups.round = 0.628) is
    what this pin forbids forever.  ``interpret_mode`` captures (CPU:
    the pallas kernels run under the interpreter, so the ratio measures
    emulation overhead, not the kernels) are EXCLUDED from the speedup
    gate and held to the layout-derived ``packed_traffic_ratio`` >=
    PACKED_TRAFFIC_MIN_RATIO instead.  A missing block (pre-PR-8
    manifest) or an explicit null (--regimes-subset capture) is a note,
    never a silent pass of the speedup claim."""
    findings: List[str] = []
    if "fused_vs_xla" not in manifest:
        findings.append("note: manifest predates the fused_vs_xla block "
                        "(schema_version < 2); fused-vs-XLA not gated")
        return findings
    fvx = manifest["fused_vs_xla"]
    if fvx is None:
        findings.append("note: fused_vs_xla is null (subset capture); "
                        "fused-vs-XLA not gated")
        return findings
    if not fvx.get("bit_equal", False):
        findings.append(
            "REGRESSION: fused_vs_xla.bit_equal is false — the fused "
            "and XLA legs diverged; the fused path is WRONG, not slow")
    ratio = normalized_traffic_ratio(fvx)
    if ratio is None or ratio < PACKED_TRAFFIC_MIN_RATIO:
        findings.append(
            f"REGRESSION: fused_vs_xla packed traffic ratio "
            f"{ratio if ratio is None else round(ratio, 4)} < "
            f"{PACKED_TRAFFIC_MIN_RATIO} at the reference geometry "
            f"(max_rounds={PACKED_RATIO_REF_MAX_ROUNDS}; the capture's "
            f"own k width is normalized out) — the bit-plane relayout "
            f"no longer cuts per-node round traffic enough (did a "
            f"field widen in state.PACK_LAYOUT?)")
    if fvx.get("interpret_mode"):
        findings.append(
            f"note: interpret-mode capture — fused/XLA speedup "
            f"{fvx.get('speedup')} measures the pallas interpreter and "
            f"is excluded from gating (the geometry-normalized traffic "
            f"ratio above carries the acceptance bound)")
        return findings
    speedup = fvx.get("speedup")
    if speedup is None or speedup <= 1.0:
        findings.append(
            f"REGRESSION: fused_vs_xla.speedup {speedup} <= 1.0 on a "
            f"real backend ({fvx.get('rounds_executed')} rounds at "
            f"N={fvx.get('n_nodes')}) — the fused fast path trails the "
            f"plain XLA loop again")
    return findings


def check_pallas_speedup_trajectory(paths: Sequence[str],
                                    collapse_ratio: float = 3.0
                                    ) -> List[str]:
    """Same-platform pallas-kernel speedup collapses along the committed
    BENCH_r*.json series — with interpret-mode captures EXCLUDED.

    Records carrying ``pallas_interpret: true`` measured the kernels
    under the CPU pallas interpreter: their ratios price XLA-vs-emulator
    and systematically read as losses (BENCH_r05's round=0.628 was this
    artifact).  Treating them as regressions — or their occasional
    emulator-beats-XLA flukes as wins — would gate on noise, so they are
    noted and skipped; only real-backend ratios participate, per
    (platform, kernel), against the best earlier same-platform value."""
    findings: List[str] = []
    best: Dict[tuple, tuple] = {}    # (platform, kernel) -> (ratio, path)
    for path in paths:
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            findings.append(f"note: {path}: unreadable ({e})")
            continue
        if not isinstance(rec, dict) or rec.get("error"):
            continue                 # the bench walk already notes these
        head = _headline(rec)
        speedups = head.get("pallas_speedups")
        if not isinstance(speedups, dict) or not speedups:
            continue                 # pre-metric capture
        if head.get("pallas_interpret"):
            findings.append(
                f"note: {path}: pallas_speedups captured under the "
                f"interpreter (pallas_interpret=true) — excluded from "
                f"kernel-ratio gating")
            continue
        plat = head.get("platform")
        for kernel, ratio in speedups.items():
            if not isinstance(ratio, (int, float)) or not plat:
                continue
            key = (plat, kernel)
            prev = best.get(key)
            if prev and ratio * collapse_ratio < prev[0]:
                findings.append(
                    f"REGRESSION: {path}: pallas_speedups.{kernel} "
                    f"{ratio:.3g} is >{collapse_ratio}x below the "
                    f"{plat} best {prev[0]:.3g} ({prev[1]})")
            if prev is None or ratio > prev[0]:
                best[key] = (ratio, path)
    return findings


def check_bench_trajectory(paths: Sequence[str],
                           collapse_ratio: float = 3.0) -> List[str]:
    """Same-platform throughput collapses along a BENCH_r*.json series.

    Compares each record's ``node_rounds_per_sec`` (the workload-
    invariant throughput number; ``value`` = trials/s is NOT comparable
    across regime-set changes — bench.py documents why) against the best
    earlier same-platform record; a drop past ``collapse_ratio`` is a
    finding.  Records that failed to parse, carried an error, or predate
    the metric are skipped with a note."""
    findings: List[str] = []
    best: Dict[str, tuple] = {}              # platform -> (value, path)
    for path in paths:
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            findings.append(f"note: {path}: unreadable ({e})")
            continue
        if not isinstance(rec, dict) or rec.get("error"):
            findings.append(f"note: {path}: error record, skipped")
            continue
        head = _headline(rec)
        plat = head.get("platform")
        nrps = head.get("node_rounds_per_sec")
        if not plat or nrps is None:
            # ABSENT metric = pre-metric capture; a present 0.0 is the
            # worst possible collapse and must flow into the comparison
            findings.append(
                f"note: {path}: no node_rounds_per_sec (pre-metric "
                f"capture), skipped")
            continue
        prev = best.get(plat)
        if prev and nrps * collapse_ratio < prev[0]:
            findings.append(
                f"REGRESSION: {path}: node_rounds_per_sec {nrps:.3g} is "
                f">{collapse_ratio}x below the {plat} best {prev[0]:.3g} "
                f"({prev[1]})")
        if prev is None or nrps > prev[0]:
            best[plat] = (nrps, path)
    return findings


def check_multichip_trajectory(paths: Sequence[str],
                               collapse_ratio: float = 3.0) -> List[str]:
    """Scaling-efficiency collapses along the MULTICHIP_r*.json series.

    The multichip round captures carry the "near-linear scaling"
    evidence the pod-scale arc rests on; once a record publishes a
    ``scaling_efficiency`` (meshscope-era captures do — the best
    same-device-count efficiency of their scaling manifest), later
    records must not collapse below it.  Mirrors the bench-series
    ``node_rounds_per_sec=0.0`` rule, one notch stricter: a MISSING or
    zero scaling_efficiency on an otherwise-ok record is treated as the
    WORST collapse (efficiency 0.0) and flows into the comparison
    instead of being skipped — a capture that stopped reporting the
    metric must not read as healthy.  Records that failed (``ok``
    false), were skipped, or are unreadable are noted and skipped, like
    error records in the bench walk.  Comparisons key on ``n_devices``
    (efficiency at 2 chips and at 8 are different experiments)."""
    findings: List[str] = []
    best: Dict[object, tuple] = {}      # n_devices -> (efficiency, path)
    for path in paths:
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            findings.append(f"note: {path}: unreadable ({e})")
            continue
        if not isinstance(rec, dict) or rec.get("skipped") \
                or not rec.get("ok"):
            findings.append(f"note: {path}: skipped/failed capture, "
                            f"not compared")
            continue
        eff = rec.get("scaling_efficiency")
        if not eff:
            # missing or zero = the worst possible collapse; it still
            # participates (flagged iff an earlier record set a bar)
            findings.append(
                f"note: {path}: no scaling_efficiency — treated as 0.0 "
                f"(the worst collapse), not skipped")
            eff = 0.0
        key = rec.get("n_devices")
        prev = best.get(key)
        if prev and eff * collapse_ratio < prev[0]:
            findings.append(
                f"REGRESSION: {path}: scaling_efficiency {eff:.3g} is "
                f">{collapse_ratio}x below the n_devices={key} best "
                f"{prev[0]:.3g} ({prev[1]})")
        if eff and (prev is None or eff > prev[0]):
            best[key] = (eff, path)
    return findings
