"""Instrumented jit entry points: every compiled executable is observable.

Before perfscope, AOT lowering happened ad hoc: ``sweep.run_curve_batched``
built its bucket executables with a bare ``jax.jit(...).lower().compile()``
chain, ``bench.py`` held its own one-off ``cost_analysis`` probe behind a
broad except, and the sharded runner's ``jax.jit(shard_map(...))`` wrappers
were invisible to any accounting.  This module is the single funnel:

  * ``instrumented_jit``  — drop-in ``jax.jit`` that registers the wrapped
    callable in ``INSTRUMENTED`` (label -> jitted fn), so any entry point
    can be AOT-introspected later (``cost_of``) without hunting for it.
    Behavior is byte-for-byte ``jax.jit``'s: the returned object IS the
    jax-jitted callable.
  * ``aot_compile``       — the instrumented ``jit(...).lower().compile()``:
    per-stage wall-clocks (trace+lower vs backend compile) recorded into
    ``metrics.REGISTRY`` timers ``perfscope.<label>.lower`` / ``.compile``,
    backend compiles counted via the jax.monitoring hook, and the
    ``cost_analysis()`` / ``memory_analysis()`` surfaces normalized into
    plain dicts.
  * ``cost_of``           — one-call cost-model lookup for any jitted (or
    plain) callable at given args — what bench.py's per-regime
    bytes-accessed accounting runs through now.
  * ``JIT_REGISTRY``      — the pure-literal roster of module-level entry
    points that keep a RAW ``functools.partial(jax.jit, ...)`` decorator
    (they predate perfscope and their donation pragmas / tracing seeds
    hang off that exact spelling).  benorlint's ``perf-unregistered-jit``
    rule parses this tuple and fails the build when a raw jit call site
    appears anywhere else in the package.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Dict, Optional

import jax

from ..utils.metrics import REGISTRY

#: Module-level entry points allowed to keep a raw
#: ``functools.partial(jax.jit, ...)`` decorator, as
#: ``<module-path>.<function>`` relative to the package root.  A pure
#: literal: benorlint re-parses it (analysis/rules_perf.py) and flags any
#: raw ``jax.jit`` / ``.lower(...).compile()`` call site not listed here
#: and not spelled through this module.
JIT_REGISTRY = (
    "sim.run_consensus",
    "sim.run_consensus_slice",
    "sweep.summarize_final",
    "sweep.record_trajectory",
)

#: label -> jax-jitted callable, filled by ``instrumented_jit`` at import
#: time of each instrumented module.
INSTRUMENTED: Dict[str, Any] = {}

#: label -> the LAST AotArtifact ``aot_compile`` produced under that
#: label.  This is how long-lived executor pools stay introspectable
#: after the fact: the serve plane's warm bucket executables
#: (serve/batcher.py, labels ``serve.bucket.<kind>.c<capacity>``)
#: register here on build, so ``AOT_ARTIFACTS["..."].cost()`` answers
#: "what does one coalesced launch cost" without re-lowering anything.
#: Bounded by construction: one entry per distinct label, and labels
#: are drawn from the same small vocabulary as the stage timers.
AOT_ARTIFACTS: Dict[str, "AotArtifact"] = {}


def instrumented_jit(fun=None, *, label: Optional[str] = None,
                     **jit_kwargs):
    """``jax.jit`` that registers its product for AOT introspection.

    Usable exactly like ``jax.jit`` — directly (``instrumented_jit(fn,
    static_argnums=0)``) or as a decorator factory
    (``@instrumented_jit(static_argnames=("interpret",))``).  The wrapped
    callable is stored in ``INSTRUMENTED`` under ``label`` (default: the
    function's qualname), so perfscope can later lower/compile it at real
    operand shapes and read its cost model (``cost_of``) without the
    call-site module exporting anything extra.
    """
    if fun is None:
        return functools.partial(instrumented_jit, label=label,
                                 **jit_kwargs)
    jitted = jax.jit(fun, **jit_kwargs)
    name = label or getattr(fun, "__qualname__", repr(fun))
    INSTRUMENTED[name] = jitted
    return jitted


def cost_analysis_dict(compiled) -> dict:
    """``Compiled.cost_analysis()`` normalized to ONE plain dict (jax
    returns a per-device list on some versions, None on backends without
    a cost model)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return dict(ca) if ca else {}


def memory_analysis_dict(compiled) -> dict:
    """``Compiled.memory_analysis()`` as the byte counts every PerfReport
    carries.  ``peak_bytes`` is the executable's device-memory high-water
    estimate: argument + output + temp - alias (what must be live at once
    when nothing is donated)."""
    ma = compiled.memory_analysis()
    if ma is None:
        return {k: 0 for k in ("argument_bytes", "output_bytes",
                               "temp_bytes", "alias_bytes",
                               "generated_code_bytes", "peak_bytes")}
    get = lambda attr: int(getattr(ma, attr, 0) or 0)  # noqa: E731
    arg_b = get("argument_size_in_bytes")
    out_b = get("output_size_in_bytes")
    temp_b = get("temp_size_in_bytes")
    alias_b = get("alias_size_in_bytes")
    return {
        "argument_bytes": arg_b,
        "output_bytes": out_b,
        "temp_bytes": temp_b,
        "alias_bytes": alias_b,
        "generated_code_bytes": get("generated_code_size_in_bytes"),
        "peak_bytes": arg_b + out_b + temp_b - alias_b,
    }


@dataclasses.dataclass
class AotArtifact:
    """One instrumented ``lower().compile()`` round trip."""

    label: str
    compiled: Any                 # jax.stages.Compiled
    trace_lower_s: float
    compile_s: float
    backend_compiles: int         # jax.monitoring-counted real compiles
    backend_compile_s: float      # time inside XLA per the same hook

    def cost(self) -> dict:
        return cost_analysis_dict(self.compiled)

    def memory(self) -> dict:
        return memory_analysis_dict(self.compiled)


def aot_compile(fun, args, *, label: str, **jit_kwargs) -> AotArtifact:
    """Trace+lower then backend-compile ``fun`` at ``args``, instrumented.

    ``fun`` may be a plain callable (jit-wrapped here with
    ``jit_kwargs``) or an already-jitted object (``jit_kwargs`` must then
    be empty).  Stage wall-clocks feed ``REGISTRY`` timers
    ``perfscope.<label>.lower`` / ``perfscope.<label>.compile``; the
    backend-compile count/duration come from the jax.monitoring hook
    (utils/compile_counter), so "one executable, one backend compile" is
    measured, not assumed.  This is the ONE sanctioned spelling of
    ``jit(...).lower(...).compile()`` outside this package
    (benorlint ``perf-unregistered-jit``).
    """
    from ..utils.compile_counter import count_backend_compiles

    if hasattr(fun, "lower"):
        if jit_kwargs:
            raise ValueError(
                f"aot_compile({label!r}): {fun!r} is already jitted; "
                f"jit kwargs {sorted(jit_kwargs)} would be ignored")
        jitted = fun
    else:
        jitted = jax.jit(fun, **jit_kwargs)
    t0 = time.perf_counter()
    lowered = jitted.lower(*args)
    lower_s = time.perf_counter() - t0
    with count_backend_compiles() as cc:
        t0 = time.perf_counter()
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
    REGISTRY.timer(f"perfscope.{label}.lower").record(lower_s)
    REGISTRY.timer(f"perfscope.{label}.compile").record(compile_s)
    REGISTRY.counter("perfscope.aot_compiles").inc()
    art = AotArtifact(label=label, compiled=compiled,
                      trace_lower_s=lower_s, compile_s=compile_s,
                      backend_compiles=cc.count,
                      backend_compile_s=cc.seconds)
    AOT_ARTIFACTS[label] = art
    return art


def cost_of(fun, *args, label: str = "cost_of") -> dict:
    """The XLA cost model of ``fun`` at ``args`` as a plain dict.

    Best-effort accounting for artifact pipelines (bench.py's per-regime
    bytes-accessed estimate): a backend without a cost model — or a
    lowering quirk on an exotic platform — yields ``{}`` plus a
    ``perfscope.cost_failures`` counter tick rather than killing the
    caller's run; the caller's science output must never die for a lost
    accounting estimate.
    """
    try:
        jitted = fun if hasattr(fun, "lower") else jax.jit(fun)
        return cost_analysis_dict(jitted.lower(*args).compile())
    # benorlint: allow-broad-except — accounting must not kill the run;
    # failures are counted (perfscope.cost_failures) and surface as {}
    except Exception:  # noqa: BLE001
        REGISTRY.counter("perfscope.cost_failures").inc()
        return {}
