"""The five compiled regimes as capturable workloads.

Every compiled path the repo ships — the traced XLA while-loop, the
fused pallas packed loop, the poll_rounds slice primitive, the batched
dynamic-F sweep bucket, and the sharded (shard_map) runner — built at a
profile scale, AOT-captured stage by stage (capture.py) and reduced to
one PerfReport each.  ``capture_all`` is what ``python -m benor_tpu
profile`` and bench.py's ``perfscope`` blob run.

Regime configs (balanced inputs, zero crashes — the multi-round science
shape, so the while-loops genuinely iterate):

  traced         uniform scheduler, f = 0.4 N (the flagship curve point),
                 plain XLA loop
  fused_pallas   count-controlling adversary + common coin with
                 ``use_pallas_round`` — closed-form counts, so the kernel
                 path engages at ANY scale (CPU interpret mode included)
                 and shares every random bit with the XLA loop
  sliced         the traced config through ``run_consensus_slice`` (one
                 slice spanning the whole run — the poll_rounds
                 executable, traced round bounds)
  batched_sweep  a 2-point dynamic-F bucket over the adversarial config
                 (vmapped ``run_consensus_traced`` + on-device summaries
                 — the sweep engine's executable shape)
  sharded        the traced config under a ('trials','nodes') mesh
                 (default (1, 1): deterministic on any host; pass
                 ``mesh_shape`` to span real devices)

The profile scale is deliberately SMALL on CPU (N=256) — the point is
the pipeline and the cost model, both of which scale-compare fine — and
the bench/TPU scale on accelerators.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

#: Manifest regime keys, capture order.  The regression gate and the
#: manifest schema both require exactly this set.
REGIME_NAMES = ("traced", "fused_pallas", "sliced", "batched_sweep",
                "sharded")


def default_profile_scale(on_cpu: Optional[bool] = None) -> dict:
    """(n_nodes, trials, max_rounds) for a profile capture — smoke scale
    on CPU, bench scale on accelerators (utils/backend.default_scale)."""
    import jax

    from ..utils.backend import default_scale
    if on_cpu is None:
        on_cpu = jax.default_backend() == "cpu"
    if on_cpu:
        return {"n_nodes": 256, "trials": 8, "max_rounds": 12}
    n, t = default_scale(False)
    return {"n_nodes": n, "trials": t, "max_rounds": 16}


def _even_quorum(n: int, f: int) -> int:
    """Adjust F so the quorum N - F is even (the tie-forcing adversary's
    requirement, cf. sweep.coin_comparison)."""
    return f + (n - f) % 2


def _uniform_cfg(n: int, trials: int, max_rounds: int, seed: int):
    from ..config import SimConfig
    return SimConfig(n_nodes=n, n_faulty=int(0.4 * n), trials=trials,
                     delivery="quorum", scheduler="uniform",
                     path="histogram", max_rounds=max_rounds, seed=seed)


def _adversarial_cfg(n: int, trials: int, max_rounds: int, seed: int,
                     use_pallas_round: bool = False):
    from ..config import SimConfig
    return SimConfig(n_nodes=n, n_faulty=_even_quorum(n, int(0.2 * n)),
                     trials=trials, delivery="quorum",
                     scheduler="adversarial", coin_mode="common",
                     path="histogram", max_rounds=min(12, max_rounds),
                     use_pallas_round=use_pallas_round, seed=seed)


def _inputs(cfg):
    from ..state import FaultSpec, init_state
    from ..sweep import balanced_inputs
    import jax

    faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes),
                       faults)
    return state, faults, jax.random.key(cfg.seed)


def capture_regime(name: str, *, n_nodes: Optional[int] = None,
                   trials: Optional[int] = None,
                   max_rounds: Optional[int] = None, seed: int = 0,
                   mesh_shape: Tuple[int, int] = (1, 1),
                   steady_reps: int = 2):
    """Capture ONE regime -> (PerfReport, raw outputs of one execution).

    The outputs are returned so callers (tests, notably) can pin the
    profiled executable bit-identical to the plain dispatch path.
    """
    import jax.numpy as jnp

    from .capture import build_report, capture_stages

    scale = default_profile_scale()
    n = scale["n_nodes"] if n_nodes is None else n_nodes
    t = scale["trials"] if trials is None else trials
    mr = scale["max_rounds"] if max_rounds is None else max_rounds

    if name == "traced":
        from ..sim import run_consensus
        cfg = _uniform_cfg(n, t, mr, seed)
        state, faults, key = _inputs(cfg)
        cap = capture_stages(f"regime.{name}", run_consensus,
                             (cfg, state, faults, key),
                             (state, faults, key),
                             steady_reps=steady_reps)
        rounds = int(cap.out[0])
        extra = {"scheduler": cfg.scheduler, "coin_mode": cfg.coin_mode}

    elif name == "fused_pallas":
        from ..ops.tally import pallas_round_active
        from ..sim import run_consensus
        cfg = _adversarial_cfg(n, t, mr, seed, use_pallas_round=True)
        if not pallas_round_active(cfg):
            raise ValueError(
                "fused_pallas regime config failed the kernel gate "
                "(pallas_round_active) — the capture would silently "
                "profile the XLA loop instead")
        state, faults, key = _inputs(cfg)
        cap = capture_stages(f"regime.{name}", run_consensus,
                             (cfg, state, faults, key),
                             (state, faults, key),
                             steady_reps=steady_reps)
        rounds = int(cap.out[0])
        extra = {"scheduler": cfg.scheduler, "coin_mode": cfg.coin_mode,
                 "use_pallas_round": True}

    elif name == "sliced":
        from ..sim import run_consensus_slice, start_state
        cfg = _uniform_cfg(n, t, mr, seed)
        state, faults, key = _inputs(cfg)
        st = start_state(cfg, state)
        bounds = (jnp.int32(1), jnp.int32(cfg.max_rounds + 2))
        cap = capture_stages(f"regime.{name}", run_consensus_slice,
                             (cfg, st, faults, key) + bounds,
                             (st, faults, key) + bounds,
                             steady_reps=steady_reps)
        rounds = int(cap.out[0]) - 1
        extra = {"scheduler": cfg.scheduler,
                 "slice_bounds": [1, cfg.max_rounds + 2]}

    elif name == "batched_sweep":
        import jax

        from ..sim import run_consensus_traced
        from ..state import DynParams, FaultSpec, init_state
        from ..sweep import (_stack_tree, _summarize_inline,
                             balanced_inputs, sweep_bucket_key)
        base = _adversarial_cfg(n, t, mr, seed)
        f_values = [_even_quorum(n, int(0.15 * n)),
                    _even_quorum(n, int(0.25 * n))]
        cfgs = [base.replace(n_faulty=f) for f in f_values]
        if any(sweep_bucket_key(c)[0] != "dyn" for c in cfgs):
            raise ValueError(
                "batched_sweep regime points fell into a static bucket — "
                "the capture would not cover the dynamic-F executable")
        bal = balanced_inputs(t, n)
        fls = [FaultSpec.none(t, n) for _ in f_values]
        states = _stack_tree([init_state(c, bal, fl)
                              for c, fl in zip(cfgs, fls)])
        faults_b = _stack_tree(fls)
        dyn = DynParams.stack(cfgs)
        rep = cfgs[0]
        key = jax.random.key(seed)

        def bucket_runner(states, faults, dyn, bk):
            def one(s, fl, d):
                out = run_consensus_traced(rep, s, fl, bk, d)
                return _summarize_inline(rep, out[0], out[1], fl) + (
                    out[1],)
            return jax.vmap(one, in_axes=(0, 0, 0))(states, faults, dyn)

        cap = capture_stages(f"regime.{name}", bucket_runner,
                             (states, faults_b, dyn, key),
                             steady_reps=steady_reps)
        cfg = rep
        rounds = int(np.max(np.asarray(cap.out[0])))
        extra = {"scheduler": base.scheduler, "f_values": list(f_values),
                 "batch": len(f_values)}

    elif name == "sharded":
        import jax.numpy as jnp

        from ..parallel import make_mesh
        from ..parallel.sharded import jitted_runner, shard_inputs
        cfg = _uniform_cfg(n, t, mr, seed)
        mesh = make_mesh(*mesh_shape)
        state, faults, key = _inputs(cfg)
        st_sh, fl_sh = shard_inputs(state, faults, mesh)
        args = (st_sh, fl_sh, key, jnp.int32(1))
        cap = capture_stages(f"regime.{name}", jitted_runner(cfg, mesh),
                             args, steady_reps=steady_reps)
        rounds = int(cap.out[0])
        extra = {"scheduler": cfg.scheduler,
                 "mesh_shape": list(mesh_shape)}

    else:
        raise ValueError(f"unknown regime {name!r}; choose from "
                         f"{REGIME_NAMES}")

    return build_report(name, cfg, cap, rounds, extra=extra), cap.out


def capture_fused_vs_xla(n_nodes: Optional[int] = None,
                         trials: Optional[int] = None,
                         max_rounds: Optional[int] = None, seed: int = 0,
                         steady_reps: int = 2) -> dict:
    """The PAIRED fused-vs-XLA measurement behind the manifest's
    ``fused_vs_xla`` block (PR 8): the fused_pallas regime config run
    twice through run_consensus — ``use_pallas_round`` on and off — on
    identical inputs.  Under the count-controlling adversary + common
    coin the two paths share every random bit, so the pair is
    bit-compared (``bit_equal``) as well as timed; ``speedup`` is the
    XLA loop's steady-state seconds over the fused loop's.

    ``interpret_mode`` labels a CPU capture, where the pallas kernels
    run under the interpreter and the ratio measures EMULATION overhead,
    not the kernels: tools/check_perf_regression.py excludes such ratios
    from gating and holds the layout-derived ``packed_traffic_ratio``
    (roofline.packing_report) to the >= 4x acceptance bound instead.
    """
    import jax

    from ..ops.pallas_round import fused_one_pass_eligible
    from ..ops.tally import pallas_round_active, pallas_round_counts_mode
    from ..sim import run_consensus
    from .capture import capture_stages
    from .roofline import packing_report

    scale = default_profile_scale()
    n = scale["n_nodes"] if n_nodes is None else n_nodes
    t = scale["trials"] if trials is None else trials
    mr = scale["max_rounds"] if max_rounds is None else max_rounds

    # Prefer the uniform CF config (counts_mode='sampled' — the regime
    # the SINGLE-PASS kernel serves) whenever the kernel gate admits it
    # at this scale; fall back to the count-controlling adversary
    # (closed-form counts engage at ANY scale, CPU interpret included)
    # whose fused leg runs the two-kernel plane pipeline.  The block
    # labels which dispatch was measured (``counts_mode``/``one_pass``),
    # so the gate's verdict can never be read as covering a kernel the
    # dispatch would not run.
    cfg_fused = _uniform_cfg(n, t, mr, seed).replace(
        use_pallas_hist=True, use_pallas_round=True)
    if not pallas_round_active(cfg_fused):
        cfg_fused = _adversarial_cfg(n, t, mr, seed,
                                     use_pallas_round=True)
    if not pallas_round_active(cfg_fused):
        raise ValueError(
            "fused_vs_xla pair config failed the kernel gate "
            "(pallas_round_active) — both legs would time the XLA loop")
    # the baseline leg drops ONLY the round fusion: under the adversary
    # that is the plain XLA loop (shared closed-form counts + common
    # coin -> exact bit-equality); under uniform CF it is the unfused
    # pallas-hist pipeline (the only path sharing the kernel stream —
    # plain XLA would be statistically, not bitwise, comparable), the
    # same pairing BENCH_TPU's on-chip pallas_round_check adjudicated
    cfg_xla = cfg_fused.replace(use_pallas_round=False)
    state, faults, key = _inputs(cfg_fused)
    caps = {}
    for label, cfg in (("fused", cfg_fused), ("xla", cfg_xla)):
        caps[label] = capture_stages(
            f"fused_vs_xla.{label}", run_consensus,
            (cfg, state, faults, key), (state, faults, key),
            steady_reps=steady_reps)
    rounds_f = int(caps["fused"].out[0])
    rounds_x = int(caps["xla"].out[0])
    bit_equal = rounds_f == rounds_x and all(
        bool(np.array_equal(np.asarray(getattr(caps["fused"].out[1], a)),
                            np.asarray(getattr(caps["xla"].out[1], a))))
        for a in ("x", "decided", "k", "killed"))
    fused_s = caps["fused"].steady_execute_s
    xla_s = caps["xla"].steady_execute_s
    return {
        "n_nodes": cfg_fused.n_nodes,
        "trials": cfg_fused.trials,
        "max_rounds": cfg_fused.max_rounds,
        "rounds_executed": rounds_f,
        "bit_equal": bit_equal,
        "interpret_mode": jax.default_backend() == "cpu",
        # which fused dispatch the measurement actually covered: the
        # single-pass kernel or the two-kernel plane pipeline, and which
        # counts source / baseline leg — so the gate's verdict is never
        # read as pinning a kernel the dispatch would not run
        "counts_mode": pallas_round_counts_mode(cfg_fused),
        "one_pass": fused_one_pass_eligible(cfg_fused, cfg_fused.trials,
                                            cfg_fused.n_nodes),
        "baseline_path": ("pallas_hist" if cfg_xla.use_pallas_hist
                          else "xla"),
        "fused_steady_execute_s": round(fused_s, 6),
        "xla_steady_execute_s": round(xla_s, 6),
        "speedup": (round(xla_s / fused_s, 4) if fused_s > 0 else None),
        **packing_report(cfg_fused.max_rounds),
    }


def capture_all(n_nodes: Optional[int] = None,
                trials: Optional[int] = None,
                max_rounds: Optional[int] = None, seed: int = 0,
                regimes: Optional[Sequence[str]] = None,
                mesh_shape: Tuple[int, int] = (1, 1),
                steady_reps: int = 2):
    """Capture every regime (or the named subset) -> list of PerfReports,
    capture order = REGIME_NAMES order."""
    reports = []
    for name in (REGIME_NAMES if regimes is None else regimes):
        report, _ = capture_regime(
            name, n_nodes=n_nodes, trials=trials, max_rounds=max_rounds,
            seed=seed, mesh_shape=mesh_shape, steady_reps=steady_reps)
        reports.append(report)
    return reports
