"""Stage-timed AOT capture -> PerfReport.

One capture = the full pipeline a compiled regime lives through, each
stage measured separately (and fed into ``metrics.REGISTRY`` so captures
land in the same JSON-lines / Prometheus / Chrome-trace exports as every
other accounting source):

  trace+lower     python trace -> StableHLO           (host, per config)
  compile         XLA backend compile                 (the 8-40 s remote
                                                       cost the batched
                                                       sweep amortizes)
  first execute   includes device-transfer warm-up
  steady execute  mean of ``steady_reps`` post-warm repetitions — the
                  number roofline placement uses

plus the compiled executable's own post-optimization cost model
(FLOPs / bytes accessed / transcendentals) and memory footprint
(argument / output / temp / peak bytes), reduced with the device peak
table (roofline.py) into arithmetic intensity + roofline position.

The capture executes the AOT-compiled object directly; it never touches
the normal jit call cache, so profiling a regime leaves the unprofiled
path's results AND compile counts bit-identical (pinned by
tests/test_perfscope.py, same discipline as the flight recorder and the
witness buffers).  Its own cost is one extra backend compile per
captured regime — out-of-band, like a ``jax.profiler`` capture.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional, Tuple

import numpy as np

from ..utils.metrics import REGISTRY
from .instrument import AotArtifact, aot_compile
from .roofline import roofline

#: PerfReport / manifest schema version; bump on any key change
#: (tools/perf_report_schema.json is the pinned schema).
#: v2 (PR 8): top-level ``fused_vs_xla`` block — the paired fused-vs-XLA
#: measurement + the bit-plane packing cost model.
REPORT_VERSION = 2


@dataclasses.dataclass
class PerfReport:
    """One regime's AOT pipeline + cost/memory/roofline accounting."""

    regime: str
    platform: str
    device_kind: str
    # the captured workload
    n_nodes: int
    n_faulty: int
    trials: int
    max_rounds: int
    seed: int
    rounds_executed: int
    # stage timings (seconds)
    trace_lower_s: float
    compile_s: float
    first_execute_s: float
    steady_execute_s: float
    steady_reps: int
    backend_compiles: int
    # XLA cost model (per program; the while-loop body counts once)
    flops: float
    bytes_accessed: float
    transcendentals: float
    # memory footprint (bytes)
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    alias_bytes: int
    generated_code_bytes: int
    peak_bytes: int
    # roofline placement (roofline.py; None off the peak tables)
    arithmetic_intensity: Optional[float]
    achieved_gbps: Optional[float]
    hbm_peak_gbps: Optional[float]
    hbm_util: Optional[float]
    ridge_flop_per_byte: Optional[float]
    bound: Optional[str]
    #: regime-specific facts (scheduler, coin, mesh shape, ...)
    extra: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CaptureResult:
    """An AOT artifact plus its measured executions and outputs."""

    art: AotArtifact
    first_execute_s: float
    steady_execute_s: float
    steady_reps: int
    out: Any                      # the first execution's outputs


def _default_barrier(out) -> None:
    """Completion barrier: fetch the first output (the rounds scalar /
    vector in every regime here) — under the axon tunnel
    ``block_until_ready`` does not actually block, a fetch does."""
    np.asarray(out[0] if isinstance(out, (tuple, list)) else out)


def capture_stages(label: str, fun, lower_args: Tuple,
                   exec_args: Optional[Tuple] = None, *,
                   steady_reps: int = 2, barrier=_default_barrier,
                   **jit_kwargs) -> CaptureResult:
    """AOT-compile ``fun`` at ``lower_args`` and measure every stage.

    ``exec_args`` are the arguments the COMPILED object takes (defaults
    to ``lower_args``; jitted functions with static leading arguments
    take only the dynamic tail).  Execution timers feed
    ``perfscope.<label>.first_execute`` / ``.steady_execute``.
    """
    art = aot_compile(fun, lower_args, label=label, **jit_kwargs)
    if exec_args is None:
        exec_args = lower_args
    t0 = time.perf_counter()
    out = art.compiled(*exec_args)
    barrier(out)
    first_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    last = out
    for _ in range(steady_reps):
        last = art.compiled(*exec_args)
    barrier(last)
    steady_s = (time.perf_counter() - t0) / max(steady_reps, 1)
    REGISTRY.timer(f"perfscope.{label}.first_execute").record(first_s)
    REGISTRY.timer(f"perfscope.{label}.steady_execute").record(steady_s)
    return CaptureResult(art=art, first_execute_s=first_s,
                         steady_execute_s=steady_s,
                         steady_reps=steady_reps, out=out)


def build_report(regime: str, cfg, cap: CaptureResult,
                 rounds_executed: int, extra: Optional[dict] = None
                 ) -> PerfReport:
    """Reduce a CaptureResult + its SimConfig into the serializable
    PerfReport (cost model, memory footprint, roofline placement)."""
    import jax

    dev = jax.devices()[0]
    cost = cap.art.cost()
    mem = cap.art.memory()
    flops = float(cost.get("flops", 0.0))
    bytes_acc = float(cost.get("bytes accessed", 0.0))
    roof = roofline(flops, bytes_acc, cap.steady_execute_s,
                    dev.device_kind)
    return PerfReport(
        regime=regime, platform=dev.platform, device_kind=dev.device_kind,
        n_nodes=cfg.n_nodes, n_faulty=cfg.n_faulty, trials=cfg.trials,
        max_rounds=cfg.max_rounds, seed=cfg.seed,
        rounds_executed=int(rounds_executed),
        trace_lower_s=round(cap.art.trace_lower_s, 6),
        compile_s=round(cap.art.compile_s, 6),
        first_execute_s=round(cap.first_execute_s, 6),
        steady_execute_s=round(cap.steady_execute_s, 6),
        steady_reps=cap.steady_reps,
        backend_compiles=cap.art.backend_compiles,
        flops=flops, bytes_accessed=bytes_acc,
        transcendentals=float(cost.get("transcendentals", 0.0)),
        argument_bytes=mem["argument_bytes"],
        output_bytes=mem["output_bytes"],
        temp_bytes=mem["temp_bytes"],
        alias_bytes=mem["alias_bytes"],
        generated_code_bytes=mem["generated_code_bytes"],
        peak_bytes=mem["peak_bytes"],
        **roof,
        extra=dict(extra or {}),
    )
