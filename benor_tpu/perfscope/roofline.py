"""Device peak tables + roofline placement for PerfReports.

The HBM peak-bandwidth table lived in ``bench.py`` since round 2, where
only the end-to-end sweep could use it; perfscope owns it now so every
per-regime report (and bench.py, which imports it back) places its
achieved bytes/s against the same published numbers.  The FLOPs table
lets a report say which side of the roofline ridge a regime sits on:
arithmetic intensity below ``ridge = peak_flops / peak_bw`` means the
regime is memory-bound — the expectation for this workload, whose round
body is a pass over [T, N] int8/int32 state (see README "Performance").

Both tables key on substrings of ``jax.Device.device_kind``
(lowercased), most-specific first; unknown kinds (including the CPU
smoke backend) yield ``None`` peaks and a ``bound`` of ``None`` — the
report then carries arithmetic intensity only, which is still
comparable across captures.
"""

from __future__ import annotations

from typing import Optional

#: Published HBM peak bandwidth per chip, bytes/s, keyed by substrings of
#: jax Device.device_kind (lowercased), most-specific first.
HBM_PEAKS = [
    ("v6", 1640e9), ("v5p", 2765e9), ("v5 lite", 819e9), ("v5e", 819e9),
    ("v5", 2765e9), ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
]

#: Published peak dense compute per chip (bf16 FLOP/s), same keying.
FLOPS_PEAKS = [
    ("v6", 918e12), ("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5", 459e12), ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]


def _lookup(table, device_kind: str) -> Optional[float]:
    kind = device_kind.lower()
    for sub, peak in table:
        if sub in kind:
            return peak
    return None


def hbm_peak_for(device_kind: str) -> Optional[float]:
    """Peak HBM bandwidth (bytes/s) for a device kind, or None."""
    return _lookup(HBM_PEAKS, device_kind)


def flops_peak_for(device_kind: str) -> Optional[float]:
    """Peak dense compute (FLOP/s) for a device kind, or None."""
    return _lookup(FLOPS_PEAKS, device_kind)


#: HBM bytes the PRE-PR-8 packed round moved per node per round: the
#: two-kernel int32-word pipeline read the 4-byte pack in the proposal
#: kernel, re-read it in the vote kernel, and wrote the new word — the
#: inter-kernel round-trip the single-pass fused kernel eliminates.
#: The denominator of ``packing_report``'s traffic ratio (and the bound
#: the CPU-only acceptance gate in tools/check_perf_regression.py holds
#: the relayout to).
UNPACKED_WORD_ROUND_BYTES = 12.0

#: Bits the old layout spent per node (one int32 word).
UNPACKED_WORD_BITS = 32


def packed_bits_per_node(max_rounds: int) -> int:
    """Hot-state bits per node under the bit-plane layout
    (state.PACK_LAYOUT): the static protocol planes plus the
    config-sized k planes.  Derived from the declarative table — a
    relayout is a table edit and this report follows it."""
    from ..state import PACK_STATIC_WIDTH, pack_k_bits_for

    return PACK_STATIC_WIDTH + pack_k_bits_for(max_rounds)


def packed_round_bytes_per_node(max_rounds: int) -> float:
    """HBM bytes the single-pass fused round moves per node per round:
    one plane-stack read + one write (the partial buffers are O(T), not
    O(N), and the count vectors O(T) — neither scales with nodes)."""
    return 2.0 * packed_bits_per_node(max_rounds) / 8.0


def packing_report(max_rounds: int) -> dict:
    """The packing cost model as manifest-ready numbers.

    ``packed_traffic_ratio`` is old-layout bytes over new-layout bytes
    per node per round (>= 4 at the bench geometry — the acceptance
    criterion tools/check_perf_regression.py pins when kernel wall
    clocks are interpret-mode noise); ``packing_efficiency`` is how much
    of the old 32-bit word the hot state actually needed (what the
    relayout recovered)."""
    bits = packed_bits_per_node(max_rounds)
    new_bytes = packed_round_bytes_per_node(max_rounds)
    return {
        "packed_bits_per_node": bits,
        "packed_round_bytes_per_node": round(new_bytes, 4),
        "unpacked_round_bytes_per_node": UNPACKED_WORD_ROUND_BYTES,
        "packed_traffic_ratio": round(UNPACKED_WORD_ROUND_BYTES
                                      / new_bytes, 4),
        "packing_efficiency": round(bits / UNPACKED_WORD_BITS, 4),
    }


def kernel_geometry(cfg, trials: Optional[int] = None,
                    n_nodes: Optional[int] = None) -> dict:
    """The fused round's grid/layout geometry, priced straight off the
    declarative tables — state.PACK_LAYOUT (plane count), the kernels'
    PARTIAL_COLS / partial_dtype (partial rows) and TILE_N (the lane
    tile).  One dict the per-stage traffic model below and the
    kernel_manifest's cross-field recomputation
    (check_metrics_schema.check_kernel_manifest) both consume, so the
    predicted bytes can always be re-derived from the committed
    numbers."""
    import numpy as np

    from ..ops.pallas_hist import TILE_N
    from ..ops.pallas_round import (PARTIAL_COLS, fused_one_pass_eligible,
                                    partial_dtype)
    from ..state import pack_width

    t = cfg.trials if trials is None else trials
    n = cfg.n_nodes if n_nodes is None else n_nodes
    np_total = n + (-n) % TILE_N
    one_pass = fused_one_pass_eligible(cfg, t, n)
    tiles = 1 if one_pass else np_total // TILE_N
    pdtype = partial_dtype(cfg.quorum,
                           np_total if one_pass else TILE_N)
    return {
        "trials": t,
        "n_nodes": n,
        "np_total": np_total,
        "tiles": tiles,
        "tile_nodes": np_total if one_pass else TILE_N,
        "planes": pack_width(cfg),
        "partial_cols": PARTIAL_COLS,
        "partial_dtype_bytes": int(np.dtype(pdtype).itemsize),
        "one_pass": bool(one_pass),
    }


def stage_traffic(geom: dict) -> dict:
    """Predicted HBM bytes PER ROUND per kernel stage, from a
    ``kernel_geometry`` dict alone (pure arithmetic — the stdlib-only
    manifest checker replays exactly this formula):

      plane_bytes    one pass over the packed plane stack:
                     T x planes x (np_total / 32) x 4
      partial_bytes  one per-tile partial buffer write:
                     tiles x T x partial_cols x dtype_bytes
      count_bytes    the [T]-vector count operands (3 classes, f32)

    Stage composition: the proposal stage reads the stack and writes its
    partials; the vote stage writes the new stack (plus, on the
    two-kernel pipeline, its own READ of the stack — the inter-kernel
    round trip the single-pass kernel deletes) and writes its partials;
    ``reduce`` is the XLA read-back of both partial buffers for the
    cross-tile sums.  O(T)-sized operands dwarfed by the O(N) terms are
    priced, not dropped, so the totals telescope."""
    t = geom["trials"]
    plane = t * geom["planes"] * (geom["np_total"] // 32) * 4
    partial = (geom["tiles"] * t * geom["partial_cols"]
               * geom["partial_dtype_bytes"])
    counts = t * 3 * 4
    # one-pass: the vote stage only WRITES the stack (the proposal
    # stage's read is still resident); two-kernel: a fresh read + the
    # write — the inter-kernel hop the fusion removes
    vote_plane_passes = 1 if geom["one_pass"] else 2
    stages = {
        "proposal": plane + partial + counts,
        "vote": vote_plane_passes * plane + partial + counts,
        "reduce": 2 * partial,
    }
    stages["total"] = sum(stages.values())
    return stages


def traffic_report(cfg, trials: Optional[int] = None,
                   n_nodes: Optional[int] = None,
                   measured_bytes_per_round: Optional[float] = None
                   ) -> dict:
    """The layout-derived HBM traffic model for one fused-round config:
    geometry + per-stage predicted bytes per round, plus — when the
    caller hands over the executable's ``cost_analysis``
    ``bytes_accessed`` for one round — the predicted/measured
    ``byte_ratio`` that telescopes the model against XLA's own cost
    accounting (the kernel_manifest's cross-check band).  This is the
    instrument ROADMAP item 2's relayout work reads: 'fused loses'
    becomes 'fused loses because stage X moves Y predicted-vs-measured
    bytes'."""
    geom = kernel_geometry(cfg, trials=trials, n_nodes=n_nodes)
    stages = stage_traffic(geom)
    ratio = None
    if measured_bytes_per_round:
        ratio = round(stages["total"] / measured_bytes_per_round, 6)
    return {
        "geometry": geom,
        "predicted_bytes_per_round": stages,
        "measured_bytes_per_round": measured_bytes_per_round,
        "byte_ratio": ratio,
    }


def roofline(flops: float, bytes_accessed: float, exec_s: float,
             device_kind: str) -> dict:
    """Place one executed program on the device roofline.

    Returns the derived keys every PerfReport carries:

      arithmetic_intensity  flops / bytes accessed (FLOP/byte); None
                            when the cost model reported zero bytes
      achieved_gbps         bytes accessed / steady-state seconds / 1e9
      hbm_peak_gbps         the table peak, or None off the table
      hbm_util              achieved / peak
      ridge_flop_per_byte   peak_flops / peak_bw — the roofline knee
      bound                 'memory' | 'compute' by which side of the
                            ridge the intensity falls on; None when the
                            device is off the peak tables
    """
    ai = (flops / bytes_accessed) if bytes_accessed else None
    gbps = (bytes_accessed / exec_s / 1e9) if exec_s > 0 else None
    peak_bw = hbm_peak_for(device_kind)
    peak_fl = flops_peak_for(device_kind)
    ridge = (peak_fl / peak_bw) if (peak_fl and peak_bw) else None
    bound = None
    if ridge is not None and ai is not None:
        bound = "memory" if ai < ridge else "compute"
    return {
        "arithmetic_intensity": round(ai, 6) if ai is not None else None,
        "achieved_gbps": round(gbps, 3) if gbps is not None else None,
        "hbm_peak_gbps": round(peak_bw / 1e9, 1) if peak_bw else None,
        "hbm_util": (round(gbps * 1e9 / peak_bw, 6)
                     if (gbps is not None and peak_bw) else None),
        "ridge_flop_per_byte": round(ridge, 3) if ridge else None,
        "bound": bound,
    }
