"""Device peak tables + roofline placement for PerfReports.

The HBM peak-bandwidth table lived in ``bench.py`` since round 2, where
only the end-to-end sweep could use it; perfscope owns it now so every
per-regime report (and bench.py, which imports it back) places its
achieved bytes/s against the same published numbers.  The FLOPs table
lets a report say which side of the roofline ridge a regime sits on:
arithmetic intensity below ``ridge = peak_flops / peak_bw`` means the
regime is memory-bound — the expectation for this workload, whose round
body is a pass over [T, N] int8/int32 state (see README "Performance").

Both tables key on substrings of ``jax.Device.device_kind``
(lowercased), most-specific first; unknown kinds (including the CPU
smoke backend) yield ``None`` peaks and a ``bound`` of ``None`` — the
report then carries arithmetic intensity only, which is still
comparable across captures.
"""

from __future__ import annotations

from typing import Optional

#: Published HBM peak bandwidth per chip, bytes/s, keyed by substrings of
#: jax Device.device_kind (lowercased), most-specific first.
HBM_PEAKS = [
    ("v6", 1640e9), ("v5p", 2765e9), ("v5 lite", 819e9), ("v5e", 819e9),
    ("v5", 2765e9), ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
]

#: Published peak dense compute per chip (bf16 FLOP/s), same keying.
FLOPS_PEAKS = [
    ("v6", 918e12), ("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5", 459e12), ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]


def _lookup(table, device_kind: str) -> Optional[float]:
    kind = device_kind.lower()
    for sub, peak in table:
        if sub in kind:
            return peak
    return None


def hbm_peak_for(device_kind: str) -> Optional[float]:
    """Peak HBM bandwidth (bytes/s) for a device kind, or None."""
    return _lookup(HBM_PEAKS, device_kind)


def flops_peak_for(device_kind: str) -> Optional[float]:
    """Peak dense compute (FLOP/s) for a device kind, or None."""
    return _lookup(FLOPS_PEAKS, device_kind)


def roofline(flops: float, bytes_accessed: float, exec_s: float,
             device_kind: str) -> dict:
    """Place one executed program on the device roofline.

    Returns the derived keys every PerfReport carries:

      arithmetic_intensity  flops / bytes accessed (FLOP/byte); None
                            when the cost model reported zero bytes
      achieved_gbps         bytes accessed / steady-state seconds / 1e9
      hbm_peak_gbps         the table peak, or None off the table
      hbm_util              achieved / peak
      ridge_flop_per_byte   peak_flops / peak_bw — the roofline knee
      bound                 'memory' | 'compute' by which side of the
                            ridge the intensity falls on; None when the
                            device is off the peak tables
    """
    ai = (flops / bytes_accessed) if bytes_accessed else None
    gbps = (bytes_accessed / exec_s / 1e9) if exec_s > 0 else None
    peak_bw = hbm_peak_for(device_kind)
    peak_fl = flops_peak_for(device_kind)
    ridge = (peak_fl / peak_bw) if (peak_fl and peak_bw) else None
    bound = None
    if ridge is not None and ai is not None:
        bound = "memory" if ai < ridge else "compute"
    return {
        "arithmetic_intensity": round(ai, 6) if ai is not None else None,
        "achieved_gbps": round(gbps, 3) if gbps is not None else None,
        "hbm_peak_gbps": round(peak_bw / 1e9, 1) if peak_bw else None,
        "hbm_util": (round(gbps * 1e9 / peak_bw, 6)
                     if (gbps is not None and peak_bw) else None),
        "ridge_flop_per_byte": round(ridge, 3) if ridge else None,
        "bound": bound,
    }
