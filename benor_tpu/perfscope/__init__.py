"""perfscope — the AOT cost/memory observatory + perf regression gate.

The repo could *see* protocol behavior (flight recorder, witness/audit)
but measured performance by hand: one buried ``cost_analysis()`` probe
in bench.py, ad-hoc wall-clocks, and five BENCH_r*.json snapshots
nothing compared.  perfscope makes performance a first-class observable
for every compiled regime (traced XLA loop, fused pallas packed loop,
poll_rounds slices, batched dynamic-F sweep, sharded mesh):

  * per-stage AOT pipeline timing — trace/lower, backend compile, first
    execute, steady-state execute — fed into ``utils.metrics.REGISTRY``;
  * the executable's own XLA cost model (FLOPs, bytes accessed,
    transcendentals) and memory footprint (argument/output/temp/peak
    bytes) from ``cost_analysis()`` / ``memory_analysis()``;
  * arithmetic intensity + roofline placement against the device-kind
    peak tables (roofline.py — the table bench.py used to own);
  * a pinned-schema JSON manifest (tools/perf_report_schema.json,
    validated by tools/check_metrics_schema.py) and a regression gate
    (tools/check_perf_regression.py vs the committed PERF_BASELINE.json,
    exit 2 on regression — perfscope/baseline.py holds the bands).

Capture is OUT-OF-BAND: the profiled executable is AOT-built next to
the normal jit cache, so profiling changes neither results nor compile
counts of the unprofiled paths (pinned in tests/test_perfscope.py, the
flight-recorder discipline).  Surfaces: ``python -m benor_tpu profile``
(--profile-out/--baseline/--update-baseline, optional jax.profiler
Perfetto capture), bench.py's ``perf_ok`` headline bool + ``perfscope``
sidecar blob, and benorlint's ``perf-unregistered-jit`` rule keeping
every jit/AOT call site routed through ``instrument.py``.

NO-NEW-DEPS CONTRACT: perfscope is jax + numpy + stdlib only — the
``profile = []`` extra in pyproject.toml documents that adding a real
dependency (a profiler UI, a stats package) must be a reviewed decision,
not import creep; the comparison half (baseline.py, the regression
tool) is stdlib-only so CI can gate without initializing a backend.
"""

from .baseline import (IncomparableManifests, Regression,
                       STRUCTURAL_BANDS, check_bench_trajectory,
                       compare_manifests)
from .capture import PerfReport, REPORT_VERSION, build_report, capture_stages
from .instrument import (INSTRUMENTED, JIT_REGISTRY, AotArtifact,
                         aot_compile, cost_of, instrumented_jit)
from .manifest import (MANIFEST_KIND, build_manifest, load_manifest,
                       missing_regimes, save_manifest)
from .roofline import flops_peak_for, hbm_peak_for, roofline

__all__ = [
    "AotArtifact", "INSTRUMENTED", "IncomparableManifests",
    "JIT_REGISTRY", "MANIFEST_KIND", "PerfReport", "REPORT_VERSION",
    "Regression", "STRUCTURAL_BANDS", "aot_compile", "build_manifest",
    "build_report", "capture_all", "capture_regime", "capture_stages",
    "check_bench_trajectory", "compare_manifests", "cost_of",
    "flops_peak_for", "hbm_peak_for", "instrumented_jit",
    "load_manifest", "missing_regimes", "roofline", "save_manifest",
]


def capture_regime(name, **kw):
    """One regime's (PerfReport, outputs) — see regimes.capture_regime.
    (Lazy import: regimes pulls in sim/sweep/parallel, which themselves
    import perfscope.instrument — the package __init__ must stay cheap
    and cycle-free.)"""
    from .regimes import capture_regime as impl
    return impl(name, **kw)


def capture_all(**kw):
    """PerfReports for all five regimes — see regimes.capture_all."""
    from .regimes import capture_all as impl
    return impl(**kw)
