"""Telemetry-accumulator assembly: raw counters -> attribution rows.

The kernels hand back one int32 ``[stages, tiles, TELEM_WIDTH]``
accumulator per run (ops/pallas_round.py, SimConfig.kernel_telemetry) —
summed over rounds and trials, per-tile and per-stage resolution
preserved.  This module turns it into the manifest's ``stages`` blocks
and the derived ratios, and owns the JSON-lines record kind
``python -m benor_tpu watch`` renders for interleaved kernel-telemetry
records.  numpy-light by design: no jax import, so the watch path and
the manifest checkers never drag a backend in.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

#: JSON-lines record kind for live kernel-telemetry records
#: (metrics.append_jsonl producers; `python -m benor_tpu watch` has a
#: renderer for it, interleaved with heartbeats / sweep-journal
#: records).
KERNEL_TELEM_KIND = "kernel_telemetry"


def stage_report(telem, columns: Sequence[str],
                 stages: Sequence[str] = ("proposal", "vote")
                 ) -> Dict[str, dict]:
    """Accumulator int32 [stages, tiles, W] -> per-stage blocks:

      ``counters``  column name -> total over every tile
      ``per_tile``  tiles x W nested lists (the tile-level attribution
                    — a straggling or pad-dominated tile is visible, not
                    averaged away)
    """
    a = np.asarray(telem, dtype=np.int64)
    if a.ndim != 3 or a.shape[0] != len(stages) or \
            a.shape[2] != len(columns):
        raise ValueError(
            f"telemetry accumulator shape {a.shape} does not match "
            f"{len(stages)} stages x tiles x {len(columns)} columns")
    out = {}
    for i, stage in enumerate(stages):
        totals = a[i].sum(axis=0)
        out[stage] = {
            "counters": {c: int(totals[j]) for j, c in enumerate(columns)},
            "per_tile": [[int(v) for v in row] for row in a[i]],
        }
    return out


def pad_waste_frac(stage_blocks: Dict[str, dict]) -> Optional[float]:
    """Fraction of all lane-slots the kernels ran for PADDING — the
    relayout/re-tiling target number.  Computed from the proposal
    stage's counters (both stages see the identical lane split; using
    one keeps the recomputation in the manifest checker unambiguous).
    None when the accumulator never saw a lane (zero executed rounds).
    """
    c = stage_blocks["proposal"]["counters"]
    active, pad = c["active_lanes"], c["pad_lanes"]
    if active + pad == 0:
        return None
    return round(pad / (active + pad), 6)


def plane_hops_per_round(stage_blocks: Dict[str, dict], trials: int,
                         rounds: int) -> Optional[float]:
    """Plane-stack HBM round trips per protocol round, recovered from
    the hop counters: each tile emits its stage's static hop count once
    per trial per round, so the counter total is
    hops x tiles x trials x rounds and the per-round figure divides it
    back out — 2.0 on the single-pass kernel, 3.0 on the two-kernel
    pipeline, MEASURED from inside the kernels rather than assumed from
    the dispatch."""
    if trials <= 0 or rounds <= 0:
        return None
    total = 0.0
    for blk in stage_blocks.values():
        tiles = len(blk["per_tile"])
        if tiles == 0:
            return None
        total += blk["counters"]["plane_hops"] / (tiles * trials * rounds)
    return round(total, 6)


def telemetry_record(label: str, kernel: str, stage_blocks: Dict[str, dict],
                     rounds: int, waste: Optional[float]) -> dict:
    """One ``kind: kernel_telemetry`` JSON-lines record for the live
    watch plane (metrics.append_jsonl): stage totals only — compact
    enough to tail, the per-tile detail stays in the manifest."""
    return {
        "kind": KERNEL_TELEM_KIND,
        "label": label,
        "kernel": kernel,
        "rounds": int(rounds),
        "pad_waste_frac": waste,
        "stage_totals": {s: dict(b["counters"])
                         for s, b in stage_blocks.items()},
    }
