"""Kernel-manifest band comparator — the regression half of kernelscope.

STDLIB-ONLY by contract: ``tools/check_kernel_regression.py`` loads this
file by path with no jax (or even the package) importable, exactly like
perfscope/baseline.py and sweepscope/gate.py.  The comparison logic
lives HERE (next to the capture that produces the numbers) so bench.py
and CI judge with one implementation.

What gates (vs the committed KERNEL_BASELINE.json):

  * a kernel the baseline measured that vanished from the new manifest
    (a silently-demoted dispatch is the classic way a fast path dies);
  * stage counters drifting at the SAME scale/seed — they are
    deterministic integers, so any drift means the kernel interior
    changed work (sampler lanes, histogram visits, quorum passes, coin
    draws) without an acknowledged re-baseline;
  * pad-waste fraction growing past PAD_WASTE_SLACK — the re-tiling
    target number regressing;
  * the predicted/measured byte ratio leaving BYTE_RATIO_BAND in either
    direction — the layout tables and the executable's cost model
    telescoped before; if they stop, either the tables lie or the
    lowering regressed;
  * a fused-vs-XLA pair whose legs stopped being bit-equal.

Scale or platform mismatch is INCOMPARABLE (exit 3), never a silent
pass.
"""

from __future__ import annotations

import dataclasses
from typing import List

#: Multiplicative band for the predicted/measured byte ratio, both
#: directions (measured cost models wobble across jax versions; the
#: counters do not, so only the ratio gets a band).
BYTE_RATIO_BAND = 2.0

#: Absolute slack on the pad-waste fraction before growth regresses
#: (a new geometry legitimately moves it; same-scale captures may not).
PAD_WASTE_SLACK = 0.02

#: Fields whose per-kernel values must match EXACTLY at the same
#: scale/seed (deterministic integers measured in-kernel).
EXACT_COUNTER_STAGES = ("proposal", "vote")


class IncomparableKernels(Exception):
    """Baseline and manifest measure different platforms/scales."""


@dataclasses.dataclass
class KernelFinding:
    kind: str
    message: str

    def to_dict(self) -> dict:
        return {"kind": self.kind, "message": self.message}


def _require_comparable(manifest: dict, baseline: dict) -> None:
    for key in ("platform", "interpret"):
        if manifest.get(key) != baseline.get(key):
            raise IncomparableKernels(
                f"{key}: manifest {manifest.get(key)!r} vs baseline "
                f"{baseline.get(key)!r}")
    if manifest.get("scale") != baseline.get("scale"):
        raise IncomparableKernels(
            f"scale: manifest {manifest.get('scale')} vs baseline "
            f"{baseline.get('scale')}")


def compare_kernels(manifest: dict, baseline: dict,
                    ratio_band: float = BYTE_RATIO_BAND
                    ) -> List[KernelFinding]:
    """Findings = regressions of ``manifest`` against ``baseline``
    (empty list = in-band).  Raises IncomparableKernels on a
    platform/scale mismatch."""
    _require_comparable(manifest, baseline)
    findings: List[KernelFinding] = []
    base_k = baseline.get("kernels", {})
    new_k = manifest.get("kernels", {})
    for name in sorted(base_k):
        if name not in new_k:
            findings.append(KernelFinding(
                "missing-kernel",
                f"kernel {name!r} present in the baseline but absent "
                f"from the manifest — its dispatch no longer runs (or "
                f"the capture silently dropped it)"))
            continue
        b, m = base_k[name], new_k[name]
        if m.get("dispatch") != b.get("dispatch"):
            findings.append(KernelFinding(
                "dispatch-drift",
                f"{name}: dispatch {m.get('dispatch')!r} != baseline "
                f"{b.get('dispatch')!r} — the measured kernel is not "
                f"the one the baseline pinned"))
            continue
        for stage in EXACT_COUNTER_STAGES:
            bc = b.get("stages", {}).get(stage, {}).get("counters", {})
            mc = m.get("stages", {}).get(stage, {}).get("counters", {})
            if bc != mc:
                drift = {k: (bc.get(k), mc.get(k))
                         for k in set(bc) | set(mc)
                         if bc.get(k) != mc.get(k)}
                findings.append(KernelFinding(
                    "counter-drift",
                    f"{name}.{stage}: stage counters drifted at the "
                    f"same scale/seed (baseline, new): {drift} — the "
                    f"kernel interior changed work without a "
                    f"re-baseline"))
        bw, mw = b.get("pad_waste_frac"), m.get("pad_waste_frac")
        if bw is not None and mw is not None and \
                mw > bw + PAD_WASTE_SLACK:
            findings.append(KernelFinding(
                "pad-waste-regression",
                f"{name}: pad_waste_frac {mw:.4f} grew past baseline "
                f"{bw:.4f} + {PAD_WASTE_SLACK} — the padding waste the "
                f"re-tiling work is meant to shrink got worse"))
        br, mr = b.get("byte_ratio"), m.get("byte_ratio")
        if br and mr:
            rel = mr / br
            if rel > ratio_band or rel < 1.0 / ratio_band:
                findings.append(KernelFinding(
                    "byte-ratio-regression",
                    f"{name}: predicted/measured byte ratio {mr:.4f} "
                    f"is {rel:.2f}x the baseline's {br:.4f} (band "
                    f"{ratio_band}x) — the layout tables and the "
                    f"executable's cost model stopped telescoping"))
        elif br and not mr:
            findings.append(KernelFinding(
                "byte-ratio-regression",
                f"{name}: baseline measured a byte ratio ({br:.4f}) "
                f"but the manifest has none — the cost-model "
                f"cross-check vanished"))
    fvx_b = baseline.get("fused_vs_xla")
    fvx_m = manifest.get("fused_vs_xla")
    if fvx_b is not None:
        if fvx_m is None:
            findings.append(KernelFinding(
                "fused-vs-xla-missing",
                "baseline carries a fused_vs_xla pair but the manifest "
                "does not — the gap attribution vanished"))
        elif not fvx_m.get("bit_equal", False):
            findings.append(KernelFinding(
                "fused-vs-xla-diverged",
                "fused_vs_xla.bit_equal is false — the fused and "
                "baseline legs no longer agree, so the byte/stage "
                "attribution is meaningless"))
    return findings
