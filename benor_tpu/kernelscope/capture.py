"""Kernel-telemetry capture: run the fused dispatches armed, price them.

One capture covers BOTH fused dispatches at a fixed scale:

  * ``fused_one_pass`` — the uniform-CF flagship (counts_mode='sampled')
    on the single-pass kernel.  At the committed CPU-smoke scale the
    quorum sits under sampling.EXACT_TABLE_MAX, where the CF regime —
    and with it the kernel gate — never engages; the capture lowers the
    table bound for its own configs only (the exact trick
    tests/test_packed_state.py established for CPU-smoke kernel
    testing), restoring it afterwards.  On-chip captures at bench scale
    clear the real bound and never patch.
  * ``two_kernel`` — the count-controlling adversary (closed-form
    delivered counts, no sampler), which always takes the two-kernel
    plane pipeline: the inter-kernel hop is visible in its
    ``plane_hops`` counters and priced by the traffic model.

Per kernel: telemetry off vs on bit-equality, the per-stage/per-tile
counter report, the layout-derived predicted bytes
(perfscope/roofline.traffic_report) telescoped against the one-round
executable's ``cost_analysis`` ``bytes_accessed``, and — across the
pair — the fused-vs-XLA byte attribution per stage.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np

from .manifest import build_kernel_manifest
from .report import (pad_waste_frac, plane_hops_per_round,
                     stage_report, telemetry_record)

#: The fixed capture scale the committed KERNEL_BASELINE.json was taken
#: at (the perfscope smoke scale — counters are deterministic integers
#: at fixed scale/seed, which is what lets the gate pin them exactly).
CAPTURE_SCALE = {"n_nodes": 256, "trials": 8, "max_rounds": 12, "seed": 0}


def _fused_cfg(n, t, mr, seed, **kw):
    from ..config import SimConfig

    # f = 0.4N (the perfscope uniform regime's fraction): balanced
    # inputs put the decide bar above the typical class count, so the
    # capture exercises MULTI-round kernel work — quorum gates, coin
    # draws — instead of a degenerate 1-round decide
    return SimConfig(n_nodes=n, n_faulty=2 * n // 5, trials=t,
                     max_rounds=mr, seed=seed, delivery="quorum",
                     scheduler="uniform", path="histogram",
                     use_pallas_hist=True, use_pallas_round=True, **kw)


def _two_kernel_cfg(n, t, mr, seed, **kw):
    from ..config import SimConfig

    return SimConfig(n_nodes=n, n_faulty=n // 4 + (n - n // 4) % 2,
                     trials=t, max_rounds=mr, seed=seed,
                     delivery="quorum", scheduler="adversarial",
                     coin_mode="common", path="histogram",
                     use_pallas_round=True, **kw)


@contextlib.contextmanager
def _cf_regime(cfg):
    """Lower sampling.EXACT_TABLE_MAX so the CF regime (and the kernel
    gate) admits ``cfg`` at smoke scale — no-op when the real bound
    already clears.  The patch stays up for every run of the capture
    configs (the jitted executables bake the regime decision at trace
    time, so patch and runs must cover each other)."""
    from ..ops import sampling, tally

    if tally.pallas_round_active(cfg):
        yield
        return
    old = sampling.EXACT_TABLE_MAX
    sampling.EXACT_TABLE_MAX = min(old, max(cfg.quorum - 1, 1))
    try:
        if not tally.pallas_round_active(cfg):
            raise ValueError(
                f"capture config still fails the kernel gate with the "
                f"CF table bound lowered — not a capturable regime: "
                f"{cfg}")
        yield
    finally:
        sampling.EXACT_TABLE_MAX = old


def _inputs(cfg):
    import jax

    from ..state import FaultSpec, init_state
    from ..sweep import balanced_inputs

    faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes),
                       faults)
    return state, faults, jax.random.key(cfg.seed)


def _science(rounds, state):
    return (int(rounds), np.asarray(state.x), np.asarray(state.decided),
            np.asarray(state.k), np.asarray(state.killed))


def _bit_equal(a, b):
    return a[0] == b[0] and all(
        np.array_equal(x, y) for x, y in zip(a[1:], b[1:]))


def _one_round_bytes(cfg, state, faults, key) -> Optional[float]:
    """``cost_analysis`` bytes_accessed of ONE fused round at real
    operand shapes — packed_round jitted as-is, so the measured
    executable is the dispatch's own kernel chain (single-pass or
    two-kernel + reduce), not a proxy.  None when the backend has no
    cost model (cost_of's contract)."""
    import jax.numpy as jnp

    from ..ops import pallas_round as pr
    from ..ops.collectives import SINGLE
    from ..perfscope.instrument import cost_of
    from ..sim import start_state

    st = start_state(cfg, state)
    pack = pr.pack_state(cfg, st, faults.faulty)
    np_total = pack.shape[2] * pr.PACK_NODES_PER_WORD
    cr, rec = pr.pad_fault_rounds(cfg, faults, np_total)
    hist1 = pr.sent_hist_from_pack(cfg, pack, cr, rec, 1, SINGLE)
    n_local = cfg.n_nodes

    def one_round(pack, hist1, key):
        return pr.packed_round(cfg, pack, faults, key, jnp.int32(1),
                               hist1, SINGLE, n_local)

    cost = cost_of(one_round, pack, hist1, key,
                   label=f"kernelscope.round.{cfg.scheduler}")
    b = cost.get("bytes accessed")
    return float(b) if b else None


def capture_one_kernel(name: str, cfg, telemetry_path=None) -> dict:
    """One kernel regime -> its manifest blob (see manifest.py)."""
    from ..ops import pallas_round as pr
    from ..ops.tally import pallas_round_counts_mode
    from ..perfscope.roofline import traffic_report
    from ..sim import run_consensus
    from ..utils.metrics import append_jsonl

    state, faults, key = _inputs(cfg)
    off = run_consensus(cfg, state, faults, key)
    on = run_consensus(cfg.replace(kernel_telemetry=True), state, faults,
                       key)
    rounds = int(on[0])
    bit_equal = _bit_equal(_science(off[0], off[1]),
                           _science(on[0], on[1]))
    telem = np.asarray(on[2])
    stages = stage_report(telem, pr.TELEM_COLUMNS)
    waste = pad_waste_frac(stages)
    hops = plane_hops_per_round(stages, cfg.trials, rounds)
    measured = _one_round_bytes(cfg, state, faults, key)
    traffic = traffic_report(cfg, measured_bytes_per_round=measured)
    one_pass = pr.fused_one_pass_eligible(cfg, cfg.trials, cfg.n_nodes)
    blob = {
        "kernel": name,
        "dispatch": "one_pass" if one_pass else "two_kernel",
        "counts_mode": pallas_round_counts_mode(cfg),
        "rounds_executed": rounds,
        "bit_equal_off_on": bool(bit_equal),
        "geometry": traffic["geometry"],
        "stages": stages,
        "pad_waste_frac": waste,
        "plane_hops_per_round": hops,
        "predicted_bytes_per_round": traffic["predicted_bytes_per_round"],
        "measured_bytes_per_round": measured,
        "byte_ratio": traffic["byte_ratio"],
    }
    if telemetry_path:
        append_jsonl(telemetry_path,
                     telemetry_record("kernelscope", name, stages,
                                      rounds, waste))
    return blob


def _fused_vs_xla(cfg_fused) -> dict:
    """The paired fused-vs-XLA byte attribution: run both legs on
    identical inputs (the adversarial pairing — closed-form counts +
    common coin make plain XLA bit-comparable, the same pairing
    perfscope's capture_fused_vs_xla adjudicates), read each whole-run
    executable's cost-model bytes, and attribute the gap to kernel
    stages by the traffic model's predicted shares — the 'which stage
    moves the bytes' number ROADMAP item 2 reads."""
    from ..perfscope.instrument import cost_of
    from ..perfscope.roofline import traffic_report
    from ..sim import run_consensus

    cfg_xla = cfg_fused.replace(use_pallas_round=False)
    state, faults, key = _inputs(cfg_fused)
    runs = {}
    for label, cfg in (("fused", cfg_fused), ("xla", cfg_xla)):
        out = run_consensus(cfg, state, faults, key)
        runs[label] = _science(out[0], out[1])
    bit_equal = _bit_equal(runs["fused"], runs["xla"])

    def run_bytes(cfg):
        from ..sim import run_consensus as rc
        cost = cost_of(rc, cfg, state, faults, key,
                       label=f"kernelscope.fvx.{cfg.use_pallas_round}")
        b = cost.get("bytes accessed")
        return float(b) if b else None

    fused_b = run_bytes(cfg_fused)
    xla_b = run_bytes(cfg_xla)
    pred = traffic_report(cfg_fused)["predicted_bytes_per_round"]
    total = pred["total"] or 1
    attribution = {s: round(pred[s] / total, 6)
                   for s in ("proposal", "vote", "reduce")}
    return {
        "rounds_executed": runs["fused"][0],
        "bit_equal": bool(bit_equal),
        "counts_mode": "delivered",
        "fused_run_bytes": fused_b,
        "xla_run_bytes": xla_b,
        "gap_bytes": (round(xla_b - fused_b, 2)
                      if fused_b is not None and xla_b is not None
                      else None),
        "stage_attribution": attribution,
    }


def capture_kernels(n_nodes: Optional[int] = None,
                    trials: Optional[int] = None,
                    max_rounds: Optional[int] = None, seed: int = 0,
                    telemetry_path: Optional[str] = None) -> dict:
    """Full kernelscope capture -> the ``kind: kernel_manifest`` dict."""
    import jax

    from ..ops import pallas_round as pr

    scale = dict(CAPTURE_SCALE)
    for k, v in (("n_nodes", n_nodes), ("trials", trials),
                 ("max_rounds", max_rounds)):
        if v is not None:
            scale[k] = int(v)
    scale["seed"] = int(seed)
    n, t, mr = scale["n_nodes"], scale["trials"], scale["max_rounds"]

    kernels = {}
    cfg_one = _fused_cfg(n, t, mr, seed)
    with _cf_regime(cfg_one):
        kernels["fused_one_pass"] = capture_one_kernel(
            "fused_one_pass", cfg_one, telemetry_path=telemetry_path)
    cfg_two = _two_kernel_cfg(n, t, mr, seed)
    kernels["two_kernel"] = capture_one_kernel(
        "two_kernel", cfg_two, telemetry_path=telemetry_path)
    fvx = _fused_vs_xla(cfg_two)
    return build_kernel_manifest(
        kernels, scale, platform=jax.default_backend(),
        device_kind=jax.devices()[0].device_kind,
        interpret=jax.default_backend() == "cpu",
        telem_columns=list(pr.TELEM_COLUMNS), fused_vs_xla=fvx)
