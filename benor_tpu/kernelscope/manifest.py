"""The pinned-schema ``kind: kernel_manifest`` document.

Emitted by ``python -m benor_tpu profile --kernels`` and bench.py's
``kernelscope`` blob, validated (schema + cross-field recomputation) by
``tools/check_metrics_schema.py:check_kernel_manifest`` against
``tools/kernel_manifest_schema.json``, and gated against the committed
``KERNEL_BASELINE.json`` by ``tools/check_kernel_regression.py``
(file-path-loading gate.py).  Stdlib-only: capture hands plain dicts in.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

#: The manifest's ``kind`` tag — registered in
#: check_metrics_schema.MANIFEST_CHECKERS (benorlint's
#: manifest-kind-parity rule fails the build if that row vanishes).
KERNEL_MANIFEST_KIND = "kernel_manifest"

SCHEMA_VERSION = 1


def build_kernel_manifest(kernels: Dict[str, dict], scale: dict,
                          platform: str, device_kind: str,
                          interpret: bool,
                          telem_columns: List[str],
                          fused_vs_xla: Optional[dict] = None) -> dict:
    """Assemble the manifest from per-kernel capture blobs
    (capture.capture_kernels builds them; tests may hand-roll).  The
    cross-field facts the checker recomputes — pad-waste fraction,
    predicted-byte sums, byte ratio, per-tile totals — are all already
    inside ``kernels``; this function only pins the envelope."""
    return {
        "kind": KERNEL_MANIFEST_KIND,
        "schema_version": SCHEMA_VERSION,
        "platform": platform,
        "device_kind": device_kind,
        "interpret": bool(interpret),
        "scale": dict(scale),
        "telem_columns": list(telem_columns),
        "kernels": kernels,
        "fused_vs_xla": fused_vs_xla,
    }


def save_kernel_manifest(path: str, manifest: dict) -> None:
    with open(path, "w") as fh:
        json.dump(manifest, fh, indent=1, sort_keys=True)


def load_kernel_manifest(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("kind") != KERNEL_MANIFEST_KIND:
        raise ValueError(
            f"{path}: kind={doc.get('kind')!r} is not a kernel manifest")
    return doc
