"""kernelscope — tile-level observability for the pallas kernel interior.

Every host-side plane is instrumented (perfscope, meshscope, servescope,
sweepscope); the pallas kernel INTERIOR — the flagship fast path of the
TPU-scale claim — was the last black box: perfscope reports
whole-executable ``cost_analysis`` numbers, but nothing said which STAGE
of which TILE burns the bytes.  kernelscope is that instrument:

  * **In-kernel stage counters** (``SimConfig(kernel_telemetry=True)``):
    the fused round kernels (ops/pallas_round.py) append a block of
    telemetry columns — laid out by the declarative ``TELEM_COLS``
    name -> (base, width) table, the same discipline as REC_LAYOUT /
    WIT_LAYOUT / PACK_LAYOUT — to their existing per-tile partial
    buffers, counting per-tile/per-stage work: sampler lanes touched,
    histogram scatter visits, quorum-gate passes, coin draws, active vs
    pad lanes (the padding waste), and plane-stack HBM hops.  Zero extra
    HBM buffers; off (the default) is bit-identical in results AND
    compile counts (tests/test_kernelscope.py).
  * **Layout-derived traffic model** (perfscope/roofline.py
    ``traffic_report``): predicted HBM bytes per kernel stage priced
    straight from the PACK_LAYOUT / PARTIAL_COLS tables and grid
    geometry, telescoped against the executable's ``cost_analysis``
    ``bytes_accessed`` — "fused loses" becomes "fused loses because
    stage X moves Y predicted-vs-measured bytes".
  * **Manifest + gate**: ``python -m benor_tpu profile --kernels`` (and
    bench.py's ``kernelscope`` blob / ``kernel_obs_ok`` headline bool)
    emit the pinned-schema ``kind: kernel_manifest`` document
    (tools/kernel_manifest_schema.json, cross-field-recomputed by
    check_metrics_schema.check_kernel_manifest), gated against the
    committed KERNEL_BASELINE.json by the stdlib-only
    tools/check_kernel_regression.py (exit 0/2/3).

``gate``/``manifest``/``report`` are stdlib-importable (the regression
tool file-path-loads ``gate.py`` with no jax on its path); ``capture``
pulls jax and is imported lazily.
"""

from .gate import (KernelFinding, IncomparableKernels,  # noqa: F401
                   compare_kernels)
from .manifest import (KERNEL_MANIFEST_KIND,  # noqa: F401
                       build_kernel_manifest, load_kernel_manifest,
                       save_kernel_manifest)
from .report import (KERNEL_TELEM_KIND, pad_waste_frac,  # noqa: F401
                     stage_report)


def capture_kernels(**kw):
    """Lazy front door for the jax-heavy capture (see capture.py)."""
    from .capture import capture_kernels as _capture

    return _capture(**kw)
