"""Protocol invariant auditor: machine-checked Ben-Or forensics.

The flight recorder (state.REC_*) says *that* something happened — e.g.
``disagree_frac > 0`` in a safety study — but not which nodes decided
which value on what evidence.  This module closes that gap: it replays a
WITNESS buffer (SimConfig(witness_trials=..., witness_nodes=k); filled
on device by every compiled regime, state.WIT_* columns) and
machine-checks the Ben-Or invariants, emitting structured violation
reports with a minimal witness (trial, round, node ids, tallies) — every
simulated run becomes a self-verifying artifact, at scales where eyeballing
``/getState`` snapshots (the reference's only forensic tool) is
impossible.

The five audited invariants, each anchored to the reference
implementation (``src/nodes/node.ts``):

  agreement        No two honest nodes in one trial decide different
                   values.  The decide rule is ``count(v) > F`` with the
                   0-branch checked first (node.ts:99-104); the witness
                   records each decide's (v0, v1) evidence, so a
                   violation report names the two nodes, their decide
                   rounds AND the tallies that justified both decisions.
  validity         If every node starts with the same input v, any
                   decision is v.  The opposing count can then only come
                   from faulty senders, never exceeding F, so
                   ``count(¬v) > F`` (node.ts:99,102) is unsatisfiable —
                   checked when the witnessed inputs are known unanimous
                   (full node coverage, or the caller asserts it).
  irrevocability   ``decided`` is set (node.ts:100,103) and never unset:
                   a decided lane freezes and keeps broadcasting its value
                   forever (node.ts:147-157 — quirk 5), so its witnessed
                   (x, decided) must be constant from the decide round on.
  quorum evidence  Every decide is backed by a ``> F`` tally of its value
                   under the active decision rule: x=0 needs v0 > F
                   (node.ts:99), x=1 needs v1 > F AND v0 <= F (the
                   0-branch is checked first, node.ts:99-104 — both
                   ``rule='reference'`` and ``'textbook'`` share this
                   ordering); deciding "?" is impossible; the tallies
                   themselves are bounded by the quorum N - F
                   (node.ts:52,88).  A coin commit (node.ts:111) needs
                   the complementary evidence: no decide, and under the
                   reference's plurality-adopt quirk (node.ts:106-112) a
                   tied v0 == v1; under 'textbook', v0 <= F and v1 <= F.
                   RELAXED under the topo delivery plane (PR 12): when
                   the bundle carries a ``tally_bound`` (an adjacency
                   topology's d + 1 neighborhood, derived from
                   cfg.topology by WitnessBundle.from_run), quorum
                   evidence is judged within the NEIGHBORHOOD — the
                   decide bar stays count > F, and every witnessed
                   phase tally must additionally fit the structural
                   ceiling p0+p1 <= d+1, v0+v1 <= d+1 (a tally no
                   neighborhood could deliver is forged evidence).
  killed silence   A killed node stops participating: birth-faulty lanes
                   are dead with null state (node.ts:21-26), /stop kills
                   at any time (node.ts:191-194) — once the witnessed
                   killed bit is set the lane's (x, decided) must freeze
                   and it must never commit another coin.

Host-side and dependency-light (numpy + the metrics registry): the
auditor never touches a device.  ``audit_witness`` feeds pass/violation
counters into utils/metrics.REGISTRY, so audit outcomes flow to the
JSON-lines / Prometheus exporters alongside compile and timer metrics.
``results.py``'s safety studies auto-rerun violating points with
witnessing enabled and dump bundles via ``save_bundle``; the same bundle
renders as Perfetto trace slices through
utils/metrics.export_chrome_trace(witness=...).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from .config import SimConfig, VAL0, VAL1, VALQ
from .state import (WIT_COINED, WIT_COLUMNS, WIT_DECIDED, WIT_KILLED,
                    WIT_P0, WIT_P1, WIT_V0, WIT_V1, WIT_WIDTH, WIT_WRITTEN,
                    WIT_X, witness_node_ids)

#: The audited invariants, in check order — the single source of truth
#: for reports, the metrics counters and the witness-bundle schema.
#: ``down_silence`` (PR 15, the faultlab plane): a crash_recover lane
#: inside its down-interval [crash_round, recover_round) participates in
#: NOTHING — no decide, no coin commit, no state change — until it
#: rejoins; irrevocability (above) then keeps holding ACROSS the
#: recovery, amnesia or not (decisions are durable).
INVARIANTS = ("agreement", "validity", "irrevocability",
              "quorum_evidence", "killed_silence", "down_silence")


# --------------------------------------------------------------------------
# Bundle: a witness buffer plus the static facts the checks need.
# --------------------------------------------------------------------------


@dataclasses.dataclass
class WitnessBundle:
    """One run's witness evidence, self-describing for offline audit.

    ``buffer`` is the device-filled int32 [max_rounds + 1, W, k,
    WIT_WIDTH] array; ``trial_ids``/``node_ids`` name the watched GLOBAL
    ids; ``faulty`` (optional bool [W, k]) marks watched lanes that are
    protocol-faulty (equivocators / byzantine senders — their own
    decisions are excluded from the agreement/validity checks);
    ``unanimous`` (0, 1 or None) asserts that ALL inputs — watched or not
    — were that value, arming the validity check even under partial node
    coverage.
    """

    buffer: np.ndarray
    trial_ids: np.ndarray          # int [W] global trial ids
    node_ids: np.ndarray           # int [k] global node ids
    rule: str                      # 'reference' | 'textbook'
    n_faulty: int                  # F — the decide bar count > F
    n_nodes: int
    freeze_decided: bool = True
    faulty: Optional[np.ndarray] = None     # bool [W, k] or None
    unanimous: Optional[int] = None         # 0 | 1 | None
    #: Structural ceiling on any witnessed tally — the RELAXED quorum-
    #: evidence bound of the topo delivery plane (ROADMAP item 3): under
    #: an adjacency topology a receiver tallies at most its d + 1
    #: neighborhood, so any p0+p1 / v0+v1 beyond that is forged
    #: evidence the complete-graph checks could never see.  None (every
    #: pre-topology bundle) disables the bound — the global quorum
    #: bound stays implied by the decide-bar checks, exactly as before.
    tally_bound: Optional[int] = None
    #: Faultlab evidence (PR 15).  ``partition``: the run's partition
    #: spec string (faults/partitions.py grammar) — during the epoch
    #: (1 <= round < heal_round) every witnessed tally is additionally
    #: bounded by the watched node's GROUP size (quorum evidence judged
    #: within the partition epoch); None = no partition, no bound.
    #: ``down_crash`` / ``down_recover`` (int [W, k] or None): the
    #: watched lanes' crash_recover down-interval bounds, arming the
    #: down_silence check; None = no churn schedule.
    partition: Optional[str] = None
    down_crash: Optional[np.ndarray] = None
    down_recover: Optional[np.ndarray] = None
    label: str = ""

    @classmethod
    def from_run(cls, cfg: SimConfig, buffer, faults=None,
                 unanimous: Optional[int] = None,
                 label: str = "") -> "WitnessBundle":
        """Bundle a run's witness output with the facts its config and
        (optionally) FaultSpec pin down.  ``faults`` narrows the honest
        population — but only under the lying fault models
        ('byzantine'/'equivocate'): a fail-stop lane ('crash',
        'crash_at_round') follows the protocol until it dies, so its
        decisions MUST count for agreement/validity.  ``unanimous``
        asserts globally-unanimous inputs.  Under an adjacency topology
        (cfg.topology) the bundle carries the d + 1 neighborhood as its
        ``tally_bound`` — the relaxed quorum-evidence ceiling the
        auditor enforces instead of the (unrepresentable) global
        quorum."""
        if not cfg.witness:
            raise ValueError("cfg has no witness armed (witness_trials)")
        trial_ids = np.asarray(cfg.witness_trials, np.int64)
        node_ids = np.asarray(witness_node_ids(cfg), np.int64)
        faulty = None
        if faults is not None and cfg.fault_model in ("byzantine",
                                                      "equivocate"):
            f = np.asarray(faults.faulty)
            faulty = f[np.ix_(trial_ids, node_ids)]
        bound = None
        if cfg.topology is not None:
            from .topo.graphs import parse_topology
            bound = parse_topology(cfg.topology).degree + 1
        down_crash = down_recover = None
        if cfg.fault_model == "crash_recover" and faults is not None \
                and faults.recover_round is not None:
            sel = np.ix_(trial_ids, node_ids)
            down_crash = np.asarray(faults.crash_round)[sel]
            down_recover = np.asarray(faults.recover_round)[sel]
        return cls(buffer=np.asarray(buffer), trial_ids=trial_ids,
                   node_ids=node_ids, rule=cfg.rule,
                   n_faulty=cfg.n_faulty, n_nodes=cfg.n_nodes,
                   freeze_decided=cfg.freeze_decided, faulty=faulty,
                   unanimous=unanimous, tally_bound=bound,
                   partition=cfg.partition, down_crash=down_crash,
                   down_recover=down_recover, label=label)

    def to_dict(self) -> Dict:
        return {
            "label": self.label,
            "rule": self.rule,
            "n_faulty": int(self.n_faulty),
            "n_nodes": int(self.n_nodes),
            "freeze_decided": bool(self.freeze_decided),
            "trial_ids": [int(t) for t in self.trial_ids],
            "node_ids": [int(n) for n in self.node_ids],
            "unanimous": (None if self.unanimous is None
                          else int(self.unanimous)),
            "tally_bound": (None if self.tally_bound is None
                            else int(self.tally_bound)),
            "partition": self.partition,
            "down_crash": (None if self.down_crash is None
                           else np.asarray(self.down_crash)
                           .astype(int).tolist()),
            "down_recover": (None if self.down_recover is None
                             else np.asarray(self.down_recover)
                             .astype(int).tolist()),
            "faulty": (None if self.faulty is None
                       else np.asarray(self.faulty).astype(bool).tolist()),
            "columns": list(WIT_COLUMNS),
            "buffer": np.asarray(self.buffer).astype(int).tolist(),
        }


def witness_rows(buffer, trial_ids, node_ids) -> List[dict]:
    """Witness buffer -> one dict per written (round, trial, node) entry,
    WIT_COLUMNS-keyed (minus the sentinel) plus global "round"/"trial"/
    "node" ids — the rendering contract TpuNetwork.get_witness and the
    Perfetto exporter share.  Unwritten rows (gap rows of a fresh-buffer
    resume included) are skipped via the WIT_WRITTEN sentinel."""
    buf = np.asarray(buffer).astype(np.int64)
    rows = []
    for r in np.nonzero(buf[:, 0, 0, WIT_WRITTEN] > 0)[0]:
        for wi, t in enumerate(trial_ids):
            for ki, n in enumerate(node_ids):
                d = {"round": int(r), "trial": int(t), "node": int(n)}
                d.update({col: int(v) for col, v
                          in zip(WIT_COLUMNS[:WIT_WRITTEN],
                                 buf[r, wi, ki])})
                rows.append(d)
    return rows


# --------------------------------------------------------------------------
# Reports
# --------------------------------------------------------------------------


@dataclasses.dataclass
class Violation:
    """One invariant breach with its minimal witness."""

    invariant: str                 # one of INVARIANTS
    trial: int                     # global trial id
    round: int                     # round index of the (last) breach
    nodes: List[int]               # global node ids involved
    detail: Dict                   # tallies / values justifying the claim
    message: str

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class AuditReport:
    """The auditor's verdict over one witness bundle."""

    ok: bool
    violations: List[Violation]
    checks: Dict[str, int]         # per-invariant count of checks applied
    rounds_audited: int
    lanes_audited: int
    label: str = ""

    def to_dict(self) -> Dict:
        return {
            "ok": self.ok,
            "label": self.label,
            "rounds_audited": self.rounds_audited,
            "lanes_audited": self.lanes_audited,
            "checks": dict(self.checks),
            "n_violations": len(self.violations),
            "violations": [v.to_dict() for v in self.violations],
        }

    def summary(self) -> str:
        if self.ok:
            return (f"audit OK: {self.lanes_audited} lanes x "
                    f"{self.rounds_audited} rounds, "
                    f"{sum(self.checks.values())} checks, 0 violations")
        v = self.violations[0]
        return (f"audit FAILED: {len(self.violations)} violation(s); "
                f"first: {v.message}")


# --------------------------------------------------------------------------
# The auditor
# --------------------------------------------------------------------------


def _first_decide(series):
    """(decide_round_index_into_series or None, pre_decided: bool)."""
    dec = series[:, WIT_DECIDED] > 0
    if not dec.any():
        return None, False
    first = int(np.argmax(dec))
    return first, first == 0      # decided in row 0 => decide unobserved


def _decide_claim(node, value, rd, v0, v1, F):
    """One node's decide, phrased with only the facts the witness saw:
    a snapshot-decided lane (fresh-buffer resume) has no observed tallies
    — never assert quorum evidence the buffer doesn't contain."""
    tally = v0 if value == VAL0 else v1
    if tally is None:
        return (f"node {node} decided {value} at round {rd} "
                f"(decide pre-dates the witness window)")
    return (f"node {node} decided {value} at round {rd} "
            f"(v{value}={tally} > F={F})")


def audit_witness(bundle: WitnessBundle) -> AuditReport:
    """Machine-check the Ben-Or invariants over a witness bundle.

    Returns an AuditReport whose violations carry minimal witnesses
    (trial, round, node ids, tallies).  Feeds the audit.* counters of
    utils/metrics.REGISTRY (runs / pass / violations, plus one counter
    per violated invariant) so outcomes reach the exporters.
    """
    buf = np.asarray(bundle.buffer).astype(np.int64)
    if buf.ndim != 4 or buf.shape[-1] != WIT_WIDTH:
        raise ValueError(
            f"witness buffer must be [rounds, W, k, {WIT_WIDTH}]; got "
            f"{buf.shape}")
    W, k = buf.shape[1], buf.shape[2]
    F = int(bundle.n_faulty)
    violations: List[Violation] = []
    checks = {name: 0 for name in INVARIANTS}
    written = np.nonzero(buf[:, 0, 0, WIT_WRITTEN] > 0)[0]
    part_spec = None
    if bundle.partition is not None:
        from .faults.partitions import parse_partition
        part_spec = parse_partition(bundle.partition)

    # validity ground truth: caller-asserted, or derivable when the
    # witness covers EVERY node (k == n_nodes) and row 0 is unanimous —
    # partial coverage must not let a locally-unanimous watched set
    # masquerade as global unanimity (an honest global-minority decide
    # would then be flagged as a violation that never happened)
    full_cover = k == bundle.n_nodes and 0 in written

    for wi in range(W):
        trial = int(bundle.trial_ids[wi])
        honest = np.ones(k, bool)
        if bundle.faulty is not None:
            honest = ~np.asarray(bundle.faulty[wi], bool)

        unanimous = bundle.unanimous
        if unanimous is None and full_cover:
            x0 = buf[0, wi, :, WIT_X]
            live0 = buf[0, wi, :, WIT_KILLED] == 0
            vals = np.unique(x0[honest & live0])
            if len(vals) == 1 and vals[0] in (VAL0, VAL1):
                unanimous = int(vals[0])

        decided_evidence = []      # (node_id, value, round, v0, v1) honest
        for ki in range(k):
            node = int(bundle.node_ids[ki])
            rounds, series = written, buf[written, wi, ki, :]
            x = series[:, WIT_X]
            dec = series[:, WIT_DECIDED] > 0
            killed = series[:, WIT_KILLED] > 0
            coined = series[:, WIT_COINED] > 0
            v0, v1 = series[:, WIT_V0], series[:, WIT_V1]

            first, pre_decided = _first_decide(series)

            # --- neighborhood tally bound (topo delivery plane) ---------
            # Under an adjacency topology the quorum rule is
            # NEIGHBORHOOD-relative: a receiver tallies at most its
            # d + 1 neighborhood, so any witnessed phase tally beyond
            # bundle.tally_bound is forged evidence — the relaxed
            # invariant ROADMAP item 3 asks the auditor to learn.
            # Filed under quorum_evidence: it is the structural half of
            # the same "was this decide backed by real counts" claim.
            if bundle.tally_bound is not None:
                checks["quorum_evidence"] += 1
                p0, p1 = series[:, WIT_P0], series[:, WIT_P1]
                over = np.nonzero((p0 + p1 > bundle.tally_bound) |
                                  (v0 + v1 > bundle.tally_bound))[0]
                for oi in over:
                    rd = int(rounds[oi])
                    violations.append(Violation(
                        "quorum_evidence", trial, rd, [node],
                        {"round": rd, "p0": int(p0[oi]), "p1": int(p1[oi]),
                         "v0": int(v0[oi]), "v1": int(v1[oi]),
                         "tally_bound": int(bundle.tally_bound)},
                        f"trial {trial} node {node} tallied more "
                        f"messages than its d+1={int(bundle.tally_bound)}"
                        f" neighborhood can deliver at round {rd} "
                        f"(p0+p1={int(p0[oi] + p1[oi])}, "
                        f"v0+v1={int(v0[oi] + v1[oi])}) — forged "
                        "evidence under the topology-relative quorum"))

            # --- partition-epoch tally bound (faultlab, PR 15) ----------
            # During the epoch (1 <= round < heal_round) a receiver can
            # tally at most its GROUP: any witnessed phase tally beyond
            # the group size is forged cross-partition quorum evidence.
            # Filed under quorum_evidence like the neighborhood bound —
            # the structural half of the same claim.  Row 0 is the
            # pre-round snapshot (no tallies) and rounds >= heal_round
            # see the whole network again.
            if part_spec is not None:
                from .faults.partitions import group_size_of
                checks["quorum_evidence"] += 1
                gsize = group_size_of(node, bundle.n_nodes, part_spec)
                p0, p1 = series[:, WIT_P0], series[:, WIT_P1]
                epoch = (rounds >= 1) & (rounds < part_spec.heal_round)
                over = np.nonzero(epoch & ((p0 + p1 > gsize) |
                                           (v0 + v1 > gsize)))[0]
                for oi in over:
                    rd = int(rounds[oi])
                    violations.append(Violation(
                        "quorum_evidence", trial, rd, [node],
                        {"round": rd, "p0": int(p0[oi]), "p1": int(p1[oi]),
                         "v0": int(v0[oi]), "v1": int(v1[oi]),
                         "group_size": int(gsize),
                         "heal_round": int(part_spec.heal_round)},
                        f"trial {trial} node {node} tallied more "
                        f"messages than its partition group of "
                        f"{int(gsize)} can deliver at round {rd} "
                        f"(p0+p1={int(p0[oi] + p1[oi])}, "
                        f"v0+v1={int(v0[oi] + v1[oi])}; epoch heals at "
                        f"round {int(part_spec.heal_round)}) — forged "
                        "cross-partition quorum evidence"))

            # --- down-interval silence (faultlab, PR 15) ----------------
            # A crash_recover lane inside [crash_round, recover_round)
            # participates in NOTHING: no coin commit, no decide flip,
            # no state change — its witnessed rows must equal the last
            # pre-crash row until the rejoin.
            if bundle.down_crash is not None:
                cr_b = int(bundle.down_crash[wi, ki])
                rv_b = int(bundle.down_recover[wi, ki])
                if cr_b > 0:
                    checks["down_silence"] += 1
                    interval = rounds >= cr_b
                    if rv_b > 0:
                        interval = interval & (rounds < rv_b)
                    before = np.nonzero(rounds < cr_b)[0]
                    idx = np.nonzero(interval)[0]
                    if before.size and idx.size:
                        b0 = int(before[-1])
                        bad = ((coined[idx]) |
                               (dec[idx] != dec[b0]) |
                               (x[idx] != x[b0]))
                        for oi in np.nonzero(bad)[0]:
                            rd = int(rounds[idx[oi]])
                            violations.append(Violation(
                                "down_silence", trial, rd, [node],
                                {"round": rd, "crash_round": cr_b,
                                 "recover_round": rv_b,
                                 "x_before": int(x[b0]),
                                 "x": int(x[idx[oi]]),
                                 "decided_before": bool(dec[b0]),
                                 "decided": bool(dec[idx[oi]]),
                                 "coined": bool(coined[idx[oi]])},
                                f"trial {trial} node {node} "
                                f"participated at round {rd} inside "
                                f"its down interval "
                                f"[{cr_b}, {rv_b if rv_b > 0 else '∞'})"
                                " — a down lane must be silent"))

            # --- irrevocability (node.ts:100,103,147-157) ---------------
            checks["irrevocability"] += 1
            if first is not None:
                tail = slice(first, None)
                if not dec[tail].all():
                    rbad = int(rounds[first:][~dec[tail]][0])
                    violations.append(Violation(
                        "irrevocability", trial, rbad, [node],
                        {"decide_round": int(rounds[first])},
                        f"trial {trial} node {node} revoked decided at "
                        f"round {rbad} (decided at {int(rounds[first])})"))
                elif bundle.freeze_decided and \
                        (x[tail] != x[first]).any():
                    bad_i = first + int(np.argmax(x[tail] != x[first]))
                    rbad = int(rounds[bad_i])
                    violations.append(Violation(
                        "irrevocability", trial, rbad, [node],
                        {"decided_value": int(x[first]),
                         "changed_to": int(x[bad_i])},
                        f"trial {trial} node {node} changed its decided "
                        f"value after deciding (round {rbad})"))

            # --- quorum evidence (node.ts:99-104; coin node.ts:111) -----
            if first is not None and not pre_decided:
                checks["quorum_evidence"] += 1
                rd = int(rounds[first])
                val = int(x[first])
                ev = {"round": rd, "v0": int(v0[first]),
                      "v1": int(v1[first]), "F": F}
                if val == VALQ:
                    violations.append(Violation(
                        "quorum_evidence", trial, rd, [node], ev,
                        f"trial {trial} node {node} decided \"?\" at "
                        f"round {rd} — no decide branch produces it"))
                elif val == VAL0 and not v0[first] > F:
                    violations.append(Violation(
                        "quorum_evidence", trial, rd, [node], ev,
                        f"trial {trial} node {node} decided 0 at round "
                        f"{rd} on v0={int(v0[first])} <= F={F}"))
                elif val == VAL1 and not v1[first] > F:
                    violations.append(Violation(
                        "quorum_evidence", trial, rd, [node], ev,
                        f"trial {trial} node {node} decided 1 at round "
                        f"{rd} on v1={int(v1[first])} <= F={F}"))
                elif val == VAL1 and v0[first] > F:
                    violations.append(Violation(
                        "quorum_evidence", trial, rd, [node], ev,
                        f"trial {trial} node {node} decided 1 at round "
                        f"{rd} although v0={int(v0[first])} > F={F} — "
                        "the 0-branch is checked first (node.ts:99)"))
            # coin commits carry complementary evidence
            for ci in np.nonzero(coined)[0]:
                checks["quorum_evidence"] += 1
                rd, ev = int(rounds[ci]), {
                    "round": int(rounds[ci]), "v0": int(v0[ci]),
                    "v1": int(v1[ci]), "F": F}
                # a decided lane only stops coining when it freezes; with
                # freeze_decided=False it legally re-coins on later ties
                bad = ((bundle.freeze_decided and dec[ci]) or
                       (bundle.rule == "reference" and v0[ci] != v1[ci]) or
                       (bundle.rule == "textbook" and
                        (v0[ci] > F or v1[ci] > F)))
                if bad:
                    violations.append(Violation(
                        "quorum_evidence", trial, rd, [node], ev,
                        f"trial {trial} node {node} committed a coin at "
                        f"round {rd} despite decide/adopt evidence "
                        f"(v0={int(v0[ci])}, v1={int(v1[ci])})"))

            # --- killed silence (node.ts:21-26,191-194) -----------------
            checks["killed_silence"] += 1
            if killed.any():
                kf = int(np.argmax(killed))
                tail = slice(kf, None)
                if (x[tail] != x[kf]).any() or \
                        (series[tail, WIT_DECIDED] !=
                         series[kf, WIT_DECIDED]).any() or \
                        coined[tail].any():
                    rbad = int(rounds[kf])
                    violations.append(Violation(
                        "killed_silence", trial, rbad, [node],
                        {"killed_round": int(rounds[kf])},
                        f"trial {trial} node {node} kept participating "
                        f"after being killed at round {int(rounds[kf])}"))

            # collect the decide evidence for the trial-level checks; a
            # snapshot decide (pre_decided: fresh-buffer resume) is a real
            # decision but its justifying tallies were never witnessed
            if honest[ki] and first is not None and \
                    int(x[first]) in (VAL0, VAL1):
                decided_evidence.append(
                    (node, int(x[first]), int(rounds[first]),
                     None if pre_decided else int(v0[first]),
                     None if pre_decided else int(v1[first])))

        # --- agreement (node.ts:99-104) ---------------------------------
        checks["agreement"] += 1
        by_value: Dict[int, tuple] = {}
        for evd in decided_evidence:
            by_value.setdefault(evd[1], evd)
        if VAL0 in by_value and VAL1 in by_value:
            a, b = by_value[VAL0], by_value[VAL1]
            violations.append(Violation(
                "agreement", trial, max(a[2], b[2]), [a[0], b[0]],
                {"node_a": {"node": a[0], "value": 0, "round": a[2],
                            "v0": a[3], "v1": a[4]},
                 "node_b": {"node": b[0], "value": 1, "round": b[2],
                            "v0": b[3], "v1": b[4]},
                 "F": F},
                f"trial {trial}: "
                f"{_decide_claim(a[0], 0, a[2], a[3], a[4], F)} but "
                f"{_decide_claim(b[0], 1, b[2], b[3], b[4], F)}"
                " — agreement violated"))

        # --- validity ----------------------------------------------------
        if unanimous is not None:
            checks["validity"] += 1
            for node, val, rd, e0, e1 in decided_evidence:
                if val != unanimous:
                    violations.append(Violation(
                        "validity", trial, rd, [node],
                        {"unanimous_input": int(unanimous),
                         "decided": val, "v0": e0, "v1": e1, "F": F},
                        f"trial {trial} node {node} decided {val} at "
                        f"round {rd} despite unanimous input "
                        f"{int(unanimous)}"))

    report = AuditReport(
        ok=not violations, violations=violations, checks=checks,
        rounds_audited=max(len(written) - 1, 0), lanes_audited=W * k,
        label=bundle.label)

    from .utils.metrics import REGISTRY
    REGISTRY.counter("audit.runs").inc()
    REGISTRY.counter("audit.pass" if report.ok else "audit.fail").inc()
    REGISTRY.counter("audit.violations").inc(len(violations))
    for v in violations:
        REGISTRY.counter(f"audit.violation.{v.invariant}").inc()
    return report


# --------------------------------------------------------------------------
# Convenience: run-and-audit, bundle persistence
# --------------------------------------------------------------------------


def default_witness_overrides(trials: int, n_nodes: int) -> Dict:
    """The default forensic watch-set, as SimConfig overrides: the first
    min(trials, 4) trials and as many nodes as the device buffer allows
    (witness_node_ids puts them at both ends of the id range, where the
    adversary camps and fault masks live).  The single policy the bench
    witness proof, the CLI ``audit`` defaults and results.py's safety
    reruns all share — edit it here and they stay in lockstep."""
    from .config import WITNESS_MAX_NODES
    return {"witness_trials": tuple(range(min(trials, 4))),
            "witness_nodes": min(n_nodes, WITNESS_MAX_NODES)}


def audit_point(cfg: SimConfig, initial_values=None, faults=None,
                unanimous: Optional[int] = None, label: str = ""):
    """Run one witnessed MC batch and audit it -> (report, bundle).

    ``cfg`` must have the witness armed; inputs/faults default like
    sweep.run_point (per-trial random bits, first-F-faulty).  The bundle
    carries the watched lanes' faulty mask, so equivocators'/byzantine
    senders' own decisions stay out of the agreement check.
    """
    import jax

    from .state import init_state
    from .sim import run_consensus
    from .sweep import default_crash_faults, random_inputs

    if not cfg.witness:
        raise ValueError(
            "audit_point needs a witnessed config: set "
            "SimConfig(witness_trials=..., witness_nodes=k)")
    if initial_values is None:
        initial_values = random_inputs(cfg.seed, cfg.trials, cfg.n_nodes)
    if faults is None:
        # run_point's exact default policy (first-F-faulty; crash_recover
        # realizes the cfg.recovery schedule) so an audited point IS the
        # swept point
        faults = default_crash_faults(cfg)
    state = init_state(cfg, initial_values, faults)
    out = run_consensus(cfg, state, faults, jax.random.key(cfg.seed))
    witness = out[-1]
    bundle = WitnessBundle.from_run(cfg, witness, faults=faults,
                                    unanimous=unanimous, label=label)
    return audit_witness(bundle), bundle


def save_bundle(path: str, bundle: WitnessBundle,
                report: Optional[AuditReport] = None) -> None:
    """Dump a witness bundle (+ its audit verdict) as one JSON document —
    the artifact results.py's safety studies attach to violating points
    (schema pinned by tools/witness_bundle_schema.json)."""
    doc = bundle.to_dict()
    if report is not None:
        doc["audit"] = report.to_dict()
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1)


def load_bundle(path: str) -> WitnessBundle:
    """Re-hydrate a saved bundle for offline (re-)auditing."""
    with open(path) as fh:
        doc = json.load(fh)
    return WitnessBundle(
        buffer=np.asarray(doc["buffer"], np.int64),
        trial_ids=np.asarray(doc["trial_ids"], np.int64),
        node_ids=np.asarray(doc["node_ids"], np.int64),
        rule=doc["rule"], n_faulty=doc["n_faulty"],
        n_nodes=doc["n_nodes"],
        freeze_decided=doc.get("freeze_decided", True),
        faulty=(None if doc.get("faulty") is None
                else np.asarray(doc["faulty"], bool)),
        unanimous=doc.get("unanimous"),
        tally_bound=doc.get("tally_bound"),
        partition=doc.get("partition"),
        down_crash=(None if doc.get("down_crash") is None
                    else np.asarray(doc["down_crash"], np.int64)),
        down_recover=(None if doc.get("down_recover") is None
                      else np.asarray(doc["down_recover"], np.int64)),
        label=doc.get("label", ""))
