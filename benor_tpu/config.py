"""Typed simulation configuration.

The reference (`/root/reference`) has exactly one config constant —
``BASE_NODE_PORT`` (src/config.ts:1) — and passes everything else positionally
(src/index.ts:4-9, src/nodes/node.ts:8-16).  This module is the framework's
replacement: a single frozen dataclass that is *static* under ``jax.jit``
(hashable, passed as a static argument), covering the protocol parameters the
reference hardcodes plus the new TPU-native axes (trials, delivery model,
fault model, mesh shape, coin mode).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

#: TCP port of node 0 for the HTTP observation layer — parity with
#: reference src/config.ts:1 (node i listens on BASE_NODE_PORT + i).
BASE_NODE_PORT = 3000

# Encodings of the protocol value domain ``Value = 0 | 1 | "?"``
# (reference src/types.ts:8) as int8 device scalars.
VAL0 = 0
VAL1 = 1
VALQ = 2  # the "?" value

#: Ceiling on SimConfig.witness_nodes.  The fused pallas round emits the
#: witness as extra per-tile partial COLUMNS of its [tiles, T, 128]
#: reduction layout (ops/pallas_round.py): the vote kernel spends 5 base
#: + 7 flight-recorder + 6-per-watched-node columns, so 16 watched nodes
#: (12 + 96 = 108 <= 128) is the largest count every regime can serve
#: uniformly.  The XLA paths could watch more, but a config that works in
#: one regime and explodes in another would defeat the witness's whole
#: cross-regime-forensics contract.
WITNESS_MAX_NODES = 16


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Static configuration for one simulated Ben-Or network.

    Attributes mirror the reference's launch parameters
    (``launchNetwork(N, F, initialValues, faultyList)``, src/index.ts:4-9)
    plus the TPU-native extensions mandated by BASELINE.json.
    """

    # --- protocol parameters (reference parity) -------------------------
    n_nodes: int                      # N — total nodes
    n_faulty: int                     # F — protocol fault parameter; quorum = N - F
    max_rounds: int = 32              # round cap (reference loops forever; node.ts:147-157)

    # --- decision rule --------------------------------------------------
    # 'reference': plurality-adopt before coin (node.ts:106-112 — quirk 9 in
    #   SURVEY §2.1; required for the k<=2 test bounds).
    # 'textbook': flip the coin whenever no value has > F votes (classic Ben-Or).
    rule: str = "reference"

    # --- randomness -----------------------------------------------------
    seed: int = 0
    # 'private':     independent fair coin per (trial, node, round) —
    #                reference Math.random() at node.ts:111.
    # 'common':      one shared coin per (trial, round) — the shared-
    #                common-coin variant (expected O(1) rounds).
    # 'weak_common': each lane sees the shared coin with probability
    #                1 - coin_eps, an independent private flip otherwise —
    #                the classical weak-coin abstraction interpolating the
    #                two (eps=0 ~ common, eps=1 ~ private); termination
    #                under the count-controlling adversary has a sharp
    #                phase transition in eps (results.weak_coin_study).
    coin_mode: str = "private"
    # Per-lane deviation probability for coin_mode='weak_common'.
    coin_eps: float = 0.0

    # --- delivery / scheduler (the N9 asynchrony model) -----------------
    # 'all':    every receiver tallies every live sender's message (the
    #           reference's *final* tally once all fetches land; deterministic).
    # 'quorum': every receiver tallies exactly N-F messages chosen by the
    #           scheduler — the "first N-F arrivals win" nondeterminism of
    #           node.ts:52,88 made explicit and seeded.
    delivery: str = "all"
    # subset selection when delivery == 'quorum':
    # 'uniform':     uniformly random N-F subset of live senders per receiver
    # 'biased':      split adversary delaying starved-class edges by
    #                adversary_strength.  Any strength on both paths: the
    #                dense path races per-edge delays; the histogram path is
    #                exact strict priority at strength >= 1 and the
    #                uniform-race model (ops/sampling.py) at 0 < s < 1.
    # 'adversarial': worst-case count-controlling adversary — forces tied
    #                0/1 tallies at every receiver (both paths); attacks
    #                TERMINATION (livelock under private coins)
    # 'targeted':    partitioned count-controlling adversary — attacks
    #                AGREEMENT directly (the true worst case of the
    #                node.ts:52,88 "first N-F arrivals" nondeterminism:
    #                nothing forces two receivers to tally the same
    #                multiset).  Three receiver camps (ops/tally.py:
    #                targeted_counts): F+1 ids seeded to decide 0, F+1 to
    #                decide 1, the rest fed perfect ties so they vote "?"
    #                and (via quirk 4, quorum-counts-"?") starve the
    #                1-camp's zero-count below the decide bar.  With an
    #                even quorum N-F this violates agreement for EVERY
    #                1 <= F < N/2 and livelocks at F >= N/2 — the sharpest
    #                possible threshold, at the fault-tolerance boundary.
    #                Under fault_model='equivocate' equivocators
    #                substitute for camp members and repair quorum parity:
    #                ONE equivocator splits the network at any N (the
    #                count > F decide rule has no Byzantine safety
    #                margin).  Closed form on BOTH compute paths;
    #                realizable as an explicit delivery schedule
    #                (ops/scheduler.py:realize_counts_mask, pinned in
    #                tests/test_targeted.py).
    scheduler: str = "uniform"
    # Delay added by the 'biased' scheduler to starved-class edges.
    adversary_strength: float = 0.0

    # --- structured delivery planes (benor_tpu/topo) ---------------------
    # Adjacency-structured delivery: a topology spec string replaces the
    # implicit complete graph — each receiver tallies exactly its d graph
    # neighbors plus itself (quirk 6: broadcasts include self), so the
    # decide rule count > F relativizes to the d+1 neighborhood.  Specs
    # (grammar in benor_tpu/topo/graphs.py): 'complete' (the identity —
    # normalized to None here, so selecting it is bit-identical to the
    # pre-topology path in results AND compile counts), 'ring:<d>',
    # 'torus2d:<rows>x<cols>', 'expander:<d>',
    # 'random_regular:<d>[:seed]'.  Requires delivery='all' (structured
    # delivery IS the deterministic neighbor fan-in; the quorum-subset
    # schedulers have no meaning on it) and the tpu backend (the
    # event-loop oracles only implement the complete graph).  The fused
    # pallas kernels never engage under a topology — delivery='all'
    # already keeps them off; sim.warn_structured_demotes_pallas
    # announces the structural demotion once, like the debug demotion.
    # Cost O(N*d): neighbor indices are closed-form arithmetic or one
    # static [N, d] table — never a dense N x N adjacency tensor.
    topology: Optional[str] = None
    # Committee-structured delivery (per-round sampled committees):
    # committee_cap > 0 arms it.  Each round, each node participates
    # with probability min(1, size*count/N) and joins one of
    # ``committee_count`` committees (fold_in-derived membership, so
    # runs are bit-reproducible and mesh-shape-identical); it then
    # tallies only its committee co-members, and non-participants sit
    # the round out.  ``committee_cap`` is the STATIC shape bound of
    # the per-committee histogram ([T, cap, 3]); count and size are
    # DynParams members, so a committee-size/count curve sweeps in one
    # bucket executable (sweep.run_points_batched).  Same constraints
    # as topology (delivery='all', tpu backend); 'equivocate' is not
    # supported (its per-edge adversary machinery is complete-graph /
    # topology only).  Mutually exclusive with ``topology``.
    committee_cap: int = 0
    committee_count: int = 0
    committee_size: int = 0

    # --- compute path ---------------------------------------------------
    # 'dense':     explicit [T, N, N] delivery mask; exact; N <= ~10^4.
    # 'histogram': O(N) global per-class counts + per-lane (multivariate)
    #              hypergeometric sampling of the tallied quorum; N up to 10^6+.
    # 'auto':      dense when N <= dense_path_max_n else histogram.
    path: str = "auto"
    dense_path_max_n: int = 2048
    # Use the fused pallas kernel (ops/pallas_tally.py) for the dense tally:
    # streams the bool mask and builds one-hots in VMEM instead of
    # materializing f32 [T, N, N] operands in HBM.  Runs in interpreter mode
    # off-TPU (tests); same results either way.
    use_pallas: bool = False
    # Use the fused pallas kernels (ops/pallas_hist.py) for the
    # histogram-path quorum sampler: threefry bits + normal quantile + CF
    # hypergeometric draws in one VMEM pass (~100x less HBM traffic than
    # the XLA pipeline; ~5x faster at N=1M on v5e, ~7x for the
    # equivocate-regime kernel).  Applies on the uniform-scheduler
    # histogram path in the CF regime (quorum > EXACT_TABLE_MAX) — every
    # fault model, with fault_model='equivocate' served by its own fused
    # mixed-population kernel — single device or shard_map mesh (draws key
    # on global ids, so results are bit-identical across mesh shapes);
    # silently ignored elsewhere.  Uses its own documented random stream
    # keyed on the run's base_key, so results are statistically (not
    # bitwise) identical to the XLA path.
    use_pallas_hist: bool = False
    # Run the WHOLE round as two pallas kernels over a packed per-lane
    # state word (ops/pallas_round.py): counts, coin, and decision logic
    # stay in VMEM; sim.run_consensus carries the packed array through the
    # entire while-loop, so no per-lane XLA op runs per round (the XLA
    # chain's re-reads of the 12 B/lane sampler counts were r3 VERDICT
    # item 2's roofline gap).  Engages ON TOP of use_pallas_hist in the
    # same CF regime, for EVERY fault model (byzantine flips ride the
    # packed faulty bit; crash_at_round re-derives killed in-kernel;
    # equivocate runs the mixed-population sampler in-kernel, r4 VERDICT
    # task 6) with coin_mode private/common/weak_common (0 < eps < 1);
    # silently ignored elsewhere, like use_pallas_hist.  BIT-identical to
    # the unfused pallas path (same streams; tests/test_pallas_round.py).
    # ADJUDICATED ON-CHIP (r4 VERDICT item 2): at N=1M x 32 trials on
    # TPU v5 lite the fused round beats the unfused pallas path 1.174x
    # (crash flagship regime) / 1.076x (equivocate), bit-equal —
    # BENCH_TPU.json pallas_round_check, 2026-07-31, interpret=false.
    # PROMOTED: bench.py engages it on every uniform-scheduler N=1M
    # regime.  (The r4 interpret-mode 0.478x "regression" was
    # interpreter overhead; on-chip evidence reversed it.)
    use_pallas_round: bool = False

    # --- Monte-Carlo ----------------------------------------------------
    trials: int = 1                   # T — independent MC trials (batch axis)

    # --- dynamic fault-injection plane (benor_tpu/faults, PR 15) ---------
    # Per-edge iid message OMISSION probability: each (receiver, live
    # sender) edge independently drops its message with this probability,
    # per phase per round.  A receiver that clears fewer than N - F
    # delivered messages STALLS that round (its state freezes — the
    # per-lane quorum gate in models/benor.py), so rounds-to-decide
    # climbs with p (results/faults curves).  Folded into the dense
    # delivery mask (ops/scheduler.py; exact per-edge Bernoulli) and a
    # closed-form binomial-thinning counts path (ops/tally.py; histogram
    # path, so N = 1M stays feasible).  A TRACED DynParams axis: a whole
    # rounds-vs-drop_prob curve compiles as ONE bucket executable
    # (sweep.run_points_batched).  Requires delivery='all' (omission IS
    # the delivery adversary — the quorum-subset schedulers model a
    # different, count-bounded one) on the tpu backend; 0 (default) = off
    # and bit-identical to the pre-faultlab path in results AND compile
    # counts.  The fused pallas kernels implement lossless delivery only
    # (delivery='all' already keeps them off — the structural demotion
    # sim.warn_faults_demote_pallas announces).
    drop_prob: float = 0.0
    # Crash-RECOVERY schedule spec for fault_model='crash_recover'
    # (grammar in benor_tpu/faults/recovery.py):
    # 'at:<crash>:<down>[:amnesia|durable]' or
    # 'stagger:<crash>:<down>[:amnesia|durable]'.  Realized as per-node
    # (crash_round, recover_round) bounds in FaultSpec; the rejoin
    # suffix decides whether an undecided rejoiner keeps its volatile x
    # (durable, the default) or restarts from "?" (amnesia) — decisions
    # are durable either way, so irrevocability holds across recovery.
    recovery: Optional[str] = None
    # Epoch-structured network PARTITION spec (grammar in
    # benor_tpu/faults/partitions.py): 'halves:<heal_round>' or
    # 'groups:<g>:<heal_round>' — G contiguous node-id groups whose
    # cross-group messages are lost until heal_round, realized as
    # per-round group masks (O(N*G) group histograms / gather masks,
    # never a dense N x N).  Composes with topology adjacency
    # (cross-group neighbor edges go silent) and drop_prob (thinning
    # applies to the group-confined counts).  Requires delivery='all'
    # and the tpu backend; None (default) = off, bit-identical to the
    # pre-faultlab path.
    partition: Optional[str] = None

    # --- fault model (N5) -----------------------------------------------
    # 'crash':          faulty nodes dead from birth (reference node.ts:21-26)
    # 'byzantine':      faulty nodes alive but broadcast bit-flipped values
    #                   (every receiver sees the SAME flipped value)
    # 'equivocate':     faulty nodes alive and two-faced: each (receiver,
    #                   equivocator) edge carries an independent fair random
    #                   bit per phase (the classic Byzantine equivocation
    #                   the 'byzantine' broadcast model cannot express).
    #                   Under scheduler='adversarial' (delivery='quorum' —
    #                   like every scheduler, it has no power over the
    #                   deterministic 'all' delivery) the count-controlling
    #                   adversary also CHOOSES the equivocators' per-receiver
    #                   values (full Byzantine power — reproduces the
    #                   N > 3F resilience bound, tests/test_equivocate.py).
    #                   Not supported with scheduler='biased' (the split
    #                   adversary keys delays on the carried value, which is
    #                   per-edge here).
    # 'crash_at_round': faulty node i dies at the start of round crash_round[i]
    # 'crash_recover':  faulty node i is DOWN for rounds
    #                   crash_round[i] <= r < recover_round[i] and then
    #                   rejoins (recover_round <= 0: never — the
    #                   crash_at_round limit, and the lane latches
    #                   killed).  While down it neither sends nor
    #                   tallies; its (x, decided, k) freeze.  The rejoin
    #                   mode (durable x vs amnesia-to-"?") rides the
    #                   ``recovery`` spec.  benor_tpu/faults/recovery.py.
    fault_model: str = "crash"

    # --- state-machine shape -------------------------------------------
    # Freeze a lane once it decides (reference nodes loop forever after
    # deciding — quirk 5; the frozen lane still *broadcasts* its decided value
    # so quorum math is preserved, but its own (x, decided, k) stop updating).
    freeze_decided: bool = True

    # --- distribution (N7) ----------------------------------------------
    # Mesh axis sizes (trials_axis, nodes_axis); None => single device.
    mesh_shape: Optional[Tuple[int, int]] = None

    # --- mid-run observability ------------------------------------------
    # poll_rounds > 0: TpuNetwork.start() steps the compiled loop in slices
    # of this many rounds, publishing the state snapshot after each slice so
    # concurrent /getState pollers observe a LIVE undecided network with
    # growing k — the reference's poll-during-run contract
    # (benorconsensus.test.ts:149-160: getState is sampled every 200 ms
    # while consensus runs).  0 (default) = one uninterrupted compiled
    # while-loop.  Final snapshots are bit-identical either way (the round
    # body is keyed on (seed, round), never on loop entry; pinned by
    # tests).  Works on the single-device AND the sharded (mesh_shape)
    # runner — the latter slices via parallel/sharded.py's shard_map'd
    # slice primitive (r4 VERDICT weak 3).
    poll_rounds: int = 0

    # --- flight recorder -------------------------------------------------
    # record=True threads a preallocated [max_rounds + 1, state.REC_WIDTH]
    # int32 telemetry buffer through the compiled round loop: every
    # executed round writes one row (decided/killed counts, the 0/1/"?"
    # histogram over live undecided lanes, coin-flip count, a tally-margin
    # summary) via dynamic_update_slice — on EVERY regime, including the
    # fused pallas loop (which cfg.debug cannot observe without demoting),
    # the sliced poll_rounds path, the batched dynamic-F sweep and the
    # sharded runner (counts psum-globalized before the row write).  Full
    # round history costs one extra HBM buffer and zero host round trips.
    # Functions whose docstrings say so return an extra recorder array
    # when this flag is set; record=False (default) leaves every
    # executable bit-identical to a build without the feature (the flag
    # is static, so the recorder never enters the trace).
    record: bool = False

    # --- live progress plane (benor_tpu/meshscope/heartbeat.py) ----------
    # heartbeat_rounds = h > 0: long sliced runs (TpuNetwork.start under
    # poll_rounds, the sharded/multihost slice wrappers) publish a
    # HOST-SIDE heartbeat — rounds/sec, decided fraction (from the
    # flight recorder when cfg.record), ETA — every time the round
    # cursor crosses a multiple of h, into the unified metrics registry
    # (heartbeat.* gauges) and, when the driver supplies a path, an
    # append-only JSON-lines file `python -m benor_tpu watch` tails.
    # The batched sweep engine beats per bucket instead (its unit of
    # progress).  Purely host-side: the knob never enters a trace, so
    # heartbeat on AND off are bit-identical in results and compile
    # counts (tests/test_meshscope.py pins it — the same discipline as
    # ``record``).  0 (default) = off.
    heartbeat_rounds: int = 0

    # --- in-kernel stage counters (benor_tpu/kernelscope) ----------------
    # kernel_telemetry=True arms the TILE-LEVEL observability plane of
    # the fused pallas round (ops/pallas_round.py): every kernel stage
    # (proposal pass, vote/commit pass) appends a block of telemetry
    # COLUMNS — laid out by the declarative ops/pallas_round.TELEM_COLS
    # name -> (base, width) table, the same discipline as REC_LAYOUT /
    # WIT_LAYOUT / PACK_LAYOUT — to its existing [tiles, T, PARTIAL_COLS]
    # per-tile partial buffer, counting per-tile/per-stage work: sampler
    # lanes touched, histogram scatter visits, quorum-gate passes, coin
    # draws, active vs pad lanes (the padding waste), and plane-stack HBM
    # hops on the two-kernel path.  Functions whose docstrings say so
    # return one extra int32 [stages, tiles, TELEM_WIDTH] accumulator
    # (summed over rounds and trials) AFTER the recorder/witness tail;
    # benor_tpu/kernelscope assembles it into the per-stage, per-tile
    # attribution report behind `python -m benor_tpu profile --kernels`.
    # Costs only extra partial COLUMNS inside buffers that already exist
    # (zero extra HBM buffers); off (the default) leaves every executable
    # bit-identical in results AND compile counts — the house rule,
    # pinned by tests/test_kernelscope.py.  Inert (no extra output, no
    # cost) on regimes that run no pallas round kernels: the XLA loop
    # has no kernel interior to count.
    kernel_telemetry: bool = False

    # --- witness traces (per-node forensics; see benor_tpu/audit.py) -----
    # witness_trials=(t0, t1, ...) + witness_nodes=k arm the WITNESS
    # recorder: a preallocated [max_rounds + 1, W, k, state.WIT_WIDTH]
    # int32 buffer rides the compiled round loop and every executed round
    # writes, for each watched (trial, node), the committed value, decided
    # bit, killed bit, coin-commit bit and the R/P tallies (proposal
    # p0/p1, vote v0/v1) that justified the transition — the per-node
    # evidence the flight recorder's aggregates cannot carry.  Works in
    # EVERY regime (traced XLA loop, fused pallas round via per-tile
    # witness partials, poll_rounds slices/resume, the batched dynamic-F
    # sweep, the sharded/multihost mesh — rows psum-globalized so every
    # shard holds the identical buffer).  The watched node set is the
    # first ceil(k/2) + last floor(k/2) global node ids
    # (state.witness_node_ids): both ends of the id range, where the
    # seeded fault masks (first-F-faulty) and the targeted adversary's
    # camps (top of the range) live.  witness off (the default) leaves
    # every executable bit-identical in results AND compile counts, the
    # same discipline as ``record``.  Host-side machine-checking of the
    # Ben-Or invariants over a filled buffer: benor_tpu/audit.py.
    witness_trials: Optional[Tuple[int, ...]] = None
    witness_nodes: int = 0

    # --- misc -----------------------------------------------------------
    # The N1 backend switch: 'tpu' = device-array simulator; 'express' =
    # pure-Python event-loop oracle; 'native' = the C++ oracle (bit-exact
    # with 'express', ~100x faster drain loop, for large-N differential
    # testing).
    backend: str = "tpu"
    # Message-delivery serialization for the event-loop oracles.  The
    # reference's fire-and-forget fetches make ANY interleaving legal
    # (SURVEY §5.8); 'fifo' delivers in queue order (the canonical
    # event-loop schedule), 'shuffle' delivers a uniformly random pending
    # message each step from a dedicated seeded stream.  Both oracles
    # (Python and C++) implement both orders bit-identically; protocol
    # properties must hold under both (tests/test_scenarios.py).
    oracle_order: str = "fifo"
    debug: bool = False               # enable host-callback tracing / profiling

    def __post_init__(self) -> None:
        if self.n_nodes < 1:
            raise ValueError("n_nodes must be >= 1")
        if not (0 <= self.n_faulty <= self.n_nodes):
            raise ValueError("n_faulty must be in [0, n_nodes]")
        if self.rule not in ("reference", "textbook"):
            raise ValueError(f"unknown rule: {self.rule}")
        if self.coin_mode not in ("private", "common", "weak_common"):
            raise ValueError(f"unknown coin_mode: {self.coin_mode}")
        if not (0.0 <= self.coin_eps <= 1.0):
            raise ValueError("coin_eps must be in [0, 1]")
        if self.coin_eps and self.coin_mode != "weak_common":
            raise ValueError(
                "coin_eps only applies to coin_mode='weak_common'")
        if self.delivery not in ("all", "quorum"):
            raise ValueError(f"unknown delivery: {self.delivery}")
        if self.scheduler not in ("uniform", "biased", "adversarial",
                                  "targeted"):
            raise ValueError(f"unknown scheduler: {self.scheduler}")
        if self.path not in ("auto", "dense", "histogram"):
            raise ValueError(f"unknown path: {self.path}")
        if self.fault_model not in ("crash", "byzantine", "equivocate",
                                    "crash_at_round", "crash_recover"):
            raise ValueError(f"unknown fault_model: {self.fault_model}")
        if self.recovery is not None:
            from .faults.recovery import parse_recovery
            parse_recovery(self.recovery)     # ValueError if malformed
            if self.fault_model != "crash_recover":
                raise ValueError(
                    "recovery schedules only apply to "
                    "fault_model='crash_recover' (the static fault "
                    f"models have no rejoin; got {self.fault_model!r})")
        if self.fault_model == "crash_recover" and self.backend != "tpu":
            raise ValueError(
                "fault_model='crash_recover' re-derives liveness from "
                "per-round down-intervals inside the device round loop; "
                "the event-loop oracles only implement permanent "
                "crashes — a silent downgrade would fake churn, so use "
                "backend='tpu'")
        if not (0.0 <= self.drop_prob < 1.0):
            raise ValueError(
                "drop_prob must be in [0, 1) — at 1.0 no message ever "
                f"arrives and every round stalls forever (got "
                f"{self.drop_prob})")
        if self.drop_prob:
            if self.delivery != "all":
                raise ValueError(
                    "drop_prob models omission on the deterministic "
                    "full-delivery plane; the quorum-subset schedulers "
                    "model a different (count-bounded) adversary and do "
                    "not compose — use delivery='all'")
            if self.backend != "tpu":
                raise ValueError(
                    "drop_prob thins the device delivery plane; the "
                    "event-loop oracles deliver losslessly — a silent "
                    "no-op would fake omission, so use backend='tpu'")
            if self.fault_model == "equivocate":
                raise ValueError(
                    "drop_prob is not supported with "
                    "fault_model='equivocate' (per-edge equivocator "
                    "bits and per-edge drops would need a joint "
                    "edge-level model the histogram path cannot thin "
                    "in closed form)")
            if self.topology is not None or self.committee_cap:
                raise ValueError(
                    "drop_prob composes with the complete graph (and "
                    "the partition plane) only; the structured "
                    "delivery planes carry their own edge semantics — "
                    "drop topology/committee_* or drop_prob")
        if self.partition is not None:
            from .faults.partitions import parse_partition
            pspec = parse_partition(self.partition)   # ValueError if bad
            pspec.validate(self.n_nodes)
            if self.delivery != "all":
                raise ValueError(
                    "partition replaces full delivery with per-epoch "
                    "group masks; the quorum-subset delivery model has "
                    "no meaning on it — use delivery='all'")
            if self.backend != "tpu":
                raise ValueError(
                    "partition runs the device delivery plane "
                    "(benor_tpu/faults); the event-loop oracles only "
                    "implement the whole network — a silent no-op "
                    "would fake the split, so use backend='tpu'")
            if self.fault_model == "equivocate":
                raise ValueError(
                    "partition is not supported with "
                    "fault_model='equivocate' (per-edge equivocator "
                    "bits are complete-graph / topology machinery and "
                    "do not compose with group masks)")
            if self.committee_cap:
                raise ValueError(
                    "partition and committee delivery are mutually "
                    "exclusive planes (committees already sample WHO "
                    "tallies whom per round); arm one")
        if self.fault_model == "equivocate" and self.scheduler == "biased":
            raise ValueError(
                "fault_model='equivocate' is not supported with "
                "scheduler='biased': the split adversary delays edges by "
                "their carried value, which is per-edge under equivocation")
        if self.delivery == "all" and self.scheduler != "uniform":
            # No scheduler has any power over deterministic full delivery —
            # every receiver tallies every live sender, and under
            # fault_model='equivocate' equivocator values stay iid fair
            # bits instead of adversary-chosen.  Running would be silently
            # weaker than the adversary advertises, so fail loudly (checked
            # after the fault-model combinations so their more specific
            # messages win).
            raise ValueError(
                f"scheduler={self.scheduler!r} has no effect under "
                "delivery='all'; use delivery='quorum' or "
                "scheduler='uniform'")
        if self.topology == "complete":
            # the identity spec: normalize to None so a 'complete' config
            # IS the pre-topology config — same hash, same jit cache
            # entry, bit-identical results and compile counts for free
            object.__setattr__(self, "topology", None)
        if self.topology is not None:
            from .topo.graphs import parse_topology
            spec = parse_topology(self.topology)   # ValueError if malformed
            spec.validate(self.n_nodes)
            if self.delivery != "all":
                raise ValueError(
                    "topology replaces the complete graph with a "
                    "deterministic neighbor fan-in — the quorum-subset "
                    "delivery model has no meaning on it; use "
                    "delivery='all'")
            if self.backend != "tpu":
                raise ValueError(
                    "topology runs the device delivery plane "
                    "(benor_tpu/topo); the event-loop oracles only "
                    "implement the complete graph — a silent no-op "
                    "would fake the structured semantics, so use "
                    "backend='tpu'")
            if self.committee_cap:
                raise ValueError(
                    "topology and committee_cap are mutually exclusive "
                    "delivery planes; arm one")
        if self.committee_cap < 0 or self.committee_count < 0 or \
                self.committee_size < 0:
            raise ValueError("committee knobs must be >= 0")
        if self.committee_cap:
            if not (1 <= self.committee_count <= self.committee_cap):
                raise ValueError(
                    "committee_count must be in [1, committee_cap] "
                    f"(got {self.committee_count} with "
                    f"cap={self.committee_cap}): the cap is the static "
                    "per-committee histogram bound the traced count "
                    "must fit under")
            if self.committee_cap > self.n_nodes:
                raise ValueError(
                    "committee_cap must be <= n_nodes (more committees "
                    "than nodes cannot all be populated)")
            if self.committee_size < 1:
                raise ValueError(
                    "committee_size must be >= 1 when committee_cap "
                    "arms committee delivery")
            if self.delivery != "all":
                raise ValueError(
                    "committee delivery samples its own membership — "
                    "the quorum-subset delivery model has no meaning "
                    "on it; use delivery='all'")
            if self.backend != "tpu":
                raise ValueError(
                    "committee delivery runs the device delivery plane "
                    "(benor_tpu/topo); the event-loop oracles only "
                    "implement the complete graph, so use backend='tpu'")
            if self.fault_model == "equivocate":
                raise ValueError(
                    "fault_model='equivocate' is not supported with "
                    "committee delivery (per-edge equivocation is "
                    "complete-graph / topology machinery); use crash, "
                    "crash_at_round or byzantine")
        elif self.committee_count or self.committee_size:
            raise ValueError(
                "committee_count/committee_size require committee_cap "
                "(the static histogram bound); set all three or none")
        if self.poll_rounds < 0:
            raise ValueError("poll_rounds must be >= 0")
        if self.heartbeat_rounds < 0:
            raise ValueError("heartbeat_rounds must be >= 0")
        if self.heartbeat_rounds and self.backend != "tpu":
            raise ValueError(
                "heartbeat_rounds publishes between the tpu backend's "
                "compiled slices; the event-loop oracles run to "
                "termination in one drain — a silent no-op would fake "
                "live progress, so use backend='tpu'")
        if self.poll_rounds and self.backend != "tpu":
            raise ValueError(
                "poll_rounds slices the tpu backend's compiled loop; the "
                "event-loop oracles run to termination in one drain — a "
                "silent no-op would fake mid-run observability, so use "
                "backend='tpu'")
        if self.use_pallas_round and self.max_rounds + 1 >= (1 << 25):
            # the packed bit-plane layout (state.PACK_LAYOUT) caps the
            # round counter k at 25 planes (PR 15 spent one plane on the
            # crash-recovery down bit); k reaches max_rounds + 1, so its
            # bit length must fit the declared width
            raise ValueError(
                "use_pallas_round packs the round counter k into at most "
                "25 bit-planes (state.PACK_LAYOUT['k']); max_rounds must "
                f"be < 2**25 - 1 (got {self.max_rounds})")
        if self.witness_trials is not None:
            # normalize to a sorted unique tuple: the config must stay
            # hashable (jit-static) and the witness row layout deterministic
            wt = tuple(sorted({int(t) for t in self.witness_trials}))
            if not wt:
                raise ValueError(
                    "witness_trials must name at least one trial "
                    "(None disables witnessing)")
            if wt[0] < 0 or wt[-1] >= self.trials:
                raise ValueError(
                    f"witness_trials must lie in [0, trials); got {wt} "
                    f"with trials={self.trials}")
            object.__setattr__(self, "witness_trials", wt)
            if not (1 <= self.witness_nodes <= self.n_nodes):
                raise ValueError(
                    "witness_nodes must be in [1, n_nodes] when "
                    f"witness_trials is set (got {self.witness_nodes})")
            if self.witness_nodes > WITNESS_MAX_NODES:
                raise ValueError(
                    f"witness_nodes must be <= {WITNESS_MAX_NODES}: the "
                    "fused pallas round carries the witness as extra "
                    "partial columns of its 128-column reduction layout "
                    "(see config.WITNESS_MAX_NODES)")
            if self.backend != "tpu":
                raise ValueError(
                    "witness_trials fills the on-device witness recorder "
                    "inside the tpu backend's compiled loop; the "
                    "event-loop oracles have no device buffer to fill — "
                    "a silent no-op would fake per-node forensics, so "
                    "use backend='tpu'")
        elif self.witness_nodes:
            raise ValueError(
                "witness_nodes requires witness_trials (which trials to "
                "watch); set both or neither")
        if self.kernel_telemetry:
            if self.backend != "tpu":
                raise ValueError(
                    "kernel_telemetry counts work inside the tpu "
                    "backend's pallas kernels; the event-loop oracles "
                    "have no kernel interior to observe — a silent "
                    "no-op would fake tile-level attribution, so use "
                    "backend='tpu'")
            if self.mesh_shape is not None:
                raise ValueError(
                    "kernel_telemetry is single-device: the per-tile "
                    "accumulator is indexed by this device's tile grid "
                    "and the sharded runners do not thread it; drop "
                    "mesh_shape or kernel_telemetry")
        if self.record and self.backend != "tpu":
            raise ValueError(
                "record=True fills the on-device flight recorder inside "
                "the tpu backend's compiled loop; the event-loop oracles "
                "have no device buffer to fill — a silent no-op would "
                "fake round history, so use backend='tpu'")
        if self.backend not in ("tpu", "express", "native"):
            raise ValueError(f"unknown backend: {self.backend}")
        if self.oracle_order not in ("fifo", "shuffle"):
            raise ValueError(f"unknown oracle_order: {self.oracle_order}")

    @property
    def quorum(self) -> int:
        """Messages required before a tally fires: N - F (node.ts:52,88)."""
        return self.n_nodes - self.n_faulty

    @property
    def witness(self) -> bool:
        """True iff the witness recorder is armed (witness_trials set)."""
        return self.witness_trials is not None

    @property
    def resolved_path(self) -> str:
        if self.path != "auto":
            return self.path
        return "dense" if self.n_nodes <= self.dense_path_max_n else "histogram"

    def replace(self, **kw) -> "SimConfig":
        return dataclasses.replace(self, **kw)
