"""Public launch facade — the reference's L3 orchestration layer.

``launch_network`` mirrors ``launchNetwork(N, F, initialValues, faultyList)``
(reference src/index.ts:4-14 -> launchNodes.ts:4-44) with the N1 backend
switch BASELINE.json mandates: ``backend='tpu'`` dispatches to the
device-array simulator, ``backend='express'`` to the event-loop oracle.
``start_consensus`` / ``stop_consensus`` mirror src/nodes/consensus.ts.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from .backends.express import ExpressNetwork
from .backends.tpu import TpuNetwork
from .config import SimConfig


def launch_network(n: int, f: int, initial_values: Sequence,
                   faulty_list: Sequence[bool], backend: Optional[str] = None,
                   cfg: Optional[SimConfig] = None, **cfg_overrides):
    """Launch a simulated network; returns a network with the parity API
    (status / start / stop / get_state / get_states).

    ``backend`` defaults to ``cfg.backend`` when a config is given (so an
    explicitly configured oracle is never silently swapped), else 'tpu'.
    Validation matches launchNodes.ts:10-13: array lengths must equal N and
    ``faulty_list`` must contain exactly ``f`` true entries.
    """
    if cfg is None:
        cfg = SimConfig(n_nodes=n, n_faulty=f,
                        backend=backend or "tpu", **cfg_overrides)
    else:
        cfg = cfg.replace(n_nodes=n, n_faulty=f,
                          backend=backend or cfg.backend, **cfg_overrides)
    if cfg.backend in ("express", "native"):
        # The oracles replicate the REFERENCE's semantics exactly: crash-
        # from-birth faults (node.ts:21-26, SURVEY §2.1 quirk 7), private
        # Math.random() coins (node.ts:111), and the plurality-adopt rule
        # (node.ts:106-112).  Silently substituting those for a requested
        # extension would fake a parity the oracle cannot provide.
        # (scheduler too: the oracles' asynchrony is their OWN event-loop
        # delivery order, cfg.oracle_order — they never read cfg.scheduler,
        # so a biased/adversarial request would silently run uniform.)
        for knob, val, want in (("fault_model", cfg.fault_model, "crash"),
                                ("coin_mode", cfg.coin_mode, "private"),
                                ("rule", cfg.rule, "reference"),
                                ("scheduler", cfg.scheduler, "uniform")):
            if val != want:
                raise ValueError(
                    f"backend={cfg.backend!r} supports only {knob}="
                    f"{want!r} (the reference's semantics); got {val!r} — "
                    f"use backend='tpu'")
    if cfg.backend == "express":
        return ExpressNetwork(cfg, list(initial_values), list(faulty_list))
    if cfg.backend == "native":
        from .backends.native_oracle import NativeExpressNetwork
        return NativeExpressNetwork(cfg, list(initial_values),
                                    list(faulty_list))
    return TpuNetwork(cfg, list(initial_values), list(faulty_list))


def start_consensus(network) -> None:
    """consensus.ts:3-8 — kick off the protocol on every node."""
    network.start()


def stop_consensus(network) -> None:
    """consensus.ts:10-15 — kill every node."""
    network.stop()


def get_nodes_state(network, trial: int = 0) -> List[dict]:
    """__test__/tests/utils.ts:14-20 — scrape all node states."""
    return network.get_states(trial)


def reached_finality(states: List[dict]) -> bool:
    """__test__/tests/utils.ts:22-24 — no state has decided === false
    (faulty nodes' null counts as final)."""
    return all(s["decided"] is not False for s in states)
