"""Science deliverable generator: the curves the BASELINE north star asks for.

The reference repo never produces a curve (its only experiment is the
hardcoded 10-node demo, src/start.ts:7-20).  This module runs the five
BASELINE.json presets plus the three N=1M science studies and writes the
results as JSON (RESULTS/*.json) and a human-readable RESULTS.md — the
"expected-rounds-vs-f curves" artifact itself, checked into the repo.

Studies beyond the presets:

  balanced_curve  — expected rounds vs fault fraction with perfectly
                    balanced inputs and ZERO crashes (F purely a protocol
                    parameter).  For f > 1/3 the decide threshold
                    count > F exceeds the typical class count (N-F)/2, so
                    convergence needs the sampling-noise random walk to
                    amplify a majority: mean_k steps from 2 to ~3.
  margin_sweep    — outcomes vs initial margin delta (1-count = N/2 +
                    delta*sqrt(N)/2) at f = 0.4.  The per-lane round-1
                    adoption probability is Phi(~1.2*delta); two distinct
                    transitions appear as delta grows: the decided VALUE
                    locks to the majority input by delta ~ 0.1, while the
                    round count only drops once the margin survives both
                    amplification phases of round 1 (delta ~ 0.4) — the
                    margin-inside-sampling-noise physics made visible.
  coin_contrast   — private vs shared common coin under the worst-case
                    count-controlling adversary at N=1M: private coins
                    livelock (decided ~ 0 at the cap), the common coin
                    escapes in O(1) rounds (Ben-Or vs Rabin).
  disagreement    — agreement-SAFETY violation rate vs split-adversary
                    strength s at N=1M: the reference's decide rule
                    (count > F) is only safe when at most N-F senders are
                    alive; with all N alive and the delay adversary
                    starving each parity class of one value, healthy nodes
                    decide OPPOSITE values (PARITY.md "Findings beyond the
                    reference"), quantified here per strength.
  safety_violation — agreement under the TARGETED (partitioned)
                    count-controlling adversary: a 0/1 curve — violated at
                    EVERY 1 <= F < N/2 (even quorum), livelock past 1/2,
                    and ONE equivocator kills agreement at any N.  The
                    sharp counterpart of the soft 'disagreement' curve.
                    Both safety studies auto-rerun every violating point
                    with the witness recorder armed (_witness_rerun) and
                    attach the invariant auditor's verdict + a
                    witness_*.json bundle pinpointing (trial, round,
                    node, tallies) — see benor_tpu/audit.py.
  oracle_parity   — oracle <-> scheduler distribution parity (SURVEY
                    hard-part 1): within the reference contract the
                    event-loop asynchrony is tally-invisible (alive ==
                    quorum), decided runs are delivery-order-invariant
                    bit-for-bit, and the rounds-to-decide law matches the
                    tpu uniform-quorum scheduler's (two-sample KS).
  equivocation    — the classic N > 3F Byzantine resilience bound located
                    to +-1 node of N/3 at N=1M: adversary-controlled
                    equivocators (fault_model='equivocate',
                    scheduler='adversarial') tie every tally forever at
                    F >= N/3 — even the shared common coin cannot
                    terminate, matching the impossibility — while at
                    F = N//3 (3F < N) the unified honest class count
                    m - F > F decides in O(1) coin rounds.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Dict, List

import numpy as np

from .config import SimConfig
from .state import FaultSpec, init_state
from .sweep import (SweepPoint, baseline_configs, coin_comparison,
                    record_trajectory, run_point)

#: Default fault fractions for the balanced rounds-vs-f curve.
CURVE_FRACS = (0.10, 0.25, 0.35, 0.40, 0.45)
#: Margin multipliers (x sqrt(N)) for the margin sweep.  The interesting
#: window is delta < ~0.5: the value bias (ones_frac) saturates by
#: delta ~ 0.1 while the round count only drops once the margin survives
#: BOTH amplification phases of round 1 (delta ~ 0.4) — two distinct
#: transitions, both inside sampling noise scale.
MARGINS = (0.0, 0.05, 0.1, 0.2, 0.3, 0.5, 1.0)


def _balanced(trials: int, n: int, extra_ones: int = 0) -> np.ndarray:
    """Inputs with exactly floor(N/2) + extra_ones ones per trial."""
    ones = n // 2 + extra_ones
    row = np.zeros(n, np.int8)
    row[:ones] = 1
    return np.tile(row, (trials, 1))


#: The fused flagship path's flag set — ONE definition, shared by the
#: accelerator branch below and the CLI's `--pallas on` force
#: (benor_tpu/__main__.py), so the two can never diverge.
FLAGSHIP_FLAGS = {"use_pallas_hist": True, "use_pallas_round": True}


def _flagship_flags() -> Dict[str, bool]:
    """The fused pallas flagship path for the accelerator-scale studies.

    On-chip the hist sampler kernels run ~5.3x the XLA pipeline and the
    fully-fused round a further 1.17x on top, bit-identical to the
    unfused pallas path (BENCH_TPU.json kernel checks, N=1M x 32 on
    v5 lite, 2026-07-31) — so the committed N=1M artifact should measure
    the path users actually get.  The pallas stream is statistically
    identical to the XLA stream (KS-gated, tests/test_pallas_hist.py):
    same science, different bits.  Off on CPU (interpret-mode pallas
    would dominate the smoke runs); silently ignored by configs the
    kernels don't serve (non-uniform schedulers, quorum below the CF
    regime) — see ops/tally.py:pallas_round_active."""
    import jax

    if jax.default_backend() == "cpu":
        return {}
    if _PROBE_OK is False:
        return {}
    return dict(FLAGSHIP_FLAGS)


#: Set by generate() on accelerator backends before the studies run:
#: False demotes every _flagship_flags() caller to the XLA path.  None
#: (the default) trusts the flags without probing — short CLI runs
#: surface a kernel failure through their own compile and lose seconds,
#: not the 2 h capture the probe insures.
_PROBE_OK: "bool | None" = None


@functools.lru_cache(maxsize=None)
def _flagship_probe(n: int) -> bool:
    """One compile+run of the fused round AT THE STUDY SCALE (trials=1,
    one round — compile-dominated, ~10-30 s on-chip) before generate()
    commits to it: a kernel lowering regression on this chip generation
    must demote the run to the XLA path, not kill a 2 h capture at
    study #1.  Mirrors bench.py's demotion policy exactly: only
    Mosaic/pallas lowering failures demote — anything else (a broken
    probe, OOM) raises with correct attribution, because it would hit
    the XLA path too.  Pallas failures are frequently shape-dependent
    (tile/layout/VMEM scaling), hence probing at the real N."""
    import jax

    from .ops import sampling
    from .sim import run_consensus

    cfg = SimConfig(n_nodes=n, n_faulty=0, trials=1,
                    delivery="quorum", scheduler="uniform",
                    path="histogram", max_rounds=1, **FLAGSHIP_FLAGS)
    if cfg.quorum <= sampling.EXACT_TABLE_MAX:
        return True                 # flags are inert below the CF regime
    faults = FaultSpec.none(1, n)
    state = init_state(cfg, _balanced(1, n), faults)
    try:
        r, _ = run_consensus(cfg, state, faults, jax.random.key(0))
        int(r)                                # force execution
        return True
    # benorlint: allow-broad-except — non-Mosaic errors re-raise below
    except Exception as e:  # noqa: BLE001 — filtered re-raise below
        if not any(s in f"{type(e).__name__}: {e}"
                   for s in ("Mosaic", "mosaic", "pallas", "Pallas")):
            raise
        print(f"  flagship pallas probe failed ({type(e).__name__}: {e}); "
              f"studies run the XLA path", flush=True)
        return False


def balanced_curve(n: int, trials: int, seed: int = 0,
                   fracs=CURVE_FRACS, verbose=True) -> List[SweepPoint]:
    pts = []
    for frac in fracs:
        cfg = SimConfig(n_nodes=n, n_faulty=int(frac * n), trials=trials,
                        max_rounds=64, delivery="quorum",
                        scheduler="uniform", path="histogram", seed=seed,
                        **_flagship_flags())
        pt = run_point(cfg, initial_values=_balanced(trials, n),
                       faults=FaultSpec.none(trials, n))
        pts.append(pt)
        if verbose:
            print(f"  f={frac:.2f}: mean_k={pt.mean_k:.3f} "
                  f"decided={pt.decided_frac:.3f} ones={pt.ones_frac:.3f} "
                  f"{pt.trials_per_sec:.1f} trials/s", flush=True)
    return pts


def margin_sweep(n: int, trials: int, seed: int = 0, f_frac: float = 0.40,
                 margins=MARGINS, verbose=True) -> List[Dict]:
    rows = []
    for delta in margins:
        extra = int(round(delta * np.sqrt(n) / 2))  # 1-count - N/2
        cfg = SimConfig(n_nodes=n, n_faulty=int(f_frac * n), trials=trials,
                        max_rounds=64, delivery="quorum",
                        scheduler="uniform", path="histogram", seed=seed,
                        **_flagship_flags())
        pt = run_point(cfg, initial_values=_balanced(trials, n, extra),
                       faults=FaultSpec.none(trials, n))
        rows.append({"delta": delta, "extra_ones": extra, **pt.to_dict()})
        if verbose:
            print(f"  delta={delta}: mean_k={pt.mean_k:.3f} "
                  f"ones={pt.ones_frac:.3f}", flush=True)
    return rows


def _witness_rerun(cfg: SimConfig, initial_values, faults, tag: str,
                   out_dir=None, verbose=True) -> Dict:
    """Forensic auto-rerun of an agreement-violating safety point.

    When a safety study reports ``disagree_frac > 0`` the aggregate says
    only THAT agreement broke; this reruns the same (config, seed) point
    with the witness recorder armed (first few trials, both ends of the
    node-id range — where the camps and fault masks live), machine-checks
    the Ben-Or invariants (benor_tpu/audit.py) and dumps the witness
    bundle as JSON so the violation is pinpointed to (trial, round, node
    ids, tallies).  The rerun is bit-identical to the original point
    (witnessing never moves a random stream), so the evidence is OF the
    violating run, not of a lookalike.  Returns the summary dict the
    study row embeds; the bundle also renders as Perfetto trace slices
    via utils/metrics.export_chrome_trace(witness=...).
    """
    import jax

    from . import audit
    from .sim import run_consensus

    wcfg = cfg.replace(
        **audit.default_witness_overrides(cfg.trials, cfg.n_nodes))
    state = init_state(wcfg, initial_values, faults)
    out = run_consensus(wcfg, state, faults, jax.random.key(wcfg.seed))
    bundle = audit.WitnessBundle.from_run(wcfg, out[-1], faults=faults,
                                          label=tag)
    report = audit.audit_witness(bundle)
    summary: Dict = {"audit_ok": report.ok,
                     "n_violations": len(report.violations)}
    if report.violations:
        summary["first_violation"] = report.violations[0].to_dict()
    if out_dir:
        path = os.path.join(out_dir, f"witness_{tag}.json")
        audit.save_bundle(path, bundle, report)
        summary["bundle"] = path
    if verbose:
        print(f"    {report.summary()}"
              + (f" -> {summary['bundle']}" if "bundle" in summary else ""),
              flush=True)
    return summary


def _violation_forensics(cfg, initial_values, faults, tag: str,
                         out_dir=None, verbose=True,
                         fault_policy: str = "none",
                         shrink: bool = False,
                         repro: bool = True) -> Dict:
    """The ONE forensic block every violating study row goes through
    (deduplicates what disagreement_sweep and safety_violation used to
    inline separately): the witness-armed bit-identical rerun + audit
    (_witness_rerun), then a replayable ``kind: atlas_repro`` document
    (benor_tpu/atlas/repro.py) whose digest and replay verdict ride in
    the row — every violation artifact is replayable via
    ``python -m benor_tpu replay``, not just inspectable.
    ``fault_policy`` is the repro's declarative fault knob ('none' for
    the adversary-only studies, 'default' for first-F-faulty rows).
    ``shrink`` defaults OFF here: at full config the repro run and its
    replay reuse the study's own jit-cached executable, while every
    shrink candidate is a new static shape = a fresh compile — the
    shrinking minimal-repro search belongs to the atlas cliff path,
    where the configs are already small.  ``repro=False`` keeps the
    per-row witness rerun but skips the repro document (its build and
    replay are two more full runs): callers emit one repro per
    violation CLASS, not per row — later rows of the same class
    replay to the same-shaped document."""
    summary = _witness_rerun(cfg, initial_values, faults, tag,
                             out_dir=out_dir, verbose=verbose)
    if not repro:
        return summary
    from .atlas import repro as arepro
    doc = arepro.build_repro(cfg, inputs="balanced",
                             faults=fault_policy, label=tag,
                             shrink=shrink)
    summary["repro_digest"] = doc["digest"]
    summary["repro_reproduced"] = bool(arepro.replay_repro(doc)["ok"])
    if out_dir:
        path = os.path.join(out_dir, f"repro_{tag}.json")
        arepro.save_repro(path, doc)
        summary["repro"] = path
        if verbose:
            print(f"    repro {doc['config']['trials']}x"
                  f"{doc['config']['n_nodes']} "
                  f"({doc['shrink_steps']} shrink steps, "
                  f"{'replays' if summary['repro_reproduced'] else 'STALE'}"
                  f") -> {path}", flush=True)
    return summary


#: Split-adversary strengths for the disagreement study — spaced to frame
#: the sharp safety phase transition (s_c ~ 0.45 at f = 0.25: below it the
#: quorum overlap still forces enough starved-class messages through to
#: keep both halves on the same majority; above it each parity class
#: decides its own favored value).  Stops at 1.0: on the histogram path
#: every s >= 1 is exact strict priority (biased_priority_counts ignores
#: the magnitude), so larger strengths are bit-identical repeats.
STRENGTHS = (0.0, 0.25, 0.4, 0.45, 0.5, 0.75, 1.0)


def disagreement_sweep(n: int, trials: int, seed: int = 0,
                       f_frac: float = 0.25, strengths=STRENGTHS,
                       verbose=True, out_dir=None) -> List[Dict]:
    # The s=0 control is the same static config as balanced_curve's f=0.25
    # point, so inside generate() its executable comes from the jit cache
    # and the "duplicate" run costs one cached dispatch, not a compile.
    rows = []
    repro_done = False
    for s in strengths:
        cfg = SimConfig(n_nodes=n, n_faulty=int(f_frac * n), trials=trials,
                        max_rounds=64, delivery="quorum",
                        scheduler="biased" if s > 0 else "uniform",
                        adversary_strength=s, path="histogram", seed=seed,
                        **_flagship_flags())
        faults = FaultSpec.none(trials, n)
        pt = run_point(cfg, initial_values=_balanced(trials, n),
                       faults=faults)
        row = {"strength": s, **pt.to_dict()}
        if verbose:
            print(f"  s={s}: disagree={pt.disagree_frac:.3f} "
                  f"decided={pt.decided_frac:.3f} mean_k={pt.mean_k:.2f}",
                  flush=True)
        if pt.disagree_frac > 0:
            # agreement broke: auto-rerun with witnessing to pin WHICH
            # nodes decided WHICH value on WHAT quorum evidence, and
            # emit the replayable minimal repro of the break
            row["witness_audit"] = _violation_forensics(
                cfg, _balanced(trials, n), faults,
                f"disagreement_s{s}", out_dir, verbose,
                repro=not repro_done)
            repro_done = True
        rows.append(row)
    return rows


#: Fault fractions for the targeted-adversary safety study, chosen to give
#: EVEN quorums at the default N (the attack's "?"-manufacturing step needs
#: perfect phase-1 ties) and to frame both boundaries: the f -> 0 edge and
#: the f = 1/2 flip to livelock.
def _even_quorum_f(n: int, frac: float) -> int:
    f = int(frac * n)
    return f + (n - f) % 2


def safety_violation(n: int, trials: int, seed: int = 0,
                     verbose=True, out_dir=None) -> List[Dict]:
    """Agreement violation under the PARTITIONED count-controlling
    adversary (scheduler='targeted') — r3 VERDICT item 3.

    Where the 'disagreement' study's delay-bounded split adversary yields a
    soft probabilistic curve with a transition near s_c ~ 0.45, this
    adversary's curve is exactly 0/1: disagree = 1.0 for EVERY
    1 <= F < N/2 (even quorum) and 0.0 outside — at f = 0 the full quorum
    leaves no slack, at f >= 1/2 the decide bar count > F is unreachable
    and the run livelocks.  The final rows put one equivocator in the
    population: agreement dies at ANY N (the count > F rule has no
    Byzantine safety margin at all).

    Every violating row auto-reruns with the witness recorder armed
    (_witness_rerun) and embeds the audit verdict — the minimal (trial,
    round, node, tallies) witness of its agreement break; bundles land in
    ``out_dir`` when given.
    """
    rows = []
    repro_classes = set()

    def _row(cfg, faults, extra, tag, fault_policy="none"):
        pt = run_point(cfg, initial_values=_balanced(trials, n),
                       faults=faults)
        row = {**extra, **pt.to_dict()}
        if pt.disagree_frac > 0:
            row["witness_audit"] = _violation_forensics(
                cfg, _balanced(trials, n), faults, tag, out_dir,
                verbose, fault_policy=fault_policy,
                repro=fault_policy not in repro_classes)
            repro_classes.add(fault_policy)
        rows.append(row)
        return pt

    for frac in (0.0, 0.01, 0.1, 0.25, 0.4, 0.49):
        f = _even_quorum_f(n, frac) if frac else 0
        cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials, max_rounds=16,
                        delivery="quorum", scheduler="targeted",
                        path="histogram", seed=seed)
        pt = _row(cfg, FaultSpec.none(trials, n),
                  {"f": f, "f_frac": round(f / n, 4),
                   "fault_model": "crash"}, f"targeted_f{f}")
        if verbose:
            print(f"  f={f:,}: disagree={pt.disagree_frac:.3f} "
                  f"decided={pt.decided_frac:.3f}", flush=True)
    # past the boundary: livelock, no decisions at all
    f_half = n // 2 + 1
    cfg = SimConfig(n_nodes=n, n_faulty=f_half, trials=trials, max_rounds=16,
                    delivery="quorum", scheduler="targeted",
                    path="histogram", seed=seed)
    pt = _row(cfg, FaultSpec.none(trials, n),
              {"f": f_half, "f_frac": round(f_half / n, 4),
               "fault_model": "crash"}, f"targeted_f{f_half}")
    if verbose:
        print(f"  f={f_half:,} (past 1/2): decided={pt.decided_frac:.3f} "
              f"(livelock)", flush=True)
    # the quirk-born parity effect: an ODD quorum admits no perfect
    # phase-1 tie, so no "?" voters can be manufactured and the attack
    # needs N <= 3F + 1 — one odd-quorum row either side of that bound
    for frac, label in ((0.05, "odd,N>3F+1"), (0.40, "odd,N<3F+1")):
        f = int(frac * n)
        f += 1 - (n - f) % 2               # force an odd quorum
        cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials, max_rounds=16,
                        delivery="quorum", scheduler="targeted",
                        path="histogram", seed=seed)
        pt = _row(cfg, FaultSpec.none(trials, n),
                  {"f": f, "f_frac": round(f / n, 4),
                   "fault_model": f"crash ({label})"},
                  f"targeted_odd_f{f}")
        if verbose:
            print(f"  f={f:,} ({label}): disagree={pt.disagree_frac:.3f}",
                  flush=True)
    # one equivocator: agreement dies at any N
    cfg = SimConfig(n_nodes=n, n_faulty=1, trials=trials, max_rounds=16,
                    delivery="quorum", scheduler="targeted",
                    fault_model="equivocate", path="histogram", seed=seed)
    pt = _row(cfg, FaultSpec.first_f(cfg),
              {"f": 1, "f_frac": round(1 / n, 7),
               "fault_model": "equivocate"}, "targeted_equivocate_f1",
              fault_policy="default")
    if verbose:
        print(f"  ONE equivocator: disagree={pt.disagree_frac:.3f}",
              flush=True)
    return rows


def ks_two_sample(a, b) -> tuple:
    """Two-sample Kolmogorov–Smirnov (statistic, asymptotic p-value).

    scipy-free (scipy is a test-only extra): the standard asymptotic
    Kolmogorov distribution evaluated at the effective sample size —
    adequate for the discrete round-count laws reported here (the test
    suite cross-checks against scipy where available)."""
    a = np.sort(np.asarray(a, float))
    b = np.sort(np.asarray(b, float))
    grid = np.concatenate([a, b])
    cdf_a = np.searchsorted(a, grid, side="right") / len(a)
    cdf_b = np.searchsorted(b, grid, side="right") / len(b)
    d = float(np.max(np.abs(cdf_a - cdf_b)))
    n_eff = len(a) * len(b) / (len(a) + len(b))
    lam = (np.sqrt(n_eff) + 0.12 + 0.11 / np.sqrt(n_eff)) * d
    # Kolmogorov survival Q(lam): the alternating large-lam series is
    # numerically useless for small lam (identical samples would report
    # p = 0 instead of 1) — use the dual theta-series there, like every
    # standard implementation.
    if lam < 1e-9:
        return d, 1.0
    if lam < 1.18:
        t = np.exp(-np.pi ** 2 / (8.0 * lam ** 2))
        cdf = (np.sqrt(2.0 * np.pi) / lam) * (t + t ** 9 + t ** 25 + t ** 49)
        p = 1.0 - cdf
    else:
        j = np.arange(1, 101)
        p = 2.0 * np.sum((-1.0) ** (j - 1) * np.exp(-2.0 * (lam * j) ** 2))
    return d, float(min(max(p, 0.0), 1.0))


def oracle_parity(trials: int, seed: int = 0, n: int = 100, f: int = 40,
                  verbose=True) -> Dict:
    """Oracle <-> scheduler distribution parity (r3 VERDICT item 4;
    SURVEY §7 hard-part 1), at a FIXED differential scale (N=100 — the
    oracles are event-loop programs, not tensor programs; N does not
    scale them).

    Three facts, each checked here and pinned in
    tests/test_distribution_parity.py:
      * decided runs are delivery-order INVARIANT (fifo == shuffle
        bit-identically): with crash faults pinned to F, alive == quorum,
        so every tally holds the full live population in any order — the
        reference's event-loop asynchrony is tally-invisible in its own
        scenario space;
      * order-dependence survives only in runs capped mid-coin-phase,
        and there only as a permutation of the coin assignment;
      * hence the per-trial rounds-to-decide law has one stochastic
        driver (iid fair coins) and matches the tpu uniform-quorum
        scheduler's law (two-sample KS).
    """
    from .backends import native_oracle
    from .sim import run_consensus
    from .state import FaultSpec as FS
    from .state import init_state as init
    import jax

    s_seeds = max(trials * 8, 256)          # oracle seeds are cheap (C++)
    faulty = [True] * f + [False] * (n - f)
    vals = [0] * f + [i % 2 for i in range(n - f)]
    healthy = np.r_[f:n]
    cfg_o = SimConfig(n_nodes=n, n_faulty=f, backend="native",
                      max_rounds=64, oracle_order="shuffle")
    seeds = np.arange(s_seeds, dtype=np.uint32)
    t0 = time.perf_counter()
    # raise_on_cap: a capped seed's state is a mid-run snapshot, not a
    # finished trace — it must not silently enter the invariance/KS
    # samples or deflate the throughput
    out_s = native_oracle.run_batch(cfg_o, vals, faulty, seeds,
                                    raise_on_cap=True)
    oracle_elapsed = time.perf_counter() - t0
    out_f = native_oracle.run_batch(cfg_o.replace(oracle_order="fifo"),
                                    vals, faulty, seeds, raise_on_cap=True)
    # the invariance theorem covers DECIDED runs only (a run capped
    # mid-coin-phase legitimately permutes its coin assignment) — compare
    # on seeds decided under both orders
    dec = (out_s["decided"][:, healthy].all(axis=1)
           & out_f["decided"][:, healthy].all(axis=1))
    order_invariant = bool((out_s["x"][dec] == out_f["x"][dec]).all()
                           and (out_s["k"][dec] == out_f["k"][dec]).all())
    # KS samples must hold FINISHED rounds-to-decide values only: a trial
    # that hit max_rounds without all healthy lanes deciding contributes a
    # CENSORED k (== the cap), which would bias both histograms in slower
    # regimes (negligible here, mean k ~ 2 — but correctness is free)
    dec_o = out_s["decided"][:, healthy].all(axis=1)
    if not dec_o.any():
        raise RuntimeError(
            "oracle_parity: every oracle trial was censored at "
            f"max_rounds={cfg_o.max_rounds}; raise max_rounds or shrink "
            "the scenario")
    k_oracle = out_s["k"][dec_o][:, healthy].max(axis=1) - 1

    cfg_t = SimConfig(n_nodes=n, n_faulty=f, trials=s_seeds,
                      delivery="quorum", scheduler="uniform",
                      path="histogram", max_rounds=64, seed=seed + 11)
    faults = FS.from_faulty_list(cfg_t, faulty)
    state = init(cfg_t, np.tile(np.asarray(vals, np.int8), (s_seeds, 1)),
                 faults)
    _, fin = run_consensus(cfg_t, state, faults, jax.random.key(seed + 11))
    dec_t = np.asarray(fin.decided)[:, healthy].all(axis=1)
    if not dec_t.any():
        raise RuntimeError(
            "oracle_parity: every tpu trial was censored at "
            f"max_rounds={cfg_t.max_rounds}; raise max_rounds or shrink "
            "the scenario")
    k_tpu = np.asarray(fin.k)[dec_t][:, healthy].max(axis=1) - 1

    stat, pvalue = ks_two_sample(k_oracle, k_tpu)
    res = {
        "n": n, "f": f, "n_seeds": int(s_seeds),
        "n_decided_both_orders": int(dec.sum()),
        "n_censored": {"oracle": int((~dec_o).sum()),
                       "tpu": int((~dec_t).sum())},
        "order_invariant_decided_runs": order_invariant,
        "oracle_mean_rounds": round(float(k_oracle.mean()), 4),
        "tpu_mean_rounds": round(float(k_tpu.mean()), 4),
        "oracle_round_hist": np.bincount(k_oracle,
                                         minlength=8)[:8].tolist(),
        "tpu_round_hist": np.bincount(k_tpu, minlength=8)[:8].tolist(),
        "ks_statistic": round(stat, 5), "ks_pvalue": round(pvalue, 5),
        "oracle_msgs_per_sec": round(
            float(out_s["steps"].sum()) / max(oracle_elapsed, 1e-9), 1),
    }
    if verbose:
        print(f"  order-invariant (fifo==shuffle, decided): "
              f"{order_invariant}", flush=True)
        print(f"  rounds-to-decide: oracle {res['oracle_round_hist']} "
              f"vs tpu {res['tpu_round_hist']}; "
              f"KS D={stat:.4f} p={pvalue:.3f}", flush=True)
    return res


def rule_comparison(n: int, trials: int, seed: int = 0,
                    f_frac: float = 0.45, verbose=True) -> List[Dict]:
    """Reference decide rule vs textbook Ben-Or, same workload (balanced
    inputs, f = 0.45, zero crashes).

    The reference adopts the PLURALITY of non-"?" votes before falling
    back to the coin (node.ts:106-112 — SURVEY §2.1 quirk 9); textbook
    Ben-Or coins whenever no value clears > F votes.  Plurality adoption
    is the amplification step that locks the network onto the round-1
    sampling-noise majority — removing it (rule='textbook') forces lanes
    to re-randomize every round, so convergence needs the per-lane vote
    margin itself to clear the threshold.  This quantifies the quirk the
    reference's own k <= 2 test bounds silently depend on.
    """
    rows = []
    for rule in ("reference", "textbook"):
        cfg = SimConfig(n_nodes=n, n_faulty=int(f_frac * n), trials=trials,
                        max_rounds=64, delivery="quorum",
                        scheduler="uniform", path="histogram", rule=rule,
                        seed=seed, **_flagship_flags())
        pt = run_point(cfg, initial_values=_balanced(trials, n),
                       faults=FaultSpec.none(trials, n))
        rows.append({"rule": rule, **pt.to_dict()})
        if verbose:
            print(f"  rule={rule}: mean_k={pt.mean_k:.3f} "
                  f"decided={pt.decided_frac:.3f}", flush=True)
    return rows


def scaling_study(n_large: int, trials: int, seed: int = 0,
                  f_frac: float = 0.45, verbose=True) -> List[Dict]:
    """Rounds-to-decide and throughput vs network size N at the hardest
    uniform point (balanced inputs, f = 0.45, zero crashes).

    Science: the decide threshold exceeds the typical class count by
    (3f-1)/2 * m ~ O(N) while per-round sampling noise is O(sqrt(N)) — yet
    mean_k stays ~3 at every N, because round 1's plurality-adopt step
    AMPLIFIES the initial sqrt(N)-scale imbalance into a network-wide
    majority (each lane adopts the majority of its own noisy sample, and
    the per-lane adoption bias compounds network-wide in one step).  The
    flat curve is the measurable signature of that amplification.

    Perf: trials/s vs N traces the framework's weak-scaling envelope on
    one chip (dispatch-bound at small N, bandwidth-bound at 10^6).
    """
    ns = [10 ** k for k in range(3, 7) if 10 ** k <= n_large]
    if not ns or ns[-1] != n_large:   # always measure the top point itself
        ns.append(n_large)
    rows = []
    for n in ns:
        cfg = SimConfig(n_nodes=n, n_faulty=int(f_frac * n), trials=trials,
                        max_rounds=64, delivery="quorum",
                        scheduler="uniform", path="histogram", seed=seed,
                        **_flagship_flags())
        pt = run_point(cfg, initial_values=_balanced(trials, n),
                       faults=FaultSpec.none(trials, n))
        rows.append({"n": n, **pt.to_dict()})
        if verbose:
            print(f"  N={n:>9,}: mean_k={pt.mean_k:.3f} "
                  f"decided={pt.decided_frac:.3f} "
                  f"{pt.trials_per_sec:.1f} trials/s", flush=True)
    return rows


def trajectory_study(n: int, trials: int, seed: int = 0,
                     f_frac: float = 0.45, n_rounds: int = 8,
                     verbose=True) -> List[Dict]:
    """Round-resolved convergence dynamics at the hardest uniform point
    (balanced inputs, f = 0.45): the decided fraction jumps 0 -> 1 in one
    round once the sampling-noise random walk amplifies a network-wide
    majority — the trajectory shows WHEN, which the endpoint cannot."""
    import jax

    cfg = SimConfig(n_nodes=n, n_faulty=int(f_frac * n), trials=trials,
                    max_rounds=64, delivery="quorum", scheduler="uniform",
                    path="histogram", seed=seed, **_flagship_flags())
    faults = FaultSpec.none(trials, n)
    state = init_state(cfg, _balanced(trials, n), faults)
    _, traj = record_trajectory(cfg, state, faults, jax.random.key(seed),
                                n_rounds)
    traj = {k: np.asarray(v) for k, v in traj.items()}
    rows = []
    for i in range(n_rounds):
        rows.append({"round": i + 1,
                     **{k: round(float(v[i]), 4) for k, v in traj.items()}})
        if verbose:
            r = rows[-1]
            print(f"  round {r['round']}: decided={r['decided']:.3f} "
                  f"zeros={r['zeros']:.3f} ones={r['ones']:.3f} "
                  f"qs={r['qs']:.3f}", flush=True)
    return rows


#: Weak-coin deviation probabilities: coarse approach + a fine straddle of
#: the predicted critical point eps* = 1 - f (the adversary can tie a coin
#: round iff the deviating minority reaches the tie target m/2, i.e.
#: eps/2 >= (1-f)/2; at N=1M the Binomial(N, eps/2) fluctuation is only
#: ~5e-4 of N, so the transition is knife-edge sharp).
WEAK_COIN_EPS = (0.0, 0.3, 0.5, 0.58, 0.597, 0.603, 0.62, 0.8, 1.0)


def weak_coin_study(n: int, trials: int, seed: int = 0,
                    f_frac: float = 0.40, eps_grid=WEAK_COIN_EPS,
                    verbose=True) -> List[Dict]:
    """Termination vs coin quality under the count-controlling adversary.

    coin_mode='weak_common' interpolates Rabin-style shared coins
    (eps = 0) and Ben-Or private coins (eps = 1): each lane deviates to a
    private flip with probability eps.  The adversary lives off the
    deviators — it can tie a post-coin round iff the minority class
    reaches m/2 — so termination has a phase transition at eps* = 1 - f,
    located here to ~1e-3 at N=1M."""
    rows = []
    for eps in eps_grid:
        cfg = SimConfig(n_nodes=n, n_faulty=int(f_frac * n), trials=trials,
                        max_rounds=16, delivery="quorum",
                        scheduler="adversarial", coin_mode="weak_common",
                        coin_eps=eps, path="histogram", seed=seed)
        pt = run_point(cfg, initial_values=_balanced(trials, n),
                       faults=FaultSpec.none(trials, n))
        rows.append({"eps": eps, **pt.to_dict()})
        if verbose:
            print(f"  eps={eps}: decided={pt.decided_frac:.3f} "
                  f"mean_k={pt.mean_k:.2f}", flush=True)
    return rows


def equivocation_threshold(n: int, trials: int, seed: int = 0,
                           verbose=True) -> List[Dict]:
    """Locate the N > 3F bound at scale: equivocators under the
    count-controlling adversary, common coin, balanced inputs.  The two
    middle rows have opposite fates across the bound: the largest F with
    3F < N strictly, and the smallest with 3F > N.  They are one node
    apart except when N % 3 == 0, where 3*(N//3) == N is already past the
    bound (it livelocks), so the sub row steps down one (same guard as
    bench.py's equiv_3f_sub) and the rows bracket the boundary two
    apart."""
    f_sub = n // 3 - (1 if n % 3 == 0 else 0)   # largest F with 3F < N
    sub_label = "N//3-1" if n % 3 == 0 else "N//3"
    rows = []
    for f, label in ((int(0.30 * n), "0.30*N"), (f_sub, sub_label),
                     (n // 3 + 1, "N//3+1"), (int(0.36 * n), "0.36*N")):
        cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials, max_rounds=16,
                        delivery="quorum", scheduler="adversarial",
                        coin_mode="common", fault_model="equivocate",
                        path="histogram", seed=seed)
        pt = run_point(cfg, initial_values=_balanced(trials, n))
        rows.append({"f": f, "label": label, "three_f_lt_n": 3 * f < n,
                     **pt.to_dict()})
        if verbose:
            print(f"  F={label} ({f:,}): decided={pt.decided_frac:.3f} "
                  f"mean_k={pt.mean_k:.2f} rounds={pt.rounds_executed}",
                  flush=True)
    return rows


def coin_contrast(n: int, trials: int, seed: int = 0,
                  f_frac: float = 0.20) -> Dict[str, List[SweepPoint]]:
    f = int(f_frac * n)
    f += (n - f) % 2                       # even quorum for a perfect tie
    cfg = SimConfig(n_nodes=n, n_faulty=f, trials=trials, max_rounds=16,
                    seed=seed, path="histogram")
    return coin_comparison(cfg)


def topo_curves(n: int, trials: int, seed: int = 0,
                max_rounds: int = 32, verbose: bool = False) -> Dict:
    """The structured-delivery science rows (PR 12, ROADMAP item 3):
    rounds-to-decide vs degree/diameter over the default
    ring/torus/random-regular ladder (neighborhood-unanimity decide
    bar — benor_tpu/topo/curves.unanimity_fault explains why laxer
    bars flatten the curve) and the committee-size sweep at a fixed
    committee count — the latter batched as ONE bucket executable
    (committee size/count ride DynParams), whose compile count rides
    the return as the coalescing proof bench's ``topo`` blob pins.

    Both curves run through the batched engine
    (sweep.run_points_batched); rows are json-ready dicts
    (tools/check_metrics_schema.check_topo_blob recomputes the
    degree/diameter metadata from the spec strings)."""
    from .topo.curves import (committee_curve, default_degree_specs,
                              degree_curve)

    base = SimConfig(n_nodes=n, n_faulty=0, trials=trials,
                     max_rounds=max_rounds, seed=seed)
    deg_rows = degree_curve(base, default_degree_specs(n),
                            verbose=verbose)
    # The swept sizes stay <= N/committee_count: the participation
    # probability p = min(1, c*g/N) clips at c = N/g, beyond which
    # every point draws the IDENTICAL membership — a ladder past the
    # clip would ship duplicate rows masquerading as distinct sizes
    # (committees.py documents the saturation).
    sizes = sorted({max(2, n // 16), max(3, n // 8), max(4, n // 4)})
    com_rows, cb = committee_curve(base.replace(n_faulty=1), sizes=sizes,
                                   committee_count=4, verbose=verbose)
    return {"degree_curve": deg_rows, "committee_curve": com_rows,
            "committee_compile_count": cb.compile_count,
            "committee_buckets": cb.n_buckets}


def faults_curves(n: int, trials: int, seed: int = 0,
                  max_rounds: int = 32, verbose: bool = False) -> Dict:
    """The faultlab science rows (PR 15, benor_tpu/faults): the paper's
    probabilistic-termination claim stress-tested along the two dynamic
    fault axes —

      * rounds-to-decide vs per-edge omission probability
        (``drop_curve``): the whole p grid compiles as ONE bucket
        executable (drop_prob rides DynParams; the compile count rides
        the return as the coalescing proof bench's ``faults`` blob
        pins).  The grid stays below the stall threshold p ~ F/N —
        beyond it the expected delivered count drops under the quorum
        N - F and every lane stalls to the round cap (the curve's
        asymptote, not its interesting region);
      * rounds-to-decide vs crash-recovery churn (``churn_curve``): a
        rolling ``stagger:2:<down>`` schedule with growing down length —
        deeper churn holds more of the quorum slack hostage per round.

    Rows are json-ready dicts; tools/check_metrics_schema
    .check_faults_blob recomputes the stall threshold and pins the
    one-bucket claim."""
    from .faults.curves import churn_curve, drop_curve

    f = max(n // 4, 1)
    base = SimConfig(n_nodes=n, n_faulty=f, trials=trials,
                     max_rounds=max_rounds, seed=seed)
    # omission grid: up to ~60% of the stall threshold F/N, so the curve
    # bends without saturating at the cap
    frac = f / n
    ps = [round(frac * s, 6) for s in (0.1, 0.25, 0.4, 0.6)]
    drop_rows, drop_cb = drop_curve(base, ps, verbose=verbose)
    churn_rows, churn_cb = churn_curve(
        base.replace(n_faulty=max(n // 8, 1)), down_lengths=(1, 3, 6),
        verbose=verbose)
    return {"drop_curve": drop_rows,
            "drop_compile_count": drop_cb.compile_count,
            "drop_buckets": drop_cb.n_buckets,
            "churn_curve": churn_rows,
            "churn_compile_count": churn_cb.compile_count}


def generate(out_dir: str = "RESULTS", n_large: int = 1_000_000,
             trials_large: int = 32, seed: int = 0,
             presets=True) -> Dict[str, object]:
    """Run every study, write JSON artifacts + RESULTS.md, return the data."""
    import jax

    from .utils.cache import enable_compile_cache
    enable_compile_cache()         # ~18 distinct configs; cache the compiles
    os.makedirs(out_dir, exist_ok=True)
    dev = jax.devices()[0]
    meta = {"device": str(dev.device_kind), "platform": dev.platform,
            "n_large": n_large, "trials_large": trials_large, "seed": seed}
    out: Dict[str, object] = {"meta": meta}

    print(f"results: device={dev.device_kind} N={n_large}", flush=True)

    # Whole-run insurance for the flagship path: probe the fused round
    # once at the study scale; a kernel lowering regression demotes
    # every _flagship_flags() study to the XLA path instead of killing
    # a 2 h on-chip capture at study #1.
    global _PROBE_OK
    if dev.platform != "cpu":
        _PROBE_OK = _flagship_probe(n_large)
        meta["flagship_pallas"] = _PROBE_OK
        print(f"  flagship pallas probe: "
              f"{'ok' if _PROBE_OK else 'DEMOTED to XLA'}", flush=True)

    print("balanced rounds-vs-f curve:", flush=True)
    pts = balanced_curve(n_large, trials_large, seed)
    out["balanced_curve"] = [
        {"f_frac": fr, **p.to_dict()} for fr, p in zip(CURVE_FRACS, pts)]

    print("margin sweep (f=0.40):", flush=True)
    out["margin_sweep"] = margin_sweep(n_large, trials_large, seed)

    print("coin contrast (adversarial):", flush=True)
    cc = coin_contrast(n_large, trials_large, seed)
    out["coin_contrast"] = {k: [p.to_dict() for p in v]
                            for k, v in cc.items()}

    print("disagreement vs adversary strength (f=0.25):", flush=True)
    out["disagreement"] = disagreement_sweep(n_large, trials_large, seed,
                                             out_dir=out_dir)

    print("safety violation under the targeted adversary:", flush=True)
    out["safety_violation"] = safety_violation(n_large, trials_large, seed,
                                               out_dir=out_dir)

    print("equivocation: the N > 3F bound at scale:", flush=True)
    out["equivocation"] = equivocation_threshold(n_large, trials_large, seed)

    print("convergence trajectory (f=0.45, balanced):", flush=True)
    out["trajectory"] = trajectory_study(n_large, trials_large, seed)

    print("scaling: rounds + throughput vs N (f=0.45, balanced):",
          flush=True)
    out["scaling"] = scaling_study(n_large, trials_large, seed)

    print("decision rule: reference vs textbook (f=0.45, balanced):",
          flush=True)
    out["rule_comparison"] = rule_comparison(n_large, trials_large, seed)

    print("weak common coin: termination vs eps (f=0.40, adversary):",
          flush=True)
    out["weak_coin"] = weak_coin_study(n_large, trials_large, seed)

    from .backends.native_oracle import native_available
    if native_available():
        print("oracle<->scheduler distribution parity (N=100):", flush=True)
        out["oracle_parity"] = oracle_parity(trials_large, seed)
    else:
        print("oracle parity: skipped (no g++)", flush=True)

    if presets:
        from .serve.jobs import JobSpec
        for name, cfg in baseline_configs().items():
            if cfg.n_nodes > n_large:      # CPU smoke scaling
                continue
            print(f"preset {name}:", flush=True)
            pt = run_point(cfg)
            print(f"  mean_k={pt.mean_k:.3f} decided={pt.decided_frac:.3f} "
                  f"{pt.trials_per_sec:.1f} trials/s", flush=True)
            row = pt.to_dict()
            # provenance through the request plane: the job document
            # that replays this row via `POST /v1/jobs` on a running
            # `python -m benor_tpu serve` — bit-equal by the serve
            # plane's house rule (tests/test_serve.py)
            row["serve_replay"] = JobSpec.from_config(cfg).to_dict()
            out[f"preset_{name}"] = row

    with open(os.path.join(out_dir, "results.json"), "w") as fh:
        json.dump(out, fh, indent=1)
    _write_markdown(out_dir, out)
    print(f"results: wrote {out_dir}/results.json and {out_dir}/RESULTS.md",
          flush=True)
    return out


def _write_markdown(out_dir: str, out: Dict) -> None:
    meta = out["meta"]
    lines = [
        "# RESULTS — expected-rounds curves (BASELINE.json north star)",
        "",
        f"Generated on `{meta['device']}` ({meta['platform']}), "
        f"N={meta['n_large']:,}, {meta['trials_large']} MC trials, "
        f"seed={meta['seed']}.  Regenerate with "
        "`python -m benor_tpu results`.",
        "",
        "## Expected rounds vs fault fraction "
        "(balanced inputs, zero crashes)",
        "",
        "Decide threshold is `count > F` of `m = N-F` tallied votes: for "
        "f > 1/3 the threshold exceeds the typical class count m/2 and "
        "deciding requires the sampling-noise random walk to amplify a "
        "network-wide majority first.",
        "",
        "(ones frac = 0.000 for f < 1/3 is the reference's decide0-first "
        "quirk, node.ts:99-104: with balanced votes BOTH classes exceed F, "
        "and the 0-branch is checked first — every lane decides 0.)",
        "",
        "| f | mean k | decided | ones frac | trials/s |",
        "|---|---|---|---|---|",
    ]
    for row in out["balanced_curve"]:
        lines.append(
            f"| {row['f_frac']:.2f} | {row['mean_k']:.3f} "
            f"| {row['decided_frac']:.3f} | {row['ones_frac']:.3f} "
            f"| {row['trials_per_sec']:.1f} |")
    lines += [
        "",
        "## Rounds vs initial margin (f = 0.40)",
        "",
        "1-count = N/2 + delta*sqrt(N)/2 per trial: the transition from "
        "sampling-noise-dominated (multi-round) to margin-dominated "
        "(1-round) decisions.",
        "",
        "| delta (x sqrt(N)) | mean k | ones frac |",
        "|---|---|---|",
    ]
    for row in out["margin_sweep"]:
        lines.append(f"| {row['delta']} | {row['mean_k']:.3f} "
                     f"| {row['ones_frac']:.3f} |")
    cc = out["coin_contrast"]
    priv, comm = cc["private"][0], cc["common"][0]
    lines += [
        "",
        "## Private vs common coin under the count-controlling adversary",
        "",
        "The adversary delivers every receiver a tied 0/1 multiset; private "
        "coins cannot break network-wide symmetry (livelock at the round "
        "cap), the shared common coin does so in O(1) expected rounds — "
        "the Ben-Or vs Rabin contrast at N=1M:",
        "",
        "| coin | decided | mean k | rounds executed |",
        "|---|---|---|---|",
        f"| private | {priv['decided_frac']:.3f} | {priv['mean_k']:.2f} "
        f"| {priv['rounds_executed']} |",
        f"| common | {comm['decided_frac']:.3f} | {comm['mean_k']:.2f} "
        f"| {comm['rounds_executed']} |",
        "",
        "## Agreement-safety violations vs split-adversary strength "
        "(f = 0.25)",
        "",
        "The reference's decide rule `count > F` is only safe while at most "
        "N-F senders are alive (its crash model guarantees that).  With all "
        "N alive, a delay adversary that starves even receivers of 1s and "
        "odd receivers of 0s makes the two halves decide OPPOSITE values — "
        "`disagree` is the fraction of trials whose decided healthy nodes "
        "hold both values.  (Every s >= 1 is exact strict priority on the "
        "histogram path — the curve is flat beyond 1.0 by construction.)",
        "",
        "| strength s | disagree | decided | mean k | ones frac |",
        "|---|---|---|---|---|",
    ]
    for row in out["disagreement"]:
        lines.append(
            f"| {row['strength']} | {row['disagree_frac']:.3f} "
            f"| {row['decided_frac']:.3f} | {row['mean_k']:.2f} "
            f"| {row['ones_frac']:.3f} |")
    if "safety_violation" in out:
        lines += [
            "",
            "## Agreement under the TARGETED (partitioned) adversary",
            "",
            "The worst case of the \"first N−F arrivals win\" "
            "nondeterminism (node.ts:52,88): nothing forces two receivers "
            "to tally the same multiset.  The targeted scheduler seeds F+1 "
            "receivers to decide 0, F+1 to decide 1, and feeds the rest "
            "perfect ties so their \"?\" votes (counted toward quorums by "
            "quirk 4) starve the 1-camp's zero-count under the bar.  Where "
            "the delay-bounded split adversary above has a soft "
            "probabilistic transition, this curve is exactly 0/1: "
            "agreement is violated at EVERY 1 ≤ F < N/2 (even quorum), "
            "and at f ≥ 1/2 the bar `count > F` is unreachable — livelock. "
            "The `odd` rows show the quirk-born parity effect: an odd "
            "quorum admits no perfect phase-1 tie, no \"?\" voters can be "
            "manufactured, and the attack weakens to N ≤ 3F + 1. "
            "The final row arms ONE equivocator: the decide rule has no "
            "Byzantine safety margin at any N.  Every violating row was "
            "auto-rerun with the witness recorder armed and machine-"
            "checked by the invariant auditor (benor_tpu/audit.py); the "
            "pinpointed (trial, round, node, tallies) witness bundles "
            "sit next to this file as `witness_*.json`.",
            "",
            "| F | fault model | disagree | decided | mean k |",
            "|---|---|---|---|---|",
        ]
        for row in out["safety_violation"]:
            lines.append(
                f"| {row['f']:,} | {row['fault_model']} "
                f"| {row['disagree_frac']:.3f} | {row['decided_frac']:.3f} "
                f"| {row['mean_k']:.2f} |")
    if "oracle_parity" in out:
        op = out["oracle_parity"]
        lines += [
            "",
            "## Oracle ↔ scheduler distribution parity (SURVEY hard-part 1)",
            "",
            "Within the reference contract, crash faults are pinned to "
            "exactly F, so alive == quorum and every tally holds the FULL "
            "live population in any delivery order — the event-loop "
            "asynchrony is *tally-invisible* in the reference's own "
            "scenario space.  Decided runs are delivery-order-invariant "
            f"(fifo == shuffle bit-identically: "
            f"{op['order_invariant_decided_runs']}), order-dependence "
            "survives only as a coin-assignment permutation in runs capped "
            "mid-coin-phase, and the per-trial rounds-to-decide law — "
            "driven solely by iid fair coins — matches the tpu "
            "uniform-quorum scheduler's:",
            "",
            f"- N={op['n']}, F={op['f']}, {op['n_seeds']} seeds/trials "
            "(balanced healthy inputs, every round a coin round)",
            f"- oracle rounds histogram: `{op['oracle_round_hist']}` "
            f"(mean {op['oracle_mean_rounds']})",
            f"- tpu    rounds histogram: `{op['tpu_round_hist']}` "
            f"(mean {op['tpu_mean_rounds']})",
            f"- two-sample KS: D = {op['ks_statistic']}, "
            f"p = {op['ks_pvalue']}",
        ]
    if "equivocation" in out:
        lines += [
            "",
            "## The N > 3F bound, located to ±1 node at N = 10⁶",
            "",
            "Equivocators (per-receiver Byzantine values) controlled by the "
            "count-controlling adversary, against the shared common coin: "
            "at F ≥ N/3 the adversary's free pool covers the tie deficit of "
            "every tally forever (the classic impossibility); at F < N/3 a "
            "coin-unified honest class forces m − F > F votes and decides. "
            "The middle rows differ by ONE node out of a million:",
            "",
            "| F | 3F < N | decided | mean k | rounds executed |",
            "|---|---|---|---|---|",
        ]
        for row in out["equivocation"]:
            lines.append(
                f"| {row['label']} = {row['f']:,} | {row['three_f_lt_n']} "
                f"| {row['decided_frac']:.3f} | {row['mean_k']:.2f} "
                f"| {row['rounds_executed']} |")
    if "scaling" in out:
        lines += [
            "",
            "## Scaling: rounds and throughput vs N (f = 0.45, balanced)",
            "",
            "The decide threshold exceeds the typical class count by O(N) "
            "while sampling noise is only O(√N) — yet mean k stays flat, "
            "because round 1's plurality-adopt step amplifies the initial "
            "√N-scale imbalance into a network-wide majority in one round. "
            "trials/s traces the single-chip weak-scaling envelope "
            "(dispatch-bound at small N, bandwidth-bound at 10⁶).",
            "",
            "| N | mean k | decided | trials/s |",
            "|---|---|---|---|",
        ]
        for row in out["scaling"]:
            lines.append(
                f"| {row['n']:,} | {row['mean_k']:.3f} "
                f"| {row['decided_frac']:.3f} "
                f"| {row['trials_per_sec']:.1f} |")
    if "weak_coin" in out:
        lines += [
            "",
            "## Weak common coin: termination vs deviation probability ε "
            "(f = 0.40)",
            "",
            "`coin_mode='weak_common'` interpolates shared (ε = 0) and "
            "private (ε = 1) coins: each lane deviates to a private flip "
            "with probability ε. The count-controlling adversary can tie a "
            "post-coin round iff the deviating minority reaches m/2, so "
            "termination flips at ε\\* = 1 − f — located below to ~10⁻³ at "
            "N = 10⁶ (weak coins *almost* as bad as ε\\* still terminate; "
            "slightly past it, livelock):",
            "",
            "| ε | decided | mean k | rounds executed |",
            "|---|---|---|---|",
        ]
        for row in out["weak_coin"]:
            lines.append(
                f"| {row['eps']} | {row['decided_frac']:.3f} "
                f"| {row['mean_k']:.2f} | {row['rounds_executed']} |")
    if "rule_comparison" in out:
        lines += [
            "",
            "## Decision rule: reference (plurality-adopt) vs textbook",
            "",
            "The reference adopts the plurality of non-\"?\" votes before "
            "coining (node.ts:106-112, quirk 9) — the amplification step "
            "that locks the network onto round 1's sampling-noise majority. "
            "Textbook Ben-Or (coin whenever no value clears > F votes) "
            "lacks it; `rule='textbook'` quantifies what the reference's "
            "own k ≤ 2 test bounds silently depend on:",
            "",
            "| rule | mean k | decided |",
            "|---|---|---|",
        ]
        for row in out["rule_comparison"]:
            lines.append(f"| {row['rule']} | {row['mean_k']:.3f} "
                         f"| {row['decided_frac']:.3f} |")
    if "trajectory" in out:
        lines += [
            "",
            "## Convergence trajectory (f = 0.45, balanced inputs)",
            "",
            "Round-resolved dynamics from `sweep.record_trajectory` (one "
            "compiled scan, on-device reductions): the decided fraction "
            "jumps 0 → 1 in a single round once sampling noise amplifies a "
            "network-wide majority; `zeros`/`ones`/`qs` are the live "
            "healthy lanes' value shares after each round.",
            "",
            "| round | decided | zeros | ones | qs | disagree |",
            "|---|---|---|---|---|---|",
        ]
        for row in out["trajectory"]:
            lines.append(
                f"| {row['round']} | {row['decided']:.3f} "
                f"| {row['zeros']:.3f} | {row['ones']:.3f} "
                f"| {row['qs']:.3f} | {row['disagree']:.3f} |")
    lines += [
        "",
        "## BASELINE.json presets",
        "",
        "As literally specified: crash-from-birth faults pin the live "
        "population to exactly the quorum N-F, so every receiver tallies "
        "the whole population deterministically and iid inputs decide in "
        "one round (mean k ~ 2) — including the adversarial preset, whose "
        "scheduler has no delivery slack to exploit.  The studies above "
        "decouple F from the crash count (zero crashes) to expose the "
        "multi-round regimes.",
        "",
        "| preset | N | F | trials | mean k | decided | trials/s |",
        "|---|---|---|---|---|---|---|",
    ]
    for key, row in out.items():
        if not key.startswith("preset_"):
            continue
        lines.append(
            f"| {key[7:]} | {row['n_nodes']:,} | {row['n_faulty']:,} "
            f"| {row['trials']} | {row['mean_k']:.3f} "
            f"| {row['decided_frac']:.3f} | {row['trials_per_sec']:.1f} |")
    lines.append("")
    with open(os.path.join(out_dir, "RESULTS.md"), "w") as fh:
        fh.write("\n".join(lines))
