"""Multi-host (multi-process) runtime — the DCN half of the N7 backend.

The reference's "distributed" story is N Express servers in ONE OS process
exchanging localhost HTTP (SURVEY.md §5.8; src/nodes/node.ts:202).  The
single-process mesh (parallel/mesh.py + sharded.py) already replaces that
message plane with ICI collectives across the chips of one host; this module
extends the SAME ('trials', 'nodes') mesh across *processes* — one JAX
process per host of a pod slice, jax.distributed coordination, XLA
collectives riding DCN between hosts — the way a torch framework would scale
out with NCCL/MPI ranks, re-hosted on jax's SPMD runtime.

Layout doctrine (mesh.py's, now with a process dimension):

  'trials' — maps across PROCESSES (DCN): trials never exchange data; the
             only cross-host collective is the scalar termination psum in
             the while-loop condition, so DCN latency is off the round's
             critical path.
  'nodes'  — stays INSIDE a process (ICI): the per-round histogram psum
             (and the dense path's all-gather) never leaves the host.

Because every random draw keys on GLOBAL (trial, node, round) ids
(ops/rng.py, ops/pallas_hist.py), a multi-host run is bit-identical to the
single-device run — the same guarantee tests/test_parallel.py pins for
single-process meshes, extended across process boundaries by
tests/test_multihost.py (two real OS processes, Gloo CPU collectives).
The fused-round regime rides the same delegation: this module reuses
sharded.py's slice bodies, whose packed path carries the bit-plane state
stack (state.PACK_LAYOUT) through the two-kernel plane pipeline — the
single-pass fused kernel is a single-device dispatch, and the dispatch
boundary is bit-invisible (tests/multihost_worker.py's fused-round leg).

No host ever materializes the full [T, N] arrays: each process builds only
its addressable slab and `jax.make_array_from_process_local_data` assembles
the global array (the jax-native equivalent of per-rank shard loading).

Usage (same program runs on every process, SPMD style):

    init_multihost(coordinator, num_processes=P, process_id=p)
    mesh = global_mesh()                       # (P, local_devices) by default
    tr, nd = local_block(mesh, cfg.trials, cfg.n_nodes)
    state, faults = ...build numpy slabs for [tr, nd]...
    shape = (cfg.trials, cfg.n_nodes)
    state, faults = to_global(state, mesh, shape), to_global(faults, mesh, shape)
    rounds, final = run_consensus_multihost(cfg, state, faults, key, mesh)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..config import SimConfig
from ..state import FaultSpec, NetState
from . import mesh as meshlib
from . import sharded


def init_multihost(coordinator_address: str, num_processes: int,
                   process_id: int, **kw) -> None:
    """Join (or form) the cross-host JAX cluster.

    Thin, explicit wrapper over ``jax.distributed.initialize`` — on cloud
    TPU pods jax can autodetect all three arguments, but the explicit form
    is what works everywhere (including the CPU Gloo backend the test
    harness uses to run two real processes on one machine).  Must be called
    before the backend is first used in this process.  Idempotent."""
    if jax.distributed.is_initialized():
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kw)


def global_mesh(trial_shards: Optional[int] = None,
                node_shards: Optional[int] = None) -> Mesh:
    """('trials', 'nodes') mesh over every device of every process.

    Defaults place the trials axis exactly across processes (DCN) and the
    node axis across each process's local devices (ICI) — the layout under
    which no per-round collective crosses a host boundary.  Devices are
    ordered by (process, id) so each mesh row is one process's devices
    whenever trial_shards == process_count."""
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    if trial_shards is None:
        trial_shards = jax.process_count()
    if node_shards is None:
        node_shards = len(devs) // trial_shards
    return meshlib.make_mesh(trial_shards, node_shards, devices=devs)


def local_block(mesh: Mesh, trials: int,
                n_nodes: int) -> Tuple[slice, slice]:
    """This process's addressable (trial, node) slab of a [T, N] array.

    The sharding grid is regular, so the union of this process's per-device
    blocks is a contiguous rectangle; returns its (row, col) slices.  Each
    process builds ONLY this slab of initial values / fault masks."""
    sh = NamedSharding(mesh, meshlib.STATE_SPEC)
    idx_map = sh.devices_indices_map((trials, n_nodes))
    mine = [idx for d, idx in idx_map.items()
            if d.process_index == jax.process_index()]
    if not mine:
        raise ValueError("mesh has no devices from this process")
    rows = [s[0].indices(trials) for s in mine]
    cols = [s[1].indices(n_nodes) for s in mine]
    tr = slice(min(r[0] for r in rows), max(r[1] for r in rows))
    nd = slice(min(c[0] for c in cols), max(c[1] for c in cols))
    # A mesh whose rows straddle process boundaries (e.g. 2 procs x 4 devs
    # arranged 2x3) gives this process a NON-rectangular union of blocks;
    # the bounding box would then claim cells owned by other processes.
    block = (rows[0][1] - rows[0][0]) * (cols[0][1] - cols[0][0])
    rect = (tr.stop - tr.start) * (nd.stop - nd.start)
    if len(mine) * block != rect:
        raise ValueError(
            f"this process's device blocks do not tile a rectangle under "
            f"this mesh (bounding box {rect} cells vs {len(mine)} blocks of "
            f"{block}); choose mesh axes that align with process boundaries "
            f"(default global_mesh() does: trials == process_count)")
    return tr, nd


def make_global(local: np.ndarray, mesh: Mesh,
                global_shape: Tuple[int, int]) -> jax.Array:
    """Assemble one [T, N] global array from this process's local slab."""
    sh = NamedSharding(mesh, meshlib.STATE_SPEC)
    return jax.make_array_from_process_local_data(sh, np.asarray(local),
                                                  global_shape)


def to_global(tree, mesh: Mesh, global_shape: Tuple[int, int]):
    """Any pytree of process-local [T_loc, N_loc] slabs -> global arrays.

    NetState and FaultSpec are registered pytrees, so one function covers
    both (and any future leaf added to either)."""
    return jax.tree.map(lambda a: make_global(a, mesh, global_shape), tree)


def _check_global(state: NetState, faults: FaultSpec,
                  shape: Tuple[int, int]) -> None:
    for name, leaf in (("state", state.x), ("faults", faults.faulty)):
        if tuple(leaf.shape) != shape:
            raise ValueError(
                f"{name} leaves must be GLOBAL [T, N] arrays (got "
                f"{leaf.shape}, want {shape}); build local slabs and call "
                f"to_global")


def run_consensus_multihost(cfg: SimConfig, state: NetState,
                            faults: FaultSpec, base_key: jax.Array,
                            mesh: Mesh):
    """Run /start -> termination over a process-spanning mesh.

    Same contract and SAME compiled executable as
    sharded.run_consensus_sharded — the mesh simply spans hosts; inputs must
    already be global arrays (to_global), because a cross-host run has no
    single host that could hold the full [T, N] data for a device_put.  ``base_key`` is host-local and identical on
    every process (all processes derive it from cfg.seed), which jit treats
    as replicated.  Must be called by every process (SPMD single-program).

    Returns (rounds, final): ``rounds`` is fully replicated (fetchable on
    any host); ``final`` leaves are global arrays — reduce them on-device
    (sweep.summarize_final) or gather with
    jax.experimental.multihost_utils.process_allgather(..., tiled=True).
    Under cfg.record the (replicated) flight recorder is appended as a
    third output, like every other runner; under cfg.witness the
    (replicated) witness buffer follows it.
    """
    meshlib.check_divisible(cfg.trials, cfg.n_nodes, mesh)
    _check_global(state, faults, (cfg.trials, cfg.n_nodes))
    return sharded._compiled(cfg, mesh)(state, faults, base_key,
                                        jnp.int32(1))


def run_consensus_slice_multihost(cfg: SimConfig, state: NetState,
                                  faults: FaultSpec, base_key: jax.Array,
                                  mesh: Mesh, from_round, until_round,
                                  recorder=None, witness=None):
    """Mid-run observability (cfg.poll_rounds) on a process-spanning mesh.

    Counterpart of sharded.run_consensus_slice_sharded with global inputs
    (the caller applies sim.start_state once, then steps in slices): every
    process calls this SPMD-style with the same round bounds and observes
    the same replicated next_round, so all hosts stay in lockstep while a
    poller on any host watches its local slab's k grow.  A sliced
    multi-host run is bit-identical to the uninterrupted one — randomness
    keys on (base_key, round, phase, global ids), never loop entry.

    Under cfg.record the (replicated) flight recorder threads through
    like every other slice primitive: pass the previous slice's buffer,
    None starts a fresh one; the filled buffer is the third output.  The
    witness buffer (cfg.witness) threads the same way, appended after
    the recorder when both are armed.

    With cfg.heartbeat_rounds, PROCESS 0 publishes the host-side
    live-progress heartbeat at cadence-crossing slice boundaries (the
    replicated round cursor is identical on every host, so one
    publisher suffices; meshscope/heartbeat.py) — registry gauges only,
    out-of-band of the compiled slice, same bit-identity contract as
    the sharded wrapper."""
    meshlib.check_divisible(cfg.trials, cfg.n_nodes, mesh)
    _check_global(state, faults, (cfg.trials, cfg.n_nodes))
    args = (state, faults, base_key, jnp.int32(from_round),
            jnp.int32(until_round))
    if cfg.record:
        if recorder is None:
            from ..state import new_recorder
            recorder = new_recorder(cfg, state)
        args = args + (recorder,)
    if cfg.witness:
        if witness is None:
            from ..state import new_witness
            witness = new_witness(cfg, state)
        args = args + (witness,)
    out = sharded._compiled_slice(cfg, mesh)(*args)
    if cfg.heartbeat_rounds and jax.process_index() == 0:
        from ..meshscope.heartbeat import publish_slice_heartbeat
        publish_slice_heartbeat(cfg, out[0],
                                recorder=out[2] if cfg.record else None,
                                label="multihost.slice",
                                from_round=from_round)
    return out


def resume_consensus_multihost(cfg: SimConfig, state: NetState,
                               faults: FaultSpec, base_key: jax.Array,
                               mesh: Mesh, from_round: int):
    """Checkpoint re-entry on a process-spanning mesh (SURVEY §5.4).

    Counterpart of sharded.resume_consensus_sharded with global inputs: a
    checkpoint written by ANY run (single-device, single-process mesh, or
    another multi-host shape) resumes bit-identically here, because
    randomness keys on (base_key, round, phase, global ids) only.  Under
    cfg.record a FRESH (re-entry) flight recorder is appended — rows
    before ``from_round`` stay unwritten (utils/metrics.py renders such
    gapped buffers by true round index)."""
    meshlib.check_divisible(cfg.trials, cfg.n_nodes, mesh)
    _check_global(state, faults, (cfg.trials, cfg.n_nodes))
    return sharded._compiled(cfg, mesh, fresh=False)(
        state, faults, base_key, jnp.int32(from_round))
