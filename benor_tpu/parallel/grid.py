"""2D (trials x nodes) grid placement for giant sweeps (ROADMAP item 1).

``parallel/sharded.py`` owns the node-axis ``shard_map`` round kernel; this
module is the *placement* layer above it: a partition-rule table mapping
every ``NetState`` / ``FaultSpec`` / recorder / witness leaf to its
``PartitionSpec``, an auto-factoring of the available devices into a
``('trials', 'nodes')`` mesh, and ``run_consensus_grid`` — a single entry
point whose results are bit-identical at every mesh shape:

  * mesh (1, 1)  -> the traced single-device loop (``run_consensus``);
  * mesh (1, d)  -> exactly ``run_consensus_sharded`` (node-only shards);
  * mesh (t, n)  -> trials-axis data parallelism multiplying the node-axis
                    psum tallies.  The trials axis carries no per-round
                    collective (trials never communicate), so bit-identity
                    follows from the (trial, node, round)-keyed RNG plus
                    the integer-exact per-round reductions.

The batched sweep engine reuses the same table through
``grid_batch_sharding`` to place its stacked [B, T, N] bucket operands, so
a 2D mesh accelerates every dyn bucket of ``run_points_batched`` without a
second code path (GSPMD partitions the vmapped executable; the summaries
are exact integer reductions, hence mesh-independent journal records).

Rules follow the partition-rule pattern of t5x/EasyLM (SNIPPETS.md [1]/[3]):
match on leaf name, fall through to replication.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import SimConfig
from ..sim import run_consensus
from .mesh import (AXIS_NODES, AXIS_TRIALS, STATE_SPEC, check_divisible,
                   make_mesh)
from .sharded import run_consensus_sharded

#: Leaf-name -> PartitionSpec rules for the consensus pytrees.  Every
#: [T, N] plane (the four NetState planes and the FaultSpec masks) is
#: block-partitioned on both mesh axes; scalars, keys and the
#: round-major observation buffers (flight recorder / witness — shaped
#: [R, ...] and reduced across nodes before they leave the shard_map)
#: stay replicated.
GRID_RULES: Tuple[Tuple[str, P], ...] = (
    # NetState planes
    ("x", STATE_SPEC),
    ("decided", STATE_SPEC),
    ("k", STATE_SPEC),
    ("killed", STATE_SPEC),
    # FaultSpec planes
    ("faulty", STATE_SPEC),
    ("crash_round", STATE_SPEC),
    ("recover_round", STATE_SPEC),
    # loop-carried scalars / keys
    ("base_key", P()),
    ("rounds", P()),
)

#: Observation buffers are appended under cfg.record / cfg.witness; they
#: are psum-reduced inside the round kernel and replicated on exit.
OBSERVATION_RULES: Tuple[Tuple[str, P], ...] = (
    ("recorder", P()),
    ("witness", P()),
)


def partition_rules(cfg: SimConfig) -> dict:
    """The active leaf-name -> PartitionSpec table for ``cfg``.

    Observation entries (``recorder`` / ``witness``) appear only when the
    corresponding plane is armed, so the table is also a manifest of what
    the runner will return beyond ``(rounds, state)``.
    """
    rules = dict(GRID_RULES)
    active = dict(OBSERVATION_RULES)
    if cfg.record:
        rules["recorder"] = active["recorder"]
    if cfg.witness:
        rules["witness"] = active["witness"]
    return rules


def spec_for(name: str, cfg: SimConfig) -> P:
    """PartitionSpec for a named leaf (replicated if no rule matches)."""
    return partition_rules(cfg).get(name, P())


def auto_factor(n_devices: int, trials: int, n_nodes: int
                ) -> Tuple[int, int]:
    """Factor ``n_devices`` into a (trial_shards, node_shards) grid.

    Prefers (1) using every device, (2) the largest node axis — the
    node-axis histogram psum is the per-round collective and should ride
    ICI; the trials axis only meets at the scalar termination psum.
    Shards must divide their axis extents (block partitioning).
    """
    best = (1, 1)
    best_rank = (1, 1)  # (devices used, node shards)
    for node_shards in range(1, n_devices + 1):
        if n_nodes % node_shards:
            continue
        trial_shards = min(n_devices // node_shards, trials)
        while trial_shards > 1 and trials % trial_shards:
            trial_shards -= 1
        used = trial_shards * node_shards
        if used > n_devices:
            continue
        rank = (used, node_shards)
        if rank > best_rank:
            best_rank, best = rank, (trial_shards, node_shards)
    return best


def make_grid_mesh(cfg: Optional[SimConfig] = None,
                   trial_shards: Optional[int] = None,
                   node_shards: Optional[int] = None,
                   devices=None) -> Mesh:
    """Build the ('trials', 'nodes') mesh.

    Explicit shard counts win; otherwise the shape is auto-factored from
    the available devices and ``cfg.trials`` / ``cfg.n_nodes`` (CPU smoke
    via ``xla_force_host_platform_device_count`` factors the same way).
    """
    if devices is None:
        devices = jax.devices()
    if trial_shards is None and node_shards is None:
        if cfg is None:
            raise ValueError("auto-factoring a grid mesh needs cfg "
                             "(trials / n_nodes extents)")
        trial_shards, node_shards = auto_factor(
            len(devices), cfg.trials, cfg.n_nodes)
    return make_mesh(trial_shards or 1, node_shards, devices=devices)


def shard_grid_inputs(cfg: SimConfig, state, faults, base_key, mesh: Mesh):
    """Place the run inputs per the partition-rule table."""
    rules = partition_rules(cfg)

    def _put(name, leaf):
        if leaf is None:
            return None
        return jax.device_put(
            leaf, NamedSharding(mesh, rules.get(name, P())))

    placed_state = type(state)(
        **{f: _put(f, getattr(state, f)) for f in ("x", "decided", "k",
                                                   "killed")})
    placed_faults = type(faults)(
        faulty=_put("faulty", faults.faulty),
        crash_round=_put("crash_round", faults.crash_round),
        recover_round=_put("recover_round", faults.recover_round),
    )
    placed_key = jax.device_put(
        base_key, NamedSharding(mesh, rules.get("base_key", P())))
    return placed_state, placed_faults, placed_key


def run_consensus_grid(cfg: SimConfig, state, faults, base_key,
                       mesh: Optional[Mesh] = None):
    """Run the consensus loop on a 2D (trials x nodes) grid mesh.

    Returns the same ``(rounds, state[, recorder][, witness])`` tuple as
    ``run_consensus`` at every mesh shape.  ``mesh=None`` auto-factors
    from the available devices; a 1-device mesh falls through to the
    traced loop so the grid entry point is safe to use unconditionally.
    """
    if mesh is None:
        mesh = make_grid_mesh(cfg)
    if mesh.size == 1:
        # (1, 1): the traced single-device loop IS the reference
        return run_consensus(cfg, state, faults, base_key)
    check_divisible(cfg.trials, cfg.n_nodes, mesh)
    state, faults, base_key = shard_grid_inputs(
        cfg, state, faults, base_key, mesh)
    return run_consensus_sharded(cfg, state, faults, base_key, mesh)


def grid_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for the sweep engine's stacked [B, T, N] bucket operands:
    bucket axis replicated (vmap lanes), trials/nodes block-partitioned."""
    return NamedSharding(mesh, P(None, AXIS_TRIALS, AXIS_NODES))


def place_batch(tree, mesh: Mesh):
    """Place a stacked bucket pytree on the grid: every [B, T, N] leaf by
    ``grid_batch_sharding``, everything else (DynParams scalars, key
    stacks) replicated.  Bit-identity is free — the bucket summaries are
    integer-exact reductions, so GSPMD partitioning cannot change them.
    """
    ts = mesh.shape[AXIS_TRIALS]
    ns = mesh.shape[AXIS_NODES]
    batch = grid_batch_sharding(mesh)
    rep = NamedSharding(mesh, P())

    def _put(leaf):
        if leaf is None:
            return None
        if (getattr(leaf, "ndim", 0) == 3
                and leaf.shape[1] % ts == 0 and leaf.shape[2] % ns == 0):
            return jax.device_put(leaf, batch)
        return jax.device_put(leaf, rep)

    return jax.tree_util.tree_map(_put, tree)
