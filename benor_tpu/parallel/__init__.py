"""Multi-chip distribution: mesh construction and shard_map'd round kernels."""

from .mesh import (AXIS_NODES, AXIS_TRIALS, STATE_SPEC, make_mesh,
                   state_sharding)
from .sharded import (MESH_CTX, resume_consensus_sharded,
                      run_consensus_sharded, shard_inputs)

__all__ = [
    "AXIS_NODES", "AXIS_TRIALS", "STATE_SPEC", "make_mesh", "state_sharding",
    "MESH_CTX", "resume_consensus_sharded", "run_consensus_sharded",
    "shard_inputs",
]
