"""Multi-chip distribution: mesh construction and shard_map'd round kernels."""
