"""Multi-chip distribution: mesh construction and shard_map'd round kernels."""

from .grid import (GRID_RULES, auto_factor, grid_batch_sharding,
                   make_grid_mesh, partition_rules, place_batch,
                   run_consensus_grid, shard_grid_inputs)
from .mesh import (AXIS_NODES, AXIS_TRIALS, STATE_SPEC, make_mesh,
                   state_sharding)
from .multihost import (global_mesh, init_multihost, local_block,
                        make_global, resume_consensus_multihost,
                        run_consensus_multihost,
                        run_consensus_slice_multihost, to_global)
from .sharded import (MESH_CTX, resume_consensus_sharded,
                      run_consensus_sharded, run_consensus_slice_sharded,
                      shard_inputs)

__all__ = [
    "AXIS_NODES", "AXIS_TRIALS", "STATE_SPEC", "make_mesh", "state_sharding",
    "GRID_RULES", "auto_factor", "grid_batch_sharding", "make_grid_mesh",
    "partition_rules", "place_batch", "run_consensus_grid",
    "shard_grid_inputs",
    "MESH_CTX", "resume_consensus_sharded", "run_consensus_sharded",
    "run_consensus_slice_sharded", "shard_inputs",
    "init_multihost", "global_mesh", "local_block", "to_global",
    "make_global", "run_consensus_multihost", "resume_consensus_multihost",
    "run_consensus_slice_multihost",
]
