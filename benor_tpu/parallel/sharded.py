"""shard_map'd consensus runner — the multi-chip round loop (SURVEY.md N7).

The single-device run (sim.py) and this runner share the SAME round kernel
(models/benor.py): the kernel takes a ``ShardCtx`` naming the mesh axes and
performs its tallies via ``psum`` over ICI instead of a local reduction.
Because every random draw is keyed on *global* (trial, node, round) ids
(ops/rng.py), the sharded run is bit-identical to the single-device run for
any mesh shape — verified by tests/test_parallel.py.

Per round and node-shard the communication is:
  histogram path:  one psum of an int32 [T_loc, 3] histogram per phase
                   (+ one [T_loc] alive-count psum, one scalar termination
                   psum) — O(1) bytes per node, pure ICI latency.
  dense path:      one tiled all-gather of int8 [T_loc, N_loc] sent values
                   and bool alive per phase.

The whole run stays inside one jitted while_loop: zero host round-trips.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..config import SimConfig
from ..models.benor import all_settled, benor_round
from ..ops.collectives import ShardCtx
from ..sim import start_state
from ..state import FaultSpec, NetState
from . import mesh as meshlib

#: ShardCtx used by every kernel invocation under the ('trials','nodes') mesh.
MESH_CTX = ShardCtx(trial_axis=meshlib.AXIS_TRIALS,
                    node_axis=meshlib.AXIS_NODES)


def _local_run(cfg: SimConfig, state: NetState, faults: FaultSpec,
               base_key: jax.Array) -> Tuple[jax.Array, NetState]:
    """Per-shard body: full /start -> termination loop on local blocks.

    The loop carries a replicated ``settled`` flag computed via psum so all
    shards take identical trip counts (a shard-local predicate would
    deadlock the collectives inside the body).
    """
    ctx = MESH_CTX
    state = start_state(cfg, state)

    def body(carry):
        r, st, _ = carry
        st = benor_round(cfg, st, faults, base_key, r, ctx)
        if cfg.debug:  # per-round host callback (SURVEY §5.1) — globalized
            # counts, emitted once per round by the (0, 0) shard; unordered
            # (ordered effects unsupported on >1 device, see tracing.py)
            from ..utils.tracing import emit_round_event
            emit_round_event(st, ctx)
        return (r + 1, st, all_settled(st, ctx))

    def cond(carry):
        r, _, settled = carry
        return (r <= cfg.max_rounds) & ~settled

    r, state, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(1), state, all_settled(state, ctx)))
    return r - 1, state


@functools.lru_cache(maxsize=None)
def _compiled(cfg: SimConfig, mesh: Mesh):
    sspec = meshlib.STATE_SPEC
    fn = shard_map(
        functools.partial(_local_run, cfg),
        mesh=mesh,
        in_specs=(sspec, sspec, P()),
        out_specs=(P(), sspec),
        check_vma=False,  # while_loop results can't be proven replicated
    )
    return jax.jit(fn)


def shard_inputs(state: NetState, faults: FaultSpec, mesh: Mesh):
    """Place state/fault leaves block-wise on the mesh (one transfer each)."""
    sh = meshlib.state_sharding(mesh)
    put = lambda a: jax.device_put(a, sh)
    state = NetState(x=put(state.x), decided=put(state.decided),
                     k=put(state.k), killed=put(state.killed))
    faults = FaultSpec(faulty=put(faults.faulty),
                       crash_round=put(faults.crash_round))
    return state, faults


def run_consensus_sharded(cfg: SimConfig, state: NetState, faults: FaultSpec,
                          base_key: jax.Array,
                          mesh: Mesh) -> Tuple[jax.Array, NetState]:
    """Run /start -> termination over a ('trials','nodes') device mesh.

    Same contract as sim.run_consensus; results are bit-identical to it.
    """
    meshlib.check_divisible(cfg.trials, cfg.n_nodes, mesh)
    state, faults = shard_inputs(state, faults, mesh)
    return _compiled(cfg, mesh)(state, faults, base_key)
