"""shard_map'd consensus runner — the multi-chip round loop (SURVEY.md N7).

The single-device run (sim.py) and this runner share the SAME round kernel
(models/benor.py): the kernel takes a ``ShardCtx`` naming the mesh axes and
performs its tallies via ``psum`` over ICI instead of a local reduction.
Because every random draw is keyed on *global* (trial, node, round) ids
(ops/rng.py), the sharded run is bit-identical to the single-device run for
any mesh shape — verified by tests/test_parallel.py.

Per round and node-shard the communication is:
  histogram path:  one psum of an int32 [T_loc, 3] histogram per phase
                   (+ one [T_loc] alive-count psum, one scalar termination
                   psum) — O(1) bytes per node, pure ICI latency.
  dense path:      one tiled all-gather of int8 [T_loc, N_loc] sent values
                   and bool alive per phase.

The whole run stays inside one jitted while_loop: zero host round-trips.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:                                  # jax >= 0.5 exports it at top level
    from jax import shard_map as _shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map  # 0.4.x

import inspect as _inspect

if "check_vma" in _inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    def shard_map(*args, check_vma=None, **kwargs):
        """0.4.x compat: the replication check is spelled check_rep there."""
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map(*args, **kwargs)
from jax.sharding import Mesh, PartitionSpec as P

from ..config import SimConfig
from ..models.benor import all_settled, benor_round
from ..ops.collectives import ShardCtx
from ..perfscope.instrument import instrumented_jit
from ..sim import start_state
from ..state import FaultSpec, NetState
from . import mesh as meshlib

#: ShardCtx used by every kernel invocation under the ('trials','nodes') mesh.
MESH_CTX = ShardCtx(trial_axis=meshlib.AXIS_TRIALS,
                    node_axis=meshlib.AXIS_NODES)


def _local_run(cfg: SimConfig, fresh: bool, state: NetState,
               faults: FaultSpec, base_key: jax.Array,
               from_round: jax.Array):
    """Per-shard body: /start (or checkpoint re-entry) -> termination loop.

    ``fresh`` (static) applies the /start transition; a resume re-enters
    the loop at ``from_round`` (a TRACED replicated scalar, so every resume
    round reuses one compiled executable — baking it into the trace would
    cost an 8-40 s remote compile per distinct checkpoint round) — the
    sharded counterpart of sim.resume_consensus (checkpoint/resume, SURVEY
    §5.4).  Randomness keys on (base_key, round, phase, global ids), never
    loop history, so a resumed run is bit-identical to an uninterrupted one
    on ANY mesh shape.

    The loop carries a replicated ``settled`` flag computed via psum so all
    shards take identical trip counts (a shard-local predicate would
    deadlock the collectives inside the body).

    Implemented as an unbounded _local_slice (until_round past the cap),
    so the round loop exists ONCE.  With cfg.record the flight recorder
    is created in-shard (its rows are psum-globalized, so every shard
    holds the identical replicated buffer) and returned as a third
    output; with cfg.witness the witness buffer follows it, same
    replication argument.
    """
    if fresh:
        state = start_state(cfg, state)
    out = _local_slice(cfg, state, faults, base_key, from_round,
                       jnp.int32(cfg.max_rounds + 1))
    return (out[0] - 1, *out[1:])


@functools.lru_cache(maxsize=None)
def _compiled(cfg: SimConfig, mesh: Mesh, fresh: bool = True):
    sspec = meshlib.STATE_SPEC
    # the flight recorder (cfg.record) and the witness buffer
    # (cfg.witness) are replicated extra outputs: their rows are
    # psum/pmax-globalized before every write, so each shard computes the
    # identical buffer
    out_specs = (P(), sspec) + (P(),) * (cfg.record + cfg.witness)
    fn = shard_map(
        functools.partial(_local_run, cfg, fresh),
        mesh=mesh,
        in_specs=(sspec, sspec, P(), P()),
        out_specs=out_specs,
        check_vma=False,  # while_loop results can't be proven replicated
    )
    return instrumented_jit(
        fn, label="sharded.run" if fresh else "sharded.resume")


def shard_inputs(state: NetState, faults: FaultSpec, mesh: Mesh):
    """Place state/fault leaves block-wise on the mesh (one transfer each)."""
    sh = meshlib.state_sharding(mesh)
    put = lambda a: jax.device_put(a, sh)
    state = NetState(x=put(state.x), decided=put(state.decided),
                     k=put(state.k), killed=put(state.killed))
    faults = FaultSpec(faulty=put(faults.faulty),
                       crash_round=put(faults.crash_round),
                       recover_round=(None if faults.recover_round is None
                                      else put(faults.recover_round)))
    return state, faults


def jitted_runner(cfg: SimConfig, mesh: Mesh, fresh: bool = True):
    """The sharded regime's jitted executable, as an object.

    ``run_consensus_sharded`` dispatches through this; perfscope's sharded
    capture (perfscope/regimes.py) lowers/compiles the SAME object AOT to
    read its cost/memory model, so what is profiled is what runs.  The
    callable takes ``(state, faults, base_key, from_round)`` with state/
    faults already placed by ``shard_inputs``.
    """
    return _compiled(cfg, mesh, fresh)


def run_consensus_sharded(cfg: SimConfig, state: NetState, faults: FaultSpec,
                          base_key: jax.Array, mesh: Mesh):
    """Run /start -> termination over a ('trials','nodes') device mesh.

    Same contract as sim.run_consensus (including the extra flight
    recorder output under cfg.record — the sharded recorder is
    bit-identical to the single-device one, since every row is
    psum-globalized before its write); results are bit-identical to it.
    """
    meshlib.check_divisible(cfg.trials, cfg.n_nodes, mesh)
    state, faults = shard_inputs(state, faults, mesh)
    return jitted_runner(cfg, mesh)(state, faults, base_key, jnp.int32(1))


def _local_slice_packed(cfg: SimConfig, state: NetState, faults: FaultSpec,
                        base_key: jax.Array, from_round: jax.Array,
                        until_round: jax.Array, recorder=None,
                        witness=None):
    """The fused-round fast path of _local_slice: the BIT-PLANE packed
    state stack (state.PACK_LAYOUT) is the while-loop carry (the sharded
    counterpart of pallas_round.run_packed).  Under a mesh the round
    always runs the two-kernel plane pipeline — the vote-phase histogram
    needs an ICI psum between phases, so the single-pass kernel is a
    single-device dispatch (pallas_round.packed_round documents the
    boundary; results are bit-identical across it).

    Per shard, pack/unpack and every per-lane XLA op run once per SLICE
    instead of once per round — between rounds only the kernels' psum'd
    partials move (int16/int8-narrowed per the quorum bound, widened
    before the psum).  One shared loop definition (run_packed_slice) serves
    this runner and the single-device run_packed; bit-identity with the
    unfused path is pinned by tests/test_pallas_round.py's sharded
    one-shot/slice/resume cases and the dryrun legs.
    """
    from ..ops.pallas_round import run_packed_slice

    return run_packed_slice(cfg, state, faults, base_key, from_round,
                            until_round, MESH_CTX, recorder=recorder,
                            witness=witness)


def _local_slice(cfg: SimConfig, state: NetState, faults: FaultSpec,
                 base_key: jax.Array, from_round: jax.Array,
                 until_round: jax.Array, recorder=None, witness=None):
    """Per-shard slice body: at most ``until_round - from_round`` rounds.

    The sharded counterpart of sim.run_consensus_slice (same contract:
    returns (next_round, state); the caller applies the /start transition
    once).  Both round bounds are TRACED replicated scalars, so every
    slice of every chunk size reuses one compiled executable per
    (config, mesh) — the same trick _local_run plays for resume.  The
    replicated ``settled`` psum keeps trip counts identical across shards.

    In the fused-round regime (tally.pallas_round_active) the loop
    carries the packed state word instead of NetState — see
    _local_slice_packed — matching sim.run_consensus's run_packed
    dispatch, with bit-identical results.

    With cfg.record the flight recorder threads through (created fresh
    when ``recorder`` is None) and is returned as a third output —
    replicated, since every row write is psum-globalized first.  The
    witness buffer (cfg.witness) threads identically, appended after the
    recorder when both ride.
    """
    from ..ops.tally import pallas_round_active
    from ..sim import warn_debug_demotes_pallas
    from ..state import new_recorder, new_witness

    ctx = MESH_CTX
    pallas = pallas_round_active(cfg)
    if pallas and cfg.debug:
        warn_debug_demotes_pallas(cfg)
    if pallas and not cfg.debug:
        return _local_slice_packed(cfg, state, faults, base_key,
                                   from_round, until_round,
                                   recorder=recorder, witness=witness)
    if cfg.record and recorder is None:
        recorder = new_recorder(cfg, state, ctx)
    if cfg.witness and witness is None:
        witness = new_witness(cfg, state, ctx)

    def body(carry):
        r, st = carry[0], carry[1]
        i = 3
        rec = wit = None
        if cfg.record:
            rec = carry[i]
            i += 1
        if cfg.witness:
            wit = carry[i]
        out = benor_round(cfg, st, faults, base_key, r, ctx,
                          recorder=rec, witness=wit)
        if cfg.record or cfg.witness:
            st, *extras = out
        else:
            st, extras = out, []
        if cfg.debug:
            from ..utils.tracing import emit_round_event
            emit_round_event(st, ctx)
        return (r + 1, st, all_settled(st, ctx), *extras)

    def cond(carry):
        r, settled = carry[0], carry[2]
        return (r <= cfg.max_rounds) & ~settled & (r < until_round)

    carry = (from_round.astype(jnp.int32), state, all_settled(state, ctx))
    if cfg.record:
        carry = carry + (recorder,)
    if cfg.witness:
        carry = carry + (witness,)
    out = jax.lax.while_loop(cond, body, carry)
    return (out[0], out[1], *out[3:])


@functools.lru_cache(maxsize=None)
def _compiled_slice(cfg: SimConfig, mesh: Mesh):
    sspec = meshlib.STATE_SPEC
    # under cfg.record / cfg.witness each armed buffer is a replicated
    # extra INPUT (so poll slices keep filling one buffer) and extra
    # output, recorder first
    rec = (P(),) * (cfg.record + cfg.witness)
    fn = shard_map(
        functools.partial(_local_slice, cfg),
        mesh=mesh,
        in_specs=(sspec, sspec, P(), P(), P()) + rec,
        out_specs=(P(), sspec) + rec,
        check_vma=False,
    )
    return instrumented_jit(fn, label="sharded.slice")


def run_consensus_slice_sharded(cfg: SimConfig, state: NetState,
                                faults: FaultSpec, base_key: jax.Array,
                                mesh: Mesh, from_round, until_round,
                                recorder=None, witness=None,
                                heartbeat: bool = True):
    """Mid-run observability (cfg.poll_rounds) under a device mesh.

    Same semantics as sim.run_consensus_slice (including the recorder /
    witness threading under cfg.record / cfg.witness: pass the previous
    slice's buffers, None starts fresh ones); because every random draw
    is keyed on global (trial, node, round) ids, a sliced sharded run is
    bit-identical to the one-shot sharded run AND to the single-device
    run for any mesh shape (tests/test_parallel.py pins both).

    With cfg.heartbeat_rounds the wrapper also publishes a HOST-side
    live-progress heartbeat (meshscope/heartbeat.py) at each slice
    boundary whose round cursor crossed the cadence — registry gauges
    only (rounds/sec, decided fraction from the recorder when armed);
    the compiled slice executable is untouched, so heartbeat on/off
    stays bit-identical in results and compile counts.  A driver that
    runs its OWN HeartbeatPublisher around the slice loop (e.g.
    TpuNetwork.start, which also owns the file plane) passes
    ``heartbeat=False`` so one beat is not published twice into the
    shared ``heartbeat.*`` gauges.
    """
    meshlib.check_divisible(cfg.trials, cfg.n_nodes, mesh)
    state, faults = shard_inputs(state, faults, mesh)
    args = (state, faults, base_key, jnp.int32(from_round),
            jnp.int32(until_round))
    if cfg.record:
        if recorder is None:
            from ..state import new_recorder
            recorder = new_recorder(cfg, state)
        args = args + (recorder,)
    if cfg.witness:
        if witness is None:
            from ..state import new_witness
            witness = new_witness(cfg, state)
        args = args + (witness,)
    out = _compiled_slice(cfg, mesh)(*args)
    if heartbeat and cfg.heartbeat_rounds:
        from ..meshscope.heartbeat import publish_slice_heartbeat
        publish_slice_heartbeat(cfg, out[0],
                                recorder=out[2] if cfg.record else None,
                                label="sharded.slice",
                                from_round=from_round)
    return out


def resume_consensus_sharded(cfg: SimConfig, state: NetState,
                             faults: FaultSpec, base_key: jax.Array,
                             mesh: Mesh, from_round: int):
    """Re-enter the round loop from a checkpointed round index on a mesh.

    Sharded counterpart of sim.resume_consensus: a checkpoint written by a
    single-device (or any-mesh) run resumes bit-identically on any mesh
    shape.  ``from_round`` is the 1-based next round (checkpoint's
    ``next_round``); it is traced, so resumes at different rounds share one
    compiled executable.  Under cfg.record a FRESH (re-entry) recorder is
    appended as a third output — rows before ``from_round`` stay
    unwritten (utils/metrics.py renders gapped buffers by round index);
    cfg.witness appends a fresh witness buffer after it, same gap
    semantics."""
    meshlib.check_divisible(cfg.trials, cfg.n_nodes, mesh)
    state, faults = shard_inputs(state, faults, mesh)
    return _compiled(cfg, mesh, fresh=False)(state, faults, base_key,
                                             jnp.int32(from_round))
