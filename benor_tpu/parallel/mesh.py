"""Device-mesh construction for multi-chip runs (SURVEY.md N7).

The mesh has two logical axes:

  'trials' — Monte-Carlo batch data-parallelism.  Trials never communicate;
             at pod scale this axis maps onto DCN (cross-host) because its
             only collective is the scalar termination psum.
  'nodes'  — the simulated-node axis.  Its per-round collective is the
             3-class histogram psum (and, on the dense path, one int8
             all-gather), so this axis should ride ICI.

On a v4-8 the natural layout is ``make_mesh(trial_shards=1, node_shards=8)``
for giant-N runs, or ``(8, 1)`` for many-trials sweeps at moderate N.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_TRIALS = "trials"
AXIS_NODES = "nodes"

#: PartitionSpec of every [T, N] state/fault leaf.
STATE_SPEC = P(AXIS_TRIALS, AXIS_NODES)


def make_mesh(trial_shards: int = 1, node_shards: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ('trials', 'nodes') mesh over ``trial_shards * node_shards``
    devices (default: node_shards = all available / trial_shards)."""
    if devices is None:
        devices = jax.devices()
    if trial_shards < 1:
        raise ValueError(f"trial_shards must be >= 1, got {trial_shards}")
    if node_shards is None:
        node_shards = len(devices) // trial_shards
    if node_shards < 1:
        raise ValueError(
            f"node_shards must be >= 1 (trial_shards={trial_shards} over "
            f"{len(devices)} devices leaves none for the node axis)")
    n = trial_shards * node_shards
    if n > len(devices):
        raise ValueError(
            f"mesh ({trial_shards}x{node_shards}) needs {n} devices, "
            f"have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(trial_shards, node_shards)
    return Mesh(grid, (AXIS_TRIALS, AXIS_NODES))


def state_sharding(mesh: Mesh) -> NamedSharding:
    """NamedSharding that places [T, N] leaves block-wise on the mesh."""
    return NamedSharding(mesh, STATE_SPEC)


def check_divisible(cfg_trials: int, cfg_nodes: int, mesh: Mesh) -> None:
    ts = mesh.shape[AXIS_TRIALS]
    ns = mesh.shape[AXIS_NODES]
    if cfg_trials % ts or cfg_nodes % ns:
        raise ValueError(
            f"mesh shape ({ts}, {ns}) must evenly divide trials="
            f"{cfg_trials} / nodes={cfg_nodes}; pad T or N to a multiple")
