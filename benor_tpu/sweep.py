"""Science harness: parameter sweeps over the consensus simulator.

The reference repo's only "experiment" is a hardcoded 10-node demo
(src/start.ts:7-20).  This module is the research surface the BASELINE.json
north star asks for: expected-rounds-vs-f curves, private-vs-common-coin
comparisons, and Monte-Carlo throughput measurement at up to millions of
simulated nodes.

Everything is summarized ON DEVICE and fetched as scalars / max_rounds-sized
histograms — under the axon tunnel a bulk [T, N] device->host transfer costs
seconds, and ``jax.block_until_ready`` does not actually block, so every
timed section ends with a scalar fetch as its completion barrier.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .config import SimConfig, VAL0, VAL1, VALQ
from .models.benor import benor_round
from .sim import run_consensus, start_state
from .state import FaultSpec, NetState, init_state


@dataclasses.dataclass
class SweepPoint:
    """Summary of one (config, fault-count) Monte-Carlo batch."""

    n_nodes: int
    n_faulty: int
    trials: int
    coin_mode: str
    scheduler: str
    rounds_executed: int        # while-loop trip count (max over lanes)
    decided_frac: float         # healthy lanes that decided
    mean_k: float               # mean observed k among decided healthy lanes
    k_hist: np.ndarray          # int64[max_rounds+2] histogram of decided k
    ones_frac: float            # decided-1 fraction among decided healthy
    seconds: float              # wall-clock for the batch (post-compile)
    trials_per_sec: float
    #: Fraction of trials where decided healthy lanes hold BOTH values — an
    #: agreement-safety violation (impossible under the reference's crash
    #: model, reachable under quorum sampling + split adversaries or
    #: byzantine faults; see PARITY.md "Findings beyond the reference").
    disagree_frac: float = 0.0

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d["k_hist"] = self.k_hist.tolist()
        return d


@functools.partial(jax.jit, static_argnums=2)
def summarize_final(final: NetState, faulty: jax.Array, max_rounds: int):
    """On-device reduction -> 5 scalars + a small k histogram."""
    healthy = ~faulty
    hd = final.decided & healthy
    n_hd = jnp.maximum(jnp.sum(hd), 1)
    decided_frac = jnp.sum(hd) / jnp.maximum(jnp.sum(healthy), 1)
    mean_k = jnp.sum(final.k * hd) / n_hd
    ones_frac = jnp.sum(hd & (final.x == VAL1)) / n_hd
    k_hist = jnp.bincount(jnp.where(hd, final.k, 0).ravel(),
                          weights=hd.ravel().astype(jnp.int32),
                          length=max_rounds + 2)
    # per-trial agreement check: decided healthy lanes holding both values
    # in the same trial is a safety violation (PARITY.md findings)
    got0 = jnp.any(hd & (final.x == VAL0), axis=-1)
    got1 = jnp.any(hd & (final.x == VAL1), axis=-1)
    disagree_frac = jnp.mean((got0 & got1).astype(jnp.float32))
    return decided_frac, mean_k, ones_frac, k_hist, disagree_frac


@functools.partial(jax.jit, static_argnums=(0, 4))
def record_trajectory(cfg: SimConfig, state: NetState, faults: FaultSpec,
                      base_key: jax.Array, n_rounds: int):
    """Round-by-round aggregate time series — convergence DYNAMICS, not just
    the endpoint.  The reference offers only /getState polling snapshots at
    the test harness's 200 ms cadence (tests/utils.ts:14-20); here the full
    per-round trajectory is captured inside one compiled ``lax.scan`` with
    on-device reductions (five scalars per round — no [T, N] transfer).

    Runs exactly ``n_rounds`` rounds from /start (no early exit — scan has
    a static trip count).  Because decided lanes freeze and settled rounds
    are state no-ops, the final state equals ``run_consensus``'s whenever
    n_rounds >= its round count (tested in tests/test_sweep.py).

    Returns (final_state, traj) with traj a dict of float32 [n_rounds]
    series over healthy lanes: ``decided`` (decided fraction), ``ones`` /
    ``zeros`` / ``qs`` (value shares among live healthy lanes — the "?"
    share is the visible signature of tie-forcing adversaries), and
    ``disagree`` (fraction of trials whose decided healthy lanes hold both
    values — the safety-violation onset, round-resolved).
    """
    healthy = ~faults.faulty
    n_healthy = jnp.maximum(jnp.sum(healthy), 1)

    def aggregates(st: NetState):
        live = healthy & ~st.killed
        n_live = jnp.maximum(jnp.sum(live), 1)
        hd = st.decided & healthy
        got0 = jnp.any(hd & (st.x == VAL0), axis=-1)
        got1 = jnp.any(hd & (st.x == VAL1), axis=-1)
        return {
            "decided": jnp.sum(hd) / n_healthy,
            "zeros": jnp.sum(live & (st.x == VAL0)) / n_live,
            "ones": jnp.sum(live & (st.x == VAL1)) / n_live,
            "qs": jnp.sum(live & (st.x == VALQ)) / n_live,
            "disagree": jnp.mean((got0 & got1).astype(jnp.float32)),
        }

    def step(st, r):
        st = benor_round(cfg, st, faults, base_key, r)
        return st, aggregates(st)

    final, traj = jax.lax.scan(step, start_state(cfg, state),
                               jnp.arange(1, n_rounds + 1, dtype=jnp.int32))
    return final, traj


def random_inputs(seed: int, trials: int, n: int) -> np.ndarray:
    """Per-trial random initial bits — the standard MC input distribution."""
    return np.random.default_rng(seed).integers(
        0, 2, size=(trials, n), dtype=np.int8)


def balanced_inputs(trials: int, n: int) -> np.ndarray:
    """Interleaved perfectly-balanced bits (node i starts with i mod 2) —
    the zero-margin worst case every multi-round science regime uses
    (margin 0 puts phase outcomes entirely inside sampling noise)."""
    return np.tile((np.arange(n) % 2).astype(np.int8), (trials, 1))


def run_point(cfg: SimConfig, initial_values=None, faulty_list=None,
              faults: Optional[FaultSpec] = None) -> SweepPoint:
    """Run one MC batch to termination; returns its on-device summary.

    Defaults: per-trial random initial bits; the first F nodes faulty
    (which F nodes crash is statistically irrelevant under the uniform
    scheduler — lanes are exchangeable).  Pass ``faults`` directly to
    decouple the protocol parameter F from the number of actual crashes
    (the reference's launch validation pins them equal, launchNodes.ts:12-13,
    but an asynchronous adversary is strongest with NO crashes: every node
    alive and the full N-F quorum slack available for message reordering).
    """
    if initial_values is None:
        initial_values = random_inputs(cfg.seed, cfg.trials, cfg.n_nodes)
    if faults is None:
        if faulty_list is None:
            faulty_list = np.zeros(cfg.n_nodes, bool)
            faulty_list[:cfg.n_faulty] = True
        faults = FaultSpec.from_faulty_list(cfg, faulty_list)
    state = init_state(cfg, initial_values, faults)
    base_key = jax.random.key(cfg.seed)

    # compile (cached across calls with the same static cfg)
    r, final = run_consensus(cfg, state, faults, base_key)
    int(r)  # completion barrier
    t0 = time.perf_counter()
    r, final = run_consensus(cfg, state, faults, base_key)
    rounds = int(r)  # completion barrier inside the timed window
    seconds = time.perf_counter() - t0

    dec, mk, ones, khist, disagree = summarize_final(
        final, faults.faulty, cfg.max_rounds)
    return SweepPoint(
        n_nodes=cfg.n_nodes, n_faulty=cfg.n_faulty, trials=cfg.trials,
        coin_mode=cfg.coin_mode, scheduler=cfg.scheduler,
        rounds_executed=rounds, decided_frac=float(dec), mean_k=float(mk),
        k_hist=np.asarray(khist).astype(np.int64), ones_frac=float(ones),
        seconds=seconds,
        trials_per_sec=cfg.trials / seconds if seconds > 0 else float("inf"),
        disagree_frac=float(disagree))


def rounds_vs_f(base_cfg: SimConfig, f_values: Sequence[int],
                verbose: bool = True) -> List[SweepPoint]:
    """The north-star curve: expected rounds-to-decide as F grows.

    Each point reuses ``base_cfg`` with ``n_faulty`` replaced; initial
    values are per-trial random bits seeded by ``base_cfg.seed``.
    """
    points = []
    for f in f_values:
        pt = run_point(base_cfg.replace(n_faulty=int(f)))
        points.append(pt)
        if verbose:
            print(f"  f={f}: mean_k={pt.mean_k:.2f} "
                  f"decided={pt.decided_frac:.3f} "
                  f"{pt.trials_per_sec:.1f} trials/s", flush=True)
    return points


def coin_comparison(base_cfg: SimConfig,
                    verbose: bool = True) -> Dict[str, List[SweepPoint]]:
    """Private vs shared common coin under the worst-case adversarial
    scheduler — the classic Ben-Or-vs-Rabin contrast: the count-controlling
    adversary livelocks private coins (decided_frac ~ 0 at the round cap)
    while the common coin terminates in O(1) expected rounds.

    The adversary is given maximum power: all N nodes stay alive (zero
    crashes), so it can discard any F messages per receiver; inputs are
    perfectly balanced.  It forces a tied (m/2, m/2) delivered multiset —
    which requires an even quorum m = N - F; for odd m a one-message
    imbalance leaks through and the run converges regardless of coin.

    Escape physics (and why termination is still guaranteed — Ben-Or's
    original argument): a tie is only constructible while the private coin
    flips stay balanced enough, min(c0, c1) >= m/2, i.e. within F/2 of the
    N/2 mean.  With per-round std sqrt(N)/2, the per-round escape
    probability is ~2*Phi(-F/sqrt(N)), so the private-coin livelock is only
    long-lived when F >> sqrt(N) (e.g. N=100, F=40 holds for ~1e4 rounds;
    N=20, F=6 escapes ~11% of rounds).  The common coin escapes in O(1)
    rounds at ANY F: the first round after all lanes flip the same value,
    the adversary cannot hide a unanimous class.
    """
    if base_cfg.quorum % 2:
        raise ValueError(
            f"coin_comparison needs an even quorum N-F for a perfect-tie "
            f"adversary (got N-F={base_cfg.quorum}); adjust N or F")
    T, N = base_cfg.trials, base_cfg.n_nodes
    no_crash = FaultSpec.none(T, N)
    balanced = balanced_inputs(T, N)
    out: Dict[str, List[SweepPoint]] = {}
    for coin in ("private", "common"):
        cfg = base_cfg.replace(coin_mode=coin, scheduler="adversarial",
                               delivery="quorum")
        if verbose:
            print(f" coin_mode={coin}:", flush=True)
        pt = run_point(cfg, initial_values=balanced, faults=no_crash)
        if verbose:
            print(f"  decided={pt.decided_frac:.3f} mean_k={pt.mean_k:.2f} "
                  f"{pt.trials_per_sec:.1f} trials/s", flush=True)
        out[coin] = [pt]
    return out


def baseline_configs() -> Dict[str, SimConfig]:
    """The five BASELINE.json benchmark configs as ready-to-run presets."""
    return {
        # "Fault-free Ben-Or, N=5 nodes, random initial x"
        "n5_faultfree": SimConfig(n_nodes=5, n_faulty=0, trials=1024,
                                  delivery="quorum", scheduler="uniform"),
        # "Crash-fault Ben-Or, N=10k nodes, f=N/5 crash mask, 1k MC trials"
        "n10k_crash": SimConfig(n_nodes=10_000, n_faulty=2_000, trials=1000,
                                delivery="quorum", scheduler="uniform",
                                path="histogram"),
        # "Byzantine Ben-Or, N=100k nodes, f<N/5 adversarial bit-flip mask"
        "n100k_byzantine": SimConfig(n_nodes=100_000, n_faulty=19_999,
                                     trials=64, fault_model="byzantine",
                                     delivery="quorum", scheduler="uniform",
                                     path="histogram"),
        # "Private-coin vs shared-common-coin, N=1M, rounds-to-decide vs f"
        "n1m_coin_sweep": SimConfig(n_nodes=1_000_000, n_faulty=200_000,
                                    trials=32, delivery="quorum",
                                    scheduler="uniform", path="histogram"),
        # "Asynchronous adversarial scheduler, N=1M nodes"
        "n1m_adversarial": SimConfig(n_nodes=1_000_000, n_faulty=200_000,
                                     trials=32, delivery="quorum",
                                     scheduler="adversarial", max_rounds=24,
                                     path="histogram"),
    }


def save_points(path: str, points: Sequence[SweepPoint]) -> None:
    with open(path, "w") as fh:
        json.dump([p.to_dict() for p in points], fh, indent=1)
