"""Science harness: parameter sweeps over the consensus simulator.

The reference repo's only "experiment" is a hardcoded 10-node demo
(src/start.ts:7-20).  This module is the research surface the BASELINE.json
north star asks for: expected-rounds-vs-f curves, private-vs-common-coin
comparisons, and Monte-Carlo throughput measurement at up to millions of
simulated nodes.

Everything is summarized ON DEVICE and fetched as scalars / max_rounds-sized
histograms — under the axon tunnel a bulk [T, N] device->host transfer costs
seconds, and ``jax.block_until_ready`` does not actually block, so every
timed section ends with a scalar fetch as its completion barrier.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .config import SimConfig, VAL0, VAL1, VALQ
from .models.benor import benor_round
from .ops import sampling, tally
from .sim import run_consensus, run_consensus_traced, start_state
from .state import DynParams, FaultSpec, NetState, init_state


@dataclasses.dataclass
class SweepPoint:
    """Summary of one (config, fault-count) Monte-Carlo batch."""

    n_nodes: int
    n_faulty: int
    trials: int
    coin_mode: str
    scheduler: str
    rounds_executed: int        # while-loop trip count (max over lanes)
    decided_frac: float         # healthy lanes that decided
    mean_k: float               # mean observed k among decided healthy lanes
    k_hist: np.ndarray          # int64[max_rounds+2] histogram of decided k
    ones_frac: float            # decided-1 fraction among decided healthy
    seconds: float              # wall-clock for the batch (post-compile)
    trials_per_sec: float
    #: Fraction of trials where decided healthy lanes hold BOTH values — an
    #: agreement-safety violation (impossible under the reference's crash
    #: model, reachable under quorum sampling + split adversaries or
    #: byzantine faults; see PARITY.md "Findings beyond the reference").
    disagree_frac: float = 0.0
    #: Flight-recorder round history (cfg.record): int32
    #: [max_rounds + 1, state.REC_WIDTH], row r = network at end of round
    #: r (state.REC_COLUMNS names the columns); None when record is off.
    round_history: Optional[np.ndarray] = None
    #: Witness trace (cfg.witness): int32
    #: [max_rounds + 1, W, k, state.WIT_WIDTH] per-node forensic rows for
    #: the watched (trial, node) pairs (state.WIT_COLUMNS names the
    #: columns; benor_tpu/audit.py machine-checks them); None when the
    #: witness is off.
    witness: Optional[np.ndarray] = None

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d["k_hist"] = self.k_hist.tolist()
        if self.round_history is not None:
            d["round_history"] = self.round_history.tolist()
        if self.witness is not None:
            d["witness"] = self.witness.tolist()
        return d


@functools.partial(jax.jit, static_argnums=2)
def summarize_final(final: NetState, faulty: jax.Array, max_rounds: int):
    """On-device reduction -> 5 scalars + a small k histogram."""
    healthy = ~faulty
    hd = final.decided & healthy
    n_hd = jnp.maximum(jnp.sum(hd), 1)
    decided_frac = jnp.sum(hd) / jnp.maximum(jnp.sum(healthy), 1)
    mean_k = jnp.sum(final.k * hd) / n_hd
    ones_frac = jnp.sum(hd & (final.x == VAL1)) / n_hd
    k_hist = jnp.bincount(jnp.where(hd, final.k, 0).ravel(),
                          weights=hd.ravel().astype(jnp.int32),
                          length=max_rounds + 2)
    # per-trial agreement check: decided healthy lanes holding both values
    # in the same trial is a safety violation (PARITY.md findings)
    got0 = jnp.any(hd & (final.x == VAL0), axis=-1)
    got1 = jnp.any(hd & (final.x == VAL1), axis=-1)
    disagree_frac = jnp.mean((got0 & got1).astype(jnp.float32))
    return decided_frac, mean_k, ones_frac, k_hist, disagree_frac


# benorlint: allow-donate-argnums — the trajectory tests replay the same
# state through run_consensus to pin endpoint equality; donation would
# poison that second use
@functools.partial(jax.jit, static_argnums=(0, 4))
def record_trajectory(cfg: SimConfig, state: NetState, faults: FaultSpec,
                      base_key: jax.Array, n_rounds: int):
    """Round-by-round aggregate time series — convergence DYNAMICS, not just
    the endpoint.  The reference offers only /getState polling snapshots at
    the test harness's 200 ms cadence (tests/utils.ts:14-20); here the full
    per-round trajectory is captured inside one compiled ``lax.scan`` with
    on-device reductions (five scalars per round — no [T, N] transfer).

    Runs exactly ``n_rounds`` rounds from /start (no early exit — scan has
    a static trip count).  Because decided lanes freeze and settled rounds
    are state no-ops, the final state equals ``run_consensus``'s whenever
    n_rounds >= its round count (tested in tests/test_sweep.py).

    Returns (final_state, traj) with traj a dict of float32 [n_rounds]
    series over healthy lanes: ``decided`` (decided fraction), ``ones`` /
    ``zeros`` / ``qs`` (value shares among live healthy lanes — the "?"
    share is the visible signature of tie-forcing adversaries), and
    ``disagree`` (fraction of trials whose decided healthy lanes hold both
    values — the safety-violation onset, round-resolved).
    """
    healthy = ~faults.faulty
    n_healthy = jnp.maximum(jnp.sum(healthy), 1)

    def aggregates(st: NetState):
        live = healthy & ~st.killed
        n_live = jnp.maximum(jnp.sum(live), 1)
        hd = st.decided & healthy
        got0 = jnp.any(hd & (st.x == VAL0), axis=-1)
        got1 = jnp.any(hd & (st.x == VAL1), axis=-1)
        return {
            "decided": jnp.sum(hd) / n_healthy,
            "zeros": jnp.sum(live & (st.x == VAL0)) / n_live,
            "ones": jnp.sum(live & (st.x == VAL1)) / n_live,
            "qs": jnp.sum(live & (st.x == VALQ)) / n_live,
            "disagree": jnp.mean((got0 & got1).astype(jnp.float32)),
        }

    def step(st, r):
        st = benor_round(cfg, st, faults, base_key, r)
        return st, aggregates(st)

    final, traj = jax.lax.scan(step, start_state(cfg, state),
                               jnp.arange(1, n_rounds + 1, dtype=jnp.int32))
    return final, traj


def default_crash_faults(cfg: SimConfig) -> FaultSpec:
    """run_point's default fault policy as a public, reusable function:
    the first F nodes crash-faulty (which F is statistically irrelevant
    under the uniform scheduler — lanes are exchangeable).  Under
    ``fault_model='crash_recover'`` the down-intervals are realized from
    the config's ``recovery`` schedule spec
    (faults.recovery.crash_recover_faults), so the schedule — like the
    mask — derives from the config alone.  The single policy the
    per-point oracle, the batched engine and the serve plane's job API
    (serve/jobs.py) all share, so "same SimConfig" means the same fault
    mask on every entry path."""
    if cfg.fault_model == "crash_recover":
        from .faults.recovery import crash_recover_faults
        if cfg.recovery is None:
            raise ValueError(
                "fault_model='crash_recover' under the default fault "
                "policy needs SimConfig.recovery (the schedule spec); "
                "pass an explicit FaultSpec to decouple them")
        return crash_recover_faults(cfg)
    fl = np.zeros(cfg.n_nodes, bool)
    fl[:cfg.n_faulty] = True
    return FaultSpec.from_faulty_list(cfg, fl)


def point_from_raw(cfg_f: SimConfig, vals, seconds: float) -> SweepPoint:
    """One SweepPoint from a bucket executable's raw per-point outputs —
    the (rounds, decided, mean_k, ones, k_hist, disagree[, recorder]
    [, witness]) tuple `_summarize_inline` lays out.  Factored out of the
    batched engine's assembly loop so the serve plane's batch slots
    (serve/jobs.py) deserialize result slices through the IDENTICAL
    code path (bit-equality depends on sharing it, not re-implementing
    it)."""
    r, dec, mk, ones, khist, dis, *rest = vals
    history = wit = None
    if cfg_f.record:
        history = np.asarray(rest.pop(0), np.int32)
    if cfg_f.witness:
        wit = np.asarray(rest.pop(0), np.int32)
    return SweepPoint(
        n_nodes=cfg_f.n_nodes, n_faulty=cfg_f.n_faulty,
        trials=cfg_f.trials, coin_mode=cfg_f.coin_mode,
        scheduler=cfg_f.scheduler, rounds_executed=int(r),
        decided_frac=float(dec), mean_k=float(mk),
        k_hist=np.asarray(khist).astype(np.int64),
        ones_frac=float(ones), seconds=seconds,
        trials_per_sec=(cfg_f.trials / seconds if seconds > 0
                        else float("inf")),
        disagree_frac=float(dis), round_history=history, witness=wit)


def random_inputs(seed: int, trials: int, n: int) -> np.ndarray:
    """Per-trial random initial bits — the standard MC input distribution."""
    # benorlint: allow-host-rng — seeded host-side INPUT generation, built
    # once per sweep before any trace; protocol draws all use ops/rng.py
    return np.random.default_rng(seed).integers(
        0, 2, size=(trials, n), dtype=np.int8)


def balanced_inputs(trials: int, n: int) -> np.ndarray:
    """Interleaved perfectly-balanced bits (node i starts with i mod 2) —
    the zero-margin worst case every multi-round science regime uses
    (margin 0 puts phase outcomes entirely inside sampling noise)."""
    return np.tile((np.arange(n) % 2).astype(np.int8), (trials, 1))


def run_point(cfg: SimConfig, initial_values=None, faulty_list=None,
              faults: Optional[FaultSpec] = None) -> SweepPoint:
    """Run one MC batch to termination; returns its on-device summary.

    Defaults: per-trial random initial bits; the first F nodes faulty
    (which F nodes crash is statistically irrelevant under the uniform
    scheduler — lanes are exchangeable).  Pass ``faults`` directly to
    decouple the protocol parameter F from the number of actual crashes
    (the reference's launch validation pins them equal, launchNodes.ts:12-13,
    but an asynchronous adversary is strongest with NO crashes: every node
    alive and the full N-F quorum slack available for message reordering).
    """
    if initial_values is None:
        initial_values = random_inputs(cfg.seed, cfg.trials, cfg.n_nodes)
    if faults is None:
        if faulty_list is None:
            faults = default_crash_faults(cfg)
        else:
            faults = FaultSpec.from_faulty_list(cfg, faulty_list)
    state = init_state(cfg, initial_values, faults)
    base_key = jax.random.key(cfg.seed)

    # compile (cached across calls with the same static cfg); under
    # cfg.record / cfg.witness the run returns the flight recorder /
    # witness buffer as extra outputs (recorder first)
    out = run_consensus(cfg, state, faults, base_key)
    int(out[0])  # completion barrier
    t0 = time.perf_counter()
    out = run_consensus(cfg, state, faults, base_key)
    rounds = int(out[0])  # completion barrier inside the timed window
    seconds = time.perf_counter() - t0
    final = out[1]
    idx = 2
    history = None
    if cfg.record:
        history = np.asarray(out[idx])
        idx += 1
    wit = np.asarray(out[idx], np.int32) if cfg.witness else None

    dec, mk, ones, khist, disagree = summarize_final(
        final, faults.faulty, cfg.max_rounds)
    return SweepPoint(
        n_nodes=cfg.n_nodes, n_faulty=cfg.n_faulty, trials=cfg.trials,
        coin_mode=cfg.coin_mode, scheduler=cfg.scheduler,
        rounds_executed=rounds, decided_frac=float(dec), mean_k=float(mk),
        k_hist=np.asarray(khist).astype(np.int64), ones_frac=float(ones),
        seconds=seconds,
        trials_per_sec=cfg.trials / seconds if seconds > 0 else float("inf"),
        disagree_frac=float(disagree), round_history=history, witness=wit)


def rounds_vs_f(base_cfg: SimConfig, f_values: Sequence[int],
                verbose: bool = True) -> List[SweepPoint]:
    """The north-star curve: expected rounds-to-decide as F grows.

    Each point reuses ``base_cfg`` with ``n_faulty`` replaced; initial
    values are per-trial random bits seeded by ``base_cfg.seed``.
    """
    points = []
    for f in f_values:
        pt = run_point(base_cfg.replace(n_faulty=int(f)))
        points.append(pt)
        if verbose:
            print(f"  f={f}: mean_k={pt.mean_k:.2f} "
                  f"decided={pt.decided_frac:.3f} "
                  f"{pt.trials_per_sec:.1f} trials/s", flush=True)
    return points


# --------------------------------------------------------------------------
# Batched dynamic-F sweep engine: one compiled executable per static-shape
# BUCKET instead of one per curve point.
#
# SimConfig is a static jit argument, so the classic per-point path
# (run_point / rounds_vs_f above) recompiles the whole round loop for every
# n_faulty value — the round-5 bench spent 43 s compiling vs 2.6 s
# simulating (BENCH_r05.json), and each remote-accelerator compile costs
# 8-40 s (utils/cache.py).  Here the f-axis is TRACED: n_faulty/quorum ride
# a DynParams pytree through the round kernel and samplers
# (sim.run_consensus_traced), the whole curve is vmapped over a [B] batch
# of per-point (state, faults, dyn) triples inside ONE buffer-donated jit,
# and per-f summaries reduce on device inside the same executable.
#
# Points whose compiled code genuinely specializes on the quorum — exact
# shared-CDF tables ([T, m+1] shapes at quorum <= sampling.EXACT_TABLE_MAX),
# dense top-k delivery masks, pallas kernels (m baked into closures) — are
# grouped into their own static buckets and run the classic path, pallas
# fast path preserved.  The per-point path stays as the parity oracle:
# batched summaries are BIT-IDENTICAL to it (tests/test_batched_sweep.py).
# --------------------------------------------------------------------------


def quorum_specialized(cfg: SimConfig) -> bool:
    """True iff this config's compiled code specializes shapes or kernels
    on n_faulty — such points cannot share a dynamic-F executable and get
    a static bucket each.  The single source of truth for the batched
    engine's bucketing (state.DynParams documents the constraint)."""
    if tally.pallas_stream_active(cfg) or tally.pallas_round_active(cfg):
        # kernels bake m/F into their closures; the PR-8 plane-packed
        # round additionally sizes its k-plane stack and partial dtype
        # (pallas_round.partial_dtype's quorum bound) per static config
        return True
    if cfg.drop_prob or cfg.partition is not None:
        # faultlab delivery planes (benor_tpu/faults): the omission
        # thinning (sampling.binomial_keep) and the partition group
        # histograms are shape-generic — no m-shaped tables, no top-k
        # masks — so these points always share a dyn bucket (drop_prob
        # itself IS a DynParams axis; partition specs stay in the
        # bucket key below).  delivery='all' keeps them clear of every
        # rule after this one.
        return False
    if (cfg.delivery == "quorum" and cfg.resolved_path == "dense"
            and cfg.scheduler not in ("adversarial", "targeted")):
        return True                 # top-k delivery mask: static m shape
    if (cfg.delivery == "quorum" and cfg.resolved_path == "histogram"
            and cfg.scheduler in ("uniform", "biased")
            and cfg.quorum <= sampling.EXACT_TABLE_MAX):
        return True                 # exact shared-CDF table: [T, m+1]
    if (cfg.fault_model == "equivocate" and cfg.delivery == "all"
            and cfg.topology is None
            and cfg.n_faulty <= sampling.EXACT_TABLE_MAX):
        # exact binomial table: [T, F+1].  A topology carries its own
        # per-edge equivocator bits (benor_tpu/topo/deliver.py) — no
        # F-shaped table, so topology points stay dyn-compatible.
        return True
    return False


def sweep_bucket_key(cfg: SimConfig):
    """Hashable bucket token: two sweep points share one compiled batched
    executable iff their keys are equal.  Quorum-specialized points key on
    the full config (a bucket of one); everything else keys on the config
    with the DYNAMIC axes erased — n_faulty always, the committee
    count/size knobs when committee delivery is armed, and drop_prob
    when the omission plane is armed (they ride DynParams; the static
    committee_cap shape bound stays in the key, as do the topology,
    partition and recovery specs — mismatched adjacency, partition
    epochs or churn schedules never share an executable)."""
    if quorum_specialized(cfg):
        return ("static", cfg)
    erase = {"n_faulty": 0}
    if cfg.committee_cap:
        erase.update(committee_count=1, committee_size=1)
    if cfg.drop_prob:
        # armed omission coalesces on the traced axis; the 0.5 sentinel
        # keeps armed and OFF (p = 0, whose executable must stay the
        # bit-identical pre-faultlab one) in separate buckets
        erase.update(drop_prob=0.5)
    return ("dyn", cfg.replace(**erase))


@dataclasses.dataclass
class BatchedCurve:
    """A batched curve run plus its compile-accounting evidence.

    The ``bucket_*`` lists (sweepscope, PR 13) attribute wall clock to
    the bucket that actually spent it, in executable-build order —
    ``SweepPoint.seconds`` stays the amortized per-point share for
    compatibility, but a straggler bucket is no longer hidden inside a
    uniform average.  ``run_s``/``compile_s`` keep their original
    meanings (sums over the buckets this run actually executed;
    journal-restored buckets contribute their JOURNALED stage clocks to
    the lists but zero to these sums — nothing ran).
    """

    points: List[SweepPoint]        # input order, same fields as run_point
    n_buckets: int
    bucket_sizes: List[int]         # per bucket, executable-build order
    compile_count: int              # XLA backend compiles observed
    compile_s: float                # wall-clock building the executables
    run_s: float                    # wall-clock executing them (post-compile)
    #: per-bucket lifecycle stage wall clocks (build order): host-side
    #: prepare/stack, AOT lower+compile, device execute (dispatch to
    #: completion barrier), host fetch/assemble
    bucket_prepare_s: List[float] = dataclasses.field(default_factory=list)
    bucket_compile_s: List[float] = dataclasses.field(default_factory=list)
    bucket_run_s: List[float] = dataclasses.field(default_factory=list)
    bucket_fetch_s: List[float] = dataclasses.field(default_factory=list)
    bucket_kinds: List[str] = dataclasses.field(default_factory=list)
    #: input-order point indices each bucket carried
    bucket_point_indices: List[List[int]] = dataclasses.field(
        default_factory=list)
    #: measured backend compiles per bucket THIS run (0 for restored)
    bucket_compile_counts: List[int] = dataclasses.field(
        default_factory=list)
    #: True where the bucket was reassembled from the sweep journal
    #: instead of executed (resume=True)
    bucket_reused: List[bool] = dataclasses.field(default_factory=list)
    #: end-to-end wall clock of the whole run_points_batched call
    wall_s: float = 0.0
    #: wall-clock an ideal compile-ahead/execute-behind pipeline would
    #: reclaim from the measured serial bucket schedule
    #: (sweepscope/gate.py owns the model)
    overlap_headroom_s: float = 0.0
    #: True when the buckets ran under the compile-ahead/execute-behind
    #: scheduler (run_points_batched(pipeline=True))
    pipelined: bool = False
    #: wall clock of the bucket loop alone — exactly the work the four
    #: stage clocks cover, so serial_s - span_s is the overlap the real
    #: scheduler achieved (gate.headroom_reclaimed_s owns the model)
    span_s: float = 0.0
    #: headroom actually reclaimed vs the strictly-serial stage schedule
    headroom_reclaimed_s: float = 0.0
    #: [trial_shards, node_shards] of the 2D grid mesh the dyn buckets
    #: were placed on (None = default single-device placement)
    mesh_shape: Optional[List[int]] = None


def _summarize_inline(cfg: SimConfig, r, final: NetState, faults: FaultSpec):
    """(rounds, decided, mean_k, ones, k_hist, disagree) for one point —
    the same ``summarize_final`` reduction, fused INSIDE the bucket
    executable so the whole batched sweep is one device dispatch."""
    dec, mk, ones, khist, dis = summarize_final(
        final, faults.faulty, cfg.max_rounds)
    return r, dec, mk, ones, khist, dis


def _stack_tree(items):
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *items)


def run_curve_batched(base_cfg: SimConfig, f_values: Sequence[int],
                      initial_values=None, faults_for=None,
                      verbose: bool = False,
                      heartbeat_path: Optional[str] = None,
                      journal_path: Optional[str] = None,
                      resume: bool = False, pipeline: bool = False,
                      mesh=None) -> BatchedCurve:
    """Run a rounds-vs-f curve with one XLA compile per static-shape
    bucket — the f-axis front door of ``run_points_batched`` (which
    batches ANY per-point config list, e.g. the topo committee curves):
    each f value becomes ``base_cfg.replace(n_faulty=f)`` and the
    generalized engine does the rest.  Semantics match the per-point
    loop exactly — same inputs, same random streams, bit-identical
    per-f summaries (tests/test_batched_sweep.py)."""
    cfgs = [base_cfg.replace(n_faulty=int(f)) for f in f_values]
    return run_points_batched(base_cfg, cfgs,
                              initial_values=initial_values,
                              faults_for=faults_for, verbose=verbose,
                              heartbeat_path=heartbeat_path,
                              journal_path=journal_path, resume=resume,
                              pipeline=pipeline, mesh=mesh)


def run_points_batched(base_cfg: SimConfig, cfgs: Sequence[SimConfig],
                       initial_values=None, faults_for=None,
                       verbose: bool = False,
                       heartbeat_path: Optional[str] = None,
                       journal_path: Optional[str] = None,
                       resume: bool = False, pipeline: bool = False,
                       mesh=None) -> BatchedCurve:
    """Run a list of per-point configs with one XLA compile per
    static-shape bucket (sweep_bucket_key groups them).

    The generalization PR 12 extracted from the f-axis engine so the
    topo workloads batch too: points may differ in ANY DynParams-traced
    axis (n_faulty, committee_count/committee_size) and share a bucket,
    or differ statically (topology spec, shapes, modes) and bucket
    apart.  Every point must share base_cfg's (trials, n_nodes) — the
    stacked input tensors are built once.

    Semantics match the per-point loop exactly — same inputs, same
    random streams, bit-identical per-point summaries:

      * ``initial_values`` defaults to ``random_inputs(seed, T, N)``
        (run_point's default, shared by every point);
      * ``faults_for(cfg_f) -> FaultSpec`` builds each point's fault spec
        (default: run_point's first-F-lanes-faulty crash mask);
      * every point runs from ``jax.random.key(base_cfg.seed)``.

    Dynamic buckets vmap ``run_consensus_traced`` over the stacked batch
    with the state/fault buffers DONATED to the executable (the [B, T, N]
    carry is the sweep's whole memory footprint — donation lets XLA alias
    it instead of holding input and carry live together).  Static buckets
    (quorum_specialized) run the classic dispatch — pallas fast path
    preserved — also as one fused run+summarize executable.

    Compile accounting: every invocation AOT-compiles each bucket
    executable (``jit(...).lower(...).compile()``) inside a
    ``count_backend_compiles`` scope, so ``compile_count`` is measured by
    the jax.monitoring hook, not inferred — exactly ``n_buckets`` unless
    XLA recompiled something behind our back (the property
    tests/test_batched_sweep.py pins).

    Timing fields on the returned points: ``seconds`` is the point's
    amortized share of its bucket's post-compile execution wall-clock
    (bucket run time / bucket size).

    With ``base_cfg.heartbeat_rounds`` > 0 the engine publishes a live
    progress heartbeat after every bucket (its unit of progress:
    points-done / points-total, never mid-executable) into the metrics
    registry and, when ``heartbeat_path`` is given, an append-only
    JSON-lines file `python -m benor_tpu watch` tails — host-side only,
    so the bucket executables (and their compile counts) are untouched
    (benor_tpu/meshscope/heartbeat.py).

    Sweepscope (benor_tpu/sweepscope): the engine stamps every bucket's
    lifecycle stages (prepare/stack -> AOT lower+compile -> execute ->
    fetch/assemble) onto the returned curve's ``bucket_*`` lists and,
    when the process-wide span log is armed (``metrics.SPANS``, e.g.
    the sweep CLI's ``--trace-out``), emits flow-linked Perfetto spans
    per bucket and point.  ``journal_path`` arms the DURABLE sweep
    journal: one line-atomic JSON record per completed bucket (input
    fingerprint, stage clocks, compile count, per-point payloads), and
    ``resume=True`` skips every bucket whose fingerprint + point
    indices match a journal record, reassembling its points
    bit-identically through ``point_from_raw`` with ZERO device work —
    a SIGKILLed sweep resumes with only its unfinished buckets
    recompiled; any journal tamper reruns rather than reuses.  Journal
    and tracing are host-side only: off OR on, results and compile
    counts are bit-identical (tests/test_sweepscope.py).

    ``pipeline=True`` (PR 16) switches bucket dispatch to the
    compile-ahead/execute-behind scheduler sweepscope's
    ``overlap_headroom`` model prices: a single worker thread runs
    bucket k+1's prepare + AOT compile (host work; XLA compilation
    releases the GIL) while the main thread executes bucket k on the
    device.  Everything ORDERED stays on the main thread — execute,
    fetch, journal records, heartbeat beats, verbose lines — in strict
    bucket order, so results, per-bucket compile counts, journal
    contents and heartbeat streams are bit-identical to the serial
    path; only the wall clock changes.  The reclaimed overlap lands on
    the curve as ``headroom_reclaimed_s`` (= serial stage sum minus the
    measured bucket-loop ``span_s``, clamped at 0; gate.py owns the
    model).

    ``mesh`` places each dyn bucket's stacked [B, T, N] operands on a
    2D ('trials', 'nodes') grid mesh (``parallel/grid.py``) so GSPMD
    partitions the bucket executable across devices — trials-axis data
    parallelism multiplying the node-axis sharding.  The per-point
    summaries are integer-exact reductions, so results and journal
    records are mesh-independent (bit-identical at every mesh shape,
    and a journal written on one mesh resumes on another).  Static
    (quorum-specialized) buckets keep the classic single-device
    dispatch — their pallas fast path bakes shapes.
    """
    import warnings

    from .perfscope.instrument import aot_compile
    from .sweepscope import gate as sweep_gate
    from .sweepscope.journal import (SweepJournal, bucket_fingerprint,
                                     deserialize_point, serialize_point)
    from .sweepscope.spans import emit_bucket_spans
    from .utils.compile_counter import count_backend_compiles

    t_wall0 = time.perf_counter()
    T, N = base_cfg.trials, base_cfg.n_nodes
    for cfg_f in cfgs:
        if (cfg_f.trials, cfg_f.n_nodes) != (T, N):
            raise ValueError(
                "run_points_batched points must share base_cfg's "
                f"(trials, n_nodes)=({T}, {N}); got "
                f"({cfg_f.trials}, {cfg_f.n_nodes})")
    if resume and journal_path is None:
        raise ValueError("resume=True requires journal_path (the "
                         "journal IS the resume substrate)")
    mesh_shape = None
    if mesh is not None:
        from .parallel.mesh import check_divisible
        check_divisible(T, N, mesh)
        mesh_shape = [int(s) for s in mesh.devices.shape]
    if initial_values is None:
        initial_values = random_inputs(base_cfg.seed, T, N)

    faults_fn = faults_for if faults_for is not None else default_crash_faults

    # ---- bucket the points (host side; input tensors are built lazily
    # per bucket so a journal-restored bucket never pays for them) ------
    cfgs = list(cfgs)
    buckets: Dict = {}
    order: List = []
    for i, cfg_f in enumerate(cfgs):
        key = sweep_bucket_key(cfg_f)
        if key not in buckets:
            buckets[key] = {"idx": [], "cfgs": []}
            order.append(key)
        buckets[key]["idx"].append(i)
        buckets[key]["cfgs"].append(cfg_f)
    base_key = jax.random.key(base_cfg.seed)
    journal = (SweepJournal(journal_path, resume=resume)
               if journal_path is not None else None)

    # ---- compile + run: ONE executable per bucket ------------------------
    raw = [None] * len(cfgs)
    secs = [0.0] * len(cfgs)       # per-point amortized bucket run time
    compile_s = run_s = 0.0
    total_compiles = 0
    bucket_sizes: List[int] = []
    stage_prepare: List[float] = []
    stage_compile: List[float] = []
    stage_run: List[float] = []
    stage_fetch: List[float] = []
    bucket_kinds: List[str] = []
    bucket_indices: List[List[int]] = []
    bucket_compiles: List[int] = []
    bucket_reused: List[bool] = []
    heartbeat = None
    if base_cfg.heartbeat_rounds:
        from .meshscope.heartbeat import (HeartbeatPublisher,
                                          publish_sweep_heartbeat)
        heartbeat = HeartbeatPublisher(base_cfg, path=heartbeat_path,
                                       label="sweep")
    points_done = 0

    def build_bucket(bi, key, b):
        """Bucket k's HOST leg: fault specs + journal match + stacked
        tensors + AOT compile.  Thread-safe by design — under
        ``pipeline=True`` this runs on the compile-ahead worker while
        the main thread executes bucket k-1 (XLA compilation releases
        the GIL), and the per-bucket ``count_backend_compiles`` scope is
        opened HERE only, never on the executing thread, so compile
        attribution is identical in both dispatch modes."""
        rep = b["cfgs"][0]
        # -- prepare/stack: fault specs (also the journal fingerprint's
        # input), then — for buckets that will actually run — the
        # stacked state tensors
        t_prep0 = time.perf_counter()
        faults = [faults_fn(c) for c in b["cfgs"]]
        rec = None
        if journal is not None:
            b["fp"] = bucket_fingerprint(b["cfgs"], initial_values,
                                         faults)
            if resume:
                rec = journal.match(b["fp"], b["idx"])
        if rec is not None:
            return {"bi": bi, "key": key, "b": b, "rec": rec,
                    "t_prep0": t_prep0,
                    "restore_s": time.perf_counter() - t_prep0}
        states = [init_state(c, initial_values, fl)
                  for c, fl in zip(b["cfgs"], faults)]
        # The executable returns the final states TOO (last position):
        # the loop carry is the sweep's whole memory footprint, and
        # donating the input states lets XLA alias them onto those
        # state outputs — the carry lives in the donated buffers
        # instead of input + carry both being live.  The states are
        # never fetched; only the six summary outputs cross the wire.
        # Under cfg.record each point's flight recorder joins the
        # executable's outputs right before the (unfetched) final
        # state — [B, R, REC_WIDTH] per dyn bucket, filled on device
        # inside the same vmapped loop.  cfg.witness appends each
        # point's witness buffer after it the same way.
        if key[0] == "dyn":
            stacked = _stack_tree(states)
            stacked_faults = _stack_tree(faults)
            dyn = DynParams.stack(b["cfgs"])
            if mesh is not None:
                # 2D grid placement: GSPMD partitions the vmapped
                # executable over ('trials', 'nodes'); the summaries
                # are integer-exact reductions, so results (and journal
                # records) are mesh-independent
                from .parallel.grid import place_batch
                stacked = place_batch(stacked, mesh)
                stacked_faults = place_batch(stacked_faults, mesh)

            def runner(states, faults, dyn, bk, _cfg=rep):
                def one(s, fl, d):
                    out = run_consensus_traced(_cfg, s, fl, bk, d)
                    r, fin = out[0], out[1]
                    summ = _summarize_inline(_cfg, r, fin, fl)
                    return summ + tuple(out[2:]) + (fin,)
                return jax.vmap(one, in_axes=(0, 0, 0))(
                    states, faults, dyn)
            args = (stacked, stacked_faults, dyn, base_key)
        else:
            # init_state aliases killed to faults.faulty under the crash
            # model; the donated state must not share a buffer with the
            # undonated faults argument ("donated buffer used twice").
            # Static buckets stay on the default device even under a
            # mesh: the quorum-specialized pallas path bakes shapes.
            st = states[0]
            state = NetState(x=st.x, decided=st.decided, k=st.k,
                             killed=jnp.array(st.killed))

            def runner(state, faults, bk, _cfg=rep):
                out = run_consensus(_cfg, state, faults, bk)
                r, fin = out[0], out[1]
                summ = _summarize_inline(_cfg, r, fin, faults)
                return summ + tuple(out[2:]) + (fin,)
            args = (state, faults[0], base_key)
        del states
        prepare_s = time.perf_counter() - t_prep0
        t0 = time.perf_counter()
        with count_backend_compiles() as bcc:
            with warnings.catch_warnings():
                # backends without donation support (XLA:CPU) warn that
                # the donated buffers went unused; that's the expected
                # platform gap, not a bug in the sweep
                warnings.filterwarnings(
                    "ignore", message=".*donated buffers were not usable.*")
                # the sanctioned jit(...).lower().compile() spelling
                # (perfscope/instrument.py): stage timers land in
                # metrics.REGISTRY and the bucket executable's cost model
                # stays introspectable after the sweep
                compiled = aot_compile(
                    runner, args, label=f"sweep.bucket.{key[0]}",
                    donate_argnums=(0,)).compiled
        bucket_compile_s = time.perf_counter() - t0
        return {"bi": bi, "key": key, "b": b, "rec": None,
                "t_prep0": t_prep0, "prepare_s": prepare_s,
                "compile_s": bucket_compile_s, "compiled": compiled,
                "args": args, "compiles": bcc.count}

    def execute_bucket(plan):
        """Bucket k's ORDERED leg, always on the main thread: device
        execute, fetch, journal record, heartbeat beat, verbose line —
        in strict bucket order under either dispatch mode."""
        nonlocal compile_s, run_s, total_compiles, points_done
        bi, key, b = plan["bi"], plan["key"], plan["b"]
        rec = plan["rec"]
        bucket_sizes.append(len(b["idx"]))
        bucket_kinds.append(key[0])
        bucket_indices.append(list(b["idx"]))
        if rec is not None:
            # journal restore: the bucket's points reassemble from disk
            # through the IDENTICAL point_from_raw path; no tensor is
            # built, no executable compiled, nothing dispatched
            share = (float(rec.get("run_s") or 0.0)
                     + float(rec.get("fetch_s") or 0.0)) / len(b["idx"])
            for j, i in enumerate(b["idx"]):
                raw[i] = deserialize_point(b["cfgs"][j],
                                           rec["points"][j])
                secs[i] = share
            # the lists carry the JOURNALED stage clocks so straggler
            # attribution survives a resume; this run spent ~nothing
            stage_prepare.append(float(rec.get("prepare_s") or 0.0))
            stage_compile.append(float(rec.get("compile_s") or 0.0))
            stage_run.append(float(rec.get("run_s") or 0.0))
            stage_fetch.append(float(rec.get("fetch_s") or 0.0))
            bucket_compiles.append(0)
            bucket_reused.append(True)
            journal.reused += 1
            emit_bucket_spans(bi, key[0], b["idx"], b["cfgs"],
                              {"restore": (plan["t_prep0"],
                                           plan["restore_s"])},
                              reused=True)
        else:
            compiled, args = plan["compiled"], plan["args"]
            prepare_s = plan["prepare_s"]
            bucket_compile_s = plan["compile_s"]
            t0 = time.perf_counter()
            *summ, _fin = compiled(*args)
            # completion barrier: ONE output fetched — device execution
            # finishes before the fetch returns, so this window is the
            # execute stage and the remaining fetches are pure host wire
            # + assembly time (the fetch stage)
            first = np.asarray(summ[0])
            bucket_run_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            out = [first] + [np.asarray(o) for o in summ[1:]]
            del _fin           # device-resident final states: not needed
            plan["compiled"] = plan["args"] = None   # donated: dead refs
            for j, i in enumerate(b["idx"]):
                raw[i] = ([o[j] for o in out] if key[0] == "dyn"
                          else [o for o in out])
            bucket_fetch_s = time.perf_counter() - t0
            # seconds stays the amortized share of the bucket's
            # post-compile execution wall (execute + fetch), as always
            for i in b["idx"]:
                secs[i] = (bucket_run_s + bucket_fetch_s) / len(b["idx"])
            compile_s += bucket_compile_s
            run_s += bucket_run_s + bucket_fetch_s
            total_compiles += plan["compiles"]
            stage_prepare.append(prepare_s)
            stage_compile.append(bucket_compile_s)
            stage_run.append(bucket_run_s)
            stage_fetch.append(bucket_fetch_s)
            bucket_compiles.append(plan["compiles"])
            bucket_reused.append(False)
            emit_bucket_spans(
                bi, key[0], b["idx"], b["cfgs"],
                {"prepare": (plan["t_prep0"], prepare_s),
                 "compile": (plan["t_prep0"] + prepare_s,
                             bucket_compile_s),
                 "execute": (plan["t_prep0"] + prepare_s
                             + bucket_compile_s, bucket_run_s),
                 "fetch": (plan["t_prep0"] + prepare_s + bucket_compile_s
                           + bucket_run_s, bucket_fetch_s)})
            if journal is not None:
                journal.record_bucket(
                    bi, key[0], b["idx"], b["fp"], plan["compiles"],
                    {"prepare_s": prepare_s,
                     "compile_s": bucket_compile_s,
                     "run_s": bucket_run_s, "fetch_s": bucket_fetch_s},
                    [serialize_point(c, raw[i])
                     for c, i in zip(b["cfgs"], b["idx"])],
                    mesh_shape=mesh_shape, pipelined=pipeline)
        points_done += len(b["idx"])
        if heartbeat is not None:
            publish_sweep_heartbeat(base_cfg, points_done, len(cfgs),
                                    publisher=heartbeat,
                                    bucket_index=bi)
        if verbose:
            # ONE print call per bucket, from the ordered thread only —
            # the compile-ahead worker never writes to stdout, so lines
            # cannot tear or interleave under async dispatch
            if rec is not None:
                detail = "journal-restored"
            else:
                detail = (f"compile {stage_compile[-1]:.2f}s, "
                          f"run {stage_run[-1] + stage_fetch[-1]:.2f}s")
            print(f"  bucket {bi + 1}/{len(order)} [{key[0]}] "
                  f"{len(b['idx'])} point(s): {detail}", flush=True)

    # ---- dispatch the buckets: serial, or compile-ahead/execute-behind.
    # span_s clocks the bucket loop ALONE (exactly the work the four
    # stage clocks cover — no input build, no assembly), so
    # serial_s - span_s is the overlap the scheduler actually achieved.
    work = [(bi, key, buckets[key]) for bi, key in enumerate(order)]
    t_span0 = time.perf_counter()
    if pipeline:
        from .sweep_async import pipeline_buckets
        for plan in pipeline_buckets(work, build_bucket):
            execute_bucket(plan)
    else:
        for bi, key, b in work:
            execute_bucket(build_bucket(bi, key, b))
    span_s = time.perf_counter() - t_span0
    del work, buckets  # the donated input buffers are dead; drop refs

    points = _assemble_points(cfgs, raw, secs)
    stage_dicts = [
        {"prepare_s": p, "compile_s": c, "run_s": r, "fetch_s": f}
        for p, c, r, f in zip(stage_prepare, stage_compile, stage_run,
                              stage_fetch)]
    headroom = sweep_gate.overlap_headroom_s(stage_dicts)
    reclaimed = sweep_gate.headroom_reclaimed_s(stage_dicts, span_s)
    cb = BatchedCurve(points=points, n_buckets=len(order),
                      bucket_sizes=bucket_sizes,
                      compile_count=total_compiles,
                      compile_s=compile_s, run_s=run_s,
                      bucket_prepare_s=stage_prepare,
                      bucket_compile_s=stage_compile,
                      bucket_run_s=stage_run,
                      bucket_fetch_s=stage_fetch,
                      bucket_kinds=bucket_kinds,
                      bucket_point_indices=bucket_indices,
                      bucket_compile_counts=bucket_compiles,
                      bucket_reused=bucket_reused,
                      wall_s=time.perf_counter() - t_wall0,
                      overlap_headroom_s=headroom,
                      pipelined=bool(pipeline), span_s=span_s,
                      headroom_reclaimed_s=reclaimed,
                      mesh_shape=mesh_shape)
    if journal is not None:
        journal.record_done(len(cfgs), len(order), headroom)
    if verbose:
        totals = [p + c + r + f
                  for p, c, r, f in zip(stage_prepare, stage_compile,
                                        stage_run, stage_fetch)]
        share = max(totals) / sum(totals) if sum(totals) > 0 else 0.0
        reused_note = (f", {sum(bucket_reused)} journal-restored"
                       if any(bucket_reused) else "")
        pipe_note = (f", pipelined: reclaimed "
                     f"{cb.headroom_reclaimed_s:.2f}s"
                     if pipeline else "")
        print(f"  batched curve: {len(cfgs)} points / {cb.n_buckets} "
              f"bucket(s), {cb.compile_count} compiles "
              f"({cb.compile_s:.1f}s), run {cb.run_s:.2f}s; max bucket "
              f"share {100 * share:.0f}%, overlap headroom "
              f"{cb.overlap_headroom_s:.2f}s{pipe_note}{reused_note}",
              flush=True)
    return cb


def _assemble_points(cfgs, raw, secs) -> List[SweepPoint]:
    return [point_from_raw(cfg_f, vals, s)
            for cfg_f, vals, s in zip(cfgs, raw, secs)]


def rounds_vs_f_batched(base_cfg: SimConfig, f_values: Sequence[int],
                        verbose: bool = True,
                        heartbeat_path: Optional[str] = None,
                        journal_path: Optional[str] = None,
                        resume: bool = False) -> List[SweepPoint]:
    """The north-star curve via the batched engine — same defaults and
    bit-identical summaries as ``rounds_vs_f``, O(buckets) compiles
    instead of O(points)."""
    cb = run_curve_batched(base_cfg, f_values, verbose=verbose,
                           heartbeat_path=heartbeat_path,
                           journal_path=journal_path, resume=resume)
    if verbose:
        for pt in cb.points:
            print(f"  f={pt.n_faulty}: mean_k={pt.mean_k:.2f} "
                  f"decided={pt.decided_frac:.3f} "
                  f"{pt.trials_per_sec:.1f} trials/s", flush=True)
    return cb.points


def coin_comparison_batched(base_cfg: SimConfig, f_values: Sequence[int],
                            verbose: bool = True
                            ) -> Dict[str, List[SweepPoint]]:
    """``coin_comparison``'s private/common contrast swept over an f-axis:
    each coin mode's whole curve runs as one batched executable (the
    count-controlling adversary's closed form has no quorum-specialized
    shapes), so the pair costs TWO compiles at any number of f values.
    Same adversary setup as coin_comparison: balanced inputs, zero
    crashes, even quorum required per point."""
    T, N = base_cfg.trials, base_cfg.n_nodes
    for f in f_values:
        if (N - int(f)) % 2:
            raise ValueError(
                f"coin_comparison needs an even quorum N-F for a "
                f"perfect-tie adversary (got N-F={N - int(f)} at f={f}); "
                f"adjust N or the f grid")
    balanced = balanced_inputs(T, N)
    out: Dict[str, List[SweepPoint]] = {}
    for coin in ("private", "common"):
        cfg = base_cfg.replace(coin_mode=coin, scheduler="adversarial",
                               delivery="quorum")
        if verbose:
            print(f" coin_mode={coin}:", flush=True)
        cb = run_curve_batched(
            cfg, f_values, initial_values=balanced,
            faults_for=lambda c: FaultSpec.none(T, N), verbose=verbose)
        out[coin] = cb.points
    return out


def coin_comparison(base_cfg: SimConfig,
                    verbose: bool = True) -> Dict[str, List[SweepPoint]]:
    """Private vs shared common coin under the worst-case adversarial
    scheduler — the classic Ben-Or-vs-Rabin contrast: the count-controlling
    adversary livelocks private coins (decided_frac ~ 0 at the round cap)
    while the common coin terminates in O(1) expected rounds.

    The adversary is given maximum power: all N nodes stay alive (zero
    crashes), so it can discard any F messages per receiver; inputs are
    perfectly balanced.  It forces a tied (m/2, m/2) delivered multiset —
    which requires an even quorum m = N - F; for odd m a one-message
    imbalance leaks through and the run converges regardless of coin.

    Escape physics (and why termination is still guaranteed — Ben-Or's
    original argument): a tie is only constructible while the private coin
    flips stay balanced enough, min(c0, c1) >= m/2, i.e. within F/2 of the
    N/2 mean.  With per-round std sqrt(N)/2, the per-round escape
    probability is ~2*Phi(-F/sqrt(N)), so the private-coin livelock is only
    long-lived when F >> sqrt(N) (e.g. N=100, F=40 holds for ~1e4 rounds;
    N=20, F=6 escapes ~11% of rounds).  The common coin escapes in O(1)
    rounds at ANY F: the first round after all lanes flip the same value,
    the adversary cannot hide a unanimous class.
    """
    if base_cfg.quorum % 2:
        raise ValueError(
            f"coin_comparison needs an even quorum N-F for a perfect-tie "
            f"adversary (got N-F={base_cfg.quorum}); adjust N or F")
    T, N = base_cfg.trials, base_cfg.n_nodes
    no_crash = FaultSpec.none(T, N)
    balanced = balanced_inputs(T, N)
    out: Dict[str, List[SweepPoint]] = {}
    for coin in ("private", "common"):
        cfg = base_cfg.replace(coin_mode=coin, scheduler="adversarial",
                               delivery="quorum")
        if verbose:
            print(f" coin_mode={coin}:", flush=True)
        pt = run_point(cfg, initial_values=balanced, faults=no_crash)
        if verbose:
            print(f"  decided={pt.decided_frac:.3f} mean_k={pt.mean_k:.2f} "
                  f"{pt.trials_per_sec:.1f} trials/s", flush=True)
        out[coin] = [pt]
    return out


def baseline_configs() -> Dict[str, SimConfig]:
    """The five BASELINE.json benchmark configs as ready-to-run presets."""
    return {
        # "Fault-free Ben-Or, N=5 nodes, random initial x"
        "n5_faultfree": SimConfig(n_nodes=5, n_faulty=0, trials=1024,
                                  delivery="quorum", scheduler="uniform"),
        # "Crash-fault Ben-Or, N=10k nodes, f=N/5 crash mask, 1k MC trials"
        "n10k_crash": SimConfig(n_nodes=10_000, n_faulty=2_000, trials=1000,
                                delivery="quorum", scheduler="uniform",
                                path="histogram"),
        # "Byzantine Ben-Or, N=100k nodes, f<N/5 adversarial bit-flip mask"
        "n100k_byzantine": SimConfig(n_nodes=100_000, n_faulty=19_999,
                                     trials=64, fault_model="byzantine",
                                     delivery="quorum", scheduler="uniform",
                                     path="histogram"),
        # "Private-coin vs shared-common-coin, N=1M, rounds-to-decide vs f"
        "n1m_coin_sweep": SimConfig(n_nodes=1_000_000, n_faulty=200_000,
                                    trials=32, delivery="quorum",
                                    scheduler="uniform", path="histogram"),
        # "Asynchronous adversarial scheduler, N=1M nodes"
        "n1m_adversarial": SimConfig(n_nodes=1_000_000, n_faulty=200_000,
                                     trials=32, delivery="quorum",
                                     scheduler="adversarial", max_rounds=24,
                                     path="histogram"),
    }


def save_points(path: str, points: Sequence[SweepPoint]) -> None:
    with open(path, "w") as fh:
        json.dump([p.to_dict() for p in points], fh, indent=1)
