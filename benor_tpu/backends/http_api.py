"""HTTP observation layer: the reference's control plane over real sockets.

Serves the four routes of the reference node server (src/nodes/node.ts) on
``BASE_NODE_PORT + node_id`` (src/config.ts:1), one listener per simulated
node, backed by EITHER network backend's device/oracle state:

    GET /status    200 "live" | 500 "faulty"          node.ts:33-39
    GET /start     200 {"message": "Algorithm started"}   node.ts:167-188
    GET /stop      200 "killed"                       node.ts:191-194
    GET /getState  200 NodeState JSON                 node.ts:197-199

plus one framework-native route with no reference counterpart:

    GET /getRoundHistory?since_round=N   200 {"rows": [...], "cursor": r}
        — the flight recorder's cursor-based incremental feed
        (SimConfig(record=True); grows live under poll_rounds; see
        _get_round_history and README Observability / meshscope)

Semantics notes:
  * The reference runs consensus *concurrently* with polling; here the
    first /start on any node runs the network to termination (the compiled
    while-loop), so by default pollers observe the final snapshot — the
    same fixed point the reference's pollers converge to.  With
    ``SimConfig(poll_rounds=c)`` the loop runs in c-round slices and the
    snapshot is republished between slices: /getState (served on its own
    thread) then observes a live undecided network with growing k, the
    reference's poll-during-run contract (benorconsensus.test.ts:149-160).
  * /stop kills only the receiving node (consensus.ts fans /stop out to all
    ports to stop the network, and so does ``stop_all``).
  * POST /message (node.ts:43-163) is SERVED when the backing network is
    an event-loop oracle (backend='express'): the forged message joins the
    seeded drain queue, so injected runs stay deterministic, and a killed
    target sends no response at all — the reference's 200 sits inside its
    ``!killed`` guard (node.ts:44-161).  On the TPU backend it answers 405
    with an explanation: peer messages are device-array data movement, not
    RPCs (SURVEY §5.8); external injection would bypass the deterministic
    scheduler.  The GET routes are the ones the reference's control plane
    and test harness actually consume (PARITY.md).

This layer exists for wire-level interop (curl, the reference's own test
utilities pointed at localhost) at demo-scale N; in-process code should use
the Python facade (api.py) which serves the same dicts without sockets.
Multi-tenant THROUGHPUT serving is deliberately not this layer's job: the
port-per-node parity plane runs one network synchronously; concurrent
client jobs belong on ``benor_tpu/serve`` (``python -m benor_tpu serve``),
whose request plane coalesces them onto the warm batched executors and
streams round history over SSE instead of /getState polling (README
"Serving").
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from ..config import BASE_NODE_PORT


class _Handler(BaseHTTPRequestHandler):
    network = None          # set per listener class
    node_id: int = -1
    start_lock: Optional[threading.Lock] = None

    def log_message(self, fmt, *args):  # silence default stderr chatter
        pass

    def _send(self, code: int, body, as_json: bool,
              extra_headers=()) -> None:
        data = (json.dumps(body) if as_json else str(body)).encode()
        self.send_response(code)
        self.send_header(
            "Content-Type",
            "application/json" if as_json else "text/plain; charset=utf-8")
        for name, value in extra_headers:
            self.send_header(name, value)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        from urllib.parse import parse_qs, urlsplit
        net, nid = self.network, self.node_id
        route = urlsplit(self.path)
        if route.path == "/status":
            body, code = net.status(nid)
            self._send(code, body, as_json=False)
        elif route.path == "/start":
            with self.start_lock:          # idempotent network-level start
                net.start()
            self._send(200, {"message": "Algorithm started"}, as_json=True)
        elif route.path == "/stop":
            net.stop_node(nid)
            self._send(200, "killed", as_json=False)
        elif route.path == "/getState":
            self._send(200, net.get_state(nid), as_json=True)
        elif route.path == "/getRoundHistory":
            self._get_round_history(parse_qs(route.query))
        else:
            self._send(404, {"error": f"no route {self.path}"}, as_json=True)

    def _get_round_history(self, query) -> None:
        """GET /getRoundHistory[?since_round=N] — the flight recorder's
        cursor-based incremental feed (meshscope's live progress plane;
        not a reference route, so it sits OUTSIDE the four parity routes
        above).  ``since_round`` is the last round the poller has seen:
        only strictly newer rows return, each carrying its true round
        index, plus ``cursor`` = the highest round in this response (or
        the request's cursor when nothing new) to pass back next poll.
        Under SimConfig(poll_rounds=c) the history grows between slices,
        so a polling client streams the run round by round without
        re-downloading the whole buffer.  405 on backends without a
        flight recorder (the event-loop oracles), 400 when the recorder
        is off (SimConfig(record=False)) or the cursor is malformed.
        """
        net = self.network
        if not hasattr(net, "get_round_history"):
            self._send(405, {
                "error": "round history not supported on this backend",
                "detail": "the flight recorder fills inside the tpu "
                          "backend's compiled loop; the event-loop "
                          "oracles have no device buffer to serve "
                          "(see README Observability)",
            }, as_json=True, extra_headers=(("Allow", "GET"),))
            return
        since = None
        raw = query.get("since_round")
        if raw:
            try:
                since = int(raw[0])
            except (TypeError, ValueError):
                self._send(400, {"error": "since_round must be an "
                                          "integer round index"},
                           as_json=True)
                return
        try:
            rows = net.get_round_history(since_round=since)
        except ValueError as e:        # recorder off (record=False)
            self._send(400, {"error": str(e)}, as_json=True)
            return
        cursor = rows[-1]["round"] if rows else (since if since is not None
                                                 else -1)
        self._send(200, {"rows": rows, "cursor": cursor}, as_json=True)

    #: Per-request drain budget in bytes (``NodeHttpCluster(drain_cap=...)``
    #: overrides it cluster-wide): how much of an unknowable-length body
    #: (chunked / malformed Content-Length) a handler will read before
    #: replying and closing.  1 MiB default — enough that any real
    #: client's in-flight bytes drain (avoiding the reply-discarding TCP
    #: RST), small enough that a hostile endless body cannot hold a
    #: handler thread.
    drain_cap: int = 1 << 20

    def _drain_best_effort(self, cap: Optional[int] = None) -> None:
        """Read whatever body bytes are ALREADY in flight before responding:
        replying and closing with unread data pending turns the close into a
        TCP RST that can discard the in-flight response.  Used when the body
        length is unknowable (chunked / malformed Content-Length).  Each
        read is gated on select() readability so a client that has finished
        sending and is awaiting the reply costs at most one 50 ms wait —
        not a blocking read that stalls until timeout.  ``cap`` defaults to
        the class's ``drain_cap`` (a NodeHttpCluster constructor knob)."""
        import select
        if cap is None:
            cap = self.drain_cap
        try:
            drained = 0
            while drained < cap:
                ready, _, _ = select.select([self.connection], [], [], 0.05)
                if not ready:
                    break
                chunk = self.rfile.read1(1 << 16)
                if not chunk:
                    break
                drained += len(chunk)
        except OSError:
            pass

    def do_POST(self):
        # A chunked body has no Content-Length and cannot be drained by
        # byte count — best-effort drain, then reject (RFC 9112 allows 411)
        # and close the connection.
        if "chunked" in (self.headers.get("Transfer-Encoding") or "").lower():
            self.close_connection = True
            self._drain_best_effort()
            self._send(411, {"error": "chunked bodies not supported"},
                       as_json=True)
            return
        # A malformed Content-Length must not crash the handler (no response
        # at all) or dispatch the route with the body unread: drain what we
        # can, answer 400, close.
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except (TypeError, ValueError):
            self.close_connection = True
            self._drain_best_effort()
            self._send(400, {"error": "malformed Content-Length"},
                       as_json=True)
            return
        # Read the declared body before replying (same RST consideration).
        # Only /message consumes it, and a valid message is tens of bytes:
        # everything else (and anything past the 1 MiB cap) is drained and
        # discarded so a huge Content-Length cannot balloon memory.
        keep = self.path == "/message"
        cap = 1 << 20
        chunks = []
        kept = 0
        while length > 0:
            chunk = self.rfile.read(min(length, 1 << 16))
            if not chunk:
                break
            if keep and kept < cap:
                chunks.append(chunk)
                kept += len(chunk)
            length -= len(chunk)
        if not keep:
            self._send(404, {"error": f"no route {self.path}"}, as_json=True)
        elif kept >= cap:
            self._send(413, {"error": "body too large"}, as_json=True)
        else:
            self._post_message(b"".join(chunks))

    def _post_message(self, body: bytes) -> None:
        """POST /message — the reference's peer-message route
        (node.ts:43-163), served where injection is DETERMINISTIC.

        On an event-loop oracle backend (one exposing ``inject_message``)
        the forged message joins the seeded drain queue like any peer
        broadcast: 200 {"message": "Message received"} (node.ts:161), or —
        matching the reference, whose 200 sits inside the ``!killed``
        guard (node.ts:44-161) — NO response at all when the target is
        killed (the connection just closes).

        On the TPU backend peer messages are device-array data movement
        under the seeded N9 scheduler; accepting external injections would
        bypass it and break reproducibility, so the deliberate non-parity
        stands: 405 points at the oracle backends (PARITY.md).
        """
        net, nid = self.network, self.node_id
        if not hasattr(net, "inject_message"):
            # tpu backend only: messages are on-device data movement under
            # the seeded N9 scheduler — both oracles serve injection.
            self._send(405, {
                "error": "message injection not supported on this backend",
                "detail": "injection is served on the event-loop oracles "
                          "(backend='express' any time; backend='native' "
                          "pre-start), where the forged message joins the "
                          "seeded drain queue; this backend serves "
                          "/status /start /stop /getState "
                          "(see PARITY.md, 'Deliberate non-parities')",
            }, as_json=True, extra_headers=(("Allow", "GET"),))
            return
        try:
            msg = json.loads(body.decode("utf-8"))
            k, x, mtype = msg["k"], msg["x"], msg["messageType"]
        except (ValueError, KeyError, UnicodeDecodeError, TypeError):
            self._send(400, {"error": "body must be JSON with k, x, "
                                      "messageType (node.ts:44)"},
                       as_json=True)
            return
        # k keys the per-round buffers and mtype is string-compared: a
        # JSON-valid but wrong-typed value (k = [1]) would otherwise
        # poison the queue and blow up INSIDE the drain, wedging /start
        if not isinstance(k, int) or isinstance(k, bool) \
                or not isinstance(mtype, str):
            self._send(400, {"error": "k must be an integer and "
                                      "messageType a string"},
                       as_json=True)
            return
        # injections serialize with /start (and each other) exactly like
        # the reference's single-threaded event loop
        try:
            with self.start_lock:
                delivered = net.inject_message(nid, k, x, mtype)
        except ValueError as e:       # e.g. native's k-range contract
            self._send(400, {"error": str(e)}, as_json=True)
            return
        except NotImplementedError as e:   # native post-start injection
            self._send(405, {"error": str(e)}, as_json=True,
                       extra_headers=(("Allow", "GET"),))
            return
        except RuntimeError as e:
            # a post-start injection cascade can trip the oracle's step
            # cap (ExpressNetwork._drain); answer 500 so the wire can
            # tell it from the deliberate killed-target no-response
            self._send(500, {"error": str(e)}, as_json=True)
            return
        if delivered:
            self._send(200, {"message": "Message received"}, as_json=True)
        else:
            self.close_connection = True    # killed target: no response


class NodeHttpCluster:
    """N HTTP listeners (ports base..base+N-1) over one simulated network.

    Knobs:
      * ``drain_cap`` — per-request byte budget for draining an
        unknowable-length POST body before replying (the ``_Handler.
        drain_cap`` class attribute, see ``_drain_best_effort``);
        default 1 MiB.
      * ``addr_retries`` / ``addr_retry_delay_s`` — when a node's port
        ``base_port + node_id`` is taken (EADDRINUSE — a TIME_WAIT
        straggler from a previous cluster, or an unrelated process),
        binding is retried that many times with that delay, and a port
        that STAYS taken parks the node instead of crashing the whole
        cluster: the remaining N-1 listeners serve normally and the
        parked ids are recorded in ``self.parked`` (a parked node is
        observable via any sibling's /getState — the network itself is
        whole; only its per-node wire endpoint is missing).  A FULLY
        taken range still raises (zero listeners would silently hand
        clients some foreign process's ports), and any other OSError
        tears down cleanly and raises.
    """

    def __init__(self, network, base_port: int = BASE_NODE_PORT,
                 host: str = "127.0.0.1", drain_cap: int = 1 << 20,
                 addr_retries: int = 2,
                 addr_retry_delay_s: float = 0.05):
        import errno
        import time as _time

        self.network = network
        self.base_port = base_port
        self.servers: List[ThreadingHTTPServer] = []
        self.threads: List[threading.Thread] = []
        #: node ids whose port stayed EADDRINUSE after the retries —
        #: parked, not fatal (see class docstring).
        self.parked: List[int] = []
        start_lock = threading.Lock()
        n = network.cfg.n_nodes if hasattr(network, "cfg") else network.n
        try:
            for i in range(n):
                handler = type(f"_Handler{i}", (_Handler,), {
                    "network": network, "node_id": i,
                    "start_lock": start_lock, "drain_cap": drain_cap})
                srv = None
                for attempt in range(addr_retries + 1):
                    try:
                        srv = ThreadingHTTPServer((host, base_port + i),
                                                  handler)
                        break
                    except OSError as e:
                        if e.errno != errno.EADDRINUSE:
                            raise
                        if attempt < addr_retries:
                            _time.sleep(addr_retry_delay_s)
                if srv is None:
                    self.parked.append(i)
                    continue
                t = threading.Thread(target=srv.serve_forever, daemon=True)
                self.servers.append(srv)
                self.threads.append(t)
        except OSError:
            # non-EADDRINUSE failure on port base+k: release the
            # already-bound listeners before raising
            for srv in self.servers:
                srv.server_close()
            self.servers.clear()
            self.threads.clear()
            raise
        if n and not self.servers:
            # EVERY port taken: almost certainly another cluster (or a
            # whole foreign service) owns the range — a "cluster" with
            # zero listeners would let clients talk to that stranger's
            # ports and read valid-looking state from the WRONG network.
            # Parking exists to survive one straggler, not to serve
            # nothing; fail loudly instead.
            self.parked.clear()
            raise OSError(
                f"all {n} ports in [{base_port}, {base_port + n}) are "
                f"taken — another cluster on this base_port? (parking "
                f"covers individual EADDRINUSE stragglers, not a fully "
                f"occupied range)")

    def serve(self) -> "NodeHttpCluster":
        """Start the listener threads (idempotent: ``serve_network`` already
        serves, and entering the result as a context manager must not try to
        start the threads a second time)."""
        for t in self.threads:
            if t.ident is None:        # never started
                t.start()
        return self

    def stop_all(self) -> None:
        """consensus.ts:10-15 — /stop every node (state-level)."""
        self.network.stop()

    def close(self) -> None:
        for srv in self.servers:
            srv.shutdown()
            srv.server_close()

    def __enter__(self):
        return self.serve()

    def __exit__(self, *exc):
        self.close()


def serve_network(network, base_port: int = BASE_NODE_PORT):
    """Convenience: wrap a launched network in a serving HTTP cluster."""
    return NodeHttpCluster(network, base_port).serve()
