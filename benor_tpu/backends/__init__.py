"""Backends (the N1 backend switch).

'tpu'     — the device-array simulator (backends/tpu.py): the whole network
            is [trials, N] tensors, one compiled kernel per round.
'express' — a pure-Python event-loop re-host of the reference's per-node
            servers (backends/express.py): the semantic oracle, quirks and
            all, used for differential/parity testing without Node.js.

Both expose the same observable contract (status/start/stop/get_state) and
pass the identical scenario suite (tests/test_scenarios.py).
"""

from .express import ExpressNetwork
from .tpu import TpuNetwork

__all__ = ["ExpressNetwork", "TpuNetwork"]
