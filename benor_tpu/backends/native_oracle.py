"""ctypes binding for the native C++ event-loop oracle.

Loads (building on first use, g++ is in the image) the shared library from
``native/express_oracle.cpp`` and exposes it behind the same parity API as
the Python oracle (backends/express.py).  The native oracle exists for
large-N differential testing: the drain loop delivers O(N^2) messages per
round, which the Python interpreter handles at ~1e6 msgs/s while the native
loop does ~1e8 — at N=500 a single run is ~100x faster.

Bit-exact with the Python oracle: the C++ side reimplements CPython's
MT19937 (init_by_array seeding + 53-bit doubles), so coin flips — and hence
full execution traces — are identical for the same (seed, scenario).
Verified by tests/test_native_oracle.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "express_oracle.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_BUILD_DIR, "libexpress_oracle.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _build() -> None:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", _LIB]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def load_library() -> ctypes.CDLL:
    """Load (compiling if stale/absent) the native oracle library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB) or
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            _build()
        lib = ctypes.CDLL(_LIB)
        lib.benor_express_run.restype = ctypes.c_int64
        lib.benor_express_run.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,   # n, f, max_r
            ctypes.c_uint32, ctypes.c_int64,                  # seed, cap
            ctypes.c_uint8,                                   # order
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),  # in/out
        ]
        lib.benor_express_run_inj.restype = ctypes.c_int64
        lib.benor_express_run_inj.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,   # n, f, max_r
            ctypes.c_uint32, ctypes.c_int64,                  # seed, cap
            ctypes.c_uint8,                                   # order
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            ctypes.c_int64,                                   # n_inj
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),  # in/out
        ]
        lib.benor_express_run_batch.restype = ctypes.c_int64
        lib.benor_express_run_batch.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,   # n, f, max_r
            np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),  # seeds
            ctypes.c_int64, ctypes.c_int64,                   # n_seeds, cap
            ctypes.c_uint8,                                   # order
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
        return lib


def run_batch(cfg, initial_values, faulty_list, seeds,
              step_cap: Optional[int] = None,
              raise_on_cap: bool = False) -> dict:
    """Run the native oracle over an [S] seed vector in ONE ctypes call.

    Same scenario for every seed (values/faulty as in launch_network);
    ``cfg.oracle_order`` picks fifo/shuffle delivery.  Returns a dict of
    numpy arrays: x int8 [S, N] (faulty lanes hold -1), decided bool
    [S, N], k int32 [S, N] (faulty lanes -1), killed bool [S, N], steps
    int64 [S] (-1 where the per-seed step cap tripped), plus
    ``n_tripped`` (int): how many seeds tripped the cap — THOSE rows are
    mid-run snapshots, not finished traces.  ``raise_on_cap=True`` turns
    any trip into a RuntimeError so capped snapshots can never be
    consumed as finished traces by accident.

    This is the engine of the oracle<->scheduler DISTRIBUTION-parity
    study (r3 VERDICT items 4+7): ~10^3 rounds-to-decide samples cost one
    library call at ~1e8 delivered messages/s instead of 10^3 Python
    round-trips.
    """
    n, f = cfg.n_nodes, cfg.n_faulty
    if len(initial_values) != len(faulty_list) or n != len(initial_values):
        raise ValueError("Arrays don't match")
    if sum(bool(b) for b in faulty_list) != f:
        raise ValueError("faultyList doesnt have F faulties")
    # same guard as the network entry point (api.py): the oracle
    # replicates the REFERENCE semantics exactly — silently running a
    # requested framework extension would fake wrong-scenario
    # distributions
    for knob, val, want in (("fault_model", cfg.fault_model, "crash"),
                            ("coin_mode", cfg.coin_mode, "private"),
                            ("rule", cfg.rule, "reference"),
                            ("scheduler", cfg.scheduler, "uniform")):
        if val != want:
            raise ValueError(
                f"the native oracle supports only {knob}={want!r} (the "
                f"reference's semantics); got {val!r} — use the 'tpu' "
                "backend")
    seeds = np.ascontiguousarray(seeds, np.uint32)
    s = len(seeds)
    cap = step_cap if step_cap is not None else \
        max(500_000, 20 * n * n * cfg.max_rounds)
    vals = np.asarray([2 if v == "?" else int(v) for v in initial_values],
                      np.int8)
    faulty = np.asarray(faulty_list, bool).astype(np.uint8)
    out_x = np.empty((s, n), np.int8)
    out_dec = np.empty((s, n), np.uint8)
    out_k = np.empty((s, n), np.int32)
    out_killed = np.empty((s, n), np.uint8)
    out_steps = np.empty(s, np.int64)
    lib = load_library()
    lib.benor_express_run_batch(
        n, f, cfg.max_rounds, seeds, s, cap,
        1 if cfg.oracle_order == "shuffle" else 0,
        vals, faulty, out_x.reshape(-1), out_dec.reshape(-1),
        out_k.reshape(-1), out_killed.reshape(-1), out_steps)
    n_tripped = int((out_steps < 0).sum())
    if raise_on_cap and n_tripped:
        raise RuntimeError(
            f"native oracle: {n_tripped}/{s} seeds tripped the step cap "
            f"({cap}); raise step_cap or shrink the scenario")
    return {"x": out_x, "decided": out_dec.astype(bool), "k": out_k,
            "killed": out_killed.astype(bool), "steps": out_steps,
            "n_tripped": n_tripped}


def native_available() -> bool:
    try:
        load_library()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


class NativeExpressNetwork:
    """Parity-API network running the C++ oracle (single trial, like the
    Python oracle).  Same validation messages as launchNodes.ts:10-13."""

    def __init__(self, cfg, initial_values, faulty_list,
                 step_cap: Optional[int] = None):
        n, f = cfg.n_nodes, cfg.n_faulty
        if cfg.trials != 1:
            raise ValueError(
                "the express oracle simulates a single trial; use the 'tpu' "
                "backend for Monte-Carlo (trials > 1) runs")
        if len(initial_values) != len(faulty_list) or n != len(initial_values):
            raise ValueError("Arrays don't match")
        if sum(bool(b) for b in faulty_list) != f:
            raise ValueError("faultyList doesnt have F faulties")
        if not (0 <= cfg.seed < 2**32):
            # the C++ PyMT19937 implements only the single-word
            # init_by_array path; a truncated seed would silently diverge
            # from the Python oracle's multi-word seeding
            raise ValueError(
                "native oracle requires 0 <= seed < 2**32 for bit-exact "
                "parity with the Python oracle")
        self.cfg = cfg
        self.n, self.f = n, f
        self._step_cap = step_cap if step_cap is not None else \
            max(500_000, 20 * n * n * cfg.max_rounds)
        self._vals = np.asarray(
            [2 if v == "?" else int(v) for v in initial_values], np.int8)
        self._faulty = np.asarray(faulty_list, bool).astype(np.uint8)
        self._x = self._vals.copy()
        self._decided = np.zeros(n, np.uint8)
        self._k = np.zeros(n, np.int32)
        self._killed = self._faulty.copy()
        self._started = False
        self._inj: list = []          # pre-start POST /message buffer

    def status(self, node_id: int, trial: int = 0):
        self._check_trial(trial)
        return ("faulty", 500) if self._killed[node_id] else ("live", 200)

    def inject_message(self, node_id: int, k, x, message_type) -> bool:
        """External message injection — the reference's POST /message
        surface (node.ts:43-163), PRE-START only on this backend.

        Buffered here and handed to ``benor_express_run_inj``, which
        pushes the messages into the delivery queue ahead of the /start
        fan-out — exactly where the Python oracle's pre-start
        inject_message puts them, so injected traces stay BIT-EQUAL
        across the two oracles for either delivery order
        (tests/test_native_oracle.py pins this).

        Returns False iff the target is killed at injection time (the
        reference's 200 sits inside its ``!killed`` guard — callers send
        no response).  Raises NotImplementedError once started: the C++
        engine runs whole trials in one library call, so a mid/post-run
        queue does not exist here — the Python express oracle serves
        that case.
        """
        if self._started:
            raise NotImplementedError(
                "post-start injection is not supported on the batched "
                "native oracle; use backend='express'")
        if not -self.n <= node_id < self.n:
            raise IndexError("node_id out of range")   # list-index parity
        if node_id < 0:
            # the Python oracle's nodes[node_id] accepts negative indices
            # (nodes[-1] == last node); normalize so a negative injection
            # lands on the SAME node in both engines — the C++ side drops
            # raw negatives, which would silently fork the traces
            node_id += self.n
        if self._killed[node_id]:
            return False
        if not isinstance(k, int) or isinstance(k, bool) or \
                not (0 <= k <= self.cfg.max_rounds + 1):
            # the C++ tally buffers are sized max_rounds + 2; the Python
            # oracle's dict buffers accept any k, so an out-of-range k
            # would silently diverge between the oracles — reject it
            raise ValueError(
                "native oracle injection requires 0 <= k <= "
                f"max_rounds + 1 (= {self.cfg.max_rounds + 1}); got {k!r}")
        # Unknown types are delivered as no-ops (phase 2): they must still
        # occupy a queue slot, or the shuffle delivery permutation would
        # diverge from the Python oracle's.  x is canonicalized with
        # Python ``==`` semantics — exactly what the express oracle's
        # list.count tallying applies — so non-canonical wire values
        # (0.5, "1", True) class identically on both engines: 0-equal,
        # 1-equal, or the neither class (counts toward the quorum
        # length, quirk 4, like "?").
        phase = {"proposal phase": 0, "voting phase": 1}.get(message_type, 2)
        xv = 0 if x == 0 else (1 if x == 1 else 2)
        self._inj.append((node_id, k, xv, phase))
        return True

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        lib = load_library()
        # _killed is an in/out buffer: pre-start stop()/stop_node() calls
        # are honored as the initial killed mask (parity with the Python
        # oracle, where a pre-start stop changes the consensus outcome).
        order = 1 if self.cfg.oracle_order == "shuffle" else 0
        if self._inj:
            inj = np.asarray(self._inj, np.int64).reshape(-1, 4)
            steps = lib.benor_express_run_inj(
                self.n, self.f, self.cfg.max_rounds, self.cfg.seed,
                self._step_cap, order, self._vals, self._faulty,
                len(self._inj),
                np.ascontiguousarray(inj[:, 0], np.int32),
                np.ascontiguousarray(inj[:, 1], np.int32),
                np.ascontiguousarray(inj[:, 2], np.int8),
                np.ascontiguousarray(inj[:, 3], np.uint8),
                self._x, self._decided, self._k, self._killed)
        else:
            steps = lib.benor_express_run(
                self.n, self.f, self.cfg.max_rounds, self.cfg.seed,
                self._step_cap, order, self._vals, self._faulty, self._x,
                self._decided, self._k, self._killed)
        if steps < 0:
            raise RuntimeError(
                f"native oracle exceeded its step cap ({self._step_cap} "
                f"deliveries) before settling")
        self.steps_delivered = int(steps)

    def stop(self) -> None:
        self._killed[:] = 1

    def stop_node(self, node_id: int) -> None:
        self._killed[node_id] = 1

    @staticmethod
    def _check_trial(trial: int) -> None:
        if trial != 0:
            raise IndexError("express oracle has a single trial (index 0)")

    def get_state(self, node_id: int, trial: int = 0) -> dict:
        self._check_trial(trial)
        if self._faulty[node_id]:
            return {"killed": True, "x": None, "decided": None, "k": None}
        x = int(self._x[node_id])
        return {"killed": bool(self._killed[node_id]),
                "x": "?" if x == 2 else x,
                "decided": bool(self._decided[node_id]),
                "k": int(self._k[node_id])}

    def get_states(self, trial: int = 0) -> List[dict]:
        self._check_trial(trial)
        return [self.get_state(i) for i in range(self.n)]

    def close(self) -> None:
        pass
