"""Express-style event-loop oracle backend (SURVEY.md §7 stage 2).

A pure-Python re-host of the reference's per-node Express servers
(src/nodes/node.ts) used as the *semantic oracle* for differential testing:
the TPU backend must agree with this one on every scenario.  The Node.js
event loop becomes an explicit FIFO message queue — one valid serialization
of the reference's fire-and-forget fetch concurrency — and the reference's
behavioral quirks (SURVEY §2.1) are preserved deliberately:

  * per-round unbounded proposal/vote buffers that re-fire the tally on
    every arrival past N-F (node.ts:47-52, 84-88 — quirk 8),
  * quorum threshold counts raw messages including "?" (quirk 4),
  * plurality-adopt before the coin (node.ts:106-112 — quirk 9),
  * broadcasts include self (quirk 6),
  * killed nodes silently drop messages (node.ts:45 — quirk 3),
  * decided nodes keep looping rounds; the only brake is the global-halt
    probe that kills everyone once all are decided (node.ts:119-145 —
    quirk 5 / sub-behavior 5e),
  * faulty nodes are crash-from-birth with all-null state (node.ts:21-26).

No HTTP, no threads: deterministic given (seed, scenario, oracle_order).

Delivery order (``cfg.oracle_order``): the reference's fire-and-forget
fetches (node.ts:72-80) make EVERY interleaving of in-flight messages a
legal execution, so the oracle supports two seeded serializations —
'fifo' (queue order, the canonical event-loop schedule) and 'shuffle'
(each step delivers a uniformly random pending message, drawn from a
dedicated PRNG stream so the protocol's coin stream is unaffected).
Protocol properties must hold under both; the native C++ oracle implements
the identical algorithm and RNG, so traces are bit-equal across languages
for either order.
"""

from __future__ import annotations

import random
from collections import defaultdict, deque
from typing import List, Optional


class _ExpressNode:
    """One reference node: state + message handler (node.ts:8-212)."""

    def __init__(self, net: "ExpressNetwork", node_id: int, n: int, f: int,
                 initial_value, is_faulty: bool):
        self.net = net
        self.node_id = node_id
        self.n = n
        self.f = f
        self.is_faulty = is_faulty
        # node.ts:21-26
        self.killed = is_faulty
        self.x = None if is_faulty else initial_value
        self.decided = None if is_faulty else False
        self.k = None if is_faulty else 0
        # node.ts:29-30 — unbounded per-round buffers
        self.proposals = defaultdict(list)
        self.votes = defaultdict(list)

    # /status (node.ts:33-39)
    def status(self):
        return ("faulty", 500) if self.killed else ("live", 200)

    # /start (node.ts:167-188)
    def on_start(self) -> None:
        if not self.killed:
            self.k = 1
            self.net.broadcast(self.k, self.x, "proposal phase")

    # /stop (node.ts:191-194)
    def on_stop(self) -> None:
        self.killed = True

    # /message (node.ts:43-163)
    def on_message(self, k: int, x, message_type: str) -> None:
        if self.killed:
            return  # quirk 3: silent drop
        if message_type == "proposal phase":
            buf = self.proposals[k]
            buf.append(x)
            if len(buf) >= self.n - self.f:          # quirk 4/8: >=, incl "?"
                count0 = buf.count(0)
                count1 = buf.count(1)
                if count0 > count1:
                    nx = 0
                elif count1 > count0:
                    nx = 1
                else:
                    nx = "?"
                self.net.broadcast(k, nx, "voting phase")
        elif message_type == "voting phase":
            buf = self.votes[k]
            buf.append(x)
            if len(buf) >= self.n - self.f:
                count0 = buf.count(0)
                count1 = buf.count(1)
                if count0 > self.f:                  # node.ts:99-104
                    self.x = 0
                    self.decided = True
                elif count1 > self.f:
                    self.x = 1
                    self.decided = True
                else:
                    if count0 + count1 > 0 and count0 > count1:   # quirk 9
                        self.x = 0
                    elif count0 + count1 > 0 and count0 < count1:
                        self.x = 1
                    else:
                        self.x = 0 if self.net.rng.random() > 0.5 else 1
                # global-halt probe (node.ts:119-145, sub-behavior 5e)
                self.net.schedule_halt_probe()
                self.k = k + 1                       # node.ts:147 — even if decided
                self.net.broadcast(self.k, self.x, "proposal phase")

    # /getState (node.ts:197-199)
    def get_state(self) -> dict:
        return {"killed": self.killed, "x": self.x,
                "decided": self.decided, "k": self.k}


class ExpressNetwork:
    """The whole network + its event loop.

    ``start()`` drains the message queue until the global-halt probe kills
    the network (all healthy decided), the round cap is exceeded (livelock
    scenarios), or the safety step cap trips.
    """

    def __init__(self, cfg, initial_values, faulty_list,
                 step_cap: Optional[int] = None):
        n = cfg.n_nodes
        f = cfg.n_faulty
        if cfg.trials != 1:
            raise ValueError(
                "the express oracle simulates a single trial; use the 'tpu' "
                "backend for Monte-Carlo (trials > 1) runs")
        if len(initial_values) != len(faulty_list) or n != len(initial_values):
            raise ValueError("Arrays don't match")          # launchNodes.ts:10-11
        if sum(bool(b) for b in faulty_list) != f:
            raise ValueError("faultyList doesnt have F faulties")  # :12-13
        self.n = n
        self.f = f
        self.max_rounds = cfg.max_rounds
        self.rng = random.Random(cfg.seed)
        self.order = cfg.oracle_order
        if self.order == "shuffle":
            # Dedicated delivery stream (seed derivation shared with the C++
            # oracle) so scheduling draws never perturb the coin stream.
            self.delivery_rng = random.Random((cfg.seed ^ 0x9E3779B9)
                                              & 0xFFFFFFFF)
            self.queue: list = []   # swap-pop bag; order is random anyway
        else:
            self.queue = deque()
        self._halt_pending = False
        self._started = False
        # Worst-case message volume per round is O(N^2) broadcasts (quirk-8
        # refires); the cap exists only to catch runaways and raises rather
        # than silently truncating the oracle.
        self._step_cap = step_cap if step_cap is not None else \
            max(500_000, 20 * n * n * cfg.max_rounds)
        self.nodes = [
            _ExpressNode(self, i, n, f, initial_values[i], bool(faulty_list[i]))
            for i in range(n)
        ]

    # fire-and-forget fetch POST /message to all N nodes, self included
    # (node.ts:72-80, 149-157, 173-185)
    def broadcast(self, k: int, x, message_type: str) -> None:
        if k > self.max_rounds:
            return  # round cap: bounds the livelock configurations
        for i in range(self.n):
            self.queue.append((i, k, x, message_type))

    def schedule_halt_probe(self) -> None:
        # The reference probe fires getState x N then maybe stop x N
        # (node.ts:119-145); both ride the same event loop as messages.
        self._halt_pending = True

    def _run_halt_probe(self) -> None:
        self._halt_pending = False
        # reachedFinality semantics: only decided === false blocks
        # (tests/utils.ts:22-24; faulty nodes' null is final).
        if all(nd.decided is not False for nd in self.nodes):
            for nd in self.nodes:
                nd.on_stop()

    # -- parity API ------------------------------------------------------
    @staticmethod
    def _check_trial(trial: int) -> None:
        if trial != 0:
            raise IndexError("express oracle has a single trial (index 0)")

    def status(self, node_id: int, trial: int = 0):
        self._check_trial(trial)
        return self.nodes[node_id].status()

    def start(self) -> None:
        # startConsensus: sequential /start fan-out (consensus.ts:3-8).
        # Idempotent so repeated /start routes don't re-broadcast.
        if self._started:
            return
        self._started = True
        for nd in self.nodes:
            nd.on_start()
        self._drain()

    def stop(self) -> None:
        for nd in self.nodes:
            nd.on_stop()

    def stop_node(self, node_id: int) -> None:
        self.nodes[node_id].on_stop()

    def inject_message(self, node_id: int, k, x, message_type) -> bool:
        """External message injection — the reference's POST /message
        surface (node.ts:43-163) on the oracle's event loop.

        The message is enqueued for ``node_id`` (under 'shuffle' its
        delivery position is drawn from the seeded delivery stream like
        any other pending message, so injected runs stay deterministic).
        If the network has already started, the event loop re-drains so
        the injection — and any cascade it triggers — settles before
        returning; pre-start injections sit ahead of the start
        broadcasts, one valid serialization of the reference's
        fire-and-forget concurrency.

        Returns False iff the target is killed at injection time: the
        reference's 200 response sits INSIDE the ``!killed`` guard
        (node.ts:44-161), so a killed node observably never answers —
        callers mirror that on the wire.
        """
        if self.nodes[node_id].killed:
            return False
        self.queue.append((node_id, k, x, message_type))
        if self._started:
            self._drain()
        return True

    def get_state(self, node_id: int, trial: int = 0) -> dict:
        self._check_trial(trial)
        return self.nodes[node_id].get_state()

    def get_states(self, trial: int = 0) -> List[dict]:
        self._check_trial(trial)
        return [nd.get_state() for nd in self.nodes]

    def close(self) -> None:
        self.queue.clear()

    # -- the event loop --------------------------------------------------
    def _drain(self) -> None:
        steps = 0
        q = self.queue
        shuffle = self.order == "shuffle"
        while q:
            if steps >= self._step_cap:
                raise RuntimeError(
                    f"express oracle exceeded its step cap ({self._step_cap} "
                    f"deliveries) before settling — results would be "
                    f"truncated mid-protocol; raise step_cap or lower "
                    f"max_rounds/N")
            if shuffle:
                # uniformly random pending message via swap-pop (identical
                # algorithm + RNG consumption as the C++ oracle's drain)
                j = self.delivery_rng.randrange(len(q))
                q[j], q[-1] = q[-1], q[j]
                dest, k, x, mtype = q.pop()
            else:
                dest, k, x, mtype = q.popleft()
            self.nodes[dest].on_message(k, x, mtype)
            if self._halt_pending:
                self._run_halt_probe()
            steps += 1
