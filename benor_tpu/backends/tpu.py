"""The TPU array-backed network: reference control surface over device arrays.

Implements the reference's observable contract (SURVEY.md N10) — the four
HTTP routes of src/nodes/node.ts served from [trials, N] tensors:

  /status   -> status(i)        node.ts:33-39
  /start    -> start()          node.ts:167-188 (+ consensus.ts:3-8 fan-out)
  /stop     -> stop()           node.ts:191-194 (+ consensus.ts:10-15)
  /getState -> get_state(i)     node.ts:197-199

``start()`` runs the whole consensus to termination (or the round cap) as
one compiled while-loop by default — the poll-until-finality loop of the
reference's tests (benorconsensus.test.ts:149-160) then observes an
already-final snapshot.  ``SimConfig(poll_rounds=c)`` instead steps the
loop in compiled c-round slices, republishing the snapshot between slices,
so pollers observe a live undecided network with growing k (the
reference's mid-run observability), with bit-identical final state.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np

from ..config import SimConfig
from ..sim import run_consensus
from ..state import FaultSpec, NetState, init_state, observable_state


def _decided_frac(state: NetState) -> Optional[float]:
    """Decided fraction over decided + LIVE undecided lanes — the same
    classes the flight recorder counts (state.recorder_snapshot_row), so
    the heartbeat's decided_frac does not change meaning with
    cfg.record: killed lanes never sit in the denominator."""
    decided = np.asarray(state.decided)
    undec = int((~decided & ~np.asarray(state.killed)).sum())
    d = int(decided.sum())
    return d / (d + undec) if (d + undec) else None


class TpuNetwork:
    """One simulated network (all trials of it) behind the parity API."""

    def __init__(self, cfg: SimConfig, initial_values, faulty_list,
                 crash_rounds=None, heartbeat_path: Optional[str] = None):
        # Validation order and messages mirror launchNodes.ts:10-13.
        if len(initial_values) != len(faulty_list) or \
                cfg.n_nodes != len(initial_values):
            raise ValueError("Arrays don't match")
        self.cfg = cfg
        #: Optional JSON-lines file the live-progress heartbeat
        #: (cfg.heartbeat_rounds; meshscope/heartbeat.py) appends to —
        #: what `python -m benor_tpu watch` tails.  Registry gauges are
        #: fed regardless; assignable after construction too.
        self.heartbeat_path = heartbeat_path
        self.faults = FaultSpec.from_faulty_list(cfg, faulty_list,
                                                 crash_rounds)
        self.state: NetState = init_state(cfg, initial_values, self.faults)
        self._faulty_list = list(faulty_list)
        self._started = False
        self.rounds_executed = 0
        #: Flight-recorder buffer (cfg.record): int32
        #: [max_rounds + 1, state.REC_WIDTH], filled by start().
        self._recorder = None
        #: Witness buffer (cfg.witness): int32
        #: [max_rounds + 1, W, k, state.WIT_WIDTH], filled by start().
        self._witness = None

    # -- /status (node.ts:33-39) ----------------------------------------
    def status(self, node_id: int, trial: int = 0):
        """Returns (body, http_code): ("faulty", 500) | ("live", 200)."""
        killed = bool(np.asarray(self.state.killed)[trial, node_id])
        return ("faulty", 500) if killed else ("live", 200)

    # -- /start (consensus.ts:3-8 -> node.ts:167-188) --------------------
    def start(self, on_slice=None) -> None:
        """Run consensus to termination (or the round cap).

        With ``cfg.poll_rounds > 0`` the compiled loop is stepped in slices
        of that many rounds and ``self.state`` is republished after every
        slice, so concurrent readers (the HTTP /getState route runs on its
        own thread) observe a live, still-undecided network with growing k —
        the reference's poll-during-run contract
        (benorconsensus.test.ts:149-160).  ``on_slice`` (optional callable,
        no args) fires after each snapshot publish; tests use it for
        deterministic mid-run observation without thread races.  Final
        state and rounds_executed are bit-identical to the one-shot path
        (sim.run_consensus_slice docstring; pinned in tests).
        """
        if self._started:
            return
        if on_slice is not None and not self.cfg.poll_rounds > 0:
            # a silently-never-fired callback is indistinguishable from a
            # real observability bug — fail loudly instead
            raise ValueError(
                "start(on_slice=...) requires SimConfig(poll_rounds > 0); "
                "this config runs one uninterrupted compiled loop")
        base_key = jax.random.key(self.cfg.seed)
        record, witness = self.cfg.record, self.cfg.witness
        if self.cfg.poll_rounds > 0:
            # sliced mid-run observability — single-device AND sharded
            # (the mesh case swaps in the shard_map'd slice primitive;
            # everything else, including bit-identity with the one-shot
            # path, is shared).  Under cfg.record / cfg.witness the
            # flight recorder and witness buffer thread across slices:
            # each published snapshot comes with the round history and
            # per-node witness filled so far (get_round_history /
            # get_witness serve them live to concurrent pollers).
            from ..models.benor import all_settled
            from ..sim import run_consensus_slice, start_state
            import jax.numpy as jnp
            if self.cfg.mesh_shape is not None:
                from ..parallel import (make_mesh,
                                        run_consensus_slice_sharded,
                                        shard_inputs)
                mesh = make_mesh(*self.cfg.mesh_shape)
                # shard ONCE, outside the slice loop: the slice's own
                # device_put is then a no-op per iteration (re-passing
                # the original host faults would re-transfer the [T, N]
                # fault arrays every poll_rounds rounds)
                self.state, faults_sh = shard_inputs(self.state,
                                                     self.faults, mesh)

                def slice_fn(st, r, until, rec, wit):
                    # heartbeat=False: this loop runs its OWN publisher
                    # below (it also owns the file plane) — the slice
                    # wrapper must not double-publish the same beat.
                    return run_consensus_slice_sharded(
                        self.cfg, st, faults_sh, base_key, mesh, r, until,
                        recorder=rec, witness=wit, heartbeat=False)
            else:
                def slice_fn(st, r, until, rec, wit):
                    return run_consensus_slice(
                        self.cfg, st, self.faults, base_key,
                        jnp.int32(r), jnp.int32(until), rec, wit)
            state = start_state(self.cfg, self.state)
            self.state = state               # k=1 visible (node.ts:172)
            heartbeat = None
            if self.cfg.heartbeat_rounds:
                # live progress plane (meshscope): host-side beats from
                # the slice boundary — the compiled slice is untouched
                from ..meshscope.heartbeat import HeartbeatPublisher
                from ..sim import heartbeat_due
                heartbeat = HeartbeatPublisher(
                    self.cfg, path=self.heartbeat_path,
                    label=f"net N={self.cfg.n_nodes}")
            r, rec, wit = 1, None, None
            while True:
                out = slice_fn(state, r, r + self.cfg.poll_rounds, rec,
                               wit)
                r_next, state = out[0], out[1]
                idx = 2
                if record:
                    rec = out[idx]
                    self._recorder = rec
                    idx += 1
                if witness:
                    wit = out[idx]
                    self._witness = wit
                self.state = state           # publish the live snapshot
                if on_slice is not None:
                    on_slice()
                rn = int(r_next)             # host sync: slice completed
                if heartbeat is not None and heartbeat_due(self.cfg,
                                                           r - 1, rn - 1):
                    heartbeat.publish(
                        rn - 1, recorder=rec,
                        decided_frac=(None if record else
                                      _decided_frac(state)))
                if (rn == r or rn > self.cfg.max_rounds
                        or bool(np.asarray(all_settled(state)))):
                    break
                r = rn
            if heartbeat is not None:
                heartbeat.close(rn - 1, recorder=rec)
            self.rounds_executed = rn - 1
        else:
            heartbeat = None
            if self.cfg.heartbeat_rounds:
                # One-shot run (poll_rounds=0): there are no slice
                # boundaries to beat from, but a silent no-op would leave
                # `watch` blocked on an empty file forever — publish the
                # single honest record the run has: its final state
                # (rate state starts here, before the compiled run).
                from ..meshscope.heartbeat import HeartbeatPublisher
                heartbeat = HeartbeatPublisher(
                    self.cfg, path=self.heartbeat_path,
                    label=f"net N={self.cfg.n_nodes}")
            if self.cfg.mesh_shape is not None:
                from ..parallel import make_mesh, run_consensus_sharded
                mesh = make_mesh(*self.cfg.mesh_shape)
                out = run_consensus_sharded(
                    self.cfg, self.state, self.faults, base_key, mesh)
            else:
                out = run_consensus(self.cfg, self.state, self.faults,
                                    base_key)
            self.rounds_executed = int(out[0])
            self.state = out[1]
            idx = 2
            if record:
                self._recorder = out[idx]
                idx += 1
            if witness:
                self._witness = out[idx]
            if heartbeat is not None:
                heartbeat.close(self.rounds_executed,
                                recorder=self._recorder)
        self._started = True

    # -- /stop (consensus.ts:10-15 -> node.ts:191-194) -------------------
    def stop(self) -> None:
        self.state = NetState(
            x=self.state.x, decided=self.state.decided, k=self.state.k,
            killed=jax.numpy.ones_like(self.state.killed))

    def stop_node(self, node_id: int) -> None:
        """Single node's /stop route (node.ts:191-194), all trials."""
        self.state = NetState(
            x=self.state.x, decided=self.state.decided, k=self.state.k,
            killed=self.state.killed.at[:, node_id].set(True))

    # -- /getState (node.ts:197-199) -------------------------------------
    def get_state(self, node_id: int, trial: int = 0) -> dict:
        return observable_state(self.cfg, self.state, self.faults,
                                node_id, trial)

    # -- flight recorder (cfg.record) -------------------------------------
    def get_round_history(self,
                          since_round: Optional[int] = None) -> List[dict]:
        """Per-round telemetry rows next to /getState (one dict per row,
        state.REC_COLUMNS keys plus "round") — the observable surface of
        the flight recorder.  Requires SimConfig(record=True); before
        start() the history is just the row-0 snapshot-to-come (empty
        list).  Under poll_rounds the history grows live between slices,
        so a concurrent poller watches decide velocity round by round.

        ``since_round`` is the incremental CURSOR (served over HTTP as
        GET /getRoundHistory?since_round=N): only rows with a STRICTLY
        greater round index return, so a poller passing the last round
        it has seen receives exactly the new rows — an empty list when
        the cursor sits at or past the end, and (because rows key on
        their TRUE round index) the post-gap rows when the cursor falls
        inside a fresh-buffer resume's unwritten gap.
        """
        if not self.cfg.record:
            raise ValueError(
                "get_round_history() requires SimConfig(record=True): "
                "the flight recorder is off and no round history was "
                "captured (cfg.debug streams host callbacks instead, but "
                "demotes the fused-pallas regime — see README "
                "Observability)")
        from ..utils.metrics import round_history_rows
        if self._recorder is None:
            return []
        return round_history_rows(np.asarray(self._recorder),
                                  since_round=since_round)

    # -- witness trace (cfg.witness) ---------------------------------------
    def get_witness(self) -> List[dict]:
        """Per-node forensic witness rows beside get_round_history() (one
        dict per watched (round, trial, node): state.WIT_COLUMNS keys plus
        "round"/"trial"/"node" global ids) — the observable surface of
        the witness recorder.  Requires SimConfig(witness_trials=...);
        before start() the history is empty.  Under poll_rounds the
        witness grows live between slices, same contract as the round
        history, so a concurrent poller watches each watched lane's
        evidence chain round by round.  Machine-check the same buffer
        with benor_tpu.audit.
        """
        if not self.cfg.witness:
            raise ValueError(
                "get_witness() requires SimConfig(witness_trials=..., "
                "witness_nodes=k): the witness recorder is off and no "
                "per-node trace was captured (see README Observability)")
        from ..audit import witness_rows
        from ..state import witness_node_ids
        if self._witness is None:
            return []
        return witness_rows(np.asarray(self._witness),
                            self.cfg.witness_trials,
                            witness_node_ids(self.cfg))

    def get_states(self, trial: int = 0) -> List[dict]:
        # Bulk path: one device->host transfer per array, then N dict builds
        # (observable_state per node would re-transfer the [T, N] arrays
        # 4N times).
        from ..config import VALQ
        x = np.asarray(self.state.x)[trial]
        decided = np.asarray(self.state.decided)[trial]
        k = np.asarray(self.state.k)[trial]
        killed = np.asarray(self.state.killed)[trial]
        birth_faulty = np.asarray(self.faults.faulty)[trial] \
            if self.cfg.fault_model == "crash" else \
            np.zeros(self.cfg.n_nodes, bool)
        out = []
        for i in range(self.cfg.n_nodes):
            if birth_faulty[i]:
                out.append({"killed": True, "x": None, "decided": None,
                            "k": None})
            else:
                xi = int(x[i])
                out.append({"killed": bool(killed[i]),
                            "x": "?" if xi == VALQ else xi,
                            "decided": bool(decided[i]), "k": int(k[i])})
        return out

    def close(self) -> None:
        pass
