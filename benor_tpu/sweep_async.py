"""Compile-ahead/execute-behind bucket scheduler for the batched sweep.

Sweepscope's pipeline model (``sweepscope/gate.py``) prices exactly one
overlap: while the device executes bucket k, the host could already be
preparing and AOT-compiling bucket k+1 — XLA compilation is pure host
work and releases the GIL, so a plain worker THREAD captures the whole
modeled headroom with no serialization risk on the device side.  This
module is that scheduler, deliberately minimal:

  * ONE worker thread runs the build leg (prepare + fingerprint +
    journal match + stacked tensors + AOT compile) strictly in bucket
    order.  Per-bucket ``count_backend_compiles`` scopes open only on
    the worker, and the executing thread never holds one — the counter
    listener is process-global and fans events to every active scope,
    so a main-thread scope during execute would steal the worker's
    compile attributions.
  * The handoff queue holds AT MOST ONE built bucket
    (``Queue(maxsize=1)``), bounding live memory at two buckets' input
    tensors (one executing + one staged) — the same footprint argument
    the donation scheme makes per bucket.
  * The consumer drains plans strictly in build order (single worker +
    FIFO queue), so everything ordered — device execute, fetch, journal
    records, heartbeat beats, verbose lines — happens on the caller's
    thread in bucket order.  Results, per-bucket compile counts and
    journal contents are bit-identical to serial dispatch
    (tests/test_gridpipe.py pins both); only the wall clock changes.

A worker exception is re-raised on the consuming thread at the bucket
it belongs to, so error behavior matches the serial loop.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Sequence, Tuple

__all__ = ["pipeline_buckets"]

#: Queue depth of the compile-ahead handoff: 1 staged bucket.
PIPELINE_DEPTH = 1


def pipeline_buckets(work: Sequence[Tuple], build: Callable,
                     depth: int = PIPELINE_DEPTH) -> Iterator:
    """Yield ``build(*item)`` for each work item, building one ahead.

    ``build`` runs on a single daemon worker thread, strictly in work
    order; plans are yielded in the same order on the caller's thread.
    The caller executes plan k while the worker builds plan k+1 —
    the compile-ahead/execute-behind overlap.  A ``build`` exception
    surfaces here, in order, as if the loop were serial.
    """
    q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
    stop = threading.Event()
    _done = object()

    def _worker():
        try:
            for item in work:
                if stop.is_set():
                    return
                q.put(("plan", build(*item)))
        # benorlint: allow-broad-except — cross-thread relay boundary:
        # whatever the build raised (including lowering/backend
        # failures) is re-raised VERBATIM on the consumer thread, in
        # bucket order — nothing is swallowed or demoted
        except BaseException as e:
            q.put(("raise", e))
            return
        q.put(("done", _done))

    t = threading.Thread(target=_worker, name="sweep-compile-ahead",
                         daemon=True)
    t.start()
    try:
        while True:
            tag, payload = q.get()
            if tag == "done":
                break
            if tag == "raise":
                raise payload
            yield payload
    finally:
        # normal exit or consumer abandoned mid-stream (its execute
        # raised): tell the worker to stop building, free a possibly
        # blocked put, and let the daemon thread wind down — a build
        # already in flight finishes (compilation is uninterruptible)
        # but no further bucket starts
        stop.set()
        try:
            q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=60.0)
