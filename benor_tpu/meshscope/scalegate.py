"""Scaling-regression detection: manifest-vs-baseline efficiency bands.

Deliberately jax-free (stdlib only), same contract as
perfscope/baseline.py: ``tools/check_scaling_regression.py`` loads this
module by FILE PATH so a CI image (or an operator's laptop) can gate a
scaling manifest without initializing any backend.  An import creep here
breaks that gate immediately.

What gates (all structural / dimensionless — wall clocks are carried in
every manifest for trend reading but never banded):

  * a baseline row (one (devices, n_nodes) ladder rung) disappearing;
  * ``efficiency`` — throughput vs d x the 1-device row — dropping below
    ``efficiency_band`` x the baseline's (missing/zero where the
    baseline had substance is the WORST collapse, the same rule
    perfscope applies to ``node_rounds_per_sec=0.0``);
  * ``straggler_ratio`` — max/median per-shard step time — at or above
    the ABSOLUTE trip ``STRAGGLER_TRIP`` (a straggling shard is a
    health event regardless of what the baseline machine looked like);
  * ``node_rounds_per_sec`` going to zero where the baseline had
    substance (a degenerated capture);
  * ``rounds`` changing at the same seed + scale (determinism drift,
    mirroring the perf gate's rounds_executed pin).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

#: Max allowed max/median per-shard step-time ratio before the imbalance
#: detector (meshscope/telemetry.py) and the gate both trip.  A 2x
#: straggler — one shard taking twice the median step time — is the
#: canonical fixture and sits comfortably past this.
STRAGGLER_TRIP = 1.5

#: Default floor on new_efficiency / baseline_efficiency: scaling
#: efficiency is a ratio of ratios on the SAME ladder shape, so it is far
#: more machine-stable than a wall clock — 0.8 tolerates CPU-smoke noise
#: while catching a real parallelism collapse.
EFFICIENCY_BAND = 0.8


class IncomparableScaling(ValueError):
    """Raised when manifest and baseline describe different ladders
    (platform / mode / axis / scale mismatch) — comparing them would
    produce confident nonsense, so the gate refuses instead."""


@dataclasses.dataclass
class ScalingFinding:
    """One out-of-band scaling metric."""

    devices: int
    metric: str
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _require_comparable(new: dict, base: dict) -> None:
    for key in ("kind", "schema_version", "platform", "mode", "axis"):
        if new.get(key) != base.get(key):
            raise IncomparableScaling(
                f"{key}: manifest has {new.get(key)!r}, baseline has "
                f"{base.get(key)!r}")
    if new.get("scale") != base.get("scale"):
        raise IncomparableScaling(
            f"scale: manifest {new.get('scale')} vs baseline "
            f"{base.get('scale')} — recapture at the baseline scale or "
            f"re-baseline")


def _rows_by_rung(manifest: dict) -> Dict[Tuple, dict]:
    # mesh_shape joins the key so a 2D grid rung (e.g. (2,2)) and a 1D
    # rung at the same device count / n_nodes stay distinct rungs
    return {(int(r["devices"]), int(r["n_nodes"]),
             tuple(int(s) for s in (r.get("mesh_shape") or ()))): r
            for r in manifest.get("rows", [])}


def compare_scaling(new: dict, base: dict,
                    efficiency_band: float = EFFICIENCY_BAND,
                    straggler_trip: float = STRAGGLER_TRIP
                    ) -> List[ScalingFinding]:
    """All out-of-band rows of ``new`` vs ``base`` (empty = gate passes).
    Raises IncomparableScaling when the two documents do not describe the
    same ladder."""
    _require_comparable(new, base)
    out: List[ScalingFinding] = []
    new_rows = _rows_by_rung(new)
    base_rows = _rows_by_rung(base)
    for rung, old in sorted(base_rows.items()):
        d, n, shape = rung
        row = new_rows.get(rung)
        if row is None:
            out.append(ScalingFinding(
                d, "row",
                f"rung devices={d} n_nodes={n}"
                + (f" mesh={shape}" if shape else "")
                + ": present in baseline but missing from the manifest "
                  "— a ladder rung disappeared"))
            continue
        if row.get("rounds") != old.get("rounds"):
            out.append(ScalingFinding(
                d, "rounds",
                f"rung devices={d}: rounds {row.get('rounds')} vs "
                f"baseline {old.get('rounds')} — same seed + scale must "
                f"execute the same rounds (determinism drift)"))
        old_eff = old.get("efficiency")
        new_eff = row.get("efficiency")
        if old_eff:
            if not new_eff:
                out.append(ScalingFinding(
                    d, "efficiency",
                    f"rung devices={d}: scaling efficiency is "
                    f"{new_eff!r} where the baseline had {old_eff} — "
                    f"missing or zero efficiency is the worst possible "
                    f"collapse"))
            elif new_eff < old_eff * efficiency_band:
                out.append(ScalingFinding(
                    d, "efficiency",
                    f"rung devices={d}: efficiency {new_eff} vs "
                    f"baseline {old_eff} "
                    f"({new_eff / old_eff:.2f}x < band "
                    f"{efficiency_band}x) — scaling regressed"))
        if old.get("node_rounds_per_sec") and \
                not row.get("node_rounds_per_sec"):
            out.append(ScalingFinding(
                d, "node_rounds_per_sec",
                f"rung devices={d}: node_rounds_per_sec went to zero "
                f"(baseline {old['node_rounds_per_sec']:.3g}) — the "
                f"capture likely degenerated"))
        ratio = row.get("straggler_ratio")
        if ratio is not None and ratio >= straggler_trip:
            out.append(ScalingFinding(
                d, "straggler_ratio",
                f"rung devices={d}: straggler_ratio {ratio} >= trip "
                f"{straggler_trip} — one shard's step time is "
                f"{ratio:.2f}x the median; the mesh is imbalanced"))
    # The straggler trip is ABSOLUTE (a health event, not a band), so it
    # must also fire on manifest rungs the baseline never captured —
    # e.g. `scale --mesh 1,2,4` against a d=1,2 baseline.
    for rung, row in sorted(new_rows.items()):
        if rung in base_rows:
            continue
        d = rung[0]
        ratio = row.get("straggler_ratio")
        if ratio is not None and ratio >= straggler_trip:
            out.append(ScalingFinding(
                d, "straggler_ratio",
                f"rung devices={d} (not in baseline): straggler_ratio "
                f"{ratio} >= trip {straggler_trip} — one shard's step "
                f"time is {ratio:.2f}x the median; the mesh is "
                f"imbalanced"))
    return out
