"""Per-shard runtime telemetry: memory watermarks, collective cost
attribution, straggler detection, Perfetto shard tracks.

Everything here runs HOST-SIDE and out-of-band of the compiled
executables — sampling memory or probing a shard never enters a trace,
so the off path stays bit-identical in results and compile counts
(the meshscope house rule; tests/test_meshscope.py).

  sample_device_memory     live device-memory watermarks into gauge
                           families: ``device.memory_stats()`` where the
                           backend serves it (TPU), a live-array
                           per-device byte sum everywhere else (CPU).
  collective_bytes         per-round psum/collective byte attribution
                           DERIVED from the declarative layout tables —
                           state.REC_LAYOUT / WIT_LAYOUT and the pallas
                           kernels' PARTIAL_COLS — not hand-counted, so
                           a relayout (the tables are the single source
                           of truth since PR 4) re-prices the
                           collectives automatically.
  probe_shard_step_times   per-device steady-state step-time probe: one
                           warm fixed-size compute kernel timed on every
                           device of the mesh.  Relative shard health is
                           the quantity straggler detection needs; the
                           absolute step time of the real run lands in
                           the scaling rows (meshscope/scaling.py).
  detect_stragglers        max/median imbalance ratio over per-shard
                           step times -> gauge + a trip counter when the
                           ratio crosses scalegate.STRAGGLER_TRIP.
  export_shard_trace       the per-shard samples as one Perfetto track
                           per shard (load next to a jax.profiler
                           capture or metrics.export_chrome_trace).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils import metrics
from .scalegate import STRAGGLER_TRIP

# --------------------------------------------------------------------------
# Device-memory watermarks
# --------------------------------------------------------------------------


def sample_device_memory(registry: Optional[metrics.MetricsRegistry] = None
                         ) -> List[dict]:
    """Sample per-device memory into gauges; returns one dict per device.

    Two sources, best first: ``device.memory_stats()`` (bytes_in_use /
    peak_bytes_in_use — the real HBM watermark on TPU backends) and a
    sum of ``jax.live_arrays()`` bytes per device (what the CPU backend
    can attribute).  Gauge families: ``meshscope.mem.live_bytes.d<i>``
    always; ``meshscope.mem.bytes_in_use.d<i>`` /
    ``meshscope.mem.peak_bytes.d<i>`` when the backend serves stats.
    """
    import jax
    registry = metrics.REGISTRY if registry is None else registry
    live: Dict[int, int] = {}
    for arr in jax.live_arrays():
        for shard in getattr(arr, "addressable_shards", []):
            nbytes = getattr(shard.data, "nbytes", 0)
            live[shard.device.id] = live.get(shard.device.id, 0) + nbytes
    out = []
    for dev in jax.local_devices():
        row = {"device": dev.id, "platform": dev.platform,
               "live_bytes": int(live.get(dev.id, 0))}
        registry.gauge(f"meshscope.mem.live_bytes.d{dev.id}").set(
            row["live_bytes"])
        stats_fn = getattr(dev, "memory_stats", None)
        stats = None
        if stats_fn is not None:
            try:
                stats = stats_fn()
            except (RuntimeError, NotImplementedError):
                stats = None         # backend has no allocator stats
        if stats:
            for key, name in (("bytes_in_use", "bytes_in_use"),
                              ("peak_bytes_in_use", "peak_bytes")):
                if key in stats:
                    row[name] = int(stats[key])
                    registry.gauge(
                        f"meshscope.mem.{name}.d{dev.id}").set(stats[key])
        out.append(row)
    return out


# --------------------------------------------------------------------------
# Collective byte attribution from the declarative layout tables
# --------------------------------------------------------------------------


def collective_bytes(cfg, registry: Optional[metrics.MetricsRegistry] = None
                     ) -> Dict[str, int]:
    """Per-ROUND collective payload bytes, by family, for one config.

    This is a cost MODEL of what crosses the mesh per round and node
    shard, priced from the same declarative tables the kernels derive
    their layouts from (PR 4's whole point — a relayout is a table edit,
    and this attribution follows it):

      tally_psum        histogram path: one int32 [T, 3] class histogram
                        psum per phase (2 phases/round)
      tally_allgather   dense path instead: int8 [T, N] sent values +
                        bool [T, N] alive per phase
      pallas_partials   fused-round regime: the per-tile [T, PARTIAL_COLS]
                        int32 reduction rows psum'd between kernels
                        (carries tallies + recorder + witness partials,
                        replacing the families above)
      termination_psum  the scalar all-settled predicate, every round
      recorder_psum     cfg.record: one [REC_WIDTH] int32 row globalized
                        before its write
      witness_psum      cfg.witness: one [W, k, WIT_WIDTH] int32 row

    Families are set as ``meshscope.collective.<family>_bytes`` gauges;
    the returned dict adds ``total`` (bytes/round).
    """
    from ..ops.pallas_round import PARTIAL_COLS
    from ..ops.tally import pallas_round_active
    from ..state import REC_WIDTH, WIT_WIDTH
    registry = metrics.REGISTRY if registry is None else registry
    T, N = cfg.trials, cfg.n_nodes
    phases = 2                                   # proposal + vote
    fam: Dict[str, int] = {}
    if pallas_round_active(cfg):
        # the packed loop's only inter-shard traffic: the per-tile
        # partial-column rows (tallies, recorder cols 5-11, witness
        # blocks) reduced across the node axis, once per kernel pass
        fam["pallas_partials"] = phases * T * PARTIAL_COLS * 4
    elif cfg.resolved_path == "dense":
        fam["tally_allgather"] = phases * (T * N * 1 + T * N * 1)
    else:
        fam["tally_psum"] = phases * T * 3 * 4
    fam["termination_psum"] = 4
    if cfg.record and not pallas_round_active(cfg):
        fam["recorder_psum"] = REC_WIDTH * 4
    if cfg.witness and not pallas_round_active(cfg):
        fam["witness_psum"] = (len(cfg.witness_trials)
                               * cfg.witness_nodes * WIT_WIDTH * 4)
    for name, nbytes in fam.items():
        registry.gauge(f"meshscope.collective.{name}_bytes").set(nbytes)
    fam["total"] = sum(fam.values())
    registry.gauge("meshscope.collective.total_bytes").set(fam["total"])
    return fam


# --------------------------------------------------------------------------
# Straggler / imbalance detection
# --------------------------------------------------------------------------


@dataclass
class StragglerReport:
    """Per-shard step times + the imbalance verdict."""

    step_times_s: List[float]
    ratio: float                 # max / median
    stragglers: List[int]        # shard indices at/above the trip
    tripped: bool

    def to_dict(self) -> dict:
        return {"step_times_s": [round(t, 6) for t in self.step_times_s],
                "ratio": round(self.ratio, 4),
                "stragglers": self.stragglers, "tripped": self.tripped}


def step_time_imbalance(step_times: Sequence[float]) -> float:
    """max/median shard step-time ratio (1.0 = perfectly balanced)."""
    t = np.asarray(list(step_times), dtype=np.float64)
    if t.size == 0:
        return 1.0
    med = float(np.median(t))
    return float(np.max(t) / med) if med > 0 else 1.0


def detect_stragglers(step_times: Sequence[float],
                      trip: float = STRAGGLER_TRIP,
                      registry: Optional[metrics.MetricsRegistry] = None
                      ) -> StragglerReport:
    """Imbalance verdict over per-shard step times.

    Sets ``meshscope.straggler_ratio`` (gauge) every call and bumps the
    ``meshscope.straggler_detected`` counter when the max/median ratio
    crosses ``trip`` — the same threshold the scaling gate applies to a
    manifest's ``straggler_ratio`` (scalegate.STRAGGLER_TRIP), so a
    live detection and a gated capture agree on what "imbalanced" means.
    """
    registry = metrics.REGISTRY if registry is None else registry
    times = [float(t) for t in step_times]
    ratio = step_time_imbalance(times)
    med = float(np.median(np.asarray(times))) if times else 0.0
    stragglers = [i for i, t in enumerate(times)
                  if med > 0 and t / med >= trip]
    tripped = ratio >= trip
    registry.gauge("meshscope.straggler_ratio").set(ratio)
    if tripped:
        registry.counter("meshscope.straggler_detected").inc()
    return StragglerReport(step_times_s=times, ratio=ratio,
                           stragglers=stragglers, tripped=tripped)


# --------------------------------------------------------------------------
# Per-device step-time probe
# --------------------------------------------------------------------------


def probe_shard_step_times(mesh=None, devices=None, reps: int = 3,
                           size: int = 256,
                           registry: Optional[
                               metrics.MetricsRegistry] = None
                           ) -> List[float]:
    """Steady-state step-time probe, one value per mesh device.

    Runs a fixed [size, size] f32 matmul ``reps`` times on EVERY device
    of the mesh (warm-up execution first, so the per-device executable
    is compiled out of the timed window) and returns each device's MIN
    wall time, in mesh order — min, not mean, because the probe wants
    the device's capability floor: a genuinely throttled chip is slow
    on every rep, while host-scheduler noise (virtual CPU devices share
    cores) only inflates some reps.  The probe is deliberately
    workload-independent: straggler detection wants RELATIVE shard
    health, which a fixed kernel measures without re-running the
    protocol.  Gauges: ``meshscope.shard.step_s.d<i>``.
    """
    import jax
    import jax.numpy as jnp
    registry = metrics.REGISTRY if registry is None else registry
    if devices is None:
        devices = (list(np.asarray(mesh.devices).flat)
                   if mesh is not None else jax.local_devices())
    a_host = np.ones((size, size), np.float32)
    times: List[float] = []
    for dev in devices:
        a = jax.device_put(a_host, dev)
        jnp.dot(a, a).block_until_ready()        # warm-up: compile + run
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jnp.dot(a, a).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        times.append(best)
        registry.gauge(f"meshscope.shard.step_s.d{dev.id}").set(best)
    return times


# --------------------------------------------------------------------------
# Perfetto per-shard tracks
# --------------------------------------------------------------------------


def export_shard_trace(path: str, samples: Sequence[Sequence[float]],
                       label: str = "shard") -> int:
    """Write per-shard step-time samples as a Chrome-trace/Perfetto file:
    one track (tid) per shard, one complete event per timed step, laid
    end to end — a straggling shard is visibly longer on its track.
    ``samples[i]`` is shard i's per-step durations in seconds.  Returns
    the event count; load next to a jax.profiler capture or a
    metrics.export_chrome_trace file in https://ui.perfetto.dev.
    """
    events = []
    for i, steps in enumerate(samples):
        ts = 0.0
        for j, dur in enumerate(steps):
            events.append({
                "name": f"step {j}", "ph": "X", "pid": 0,
                "tid": f"{label} {i}",
                "ts": ts * 1e6, "dur": float(dur) * 1e6,
            })
            ts += float(dur)
    with open(path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
    return len(events)
