"""Live progress plane: heartbeats from long sliced runs & batched sweeps.

An hour-long pod-scale sweep is a black box between its first compile
and its final summary — nothing reports rounds/sec, decided fraction or
an ETA while the compiled loops run.  The heartbeat closes that gap
HOST-SIDE, between slices/buckets, from buffers the run already
publishes (the flight-recorder rows, the slice round cursor): nothing
here enters a trace, so heartbeat off — and on — is bit-identical in
results and compile counts (tests/test_meshscope.py pins it).

Three publication surfaces per heartbeat:

  * gauges in utils/metrics.REGISTRY (``heartbeat.round``,
    ``heartbeat.rounds_per_sec``, ``heartbeat.decided_frac``,
    ``heartbeat.eta_s``, ``heartbeat.progress``) plus a
    ``heartbeat.published`` counter — every exporter sees them;
  * an append-only JSON-lines file (one record per beat, written
    line-atomically via metrics.append_jsonl) that the
    ``python -m benor_tpu watch`` CLI tails from another process;
  * TpuNetwork.get_round_history(since_round=...) /
    GET /getRoundHistory?since_round=N — the cursor-based incremental
    round-history feed the HTTP control plane serves between slices.

Cadence is SimConfig.heartbeat_rounds (0 = off): a beat fires whenever
the run's round cursor crosses a multiple of it (sim.heartbeat_due).
The batched sweep engine beats per bucket instead (buckets, not rounds,
are its unit of progress).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..utils import metrics

#: Record tag on every heartbeat JSON line (what ``watch`` filters on).
HEARTBEAT_KIND = "heartbeat"


def _decided_frac_from_recorder(recorder) -> Optional[float]:
    """Decided fraction of non-killed lanes, from the LAST written
    flight-recorder row (None when no row was written yet)."""
    from ..state import (REC_DECIDED, REC_UNDEC0, REC_UNDEC1, REC_UNDECQ)
    rows = metrics.executed_rows(recorder)
    if rows.shape[0] == 0:
        return None
    last = rows[-1]
    undec = (last[REC_UNDEC0] + last[REC_UNDEC1] + last[REC_UNDECQ])
    denom = int(last[REC_DECIDED] + undec)
    return float(last[REC_DECIDED] / denom) if denom else None


class HeartbeatPublisher:
    """Stateful per-run heartbeat emitter (rate + ETA need history).

    ``path`` (optional) is the append-only JSON-lines file; gauges feed
    the registry regardless.  Thread-safe: the poll loop and any
    concurrent exporter serialize on the registry/export locks
    (utils/metrics.py)."""

    def __init__(self, cfg, path: Optional[str] = None,
                 label: str = "run",
                 registry: Optional[metrics.MetricsRegistry] = None):
        self.cfg = cfg
        self.path = path
        self.label = label
        self.registry = metrics.REGISTRY if registry is None else registry
        self._t0 = time.perf_counter()
        self._last_t = self._t0
        self._last_round = 0
        self._published = 0

    def publish(self, round_: Optional[int] = None, recorder=None,
                decided_frac: Optional[float] = None,
                progress: Optional[float] = None,
                rate: Optional[float] = None, done: bool = False,
                **extra) -> dict:
        """Emit one beat; returns the record written/registered.

        ``round_`` is the run's round cursor (rounds/sec and the ETA
        derive from its motion); ``recorder`` (a flight-recorder buffer)
        supplies the decided fraction when ``decided_frac`` is not given;
        ``progress`` in [0, 1] serves drivers whose unit is not rounds
        (the batched sweep passes buckets-done / buckets-total).
        """
        now = time.perf_counter()
        rps = rate
        eta = None
        if round_ is not None and rps is None:
            dt = now - self._last_t
            dr = round_ - self._last_round
            if dr > 0 and dt > 0:
                rps = dr / dt
            elif round_ and (now - self._t0) > 0:
                rps = round_ / (now - self._t0)
        if decided_frac is None and recorder is not None:
            decided_frac = _decided_frac_from_recorder(
                np.asarray(recorder))
        if round_ is not None and rps:
            remaining = max(0, self.cfg.max_rounds - round_)
            if decided_frac is not None and decided_frac >= 1.0:
                remaining = 0
            eta = remaining / rps
        if progress is None and round_ is not None and self.cfg.max_rounds:
            progress = min(1.0, round_ / self.cfg.max_rounds)
        if done:
            eta, progress = 0.0, 1.0
        record = {
            "kind": HEARTBEAT_KIND, "label": self.label,
            "round": (int(round_) if round_ is not None else None),
            "max_rounds": int(self.cfg.max_rounds),
            "rounds_per_sec": (round(float(rps), 4)
                               if rps is not None else None),
            "decided_frac": (round(float(decided_frac), 6)
                             if decided_frac is not None else None),
            "eta_s": round(float(eta), 3) if eta is not None else None,
            "progress": (round(float(progress), 6)
                         if progress is not None else None),
            "elapsed_s": round(now - self._t0, 3),
            "done": bool(done),
        }
        record.update(extra)
        g = self.registry.gauge
        if round_ is not None:
            g("heartbeat.round").set(round_)
            self._last_round = int(round_)
        if rps is not None:
            g("heartbeat.rounds_per_sec").set(rps)
        if decided_frac is not None:
            g("heartbeat.decided_frac").set(decided_frac)
        if eta is not None:
            g("heartbeat.eta_s").set(eta)
        if progress is not None:
            g("heartbeat.progress").set(progress)
        self.registry.counter("heartbeat.published").inc()
        self._last_t = now
        self._published += 1
        if self.path:
            metrics.append_jsonl(self.path, record)
        return record

    def close(self, round_: Optional[int] = None, recorder=None,
              decided_frac: Optional[float] = None) -> dict:
        """Final beat with ``done: true`` (what ``watch`` stops on)."""
        return self.publish(round_=round_, recorder=recorder,
                            decided_frac=decided_frac, done=True)


# --------------------------------------------------------------------------
# Slice-level publishing for the sharded / multihost regimes: the slice
# wrappers (parallel/sharded.py, parallel/multihost.py) call this after
# every compiled slice when cfg.heartbeat_rounds is armed — registry
# gauges only (the file plane belongs to the driver that owns the path,
# e.g. TpuNetwork.start's poll loop).  Keyed per label so concurrent
# runs don't share rate state.
# --------------------------------------------------------------------------

_SLICE_LOCK = threading.Lock()
#: label -> (publisher, round cursor BEFORE the next expected slice) —
#: the cursor advances on EVERY boundary (not just cadence-crossing
#: ones), so a fresh run is recognized by its from_round not continuing
#: where the previous slice stopped.
_SLICE_PUBS: Dict[str, Tuple[HeartbeatPublisher, int]] = {}


def publish_slice_heartbeat(cfg, next_round, recorder=None,
                            label: str = "slice",
                            from_round=None) -> Optional[dict]:
    """Registry-only heartbeat from one slice boundary; returns the
    record when the cadence fired, else None.  ``next_round`` may be a
    device scalar (the slice output) — it is fetched, which is the host
    sync the caller is about to do anyway at a slice boundary.

    ``from_round`` (the slice's entry cursor) distinguishes a NEW run
    from a continuation: a publisher cached under ``label`` is only
    reused when the slice picks up exactly where the previous one
    stopped — otherwise its rate state would span the idle/compile gap
    between two runs and the first beat of the new run would report a
    near-zero rounds/sec."""
    from ..sim import heartbeat_due
    r = int(next_round) - 1          # rounds fully executed so far
    prev = None if from_round is None else int(from_round) - 1
    with _SLICE_LOCK:
        pub, seen = _SLICE_PUBS.get(label, (None, 0))
        if (pub is None or pub.cfg != cfg or r < pub._last_round
                or (prev is not None and prev != seen)):
            pub = HeartbeatPublisher(cfg, label=label)
        _SLICE_PUBS[label] = (pub, r)
    if not heartbeat_due(cfg, pub._last_round, r):
        return None
    return pub.publish(round_=r, recorder=recorder)


def publish_sweep_heartbeat(cfg, done: int, total: int,
                            publisher: Optional[HeartbeatPublisher] = None,
                            path: Optional[str] = None,
                            bucket_index: Optional[int] = None) -> dict:
    """Per-bucket heartbeat for the batched sweep engine
    (sweep.run_curve_batched): progress = points finished / points
    total.  Returns the record; pass a publisher to keep one rate state
    across buckets (the engine does).  ``bucket_index`` stamps which
    bucket just completed — under pipelined dispatch the beats still
    arrive in completion order (the engine publishes only from its
    ordered thread), and the index makes that order auditable from the
    ``watch`` tail."""
    pub = publisher if publisher is not None else HeartbeatPublisher(
        cfg, path=path, label="sweep")
    extra = {}
    if bucket_index is not None:
        extra["bucket_index"] = int(bucket_index)
    return pub.publish(progress=done / max(total, 1),
                       done=(done >= total),
                       points_done=int(done), points_total=int(total),
                       **extra)


# --------------------------------------------------------------------------
# Reading side: what `python -m benor_tpu watch` runs.
# --------------------------------------------------------------------------


def read_records(path: str,
                 kinds: Optional[Tuple[str, ...]] = None) -> List[dict]:
    """Parse a JSON-lines file -> records, in file order.

    The MIXED-KIND reader behind ``python -m benor_tpu watch``: a
    heartbeat file, a sweep journal (benor_tpu/sweepscope/journal.py)
    or one file carrying both interleave freely — ``kinds`` filters
    when given, otherwise every parseable record passes through (a
    record without a ``kind`` is wrapped as ``{"kind": None, "raw":
    value}``, as is any non-dict JSON value, so unknown producers are
    surfaced raw rather than dropped).  A torn (still-being-written or
    killed-mid-append) line is skipped, not an error — the writers
    append line-atomically, but a reader can still catch the file
    between the open and the flush of a line, and a SIGKILLed writer
    legitimately leaves a partial tail."""
    out: List[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue             # torn tail line; next poll re-reads
            if not isinstance(rec, dict) or "kind" not in rec:
                rec = {"kind": None, "raw": rec}
            if kinds is None or rec.get("kind") in kinds:
                out.append(rec)
    return out


def read_heartbeats(path: str) -> List[dict]:
    """Parse a heartbeat JSON-lines file -> heartbeat records only, in
    file order (the kind-filtered view of :func:`read_records`)."""
    return read_records(path, kinds=(HEARTBEAT_KIND,))


def _read_new_records(path: str, offset: int,
                      kinds: Optional[Tuple[str, ...]]
                      ) -> Tuple[List[dict], int]:
    """Parse only the bytes appended since ``offset`` -> (new records,
    new offset).  The tail engine's incremental read: a sweep-journal
    bucket record can carry hundreds of KB of per-point payload, so
    re-parsing the whole file every poll would make the watch loop
    O(file^2) over a long sweep.  The offset only ever advances past
    COMPLETE (newline-terminated) lines — a torn tail (mid-append, or
    a SIGKILLed writer's last gasp) is left in place and re-read on the
    next poll; a complete-but-unparseable line is skipped permanently,
    like :func:`read_records`."""
    with open(path, "rb") as fh:
        fh.seek(offset)
        chunk = fh.read()
    nl = chunk.rfind(b"\n")
    if nl < 0:
        return [], offset
    out: List[dict] = []
    for raw in chunk[:nl + 1].splitlines():
        line = raw.strip()
        if not line:
            continue
        try:
            rec = json.loads(line.decode("utf-8", errors="replace"))
        except ValueError:
            continue
        if not isinstance(rec, dict) or "kind" not in rec:
            rec = {"kind": None, "raw": rec}
        if kinds is None or rec.get("kind") in kinds:
            out.append(rec)
    return out, offset + nl + 1


def tail_records(path: str, poll_s: float = 0.2,
                 timeout_s: float = 60.0, follow: bool = True,
                 stop_when_done: bool = True,
                 kinds: Optional[Tuple[str, ...]] = None
                 ) -> Iterator[dict]:
    """Yield records as they are appended (the watch engine).

    Polls ``path`` every ``poll_s`` seconds, yielding only NEW records
    (``kinds`` filters like :func:`read_records`; reads are
    incremental by byte offset, so a journal full of large bucket
    payloads is parsed once, not once per poll); stops on a ``done:
    true`` record of ANY kind (when ``stop_when_done`` — a heartbeat
    close beat and a sweep journal's ``sweep_done`` both qualify), when
    ``follow`` is False and the file has been read through once, or
    after ``timeout_s`` seconds without any new record.  A not-yet-
    created file counts as "no new records" (the sweep may still be
    compiling), so the timeout is the only way out of a path that never
    materializes; a file that SHRANK (a fresh run truncated its
    journal) restarts the tail from the top."""
    import os as _os

    offset = 0
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            if _os.path.getsize(path) < offset:
                offset = 0          # truncated/rewritten: start over
            new, offset = _read_new_records(path, offset, kinds)
        except OSError:
            new = []
        for rec in new:
            deadline = time.monotonic() + timeout_s
            yield rec
            if stop_when_done and rec.get("done"):
                return
        if not follow:
            return
        if time.monotonic() >= deadline:
            return
        time.sleep(poll_s)


def tail_heartbeats(path: str, poll_s: float = 0.2,
                    timeout_s: float = 60.0, follow: bool = True,
                    stop_when_done: bool = True) -> Iterator[dict]:
    """:func:`tail_records` filtered to heartbeat records (the original
    single-kind watch surface, kept for its callers)."""
    return tail_records(path, poll_s=poll_s, timeout_s=timeout_s,
                        follow=follow, stop_when_done=stop_when_done,
                        kinds=(HEARTBEAT_KIND,))
