"""meshscope — live runtime & multichip scaling observatory (ISSUE 6).

Perfscope (benor_tpu/perfscope) observes the system BEFORE it runs: AOT
stage timings, the XLA cost model, the roofline.  Meshscope observes it
WHILE it runs, and across mesh shapes:

  telemetry  per-shard runtime telemetry — steady-state step wall-time,
             live device-memory watermarks (memory_stats / live-array
             sums), psum/collective byte attribution derived from the
             declarative layout tables (state.REC_LAYOUT / WIT_LAYOUT,
             pallas_round.PARTIAL_COLS), and straggler/imbalance
             detection (max/median shard step-time ratio) with a
             Perfetto per-shard track export.
  scaling    weak-/strong-scaling ladders across mesh shapes -> a
             pinned-schema ``kind: scaling_manifest`` document
             (tools/scaling_manifest_schema.json), gated against the
             committed SCALING_BASELINE.json by
             tools/check_scaling_regression.py (exit 0/2/3).
  heartbeat  the live progress plane — long sliced runs and batched
             sweeps publish rounds/sec, decided fraction and an ETA
             between slices (registry gauges + an append-only JSON-lines
             file the ``python -m benor_tpu watch`` CLI tails).
  scalegate  the stdlib-only band comparator behind the scaling gate
             (file-path-loaded by tools/check_scaling_regression.py, the
             same no-jax contract as perfscope/baseline.py).

House rule (PRs 2, 3, 5): meshscope OFF is bit-identical in results AND
compile counts — every hook here is host-side, out-of-band of the
compiled executables, and armed only by explicit knobs
(SimConfig.heartbeat_rounds, the scale/watch CLI).  Pinned by
tests/test_meshscope.py across the sharded, multihost, sliced and
batched regimes.
"""

from .heartbeat import (HeartbeatPublisher, publish_slice_heartbeat,
                        publish_sweep_heartbeat, read_heartbeats,
                        read_records, tail_heartbeats, tail_records)
from .scalegate import (STRAGGLER_TRIP, IncomparableScaling,
                        compare_scaling)
from .scaling import (SCALING_MANIFEST_KIND, build_scaling_manifest,
                      load_scaling_manifest, run_scaling_ladder,
                      save_scaling_manifest)
from .telemetry import (collective_bytes, detect_stragglers,
                        export_shard_trace, probe_shard_step_times,
                        sample_device_memory, step_time_imbalance)

__all__ = [
    "HeartbeatPublisher", "publish_slice_heartbeat",
    "publish_sweep_heartbeat", "read_heartbeats", "read_records",
    "tail_heartbeats", "tail_records",
    "STRAGGLER_TRIP", "IncomparableScaling", "compare_scaling",
    "SCALING_MANIFEST_KIND", "build_scaling_manifest",
    "load_scaling_manifest", "run_scaling_ladder",
    "save_scaling_manifest", "collective_bytes", "detect_stragglers",
    "export_shard_trace", "probe_shard_step_times",
    "sample_device_memory", "step_time_imbalance",
]
