"""Scaling-efficiency capture: weak/strong ladders across mesh shapes.

The "near-linear scaling" headline of "Simulating BFT Protocol
Implementations at Scale" (PAPERS.md) was, until this module, asserted
by hand-dropped MULTICHIP_r*.json log captures no schema validated and
no gate protected.  This module makes it a MEASURED, pinned artifact:

  run_scaling_ladder    run the sharded regime (parallel/sharded.py,
                        via its instrumented jitted_runner — what is
                        measured is what runs) over a ladder of mesh
                        shapes; per rung: steady-state wall time,
                        node-rounds/sec throughput, a per-device step
                        probe and its straggler ratio.
  build_scaling_manifest  ladder rows -> the pinned-schema
                        ``kind: scaling_manifest`` document
                        (tools/scaling_manifest_schema.json, validated
                        by tools/check_metrics_schema.py).  Efficiency
                        of rung d = throughput_d / (d x throughput_1) —
                        always vs the mandatory 1-device rung, for weak
                        AND strong mode (ideal node-rounds/sec scales
                        with d either way).
  tools/check_scaling_regression.py gates a manifest against the
  committed SCALING_BASELINE.json via meshscope/scalegate.py (stdlib-
  only, loaded by file path): exit 0 in-band / 2 regression / 3
  incomparable.

Ladder modes:
  weak    N grows with the mesh (n_nodes x d on a d-device rung): the
          per-shard slab is constant — the paper's pod-scale shape.
  strong  N fixed: the same problem spread thinner (requires d | N).
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .telemetry import (detect_stragglers, probe_shard_step_times,
                        sample_device_memory)

#: The manifest's auto-detection tag (tools/check_metrics_schema.py).
SCALING_MANIFEST_KIND = "scaling_manifest"

SCALING_SCHEMA_VERSION = 1

#: Default capture scale per rung.  N=8192 per device is the smallest
#: CPU-smoke shape where the per-round collective overhead stops
#: dominating the per-shard compute — below it the ladder measures
#: dispatch latency, not scaling (observed: efficiency 0.36 at N=128/
#: device vs 0.87-0.97 here, run-to-run stable) — and it still ladders
#: 1->4 virtual devices in seconds.  Accelerator runs pass their own.
DEFAULT_SCALE = {"n_nodes": 8192, "trials": 8, "max_rounds": 6, "seed": 0,
                 "reps": 3}


def _ladder_cfg(n: int, trials: int, max_rounds: int, seed: int):
    """The shape every rung runs: balanced inputs, zero crashes, the
    count-controlling adversary under private coins on the histogram
    path.  Chosen for MEASUREMENT, not science: the forced-tie livelock
    makes every rung execute exactly ``max_rounds`` rounds at every N
    and mesh shape (deterministic, equal work per round), so throughput
    ratios across rungs compare the MESH, never the protocol's luck —
    and the histogram path is the O(1)-bytes-per-node psum regime the
    node axis is built for."""
    from ..config import SimConfig
    f = int(0.2 * n)
    f += (n - f) % 2           # the tie adversary needs an even quorum
    return SimConfig(n_nodes=n, n_faulty=f, trials=trials,
                     delivery="quorum", scheduler="adversarial",
                     coin_mode="private", path="histogram",
                     max_rounds=max_rounds, seed=seed)


def _rung_inputs(cfg):
    import jax

    from ..state import FaultSpec, init_state
    from ..sweep import balanced_inputs
    faults = FaultSpec.none(cfg.trials, cfg.n_nodes)
    state = init_state(cfg, balanced_inputs(cfg.trials, cfg.n_nodes),
                       faults)
    return state, faults, jax.random.key(cfg.seed)


def run_scaling_rung(cfg, mesh, reps: int = 2) -> dict:
    """One ladder rung: compile + warm the sharded executable on
    ``mesh``, time ``reps`` steady-state executions, probe per-device
    step times, sample memory watermarks.  Returns the manifest row
    (without ``efficiency`` — attach_efficiency adds it ladder-wide)."""
    import jax.numpy as jnp

    from ..parallel import mesh as meshlib
    from ..parallel.sharded import jitted_runner, shard_inputs
    from ..utils.metrics import REGISTRY
    meshlib.check_divisible(cfg.trials, cfg.n_nodes, mesh)
    state, faults, key = _rung_inputs(cfg)
    runner = jitted_runner(cfg, mesh)
    st, fl = shard_inputs(state, faults, mesh)
    args = (st, fl, key, jnp.int32(1))
    rounds = int(runner(*args)[0])            # warm-up: compile + run
    with REGISTRY.timer("meshscope.rung").time():
        t0 = time.perf_counter()
        for _ in range(reps):
            out = runner(*args)
        int(out[0])                           # completion barrier
        steady = (time.perf_counter() - t0) / reps
    devices = list(np.asarray(mesh.devices).flat)
    probe = probe_shard_step_times(mesh=mesh)
    straggler = detect_stragglers(probe)
    mem = sample_device_memory()
    thr = (rounds * cfg.n_nodes * cfg.trials / steady) if steady > 0 \
        else 0.0
    per_round = steady / rounds if rounds else None
    REGISTRY.gauge("meshscope.step.round_s").set(per_round or 0.0)
    return {
        "devices": len(devices),
        "mesh_shape": [int(mesh.shape[meshlib.AXIS_TRIALS]),
                       int(mesh.shape[meshlib.AXIS_NODES])],
        "n_nodes": int(cfg.n_nodes),
        "trials": int(cfg.trials),
        "rounds": int(rounds),
        "steady_s": round(steady, 6),
        "step_round_s": (round(per_round, 6) if per_round is not None
                         else None),
        "node_rounds_per_sec": round(thr, 3),
        "straggler_ratio": straggler.to_dict()["ratio"],
        "shard_probe_s": [round(t, 6) for t in probe],
        "live_bytes_max": max((m["live_bytes"] for m in mem), default=0),
    }


def attach_efficiency(rows: List[dict]) -> List[dict]:
    """Add ``efficiency`` to every row: throughput vs d x the 1-device
    rung.  The 1-device rung is mandatory — without it "efficiency" has
    no anchor and the gate would pass vacuously."""
    ones = [r for r in rows if r["devices"] == 1]
    if not ones:
        raise ValueError(
            "scaling ladder needs the 1-device rung (mesh size 1): "
            "efficiency is defined vs d x the single-device throughput")
    base = ones[0]["node_rounds_per_sec"]
    for r in rows:
        ideal = r["devices"] * base
        r["efficiency"] = (round(r["node_rounds_per_sec"] / ideal, 6)
                           if ideal > 0 else None)
    return rows


def parse_mesh_2d(spec: str):
    """One ``--mesh-2d t,n`` rung spec -> (trial_shards, node_shards).

    The scale CLI's 2D rung grammar: two comma-separated positive
    integers, e.g. ``2,2`` or ``2,4``."""
    parts = [p.strip() for p in str(spec).split(",")]
    try:
        t, n = (int(p) for p in parts)
    except ValueError:
        raise ValueError(
            f"--mesh-2d expects 't,n' (two comma-separated shard "
            f"counts, e.g. 2,2); got {spec!r}") from None
    if t < 1 or n < 1:
        raise ValueError(f"--mesh-2d shard counts must be >= 1, got "
                         f"({t}, {n})")
    return t, n


def run_scaling_ladder(mesh_sizes: Sequence[int], mode: str = "weak",
                       axis: str = "nodes",
                       n_nodes: Optional[int] = None,
                       trials: Optional[int] = None,
                       max_rounds: Optional[int] = None, seed: int = 0,
                       reps: int = 2, verbose: bool = False,
                       mesh_2d: Optional[Sequence] = None):
    """Run the ladder -> (rows, scale dict) ready for the manifest.

    ``mesh_sizes`` are device counts (must include 1; see
    attach_efficiency); ``axis`` picks which mesh axis the ladder grows
    — 'nodes' (the ICI psum leg, default) or 'trials' (the DCN
    data-parallel leg).  ``mode``: 'weak' grows the sharded axis's
    problem dimension with the rung; 'strong' keeps it fixed (each
    rung's device count must divide it).

    ``mesh_2d`` appends explicit 2D ``(trial_shards, node_shards)``
    rungs after the 1D ladder (the ``--mesh-2d t,n`` CLI grammar;
    strings accepted).  A 2D rung runs the same flagship regime on the
    full ('trials', 'nodes') grid: in weak mode each mesh axis grows
    its own problem dimension (n_nodes x node_shards, trials x
    trial_shards — the per-shard slab stays constant in BOTH
    directions), strong mode keeps the base shape.  Efficiency is still
    anchored at the 1-device rung: ideal throughput scales with the
    device count either way."""
    from ..parallel import make_mesh
    if mode not in ("weak", "strong"):
        raise ValueError(f"unknown scaling mode {mode!r}")
    if axis not in ("nodes", "trials"):
        raise ValueError(f"unknown ladder axis {axis!r}")
    sizes = sorted({int(d) for d in mesh_sizes})
    if not sizes or sizes[0] < 1:
        raise ValueError(f"mesh sizes must be >= 1, got {mesh_sizes}")
    if 1 not in sizes:
        raise ValueError(
            "scaling ladder needs the 1-device rung (--mesh 1,...): "
            "efficiency is measured vs the single-device row")
    shapes_2d = [s if isinstance(s, tuple) else parse_mesh_2d(s)
                 for s in (mesh_2d or [])]
    scale = dict(DEFAULT_SCALE)
    for key, val in (("n_nodes", n_nodes), ("trials", trials),
                     ("max_rounds", max_rounds)):
        if val is not None:
            scale[key] = int(val)
    scale["seed"] = int(seed)
    scale["reps"] = int(reps)
    rungs = [((1, d) if axis == "nodes" else (d, 1)) for d in sizes]
    rungs += shapes_2d
    rows = []
    for ts, ns in rungs:
        n, t = scale["n_nodes"], scale["trials"]
        if mode == "weak":
            n = n * ns
            t = t * ts
        cfg = _ladder_cfg(n, t, scale["max_rounds"], scale["seed"])
        mesh = make_mesh(ts, ns)
        row = run_scaling_rung(cfg, mesh, reps=reps)
        rows.append(row)
        if verbose:
            print(f"  rung mesh=({ts},{ns}) d={ts * ns}: N={n} T={t} "
                  f"rounds={row['rounds']} "
                  f"{row['node_rounds_per_sec']:.3g} node-rounds/s "
                  f"straggler={row['straggler_ratio']:.2f}", flush=True)
    return attach_efficiency(rows), scale


def build_scaling_manifest(rows: List[dict], mode: str, axis: str,
                           scale: Dict[str, int]) -> dict:
    """Assemble the pinned-schema scaling manifest document."""
    import jax
    dev = jax.devices()[0]
    return {
        "kind": SCALING_MANIFEST_KIND,
        "schema_version": SCALING_SCHEMA_VERSION,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "jax_version": jax.__version__,
        "created_unix": round(time.time(), 3),
        "mode": mode,
        "axis": axis,
        "scale": {k: int(scale[k])
                  for k in ("n_nodes", "trials", "max_rounds", "seed",
                            "reps")},
        "rows": rows,
    }


def save_scaling_manifest(path: str, manifest: dict) -> None:
    with open(path, "w") as fh:
        json.dump(manifest, fh, indent=1)
        fh.write("\n")


def load_scaling_manifest(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("kind") != SCALING_MANIFEST_KIND:
        raise ValueError(
            f"{path}: not a scaling manifest (kind={doc.get('kind')!r})")
    return doc
