"""Vectorized node-state store (SURVEY.md N2).

The reference keeps per-node state in one JS closure per Express server
(``currentState = {killed, x, decided, k}``, src/nodes/node.ts:21-26).  Here
all N nodes x T Monte-Carlo trials live in structure-of-arrays device tensors:

    x:       int8 [T, N]   protocol value, VAL0 | VAL1 | VALQ
    decided: bool [T, N]
    k:       int32[T, N]   round counter as *observed* (k=0 before /start,
                           k=1 after start, k=r+1 after completing round r —
                           exactly the reference's update points,
                           node.ts:25,172,147)
    killed:  bool [T, N]   true for birth-faulty nodes and after /stop

Faulty-at-birth nodes report all-null observable state in the parity API
(node.ts:21-26 projects them to null); internally their lanes simply carry
inert values and a ``killed`` flag.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import SimConfig, VAL0, VAL1, VALQ


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class NetState:
    """Pytree of all node state. Leading axis T = trials, second axis N = nodes."""

    x: jax.Array        # int8  [T, N]
    decided: jax.Array  # bool  [T, N]
    k: jax.Array        # int32 [T, N]
    killed: jax.Array   # bool  [T, N]

    @property
    def shape(self):
        return self.x.shape


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DynParams:
    """TRACED dynamic protocol parameters — the f-axis of a batched sweep.

    ``SimConfig`` is a static (hashable) jit argument, so every distinct
    ``n_faulty`` historically cost a full XLA recompile of the round loop
    — ~8-40 s per sweep point under remote-accelerator compiles
    (utils/cache.py) for a curve whose points differ only in two scalars.
    DynParams is the dynamic half of that split: the protocol fault
    parameter F and the quorum N - F as int32 device scalars, threaded
    through the round kernel (models/benor.py), the tally dispatch and
    closed-form adversaries (ops/tally.py) and the Cornish-Fisher
    samplers (ops/sampling.py) so one compiled executable serves every f
    on the curve (sweep.run_curve_batched vmaps over a [B] batch of
    these).

    Only valid where the compiled code does NOT specialize shapes or
    kernels on the quorum — no exact shared-CDF tables ([T, m+1]), no
    dense top-k delivery masks, no pallas kernels (m is baked into their
    closures).  sweep.quorum_specialized is the single predicate deciding
    that; configs it flags keep the classic static path (dyn=None).
    """

    n_faulty: jax.Array  # int32 [] — F, the protocol fault parameter
    quorum: jax.Array    # int32 [] — N - F (node.ts:52,88)
    # Committee-delivery knobs (benor_tpu/topo/committees.py): the
    # committee count g and target size c as traced scalars, so a
    # committee-size/count curve sweeps inside one bucket executable
    # exactly like the f-axis (the STATIC shape bound stays
    # cfg.committee_cap).  0/0 whenever committee delivery is off —
    # the values are only ever read under cfg.committee_cap > 0.
    committee_count: jax.Array  # int32 []
    committee_size: jax.Array   # int32 []
    # Message-omission probability (benor_tpu/faults, PR 15): the
    # per-edge drop probability as a traced f32 scalar, so a whole
    # rounds-vs-drop_prob curve sweeps inside one bucket executable
    # (the thinning draws — sampling.binomial_keep — are shape-generic
    # in it).  0.0 whenever the omission plane is off — only ever read
    # under cfg.drop_prob > 0.
    drop_prob: jax.Array        # float32 []

    @classmethod
    def from_config(cls, cfg: SimConfig) -> "DynParams":
        return cls(n_faulty=jnp.int32(cfg.n_faulty),
                   quorum=jnp.int32(cfg.quorum),
                   committee_count=jnp.int32(cfg.committee_count),
                   committee_size=jnp.int32(cfg.committee_size),
                   drop_prob=jnp.float32(cfg.drop_prob))

    @classmethod
    def stack(cls, cfgs) -> "DynParams":
        """[B]-batched params from per-point configs (the vmap input)."""
        f = np.asarray([c.n_faulty for c in cfgs], np.int32)
        m = np.asarray([c.quorum for c in cfgs], np.int32)
        g = np.asarray([c.committee_count for c in cfgs], np.int32)
        s = np.asarray([c.committee_size for c in cfgs], np.int32)
        p = np.asarray([c.drop_prob for c in cfgs], np.float32)
        return cls(n_faulty=jnp.asarray(f), quorum=jnp.asarray(m),
                   committee_count=jnp.asarray(g),
                   committee_size=jnp.asarray(s),
                   drop_prob=jnp.asarray(p))


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class FaultSpec:
    """Fault-injection masks (SURVEY.md N5).

    ``faulty`` reproduces the reference's ``faultyList`` (launchNodes.ts:8):
    under the 'crash' model those lanes are killed at birth with null state.
    Under 'byzantine' they stay alive but broadcast bit-flipped values.
    Under 'equivocate' they stay alive and two-faced: each (receiver,
    equivocator) edge carries an independent fair bit per phase — or a
    value the count-controlling adversary chooses outright under
    scheduler='adversarial' (ops/tally.py).
    Under 'crash_at_round' lane i dies at the start of round crash_round[i]
    (crash_round <= 0 means never).
    Under 'crash_recover' lane i is DOWN for rounds
    crash_round[i] <= r < recover_round[i] and then rejoins
    (recover_round <= 0: never — the lane latches killed, exactly the
    crash_at_round semantics); benor_tpu/faults/recovery.py realizes
    SimConfig.recovery spec strings into these bounds.  ``recover_round``
    is None (an EMPTY pytree leaf — zero extra buffers, zero trace
    footprint) for every other fault model, so injection off stays
    bit-identical in results and compile counts.
    """

    faulty: jax.Array       # bool  [T, N]
    crash_round: jax.Array  # int32 [T, N]
    recover_round: Optional[jax.Array] = None  # int32 [T, N] | None

    @classmethod
    def from_faulty_list(cls, cfg: SimConfig, faulty_list,
                         crash_rounds=None, recover_rounds=None
                         ) -> "FaultSpec":
        f = np.asarray(faulty_list, dtype=bool)
        if f.shape != (cfg.n_nodes,):
            raise ValueError("faultyList length must equal N (launchNodes.ts:10-11)")
        if int(f.sum()) != cfg.n_faulty:
            # reference: "faultyList doesnt have F faulties" (launchNodes.ts:12-13)
            raise ValueError("faultyList doesnt have F faulties")
        faulty = jnp.broadcast_to(jnp.asarray(f), (cfg.trials, cfg.n_nodes))
        recover_round = None
        if cfg.fault_model in ("crash_at_round", "crash_recover"):
            if crash_rounds is None:
                raise ValueError(
                    f"fault_model={cfg.fault_model!r} requires crash_rounds "
                    "(int[N], round at which each faulty node dies; <=0 = never)")
            cr = np.asarray(crash_rounds, dtype=np.int32)
            if cr.shape != (cfg.n_nodes,):
                raise ValueError("crash_rounds length must equal N")
            crash_round = jnp.broadcast_to(jnp.asarray(cr),
                                           (cfg.trials, cfg.n_nodes))
            if cfg.fault_model == "crash_recover":
                if recover_rounds is None:
                    raise ValueError(
                        "fault_model='crash_recover' requires "
                        "recover_rounds (int[N], first round each "
                        "crashed node is back; <=0 = never rejoins)")
                rr = np.asarray(recover_rounds, dtype=np.int32)
                if rr.shape != (cfg.n_nodes,):
                    raise ValueError("recover_rounds length must equal N")
                recover_round = jnp.broadcast_to(
                    jnp.asarray(rr), (cfg.trials, cfg.n_nodes))
        elif crash_rounds is not None:
            raise ValueError(
                "crash_rounds only applies to fault_model='crash_at_round'"
                " / 'crash_recover'")
        else:
            crash_round = jnp.zeros((cfg.trials, cfg.n_nodes), jnp.int32)
        if recover_rounds is not None and recover_round is None:
            raise ValueError(
                "recover_rounds only applies to fault_model='crash_recover'")
        return cls(faulty=faulty, crash_round=crash_round,
                   recover_round=recover_round)

    @classmethod
    def first_f(cls, cfg: SimConfig, crash_rounds=None,
                recover_rounds=None) -> "FaultSpec":
        """Mark the first ``cfg.n_faulty`` lanes faulty — the canonical
        mask every harness uses (WHICH lanes are faulty is statistically
        irrelevant under the uniform scheduler: lanes are exchangeable)."""
        mask = np.zeros(cfg.n_nodes, bool)
        mask[:cfg.n_faulty] = True
        return cls.from_faulty_list(cfg, mask, crash_rounds,
                                    recover_rounds)

    @classmethod
    def none(cls, trials: int, n_nodes: int) -> "FaultSpec":
        """Zero-crash spec: every node alive, F purely a protocol parameter.

        The science workloads use this to decouple F from the crash count —
        with crash-from-birth faults the live population equals the quorum
        and every tally is the deterministic full-population draw (the
        reference pins them equal, launchNodes.ts:12-13; an asynchronous
        adversary is strongest with all N alive)."""
        return cls(faulty=jnp.zeros((trials, n_nodes), bool),
                   crash_round=jnp.zeros((trials, n_nodes), jnp.int32))


# --------------------------------------------------------------------------
# Packed node state (the fused-kernel fast path, SimConfig.use_pallas_round):
# the declarative BIT-FIELD layout of the hot per-node state.
#
# PR 8 relaid the packed representation from one int32 word per node
# (4 B/node, bits 0-1 x / 2 decided / 3 killed / 4 faulty / 5+ k) to
# BIT-PLANES: a uint32 [T, planes, N/32] stack where plane ``base + b``
# holds bit ``b`` of the named field for 32 nodes per word.  The hot
# protocol state (x, decided, killed, coin-commit, faulty) costs 6 bits
# per node; the round counter k adds only ``pack_k_bits(cfg)`` planes
# (the bit length of max_rounds + 1 — 4 planes at the bench geometry's
# max_rounds=12) instead of a fixed 26.  The fused round kernels
# (ops/pallas_round.py) read and write this stack directly, so one round
# moves ~2 x (6 + k_bits)/8 bytes per node instead of the old layout's
# 12 (two kernels x 4-byte word read + one write) — the 4x+ traffic cut
# perfscope's bytes-per-node report (perfscope/roofline.py) prices from
# THIS table.
# --------------------------------------------------------------------------

#: Packed-state bit-field layout — name -> (base, width) in BITS (= plane
#: indices), the same machine-readable pure-literal discipline as
#: REC_LAYOUT / WIT_LAYOUT below: the runtime (pack/unpack here, the
#: kernel plane loads/stores in ops/pallas_round.py, the perfscope
#: bytes-per-node pricing) derives every index from this table and the
#: static layout checker (benor_tpu/analysis/rules_layout.py) re-parses
#: it and proves: ranges overlap-free and dense from bit 0, the total
#: width fits one uint32 word (so a 32-plane stack — or a transposed
#: one-word-per-node view — can always hold it), and the field names
#: cover NetState's fields plus PACK_EXTRA_FIELDS exactly.  ``k``'s
#: declared width is the CAP; at runtime only ``pack_k_bits(cfg)``
#: planes of it are materialized (config.py rejects max_rounds that
#: would not fit).
PACK_LAYOUT = {
    "x": (0, 2),        # protocol value VAL0 | VAL1 | VALQ
    "decided": (2, 1),  # decided bit (node.ts:100,103)
    "killed": (3, 1),   # killed bit (pad lanes carry it too)
    "coined": (4, 1),   # lane committed a coin flip this round
    "faulty": (5, 1),   # fault mask (byzantine flip / equivocator tag)
    "down": (6, 1),     # crash_recover down-interval bit (stored round)
    "k": (7, 25),       # round counter, low bit first (width = the cap)
}

#: Packed fields that are NOT NetState leaves (the layout checker proves
#: set(PACK_LAYOUT) == NetState fields + these, so a field can neither
#: silently vanish from the pack nor ride it undeclared).  ``faulty``
#: packs the FaultSpec mask the kernels consult every round; ``coined``
#: carries each round's coin-commit bit in the stack for forensic
#: unpacking (``pallas_round.plane_field(pack, PACK_COINED, 1)``) — the
#: recorder/witness partials compute their own coined mask in-register,
#: so dropping this plane would save 1 bit/node at the cost of the
#: post-hoc evidence channel.  ``down`` (PR 15) is the crash-recovery
#: twin: the kernels re-derive each round's down-interval membership
#: from the (crash_round, recover_round) bounds in-register
#: (fault_model='crash_recover') and store the bit here so forensic
#: unpacking can see WHO sat the stored round out — the protocol never
#: reads it back (liveness always re-derives from the bounds, so a
#: sliced run cannot inherit a stale bit).  The k cap paid for the
#: plane: 26 -> 25 (config.py re-anchors the max_rounds bound).
PACK_EXTRA_FIELDS = ("faulty", "coined", "down")

PACK_X = PACK_LAYOUT["x"][0]
PACK_DECIDED = PACK_LAYOUT["decided"][0]
PACK_KILLED = PACK_LAYOUT["killed"][0]
PACK_COINED = PACK_LAYOUT["coined"][0]
PACK_FAULTY = PACK_LAYOUT["faulty"][0]
PACK_DOWN = PACK_LAYOUT["down"][0]
PACK_K = PACK_LAYOUT["k"][0]
PACK_K_MAX_BITS = PACK_LAYOUT["k"][1]
#: Planes below the (variable-width) k field — the hot protocol bits.
PACK_STATIC_WIDTH = PACK_K
#: Nodes per uint32 plane word.
PACK_NODES_PER_WORD = 32


def pack_k_bits_for(max_rounds: int) -> int:
    """Planes a round counter capped at ``max_rounds`` needs: k reaches
    max_rounds + 1, low bit first.  Config-free so jax-light consumers
    (perfscope/roofline.py's packing cost model) can share the one
    formula."""
    return max(int(max_rounds + 1).bit_length(), 1)


def pack_k_bits(cfg: SimConfig) -> int:
    """Planes the round counter needs for this config.  Static
    (config-only), <= the PACK_LAYOUT cap — config.py rejects max_rounds
    past it."""
    return pack_k_bits_for(cfg.max_rounds)


def pack_width(cfg: SimConfig) -> int:
    """Total planes a packed [T, planes, N/32] stack carries for this
    config: the static protocol bits + the k planes."""
    return PACK_STATIC_WIDTH + pack_k_bits(cfg)


# --------------------------------------------------------------------------
# Flight recorder (SimConfig.record): the on-device round-history buffer.
#
# One int32 row per executed round, written inside the compiled while-loop
# via dynamic_update_slice — full round history for one extra HBM buffer
# and zero host round trips, in EVERY regime (traced XLA, fused pallas,
# sliced poll_rounds, batched dynamic-F sweep, sharded mesh).  Row 0 is the
# post-/start snapshot; row r (1-based) is the network at the END of round
# r; unwritten rows stay all-zero (distinguishable: a written row's
# decided + killed + undecided classes sum to T*N >= 1).
# --------------------------------------------------------------------------

#: Recorder column layout — name -> (base, width), the machine-readable
#: single source of truth that BOTH the runtime (the REC_* indices below,
#: the host-side renderers in utils/metrics.py, the vote kernel's
#: telemetry partials in ops/pallas_round.py) and the static layout
#: checker (benor_tpu/analysis/rules_layout.py) consume.  Keep it a PURE
#: LITERAL: the checker reads it by parsing this file, never by importing
#: it.  All columns are network-global counts (summed over trials AND
#: nodes) except tally_margin — the tally-margin summary, sum over trials
#: of the per-trial MAX |v0 - v1| vote margin over lanes that ran the
#: vote phase (a max, not a sum, so int32 cannot overflow at N=1M x 1k
#: trials; 0 everywhere = the count-controlling adversary's forced-tie
#: livelock; 0 on row 0).
REC_LAYOUT = {
    "decided": (0, 1),      # decided lanes (cumulative)
    "killed": (1, 1),       # killed lanes
    "undecided_0": (2, 1),  # live undecided lanes holding x=0
    "undecided_1": (3, 1),  # live undecided lanes holding x=1
    "undecided_q": (4, 1),  # live undecided lanes holding "?"
    "coin_flips": (5, 1),   # lanes that committed a coin flip this round
    "tally_margin": (6, 1),  # tally-margin summary (see above)
}

REC_DECIDED = REC_LAYOUT["decided"][0]
REC_KILLED = REC_LAYOUT["killed"][0]
REC_UNDEC0 = REC_LAYOUT["undecided_0"][0]
REC_UNDEC1 = REC_LAYOUT["undecided_1"][0]
REC_UNDECQ = REC_LAYOUT["undecided_q"][0]
REC_COINS = REC_LAYOUT["coin_flips"][0]
REC_MARGIN = REC_LAYOUT["tally_margin"][0]
REC_WIDTH = max(b + w for b, w in REC_LAYOUT.values())

#: Column names, index-aligned with the REC_* constants — derived from
#: the layout table so host-side renderers (utils/metrics.py) can never
#: drift from the kernel emission order.
REC_COLUMNS = tuple(sorted(REC_LAYOUT, key=lambda c: REC_LAYOUT[c][0]))


def recorder_snapshot_row(x: jax.Array, decided: jax.Array,
                          killed: jax.Array, ctx=None) -> jax.Array:
    """Network-global recorder row from raw state fields -> int32 [REC_WIDTH].

    Used for row 0 (post-/start snapshot: no votes yet, so coin-flip count
    and tally margin are 0).  Under a mesh ``ctx`` the counts are psum'd
    over every axis, so each shard holds the identical global row.
    """
    from .ops.collectives import SINGLE
    ctx = SINGLE if ctx is None else ctx
    undec = ~decided & ~killed
    cols = [decided, killed, undec & (x == VAL0), undec & (x == VAL1),
            undec & (x == VALQ)]
    counts = [ctx.psum_all(jnp.sum(c, dtype=jnp.int32)) for c in cols]
    zero = jnp.int32(0)
    return jnp.stack(counts + [zero, zero])


def recorder_round_row(x: jax.Array, decided: jax.Array, killed: jax.Array,
                       coined: jax.Array, margin: jax.Array,
                       ctx=None) -> jax.Array:
    """Full end-of-round recorder row -> int32 [REC_WIDTH].

    ``x``/``decided``/``killed`` are the committed post-round fields;
    ``coined`` bool [T, N] marks lanes that committed a coin flip;
    ``margin`` int32 [T, N] is each vote-phase lane's |v0 - v1| (0 for
    lanes that did not run the phase).  Counts psum over every mesh axis;
    the margin column is pmax over the node axis (per-trial max), then a
    trial sum — see REC_MARGIN.
    """
    from .ops.collectives import SINGLE
    ctx = SINGLE if ctx is None else ctx
    base = recorder_snapshot_row(x, decided, killed, ctx)
    coins = ctx.psum_all(jnp.sum(coined, dtype=jnp.int32))
    per_trial_max = ctx.pmax_nodes(jnp.max(margin, axis=-1))
    marg = ctx.psum_trials(jnp.sum(per_trial_max, dtype=jnp.int32))
    return base.at[REC_COINS].set(coins).at[REC_MARGIN].set(marg)


def recorder_write(recorder: jax.Array, r: jax.Array,
                   row: jax.Array) -> jax.Array:
    """Write one row at (traced) round index ``r`` — the loop-body update."""
    return jax.lax.dynamic_update_slice(
        recorder, row[None, :], (jnp.asarray(r, jnp.int32), jnp.int32(0)))


def new_recorder(cfg: SimConfig, state: NetState, ctx=None) -> jax.Array:
    """Fresh [max_rounds + 1, REC_WIDTH] int32 buffer with row 0 set to the
    snapshot of ``state``.  Traceable (callers embed it in their jits) and
    mesh-safe (``ctx`` globalizes the row-0 counts)."""
    rec = jnp.zeros((cfg.max_rounds + 1, REC_WIDTH), jnp.int32)
    row0 = recorder_snapshot_row(state.x, state.decided, state.killed, ctx)
    return rec.at[0].set(row0)


# --------------------------------------------------------------------------
# Witness recorder (SimConfig.witness_trials / witness_nodes): the
# on-device PER-NODE forensic trace behind benor_tpu/audit.py.
#
# Where the flight recorder (above) keeps network-global aggregates, the
# witness keeps, for every watched (trial, node) pair, the full per-round
# evidence chain — committed value, decided/killed bits, coin-commit bit,
# and the proposal/vote tallies that justified the transition — written
# inside the compiled while-loop via dynamic_update_slice, in EVERY regime
# (traced XLA, fused pallas via per-tile witness partials, sliced
# poll_rounds, batched dynamic-F sweep, sharded mesh).  Extra HBM:
# (max_rounds + 1) * W * k * WIT_WIDTH * 4 bytes.  Row 0 is the
# post-/start snapshot; row r the watched lanes at the END of round r.
# --------------------------------------------------------------------------

#: Witness column layout — name -> (base, width), per watched
#: (trial, node) per round.  Same contract as REC_LAYOUT: a pure-literal
#: machine-readable table that the runtime (WIT_* indices, the pallas
#: witness partials, audit.witness_rows) and the static layout checker
#: both consume.  Every name except the host-set ``written`` sentinel
#: must be emitted by exactly one kernel witness block
#: (ops/pallas_round.py WITNESS_PROP_FIELDS / WITNESS_VOTE_FIELDS) — the
#: cross-file parity the checker proves.
WIT_LAYOUT = {
    "x": (0, 1),        # committed protocol value (VAL0 | VAL1 | VALQ)
    "decided": (1, 1),  # decided bit (node.ts:100,103)
    "killed": (2, 1),   # killed bit (crash / crash_at_round / stop)
    "coined": (3, 1),   # lane committed a coin flip this round (node.ts:111)
    "p0": (4, 1),       # proposal-phase tally for 0 (node.ts:63-69 input)
    "p1": (5, 1),       # proposal-phase tally for 1
    "v0": (6, 1),       # vote-phase tally for 0 (decide evidence, node.ts:99)
    "v1": (7, 1),       # vote-phase tally for 1 (node.ts:102)
    "written": (8, 1),  # 1 on every written row (unwritten-row sentinel)
}

WIT_X = WIT_LAYOUT["x"][0]
WIT_DECIDED = WIT_LAYOUT["decided"][0]
WIT_KILLED = WIT_LAYOUT["killed"][0]
WIT_COINED = WIT_LAYOUT["coined"][0]
WIT_P0 = WIT_LAYOUT["p0"][0]
WIT_P1 = WIT_LAYOUT["p1"][0]
WIT_V0 = WIT_LAYOUT["v0"][0]
WIT_V1 = WIT_LAYOUT["v1"][0]
WIT_WRITTEN = WIT_LAYOUT["written"][0]
WIT_WIDTH = max(b + w for b, w in WIT_LAYOUT.values())

#: Column names, index-aligned with the WIT_* constants — derived from
#: the layout table (single source of truth for audit.witness_rows).
WIT_COLUMNS = tuple(sorted(WIT_LAYOUT, key=lambda c: WIT_LAYOUT[c][0]))


def witness_node_ids(cfg: SimConfig) -> np.ndarray:
    """The k watched GLOBAL node ids -> int32 [witness_nodes], sorted.

    First ceil(k/2) + last floor(k/2) ids: both ends of the id range,
    which is where the forensically interesting populations live — the
    canonical fault masks mark the FIRST F lanes faulty
    (FaultSpec.first_f) while the targeted adversary's value camps sit at
    the TOP of the range (ops/tally.py:targeted_counts).  k == n_nodes
    watches every node.  Static (a pure function of the config), so the
    gather indices bake into the trace."""
    k, n = cfg.witness_nodes, cfg.n_nodes
    lo = (k + 1) // 2
    hi = k - lo
    # benorlint: allow-host-sync — static config-only math; constant-folds
    return np.asarray(list(range(lo)) + list(range(n - hi, n)), np.int32)


def witness_select(cfg: SimConfig, arr: jax.Array, ctx=None) -> jax.Array:
    """Gather the watched (trial, node) entries of a [T, N] field ->
    int32 [W, k], mesh-globalized.

    One-hot masked reduction over GLOBAL ids: under a mesh each shard
    contributes only the watched entries it owns (its local one-hots are
    zero elsewhere) and the psum over every axis leaves the identical
    [W, k] block on all shards — the witness analog of the recorder's
    psum-before-write discipline."""
    from .ops.collectives import SINGLE
    ctx = SINGLE if ctx is None else ctx
    T, N = arr.shape
    wt = jnp.asarray(cfg.witness_trials, jnp.int32)           # [W]
    wn = jnp.asarray(witness_node_ids(cfg), jnp.int32)        # [k]
    t_oh = (ctx.trial_ids(T)[None, :] == wt[:, None]).astype(jnp.int32)
    n_oh = (ctx.node_ids(N)[None, :] == wn[:, None]).astype(jnp.int32)
    out = jnp.einsum("wt,tn,kn->wk", t_oh, arr.astype(jnp.int32), n_oh)
    return ctx.psum_all(out)


def witness_snapshot_row(cfg: SimConfig, x: jax.Array, decided: jax.Array,
                         killed: jax.Array, ctx=None) -> jax.Array:
    """Row 0 (post-/start snapshot): state fields only, no tallies/coins
    yet -> int32 [W, k, WIT_WIDTH] with the written sentinel set."""
    fields = [witness_select(cfg, f, ctx)
              for f in (x, decided, killed)]
    zero = jnp.zeros_like(fields[0])
    one = jnp.ones_like(fields[0])
    return jnp.stack(fields + [zero] * 5 + [one], axis=-1)


def witness_round_row(cfg: SimConfig, x: jax.Array, decided: jax.Array,
                      killed: jax.Array, coined: jax.Array,
                      p0: jax.Array, p1: jax.Array,
                      v0: jax.Array, v1: jax.Array, ctx=None) -> jax.Array:
    """Full end-of-round witness row -> int32 [W, k, WIT_WIDTH].

    ``x``/``decided``/``killed`` are the committed post-round fields;
    ``coined`` marks lanes that committed a coin flip; ``p0``/``p1`` and
    ``v0``/``v1`` are the per-lane proposal / vote tallies the round's
    transitions were justified by (cast to int32 — the CF samplers hand
    them over as integral f32)."""
    fields = [witness_select(cfg, f, ctx)
              for f in (x, decided, killed, coined, p0, p1, v0, v1)]
    return jnp.stack(fields + [jnp.ones_like(fields[0])], axis=-1)


def witness_write(witness: jax.Array, r: jax.Array,
                  row: jax.Array) -> jax.Array:
    """Write one [W, k, WIT_WIDTH] row at (traced) round index ``r``."""
    return jax.lax.dynamic_update_slice(
        witness, row[None], (jnp.asarray(r, jnp.int32), jnp.int32(0),
                             jnp.int32(0), jnp.int32(0)))


def new_witness(cfg: SimConfig, state: NetState, ctx=None) -> jax.Array:
    """Fresh [max_rounds + 1, W, k, WIT_WIDTH] int32 buffer with row 0 set
    to the snapshot of ``state``.  Traceable and mesh-safe, like
    new_recorder."""
    wit = jnp.zeros((cfg.max_rounds + 1, len(cfg.witness_trials),
                     cfg.witness_nodes, WIT_WIDTH), jnp.int32)
    row0 = witness_snapshot_row(cfg, state.x, state.decided, state.killed,
                                ctx)
    return wit.at[0].set(row0)


def init_state(cfg: SimConfig, initial_values, faults: FaultSpec) -> NetState:
    """Build the T x N state arrays from per-node initial values.

    Mirrors the reference's per-node init (node.ts:21-26): healthy lanes get
    {x: initial, decided: False, k: 0}; crash-faulty lanes are killed at birth.
    ``initial_values`` accepts 0/1/"?" (or VALQ) per node, shape [N] or [T, N].
    """
    arr = np.asarray(initial_values)
    if arr.dtype.kind in "iub":  # already numeric: vectorized fast path
        if not np.isin(arr, (VAL0, VAL1, VALQ)).all():  # pre-cast: no wrap
            raise ValueError(
                "initial_values must be 0, 1 or '?' (reference src/types.ts:8)")
        vals = arr.astype(np.int8)
    else:  # mixed 0/1/"?" python lists (the reference's Value domain)
        vals = np.asarray(
            [VALQ if v == "?" else int(v) for v in np.ravel(arr)],
            dtype=np.int8,
        ).reshape(arr.shape)
        if not np.isin(vals, (VAL0, VAL1, VALQ)).all():
            raise ValueError(
                "initial_values must be 0, 1 or '?' (reference src/types.ts:8)")
    if vals.ndim == 1:
        if vals.shape != (cfg.n_nodes,):
            raise ValueError("Arrays don't match")  # launchNodes.ts:10-11
        vals = np.broadcast_to(vals, (cfg.trials, cfg.n_nodes))
    elif vals.shape != (cfg.trials, cfg.n_nodes):
        raise ValueError("initial_values must be [N] or [T, N]")

    killed_at_birth = (
        faults.faulty if cfg.fault_model == "crash"
        else jnp.zeros_like(faults.faulty)
    )
    return NetState(
        x=jnp.asarray(vals, jnp.int8),
        decided=jnp.zeros((cfg.trials, cfg.n_nodes), bool),
        k=jnp.zeros((cfg.trials, cfg.n_nodes), jnp.int32),
        killed=killed_at_birth,
    )


def observable_state(cfg: SimConfig, state: NetState, faults: FaultSpec,
                     node_id: int, trial: int = 0) -> dict:
    """The reference's ``/getState`` JSON for one node (node.ts:197-199).

    Birth-faulty crash nodes project to all-null (node.ts:21-26); every other
    node reports its live arrays.  Returns plain Python values.
    """
    birth_faulty = bool(np.asarray(faults.faulty)[trial, node_id]) and \
        cfg.fault_model == "crash"
    if birth_faulty:
        return {"killed": True, "x": None, "decided": None, "k": None}
    x = int(np.asarray(state.x)[trial, node_id])
    return {
        "killed": bool(np.asarray(state.killed)[trial, node_id]),
        "x": "?" if x == VALQ else x,
        "decided": bool(np.asarray(state.decided)[trial, node_id]),
        "k": int(np.asarray(state.k)[trial, node_id]),
    }
