"""Unified host-side metrics: one process-wide registry, three exporters.

Before this module the repo had three disjoint accounting mechanisms —
``utils/tracing.timed`` (ad-hoc stderr wall-clocks), ``utils/
compile_counter`` (the jax.monitoring backend-compile hook) and the
backend-probe retry loop (``utils/backend.py``) — none of which could be
exported or correlated.  They now all feed ONE registry of counters /
gauges / timers (each keeps feeding its original surface too: stderr
lines, scoped CompileCounter objects), and the registry exports as:

  * JSON-lines   (``export_jsonl``)      — one metric per line, grep/jq-able
  * Prometheus   (``export_prometheus``) — textfile-collector format
  * Chrome trace (``export_chrome_trace``) — Perfetto / chrome://tracing;
    timer spans render as complete events on the host track, and a
    flight-recorder buffer (SimConfig.record) renders as one trace slice
    per protocol round on a synthetic round track — next to any
    ``jax.profiler`` capture you take of the same run.

The registry is dependency-free and import-cheap (stdlib only): the
device-side flight recorder must never pay for host-side bookkeeping.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import json
import os
import re
import threading
import time
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

# --------------------------------------------------------------------------
# Registry
# --------------------------------------------------------------------------


@dataclasses.dataclass
class Counter:
    """Monotone accumulator (events, compiles, probe attempts)."""

    name: str
    value: float = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with _REGISTRY_LOCK:
            self.value += amount


@dataclasses.dataclass
class Gauge:
    """Last-write-wins sample (sizes, utilizations, platform flags)."""

    name: str
    value: float = 0.0

    def set(self, value: float) -> None:
        with _REGISTRY_LOCK:
            self.value = float(value)


@dataclasses.dataclass
class Timer:
    """Duration accumulator; keeps per-span events for the trace export."""

    name: str
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0
    #: (start wall-clock epoch seconds, duration seconds) per span, in
    #: record order — the Chrome-trace exporter's raw material.
    events: List = dataclasses.field(default_factory=list)

    def record(self, seconds: float, start: Optional[float] = None) -> None:
        with _REGISTRY_LOCK:
            self.count += 1
            self.total_s += seconds
            self.min_s = min(self.min_s, seconds)
            self.max_s = max(self.max_s, seconds)
            self.events.append(
                (time.time() - seconds if start is None else start, seconds))

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        start = time.time()
        yield
        self.record(time.perf_counter() - t0, start=start)

    def percentiles(self, qs=(50, 99)) -> Dict[int, float]:
        """Span-duration percentiles in SECONDS, from the recorded
        events — what a latency timer (e.g. the serve plane's
        ``serve.client_latency``) reduces to for p50/p99 reporting.
        Empty timer -> an empty dict (no fabricated zeros)."""
        with _REGISTRY_LOCK:
            durs = [d for _, d in self.events]
        if not durs:
            return {}
        return {int(q): float(np.percentile(np.asarray(durs), q))
                for q in qs}


_REGISTRY_LOCK = threading.RLock()


class MetricsRegistry:
    """Process-wide named metric store.  ``counter``/``gauge``/``timer``
    are get-or-create (idempotent, thread-safe); ``snapshot`` returns
    plain dicts for the exporters."""

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls):
        with _REGISTRY_LOCK:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name=name)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def snapshot(self) -> List[dict]:
        """All metrics as JSON-able dicts (one per metric, typed)."""
        out = []
        with _REGISTRY_LOCK:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if isinstance(m, Counter):
                    out.append({"name": name, "type": "counter",
                                "value": m.value})
                elif isinstance(m, Gauge):
                    out.append({"name": name, "type": "gauge",
                                "value": m.value})
                else:
                    out.append({
                        "name": name, "type": "timer", "count": m.count,
                        "total_s": round(m.total_s, 6),
                        "min_s": (round(m.min_s, 6) if m.count else None),
                        "max_s": round(m.max_s, 6),
                    })
        return out

    def reset(self) -> None:
        """Drop every metric (tests only — the registry is process-global)."""
        with _REGISTRY_LOCK:
            self._metrics.clear()


#: The process-wide registry every instrumented module feeds.
REGISTRY = MetricsRegistry()


# --------------------------------------------------------------------------
# Spans (servescope): request-scoped tracing for the serve plane
# --------------------------------------------------------------------------

#: Anchor for converting ``time.perf_counter()`` stamps (the serve
#: plane's stage clocks — monotonic, comparable across threads) into
#: wall-clock epoch seconds for the Chrome-trace timeline.  Captured
#: once at import so every span shares one consistent offset.
_PERF_EPOCH = time.time() - time.perf_counter()


def perf_to_epoch(t_perf: float) -> float:
    """A ``time.perf_counter()`` stamp -> epoch seconds (trace domain)."""
    return t_perf + _PERF_EPOCH


@dataclasses.dataclass
class Span:
    """One traced interval: explicit start/duration (seconds, epoch
    domain — use :func:`perf_to_epoch` on perf_counter stamps),
    parent/child structure via ``parent_id`` and Perfetto flow links via
    ``flow_in``/``flow_out`` (flow ids BEGIN at this span / TERMINATE at
    this span — how a batch-level span points at the job slots it
    carried).  ``track`` is the trace row (Chrome-trace ``tid``)."""

    name: str
    start: float
    dur_s: float
    track: str = "host"
    span_id: int = 0
    parent_id: Optional[int] = None
    flow_in: Tuple[int, ...] = ()
    flow_out: Tuple[int, ...] = ()
    args: Dict = dataclasses.field(default_factory=dict)


def _as_ids(v: Union[None, int, Tuple[int, ...], List[int]]) -> Tuple:
    if v is None:
        return ()
    if isinstance(v, int):
        return (v,)
    return tuple(v)


class SpanLog:
    """The process-wide span plane.  DISABLED by default: ``add`` is a
    no-op returning 0, so instrumented code paths (the serve batcher,
    the HTTP front door) pay one attribute read when tracing is off —
    and, because spans only ever consume host-side ``perf_counter``
    stamps that are taken regardless, tracing on/off is bit-identical
    in device results AND compile counts (tests/test_servescope.py pins
    it, the flight-recorder house rule applied to the host plane).

    ``cap`` bounds retained spans so a long-lived server with tracing
    enabled cannot grow without limit; overflow increments ``dropped``
    (surfaced in the export) instead of silently evicting."""

    def __init__(self, cap: int = 200_000):
        self.enabled = False
        self.cap = cap
        self.dropped = 0
        self._spans: List[Span] = []
        self._ids = itertools.count(1)
        self._flows = itertools.count(1)
        self._lock = threading.Lock()

    def enable(self) -> "SpanLog":
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def new_flow(self) -> int:
        """A fresh flow id (links an emitting span to consumers)."""
        return next(self._flows)

    def add(self, name: str, start: float, dur_s: float, *,
            track: str = "host", parent_id: Optional[int] = None,
            flow_in=None, flow_out=None,
            args: Optional[Dict] = None) -> int:
        """Record one span; returns its span id (0 when disabled)."""
        if not self.enabled:
            return 0
        span = Span(name=name, start=start, dur_s=max(0.0, dur_s),
                    track=track, span_id=next(self._ids),
                    parent_id=parent_id, flow_in=_as_ids(flow_in),
                    flow_out=_as_ids(flow_out), args=dict(args or {}))
        with self._lock:
            if len(self._spans) >= self.cap:
                self.dropped += 1
                return 0
            self._spans.append(span)
        return span.span_id

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


#: The process-wide span log (off until ``SPANS.enable()`` — e.g. the
#: CLI's ``serve/load --trace-out``).
SPANS = SpanLog()


# --------------------------------------------------------------------------
# Flight-recorder rendering (SimConfig.record buffers -> host structures)
# --------------------------------------------------------------------------


def written_round_indices(recorder) -> np.ndarray:
    """Indices of recorder rows that were actually written, ascending.

    A written row's decided + killed + undecided-class counts sum to
    T*N >= 1, so an all-zero row marks a round the loop never wrote.
    Contiguous in the common case (row 0 snapshot + rounds 1..R), but a
    ``resume_consensus(..., recorder=None)`` buffer legitimately has a
    GAP: row 0 re-snapshots the re-entry state and the next written row
    is ``from_round`` — every renderer here keys rows by their true
    round index instead of assuming contiguity.
    """
    rec = np.asarray(recorder)
    return np.nonzero(rec[:, :5].sum(axis=1) > 0)[0]


def executed_rows(recorder) -> np.ndarray:
    """The written rows of a recorder buffer (see written_round_indices
    for which), as int64 [n_written, REC_WIDTH]."""
    rec = np.asarray(recorder).astype(np.int64)
    return rec[written_round_indices(recorder)]


def round_history_rows(recorder,
                       since_round: Optional[int] = None) -> List[dict]:
    """Recorder buffer -> one dict per WRITTEN row, REC_COLUMNS-keyed plus
    the row's true round index ("round": 0 = post-/start snapshot;
    unwritten gap rows, e.g. before a fresh-buffer resume's re-entry
    round, are skipped).

    ``since_round`` is the incremental CURSOR: only rows with a round
    index STRICTLY greater are returned, so a poller that passes the
    last round it has seen receives exactly the new rows (and an empty
    list when the cursor is at — or past — the end).  Rows key on their
    TRUE round index, so the cursor is stable across a fresh-buffer
    resume's gap: a cursor inside the gap yields the post-gap rows."""
    from ..state import REC_COLUMNS
    rec = np.asarray(recorder).astype(np.int64)
    rows = []
    for r in written_round_indices(recorder):
        if since_round is not None and int(r) <= int(since_round):
            continue
        d = {"round": int(r)}
        d.update({col: int(v) for col, v in zip(REC_COLUMNS, rec[r])})
        rows.append(d)
    return rows


def round_history_summary(recorder) -> dict:
    """Derived science of one recorder buffer: the keys bench.py ships.

      rounds_executed           written rounds (excluding the first row,
                                the snapshot)
      rounds_to_quiescence      first written round with zero undecided
                                live lanes (None = never quiesced inside
                                the history)
      decide_velocity           newly decided lanes between consecutive
                                WRITTEN rows (diff of the cumulative
                                decided column) — per round in the common
                                contiguous case; across a fresh-resume
                                gap one entry aggregates the unobserved
                                rounds
      rounds_to_quiescence_hist histogram over lanes of their decide round
                                (numerically the velocity, exposed as the
                                lane-population histogram it is)
      final                     the last written row, REC_COLUMNS-keyed
    """
    from ..state import (REC_COLUMNS, REC_DECIDED, REC_UNDEC0, REC_UNDEC1,
                         REC_UNDECQ)
    rows = executed_rows(recorder)
    undec = rows[:, REC_UNDEC0] + rows[:, REC_UNDEC1] + rows[:, REC_UNDECQ]
    quiesced = np.nonzero(undec == 0)[0]
    idx = written_round_indices(recorder)
    velocity = np.diff(rows[:, REC_DECIDED]).tolist()
    return {
        "rounds_executed": int(rows.shape[0] - 1),
        "rounds_to_quiescence": (int(idx[quiesced[0]]) if quiesced.size
                                 else None),
        "decide_velocity": velocity,
        "rounds_to_quiescence_hist": velocity,
        "final": {c: int(v) for c, v in zip(REC_COLUMNS, rows[-1])},
    }


# --------------------------------------------------------------------------
# Exporters
#
# Thread-safety contract (meshscope's heartbeat publisher runs on the
# driver thread while HTTP handlers and pollers read): metric MUTATION
# is already serialized on _REGISTRY_LOCK; the exporters below
# additionally (a) write whole-file snapshots to a temp file and
# os.replace() it into place, so a concurrent reader (``watch``, a
# Prometheus textfile collector) never observes a torn document, and
# (b) serialize line APPENDS (append_jsonl) on _EXPORT_LOCK with one
# write() call per line, so interleaved writers cannot corrupt a
# JSON-lines stream.  tests/test_metrics.py hammers both concurrently.
# --------------------------------------------------------------------------

_EXPORT_LOCK = threading.Lock()


def _atomic_write(path: str, text: str) -> None:
    """Write ``text`` to ``path`` via temp-file + rename, so concurrent
    readers see either the old complete file or the new one — never a
    partial write."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)


def append_jsonl(path: str, record: dict) -> None:
    """Append ONE record as a JSON line (timestamped), line-atomically:
    the line is serialized first and written in a single call under the
    export lock, so concurrent in-process appenders (the heartbeat
    publisher vs. the main loop's exporter) cannot interleave bytes and
    a tailing reader (``python -m benor_tpu watch``) always parses."""
    line = json.dumps({"ts": time.time(), **record}) + "\n"
    with _EXPORT_LOCK:
        with open(path, "a") as fh:
            fh.write(line)


def export_jsonl(path: str, registry: MetricsRegistry = None,
                 extra: Optional[List[dict]] = None) -> int:
    """Write the registry snapshot (plus optional extra records, e.g.
    round_history_rows) as JSON-lines; returns the record count.
    Atomic (temp file + rename): a concurrent reader never sees a
    half-written snapshot."""
    registry = REGISTRY if registry is None else registry
    records = registry.snapshot() + list(extra or [])
    ts = time.time()
    text = "".join(json.dumps({"ts": ts, **rec}) + "\n"
                   for rec in records)
    with _EXPORT_LOCK:
        _atomic_write(path, text)
    return len(records)


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    return prefix + _PROM_BAD.sub("_", name)


def export_prometheus(path: str, registry: MetricsRegistry = None,
                      prefix: str = "benor_tpu_") -> int:
    """Write the registry in Prometheus textfile-collector format (the
    node_exporter drop-in contract: ``# TYPE`` headers + bare samples;
    timers expand to _count/_seconds_total/_seconds_max).  Returns the
    sample count."""
    registry = REGISTRY if registry is None else registry
    lines = []
    n = 0
    for m in registry.snapshot():
        name = _prom_name(m["name"], prefix)
        if m["type"] in ("counter", "gauge"):
            lines.append(f"# TYPE {name} {m['type']}")
            lines.append(f"{name} {m['value']}")
            n += 1
        else:
            lines.append(f"# TYPE {name}_count counter")
            lines.append(f"{name}_count {m['count']}")
            lines.append(f"# TYPE {name}_seconds_total counter")
            lines.append(f"{name}_seconds_total {m['total_s']}")
            lines.append(f"# TYPE {name}_seconds_max gauge")
            lines.append(f"{name}_seconds_max {m['max_s']}")
            n += 3
    with _EXPORT_LOCK:
        _atomic_write(path, "\n".join(lines) + "\n")
    return n


def export_chrome_trace(path: str, registry: MetricsRegistry = None,
                        round_history=None,
                        rounds_label: str = "consensus",
                        witness=None, spans=None) -> int:
    """Write a Chrome-trace/Perfetto JSON file; returns the event count.

    Timer spans land on pid 0 / tid "host" as complete ("X") events at
    their real wall-clock offsets.  ``round_history`` (a flight-recorder
    buffer) lands on tid "rounds" with a SYNTHETIC 1 ms-per-round
    timescale — the recorder is filled on device with no per-round host
    timestamps (that is the point) — each slice carrying its full
    telemetry row in ``args``.  ``witness`` (an audit.WitnessBundle, or
    a witness buffer paired with its watched ids as ``(buffer,
    trial_ids, node_ids)``) adds one track per watched (trial, node)
    lane on the same synthetic timescale, each round-slice carrying the
    lane's full evidence row (value, decided/killed/coined bits, p/v
    tallies) — the flight recorder's aggregates and the per-node
    forensics line up round for round.  Counters/gauges become metadata
    counter events.  ``spans`` renders a servescope span set (``True``
    for the process-wide :data:`SPANS` log, or an explicit Span list):
    each span is a complete event on its own track, parent ids ride in
    ``args``, and ``flow_out``/``flow_in`` ids become Chrome-trace flow
    start ("s") / finish ("f") event pairs — Perfetto draws the arrow
    from a batch launch to every job slot it carried.  Open in
    https://ui.perfetto.dev or chrome://tracing; ``jax.profiler.trace``
    captures of the same run sit alongside as separate tracks when
    loaded together.
    """
    registry = REGISTRY if registry is None else registry
    if spans is True:
        spans = SPANS.snapshot()
    events = []
    t0 = None
    snap = registry.snapshot()
    with _REGISTRY_LOCK:
        timers = [(m.name, list(m.events))
                  for m in registry._metrics.values()
                  if isinstance(m, Timer)]
    for _, evs in timers:
        for start, _ in evs:
            t0 = start if t0 is None else min(t0, start)
    for sp in spans or ():
        t0 = sp.start if t0 is None else min(t0, sp.start)
    t0 = t0 or time.time()
    for name, evs in timers:
        for start, dur in evs:
            events.append({
                "name": name, "ph": "X", "pid": 0, "tid": "host",
                "ts": (start - t0) * 1e6, "dur": dur * 1e6,
            })
    for m in snap:
        if m["type"] in ("counter", "gauge"):
            events.append({
                "name": m["name"], "ph": "C", "pid": 0, "ts": 0,
                "args": {m["type"]: m["value"]},
            })
    if round_history is not None:
        for row in round_history_rows(round_history):
            r = row["round"]
            events.append({
                "name": (f"{rounds_label} round {r}" if r
                         else f"{rounds_label} start"),
                "ph": "X", "pid": 0, "tid": "rounds",
                "ts": r * 1000.0, "dur": 1000.0,
                "args": {k: v for k, v in row.items() if k != "round"},
            })
    if witness is not None:
        from ..audit import witness_rows
        if hasattr(witness, "buffer"):              # a WitnessBundle
            buf, tids, nids = (witness.buffer, witness.trial_ids,
                               witness.node_ids)
        else:
            buf, tids, nids = witness
        for row in witness_rows(buf, tids, nids):
            r = row["round"]
            events.append({
                "name": (f"x={row['x']}"
                         + (" decided" if row["decided"] else "")
                         + (" killed" if row["killed"] else "")
                         + (" coin" if row["coined"] else "")),
                "ph": "X", "pid": 0,
                "tid": f"witness t{row['trial']} n{row['node']}",
                "ts": r * 1000.0, "dur": 1000.0,
                "args": {k: v for k, v in row.items()
                         if k not in ("round", "trial", "node")},
            })
    for sp in spans or ():
        ts = (sp.start - t0) * 1e6
        dur = sp.dur_s * 1e6
        args = dict(sp.args)
        args["span_id"] = sp.span_id
        if sp.parent_id is not None:
            args["parent_id"] = sp.parent_id
        events.append({"name": sp.name, "ph": "X", "pid": 0,
                       "tid": sp.track, "ts": ts, "dur": dur,
                       "args": args})
        # flow arrows: an id STARTS ("s") where flow_out names it and
        # FINISHES ("f", binding enclosing slice) where flow_in does —
        # the s event anchors at the span start, the f at the span start
        # too so the arrow lands on the consumer slice's left edge
        for fid in sp.flow_out:
            events.append({"name": "flow", "ph": "s", "id": fid,
                           "pid": 0, "tid": sp.track, "ts": ts})
        for fid in sp.flow_in:
            events.append({"name": "flow", "ph": "f", "bp": "e",
                           "id": fid, "pid": 0, "tid": sp.track,
                           "ts": ts})
    if spans is not None and SPANS.dropped:
        events.append({"name": "spans_dropped", "ph": "C", "pid": 0,
                       "ts": 0, "args": {"counter": SPANS.dropped}})
    with _EXPORT_LOCK:
        _atomic_write(path, json.dumps({"traceEvents": events,
                                        "displayTimeUnit": "ms"}))
    return len(events)
