"""XLA backend-compile accounting via the ``jax.monitoring`` hook.

The batched dynamic-F sweep engine's whole claim is "one compile per
static-shape bucket instead of one per point" — this module is how that
claim is *measured* rather than asserted: jax records a
``/jax/core/compile/backend_compile_duration`` event for every real
backend compile (jax/_src/dispatch.py BACKEND_COMPILE_EVENT, emitted by
the pjit lowering path on every platform), and ``count_backend_compiles``
scopes a counter over any code region.  sweep.run_curve_batched wraps its
compile+execute phase in one, bench.py wraps the regime warm-up, and
tests/test_batched_sweep.py pins the one-compile-per-bucket contract.

jax.monitoring has no per-listener deregistration (only a global
``clear_event_listeners``), so ONE process-lifetime listener is installed
lazily and fans out to whatever counters are currently in scope — zero
listeners touched on exit, nested scopes both count.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Iterator, List

#: The event jax records around every backend (XLA) compile — one event
#: per compiled executable, cache hits excluded.  Name pinned by
#: jax/_src/dispatch.py:BACKEND_COMPILE_EVENT.
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_active: List["CompileCounter"] = []


# eq=False: identity comparison — nested scopes hold counters that can be
# value-equal mid-flight, and the teardown's list.remove must take out the
# exact object, not the first look-alike.
@dataclasses.dataclass(eq=False)
class CompileCounter:
    """Mutable tally handed out by ``count_backend_compiles``."""

    count: int = 0
    seconds: float = 0.0


def _listener(event: str, duration: float, **kwargs) -> None:
    if event != BACKEND_COMPILE_EVENT:
        return
    with _lock:
        active = list(_active)
    for c in active:
        c.count += 1
        c.seconds += duration
    # unified metrics (utils/metrics.py): process-lifetime compile totals,
    # exportable even when no scoped counter is open
    from .metrics import REGISTRY
    REGISTRY.counter("jax.backend_compiles").inc()
    REGISTRY.counter("jax.backend_compile_seconds").inc(duration)


def _ensure_installed() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_listener)
        _installed = True


def install() -> None:
    """Install the process-lifetime listener WITHOUT opening a scope —
    for callers that only want the unified-metrics compile counters
    (utils/metrics.REGISTRY) fed, e.g. the CLI's --metrics-out.  Must
    run before the compiles it should observe."""
    _ensure_installed()


@contextlib.contextmanager
def count_backend_compiles() -> Iterator[CompileCounter]:
    """Count XLA backend compiles (and their total duration) in a scope.

    Counts every backend compile issued process-wide while the scope is
    open — including op-by-op dispatch compiles — so callers measuring a
    specific code path should build inputs (device_put, key creation,
    stacking) *before* entering the scope.
    """
    _ensure_installed()
    counter = CompileCounter()
    with _lock:
        _active.append(counter)
    try:
        yield counter
    finally:
        with _lock:
            _active.remove(counter)
