"""Checkpoint / resume (SURVEY.md §5.4).

The reference has no persistence at all — its entire run state lives in
per-node JS closures (src/nodes/node.ts:21-30) and a crash loses everything.
Here a checkpoint is one ``device_get`` of the structure-of-arrays state plus
the static config, and resume is one ``device_put`` followed by re-entering
the compiled round loop at the saved round index (sim.resume_consensus).
Because every random draw is keyed on (seed, round, phase, trial, node) —
never on loop history — a resumed run is bit-identical to an uninterrupted
one (verified by tests/test_checkpoint.py).

Format: a single ``.npz`` (state + fault arrays + round counter) with the
config embedded as a JSON string — self-describing, portable, no Orbax
dependency for what is a handful of flat arrays.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import SimConfig
from ..state import FaultSpec, NetState

# v2: added key_data (the run's base PRNG key) to the payload.
_FORMAT_VERSION = 2


def save_checkpoint(path: str, cfg: SimConfig, state: NetState,
                    faults: FaultSpec, next_round: int,
                    base_key: "jax.Array | None" = None,
                    mesh_shape: Optional[Tuple[int, int]] = None) -> None:
    """Snapshot a (possibly mid-run) simulation to ``path`` (.npz).

    ``next_round`` is the 1-based round index the loop would execute next —
    pass ``rounds_executed + 1`` from a capped ``run_consensus``.
    ``base_key`` is the PRNG key the run was started with; it is persisted
    (as raw key data) so resume continues the same random streams.  Omit it
    only if the run used the default ``jax.random.key(cfg.seed)``.
    ``mesh_shape`` optionally records the (trial_shards, node_shards)
    grid the run was placed on — provenance only, never a constraint:
    checkpoints stay mesh-agnostic and ``resume_from(mesh="auto")``
    merely PREFERS the recorded shape when the devices exist.
    """
    if base_key is None:
        base_key = jax.random.key(cfg.seed)
    payload = {
        "key_data": np.asarray(jax.random.key_data(base_key)),
        "x": np.asarray(state.x),
        "decided": np.asarray(state.decided),
        "k": np.asarray(state.k),
        "killed": np.asarray(state.killed),
        "faulty": np.asarray(faults.faulty),
        "crash_round": np.asarray(faults.crash_round),
        "next_round": np.int32(next_round),
        "version": np.int32(_FORMAT_VERSION),
        "config_json": np.bytes_(
            json.dumps(dataclasses.asdict(cfg)).encode()),
    }
    if faults.recover_round is not None:
        # crash_recover down-intervals (PR 15): an OPTIONAL key, so
        # archives from static-fault runs keep their exact byte layout
        payload["recover_round"] = np.asarray(faults.recover_round)
    if mesh_shape is not None:
        # 2D grid provenance (PR 16): same OPTIONAL-key discipline —
        # single-device archives keep their exact byte layout
        payload["mesh_shape"] = np.asarray(
            [int(s) for s in mesh_shape], dtype=np.int32)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **payload)
    os.replace(tmp, path)  # atomic: no torn checkpoints on crash


def load_checkpoint(path: str):
    """Load a checkpoint; returns (cfg, state, faults, next_round, base_key)."""
    with np.load(path, allow_pickle=False) as z:
        version = int(z["version"])
        # v1 archives that already carry key_data (written during the
        # pre-version-bump window) are fully loadable; bare v1 is not.
        if version != _FORMAT_VERSION and not (
                version == 1 and "key_data" in z.files):
            raise ValueError(f"unsupported checkpoint version {version}")
        raw = json.loads(bytes(z["config_json"]).decode())
        if raw.get("mesh_shape") is not None:
            raw["mesh_shape"] = tuple(raw["mesh_shape"])
        cfg = SimConfig(**raw)
        state = NetState(
            x=jnp.asarray(z["x"]), decided=jnp.asarray(z["decided"]),
            k=jnp.asarray(z["k"]), killed=jnp.asarray(z["killed"]))
        faults = FaultSpec(
            faulty=jnp.asarray(z["faulty"]),
            crash_round=jnp.asarray(z["crash_round"]),
            recover_round=(jnp.asarray(z["recover_round"])
                           if "recover_round" in z.files else None))
        next_round = int(z["next_round"])
        base_key = jax.random.wrap_key_data(jnp.asarray(z["key_data"]))
    return cfg, state, faults, next_round, base_key


def saved_mesh_shape(path: str) -> Optional[Tuple[int, int]]:
    """The (trial_shards, node_shards) recorded in ``path``, or None
    for archives written without grid provenance (pre-PR-16, or
    single-device runs)."""
    with np.load(path, allow_pickle=False) as z:
        if "mesh_shape" not in z.files:
            return None
        t, n = (int(v) for v in z["mesh_shape"])
    return t, n


def resume_from(path: str, mesh=None):
    """Load ``path`` and run the loop to termination.

    Returns (rounds_executed_total, final_state, faults) — ``rounds`` counts
    from the start of the original run, matching an uninterrupted
    ``run_consensus``.  Pass a ``jax.sharding.Mesh`` to resume on a device
    mesh: checkpoints are mesh-agnostic (randomness keys on global ids), so
    a single-device checkpoint resumes bit-identically on any mesh shape
    and vice versa.  Pass ``mesh="auto"`` to re-derive the placement from
    the archive's recorded grid shape (parallel/grid.py): the recorded
    (trial_shards, node_shards) when those devices exist here, else a
    single-device resume — bit-identical either way.
    """
    if mesh == "auto":
        import jax

        from ..parallel.grid import make_grid_mesh
        shape = saved_mesh_shape(path)
        mesh = None
        if shape is not None and shape != (1, 1) \
                and shape[0] * shape[1] <= len(jax.devices()):
            mesh = make_grid_mesh(trial_shards=shape[0],
                                  node_shards=shape[1])
    cfg, state, faults, next_round, base_key = load_checkpoint(path)
    if mesh is not None:
        from ..parallel import resume_consensus_sharded
        out = resume_consensus_sharded(
            cfg, state, faults, base_key, mesh, next_round)
    else:
        from ..sim import resume_consensus
        out = resume_consensus(cfg, state, faults, base_key, next_round)
    # under cfg.record the runners append the (resume-fresh) flight
    # recorder; the checkpoint return contract stays (rounds, final, faults)
    rounds, final = out[0], out[1]
    return rounds, final, faults
