"""Ambient-accelerator probing shared by bench.py and the CLI.

The axon TPU plugin has two known failure modes (observed across rounds —
see bench.py's round-1/round-2 postmortems): a fast ``UNAVAILABLE`` raise
at client creation, and an INDEFINITE hang at backend init when the chip
is unreachable.  Both make "just import jax and try" unusable for anything
that must not wedge the caller, so the probe runs in a THROWAWAY
subprocess with a timeout.  One implementation, used by bench.py's
acquire_platform (3 x 150 s, backoff — the artifact path can afford
patience) and the CLI's _ensure_live_backend (2 x 120 s — interactive).
"""

from __future__ import annotations

import subprocess
import sys
import time
from typing import Callable, Optional

#: Probe payload: initializes the ambient backend and reports its platform.
PROBE_CODE = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"

#: Default (N, trials) for the flagship workloads at full accelerator
#: scale vs the CPU smoke scale — ONE definition shared by bench.py and
#: the results CLI so their platform-aware defaults cannot drift.
FULL_SCALE = (1_000_000, 32)
SMOKE_SCALE = (50_000, 8)


def default_scale(on_cpu: bool) -> tuple[int, int]:
    """(n_nodes, trials) defaults for the platform class."""
    return SMOKE_SCALE if on_cpu else FULL_SCALE


def probe_backend(timeout_s: float,
                  log: Optional[Callable[[str], None]] = None,
                  cwd: Optional[str] = None) -> Optional[str]:
    """Initialize the ambient JAX backend in a subprocess; return its
    platform name ('tpu'/'axon'/'cpu'/...), or None on failure/timeout.
    ``log`` receives one diagnostic line on failure (rc + stderr tail, or
    the timeout)."""
    from .metrics import REGISTRY

    say = log or (lambda s: None)
    REGISTRY.counter("backend.probe_attempts").inc()
    t0 = time.perf_counter()
    try:
        r = subprocess.run([sys.executable, "-c", PROBE_CODE],
                           capture_output=True, text=True,
                           timeout=timeout_s, cwd=cwd)
    except subprocess.TimeoutExpired:
        REGISTRY.counter("backend.probe_timeouts").inc()
        say(f"backend probe timed out after {timeout_s:.0f}s")
        return None
    finally:
        REGISTRY.counter("backend.probe_seconds").inc(
            time.perf_counter() - t0)
    if r.returncode != 0:
        REGISTRY.counter("backend.probe_failures").inc()
        tail = (r.stderr or "").strip().splitlines()[-1:]
        say(f"backend probe failed rc={r.returncode} {tail}")
        return None
    for line in r.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1].strip()
    return None


def probe_with_retries(retries: int, timeout_s: float, backoff_s: float,
                       log: Optional[Callable[[str], None]] = None,
                       cwd: Optional[str] = None) -> Optional[str]:
    """probe_backend with retry + linear backoff; returns the first
    non-cpu platform seen, 'cpu' immediately if that IS the ambient
    backend, or None if the accelerator never comes up."""
    for attempt in range(retries):
        plat = probe_backend(timeout_s, log=log, cwd=cwd)
        if plat:
            return plat
        if attempt < retries - 1:
            wait = backoff_s * (attempt + 1)
            if log:
                log(f"backend unavailable (attempt {attempt + 1}/"
                    f"{retries}); retry in {wait:.0f}s")
            time.sleep(wait)
    return None
