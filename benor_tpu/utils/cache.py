"""Persistent XLA compilation cache (accelerator backends only).

Under the axon tunnel every distinct SimConfig costs an ~8-40 s remote
compile; the persistent cache cuts repeat invocations (bench reps, results
regeneration, driver re-runs) to seconds — measured 52.7 s -> 12.7 s for
the bench's 10-regime warm-up.  Failures are logged and ignored: a cache
problem must never take down a run.

The CPU backend is EXCLUDED.  XLA:CPU entries are AOT artifacts tied to
the exact machine profile of the writer, the cache key does not include
that profile, and the (de)serializer is not crash-safe: on 2026-07-31
three consecutive full-suite runs on a migrated workspace segfaulted
inside compilation_cache.get_executable_and_time (loading an entry
written by an earlier-round host — the "Machine type used for XLA:CPU
compilation doesn't match" warning path) and put_executable_and_time
(serializing a fresh entry), while the identical tests pass with the
cache off.  CPU compiles are local and comparatively cheap; the cache's
real value is the REMOTE accelerator compiles — so the CPU lane simply
runs uncached.
"""

from __future__ import annotations

import sys


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point jax at a persistent compilation cache directory.

    Default location: `.jax_cache/` next to the repository root (one
    level above this package) — kept inside the workspace so it survives
    across driver invocations, .gitignore'd.  No-op on the CPU backend
    (see module docstring) unless an explicit ``cache_dir`` is passed.
    """
    try:
        import os

        import jax
        if cache_dir is None:
            if jax.default_backend() == "cpu":
                return
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            cache_dir = os.path.join(pkg_root, ".jax_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # benorlint: allow-broad-except — a cold cache only costs time
    except Exception as e:  # noqa: BLE001 — strictly best-effort
        print(f"[benor_tpu] compile cache unavailable: {e}",
              file=sys.stderr, flush=True)
