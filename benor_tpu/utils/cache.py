"""Persistent XLA compilation cache (best-effort).

Under the axon tunnel every distinct SimConfig costs an ~8-40 s remote
compile; the persistent cache cuts repeat invocations (bench reps, results
regeneration, driver re-runs) to seconds — measured 52.7 s -> 12.7 s for
the bench's 10-regime warm-up.  Failures are logged and ignored: a cache
problem must never take down a run.
"""

from __future__ import annotations

import os
import sys


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point jax at a persistent compilation cache directory.

    Default location: `.jax_cache/` next to the repository root (one level
    above this package) — kept inside the workspace so it survives across
    driver invocations and is .gitignore'd.
    """
    try:
        import jax
        if cache_dir is None:
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            cache_dir = os.path.join(pkg_root, ".jax_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # noqa: BLE001 — strictly best-effort
        print(f"[benor_tpu] compile cache unavailable: {e}",
              file=sys.stderr, flush=True)
