"""Tracing / profiling hooks (SURVEY.md §5.1).

The reference's observability is ``console.log`` on decisions
(src/nodes/node.ts:71) and on listen (node.ts:203-205).  Here the round loop
is one fused device program, so per-round visibility needs an explicit
escape hatch: with ``SimConfig(debug=True)`` the simulator emits one
``jax.debug.callback`` per executed round carrying (round, #decided,
#killed) — streamed to every registered sink without leaving the compiled
while-loop.

PERF CLIFF — debug is NOT zero-cost in the fused-pallas regime: host
callbacks cannot run inside the packed round kernels, so a
pallas-round-eligible config with debug=True is silently DEMOTED to the
per-round XLA loop (sim.warn_debug_demotes_pallas fires once per
process).  debug=False still costs nothing anywhere, and off the fused
regime the callback cost is one async host transfer per round.  For
observation that does not change which code runs, use
``SimConfig(record=True)`` — the flight recorder fills on device inside
the fused loop (README "Observability" has the decision table).

``profile_trace`` wraps ``jax.profiler.trace`` for XLA-level traces
viewable in TensorBoard / Perfetto.
"""

from __future__ import annotations

import contextlib
import sys
import time
from typing import Callable, List

import jax

#: Registered sinks; each is called as sink(round, n_decided, n_killed).
_SINKS: List[Callable[[int, int, int], None]] = []


def default_sink(r: int, n_decided: int, n_killed: int) -> None:
    print(f"[benor_tpu] round {int(r)}: decided={int(n_decided)} "
          f"killed={int(n_killed)}", file=sys.stderr, flush=True)


def add_sink(sink: Callable[[int, int, int], None]) -> None:
    _SINKS.append(sink)


def remove_sink(sink: Callable[[int, int, int], None]) -> None:
    _SINKS.remove(sink)


def round_callback(r, n_decided, n_killed) -> None:
    """Host-side fanout invoked (async, ordered) once per executed round."""
    sinks = _SINKS or [default_sink]
    for sink in sinks:
        sink(int(r), int(n_decided), int(n_killed))


def emit_round_event(state, ctx=None) -> None:
    """Called from the jitted round loop when cfg.debug is set.

    Single device: ``ordered=True`` threads a sequencing token through the
    loop so sinks observe rounds in execution order even with async host
    dispatch; the cost only exists when cfg.debug is set (otherwise nothing
    is traced in).

    Under ``shard_map`` (pass the kernel's ``ShardCtx``): counts are first
    globalized with ``psum`` over every mesh axis, then exactly ONE shard —
    mesh coordinate (0, 0) — emits the callback via ``lax.cond``, so sinks
    see one event per round with network-global numbers, same as the
    single-device path.  Limitation: ordered effects are unsupported on >1
    device (jax raises "ordered effects are not supported for more than 1
    device"), so the sharded emission is ``ordered=False`` — events carry
    the round index and in practice arrive in order from the single emitting
    shard, but cross-round ordering is best-effort, not guaranteed.
    """
    import jax.numpy as jnp
    from jax import lax
    if ctx is None or (ctx.trial_axis is None and ctx.node_axis is None):
        jax.debug.callback(round_callback, state.k.max(),
                           jnp.sum(state.decided), jnp.sum(state.killed),
                           ordered=True)
        return
    k_max = lax.pmax(state.k.max(), tuple(
        a for a in (ctx.trial_axis, ctx.node_axis) if a is not None))
    n_dec = ctx.psum_all(jnp.sum(state.decided))
    n_kil = ctx.psum_all(jnp.sum(state.killed))
    is_origin = jnp.bool_(True)
    for a in (ctx.trial_axis, ctx.node_axis):
        if a is not None:
            is_origin &= lax.axis_index(a) == 0
    lax.cond(
        is_origin,
        lambda: jax.debug.callback(round_callback, k_max, n_dec, n_kil,
                                   ordered=False),
        lambda: None)


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """XLA profiler trace around a block: TensorBoard-compatible output.

    Yields the trace directory path, so callers can report where the
    capture landed (``python -m benor_tpu profile --trace-dir`` prints
    it) or post-process the files; each completed capture also ticks the
    ``tracing.profile_capture`` counter in the unified metrics registry,
    making profiler runs visible in the JSON-lines / Prometheus /
    Chrome-trace exports next to the compile and probe accounting."""
    from .metrics import REGISTRY
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
        REGISTRY.counter("tracing.profile_capture").inc()


@contextlib.contextmanager
def timed(label: str, sink=None):
    """Wall-clock a host-side block; prints to stderr by default.

    Every span ALSO records into the unified metrics registry
    (utils/metrics.REGISTRY timer ``label``), so ad-hoc timings show up
    in the JSON-lines / Prometheus / Chrome-trace exports next to the
    compile and probe counters."""
    from .metrics import REGISTRY
    start = time.time()
    t0 = time.perf_counter()
    yield
    dt = time.perf_counter() - t0
    REGISTRY.timer(label).record(dt, start=start)
    msg = f"[benor_tpu] {label}: {dt * 1e3:.1f} ms"
    (sink or (lambda m: print(m, file=sys.stderr, flush=True)))(msg)
