"""Host-side utilities: checkpointing, profiling, logging."""
