"""benor_tpu — a TPU-native randomized-consensus simulation framework.

A brand-new framework with the capabilities of
``viviendbk/ben-or-consensus-algorithm`` (the Ben-Or binary consensus
protocol, its crash-fault model, its start/stop/status/getState control API
and its integration-test contract), re-hosted as vectorized JAX device
arrays: all N nodes' state lives in [trials, N] tensors and one protocol
round is one compiled kernel instead of O(N^2) localhost HTTP requests.

Layout (SURVEY.md §7):
  config.py        static SimConfig (the reference's src/config.ts + flags)
  state.py         NetState / FaultSpec arrays (N2)
  models/benor.py  the round kernel (N3)
  ops/             tally, scheduler, sampling, rng (N4, N6, N9)
  parallel/        mesh + shard_map distribution (N7)
  backends/        'tpu' array network + 'express' asyncio oracle (N1)
  sim.py           while-loop driver + checkpoint re-entry
  api.py           launch_network parity facade (N10)
  utils/metrics.py unified metrics registry + flight-recorder rendering
                   (SimConfig.record; see README "Observability")
  audit.py         witness traces + protocol invariant auditor
                   (SimConfig.witness_trials; per-node forensics for
                   every regime — see README "Observability")
  topo/            adjacency- and committee-structured delivery planes
                   (SimConfig.topology / committee_*; O(N*d) neighbor
                   tallies, per-round sampled committees, rounds-vs-
                   degree curves — see README "Topology & committees")
"""

from .api import (get_nodes_state, launch_network, reached_finality,
                  start_consensus, stop_consensus)
from .config import (BASE_NODE_PORT, SimConfig, VAL0, VAL1, VALQ,
                     WITNESS_MAX_NODES)
from .state import (DynParams, FaultSpec, NetState, REC_COLUMNS, REC_WIDTH,
                    WIT_COLUMNS, WIT_WIDTH, init_state, new_recorder,
                    new_witness, observable_state, witness_node_ids)
from .sim import (run_consensus, run_consensus_traced, resume_consensus,
                  simulate, start_state)

__all__ = [
    "BASE_NODE_PORT", "SimConfig", "VAL0", "VAL1", "VALQ",
    "WITNESS_MAX_NODES",
    "DynParams", "FaultSpec", "NetState", "init_state", "observable_state",
    "REC_COLUMNS", "REC_WIDTH", "new_recorder",
    "WIT_COLUMNS", "WIT_WIDTH", "new_witness", "witness_node_ids",
    "run_consensus", "run_consensus_traced", "resume_consensus",
    "simulate", "start_state",
    "launch_network", "start_consensus", "stop_consensus",
    "get_nodes_state", "reached_finality",
]

__version__ = "0.5.0"  # kept in sync with pyproject.toml
