"""Seeded asynchronous-adversary message scheduler (SURVEY.md N9).

The reference's asynchrony is accidental: a node tallies whichever N-F
messages the Node.js event loop happens to deliver first (node.ts:52,88).
Here that nondeterminism is explicit, deterministic and seeded.  Three
schedulers, selected by ``SimConfig.scheduler``:

  uniform:      every (receiver, sender) edge draws an iid delay; the N-F
                smallest delays per receiver define the tallied multiset.
  biased:       uniform delays plus ``adversary_strength`` added to edges
                whose message carries the value the receiver's parity class
                is being starved of — a *delay-bounded* adversary whose
                power is limited by quorum overlap.  The histogram path
                mirrors this at any strength: strict priority (exact) at
                strength >= 1 (tally.biased_priority_counts), the
                uniform-race model at 0 < s < 1
                (tally.biased_fractional_counts).
  adversarial:  the worst-case *count-controlling* adversary — handled in
                ops/tally.py (both paths): every receiver tallies a multiset
                whose 0/1 counts tie, so phase-1 yields "?" and private-coin
                runs livelock; the common coin defeats it in O(1) rounds.
  targeted:     the *partitioned* count-controlling adversary (agreement
                attack): closed form on both paths in
                ops/tally.py:targeted_counts; realize_counts_mask below
                builds the equivalent explicit per-edge mask, proving the
                closed form corresponds to a realizable schedule
                (test witness, not the runtime path).

Dense path only in this module (N x N mask, N <= dense_path_max_n).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import SimConfig, VAL0, VAL1, VALQ
from . import rng


def full_delivery_mask(alive: jax.Array) -> jax.Array:
    """delivery == 'all': every live sender reaches every receiver.

    alive: bool [T, N] -> mask bool [T, N_recv, N_send].
    (Broadcast includes self, matching reference loops i = 0..N-1 at
    node.ts:72,149,173 — quirk 6.)
    """
    T, N = alive.shape
    return jnp.broadcast_to(alive[:, None, :], (T, N, N))


def quorum_delivery_mask(cfg: SimConfig, base_key: jax.Array, r: jax.Array,
                         phase: int, sent: jax.Array, alive: jax.Array,
                         trial_ids=None, recv_ids=None) -> jax.Array:
    """Per-receiver top-(N-F) arrival mask for 'uniform'/'biased' schedulers.

    sent: int8 [T, N_send] GLOBAL sender values this phase (used only by the
    biased scheduler); alive: bool [T, N_send].  ``trial_ids``/``recv_ids``
    are the global ids of this shard's trials/receivers (defaults: unsharded
    0..T-1 / 0..N-1).  Returns bool [T, N_recv, N_send] selecting, for each
    local receiver, the min(N-F, #alive) live senders with smallest delays —
    delays keyed on global (trial, receiver, sender) ids, so the mask is
    bit-identical across mesh shapes.
    """
    T, N = alive.shape
    if trial_ids is None:
        trial_ids = rng.ids(T)
    if recv_ids is None:
        recv_ids = rng.ids(N)
    n_recv = recv_ids.shape[0]
    m = cfg.quorum
    delays = rng.edge_uniforms(base_key, r, phase, trial_ids, recv_ids,
                               rng.ids(N))                   # [T, n_recv, N]

    if cfg.scheduler == "biased" and cfg.adversary_strength != 0.0:
        # Split-bias: even receivers' 1-carrying edges and odd receivers'
        # 0-carrying edges are delayed, so the two halves of the network see
        # opposite majorities.  Bounded adversary: once the quorum N-F forces
        # overlap with the starved class, messages get through regardless —
        # use scheduler='adversarial' for the unbounded worst case.
        even_recv = (recv_ids % 2 == 0)[None, :, None]       # [1, n_recv, 1]
        carries0 = (sent == VAL0)[:, None, :]
        carries1 = (sent == VAL1)[:, None, :]
        starved = jnp.where(even_recv, carries1, carries0)
        delays = delays + cfg.adversary_strength * starved.astype(jnp.float32)

    delays = jnp.where(alive[:, None, :], delays, jnp.inf)
    return _top_m_mask(delays, m) & alive[:, None, :]


def omission_delivery_mask(cfg: SimConfig, base_key: jax.Array,
                           r: jax.Array, phase: int, alive_g: jax.Array,
                           drop_p: jax.Array, trial_ids=None,
                           recv_ids=None, part=None) -> jax.Array:
    """Full delivery minus per-edge iid omission (SimConfig.drop_prob),
    intersected with the partition epoch's group mask when one is armed
    -> bool [T, N_recv, N_send].

    The DENSE-path realization of the faultlab omission plane
    (benor_tpu/faults): each (receiver, live sender) edge — self
    included; the reference's self-broadcast is a localhost fetch like
    any other (node.ts:72) — survives with probability ``1 - drop_p``,
    from a dedicated per-edge stream (salt ``phase + 8``, the same salt
    family as the histogram path's thinning draws).  ``drop_p`` may be
    traced (the DynParams sweep axis); the mask's shape never depends on
    it.  The histogram path's closed-form binomial thinning
    (tally.omission_thin_counts) is the O(N) twin; this mask is its
    exact edge-level oracle (tests/test_faults.py compares the two
    statistically, the dense/histogram duality every scheduler keeps).

    ``part`` (faults.partitions.PartitionSpec or None): during the
    epoch (r < heal_round) cross-group edges are additionally lost —
    deterministically, before any omission randomness.
    """
    T, N = alive_g.shape
    if trial_ids is None:
        trial_ids = rng.ids(T)
    if recv_ids is None:
        recv_ids = rng.ids(N)
    u = rng.edge_uniforms(base_key, r, phase + 8, trial_ids, recv_ids,
                          rng.ids(N))                     # [T, n_recv, N]
    mask = alive_g[:, None, :] & (u >= jnp.asarray(drop_p, jnp.float32))
    if part is not None:
        from ..faults.partitions import group_of
        g_recv = group_of(recv_ids, cfg.n_nodes, part.groups)
        g_send = group_of(rng.ids(N), cfg.n_nodes, part.groups)
        same = (g_recv[:, None] == g_send[None, :])[None, :, :]
        healed = jnp.asarray(r, jnp.int32) >= part.heal_round
        mask = mask & (same | healed)
    return mask


def _top_m_mask(delays: jax.Array, m: int) -> jax.Array:
    """bool mask of the m smallest entries per receiver row.

    If fewer than m senders are alive (inf-delay slots selected), callers
    intersect with alive so those rows tally only live senders — and the
    quorum gate in the round kernel stalls them, as the reference would.
    """
    T, n_recv, N = delays.shape
    _, idx = jax.lax.top_k(-delays, m)                       # [T, n_recv, m]
    mask = jnp.zeros((T, n_recv, N), bool)
    return jax.vmap(jax.vmap(lambda row, i: row.at[i].set(True)))(mask, idx)


def realize_counts_mask(counts: jax.Array, sent: jax.Array,
                        alive: jax.Array) -> jax.Array:
    """Realize per-receiver class-count quotas as an explicit delivery mask.

    The count-controlling adversaries (tally.adversarial_counts /
    targeted_counts) specify WHAT each receiver tallies as closed-form
    class counts.  This builds a concrete schedule achieving them: sender
    s reaches receiver r iff s's rank among live senders of its own class
    is below r's quota for that class.  dense_counts(mask, ...) then
    reproduces ``counts`` bit-for-bit (per-receiver class counts depend
    only on how many of each class arrive, not which members) — proving
    the closed forms are schedules an asynchronous network could actually
    exhibit, not just abstract count assignments.  Test witness
    (tests/test_targeted.py); not on the runtime path.

    counts: int32 [T, n_recv, 3] desired per-receiver (c0, c1, cq) over
    honest live senders; sent: int8 [T, N_send]; alive: bool [T, N_send].
    Quotas must not exceed the live class populations (the closed forms
    guarantee this).  Returns bool [T, n_recv, N_send].
    """
    # rank of each sender within its own (value-class, liveness) cohort
    rank = jnp.zeros(sent.shape, jnp.int32)
    for v in (VAL0, VAL1, VALQ):
        in_class = (sent == v) & alive
        r_v = jnp.cumsum(in_class.astype(jnp.int32), axis=-1) - 1
        rank = jnp.where(in_class, r_v, rank)
    quota = jnp.take_along_axis(
        counts, jnp.broadcast_to(
            sent.astype(jnp.int32)[:, None, :],
            counts.shape[:2] + (sent.shape[-1],)), axis=-1)
    return (rank[:, None, :] < quota) & alive[:, None, :]
