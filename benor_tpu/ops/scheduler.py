"""Seeded asynchronous-adversary message scheduler (SURVEY.md N9).

The reference's asynchrony is accidental: a node tallies whichever N-F
messages the Node.js event loop happens to deliver first (node.ts:52,88).
Here that nondeterminism is explicit, deterministic and seeded.  Three
schedulers, selected by ``SimConfig.scheduler``:

  uniform:      every (receiver, sender) edge draws an iid delay; the N-F
                smallest delays per receiver define the tallied multiset.
  biased:       uniform delays plus ``adversary_strength`` added to edges
                whose message carries the value the receiver's parity class
                is being starved of — a *delay-bounded* adversary whose
                power is limited by quorum overlap.  The histogram path
                mirrors this at any strength: strict priority (exact) at
                strength >= 1 (tally.biased_priority_counts), the
                uniform-race model at 0 < s < 1
                (tally.biased_fractional_counts).
  adversarial:  the worst-case *count-controlling* adversary — handled in
                ops/tally.py (both paths): every receiver tallies a multiset
                whose 0/1 counts tie, so phase-1 yields "?" and private-coin
                runs livelock; the common coin defeats it in O(1) rounds.

Dense path only in this module (N x N mask, N <= dense_path_max_n).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import SimConfig, VAL0, VAL1
from . import rng


def full_delivery_mask(alive: jax.Array) -> jax.Array:
    """delivery == 'all': every live sender reaches every receiver.

    alive: bool [T, N] -> mask bool [T, N_recv, N_send].
    (Broadcast includes self, matching reference loops i = 0..N-1 at
    node.ts:72,149,173 — quirk 6.)
    """
    T, N = alive.shape
    return jnp.broadcast_to(alive[:, None, :], (T, N, N))


def quorum_delivery_mask(cfg: SimConfig, base_key: jax.Array, r: jax.Array,
                         phase: int, sent: jax.Array, alive: jax.Array,
                         trial_ids=None, recv_ids=None) -> jax.Array:
    """Per-receiver top-(N-F) arrival mask for 'uniform'/'biased' schedulers.

    sent: int8 [T, N_send] GLOBAL sender values this phase (used only by the
    biased scheduler); alive: bool [T, N_send].  ``trial_ids``/``recv_ids``
    are the global ids of this shard's trials/receivers (defaults: unsharded
    0..T-1 / 0..N-1).  Returns bool [T, N_recv, N_send] selecting, for each
    local receiver, the min(N-F, #alive) live senders with smallest delays —
    delays keyed on global (trial, receiver, sender) ids, so the mask is
    bit-identical across mesh shapes.
    """
    T, N = alive.shape
    if trial_ids is None:
        trial_ids = rng.ids(T)
    if recv_ids is None:
        recv_ids = rng.ids(N)
    n_recv = recv_ids.shape[0]
    m = cfg.quorum
    delays = rng.edge_uniforms(base_key, r, phase, trial_ids, recv_ids,
                               rng.ids(N))                   # [T, n_recv, N]

    if cfg.scheduler == "biased" and cfg.adversary_strength != 0.0:
        # Split-bias: even receivers' 1-carrying edges and odd receivers'
        # 0-carrying edges are delayed, so the two halves of the network see
        # opposite majorities.  Bounded adversary: once the quorum N-F forces
        # overlap with the starved class, messages get through regardless —
        # use scheduler='adversarial' for the unbounded worst case.
        even_recv = (recv_ids % 2 == 0)[None, :, None]       # [1, n_recv, 1]
        carries0 = (sent == VAL0)[:, None, :]
        carries1 = (sent == VAL1)[:, None, :]
        starved = jnp.where(even_recv, carries1, carries0)
        delays = delays + cfg.adversary_strength * starved.astype(jnp.float32)

    delays = jnp.where(alive[:, None, :], delays, jnp.inf)
    # top-(m) smallest delays per receiver row
    _, idx = jax.lax.top_k(-delays, m)                       # [T, n_recv, m]
    mask = jnp.zeros((T, n_recv, N), bool)
    mask = jax.vmap(jax.vmap(lambda row, i: row.at[i].set(True)))(mask, idx)
    # If fewer than m senders are alive, top_k picked dead (inf-delay) slots;
    # intersect with alive so those rows tally only live senders (and the
    # quorum gate in the round kernel stalls them, as the reference would).
    return mask & alive[:, None, :]
