"""Hypergeometric quorum-count sampling (SURVEY.md §7 stage 5).

At N = 1M nodes the dense N x N delivery mask is impossible (10^12 entries).
But Ben-Or messages are broadcast scalars over the 3-value domain {0, 1, "?"},
so a receiver that tallies "the first N-F arrivals" (reference node.ts:52,88)
is statistically drawing N-F senders *without replacement* from the global
multiset of sent values — i.e. its per-class tallied counts follow a
multivariate hypergeometric distribution over the global class histogram.
Sampling those counts directly is O(1) per lane: O(N) per round network-wide.

Exactness strategy, switched on the (static) quorum size m:

  m <= EXACT_TABLE_MAX — exact inverse-CDF for the class-0 count ``h0``: the
    pmf depends only on trial-global quantities (total, c0, m), so one
    [T, m+1] CDF table is shared by all N lanes of a trial; each lane draws
    its own uniform and binary-searches the shared CDF.  ``h1 | h0`` uses the
    normal approximation (its parameters vary per lane through h0, so an
    exact shared table would be an O(m^2) blowup).

  m > EXACT_TABLE_MAX — Cornish-Fisher (skew-corrected normal) quantiles for
    both classes.  Rationale: binary-searching a shared CDF is a
    gather-per-step op, and TPU gather throughput (~4e7/s) makes it ~8 s per
    phase at [32 trials x 1e6 lanes] — while at m ~ 10^5-10^6 the
    hypergeometric is within O(1/sqrt(m)) of its normal limit and count
    errors of +-1-2 sit far inside one standard deviation, invisible to the
    protocol's > F threshold tests.  The approximation is gather-free
    elementwise VPU math: ~75 ms at the same shape, a ~100x speedup.

``tests/test_sampling.py`` KS-checks the end-to-end rounds-to-decide
distribution against the exact dense path at small N (exact regime) and
checks Cornish-Fisher quantiles against scipy's ppf in the approx regime.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import gammaln

#: Largest quorum for which h0 uses the exact shared-CDF inverse sampler.
#: Above this the table search's gather cost dominates the whole round and
#: the normal limit is accurate to well under one standard deviation.
EXACT_TABLE_MAX = 4096


def static_m(m) -> int | None:
    """The Python value of a draw count, or None when it is traced.

    The batched dynamic-F sweep (sweep.run_curve_batched) threads the
    quorum through these samplers as a TRACED int32 scalar; the exact
    shared-CDF samplers build [T, m+1] tables and therefore need a static
    m — callers use this to pick the CF branch under tracing.  The
    engine's bucketing (sweep.quorum_specialized) guarantees a traced m
    only ever reaches the CF regime, so the branch choice is unchanged.
    """
    return int(m) if isinstance(m, (int, np.integer)) else None


def _log_comb(n, k):
    """log C(n, k) with -inf outside the valid range; float32 inputs."""
    n = n.astype(jnp.float32)
    k = k.astype(jnp.float32)
    valid = (k >= 0) & (k <= n)
    k_safe = jnp.clip(k, 0.0, jnp.maximum(n, 0.0))
    out = gammaln(n + 1) - gammaln(k_safe + 1) - gammaln(n - k_safe + 1)
    return jnp.where(valid, out, -jnp.inf)


def hypergeom_cdf_table(total: jax.Array, good: jax.Array, m: int) -> jax.Array:
    """CDF of Hypergeometric(total, good, m) over support h = 0..m.

    total, good: int32 [...], broadcastable; returns float32 [..., m+1].
    Computed in log space then normalized (tolerates float32 lgamma error).
    """
    h = jnp.arange(m + 1, dtype=jnp.int32)
    shape = total.shape + (m + 1,)
    t = jnp.broadcast_to(total[..., None], shape)
    g = jnp.broadcast_to(good[..., None], shape)
    logpmf = (_log_comb(g, h) + _log_comb(t - g, m - h) - _log_comb(t, jnp.full_like(h, m)))
    logpmf = jnp.where(jnp.isfinite(logpmf), logpmf, -jnp.inf)
    mx = jnp.max(logpmf, axis=-1, keepdims=True)
    pmf = jnp.exp(logpmf - jnp.where(jnp.isfinite(mx), mx, 0.0))
    pmf = pmf / jnp.maximum(jnp.sum(pmf, axis=-1, keepdims=True), 1e-30)
    return jnp.cumsum(pmf, axis=-1)


def hypergeom_exact_shared(u: jax.Array, total: jax.Array, good: jax.Array,
                           m: int) -> jax.Array:
    """Exact hypergeometric draws from per-trial parameters shared by lanes.

    u: float32 [T, N] per-lane uniforms; total/good: int32 [T].
    Returns int32 [T, N] counts h ~ Hypergeom(total, good, m).
    """
    cdf = hypergeom_cdf_table(total, good, m)              # [T, m+1]
    # searchsorted per trial row against that trial's lanes
    idx = jax.vmap(lambda c, uu: jnp.searchsorted(c, uu))(cdf, u)
    return jnp.clip(idx, 0, m).astype(jnp.int32)


def hypergeom_normal_approx(u: jax.Array, total: jax.Array, good: jax.Array,
                            nsample: jax.Array,
                            skew_correct: bool = False) -> jax.Array:
    """Clamped normal-approximation hypergeometric draws, fully per-lane.

    u: uniforms [...]; total/good/nsample: int32 broadcastable to u's shape.
    ``skew_correct`` applies the second-order Cornish-Fisher term
    z + (z^2 - 1) * skewness / 6, tightening tail quantiles in the
    large-m regime (used when the exact table sampler is disabled).
    """
    t = jnp.maximum(total.astype(jnp.float32), 1.0)
    g = good.astype(jnp.float32)
    n = nsample.astype(jnp.float32)
    p = g / t
    mean = n * p
    fpc = jnp.where(t > 1, (t - n) / jnp.maximum(t - 1, 1.0), 0.0)
    var = jnp.maximum(n * p * (1 - p) * fpc, 0.0)
    z = jax.scipy.special.ndtri(jnp.clip(u, 1e-7, 1 - 1e-7))
    if skew_correct:
        # hypergeometric skewness (guarded against degenerate denominators)
        denom = jnp.sqrt(jnp.maximum(n * g * (t - g) * (t - n), 1.0)) * \
            jnp.maximum(t - 2.0, 1.0)
        skew = (t - 2 * g) * jnp.sqrt(jnp.maximum(t - 1.0, 0.0)) * \
            (t - 2 * n) / denom
        z = z + (z * z - 1.0) * skew / 6.0
    draw = jnp.round(mean + z * jnp.sqrt(var))
    lo = jnp.maximum(0.0, n - (t - g))
    hi = jnp.minimum(g, n)
    return jnp.clip(draw, lo, hi).astype(jnp.int32)


def uniform_race_favored_count(u: jax.Array, nf: jax.Array, ns: jax.Array,
                               m: int, s: float) -> jax.Array:
    """#favored among the m smallest of a two-population uniform delay race.

    The dense biased scheduler (ops/scheduler.py) gives favored edges delays
    ~ U[0, 1) and starved edges ~ U[s, 1+s); a receiver tallies the m
    smallest.  The favored count J has an exact mean-field solution because
    both delay CDFs are piecewise linear: with threshold tau solving
    nf*F_f(tau) + ns*F_s(tau) = m,

        F_f(t) = clip(t, 0, 1),  F_s(t) = clip(t - s, 0, 1),

    tau has three closed-form regimes (before the starved window opens; in
    the competition window; all favored exhausted).  Fluctuations come from
    the delta method on the counting processes: with densities lam_f =
    nf*f_f(tau), lam_s = ns*f_s(tau) and binomial variances sig2_f/sig2_s at
    tau,  Var(J) = (lam_s^2 sig2_f + lam_f^2 sig2_s) / (lam_f + lam_s)^2 —
    which correctly degenerates to 0 when either population's density
    vanishes at tau (validated against brute-force races over the regime
    grid in tests/test_sampling.py).

    u: per-lane uniforms [...]; nf/ns: int32 population sizes broadcastable
    to u; m: static draw count; s: strength in (0, 1).
    Returns int32 J in [max(0, m-ns), min(nf, m)]; when nf + ns < m
    (deliverable messages short of the quorum) returns nf (all favored).
    """
    nf_f = nf.astype(jnp.float32)
    ns_f = ns.astype(jnp.float32)
    m_f = jnp.asarray(m, jnp.float32)     # accepts a traced quorum too
    safe_nf = jnp.maximum(nf_f, 1e-6)
    safe_ns = jnp.maximum(ns_f, 1e-6)
    # threshold regimes (each guard also keeps the previous regime's tau)
    tau = m_f / safe_nf                                   # m <= nf*s
    tau2 = (m_f + ns_f * s) / jnp.maximum(nf_f + ns_f, 1e-6)
    tau = jnp.where(m_f > nf_f * s, tau2, tau)            # competition window
    tau3 = s + (m_f - nf_f) / safe_ns
    tau = jnp.where(tau2 > 1.0, tau3, tau)                # favored exhausted
    ff = jnp.clip(tau, 0.0, 1.0)
    fs = jnp.clip(tau - s, 0.0, 1.0)
    mu = nf_f * ff
    # delta-method variance of the favored count at the threshold (closed
    # upper interval ends: at a saturating tau the clip below keeps the
    # distribution one-sided, matching the true truncation)
    lam_f = nf_f * ((tau > 0.0) & (tau <= 1.0))
    lam_s = ns_f * ((tau > s) & (tau <= 1.0 + s))
    sig2_f = nf_f * ff * (1.0 - ff)
    sig2_s = ns_f * fs * (1.0 - fs)
    denom = jnp.maximum((lam_f + lam_s) ** 2, 1e-6)
    var = (lam_s ** 2 * sig2_f + lam_f ** 2 * sig2_s) / denom
    z = jax.scipy.special.ndtri(jnp.clip(u, 1e-7, 1 - 1e-7))
    draw = jnp.round(mu + z * jnp.sqrt(var))
    hi = jnp.minimum(nf_f, m_f)
    lo = jnp.minimum(jnp.maximum(0.0, m_f - ns_f), hi)
    return jnp.clip(draw, lo, hi).astype(jnp.int32)


def binomial_keep(u: jax.Array, n: jax.Array, keep: jax.Array) -> jax.Array:
    """Binomial(n, keep) via the clamped normal quantile — the
    message-omission thinning draw (SimConfig.drop_prob;
    tally.omission_thin_counts).

    u: uniforms [...]; n: counts broadcastable to u (int or integral
    f32); ``keep`` the survival probability, possibly TRACED (the
    DynParams drop_prob axis rides through 1 - p) — everything here is
    shape-generic elementwise VPU math, so one executable serves a whole
    drop-probability curve.  Exact at the endpoints by construction
    (keep -> 1 has zero variance and rounds to n); in between the normal
    approximation sits within O(1/sqrt(n)) of the true binomial — the
    same accuracy argument as the CF hypergeometric regime, with the
    dense per-edge mask (scheduler.omission_delivery_mask) as the exact
    oracle."""
    nf = jnp.maximum(n.astype(jnp.float32), 0.0)
    q = jnp.clip(jnp.asarray(keep, jnp.float32), 0.0, 1.0)
    mean = nf * q
    var = jnp.maximum(nf * q * (1.0 - q), 0.0)
    z = jax.scipy.special.ndtri(jnp.clip(u, 1e-7, 1 - 1e-7))
    draw = jnp.round(mean + z * jnp.sqrt(var))
    return jnp.clip(draw, 0.0, nf).astype(jnp.int32)


def binomial_half(u: jax.Array, n: jax.Array) -> jax.Array:
    """Binomial(n, 1/2) draws via the normal quantile, fully per-lane.

    u: uniforms [...]; n: int32 broadcastable to u's shape.  The p = 1/2
    binomial is symmetric (zero skewness), so the plain normal quantile is
    the correct second-order approximation — no Cornish-Fisher term needed
    (still ~4% relative error on the extreme counts at n ~ 2-10; use
    binomial_half_exact_shared when the parameter is lane-shared).  Used
    for the class split of delivered equivocator messages (each carries an
    independent fair bit per receiver).
    """
    nf = n.astype(jnp.float32)
    z = jax.scipy.special.ndtri(jnp.clip(u, 1e-7, 1 - 1e-7))
    draw = jnp.round(nf * 0.5 + z * jnp.sqrt(nf) * 0.5)
    return jnp.clip(draw, 0.0, nf).astype(jnp.int32)


def binomial_half_exact_shared(u: jax.Array, n: jax.Array,
                               n_max: int) -> jax.Array:
    """EXACT Binomial(n, 1/2) draws from a per-trial parameter shared by
    all lanes — the binomial analogue of hypergeom_exact_shared.

    u: float32 [T, N] per-lane uniforms; n: int32 [T] (n <= n_max, static).
    One [T, n_max+1] CDF table serves every lane of a trial; each lane
    binary-searches its own uniform.  Used by the 'all'-delivery
    equivocator split, whose count parameter is the trial-global live
    equivocator total (the normal approximation is visibly biased at
    small counts: Binomial(2, 1/2) is 1/4, 1/2, 1/4 but the rounded
    quantile gives ~0.24/0.52/0.24).
    """
    k = jnp.arange(n_max + 1, dtype=jnp.int32)
    nf = n[:, None]
    logpmf = _log_comb(jnp.broadcast_to(nf, (n.shape[0], n_max + 1)),
                       jnp.broadcast_to(k[None, :], (n.shape[0], n_max + 1)))
    logpmf = logpmf - nf.astype(jnp.float32) * jnp.log(2.0)
    logpmf = jnp.where(jnp.isfinite(logpmf), logpmf, -jnp.inf)
    mx = jnp.max(logpmf, axis=-1, keepdims=True)
    pmf = jnp.exp(logpmf - jnp.where(jnp.isfinite(mx), mx, 0.0))
    pmf = pmf / jnp.maximum(jnp.sum(pmf, axis=-1, keepdims=True), 1e-30)
    cdf = jnp.cumsum(pmf, axis=-1)
    idx = jax.vmap(lambda c, uu: jnp.searchsorted(c, uu))(cdf, u)
    return jnp.minimum(jnp.clip(idx, 0, n_max), n[:, None]).astype(jnp.int32)


def equivocate_hypergeom_counts(u_b: jax.Array, u0: jax.Array, u1: jax.Array,
                                u_s: jax.Array, honest_counts: jax.Array,
                                n_equiv: jax.Array, m: int) -> jax.Array:
    """Per-lane tallied counts when live equivocators hide among the senders.

    The tallied quorum is m draws without replacement from a mixed
    population: honest senders with fixed values (global histogram
    ``honest_counts`` int32 [T, 3]) plus ``n_equiv`` int32 [T] equivocators
    whose delivered value is an independent fair bit per (receiver, phase)
    edge.  Sampled in two stages, mirroring the law exactly:

      h_b ~ Hypergeom(total, n_equiv, m)        how many equivocators the
                                                lane's quorum happened to
                                                include (exact shared-CDF
                                                table when m is in the
                                                exact regime — parameters
                                                are trial-global)
      honest split of the remaining m - h_b     multivariate hypergeometric
                                                over honest_counts (same
                                                normal/CF machinery as
                                                multivariate_hypergeom_counts)
      b1 ~ Binomial(h_b, 1/2)                   fair-bit split of the
                                                delivered equivocator
                                                messages between 0 and 1

    u_b/u0/u1/u_s: independent float32 [T, N] per-lane uniforms.
    Returns int32 [T, N, 3] (clamped into the feasible region like the
    uniform-path sampler).  Statistically matched against the dense
    per-edge-bit path by tests/test_equivocate.py.
    """
    ms = static_m(m)               # None = traced quorum (CF regime only)
    c0 = honest_counts[:, 0]
    c1 = honest_counts[:, 1]
    total_h = honest_counts.sum(axis=-1)                    # [T]
    total = total_h + n_equiv
    if ms is not None and ms <= EXACT_TABLE_MAX:
        h_b = hypergeom_exact_shared(u_b, total, n_equiv, ms)
    else:
        h_b = hypergeom_normal_approx(
            u_b, jnp.broadcast_to(total[:, None], u_b.shape),
            jnp.broadcast_to(n_equiv[:, None], u_b.shape),
            jnp.full(u_b.shape, m, jnp.int32), skew_correct=True)
    rem = jnp.maximum(m - h_b, 0)                           # honest draws
    skew = ms is None or ms > EXACT_TABLE_MAX
    h0 = hypergeom_normal_approx(
        u0, jnp.broadcast_to(total_h[:, None], u0.shape),
        jnp.broadcast_to(c0[:, None], u0.shape), rem, skew_correct=skew)
    h1 = hypergeom_normal_approx(
        u1, jnp.maximum(total_h[:, None] - c0[:, None], 0), c1[:, None],
        jnp.maximum(rem - h0, 0), skew_correct=skew)
    hq = jnp.maximum(rem - h0 - h1, 0)
    b1 = binomial_half(u_s, h_b)
    return jnp.stack([h0 + (h_b - b1), h1 + b1, hq], axis=-1)


def multivariate_hypergeom_counts(u0: jax.Array, u1: jax.Array,
                                  class_counts: jax.Array, m: int) -> jax.Array:
    """Sample per-lane tallied class counts (h0, h1, hq) without replacement.

    u0, u1: float32 [T, N] independent uniforms per lane.
    class_counts: int32 [T, 3] global (c0, c1, cq) histogram of sent values.
    m: static quorum size (N - F).  Returns int32 [T, N, 3] with rows summing
    to m (clamped into the feasible region).
    """
    ms = static_m(m)               # None = traced quorum (CF regime only)
    c0 = class_counts[:, 0]
    c1 = class_counts[:, 1]
    total = class_counts.sum(axis=-1)                       # [T]
    if ms is not None and ms <= EXACT_TABLE_MAX:
        h0 = hypergeom_exact_shared(u0, total, c0, ms)      # [T, N] exact
    else:
        h0 = hypergeom_normal_approx(
            u0, jnp.broadcast_to(total[:, None], u0.shape),
            jnp.broadcast_to(c0[:, None], u0.shape),
            jnp.full(u0.shape, m, jnp.int32), skew_correct=True)
    rem_total = jnp.maximum(total[:, None] - c0[:, None], 0)
    rem_draw = jnp.maximum(m - h0, 0)
    h1 = hypergeom_normal_approx(u1, rem_total, c1[:, None], rem_draw,
                                 skew_correct=(ms is None
                                               or ms > EXACT_TABLE_MAX))
    hq = jnp.maximum(m - h0 - h1, 0)
    return jnp.stack([h0, h1, hq], axis=-1)
