"""Shard context: the framework's collective-communication abstraction (N7).

The reference's "communication backend" is localhost HTTP fan-out
(src/nodes/node.ts:72-80; SURVEY §5.8).  The TPU-native equivalent is data
movement XLA already performs: within a chip the tally is a reduction in HBM;
across chips it is a ``psum`` of per-shard class histograms over the ICI mesh
(and over DCN for a second trials axis at pod scale).

``ShardCtx`` names the mesh axes a kernel is running under (inside
``shard_map``) — or none (single device).  Every op in models/ and ops/ takes
a ctx and calls these methods instead of raw ``lax`` collectives, so the SAME
round kernel runs unmodified on one chip or a v4-pod mesh:

  * id offsets: RNG keys derive from *global* (trial, node) ids
    (ops/rng.py), so a shard folds in ``axis_index * local_size + arange``
    — never shard-local order.  This makes results bit-identical across
    mesh shapes (SURVEY §7 hard-part 5).
  * ``psum_nodes``: local class histogram -> global histogram (the vote
    tally that replaces the O(N^2) HTTP broadcast).
  * ``all_gather_nodes``: dense path needs every sender's value on every
    receiver shard — one tiled all-gather of an int8 [T, N_local] block.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import rng


@dataclasses.dataclass(frozen=True)
class ShardCtx:
    """Mesh-axis names for a kernel invocation; hashable (jit-static).

    ``None`` axis => that dimension is not sharded (no collective emitted).
    The default instance is the single-device context.
    """

    trial_axis: Optional[str] = None
    node_axis: Optional[str] = None

    # -- global id vectors (for RNG key derivation) -----------------------
    def trial_ids(self, t_local: int) -> jax.Array:
        """Global trial ids owned by this shard -> int32 [t_local]."""
        base = jnp.int32(0)
        if self.trial_axis is not None:
            base = lax.axis_index(self.trial_axis).astype(jnp.int32) * t_local
        return rng.ids(t_local, base)

    def node_ids(self, n_local: int) -> jax.Array:
        """Global node ids owned by this shard -> int32 [n_local]."""
        base = jnp.int32(0)
        if self.node_axis is not None:
            base = lax.axis_index(self.node_axis).astype(jnp.int32) * n_local
        return rng.ids(n_local, base)

    # -- collectives ------------------------------------------------------
    def psum_nodes(self, x: jax.Array) -> jax.Array:
        """Sum partial reductions over the node axis (ICI all-reduce)."""
        if self.node_axis is None:
            return x
        return lax.psum(x, self.node_axis)

    def all_gather_nodes(self, x: jax.Array, axis: int = -1) -> jax.Array:
        """Concatenate node-sharded blocks along ``axis`` on every shard."""
        if self.node_axis is None:
            return x
        if axis < 0:
            axis = x.ndim + axis
        return lax.all_gather(x, self.node_axis, axis=axis, tiled=True)

    def pmax_nodes(self, x: jax.Array) -> jax.Array:
        """Max of per-shard partial maxima over the node axis.

        The flight recorder's tally-margin column is a per-trial MAX over
        lanes (state.REC_MARGIN) — a sum would overflow int32 at
        N=1M x 1k-trial scale — so its node-axis combine is pmax, not
        psum."""
        if self.node_axis is None:
            return x
        return lax.pmax(x, self.node_axis)

    def psum_trials(self, x: jax.Array) -> jax.Array:
        """Sum partial reductions over the trial axis (DCN all-reduce).

        Pairs with psum_nodes for values that are already node-global —
        e.g. the packed loop's per-trial unsettled counts, whose scalar
        sum must be replicated across TRIAL shards for the while-loop
        predicate (summing over both axes again would double-count the
        node reduction)."""
        if self.trial_axis is None:
            return x
        return lax.psum(x, self.trial_axis)

    def psum_all(self, x: jax.Array) -> jax.Array:
        """Sum over every mesh axis (global scalar reductions)."""
        axes: Tuple[str, ...] = tuple(
            a for a in (self.trial_axis, self.node_axis) if a is not None)
        if not axes:
            return x
        return lax.psum(x, axes)


#: The single-device (no-mesh) context used by default everywhere.
SINGLE = ShardCtx()
