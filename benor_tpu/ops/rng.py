"""Deterministic, mesh-shape-independent randomness (SURVEY.md N6).

The reference's only randomness is a private per-node fair coin,
``Math.random() > 0.5`` at src/nodes/node.ts:111, drawn from the global
process RNG.  Here every random draw is derived counter-style from
``(seed, round, phase, trial, node[, peer])`` by *chained*
``jax.random.fold_in`` — never from an arithmetic product of indices — so:

  * results are bit-identical across mesh shapes (a shard folds in the
    *global* ids it owns, never shard-local indices),
  * no id ever overflows: each folded component stays < 2^31 even at
    10^6 nodes x 10^6 trials (a flat trial*N+node id would wrap int32),
  * per-(trial, node, round) streams are independent.

This is SURVEY §7 hard-part 5 ("sharded randomness") solved by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Phase tags folded into the round key so proposal-phase scheduling, vote-phase
# scheduling and the coin never share a stream.  Phase-2 sampling uses
# PHASE_* + 16 for a second independent uniform.
PHASE_PROPOSAL = 0
PHASE_VOTE = 1
PHASE_COIN = 2
PHASE_COIN_DEV = 3   # weak-common-coin per-lane deviation stream


def round_key(base_key: jax.Array, r: jax.Array, phase: int) -> jax.Array:
    """Key for (round, phase), shared across all lanes."""
    return jax.random.fold_in(jax.random.fold_in(base_key, r), phase)


def grid_keys(rp_key: jax.Array, trial_ids: jax.Array,
              node_ids: jax.Array) -> jax.Array:
    """Independent key per (trial, node) -> keys [T, N].

    trial_ids int32 [T], node_ids int32 [N] — *global* ids; shards pass the
    id ranges they own.
    """
    tkeys = jax.vmap(lambda t: jax.random.fold_in(rp_key, t))(trial_ids)
    return jax.vmap(lambda tk: jax.vmap(
        lambda n: jax.random.fold_in(tk, n))(node_ids))(tkeys)


def grid_uniforms(base_key: jax.Array, r: jax.Array, phase: int,
                  trial_ids: jax.Array, node_ids: jax.Array) -> jax.Array:
    """One float32 uniform in [0,1) per (trial, node) -> [T, N]."""
    keys = grid_keys(round_key(base_key, r, phase), trial_ids, node_ids)
    flat = keys.reshape(-1)
    u = jax.vmap(lambda k: jax.random.uniform(k))(flat)
    return u.reshape(trial_ids.shape[0], node_ids.shape[0])


def edge_uniforms(base_key: jax.Array, r: jax.Array, phase: int,
                  trial_ids: jax.Array, recv_ids: jax.Array,
                  send_ids: jax.Array) -> jax.Array:
    """One float32 uniform per (trial, receiver, sender) edge -> [T, R, S].

    Dense-path delay tensor; R * S stays <= ~10^8 by construction
    (dense_path_max_n), ids never combined arithmetically.
    """
    rk = round_key(base_key, r, phase)
    tkeys = jax.vmap(lambda t: jax.random.fold_in(rk, t))(trial_ids)

    def per_trial(tk):
        rkeys = jax.vmap(lambda i: jax.random.fold_in(tk, i))(recv_ids)

        def per_recv(rkey):
            return jax.vmap(
                lambda s: jax.random.uniform(jax.random.fold_in(rkey, s))
            )(send_ids)

        return jax.vmap(per_recv)(rkeys)

    return jax.vmap(per_trial)(tkeys)


def coin_flips(base_key: jax.Array, r: jax.Array, trial_ids: jax.Array,
               node_ids: jax.Array, common: bool) -> jax.Array:
    """Fair coin -> int8 in {0, 1}, shape [T, N].

    private: independent per (trial, node, round) — reference node.ts:111.
    common:  one shared coin per (trial, round); all nodes of a trial agree
             (the shared-common-coin variant, expected O(1) rounds).
    """
    kr = round_key(base_key, r, PHASE_COIN)
    if common:
        tkeys = jax.vmap(lambda t: jax.random.fold_in(kr, t))(trial_ids)
        bits = jax.vmap(lambda k: jax.random.bernoulli(k))(tkeys)
        return jnp.broadcast_to(
            bits[:, None], (trial_ids.shape[0], node_ids.shape[0])
        ).astype(jnp.int8)
    keys = grid_keys(kr, trial_ids, node_ids)
    flat = keys.reshape(-1)
    bits = jax.vmap(lambda k: jax.random.bernoulli(k))(flat)
    return bits.reshape(trial_ids.shape[0], node_ids.shape[0]).astype(jnp.int8)


def weak_common_coin_flips(base_key: jax.Array, r: jax.Array,
                           trial_ids: jax.Array, node_ids: jax.Array,
                           eps: float) -> jax.Array:
    """epsilon-weak common coin -> int8 {0, 1}, shape [T, N].

    Each lane sees the round's shared coin with probability 1 - eps and an
    independent private flip otherwise — the classical weak/common-coin
    abstraction (Rabin-style shared coins are eps = 0; Ben-Or's private
    coins are the eps = 1 limit).  Against the count-controlling adversary
    the deviating minority is what the adversary ties WITH, so termination
    has a sharp phase transition in eps (see results.weak_coin_study).

    Three independent streams: the shared bit (PHASE_COIN, per trial), the
    per-lane deviation mask (PHASE_COIN_DEV), and the per-lane private
    fallback (PHASE_COIN per (trial, node) — the same stream private mode
    uses).  All keyed on global ids: mesh-shape bit-identical.
    """
    # eps is trace-time static: the endpoints ARE the existing modes, so
    # short-circuit instead of generating two [T, N] streams only to mask
    # them out entirely (2 full grid-RNG passes per round at N=1M).
    if eps <= 0.0:
        return coin_flips(base_key, r, trial_ids, node_ids, common=True)
    if eps >= 1.0:
        return coin_flips(base_key, r, trial_ids, node_ids, common=False)
    shared = coin_flips(base_key, r, trial_ids, node_ids, common=True)
    private = coin_flips(base_key, r, trial_ids, node_ids, common=False)
    dev_u = grid_uniforms(base_key, r, PHASE_COIN_DEV, trial_ids, node_ids)
    return jnp.where(dev_u < eps, private, shared)


def ids(n: int, offset: int = 0) -> jax.Array:
    """Global id vector [n] starting at ``offset`` (shards pass their base)."""
    return jnp.arange(n, dtype=jnp.int32) + offset
