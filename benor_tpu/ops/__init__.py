"""Device-side operators: tallies, delivery scheduling, sampling, randomness."""

from . import rng, sampling, scheduler, tally

__all__ = ["rng", "sampling", "scheduler", "tally"]
