"""Pallas TPU kernel for the dense-path tally (the hot op at N <= ~10^4).

The XLA dense path (ops/tally.py:dense_counts) converts the bool delivery
mask and the int8 sent values to float32 one-hots in HBM before the einsum —
materializing a [T, N, N] f32 tensor (4x the bool mask's bytes) plus a
[T, N, 3] one-hot.  This kernel instead:

  * streams the bool mask into VMEM tile-by-tile and converts on-chip,
  * builds the [S, 128] one-hot (3 live columns, zero-padded to the 128-lane
    MXU width) in VMEM from the raw int8 ``sent`` / bool ``alive`` vectors,
  * issues one [TILE_R, S] x [S, 128] MXU matmul per (trial, receiver-tile)
    grid step.

HBM traffic per phase drops from ~5 bytes to ~1 byte per mask entry; the
matmul itself is identical MXU work.  Enabled with
``SimConfig(use_pallas=True)`` (TPU backend; tests exercise it in
interpreter mode on CPU).

Reference for semantics: the per-receiver tally of node.ts:52-69 / 88-98 —
counts[t, r, c] = #{s : mask[t, r, s] and alive[t, s] and sent[t, s] == c}.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import VAL0, VAL1, VALQ
from ..perfscope.instrument import instrumented_jit

#: Receiver-tile height; 128 matches the MXU systolic dimension.
TILE_R = 128
#: Lane width of the padded class axis (only the first 3 columns are live).
LANES = 128


def _tally_kernel(mask_ref, sent_ref, alive_ref, out_ref):
    """One (trial, receiver-tile) grid step.

    mask_ref:  bool [1, TILE_R, S]   this tile's delivery mask
    sent_ref:  int8 [T, S]           ALL trials' sender values (full-array
                                     block: a [1, S] block would violate the
                                     TPU (8, 128) block-divisibility rule on
                                     its second-to-last dim; [T, S] is only
                                     ~T*S bytes of VMEM and equal-to-array
                                     dims are always legal)
    alive_ref: bool [T, S]           sender liveness, same layout
    out_ref:   f32  [1, TILE_R, LANES]
    """
    t = pl.program_id(0)
    mask = mask_ref[0].astype(jnp.float32)                  # [TILE_R, S]
    # Select this trial's row WITHOUT a dynamic sublane index (Mosaic can't
    # prove alignment for sent_ref[t]): one-hot the trial axis and reduce.
    # Everything is widened to 32-bit immediately — Mosaic supports minor-dim
    # reshapes ([:, None]) only for 32-bit element types.
    n_trials = sent_ref.shape[0]
    sel = jax.lax.broadcasted_iota(jnp.int32, (n_trials, 1), 0) == t
    sent = jnp.sum(jnp.where(sel, sent_ref[...].astype(jnp.int32), 0),
                   axis=0)                                  # int32 [S]
    alive = jnp.sum(jnp.where(sel, alive_ref[...].astype(jnp.int32), 0),
                    axis=0)                                 # int32 0/1 [S]
    s = sent.shape[0]
    # one-hot [S, LANES]: column c in {0,1,2} is (sent == c) & alive
    class_ids = jax.lax.broadcasted_iota(jnp.int32, (s, LANES), 1)
    onehot = ((sent[:, None] == class_ids) & (class_ids < 3)
              ).astype(jnp.float32) * alive[:, None].astype(jnp.float32)
    out_ref[0] = jnp.dot(mask, onehot,
                         preferred_element_type=jnp.float32)


@instrumented_jit(static_argnames=("interpret",))
def dense_counts_pallas(mask: jax.Array, sent: jax.Array, alive: jax.Array,
                        interpret: bool = False) -> jax.Array:
    """Drop-in replacement for ops.tally.dense_counts.

    mask: bool [T, R, S]; sent: int8 [T, S]; alive: bool [T, S]
    -> int32 [T, R, 3].
    """
    T, R, S = mask.shape
    r_pad = (-R) % TILE_R
    if r_pad:
        mask = jnp.pad(mask, ((0, 0), (0, r_pad), (0, 0)))
    rp = R + r_pad

    grid = (T, rp // TILE_R)
    out = pl.pallas_call(
        _tally_kernel,
        out_shape=jax.ShapeDtypeStruct((T, rp, LANES), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, TILE_R, S), lambda t, i: (t, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((T, S), lambda t, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((T, S), lambda t, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, TILE_R, LANES), lambda t, i: (t, i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(mask, sent, alive)
    return out[:, :R, :3].astype(jnp.int32)
