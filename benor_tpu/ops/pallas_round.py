"""Fused round kernels over BIT-PLANE packed node state.

r3 VERDICT item 2 (the HBM roofline gap): on the flagship path each
phase's sampler kernel (ops/pallas_hist.py:cf_counts_pallas) writes int32
counts [T, N, 3] (12 B/lane) that a chain of XLA elementwise kernels then
re-reads — every intermediate materialized in HBM because XLA cannot fuse
INTO a pallas call.  PR 8 finishes the job the two-kernel packed pipeline
started: the hot per-lane state now travels as BIT-PLANES — a uint32
[T, planes, N/32] stack laid out by the declarative ``state.PACK_LAYOUT``
table (x 2 bits, decided / killed / coin-commit / faulty 1 bit each, the
round counter k in ``state.pack_k_bits(cfg)`` planes) at 32 nodes per
word — and the whole round runs as ONE pallas pass where the regime
allows it:

  fused_round_pallas    — proposal tallies + majority -> vote values ->
                          the vote-phase GLOBAL histogram + quorum gate
                          IN-REGISTER -> vote tallies + coin + decide/
                          adopt/commit -> the new plane stack, plus both
                          per-tile partial buffers.  No inter-kernel HBM
                          round-trip: per round the kernel moves
                          2 x (6 + k_bits)/8 bytes per node (2.5 B at the
                          bench geometry's max_rounds=12) where the old
                          two-kernel int32-word pipeline moved 12 — the
                          >= 4x traffic cut perfscope prices from the
                          layout tables (perfscope/roofline.py).
  proposal_hist_pallas  — the two-kernel fallback's proposal pass (the
  vote_commit_pallas      cross-shard vote histogram needs an ICI psum
                          between phases, which no single kernel can
                          perform), also serving the closed-form
                          count-controlling adversaries; both now read
                          and write the plane stack.

The single-pass kernel engages for counts_mode='sampled' (the CF-regime
flagship — the memory-bound path the relayout targets) on a single
device (ctx SINGLE) within the FUSED_ONE_PASS_* VMEM caps; everything
else — node-sharded meshes, the 'delivered'/'camps' adversaries, larger
tiles — takes the two-kernel plane path, with bit-identical results
(README "The fused fast path" documents the demotion policy).  Per-tile
partials are narrowed from int32 to int16/int8 where the N-F quorum
bound and the tile width provably fit (``partial_dtype``) and widened
back to int32 before any cross-tile or cross-shard reduction.

``run_packed`` (used by sim.run_consensus) carries the padded plane
stack through the entire while-loop: pack/unpack happen once per RUN.
``packed_round`` wraps one round for the per-round callers
(models/benor.py under the sharded runner, trajectory/slice paths).

Stream identity: the draws use the SAME key/counter schemes as
cf_counts_pallas / equiv_counts_pallas / coin_flips_pallas /
weak_coin_flips_pallas, so a ``use_pallas_round=True`` run is
BIT-IDENTICAL to the unfused ``use_pallas_hist=True`` path — pinned by
tests/test_pallas_round.py, which makes interpret-mode CPU testing exact
rather than statistical — and the one-pass and two-kernel plane paths
share every stream and every integer reduction, so regime dispatch can
never move a result bit (tests/test_packed_state.py).

Engages (ops/tally.py:pallas_round_active) on top of the pallas-hist
regime for every fault model (equivocate runs the mixed-population
sampler in-kernel over honest-only histograms + the run-constant
n_equiv), coin_mode private / common / weak_common with 0 < eps < 1.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_hist import (_COIN_SALT, _EQUIV_SALT_OFFSET, TILE_N,
                          _bits_to_uniform, _cf_draw, _lane_ids,
                          _ndtri_as241, _stream_scal, _threefry2x32)
from ..config import VAL0, VAL1, VALQ
from ..state import (NetState, PACK_COINED, PACK_DECIDED, PACK_DOWN,
                     PACK_FAULTY, PACK_K, PACK_KILLED, PACK_LAYOUT,
                     PACK_NODES_PER_WORD, PACK_STATIC_WIDTH, PACK_X,
                     pack_k_bits)
from ..perfscope.instrument import instrumented_jit

#: Physical width of all kernels' [tiles, T, PARTIAL_COLS] per-tile
#: reduction layout.  128 = one TPU lane register row; every out_spec and
#: partial constructor below must be sized with THIS NAME (the static
#: layout checker, analysis/rules_layout.py, flags bare literals) so the
#: declared layouts and the shipped buffer shapes cannot drift apart.
PARTIAL_COLS = 128

#: Single-pass fused-kernel caps: the one-pass kernel holds the whole
#: padded node axis of a trial block in VMEM (per-lane f32 temporaries
#: for both phases), so it engages only when the padded node count and
#: the total lane count fit; past either cap packed_round demotes to the
#: two-kernel plane path (bit-identical — shared streams and integer
#: reductions).  README "The fused fast path" carries the cost model.
FUSED_ONE_PASS_MAX_NODES = 8192
FUSED_ONE_PASS_MAX_LANES = 1 << 18

#: Per-tile partial-column layouts — name -> (base, width), pure literals
#: (the layout checker PARSES these tables out of this file and proves:
#: ranges disjoint, recorder block == state.REC_LAYOUT column-for-column,
#: witness fields == state.WIT_LAYOUT minus the host-set sentinel, and
#: base + per-node blocks for WITNESS_MAX_NODES watched nodes fit inside
#: PARTIAL_COLS).  PR 2/3 assigned these columns by hand — the exact
#: silent-corruption surface the checker now owns.
#:
#: Proposal kernel: vote-class histogram over honest live lanes + the
#: tile's alive count; witness blocks (2 cols per watched node) follow.
#: The single-pass fused kernel emits this SAME layout as its first
#: partial output, so the cross-regime assembly in packed_round is one
#: code path.
PROP_PARTIAL_LAYOUT = {
    "vote_hist": (0, 3),    # cols 0-2: sent-vote class histogram 0/1/"?"
    "alive": (3, 1),        # alive count (quorum gate / n_alive)
}

#: Vote kernel base partials: the NEXT round's proposal histogram + the
#: loop predicate's settled/unsettled counts.
VOTE_PARTIAL_LAYOUT = {
    "next_hist": (0, 3),    # cols 0-2: next round's proposal histogram
    "settled": (3, 1),
    "unsettled": (4, 1),    # the while-loop predicate
}

#: Flight-recorder partials appended by the vote kernel when record=True,
#: one column per state.REC_LAYOUT column IN REC COLUMN ORDER, based
#: directly after the base partials.  All per-tile SUMS except
#: tally_margin, a per-tile per-trial MAX (cross-tile combine = max).
#: ``killed`` includes this shard's pad lanes (they carry the killed
#: bit); packed_round subtracts the static pad count before the psum.
VOTE_RECORD_LAYOUT = {
    "decided": (5, 1),
    "killed": (6, 1),
    "undecided_0": (7, 1),
    "undecided_1": (8, 1),
    "undecided_q": (9, 1),
    "coin_flips": (10, 1),
    "tally_margin": (11, 1),
}

#: Witness-partial blocks (SimConfig.witness_trials / witness_nodes).
#: Each watched global node id owns one column per field — only the tile
#: holding the (real, non-pad) lane contributes, so the cross-tile/
#: cross-shard combine is a plain sum.  The proposal kernel emits
#: WITNESS_PROP_FIELDS per watched node starting at _WITA_BASE; the vote
#: kernel emits WITNESS_VOTE_FIELDS starting after its base + (when
#: record rides) flight-recorder columns (_witb_base).  The per-trial
#: values ride the partial layout's [T] axis; packed_round selects the
#: watched trials outside the kernel.  Field names are state.WIT_LAYOUT
#: column names: together with the host-set "written" sentinel the two
#: tuples must cover that table exactly (layout checker).
WITNESS_PROP_FIELDS = ("p0", "p1")
WITNESS_VOTE_FIELDS = ("x", "decided", "killed", "coined", "v0", "v1")

#: In-kernel stage-counter columns (SimConfig.kernel_telemetry;
#: benor_tpu/kernelscope) — name -> (base, width) OFFSETS within the
#: telemetry block each kernel appends after its base / recorder /
#: witness columns (absolute base: _telem_base).  Same pure-literal
#: discipline as every other layout table: the kernels derive their
#: emission order from it (``_telem_cols``), the host-side assembly
#: (kernelscope/report.py) labels columns from it, and the static
#: checker (analysis/rules_layout.py, rule ``telem-layout``) re-parses
#: it — overlap-free, dense, kernel emission keys exactly equal to the
#: table's, and the worst-case column budget (base + recorder + witness
#: blocks at WITNESS_MAX_NODES + this block) still inside PARTIAL_COLS.
#: Hand-numbered telemetry constants are a lint failure.
#:
#: Per tile, per trial, per round:
#:   active_lanes   real (non-pad) lanes this tile carries
#:   pad_lanes      padding-waste lanes (TILE - active; every one runs
#:                  the full vectorized stage for nothing)
#:   sampler_draws  lanes the stage's CF sampler touched (0 under the
#:                  closed-form 'delivered'/'camps' adversaries, which
#:                  run no sampler at all)
#:   hist_visits    histogram scatter visits — lanes contributing to
#:                  the stage's vote-class histogram (honest live
#:                  senders)
#:   quorum_passes  lanes that passed the quorum gate and ran the
#:                  decide/adopt/coin chain (vote stage; 0 in proposal)
#:   coin_draws     lanes that committed a coin flip (vote stage)
#:   plane_hops     plane-stack HBM round trips this stage performs —
#:                  the two-kernel pipeline's read / read+write vs the
#:                  single-pass kernel's read + write (2 vs 3 per round
#:                  summed over stages: the inter-kernel traffic the
#:                  fusion exists to remove, now measured per tile)
TELEM_COLS = {
    "active_lanes": (0, 1),
    "pad_lanes": (1, 1),
    "sampler_draws": (2, 1),
    "hist_visits": (3, 1),
    "quorum_passes": (4, 1),
    "coin_draws": (5, 1),
    "plane_hops": (6, 1),
}

#: Telemetry block width + column names in base order, derived from the
#: table (never hand-counted — the telem-layout rule enforces it).
TELEM_WIDTH = max(b + w for b, w in TELEM_COLS.values())
TELEM_COLUMNS = tuple(sorted(TELEM_COLS, key=lambda c: TELEM_COLS[c][0]))

#: Stage axis of the telemetry accumulator (kernelscope report rows).
TELEM_STAGES = ("proposal", "vote")


def _extent(*layouts) -> int:
    """One-past-the-last column of the union of layout tables."""
    return max(b + w for lay in layouts for b, w in lay.values())


_RP_DEC = VOTE_RECORD_LAYOUT["decided"][0]
_RP_KILL = VOTE_RECORD_LAYOUT["killed"][0]
_RP_U0 = VOTE_RECORD_LAYOUT["undecided_0"][0]
_RP_U1 = VOTE_RECORD_LAYOUT["undecided_1"][0]
_RP_UQ = VOTE_RECORD_LAYOUT["undecided_q"][0]
_RP_COIN = VOTE_RECORD_LAYOUT["coin_flips"][0]
_RP_MARGIN = VOTE_RECORD_LAYOUT["tally_margin"][0]

_WITA_BASE = _extent(PROP_PARTIAL_LAYOUT)
_WITA_PER_NODE = len(WITNESS_PROP_FIELDS)
_WITB_PER_NODE = len(WITNESS_VOTE_FIELDS)

#: Plane-words per lane tile: each grid step covers TILE_N nodes =
#: _TILE_W uint32 words per plane.
_TILE_W = TILE_N // PACK_NODES_PER_WORD
_X_BITS = PACK_LAYOUT["x"][1]


def _witb_base(record: bool) -> int:
    """First vote-kernel witness column: after the base partials and,
    when the flight recorder rides too, its telemetry columns."""
    if record:
        return _extent(VOTE_PARTIAL_LAYOUT, VOTE_RECORD_LAYOUT)
    return _extent(VOTE_PARTIAL_LAYOUT)


def _telem_base(stage: str, record: bool, n_witness: int) -> int:
    """First TELEM_COLS column for one kernel stage: after everything
    else that stage emits — so unarmed executables keep their historical
    layout bit-for-bit.  Derived from the tables, never hand-numbered."""
    if stage == "proposal":
        return _WITA_BASE + _WITA_PER_NODE * n_witness
    return _witb_base(record) + _WITB_PER_NODE * n_witness


def fused_one_pass_eligible(cfg, trials: int, n_nodes: int) -> bool:
    """True iff packed_round would take the SINGLE-PASS kernel for this
    (config, shape) on a single device: sampled counts (the closed-form
    adversaries run no sampler — nothing to fuse) and the padded node
    axis within the VMEM caps.  The one condition packed_round's
    dispatch and perfscope's fused_vs_xla labeling
    (regimes.capture_fused_vs_xla) both consume — so the measurement can
    never claim a kernel the dispatch would not run."""
    from . import tally

    if tally.pallas_round_counts_mode(cfg) != "sampled":
        return False
    np_total = n_nodes + (-n_nodes) % TILE_N
    return (np_total <= FUSED_ONE_PASS_MAX_NODES
            and trials * np_total <= FUSED_ONE_PASS_MAX_LANES)


def telemetry_tiles(cfg, trials: int, n_nodes: int) -> int:
    """Tile count of the telemetry accumulator's middle axis for this
    (config, shape) on a single device — 1 when the single-pass kernel
    engages (its grid sees the whole padded node axis in one step),
    np_total / TILE_N on the two-kernel plane pipeline.  Kept next to
    fused_one_pass_eligible so the accumulator shape can never drift
    from the dispatch that fills it."""
    if fused_one_pass_eligible(cfg, trials, n_nodes):
        return 1
    return (n_nodes + (-n_nodes) % TILE_N) // TILE_N


def partial_dtype(m: int, tile_nodes: int):
    """Narrowest dtype every per-tile partial column provably fits.

    The quorum bound is the whole trick: per-tile counts (histograms,
    settled/unsettled, the recorder classes) never exceed the tile's
    lane count (pads included), and per-lane tallies / margins never
    exceed the quorum m = N - F — so the bound is max(tile, m) and the
    kernels can emit int16 partials instead of int32, halving the
    partial-buffer HBM traffic.  (The int8 rung needs a sub-128-lane
    tile; with node padding to TILE_N = 512 it is unreachable from the
    shipped kernels and exists for smaller future tilings.)  Widened
    back to int32 by packed_round BEFORE any cross-tile or cross-shard
    sum, so the reductions can never wrap.
    """
    bound = max(m, tile_nodes)          # both static python ints
    if bound < (1 << 7):
        return jnp.int8
    if bound < (1 << 15):
        return jnp.int16
    return jnp.int32


def _witness_cols(scal_ref, shape, witness_ids, n_local, fields):
    """Per-tile witness partial columns: for each watched GLOBAL node id,
    one column per field carrying that lane's value (all other tiles
    contribute 0, so the combine is a sum).  Pad lanes are masked by
    LOCAL index: on a node-sharded mesh a non-final shard's pad ids alias
    the NEXT shard's real id range (same caveat _camp_select documents)
    and their in-kernel draws are keyed on those aliased global ids — an
    unmasked pad lane would exactly double the real lane's contribution
    after the node-axis psum.  The bit-plane relayout does not move this
    boundary: pads live inside the last plane words, but their local
    lane index (word * 32 + bit) is >= n_local exactly as before."""
    node, _ = _lane_ids(scal_ref, shape)
    tile = shape[1]
    lidx = (jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
            + jnp.uint32(pl.program_id(0) * tile))
    real = lidx < jnp.uint32(n_local)
    cols = []
    for wid in witness_ids:
        sel = (node == jnp.uint32(wid)) & real
        for f in fields:
            if f.dtype == jnp.float32:
                v = jnp.sum(jnp.where(sel, f, 0.0), axis=1)
            else:
                v = jnp.sum(jnp.where(sel, f, 0), axis=1)
            cols.append(v.astype(jnp.int32))
    return cols


def _telem_cols(shape, n_local, sampled, hops, hon=None, quorum=None,
                coined=None):
    """The TELEM_COLS block for one kernel stage -> [T] int32 columns in
    table order (SimConfig.kernel_telemetry).

    ``shape`` is the stage's per-lane block (T, tile); pad lanes are
    classified by LOCAL lane index against ``n_local`` exactly as
    ``_witness_cols`` masks them, so the active/pad split is the real
    padding waste of this tile, per trial, per round.  ``sampled`` is
    static (the closed-form adversaries run no sampler — their
    sampler_draws column is honestly zero); ``hops`` is the static
    plane-stack round-trip count of this stage.  ``hon``/``quorum``/
    ``coined`` are the stage's own masks (None emits 0 — e.g. the
    proposal stage never reaches the quorum gate or the coin)."""
    t, tile = shape
    lidx = (jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
            + jnp.uint32(pl.program_id(0) * tile))
    real = lidx < jnp.uint32(n_local)
    active = jnp.sum(real, axis=1, dtype=jnp.int32)
    zeros = jnp.zeros((t,), jnp.int32)

    def count(mask):
        if mask is None:
            return zeros
        return jnp.sum(mask, axis=1, dtype=jnp.int32)

    vals = {
        "active_lanes": active,
        "pad_lanes": jnp.int32(tile) - active,
        "sampler_draws": (jnp.full((t,), tile, jnp.int32) if sampled
                          else zeros),
        "hist_visits": count(hon),
        "quorum_passes": count(quorum),
        "coin_draws": count(coined),
        "plane_hops": jnp.full((t,), hops, jnp.int32),
    }
    return [vals[name] for name in TELEM_COLUMNS]


def _telem_slice(parts, base):
    """Per-tile telemetry block from a kernel's RAW [tiles, T,
    PARTIAL_COLS] partial buffer -> int32 [tiles, TELEM_WIDTH], summed
    over the trial axis (the accumulator aggregates trials and rounds;
    per-tile, per-stage resolution is what the attribution needs)."""
    block = parts.astype(jnp.int32)[:, :, base:base + TELEM_WIDTH]
    return jnp.sum(block, axis=1)


# --------------------------------------------------------------------------
# Bit-plane pack / unpack — XLA side.
#
# state.PACK_LAYOUT is the single source of truth for plane indices; the
# helpers here (and the in-kernel loads/stores below) derive everything
# from the PACK_* constants state.py exports from it.
# --------------------------------------------------------------------------


def pack_state(cfg, state: NetState, faulty: jax.Array) -> jax.Array:
    """NetState leaves + faulty mask -> padded plane stack uint32
    [T, state.pack_width(cfg), Np/32].

    Pad lanes carry the killed bit and x = "?" (inert everywhere:
    excluded from histograms and alive counts, never active, counted as
    settled); every other pad plane is 0.  The coin-commit plane starts
    0 (no round has run).
    """
    kb = pack_k_bits(cfg)
    n = state.x.shape[-1]
    n_pad = (-n) % TILE_N

    def lanes(a, pad_const):
        a = a.astype(jnp.uint32)
        if n_pad:
            a = jnp.pad(a, ((0, 0), (0, n_pad)),
                        constant_values=jnp.uint32(pad_const))
        return a.reshape(a.shape[0], -1, PACK_NODES_PER_WORD)

    x = lanes(state.x, VALQ)
    dec = lanes(state.decided, 0)
    kil = lanes(state.killed, 1)
    fau = lanes(faulty, 0)
    k = lanes(state.k, 0)
    planes = [None] * (PACK_STATIC_WIDTH + kb)
    for b in range(_X_BITS):
        planes[PACK_X + b] = (x >> b) & 1
    planes[PACK_DECIDED] = dec
    planes[PACK_KILLED] = kil
    planes[PACK_COINED] = jnp.zeros_like(dec)
    planes[PACK_FAULTY] = fau
    # forensic down-interval bit (crash_recover): no round has run, so
    # the stored plane starts clear — the kernels re-derive liveness
    # from the (crash_round, recover_round) bounds, never from this bit
    planes[PACK_DOWN] = jnp.zeros_like(dec)
    for b in range(kb):
        planes[PACK_K + b] = (k >> b) & 1
    j = jnp.arange(PACK_NODES_PER_WORD, dtype=jnp.uint32)
    words = [jnp.sum(p << j, axis=-1, dtype=jnp.uint32) for p in planes]
    return jnp.stack(words, axis=1)


def plane_field(pack: jax.Array, base: int, width: int) -> jax.Array:
    """One PACK_LAYOUT field of a plane stack -> int32 [T, Np] per-lane
    values (XLA side; the in-kernel twin is _kfield)."""
    T, _, n_w = pack.shape
    j = jnp.arange(PACK_NODES_PER_WORD, dtype=jnp.uint32)
    val = jnp.zeros((T, n_w, PACK_NODES_PER_WORD), jnp.uint32)
    for b in range(width):
        val = val | (((pack[:, base + b, :, None] >> j) & 1)
                     << jnp.uint32(b))
    return val.reshape(T, n_w * PACK_NODES_PER_WORD).astype(jnp.int32)


def unpack_state(pack: jax.Array, n_nodes: int) -> NetState:
    """Plane stack -> NetState (pad lanes dropped).  The k width is
    whatever the stack carries (pack.shape[1] - PACK_STATIC_WIDTH), so
    unpack needs no config."""
    kb = pack.shape[1] - PACK_STATIC_WIDTH
    x = plane_field(pack, PACK_X, _X_BITS)[:, :n_nodes]
    dec = plane_field(pack, PACK_DECIDED, 1)[:, :n_nodes]
    kil = plane_field(pack, PACK_KILLED, 1)[:, :n_nodes]
    k = plane_field(pack, PACK_K, kb)[:, :n_nodes]
    return NetState(x=x.astype(jnp.int8), decided=dec.astype(bool),
                    k=k, killed=kil.astype(bool))


# --------------------------------------------------------------------------
# Bit-plane loads / stores — kernel side.
# --------------------------------------------------------------------------


def _kfield(w, base, width):
    """One field of a loaded plane block uint32 [T, P, TW] -> per-lane
    int32 [T, TW * 32] (node order: word-major, bit = in-word lane)."""
    t, _, tw = w.shape
    j = jax.lax.broadcasted_iota(jnp.uint32, (t, tw, PACK_NODES_PER_WORD),
                                 2)
    val = jnp.zeros((t, tw, PACK_NODES_PER_WORD), jnp.uint32)
    for b in range(width):
        val = val | (((w[:, base + b, :][..., None] >> j) & 1)
                     << jnp.uint32(b))
    return val.reshape(t, tw * PACK_NODES_PER_WORD).astype(jnp.int32)


def _load_fields(p, kbits, rr, cr, rcv, fault_model, freeze,
                 rejoin="durable"):
    """Loaded plane block + the crash-at-round / crash-recover update,
    in-kernel.

    Returns (x, decided, killed_now, faulty, k, alive, frozen, down) —
    all per-lane int32 [T, TILE] except the bool masks (the same
    contract the old int32-word ``_fields`` had, so the phase logic is
    unchanged).  ``killed_now`` is the STORED killed bit (latched
    permanent deaths only); under 'crash_recover' the per-round
    down-interval membership is the separate ``down`` mask, re-derived
    from the (cr, rcv) round bounds — never read back from the stack —
    so sliced/resumed runs are bit-identical to one-shot runs.  With
    ``rejoin='amnesia'`` an UNDECIDED lane at its first round back
    (rr == rcv) restarts x from "?" before any phase logic (decisions
    are durable: irrevocability holds across recovery)."""
    x = _kfield(p, PACK_X, _X_BITS)
    decided = _kfield(p, PACK_DECIDED, 1)
    killed = _kfield(p, PACK_KILLED, 1)
    faulty = _kfield(p, PACK_FAULTY, 1)
    k = _kfield(p, PACK_K, kbits)
    down = None
    if fault_model == "crash_at_round":
        crashing = (faulty == 1) & (cr > 0) & (rr >= cr)
        killed = jnp.where(crashing, 1, killed)
    elif fault_model == "crash_recover":
        started = (faulty == 1) & (cr > 0) & (rr >= cr)
        killed = jnp.where(started & (rcv <= 0), 1, killed)
        down = started & (rcv > 0) & (rr < rcv)
        if rejoin == "amnesia":
            # cr > 0: no crash, nothing to forget (mirrors the XLA path)
            rj = (faulty == 1) & (cr > 0) & (rcv > 0) & (rr == rcv) & \
                (decided == 0)
            x = jnp.where(rj, VALQ, x)
    alive = killed == 0
    if down is not None:
        alive = alive & ~down
    frozen = (decided == 1) if freeze else jnp.zeros_like(alive)
    return x, decided, killed, faulty, k, alive, frozen, down


def _store_planes(np_ref, kbits, new_x, new_dec, killed, faulty, new_k,
                  coined, down=None):
    """Commit the per-lane fields back to the plane layout -> the new
    uint32 [T, P, TW] block.  Pad lanes arrive with the killed bit and
    inert values, so the stored words keep the pad invariants.  ``down``
    (crash_recover) stores this round's down-interval membership for
    forensic unpacking — the protocol re-derives liveness from the
    round bounds, so the plane is write-only evidence (None clears it,
    like the coined plane before any round)."""
    t, tile = new_x.shape
    tw = tile // PACK_NODES_PER_WORD
    jj = jax.lax.broadcasted_iota(jnp.uint32, (t, tw, PACK_NODES_PER_WORD),
                                  2)

    def fold(v, b):
        lanes = v.astype(jnp.uint32).reshape(t, tw, PACK_NODES_PER_WORD)
        return jnp.sum(((lanes >> jnp.uint32(b)) & 1) << jj, axis=-1,
                       dtype=jnp.uint32)

    planes = [None] * (PACK_STATIC_WIDTH + kbits)
    for b in range(_X_BITS):
        planes[PACK_X + b] = fold(new_x, b)
    planes[PACK_DECIDED] = fold(new_dec, 0)
    planes[PACK_KILLED] = fold(killed, 0)
    planes[PACK_COINED] = fold(coined, 0)
    planes[PACK_FAULTY] = fold(faulty, 0)
    planes[PACK_DOWN] = (fold(down, 0) if down is not None
                         else jnp.zeros_like(planes[PACK_KILLED]))
    for b in range(kbits):
        planes[PACK_K + b] = fold(new_k, b)
    np_ref[...] = jnp.stack(planes, axis=1)


def _flip(v):
    """Byzantine bit-flip on packed x values: 0 <-> 1, "?" unchanged."""
    return jnp.where(v == VAL0, VAL1, jnp.where(v == VAL1, VAL0, v))


def _sent(fault_model, vote, faulty):
    if fault_model == "byzantine":
        return jnp.where(faulty == 1, _flip(vote), vote)
    return vote


def _honest(fault_model, alive, faulty):
    """Histogram population: under 'equivocate' the faulty bit marks live
    equivocators, whose broadcast slot is ignored (their per-edge values
    are drawn receiver-side) — every other fault model tallies all live
    senders (byzantine lanes count, with flipped values)."""
    if fault_model == "equivocate":
        return alive & (faulty == 0)
    return alive


def _mixed_draws(m, scal_ref, scal2_ref, c0, c1, cq, ne, shape):
    """The equivocate-regime mixed-population sampler, fused.

    Verbatim mirror of pallas_hist._equiv_kernel (draw ORDER included, so
    the fused round stays bit-identical to the unfused
    equiv_counts_pallas path): h_b delivered equivocators ~ CF
    hypergeometric from the phase+64 block's word 0, honest (c0, c1, cq)
    split of the remainder from the phase block's two words, fair
    Binomial(h_b, 1/2) class split from the phase+64 block's word 1.
    Returns the per-lane TOTAL (p0, p1) tallies, f32.
    """
    node, trial = _lane_ids(scal_ref, shape)
    b0, b1 = _threefry2x32(scal_ref[0], scal_ref[1], node, trial)
    b2, b3 = _threefry2x32(scal2_ref[0], scal2_ref[1], node, trial)
    u0 = _bits_to_uniform(b0)
    u1 = _bits_to_uniform(b1)
    u_b = _bits_to_uniform(b2)
    u_s = _bits_to_uniform(b3)
    total_h = c0 + c1 + cq
    total = total_h + ne
    mf = jnp.float32(m)
    h_b = _cf_draw(u_b, total, ne, mf)
    rem = jnp.maximum(mf - h_b, 0.0)
    h0 = _cf_draw(u0, total_h, c0, rem)
    h1 = _cf_draw(u1, jnp.maximum(total_h - c0, 0.0), c1,
                  jnp.maximum(rem - h0, 0.0))
    z = _ndtri_as241(u_s)
    bs = jnp.clip(jnp.round(h_b * 0.5 + z * jnp.sqrt(h_b) * 0.5), 0.0, h_b)
    return h0 + (h_b - bs), h1 + bs


def _cf_pair_draws(m, scal_ref, c0, c1, cq, shape):
    """The uniform CF-regime per-lane tally pair — verbatim from
    pallas_hist._cf_kernel (one threefry block per lane yields both
    uniforms), shared by the two-kernel and single-pass paths so their
    streams cannot drift."""
    node, trial = _lane_ids(scal_ref, shape)
    b0, b1 = _threefry2x32(scal_ref[0], scal_ref[1], node, trial)
    u0 = _bits_to_uniform(b0)
    u1 = _bits_to_uniform(b1)
    total = c0 + c1 + cq
    mf = jnp.float32(m)
    p0 = _cf_draw(u0, total, c0, mf)
    p1 = _cf_draw(u1, jnp.maximum(total - c0, 0.0), c1,
                  jnp.maximum(mf - p0, 0.0))
    return p0, p1


def _partial_cols(t, cols, dtype=jnp.int32):
    """[T]-vectors -> the [1, T, PARTIAL_COLS] partial layout
    (col i = cols[i]); built in int32 and narrowed once at the end
    (every value is bounded by ``partial_dtype``'s argument bound)."""
    col = jax.lax.broadcasted_iota(jnp.int32, (1, t, PARTIAL_COLS), 2)
    out = jnp.zeros((1, t, PARTIAL_COLS), jnp.int32)
    for i, v in enumerate(cols):
        out = out + (col == i) * v[None, :, None]
    return out.astype(dtype)


def _camp_select(scal_ref, shape, camp_b0, camp_b1, vecs):
    """counts_mode='camps': pick each lane's camp triple by GLOBAL lane id
    (targeted adversary camp layout — value camps at the top of the id
    range, tally.targeted_counts).  ``vecs`` = six [T, 1] refs, the
    (h0, h1) pair per camp in (0-camp, 1-camp, "?"-camp) order.  Pad
    lanes may select ANY camp — on a node-sharded mesh a non-final
    shard's pad ids overlap the next shard's real range, so no camp
    assignment can be promised for them; the invariant that matters is
    the killed-bit exclusion: pad lanes carry the killed PLANE bit, so
    neither their commit nor the histogram partials ever see them,
    whichever camp triple they happened to read."""
    c0h0, c0h1, c1h0, c1h1, qh0, qh1 = [v[...] for v in vecs]
    node, _ = _lane_ids(scal_ref, shape)
    in1 = node >= jnp.uint32(camp_b1)
    in0 = (node >= jnp.uint32(camp_b0)) & ~in1
    p0 = jnp.where(in1, c1h0, jnp.where(in0, c0h0, qh0))
    p1 = jnp.where(in1, c1h1, jnp.where(in0, c0h1, qh1))
    return p0, p1


def _decide_commit(n_faulty, rule, coin_mode, eps, shape, coin_scal,
                   shared, qok, rk, x, decided, killed, k, alive, frozen,
                   v0, v1):
    """The coin + decide / adopt / commit chain (node.ts:99-112), shared
    by the two-kernel vote pass and the single-pass fused kernel so the
    two dispatch targets are bit-aligned by construction.

    ``shared``/``qok`` are [T, 1] int32 (per-trial shared coin bit /
    quorum gate); returns (new_x, new_dec, new_k, coined) per-lane
    int32/bool tensors.  The coin stream is verbatim _coin_kernel /
    _weak_coin_kernel (word 0 private bit, word 1 deviation uniform)."""
    node, trial = _lane_ids(coin_scal, shape)
    pbits, dbits = _threefry2x32(coin_scal[0], coin_scal[1], node, trial)
    private = (pbits & jnp.uint32(1)).astype(jnp.int32)
    if coin_mode == "private":
        coin = private
    elif coin_mode == "common":
        coin = jnp.broadcast_to(shared, private.shape)
    else:  # weak_common, 0 < eps < 1
        dev = _bits_to_uniform(dbits) < jnp.float32(eps)
        coin = jnp.where(dev, private, shared)

    ff = jnp.float32(n_faulty)
    decide0 = v0 > ff
    decide1 = v1 > ff
    no_adopt = None
    if rule == "reference":                              # quirk 9
        any_votes = (v0 + v1) > 0.0
        adopt0 = any_votes & (v0 > v1)
        adopt1 = any_votes & (v0 < v1)
        no_adopt = ~adopt0 & ~adopt1
        x2 = jnp.where(decide0, VAL0,
             jnp.where(decide1, VAL1,
             jnp.where(adopt0, VAL0,
             jnp.where(adopt1, VAL1, coin))))
    else:                                                # textbook
        x2 = jnp.where(decide0, VAL0,
             jnp.where(decide1, VAL1, coin))

    active = alive & (qok != 0) & ~frozen
    newly = active & (decide0 | decide1)
    new_x = jnp.where(active, x2, x)
    new_dec = jnp.where(newly, 1, decided)
    new_k = jnp.where(active, rk, k)
    # coin-commit mask, same branch structure as the XLA path in
    # models/benor.py (the coined PLANE + recorder/witness partials)
    coined = active & ~decide0 & ~decide1
    if no_adopt is not None:
        coined = coined & no_adopt
    return new_x, new_dec, new_k, coined, active


def _vote_partial_cols(fault_model, record, witness_ids, n_local,
                       vote_scal, shape, new_x, new_dec, killed, faulty,
                       alive, active, coined, v0, v1,
                       telemetry=False, telem_sampled=True, telem_hops=2):
    """The vote pass's per-tile partial columns (VOTE_PARTIAL_LAYOUT +
    optional VOTE_RECORD_LAYOUT + witness blocks + optional TELEM_COLS
    stage counters) — shared by the two-kernel and single-pass paths."""
    sent_next = _sent(fault_model, new_x, faulty)
    settled = (new_dec == 1) | (killed == 1)
    hon = _honest(fault_model, alive, faulty)
    cols = [
        jnp.sum((sent_next == v) & hon, axis=1, dtype=jnp.int32)
        for v in (VAL0, VAL1, VALQ)
    ] + [jnp.sum(settled, axis=1, dtype=jnp.int32),
         jnp.sum(~settled, axis=1, dtype=jnp.int32)]
    if record:
        # flight-recorder partials (_RP_* layout, same masks as the XLA
        # path in models/benor.py — so the delivered/camps regimes, where
        # both paths share every bit, record identical rows)
        undec = (new_dec == 0) & (killed == 0)
        margin = jnp.where(active, jnp.abs(v0 - v1), 0.0)
        cols = cols + [
            jnp.sum(new_dec == 1, axis=1, dtype=jnp.int32),
            jnp.sum(killed == 1, axis=1, dtype=jnp.int32),
            jnp.sum(undec & (new_x == VAL0), axis=1, dtype=jnp.int32),
            jnp.sum(undec & (new_x == VAL1), axis=1, dtype=jnp.int32),
            jnp.sum(undec & (new_x == VALQ), axis=1, dtype=jnp.int32),
            jnp.sum(coined, axis=1, dtype=jnp.int32),
            jnp.max(margin, axis=1).astype(jnp.int32),
        ]
    if witness_ids:
        cols = cols + _witness_cols(
            vote_scal, shape, witness_ids, n_local,
            [new_x, new_dec, killed, coined.astype(jnp.int32), v0, v1])
    if telemetry:
        cols = cols + _telem_cols(shape, n_local, telem_sampled,
                                  telem_hops, hon=hon, quorum=active,
                                  coined=coined)
    return cols


def _prop_hist_kernel(m, fault_model, freeze, has_cr, counts_mode,
                      camp_b0, camp_b1, witness_ids, n_local, kbits,
                      telemetry, rejoin, *refs):
    """One lane-tile of the two-kernel path's PROPOSAL phase.

    Per-lane tallies -> phase-1 majority/tie (node.ts:63-69) -> each
    lane's (byzantine-flipped) vote value -> per-tile partials: cols 0-2
    vote-class histogram over HONEST live lanes, col 3 the tile's alive
    count (feeding n_alive / the quorum gate — equivocators count as live
    senders).  Tallies by counts_mode: 'sampled' draws them in-kernel
    from the global histogram (CF sampler; mixed-population under
    'equivocate'); 'delivered' broadcasts the adversary's per-trial
    closed-form counts; 'camps' selects the targeted adversary's per-camp
    triple by global lane id — the latter two run no sampler at all.

    ``witness_ids`` (static tuple of global node ids; the witness
    recorder, SimConfig.witness_trials) appends 2 columns per watched
    node — its per-lane (p0, p1) proposal tallies, pad lanes masked by
    ``n_local`` — at _WITA_BASE.  witness off (the empty tuple) emits
    exactly the historical four columns, so unwitnessed executables stay
    bit-identical.
    """
    has_eq = fault_model == "equivocate" and counts_mode == "sampled"
    refs = list(refs)
    scal_ref = refs.pop(0)
    scal2_ref = refs.pop(0) if has_eq else None
    rr_ref = refs.pop(0)
    n_cvec = {"sampled": 3, "delivered": 2, "camps": 6}[counts_mode]
    cvecs = refs[:n_cvec]
    refs = refs[n_cvec:]
    ne_ref = refs.pop(0) if has_eq else None
    p_ref = refs.pop(0)
    cr = refs.pop(0)[...] if has_cr else None
    rcv = refs.pop(0)[...] if fault_model == "crash_recover" else None
    (out_ref,) = refs
    p = p_ref[...]
    x, decided, killed, faulty, k, alive, frozen, down = _load_fields(
        p, kbits, rr_ref[0], cr, rcv, fault_model, freeze, rejoin)
    shape = x.shape

    if counts_mode == "delivered":
        p0, p1 = cvecs[0][...], cvecs[1][...]
    elif counts_mode == "camps":
        p0, p1 = _camp_select(scal_ref, shape, camp_b0, camp_b1, cvecs)
    elif has_eq:
        c0, c1, cq = (v[...] for v in cvecs)
        p0, p1 = _mixed_draws(m, scal_ref, scal2_ref, c0, c1, cq,
                              ne_ref[...], shape)
    else:
        c0, c1, cq = (v[...] for v in cvecs)
        p0, p1 = _cf_pair_draws(m, scal_ref, c0, c1, cq, shape)
    x1 = jnp.where(p0 > p1, VAL0, jnp.where(p1 > p0, VAL1, VALQ))

    vote = _sent(fault_model, jnp.where(frozen, x, x1), faulty)
    hon = _honest(fault_model, alive, faulty)
    t = shape[0]
    cols = [
        jnp.sum((vote == v) & hon, axis=1, dtype=jnp.int32)
        for v in (VAL0, VAL1, VALQ)
    ] + [jnp.sum(alive, axis=1, dtype=jnp.int32)]
    if witness_ids:
        cols += _witness_cols(scal_ref, shape, witness_ids, n_local,
                              [p0, p1])
    if telemetry:
        # proposal stage: one plane-stack read, no quorum gate, no coin
        cols += _telem_cols(shape, n_local, counts_mode == "sampled", 1,
                            hon=hon)
    out_ref[...] = _partial_cols(t, cols, out_ref.dtype)


def _vote_commit_kernel(m, n_faulty, rule, coin_mode, eps, freeze,
                        fault_model, has_cr, counts_mode, camp_b0,
                        camp_b1, record, witness_ids, n_local, kbits,
                        telemetry, rejoin, *refs):
    """One lane-tile of the two-kernel path's VOTE phase + commit.

    Per-lane vote tallies (by counts_mode, as in _prop_hist_kernel) ->
    decide/adopt/coin (node.ts:99-112) -> the new plane-stack block, plus
    per-tile partials: cols 0-2 the NEXT round's proposal histogram (of
    the new sent values over HONEST live lanes; exact for static-killed
    fault models — the crash_at_round caller recomputes it in XLA
    instead), col 3 settled count, col 4 unsettled count (the loop
    predicate).

    ``record`` (static; the flight recorder, SimConfig.record) adds the
    telemetry partials in cols 5-11 (_RP_* layout): decided / killed
    (pads included — the wrapper's caller subtracts the static pad count)
    / live-undecided 0-1-"?" histogram / coin-flip count, all per-tile
    sums, plus col 11 the per-trial MAX |v0 - v1| vote margin over active
    lanes (combined across tiles with max, not sum — see
    vote_commit_pallas).  record=False emits exactly the historical five
    columns, so unrecorded executables stay bit-identical.

    ``witness_ids`` (static; the witness recorder) appends 6 columns per
    watched global node id — the lane's committed x / decided / killed /
    coin-commit bit and its (v0, v1) vote tallies, pad lanes masked by
    ``n_local`` — after the base (and, when record, telemetry) columns;
    see _witb_base.  The empty tuple leaves the layout untouched.
    """
    has_eq = fault_model == "equivocate" and counts_mode == "sampled"
    refs = list(refs)
    vote_scal_ref = refs.pop(0)
    vote_scal2_ref = refs.pop(0) if has_eq else None
    coin_scal_ref, rk_ref = refs[:2]
    refs = refs[2:]
    n_cvec = {"sampled": 3, "delivered": 2, "camps": 6}[counts_mode]
    cvecs = refs[:n_cvec]
    refs = refs[n_cvec:]
    ne_ref = refs.pop(0) if has_eq else None
    qok_ref, shared_ref, p_ref = refs[:3]
    refs = refs[3:]
    cr = refs.pop(0)[...] if has_cr else None
    rcv = refs.pop(0)[...] if fault_model == "crash_recover" else None
    np_ref, part_ref = refs
    p = p_ref[...]
    rr = rk_ref[0] - 1
    x, decided, killed, faulty, k, alive, frozen, down = _load_fields(
        p, kbits, rr, cr, rcv, fault_model, freeze, rejoin)
    shape = x.shape

    # --- the vote tallies ------------------------------------------------
    # 'sampled': verbatim from pallas_hist._cf_kernel (or _equiv_kernel in
    # the equivocate regime); 'delivered'/'camps': the adversary's
    # closed-form counts, broadcast / camp-selected — no draws.
    if counts_mode == "delivered":
        v0, v1 = cvecs[0][...], cvecs[1][...]
    elif counts_mode == "camps":
        v0, v1 = _camp_select(vote_scal_ref, shape, camp_b0, camp_b1,
                              cvecs)
    elif has_eq:
        c0, c1, cq = (v[...] for v in cvecs)
        v0, v1 = _mixed_draws(m, vote_scal_ref, vote_scal2_ref, c0, c1,
                              cq, ne_ref[...], shape)
    else:
        c0, c1, cq = (v[...] for v in cvecs)
        v0, v1 = _cf_pair_draws(m, vote_scal_ref, c0, c1, cq, shape)

    new_x, new_dec, new_k, coined, active = _decide_commit(
        n_faulty, rule, coin_mode, eps, shape, coin_scal_ref,
        shared_ref[...], qok_ref[...], rk_ref[0], x, decided, killed, k,
        alive, frozen, v0, v1)
    _store_planes(np_ref, kbits, new_x, new_dec, killed, faulty, new_k,
                  coined, down=down)
    cols = _vote_partial_cols(fault_model, record, witness_ids, n_local,
                              vote_scal_ref, shape, new_x, new_dec,
                              killed, faulty, alive, active, coined, v0,
                              v1, telemetry=telemetry,
                              telem_sampled=counts_mode == "sampled",
                              telem_hops=2)
    part_ref[...] = _partial_cols(shape[0], cols, part_ref.dtype)


def _fused_round_kernel(m, n_faulty, rule, coin_mode, eps, freeze,
                        fault_model, has_cr, record, witness_ids, n_local,
                        kbits, telemetry, rejoin, *refs):
    """The SINGLE-PASS fused round: both phases of one Ben-Or round over
    the whole (padded) node axis in one kernel invocation.

    The cross-phase dependency — the vote-phase sampler draws from the
    GLOBAL vote-class histogram, which depends on every lane's phase-1
    result — is resolved in-register: with the full node axis resident,
    the histogram is three integer row-sums, and the quorum gate
    (n_alive >= m) one more.  Those sums are the exact integers the
    two-kernel path obtains from its proposal partials (+ psum), so the
    two dispatch targets are bit-identical by construction.  Serves
    counts_mode='sampled' only (the closed-form adversaries run no
    sampler and keep the two-kernel path; see packed_round).

    Emits the new plane stack plus BOTH partial buffers — partsA in the
    proposal kernel's PROP_PARTIAL_LAYOUT (+ witness p0/p1 blocks) and
    partsB in the vote kernel's layout — so packed_round's recorder /
    witness / predicate assembly is one code path for every dispatch.
    """
    has_eq = fault_model == "equivocate"
    refs = list(refs)
    prop_scal = refs.pop(0)
    prop_scal2 = refs.pop(0) if has_eq else None
    vote_scal = refs.pop(0)
    vote_scal2 = refs.pop(0) if has_eq else None
    coin_scal = refs.pop(0)
    rk_ref = refs.pop(0)
    c0_ref, c1_ref, cq_ref = refs[:3]
    refs = refs[3:]
    ne_ref = refs.pop(0) if has_eq else None
    shared_ref = refs.pop(0)
    p_ref = refs.pop(0)
    cr = refs.pop(0)[...] if has_cr else None
    rcv = refs.pop(0)[...] if fault_model == "crash_recover" else None
    np_ref, partA_ref, partB_ref = refs
    p = p_ref[...]
    rr = rk_ref[0] - 1
    x, decided, killed, faulty, k, alive, frozen, down = _load_fields(
        p, kbits, rr, cr, rcv, fault_model, freeze, rejoin)
    shape = x.shape
    t = shape[0]

    # --- phase 1: proposal tallies -> majority -> vote values ------------
    c0, c1, cq = c0_ref[...], c1_ref[...], cq_ref[...]
    if has_eq:
        p0, p1 = _mixed_draws(m, prop_scal, prop_scal2, c0, c1, cq,
                              ne_ref[...], shape)
    else:
        p0, p1 = _cf_pair_draws(m, prop_scal, c0, c1, cq, shape)
    x1 = jnp.where(p0 > p1, VAL0, jnp.where(p1 > p0, VAL1, VALQ))
    vote = _sent(fault_model, jnp.where(frozen, x, x1), faulty)
    hon = _honest(fault_model, alive, faulty)

    colsA = [
        jnp.sum((vote == v) & hon, axis=1, dtype=jnp.int32)
        for v in (VAL0, VAL1, VALQ)
    ] + [jnp.sum(alive, axis=1, dtype=jnp.int32)]
    if witness_ids:
        colsA += _witness_cols(prop_scal, shape, witness_ids, n_local,
                               [p0, p1])
    if telemetry:
        # single-pass proposal stage: the one plane-stack READ (the
        # write is the vote stage's hop — 2 total per round, vs the
        # two-kernel pipeline's 3)
        colsA += _telem_cols(shape, n_local, True, 1, hon=hon)
    partA_ref[...] = _partial_cols(t, colsA, partA_ref.dtype)

    # --- the vote-phase GLOBAL histogram + quorum gate, in-register ------
    # (the full node axis is resident, so the tile sums ARE the globals
    # the two-kernel path psums from its proposal partials)
    c0v = colsA[0].astype(jnp.float32)[:, None]
    c1v = colsA[1].astype(jnp.float32)[:, None]
    cqv = colsA[2].astype(jnp.float32)[:, None]
    qok = (colsA[3] >= m).astype(jnp.int32)[:, None]

    # --- phase 2: vote tallies -> decide/adopt/coin -> commit ------------
    if has_eq:
        v0, v1 = _mixed_draws(m, vote_scal, vote_scal2, c0v, c1v, cqv,
                              ne_ref[...], shape)
    else:
        v0, v1 = _cf_pair_draws(m, vote_scal, c0v, c1v, cqv, shape)
    new_x, new_dec, new_k, coined, active = _decide_commit(
        n_faulty, rule, coin_mode, eps, shape, coin_scal,
        shared_ref[...], qok, rk_ref[0], x, decided, killed, k, alive,
        frozen, v0, v1)
    _store_planes(np_ref, kbits, new_x, new_dec, killed, faulty, new_k,
                  coined, down=down)
    colsB = _vote_partial_cols(fault_model, record, witness_ids, n_local,
                               vote_scal, shape, new_x, new_dec, killed,
                               faulty, alive, active, coined, v0, v1,
                               telemetry=telemetry, telem_sampled=True,
                               telem_hops=1)
    partB_ref[...] = _partial_cols(t, colsB, partB_ref.dtype)


def _smem():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _vec(t):
    return pl.BlockSpec((t, 1), lambda j: (0, 0), memory_space=pltpu.VMEM)


def _lane(t):
    return pl.BlockSpec((t, TILE_N), lambda j: (0, j),
                        memory_space=pltpu.VMEM)


def _planes(t, p):
    """Plane-stack block: the same TILE_N nodes per grid step as _lane,
    expressed as _TILE_W uint32 words per plane."""
    return pl.BlockSpec((t, p, _TILE_W), lambda j: (0, 0, j),
                        memory_space=pltpu.VMEM)


def _part(t):
    return pl.BlockSpec((1, t, PARTIAL_COLS), lambda j: (j, 0, 0),
                        memory_space=pltpu.VMEM)


def _count_vecs(hist, counts_mode):
    """The kernels' count operands as [T, 1] f32 vecs, by counts_mode:
    'sampled' -> the [T, 3] global histogram's three classes; 'delivered'
    -> the adversary's [T, 3] delivered counts' two value classes ("?"
    never enters majority/decide math); 'camps' -> the [T, 3, 3] camp
    triples' six value-class entries, camp-major."""
    f = hist.astype(jnp.float32)
    if counts_mode == "sampled":
        return [f[:, i:i + 1] for i in range(3)]
    if counts_mode == "delivered":
        return [f[:, i:i + 1] for i in range(2)]
    return [f[:, c, i:i + 1] for c in range(3) for i in range(2)]


@instrumented_jit(static_argnames=(
    "m", "fault_model", "freeze", "interpret", "counts_mode", "camp_b0",
    "camp_b1", "witness_ids", "n_local", "telemetry", "rejoin"))
def proposal_hist_pallas(base_key, r, phase, hist, pack, crash_round,
                         m: int, fault_model: str, freeze: bool,
                         interpret: bool = False, node_offset=0,
                         trial_offset=0, n_equiv=None,
                         counts_mode: str = "sampled", camp_b0: int = 0,
                         camp_b1: int = 0, witness_ids: tuple = (),
                         n_local: int = 0, telemetry: bool = False,
                         recover_round=None, rejoin: str = "durable"):
    """Fused proposal phase over the plane stack -> partials
    [T, PARTIAL_COLS] (partial_dtype-narrowed; cast to int32 before
    summing): cols 0-2 this shard's LOCAL vote histogram, col 3 its alive
    count (callers psum both over the nodes axis under a mesh).

    hist: by counts_mode — 'sampled': int32 [T, 3] global PROPOSAL class
    counts (HONEST senders only under 'equivocate'), drawn from in-kernel
    with the PHASE_PROPOSAL stream of cf_counts_pallas
    (equiv_counts_pallas in the equivocate regime) verbatim, so the
    implied per-lane x1 — and hence the histogram — is bit-identical to
    the unfused pallas path; 'delivered': int32 [T, 3] closed-form
    delivered counts (tally.adversarial_counts — identical per receiver,
    so the kernel is deterministic given them); 'camps': int32 [T, 3, 3]
    per-camp triples (tally.targeted_camp_triples), selected per lane by
    global id against the static camp boundaries camp_b0/camp_b1.
    pack: padded plane stack uint32 [T, PACK planes, Np/32];
    crash_round: int32 [T, Np] (crash_at_round only, else None);
    n_equiv: int32 [T] global live-equivocator count ('equivocate' +
    'sampled' only, else None).  The k-plane count is read off the stack
    (pack.shape[1] - PACK_STATIC_WIDTH), so a caller can never hand the
    kernel fewer planes than the stack carries.
    """
    T = pack.shape[0]
    np_total = pack.shape[2] * PACK_NODES_PER_WORD
    n_planes = pack.shape[1]
    kbits = n_planes - PACK_STATIC_WIDTH
    r = jnp.asarray(r, jnp.int32)
    scal = _stream_scal(base_key, r, phase, node_offset, trial_offset)
    cvecs = _count_vecs(hist, counts_mode)
    has_cr = fault_model in ("crash_at_round", "crash_recover")
    has_eq = fault_model == "equivocate" and counts_mode == "sampled"
    pdtype = partial_dtype(m, TILE_N)

    args = [scal, r.reshape(1), *cvecs, pack]
    specs = [_smem(), _smem(), *[_vec(T)] * len(cvecs), _planes(T, n_planes)]
    if has_eq:
        scal2 = _stream_scal(base_key, r, phase + _EQUIV_SALT_OFFSET,
                             node_offset, trial_offset)
        args.insert(1, scal2)
        specs.insert(1, _smem())
        args.insert(6, n_equiv.astype(jnp.float32)[:, None])
        specs.insert(6, _vec(T))
    if has_cr:
        args.append(crash_round)
        specs.append(_lane(T))
    if fault_model == "crash_recover":
        args.append(recover_round)
        specs.append(_lane(T))
    parts = pl.pallas_call(
        functools.partial(_prop_hist_kernel, m, fault_model, freeze,
                          has_cr, counts_mode, camp_b0, camp_b1,
                          witness_ids, n_local, kbits, telemetry,
                          rejoin),
        out_shape=jax.ShapeDtypeStruct((np_total // TILE_N, T,
                                        PARTIAL_COLS), pdtype),
        grid=(np_total // TILE_N,),
        in_specs=specs,
        out_specs=_part(T),
        interpret=interpret,
    )(*args)
    summed = jnp.sum(parts.astype(jnp.int32), axis=0)
    if telemetry:
        # per-tile stage counters ride back next to the summed partials
        # (SimConfig.kernel_telemetry; off = the historical return)
        return summed, _telem_slice(
            parts, _telem_base("proposal", False, len(witness_ids)))
    return summed


@instrumented_jit(static_argnames=(
    "m", "n_faulty", "rule", "coin_mode", "eps", "freeze", "fault_model",
    "interpret", "counts_mode", "camp_b0", "camp_b1", "record",
    "witness_ids", "n_local", "telemetry", "rejoin"))
def vote_commit_pallas(base_key, r, phase, hist, pack, crash_round,
                       quorum_ok, shared, m: int, n_faulty: int, rule: str,
                       coin_mode: str, eps: float, freeze: bool,
                       fault_model: str, interpret: bool = False,
                       node_offset=0, trial_offset=0, n_equiv=None,
                       counts_mode: str = "sampled", camp_b0: int = 0,
                       camp_b1: int = 0, record: bool = False,
                       witness_ids: tuple = (), n_local: int = 0,
                       telemetry: bool = False, recover_round=None,
                       rejoin: str = "durable"):
    """Fused vote phase + commit -> (new plane stack, partials
    [T, PARTIAL_COLS] int32).

    Partials: cols 0-2 the next round's LOCAL proposal histogram (valid
    for static-killed fault models; honest senders only under
    'equivocate'), col 3 settled count, col 4 unsettled count.  hist: the
    VOTE-phase counts in the counts_mode layout of proposal_hist_pallas
    ('sampled': [T, 3] global class counts, psum'd under a mesh;
    'delivered': [T, 3] closed-form counts; 'camps': [T, 3, 3] camp
    triples); quorum_ok: bool [T]; shared: int32-able [T] per-trial
    shared coin bit (ignored for coin_mode='private'); n_equiv: int32 [T]
    global live-equivocator count ('equivocate' + 'sampled' only, else
    None).  The k-plane count is read off the stack, as in
    proposal_hist_pallas.
    """
    T = pack.shape[0]
    np_total = pack.shape[2] * PACK_NODES_PER_WORD
    n_planes = pack.shape[1]
    kbits = n_planes - PACK_STATIC_WIDTH
    r = jnp.asarray(r, jnp.int32)
    vote_scal = _stream_scal(base_key, r, phase, node_offset, trial_offset)
    coin_scal = _stream_scal(base_key, r, _COIN_SALT, node_offset,
                             trial_offset)
    rk = (r + 1).reshape(1)
    cvecs = _count_vecs(hist, counts_mode)
    qok = quorum_ok.astype(jnp.int32)[:, None]
    sh = shared.astype(jnp.int32)[:, None]
    has_cr = fault_model in ("crash_at_round", "crash_recover")
    has_eq = fault_model == "equivocate" and counts_mode == "sampled"
    pdtype = partial_dtype(m, TILE_N)

    args = [vote_scal, coin_scal, rk, *cvecs, qok, sh, pack]
    specs = [_smem(), _smem(), _smem(), *[_vec(T)] * len(cvecs),
             _vec(T), _vec(T), _planes(T, n_planes)]
    if has_eq:
        vote_scal2 = _stream_scal(base_key, r,
                                  phase + _EQUIV_SALT_OFFSET,
                                  node_offset, trial_offset)
        args.insert(1, vote_scal2)
        specs.insert(1, _smem())
        args.insert(7, n_equiv.astype(jnp.float32)[:, None])
        specs.insert(7, _vec(T))
    if has_cr:
        args.append(crash_round)
        specs.append(_lane(T))
    if fault_model == "crash_recover":
        args.append(recover_round)
        specs.append(_lane(T))
    new_pack, parts = pl.pallas_call(
        functools.partial(_vote_commit_kernel, m, n_faulty, rule,
                          coin_mode, eps, freeze, fault_model, has_cr,
                          counts_mode, camp_b0, camp_b1, record,
                          witness_ids, n_local, kbits, telemetry,
                          rejoin),
        out_shape=[jax.ShapeDtypeStruct((T, n_planes,
                                         np_total // PACK_NODES_PER_WORD),
                                        jnp.uint32),
                   jax.ShapeDtypeStruct((np_total // TILE_N, T,
                                         PARTIAL_COLS), pdtype)],
        grid=(np_total // TILE_N,),
        in_specs=specs,
        out_specs=[_planes(T, n_planes), _part(T)],
        interpret=interpret,
    )(*args)
    parts = parts.astype(jnp.int32)
    summed = jnp.sum(parts, axis=0)
    if record:
        # the margin partial is a per-tile MAX, not a sum
        summed = summed.at[:, _RP_MARGIN].set(
            jnp.max(parts[:, :, _RP_MARGIN], axis=0))
    if telemetry:
        return new_pack, summed, _telem_slice(
            parts, _telem_base("vote", record, len(witness_ids)))
    return new_pack, summed


@instrumented_jit(static_argnames=(
    "m", "n_faulty", "rule", "coin_mode", "eps", "freeze", "fault_model",
    "interpret", "record", "witness_ids", "n_local", "telemetry",
    "rejoin"))
def fused_round_pallas(base_key, r, hist1, pack, crash_round, shared,
                       m: int, n_faulty: int, rule: str, coin_mode: str,
                       eps: float, freeze: bool, fault_model: str,
                       interpret: bool = False, n_equiv=None,
                       record: bool = False, witness_ids: tuple = (),
                       n_local: int = 0, telemetry: bool = False,
                       recover_round=None, rejoin: str = "durable"):
    """ONE pallas pass for a whole Ben-Or round (single device,
    counts_mode='sampled', within the FUSED_ONE_PASS_* caps) ->
    (new plane stack, partsA, partsB) with partsA/partsB int32
    [T, PARTIAL_COLS] in the proposal / vote kernels' layouts.

    hist1: int32 [T, 3] — this round's global proposal histogram (the
    loop carry; honest-only under 'equivocate'); shared: int32-able [T]
    per-trial shared coin bit.  Node/trial offsets are 0 by definition
    (the pass only serves ctx SINGLE), so every stream key matches the
    two-kernel path's.
    """
    from . import rng

    T = pack.shape[0]
    n_w = pack.shape[2]
    np_total = n_w * PACK_NODES_PER_WORD
    n_planes = pack.shape[1]
    kbits = n_planes - PACK_STATIC_WIDTH
    r = jnp.asarray(r, jnp.int32)
    prop_scal = _stream_scal(base_key, r, rng.PHASE_PROPOSAL, 0, 0)
    vote_scal = _stream_scal(base_key, r, rng.PHASE_VOTE, 0, 0)
    coin_scal = _stream_scal(base_key, r, _COIN_SALT, 0, 0)
    rk = (r + 1).reshape(1)
    cvecs = _count_vecs(hist1, "sampled")
    sh = shared.astype(jnp.int32)[:, None]
    has_cr = fault_model in ("crash_at_round", "crash_recover")
    has_eq = fault_model == "equivocate"
    pdtype = partial_dtype(m, np_total)

    # whole-axis blocks: the single grid step sees every node of every
    # trial (that residency is what lets the vote-phase histogram and the
    # quorum gate happen in-register)
    whole_planes = pl.BlockSpec((T, n_planes, n_w), lambda j: (0, 0, 0),
                                memory_space=pltpu.VMEM)
    whole_lane = pl.BlockSpec((T, np_total), lambda j: (0, 0),
                              memory_space=pltpu.VMEM)
    whole_part = pl.BlockSpec((1, T, PARTIAL_COLS), lambda j: (0, 0, 0),
                              memory_space=pltpu.VMEM)

    args = [prop_scal, vote_scal, coin_scal, rk, *cvecs, sh, pack]
    specs = [_smem(), _smem(), _smem(), _smem(), *[_vec(T)] * 3,
             _vec(T), whole_planes]
    if has_eq:
        prop_scal2 = _stream_scal(base_key, r,
                                  rng.PHASE_PROPOSAL + _EQUIV_SALT_OFFSET,
                                  0, 0)
        vote_scal2 = _stream_scal(base_key, r,
                                  rng.PHASE_VOTE + _EQUIV_SALT_OFFSET,
                                  0, 0)
        args.insert(1, prop_scal2)
        specs.insert(1, _smem())
        args.insert(3, vote_scal2)
        specs.insert(3, _smem())
        args.insert(9, n_equiv.astype(jnp.float32)[:, None])
        specs.insert(9, _vec(T))
    if has_cr:
        args.append(crash_round)
        specs.append(whole_lane)
    if fault_model == "crash_recover":
        args.append(recover_round)
        specs.append(whole_lane)
    new_pack, partsA, partsB = pl.pallas_call(
        functools.partial(_fused_round_kernel, m, n_faulty, rule,
                          coin_mode, eps, freeze, fault_model, has_cr,
                          record, witness_ids, n_local, kbits, telemetry,
                          rejoin),
        out_shape=[jax.ShapeDtypeStruct((T, n_planes, n_w), jnp.uint32),
                   jax.ShapeDtypeStruct((1, T, PARTIAL_COLS), pdtype),
                   jax.ShapeDtypeStruct((1, T, PARTIAL_COLS), pdtype)],
        grid=(1,),
        in_specs=specs,
        out_specs=[whole_planes, whole_part, whole_part],
        interpret=interpret,
    )(*args)
    out = (new_pack, jnp.sum(partsA.astype(jnp.int32), axis=0),
           jnp.sum(partsB.astype(jnp.int32), axis=0))
    if telemetry:
        k = len(witness_ids)
        return out + (_telem_slice(partsA, _telem_base("proposal", False,
                                                       k)),
                      _telem_slice(partsB, _telem_base("vote", record,
                                                       k)))
    return out


def _pad_cr(faults, np_total):
    cr = faults.crash_round.astype(jnp.int32)
    n_pad = np_total - cr.shape[-1]
    if n_pad:
        cr = jnp.pad(cr, ((0, 0), (0, n_pad)))
    return cr


def pad_fault_rounds(cfg, faults, np_total):
    """(crash_round, recover_round) padded to the plane geometry — the
    per-round-bound operands the kernels re-derive liveness from.
    (None, None) for the statically-killed fault models, (cr, None)
    under crash_at_round, (cr, rec) under crash_recover.  Pad lanes get
    0 bounds (never crash, never rejoin) and carry the killed plane bit
    anyway."""
    if cfg.fault_model == "crash_at_round":
        return _pad_cr(faults, np_total), None
    if cfg.fault_model == "crash_recover":
        if faults.recover_round is None:
            raise ValueError(
                "fault_model='crash_recover' needs FaultSpec."
                "recover_round (faults.recovery.crash_recover_faults "
                "builds it from the SimConfig.recovery spec)")
        rec = faults.recover_round.astype(jnp.int32)
        n_pad = np_total - rec.shape[-1]
        if n_pad:
            rec = jnp.pad(rec, ((0, 0), (0, n_pad)))
        return _pad_cr(faults, np_total), rec
    return None, None


def sent_hist_from_pack(cfg, pack, crash_round, recover_round, r, ctx):
    """XLA fallback for the proposal histogram (round 1 of every run, and
    every round under crash_at_round / crash_recover, whose per-round
    liveness changes invalidate the vote kernel's emitted next-round
    partials).  Under 'equivocate' the histogram spans HONEST live
    senders only (equivocator values are drawn receiver-side); under
    'crash_recover' it excludes this round's down-interval lanes and
    applies the amnesia rejoin reset, mirroring the in-kernel
    _load_fields exactly."""
    x = plane_field(pack, PACK_X, _X_BITS)
    killed = plane_field(pack, PACK_KILLED, 1)
    faulty = plane_field(pack, PACK_FAULTY, 1)
    rr = jnp.asarray(r, jnp.int32)
    if cfg.fault_model == "crash_at_round":
        crashing = (faulty == 1) & (crash_round > 0) & (rr >= crash_round)
        killed = jnp.where(crashing, 1, killed)
    alive = killed == 0
    if cfg.fault_model == "crash_recover":
        from ..faults.recovery import rejoin_mode
        started = (faulty == 1) & (crash_round > 0) & (rr >= crash_round)
        killed = jnp.where(started & (recover_round <= 0), 1, killed)
        down = started & (recover_round > 0) & (rr < recover_round)
        alive = (killed == 0) & ~down
        if rejoin_mode(cfg.recovery) == "amnesia":
            decided = plane_field(pack, PACK_DECIDED, 1)
            # cr > 0: no crash, nothing to forget (mirrors _load_fields)
            rj = (faulty == 1) & (crash_round > 0) & \
                (recover_round > 0) & (rr == recover_round) & \
                (decided == 0)
            x = jnp.where(rj, VALQ, x)
    sent = _sent(cfg.fault_model, x, faulty)
    hon = _honest(cfg.fault_model, alive, faulty)
    cnt = [jnp.sum((sent == v) & hon, axis=-1, dtype=jnp.int32)
           for v in (VAL0, VAL1, VALQ)]
    return ctx.psum_nodes(jnp.stack(cnt, axis=-1))


def n_equiv_from_pack(cfg, pack, ctx):
    """Global live-equivocator count int32 [T] (RUN-constant under
    'equivocate': the killed and faulty planes are static for this fault
    model, so run_packed hoists this out of the while-loop); None for
    every other fault model.  Pure plane-word math: popcount of
    faulty & ~killed, no per-lane expansion."""
    if cfg.fault_model != "equivocate":
        return None
    live_eqv = pack[:, PACK_FAULTY, :] & ~pack[:, PACK_KILLED, :]
    return ctx.psum_nodes(jnp.sum(
        jax.lax.population_count(live_eqv), axis=-1).astype(jnp.int32))


def packed_round(cfg, pack, faults, base_key, r, hist1, ctx, n_local,
                 n_equiv=None):
    """One fused round over the plane-packed state.

    ``n_local`` is this shard's TRUE (unpadded) node count — the global-id
    base derivation needs it.  ``hist1`` is this round's global proposal
    histogram.  ``n_equiv`` is the global live-equivocator count [T]
    ('equivocate' only; derived from the pack when not supplied —
    run_packed precomputes it so the loop stays free of per-lane XLA
    ops).  Returns (new_pack, hist1_next or None, unsettled [T], row,
    wrow); hist1_next is None under crash_at_round (recompute via
    sent_hist_from_pack); ``row`` is the flight-recorder row int32
    [state.REC_WIDTH] when cfg.record (globalized: counts psum'd, margin
    pmax'd over nodes then summed over trials) and None otherwise;
    ``wrow`` is the witness row int32 [W, k, state.WIT_WIDTH] when
    cfg.witness (assembled from the kernels' per-tile witness partials,
    psum-globalized over both mesh axes) and None otherwise.  With
    cfg.kernel_telemetry a SIXTH element rides the return: this round's
    per-tile stage counters int32 [2, tiles, TELEM_WIDTH] (TELEM_STAGES
    order — telemetry_tiles gives the tile count the dispatch will
    produce).

    Dispatch: counts_mode='sampled' on a single device within the
    FUSED_ONE_PASS_* caps takes the SINGLE-PASS kernel
    (fused_round_pallas — both phases, no inter-kernel HBM round trip);
    meshes, the closed-form adversaries, and over-cap tiles take the
    two-kernel plane pipeline.  Both emit the same partial layouts, so
    everything below the kernel calls is one code path — and both share
    every stream and integer reduction, so results are bit-identical.
    """
    from . import rng, tally
    from .collectives import SINGLE
    from ..state import witness_node_ids

    T = pack.shape[0]
    np_total = pack.shape[2] * PACK_NODES_PER_WORD
    interp = jax.default_backend() == "cpu"
    m = cfg.quorum
    cr, rec = pad_fault_rounds(cfg, faults, np_total)
    from ..faults.recovery import rejoin_mode
    rejoin = rejoin_mode(cfg.recovery)
    if n_equiv is None:
        n_equiv = n_equiv_from_pack(cfg, pack, ctx)
    node_off = ctx.node_ids(n_local)[0]
    trial_off = ctx.trial_ids(T)[0]
    wids = (tuple(int(i) for i in witness_node_ids(cfg))
            if cfg.witness else ())

    # Counts source (tally.pallas_round_counts_mode): the uniform CF
    # regime samples tallies in-kernel from the phase histogram; the
    # count-controlling adversaries turn the histogram into CLOSED-FORM
    # delivered counts here — [T, 3]-sized XLA math, mirroring the
    # unfused receiver_counts dispatch exactly — and the kernels
    # broadcast/camp-select them with no sampler at all.
    mode = tally.pallas_round_counts_mode(cfg)
    camp_b0 = camp_b1 = 0
    if mode == "camps":
        size_v, _ = tally.targeted_camp_sizes(cfg)
        camp_b1 = max(cfg.n_nodes - size_v, 0)
        camp_b0 = max(cfg.n_nodes - 2 * size_v, 0)

    def kernel_counts(hist):
        if mode == "delivered":
            return tally.adversarial_counts(hist, m, n_free=n_equiv)
        if mode == "camps":
            return tally.targeted_camp_triples(cfg, hist, n_free=n_equiv)
        return hist

    if cfg.coin_mode == "private":
        shared = jnp.zeros((T,), jnp.int32)
    else:
        shared = rng.coin_flips(base_key, r, ctx.trial_ids(T),
                                rng.ids(1), common=True)[:, 0]

    telem = bool(cfg.kernel_telemetry)
    telemA = telemB = None
    one_pass = (ctx is SINGLE
                and fused_one_pass_eligible(cfg, T, n_local))
    if one_pass:
        out = fused_round_pallas(
            base_key, r, hist1, pack, cr, shared, m, cfg.n_faulty,
            cfg.rule, cfg.coin_mode, float(cfg.coin_eps),
            bool(cfg.freeze_decided), cfg.fault_model, interpret=interp,
            n_equiv=n_equiv, record=bool(cfg.record), witness_ids=wids,
            n_local=n_local, telemetry=telem, recover_round=rec,
            rejoin=rejoin)
        new_pack, partsA, partsB = out[:3]
        if telem:
            telemA, telemB = out[3:]
    else:
        out = proposal_hist_pallas(
            base_key, r, rng.PHASE_PROPOSAL, kernel_counts(hist1), pack,
            cr, m, cfg.fault_model, bool(cfg.freeze_decided),
            interpret=interp, node_offset=node_off,
            trial_offset=trial_off, n_equiv=n_equiv, counts_mode=mode,
            camp_b0=camp_b0, camp_b1=camp_b1, witness_ids=wids,
            n_local=n_local, telemetry=telem, recover_round=rec,
            rejoin=rejoin)
        partsA = out[0] if telem else out
        if telem:
            telemA = out[1]
        hist2 = ctx.psum_nodes(partsA[:, :3])
        n_alive = ctx.psum_nodes(partsA[:, 3])
        quorum_ok = n_alive >= m
        out = vote_commit_pallas(
            base_key, r, rng.PHASE_VOTE, kernel_counts(hist2), pack, cr,
            quorum_ok, shared, m, cfg.n_faulty, cfg.rule, cfg.coin_mode,
            float(cfg.coin_eps), bool(cfg.freeze_decided),
            cfg.fault_model, interpret=interp, node_offset=node_off,
            trial_offset=trial_off, n_equiv=n_equiv, counts_mode=mode,
            camp_b0=camp_b0, camp_b1=camp_b1, record=bool(cfg.record),
            witness_ids=wids, n_local=n_local, telemetry=telem,
            recover_round=rec, rejoin=rejoin)
        new_pack, partsB = out[:2]
        if telem:
            telemB = out[2]
    # crash_at_round / crash_recover: the vote kernel's emitted
    # next-round histogram is invalid (liveness — and under amnesia x —
    # changes between rounds); the loop recomputes via
    # sent_hist_from_pack instead
    hist1_next = (None
                  if cfg.fault_model in ("crash_at_round",
                                         "crash_recover")
                  else ctx.psum_nodes(partsB[:, :3]))
    unsettled = ctx.psum_nodes(partsB[:, 4])
    row = None
    if cfg.record:
        from ..state import (REC_COINS, REC_DECIDED, REC_KILLED,
                             REC_MARGIN, REC_UNDEC0, REC_UNDEC1,
                             REC_UNDECQ, REC_WIDTH)
        # pad lanes carry the killed bit — remove this shard's static pad
        # count per trial BEFORE the node-axis psum
        killed_local = partsB[:, _RP_KILL] - jnp.int32(np_total - n_local)
        per_trial = {
            REC_DECIDED: ctx.psum_nodes(partsB[:, _RP_DEC]),
            REC_KILLED: ctx.psum_nodes(killed_local),
            REC_UNDEC0: ctx.psum_nodes(partsB[:, _RP_U0]),
            REC_UNDEC1: ctx.psum_nodes(partsB[:, _RP_U1]),
            REC_UNDECQ: ctx.psum_nodes(partsB[:, _RP_UQ]),
            REC_COINS: ctx.psum_nodes(partsB[:, _RP_COIN]),
            REC_MARGIN: ctx.pmax_nodes(partsB[:, _RP_MARGIN]),
        }
        row = jnp.stack([
            ctx.psum_trials(jnp.sum(per_trial[i], dtype=jnp.int32))
            for i in range(REC_WIDTH)])
    wrow = None
    if cfg.witness:
        from ..state import (WIT_COINED, WIT_DECIDED, WIT_KILLED, WIT_P0,
                             WIT_P1, WIT_V0, WIT_V1, WIT_WIDTH,
                             WIT_WRITTEN, WIT_X)
        k = cfg.witness_nodes
        witb = _witb_base(bool(cfg.record))
        # node-axis psum: only the (real-lane) tile owning each watched id
        # contributed, so the sum IS the value
        pa = ctx.psum_nodes(
            partsA[:, _WITA_BASE:_WITA_BASE + _WITA_PER_NODE * k])
        wb = ctx.psum_nodes(partsB[:, witb:witb + _WITB_PER_NODE * k])
        # watched-trial selection by GLOBAL id, then the trial-axis psum —
        # mirrors state.witness_select's mesh discipline
        wt = jnp.asarray(cfg.witness_trials, jnp.int32)
        t_oh = (ctx.trial_ids(T)[None, :] == wt[:, None]).astype(jnp.int32)
        pa_sel = ctx.psum_trials(t_oh @ pa)                   # [W, 2k]
        wb_sel = ctx.psum_trials(t_oh @ wb)                   # [W, 6k]
        W = len(cfg.witness_trials)
        wrow = jnp.zeros((W, k, WIT_WIDTH), jnp.int32)
        wrow = (wrow
                .at[:, :, WIT_P0].set(pa_sel[:, 0::2])
                .at[:, :, WIT_P1].set(pa_sel[:, 1::2])
                .at[:, :, WIT_X].set(wb_sel[:, 0::6])
                .at[:, :, WIT_DECIDED].set(wb_sel[:, 1::6])
                .at[:, :, WIT_KILLED].set(wb_sel[:, 2::6])
                .at[:, :, WIT_COINED].set(wb_sel[:, 3::6])
                .at[:, :, WIT_V0].set(wb_sel[:, 4::6])
                .at[:, :, WIT_V1].set(wb_sel[:, 5::6])
                .at[:, :, WIT_WRITTEN].set(1))
    if telem:
        # stage-major per-tile stage counters int32 [2, tiles,
        # TELEM_WIDTH] (TELEM_STAGES order) — this round's increment of
        # the run accumulator run_packed_slice carries
        return (new_pack, hist1_next, unsettled, row, wrow,
                jnp.stack([telemA, telemB]))
    return new_pack, hist1_next, unsettled, row, wrow


def run_packed_slice(cfg, state, faults, base_key, from_round, until_round,
                     ctx=None, recorder=None, witness=None):
    """The packed while-loop, generalized over (mesh ctx, round bounds).

    At most ``until_round - from_round`` rounds from ``from_round`` (both
    TRACED), carrying the bit-plane stack: pack/unpack and every
    per-lane XLA op run once per CALL, not per round.  Under a mesh
    ``ctx`` the loop predicate reads the globally psum'd unsettled count
    (node-axis psum from the vote kernel's partials, trial-axis psum
    here), so all shards take identical trip counts.  The caller applies
    the /start transition; returns (next_round, NetState) — the
    run_consensus_slice contract.  ONE definition serves the
    single-device runner (run_packed) and the shard_map'd runner
    (parallel/sharded.py:_local_slice), so the fused loop cannot drift
    between them.

    With cfg.record the flight recorder rides the carry — each round's
    globalized row (packed_round) lands via dynamic_update_slice, so the
    FUSED regime gets full round history with no demotion and no host
    round trips.  ``recorder`` threads an existing buffer across slices
    (None builds a fresh one snapshotting ``state`` into row 0); the
    filled buffer is appended to the return.  cfg.witness threads
    ``witness`` identically (appended after the recorder when both ride):
    the kernels' per-tile witness partials land in the same buffer the
    XLA regimes fill, with no demotion.

    cfg.kernel_telemetry appends LAST (after recorder and witness) the
    per-tile stage-counter accumulator int32 [2, tiles, TELEM_WIDTH]
    (TELEM_STAGES x telemetry_tiles x TELEM_COLS), summed over this
    CALL's executed rounds and trials.  Fresh per call — a sliced run's
    per-slice accumulators ADD UP to the one-shot run's, so resume
    needs no threading (tests/test_kernelscope.py pins the identity).
    Positional consumers that predate the flag never index past the
    tails they know, so the extra element is inert for them.
    """
    from .collectives import SINGLE
    from ..state import (new_recorder, new_witness, recorder_write,
                         witness_write)

    ctx = SINGLE if ctx is None else ctx
    n_local = state.x.shape[-1]
    if cfg.record and recorder is None:
        recorder = new_recorder(cfg, state, ctx)
    if cfg.witness and witness is None:
        witness = new_witness(cfg, state, ctx)
    telem0 = None
    if cfg.kernel_telemetry:
        telem0 = jnp.zeros((len(TELEM_STAGES),
                            telemetry_tiles(cfg, state.x.shape[0],
                                            n_local), TELEM_WIDTH),
                           jnp.int32)
    pack = pack_state(cfg, state, faults.faulty)
    np_total = pack.shape[2] * PACK_NODES_PER_WORD
    cr, rec = pad_fault_rounds(cfg, faults, np_total)
    n_equiv = n_equiv_from_pack(cfg, pack, ctx)      # run-constant, hoisted
    hist1 = sent_hist_from_pack(cfg, pack, cr, rec, from_round, ctx)
    # unsettled lanes straight off the decided/killed planes (pads carry
    # the killed bit, so ~(dec | kill) is 0 on every pad word bit)
    unsett_bits = ~(pack[:, PACK_DECIDED, :] | pack[:, PACK_KILLED, :])
    unsettled0 = ctx.psum_all(jnp.sum(
        jax.lax.population_count(unsett_bits)).astype(jnp.int32))

    def cond(carry):
        r, unsettled = carry[0], carry[3]
        return (r <= cfg.max_rounds) & (unsettled > 0) & (r < until_round)

    def body(carry):
        r, pack, hist1 = carry[0], carry[1], carry[2]
        if cfg.fault_model in ("crash_at_round", "crash_recover"):
            hist1 = sent_hist_from_pack(cfg, pack, cr, rec, r, ctx)
        rout = packed_round(cfg, pack, faults, base_key, r, hist1, ctx,
                            n_local, n_equiv=n_equiv)
        new_pack, hist1_next, unsettled, row, wrow = rout[:5]
        if hist1_next is None:
            hist1_next = hist1              # recomputed next iteration
        out = (r + 1, new_pack, hist1_next,
               ctx.psum_trials(jnp.sum(unsettled)))
        i = 4
        if cfg.record:
            out = out + (recorder_write(carry[i], r, row),)
            i += 1
        if cfg.witness:
            out = out + (witness_write(carry[i], r, wrow),)
            i += 1
        if cfg.kernel_telemetry:
            out = out + (carry[i] + rout[5],)
        return out

    carry = (jnp.asarray(from_round, jnp.int32), pack, hist1, unsettled0)
    if cfg.record:
        carry = carry + (recorder,)
    if cfg.witness:
        carry = carry + (witness,)
    if cfg.kernel_telemetry:
        carry = carry + (telem0,)
    out = jax.lax.while_loop(cond, body, carry)
    r, pack = out[0], out[1]
    return (r, unpack_state(pack, n_local), *out[4:])


def run_packed(cfg, state, faults, base_key):
    """Single-device fast path for sim.run_consensus: run_packed_slice
    from /start with an unbounded slice.  Bit-identical to the generic
    loop.  With cfg.record / cfg.witness, returns the filled flight
    recorder / witness buffer too; with cfg.kernel_telemetry the
    per-tile stage-counter accumulator rides last (the kernelscope
    capture's raw material)."""
    from ..sim import start_state

    state = start_state(cfg, state)
    out = run_packed_slice(cfg, state, faults, base_key,
                           jnp.int32(1), jnp.int32(cfg.max_rounds + 2))
    return (out[0] - 1, *out[1:])
