"""Fused round kernels: the whole Ben-Or round as two VMEM passes.

r3 VERDICT item 2 (the HBM roofline gap): on the flagship path each
phase's sampler kernel (ops/pallas_hist.py:cf_counts_pallas) writes int32
counts [T, N, 3] (12 B/lane) that a chain of XLA elementwise kernels then
re-reads — phase 1 to compute x1/vote values, phase 2 to compute
decide0/decide1 (node.ts:99-104), plurality-adopt (node.ts:106-112), the
coin (a separate pallas kernel, 4 B/lane write + read), and the commit
masks — every intermediate materialized in HBM because XLA cannot fuse
INTO a pallas call.  The two kernels here eliminate all of it:

  proposal_hist_pallas  — per-lane proposal tallies + majority/tie + the
                          vote value, reduced IN-KERNEL to a per-tile
                          partial vote histogram (~1 B/lane out; the
                          [T,N,3] counts and [T,N] x1 never exist).
  vote_commit_pallas    — per-lane vote tallies + coin + decide/adopt/
                          commit; HBM traffic is the state in/out only.

Stream identity: the vote draws use the SAME key/counter scheme as
cf_counts_pallas(phase=PHASE_VOTE) and the coin the SAME scheme as
coin_flips_pallas / weak_coin_flips_pallas (word 0 = private bit, word 1 =
deviation uniform), so a run with ``use_pallas_round=True`` is
BIT-IDENTICAL to the unfused ``use_pallas_hist=True`` path — pinned by
tests/test_pallas_round.py, which makes interpret-mode CPU testing exact
rather than statistical.

Engages (models/benor.py) on top of the pallas-hist regime for
fault_model='crash', any rule, coin_mode private / common / weak_common
with 0 < eps < 1 (the weak endpoints short-circuit to the plain streams on
the XLA side, exactly like the unfused dispatch).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_hist import (_COIN_SALT, TILE_N, _bits_to_uniform, _cf_draw,
                          _lane_ids, _stream_scal, _threefry2x32)
from ..config import VAL0, VAL1, VALQ


def _prop_hist_kernel(m, scal_ref, c0_ref, c1_ref, cq_ref, src_ref,
                      out_ref):
    """One lane-tile of the fused PROPOSAL phase: per-lane CF tallies ->
    phase-1 majority/tie -> each lane's vote value -> this tile's partial
    vote-class histogram.  NO per-lane output reaches HBM at all — the
    [T, N, 3] proposal counts and the [T, N] x1 tensor of the unfused
    path become one [T, 128]-padded partial per tile (~1 B/lane).

    src_ref: VMEM int32 [T, TILE_N] vote source: -2 = dead (not counted),
    -1 = live undecided (vote the in-kernel x1), -3 = live undecided
    byzantine (vote the BIT-FLIP of the in-kernel x1 — every receiver
    hears the flipped broadcast, models/benor.py:_flip), 0/1/2 = frozen
    lane's decided value, pre-flipped by the caller where byzantine (the
    reference's decided nodes keep vouching, node.ts:147-157).
    out_ref: VMEM int32 [1, T, 128] — columns 0..2 are the tile's
    (c0, c1, cq) vote counts, the rest zero padding (a 3-wide minor dim
    would fight Mosaic tiling).
    """
    node, trial = _lane_ids(scal_ref, src_ref.shape)
    b0, b1 = _threefry2x32(scal_ref[0], scal_ref[1], node, trial)
    u0 = _bits_to_uniform(b0)
    u1 = _bits_to_uniform(b1)
    c0 = c0_ref[...]
    c1 = c1_ref[...]
    cq = cq_ref[...]
    total = c0 + c1 + cq
    mf = jnp.float32(m)
    p0 = _cf_draw(u0, total, c0, mf)
    p1 = _cf_draw(u1, jnp.maximum(total - c0, 0.0), c1,
                  jnp.maximum(mf - p0, 0.0))
    x1 = jnp.where(p0 > p1, VAL0,
                   jnp.where(p1 > p0, VAL1, VALQ))         # node.ts:63-69
    x1_flip = jnp.where(x1 == VAL0, VAL1,
                        jnp.where(x1 == VAL1, VAL0, VALQ))
    src = src_ref[...]
    vote = jnp.where(src == -1, x1, jnp.where(src == -3, x1_flip, src))
    alive = src != -2
    t = src.shape[0]
    parts = [jnp.sum((vote == v) & alive, axis=1,
                     dtype=jnp.int32)[None, :, None]        # [1, T, 1]
             for v in (VAL0, VAL1, VALQ)]
    col = jax.lax.broadcasted_iota(jnp.int32, (1, t, 128), 2)
    out_ref[...] = ((col == 0) * parts[0] + (col == 1) * parts[1]
                    + (col == 2) * parts[2])


@functools.partial(jax.jit, static_argnames=("m", "n_nodes", "interpret"))
def proposal_hist_pallas(base_key: jax.Array, r: jax.Array, phase: int,
                         hist: jax.Array, vote_src: jax.Array,
                         m: int, n_nodes: int, interpret: bool = False,
                         node_offset: jax.Array | int = 0,
                         trial_offset: jax.Array | int = 0) -> jax.Array:
    """Fused proposal phase -> this shard's LOCAL vote histogram int32
    [T, 3] (callers psum it over the nodes axis under a mesh).

    hist: int32 [T, 3] global PROPOSAL class counts; vote_src: int32
    [T, N_local] (-2 dead / -1 undecided / 0,1,2 frozen value).  Uses the
    PHASE_PROPOSAL stream of cf_counts_pallas verbatim, so the implied
    per-lane x1 — and hence the histogram — is bit-identical to the
    unfused pallas path (integer sums are order-free).
    """
    T = hist.shape[0]
    n_pad = (-n_nodes) % TILE_N
    np_total = n_nodes + n_pad

    r = jnp.asarray(r, jnp.int32)
    scal = _stream_scal(base_key, r, phase, node_offset, trial_offset)
    cls = hist.astype(jnp.float32)[..., None]               # [T, 3, 1]
    c0, c1, cq = cls[:, 0], cls[:, 1], cls[:, 2]
    src = vote_src.astype(jnp.int32)
    if n_pad:
        src = jnp.pad(src, ((0, 0), (0, n_pad)), constant_values=-2)

    vec = pl.BlockSpec((T, 1), lambda j: (0, 0), memory_space=pltpu.VMEM)
    parts = pl.pallas_call(
        functools.partial(_prop_hist_kernel, m),
        out_shape=jax.ShapeDtypeStruct((np_total // TILE_N, T, 128),
                                       jnp.int32),
        grid=(np_total // TILE_N,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  vec, vec, vec,
                  pl.BlockSpec((T, TILE_N), lambda j: (0, j),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, T, 128), lambda j: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(scal, c0, c1, cq, src)
    return jnp.sum(parts, axis=0)[:, :3]


def _vote_commit_kernel(m, n_faulty, rule, coin_mode, eps, freeze,
                        vote_scal_ref, coin_scal_ref, rk_ref,
                        c0_ref, c1_ref, cq_ref, qok_ref, shared_ref,
                        x_ref, dec_ref, k_ref, killed_ref,
                        nx_ref, ndec_ref, nk_ref):
    """One lane-tile: vote-phase CF draws -> decide/adopt/coin -> commit.

    vote_scal_ref / coin_scal_ref: SMEM uint32 [4] stream keys (the
    PHASE_VOTE sampler stream and the _COIN_SALT coin stream — identical
    to the standalone kernels').  rk_ref: SMEM int32 [1] = r + 1 (the
    committed k for lanes that run the round, node.ts:147).
    c0/c1/cq_ref: VMEM f32 [T, 1] global vote-class counts;
    qok_ref / shared_ref: VMEM int32 [T, 1] quorum gate / per-trial shared
    coin bit; x/dec/k/killed_ref: VMEM int32 [T, TILE_N] current state.
    """
    # --- the sampler body, verbatim from pallas_hist._cf_kernel ---------
    node, trial = _lane_ids(vote_scal_ref, nx_ref.shape)
    b0, b1 = _threefry2x32(vote_scal_ref[0], vote_scal_ref[1], node, trial)
    u0 = _bits_to_uniform(b0)
    u1 = _bits_to_uniform(b1)
    c0 = c0_ref[...]
    c1 = c1_ref[...]
    cq = cq_ref[...]
    total = c0 + c1 + cq
    mf = jnp.float32(m)
    v0 = _cf_draw(u0, total, c0, mf)
    v1 = _cf_draw(u1, jnp.maximum(total - c0, 0.0), c1,
                  jnp.maximum(mf - v0, 0.0))

    # --- the coin, verbatim from _coin_kernel / _weak_coin_kernel -------
    pbits, dbits = _threefry2x32(coin_scal_ref[0], coin_scal_ref[1],
                                 node, trial)
    private = (pbits & jnp.uint32(1)).astype(jnp.int32)
    if coin_mode == "private":
        coin = private
    elif coin_mode == "common":
        coin = jnp.broadcast_to(shared_ref[...], private.shape)
    else:  # weak_common, 0 < eps < 1
        dev = _bits_to_uniform(dbits) < jnp.float32(eps)
        coin = jnp.where(dev, private, shared_ref[...])

    # --- decide / adopt / commit (models/benor.py lines 115-174) --------
    ff = jnp.float32(n_faulty)
    decide0 = v0 > ff                                    # node.ts:99
    decide1 = v1 > ff                                    # node.ts:102
    if rule == "reference":                              # quirk 9
        any_votes = (v0 + v1) > 0.0
        adopt0 = any_votes & (v0 > v1)
        adopt1 = any_votes & (v0 < v1)
        x2 = jnp.where(decide0, VAL0,
             jnp.where(decide1, VAL1,
             jnp.where(adopt0, VAL0,
             jnp.where(adopt1, VAL1, coin))))
    else:                                                # textbook
        x2 = jnp.where(decide0, VAL0,
             jnp.where(decide1, VAL1, coin))

    x = x_ref[...]
    decided = dec_ref[...]
    killed = killed_ref[...]
    alive = killed == 0
    if freeze:
        frozen = decided != 0
    else:
        frozen = jnp.zeros_like(alive)
    active = alive & (qok_ref[...] != 0) & ~frozen
    newly = active & (decide0 | decide1)
    nx_ref[...] = jnp.where(active, x2, x)
    ndec_ref[...] = jnp.where(newly, 1, decided)
    nk_ref[...] = jnp.where(active, rk_ref[0], k_ref[...])


@functools.partial(jax.jit, static_argnames=(
    "m", "n_faulty", "n_nodes", "rule", "coin_mode", "eps", "freeze",
    "interpret"))
def vote_commit_pallas(base_key: jax.Array, r: jax.Array, phase: int,
                       hist: jax.Array, x: jax.Array, decided: jax.Array,
                       k: jax.Array, killed: jax.Array,
                       quorum_ok: jax.Array, shared: jax.Array,
                       m: int, n_faulty: int, n_nodes: int, rule: str,
                       coin_mode: str, eps: float, freeze: bool,
                       interpret: bool = False,
                       node_offset: jax.Array | int = 0,
                       trial_offset: jax.Array | int = 0):
    """Fused vote phase -> (new_x int8, new_decided bool, new_k int32).

    hist: int32 [T, 3] global vote-class counts (psum'd under a mesh);
    x int8 / decided bool / k int32 / killed bool [T, N] current state;
    quorum_ok bool [T]; shared int32-able [T] per-trial shared coin bit
    (ignored for coin_mode='private').  Drop-in replacement for
    cf_counts_pallas(vote) + coin kernel + the XLA decide/adopt/commit
    chain — bit-identical to that unfused pallas path by stream identity.
    """
    T = hist.shape[0]
    n_pad = (-n_nodes) % TILE_N
    np_total = n_nodes + n_pad

    r = jnp.asarray(r, jnp.int32)
    vote_scal = _stream_scal(base_key, r, phase, node_offset, trial_offset)
    coin_scal = _stream_scal(base_key, r, _COIN_SALT, node_offset,
                             trial_offset)
    rk = (r + 1).reshape(1)

    cls = hist.astype(jnp.float32)[..., None]               # [T, 3, 1]
    c0, c1, cq = cls[:, 0], cls[:, 1], cls[:, 2]            # [T, 1]
    qok = quorum_ok.astype(jnp.int32)[:, None]
    sh = shared.astype(jnp.int32)[:, None]

    def pad(a, fill):
        a = a.astype(jnp.int32)
        if n_pad:
            a = jnp.pad(a, ((0, 0), (0, n_pad)), constant_values=fill)
        return a

    state_in = (pad(x, VALQ), pad(decided, 0), pad(k, 0), pad(killed, 1))

    vec = pl.BlockSpec((T, 1), lambda j: (0, 0), memory_space=pltpu.VMEM)
    lane = pl.BlockSpec((T, TILE_N), lambda j: (0, j),
                        memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    nx, ndec, nk = pl.pallas_call(
        functools.partial(_vote_commit_kernel, m, n_faulty, rule,
                          coin_mode, eps, freeze),
        out_shape=[jax.ShapeDtypeStruct((T, np_total), jnp.int32)] * 3,
        grid=(np_total // TILE_N,),
        in_specs=[smem, smem, smem, vec, vec, vec, vec, vec,
                  lane, lane, lane, lane],
        out_specs=[lane] * 3,
        interpret=interpret,
    )(vote_scal, coin_scal, rk, c0, c1, cq, qok, sh, *state_in)
    return (nx[:, :n_nodes].astype(jnp.int8),
            ndec[:, :n_nodes].astype(bool),
            nk[:, :n_nodes])
