"""Fused Pallas TPU kernel for the histogram-path quorum sampler.

This is the flagship-path kernel: at N=1M the round cost is dominated by the
per-lane Cornish-Fisher hypergeometric sampling in ops/sampling.py — XLA's
cost model measures ~2 KB of HBM traffic per lane per round (key-derivation
chains, two uniforms, ndtri temporaries, CF arithmetic, all materialized as
f32 [T, N] tensors between kernels).  This kernel fuses the entire pipeline
—

    counter-based threefry2x32 bits -> uniforms -> AS241 normal quantile ->
    skew-corrected CF hypergeometric draws h0, h1 | h0 -> clamped counts

— into one VMEM-resident pass whose only HBM traffic is the three int32
[T, N] outputs (~12 B/lane), a ~100x traffic reduction on the op it
replaces (measured ~5x op speedup at [32 x 1M] on v5e; the
equivocate-regime variant ``equiv_counts_pallas`` fuses FOUR uniforms +
three CF draws + a binomial split and measures ~7x).  Enabled with
``SimConfig(use_pallas_hist=True)`` on the histogram path in the CF regime
(quorum m > EXACT_TABLE_MAX, i.e. exactly the N=1M operating point);
``bench.py`` measures the win on-chip.

Design notes:
  * RNG is a hand-rolled threefry2x32 on (node_id, trial_id) counters with
    a per-(seed, round, phase, stream) key — plain uint32 arithmetic, so
    the kernel runs bit-identically in interpreter mode on CPU (the pltpu
    PRNG primitives have no interpret-mode lowering) and its stream is
    independent of grid tiling by construction, keyed on the run's
    ``base_key`` (so distinct-key MC replications stay independent).  It is
    a DIFFERENT stream than the XLA path's chained ``jax.random.fold_in``
    derivation (ops/rng.py), so pallas-on vs pallas-off runs are
    statistically, not bitwise, identical — tests/test_pallas_hist.py
    KS-gates that.
  * ndtri is Wichura's AS241 PPND7 rational approximation (scalar
    coefficients only: jax.scipy.special.ndtri captures coefficient
    *arrays*, which pallas kernels cannot close over); |error| < 1e-6 in
    z, far below one count at any m this path serves.
  * The uniform uses the exponent-splice bitcast trick
    (bits >> 9 | 0x3F800000 -> f32 in [1, 2) - 1): Mosaic has no
    uint32 -> f32 cast.

Semantics mirrored from ops/sampling.py (multivariate_hypergeom_counts,
approx branch, skew_correct=True): the sampled counts follow the same
multivariate hypergeometric law over the global class histogram that models
the reference's "first N-F arrivals win" tally (node.ts:52,88).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from ..perfscope.instrument import instrumented_jit

#: Lane-tile width per grid step (multiple of the 128-lane VPU width).
TILE_N = 512


def _rotl(x: jax.Array, d: int) -> jax.Array:
    return (x << jnp.uint32(d)) | (x >> jnp.uint32(32 - d))


def _threefry2x32(k0, k1, x0, x1):
    """Standard Threefry-2x32-20 block cipher on uint32 arrays.

    k0/k1: uint32 key words (broadcastable); x0/x1: uint32 counter arrays.
    Returns the two output words.  Same algorithm family as jax's PRNG
    (Salmon et al. 2011), reimplemented so it lowers inside a pallas kernel
    (and in interpreter mode) with nothing but shifts/xors/adds.
    """
    ks2 = k0 ^ k1 ^ jnp.uint32(0x1BD11BDA)
    rot_a = (13, 15, 26, 6)
    rot_b = (17, 29, 16, 24)
    x0 = x0 + k0
    x1 = x1 + k1
    keys = (k0, k1, ks2)
    for group in range(5):
        rots = rot_a if group % 2 == 0 else rot_b
        for d in rots:
            x0 = x0 + x1
            x1 = _rotl(x1, d) ^ x0
        x0 = x0 + keys[(group + 1) % 3]
        x1 = x1 + keys[(group + 2) % 3] + jnp.uint32(group + 1)
    return x0, x1


def _lane_ids(scal_ref, shape):
    """GLOBAL (node, trial) uint32 counter grids for the current tile.

    x0 = global lane (node) id, x1 = global trial id — unique per lane,
    independent of the grid tiling AND of mesh sharding (under shard_map
    the shard's id offsets ride in scal_ref[2] / scal_ref[3]), so every
    stream built on these counters is bit-identical for every mesh shape.
    Shared by ALL kernels in this module — the paired-stream guarantee
    depends on a single counter scheme.
    """
    j = pl.program_id(0)
    n_trials, tile = shape
    node = (jax.lax.broadcasted_iota(jnp.uint32, (n_trials, tile), 1) +
            jnp.uint32(j * tile) + scal_ref[2])
    trial = (jax.lax.broadcasted_iota(jnp.uint32, (n_trials, tile), 0) +
             scal_ref[3])
    return node, trial


def _stream_scal(base_key: jax.Array, r: jax.Array, salt: int,
                 node_offset, trial_offset) -> jax.Array:
    """SMEM scalar vector [4] = (k0, k1, node_offset, trial_offset).

    The kernel key is one scalar threefry application OUTSIDE the kernel:
    key words = base_key data, counter words = (round, salt) — collision-
    free across rounds/streams.  uint32 up front: in-kernel scalar
    bitcasts are unsupported.  Shared by all kernels in this module.
    """
    kd = jax.random.key_data(base_key).astype(jnp.uint32).reshape(-1)
    k0, k1 = _threefry2x32(kd[0], kd[-1], r.astype(jnp.uint32),
                           jnp.uint32(salt))
    return jnp.stack([
        k0, k1,
        jnp.asarray(node_offset).astype(jnp.uint32),
        jnp.asarray(trial_offset).astype(jnp.uint32)])


def _bits_to_uniform(bits: jax.Array) -> jax.Array:
    """uint32 bits -> f32 uniform in (0, 1), Mosaic-safe (no int->float
    cast): splice the top 23 bits into a [1, 2) mantissa and subtract 1."""
    f = pltpu.bitcast((bits >> jnp.uint32(9)) | jnp.uint32(0x3F800000),
                      jnp.float32) - jnp.float32(1.0)
    return jnp.clip(f, 1e-7, 1.0 - 1e-7)


def _ndtri_as241(p: jax.Array) -> jax.Array:
    """Inverse normal CDF, Wichura AS241 PPND7 (single-precision grade).

    Scalar coefficients only — usable inside pallas.  |err| <~ 1e-6 over
    p in [1e-7, 1 - 1e-7], which is < 0.01 count at every m this kernel
    serves (sqrt(var) >> 100 in the CF regime).
    """
    q = p - 0.5
    r_c = 0.180625 - q * q
    num_c = ((((5.9109374720e+01 * r_c + 1.5929113202e+02) * r_c +
               5.0434271938e+01) * r_c + 3.3871327179e+00))
    den_c = ((((6.7187563600e+01 * r_c + 7.8757757664e+01) * r_c +
               1.7895169469e+01) * r_c + 1.0))
    central = q * num_c / den_c

    r_t = jnp.sqrt(-jnp.log(jnp.minimum(p, 1.0 - p)))
    r_m = r_t - 1.6
    num_m = ((((1.7023821103e-01 * r_m + 1.3067284816e+00) * r_m +
               2.7568153900e+00) * r_m + 1.4234372777e+00))
    den_m = (1.2021132975e-01 * r_m + 7.3700164250e-01) * r_m + 1.0
    r_f = r_t - 5.0
    num_f = ((((1.7337203997e-02 * r_f + 4.2868294337e-01) * r_f +
               3.0812263860e+00) * r_f + 6.6579051150e+00))
    den_f = (1.2258202635e-02 * r_f + 2.4197894225e-01) * r_f + 1.0
    tail = jnp.where(r_t <= 5.0, num_m / den_m, num_f / den_f)
    tail = jnp.where(q < 0.0, -tail, tail)

    return jnp.where(jnp.abs(q) <= 0.425, central, tail)


def _cf_draw(u, total, good, nsample):
    """Skew-corrected (Cornish-Fisher) hypergeometric quantile draw.

    Mirrors ops/sampling.py:hypergeom_normal_approx(skew_correct=True)
    exactly, modulo the ndtri implementation; all f32 elementwise.
    """
    t = jnp.maximum(total, 1.0)
    g = good
    n = nsample
    p = g / t
    mean = n * p
    fpc = jnp.where(t > 1.0, (t - n) / jnp.maximum(t - 1.0, 1.0), 0.0)
    var = jnp.maximum(n * p * (1.0 - p) * fpc, 0.0)
    z = _ndtri_as241(u)
    denom = jnp.sqrt(jnp.maximum(n * g * (t - g) * (t - n), 1.0)) * \
        jnp.maximum(t - 2.0, 1.0)
    skew = (t - 2.0 * g) * jnp.sqrt(jnp.maximum(t - 1.0, 0.0)) * \
        (t - 2.0 * n) / denom
    z = z + (z * z - 1.0) * skew / 6.0
    draw = jnp.round(mean + z * jnp.sqrt(var))
    lo = jnp.maximum(0.0, n - (t - g))
    hi = jnp.minimum(g, n)
    return jnp.clip(draw, lo, hi)


def _cf_kernel(m, scal_ref, c0_ref, c1_ref, cq_ref,
               h0_ref, h1_ref, hq_ref):
    """One lane-tile: fused uniforms + CF draws for all T trials.

    scal_ref: SMEM uint32 [4] = the (k0, k1) threefry key — derived per
    (base_key, round, phase) on the XLA side of the call — plus this
    shard's (node_offset, trial_offset) global-id bases (0 on a single
    device).  ONE threefry block per lane yields BOTH uniforms (the two
    output words of the 2x32 PRF are independent).
    c0/c1/cq_ref: VMEM f32 [T, 1] global class counts per trial.
    h0/h1/hq_ref: VMEM int32 [T, TILE_N] outputs (this tile's lanes).
    """
    node, trial = _lane_ids(scal_ref, h0_ref.shape)
    b0, b1 = _threefry2x32(scal_ref[0], scal_ref[1], node, trial)
    u0 = _bits_to_uniform(b0)
    u1 = _bits_to_uniform(b1)

    c0 = c0_ref[...]                                        # f32 [T, 1]
    c1 = c1_ref[...]
    cq = cq_ref[...]
    total = c0 + c1 + cq
    mf = jnp.float32(m)
    h0 = _cf_draw(u0, total, c0, mf)
    rem_total = jnp.maximum(total - c0, 0.0)
    rem_draw = jnp.maximum(mf - h0, 0.0)
    h1 = _cf_draw(u1, rem_total, c1, rem_draw)
    hq = jnp.maximum(mf - h0 - h1, 0.0)
    h0_ref[...] = h0.astype(jnp.int32)
    h1_ref[...] = h1.astype(jnp.int32)
    hq_ref[...] = hq.astype(jnp.int32)


def _coin_kernel(scal_ref, out_ref):
    """Private fair coin per lane: one threefry block, bit 0.

    scal_ref: SMEM uint32 [4] = (k0, k1, node_offset, trial_offset)."""
    node, trial = _lane_ids(scal_ref, out_ref.shape)
    bits, _ = _threefry2x32(scal_ref[0], scal_ref[1], node, trial)
    # int32 store: narrow int8 vector stores are a Mosaic constraint risk
    # (cf. the minor-dim-reshape rule); the cast to int8 happens outside
    out_ref[...] = (bits & jnp.uint32(1)).astype(jnp.int32)


#: Key-derivation counter word (the second threefry counter, the first is
#: the round index) for the coin stream.  Reserved words: cf_counts_pallas
#: uses its raw ``phase`` tag here (rng.PHASE_PROPOSAL=0 / PHASE_VOTE=1),
#: equiv_counts_pallas additionally uses phase+64 (64/65) for its second
#: uniform pair; the weak-coin kernel reuses _COIN_SALT (word 0 = the
#: private bit, word 1 = its deviation uniform); any new stream must pick
#: a word outside {0, 1, 64, 65, 255}.
_COIN_SALT = 255
_EQUIV_SALT_OFFSET = 64


@instrumented_jit(static_argnames=("trials", "n_nodes", "interpret"))
def coin_flips_pallas(base_key: jax.Array, r: jax.Array, trials: int,
                      n_nodes: int, interpret: bool = False,
                      node_offset: jax.Array | int = 0,
                      trial_offset: jax.Array | int = 0) -> jax.Array:
    """Private per-(trial, node, round) fair coins -> int8 [T, N].

    Drop-in statistical replacement for ops.rng.coin_flips(common=False)
    on the pallas-accelerated path: the XLA pipeline spends a chained
    fold_in (two threefry blocks + key materialization) per lane per
    round; this is ONE block per lane in VMEM.  Same global-id counter
    scheme as cf_counts_pallas, so results are bit-identical across mesh
    shapes.  (The common coin stays on the XLA path — it is one draw per
    trial, not a per-lane op.)
    """
    n_pad = (-n_nodes) % TILE_N
    np_total = n_nodes + n_pad
    scal = _stream_scal(base_key, r, _COIN_SALT, node_offset, trial_offset)
    out = pl.pallas_call(
        _coin_kernel,
        out_shape=jax.ShapeDtypeStruct((trials, np_total), jnp.int32),
        grid=(np_total // TILE_N,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((trials, TILE_N), lambda j: (0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(scal)
    return out[:, :n_nodes].astype(jnp.int8)


def _equiv_kernel(m, scal_ref, scal2_ref, c0_ref, c1_ref, cq_ref, ne_ref,
                  h0_ref, h1_ref, hq_ref):
    """Equivocate-regime lane-tile: the mixed-population sampler fused.

    Mirrors ops/sampling.py:equivocate_hypergeom_counts — h_b (delivered
    equivocators) ~ CF hypergeometric, honest split of the remainder, fair
    Binomial(h_b, 1/2) class split — four uniforms per lane from TWO
    threefry blocks (scal_ref carries the phase key, scal2_ref the
    phase+64 key; both use the shared global-id counter scheme).
    ne_ref: VMEM f32 [T, 1] live-equivocator count per trial.
    """
    node, trial = _lane_ids(scal_ref, h0_ref.shape)
    b0, b1 = _threefry2x32(scal_ref[0], scal_ref[1], node, trial)
    b2, b3 = _threefry2x32(scal2_ref[0], scal2_ref[1], node, trial)
    u0 = _bits_to_uniform(b0)
    u1 = _bits_to_uniform(b1)
    u_b = _bits_to_uniform(b2)
    u_s = _bits_to_uniform(b3)

    c0 = c0_ref[...]                                        # f32 [T, 1]
    c1 = c1_ref[...]
    cq = cq_ref[...]
    ne = ne_ref[...]
    total_h = c0 + c1 + cq
    total = total_h + ne
    mf = jnp.float32(m)
    h_b = _cf_draw(u_b, total, ne, mf)
    rem = jnp.maximum(mf - h_b, 0.0)
    h0 = _cf_draw(u0, total_h, c0, rem)
    h1 = _cf_draw(u1, jnp.maximum(total_h - c0, 0.0), c1,
                  jnp.maximum(rem - h0, 0.0))
    hq = jnp.maximum(rem - h0 - h1, 0.0)
    # Binomial(h_b, 1/2): symmetric, so the plain normal quantile is the
    # correct second-order approximation (sampling.binomial_half)
    z = _ndtri_as241(u_s)
    bs = jnp.clip(jnp.round(h_b * 0.5 + z * jnp.sqrt(h_b) * 0.5), 0.0, h_b)
    h0_ref[...] = (h0 + (h_b - bs)).astype(jnp.int32)
    h1_ref[...] = (h1 + bs).astype(jnp.int32)
    hq_ref[...] = hq.astype(jnp.int32)


def _weak_coin_kernel(eps, scal_ref, shared_ref, out_ref):
    """Weak-common coin lane-tile: private bit + deviation mask fused.

    ONE threefry block per lane serves both streams: word 0 is the private
    bit (the _COIN_SALT stream — bit-identical to _coin_kernel, which uses
    word 0 and discards word 1), word 1 the deviation uniform (the block's
    two output words are independent, cf. _cf_kernel).
    shared_ref: VMEM int32 [T, 1] — the round's shared coin per trial,
    drawn on the XLA side (one bit per trial is not kernel work).
    eps is a trace-time constant."""
    node, trial = _lane_ids(scal_ref, out_ref.shape)
    pbits, dbits = _threefry2x32(scal_ref[0], scal_ref[1], node, trial)
    private = (pbits & jnp.uint32(1)).astype(jnp.int32)
    dev = _bits_to_uniform(dbits) < jnp.float32(eps)
    out_ref[...] = jnp.where(dev, private, shared_ref[...])


@instrumented_jit(static_argnames=("trials", "n_nodes", "eps",
                                  "interpret"))
def weak_coin_flips_pallas(base_key: jax.Array, r: jax.Array, trials: int,
                           n_nodes: int, eps: float,
                           shared: jax.Array, interpret: bool = False,
                           node_offset: jax.Array | int = 0,
                           trial_offset: jax.Array | int = 0) -> jax.Array:
    """epsilon-weak common coins -> int8 [T, N] (pallas-stream family).

    Drop-in statistical replacement for ops.rng.weak_common_coin_flips on
    the kernel-accelerated path: the private component shares the
    private-coin kernel's exact stream, the deviation mask gets its own
    salt, and ``shared`` is the XLA-side per-trial common bit (int32 [T]).
    Global-id counters as everywhere: mesh-shape bit-identical."""
    n_pad = (-n_nodes) % TILE_N
    np_total = n_nodes + n_pad
    scal = _stream_scal(base_key, r, _COIN_SALT, node_offset, trial_offset)
    out = pl.pallas_call(
        functools.partial(_weak_coin_kernel, eps),
        out_shape=jax.ShapeDtypeStruct((trials, np_total), jnp.int32),
        grid=(np_total // TILE_N,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((trials, 1), lambda j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((trials, TILE_N), lambda j: (0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(scal, shared.astype(jnp.int32)[:, None])
    return out[:, :n_nodes].astype(jnp.int8)


@instrumented_jit(static_argnames=("m", "n_nodes", "interpret"))
def equiv_counts_pallas(base_key: jax.Array, r: jax.Array, phase: int,
                        hist: jax.Array, n_equiv: jax.Array, m: int,
                        n_nodes: int, interpret: bool = False,
                        node_offset: jax.Array | int = 0,
                        trial_offset: jax.Array | int = 0) -> jax.Array:
    """Fused equivocate-regime quorum sampler -> int32 [T, N, 3].

    Drop-in statistical replacement for
    ops.sampling.equivocate_hypergeom_counts driven by four grid_uniforms
    pipelines (fault_model='equivocate', uniform scheduler, CF regime) —
    same law, the kernel-family random stream.  Same contract as
    cf_counts_pallas (global-id counters, mesh-shape bit-identity, psum'd
    global ``hist``/``n_equiv``); KS-gated by tests/test_pallas_hist.py.
    """
    T = hist.shape[0]
    n_pad = (-n_nodes) % TILE_N
    np_total = n_nodes + n_pad

    scal = _stream_scal(base_key, r, phase, node_offset, trial_offset)
    scal2 = _stream_scal(base_key, r, phase + _EQUIV_SALT_OFFSET,
                         node_offset, trial_offset)

    cls = hist.astype(jnp.float32)[..., None]               # [T, 3, 1]
    c0, c1, cq = cls[:, 0], cls[:, 1], cls[:, 2]            # [T, 1] each
    ne = n_equiv.astype(jnp.float32)[:, None]               # [T, 1]

    out_shape = [jax.ShapeDtypeStruct((T, np_total), jnp.int32)] * 3
    vec_spec = pl.BlockSpec((T, 1), lambda j: (0, 0),
                            memory_space=pltpu.VMEM)
    h0, h1, hq = pl.pallas_call(
        functools.partial(_equiv_kernel, m),
        out_shape=out_shape,
        grid=(np_total // TILE_N,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            vec_spec, vec_spec, vec_spec, vec_spec,
        ],
        out_specs=[pl.BlockSpec((T, TILE_N), lambda j: (0, j),
                                memory_space=pltpu.VMEM)] * 3,
        interpret=interpret,
    )(scal, scal2, c0, c1, cq, ne)
    counts = jnp.stack([h0, h1, hq], axis=-1)               # [T, Np, 3]
    return counts[:, :n_nodes, :]


@instrumented_jit(static_argnames=("m", "n_nodes", "interpret"))
def cf_counts_pallas(base_key: jax.Array, r: jax.Array, phase: int,
                     hist: jax.Array, m: int, n_nodes: int,
                     interpret: bool = False,
                     node_offset: jax.Array | int = 0,
                     trial_offset: jax.Array | int = 0) -> jax.Array:
    """Fused histogram-path quorum sampler -> int32 [T, N, 3].

    base_key: a jax PRNG key — the SAME run key every runner threads
    through the round loop, so independent MC replications with distinct
    base keys get independent message-plane randomness (keying on cfg.seed
    would silently correlate them); r: int32 round index (traced — flows
    into the threefry key, not the trace); phase: static phase tag;
    hist: int32 [T, 3] global class counts; m: static quorum size.
    node_offset/trial_offset: this shard's global-id bases when called
    inside ``shard_map`` (hist must already be the psum'd GLOBAL
    histogram) — draws are keyed on global ids, so results are
    bit-identical across mesh shapes, single device included.

    Drop-in statistical replacement for
    ops.sampling.multivariate_hypergeom_counts in the CF regime
    (m > EXACT_TABLE_MAX) driven by ops.rng.grid_uniforms — same law,
    different (documented) random stream.
    """
    T = hist.shape[0]
    n_pad = (-n_nodes) % TILE_N
    np_total = n_nodes + n_pad

    # stream salt = the raw phase tag; inside the kernel one PRF block per
    # lane yields both uniforms (the XLA path's phase / phase+16 split
    # becomes the block's two output words)
    scal = _stream_scal(base_key, r, phase, node_offset, trial_offset)

    cls = hist.astype(jnp.float32)[..., None]               # [T, 3, 1]
    c0, c1, cq = cls[:, 0], cls[:, 1], cls[:, 2]            # [T, 1] each

    out_shape = [jax.ShapeDtypeStruct((T, np_total), jnp.int32)] * 3
    vec_spec = pl.BlockSpec((T, 1), lambda j: (0, 0),
                            memory_space=pltpu.VMEM)
    h0, h1, hq = pl.pallas_call(
        functools.partial(_cf_kernel, m),
        out_shape=out_shape,
        grid=(np_total // TILE_N,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            vec_spec, vec_spec, vec_spec,
        ],
        out_specs=[pl.BlockSpec((T, TILE_N), lambda j: (0, j),
                                memory_space=pltpu.VMEM)] * 3,
        interpret=interpret,
    )(scal, c0, c1, cq)
    counts = jnp.stack([h0, h1, hq], axis=-1)               # [T, Np, 3]
    return counts[:, :n_nodes, :]
