"""Vote/proposal tallies — the framework's hot op (SURVEY.md §3.3-3.4).

In the reference one protocol round is O(N^2) localhost HTTP POSTs, each
re-counting a JS array (node.ts:52-69, 88-98).  Here a round's entire message
plane is one of two data movements:

  dense:     [T, N_recv, N_send] delivery mask (x) one-hot sent values ->
             einsum on the MXU; exact, any scheduler; N <= ~10^4.
  histogram: O(N) global class histogram; 'all' delivery broadcasts it,
             'quorum' delivery draws per-lane multivariate-hypergeometric
             counts from it (ops/sampling.py); N up to 10^6+.

Both return per-receiver class counts int32 [T, N, 3] over {0, 1, "?"}.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import SimConfig, VAL0, VAL1, VALQ
from . import rng, sampling, scheduler
from .collectives import SINGLE, ShardCtx


def pallas_stream_active(cfg: SimConfig) -> bool:
    """The shared gating for every fused histogram-path kernel: the
    uniform-scheduler quorum-delivery CF regime.  Kept in ONE place so the
    sampler kernels — and the private-coin kernel, which must switch
    streams together with WHICHEVER sampler serves the config — can never
    diverge in when they engage."""
    return (cfg.use_pallas_hist and cfg.scheduler == "uniform"
            and cfg.delivery == "quorum"
            and cfg.resolved_path == "histogram"
            and cfg.quorum > sampling.EXACT_TABLE_MAX)


def pallas_requested(cfg: SimConfig) -> bool:
    """True iff the config ASKS for any fused kernel (hist or round) —
    regardless of whether its regime can serve one.  sim.run_consensus
    uses this to announce the structural demotion under a structured
    delivery plane (topology/committees require delivery='all', which
    every pallas gate below rejects), without the driver re-reading the
    kernel flags itself."""
    return cfg.use_pallas_hist or cfg.use_pallas_round


def pallas_hist_active(cfg: SimConfig) -> bool:
    """True iff the fused pallas sampler serves this config's histogram
    tallies."""
    return pallas_stream_active(cfg) and cfg.fault_model != "equivocate"


def pallas_equiv_active(cfg: SimConfig) -> bool:
    """True iff the fused equivocate-regime kernel serves this config's
    histogram tallies (the equivocate counterpart of pallas_hist_active —
    same CF-regime gating, different sampler kernel)."""
    return pallas_stream_active(cfg) and cfg.fault_model == "equivocate"


def pallas_round_active(cfg: SimConfig) -> bool:
    """True iff the fully-fused round kernels (ops/pallas_round.py) serve
    this config: ANY fault model (crash and crash_at_round feed the
    kernels a per-round killed mask; byzantine rides the vote-source flip
    sentinel; equivocate runs the mixed-population sampler in-kernel with
    honest-only histograms, r4 VERDICT task 6), a coin the kernel can
    produce in-VMEM (private / common / weak with 0 < eps < 1 — the weak
    endpoints short-circuit to plain streams on the XLA side, mirroring
    the unfused dispatch in models/benor.py), and a counts source the
    kernel implements:

      * the pallas-hist CF regime (uniform scheduler) — per-lane sampled
        tallies, drawn in-kernel (counts_mode='sampled');
      * the count-controlling adversaries — delivered counts are
        CLOSED-FORM per-trial (scheduler='adversarial') or per-camp
        (scheduler='targeted') scalars computed in XLA on [T, 3]-sized
        data; the kernels broadcast them per lane with no sampler at all
        (counts_mode='delivered' / 'camps').  No use_pallas_hist or
        CF-regime gate applies: there is nothing to sample.
    """
    if not cfg.use_pallas_round:
        return False
    if cfg.coin_mode == "weak_common":
        if not (0.0 < cfg.coin_eps < 1.0):
            return False
    elif cfg.coin_mode not in ("private", "common"):
        return False
    if pallas_stream_active(cfg):
        # pallas_hist_active | pallas_equiv_active partition
        # pallas_stream_active on fault_model, so the shared gate IS the
        # condition — stated directly so future regime edits live in one
        # place (the module comment's promise)
        return True
    return (cfg.scheduler in ("adversarial", "targeted")
            and cfg.delivery == "quorum")


def pallas_round_counts_mode(cfg: SimConfig) -> str:
    """Which counts source the fused round kernels run for this config —
    keep in sync with the dispatch in receiver_counts below."""
    if cfg.scheduler == "adversarial":
        return "delivered"
    if cfg.scheduler == "targeted":
        return "camps"
    return "sampled"


def dense_gather_needed(cfg: SimConfig) -> bool:
    """True iff receiver_counts will take the dense masked path (and thus
    gather sender arrays).  Callers use this to prefetch the round-constant
    ``alive`` gather once for both phases — keep in sync with the dispatch
    order in receiver_counts below.  The dense OMISSION path (PR 15:
    delivery='all' + drop_prob on resolved_path='dense' — the per-edge
    Bernoulli mask) gathers exactly like the quorum-delivery masks, so
    it rides the same prefetch."""
    if (cfg.delivery == "all" and cfg.drop_prob
            and cfg.resolved_path == "dense"):
        return True
    return (cfg.delivery == "quorum" and cfg.scheduler != "adversarial"
            and cfg.resolved_path == "dense")


def class_histogram(sent: jax.Array, alive: jax.Array,
                    ctx: ShardCtx = SINGLE) -> jax.Array:
    """Global per-trial class counts of live senders' values -> int32 [T, 3].

    Under a node-sharded mesh this is a local partial histogram + one psum
    over ICI — the entire replacement for the reference's O(N^2) HTTP
    message plane (SURVEY §5.8).
    """
    cnt = [jnp.sum((sent == v) & alive, axis=-1, dtype=jnp.int32)
           for v in (VAL0, VAL1, VALQ)]
    return ctx.psum_nodes(jnp.stack(cnt, axis=-1))


def dense_counts(mask: jax.Array, sent: jax.Array, alive: jax.Array) -> jax.Array:
    """Exact per-receiver counts from an explicit delivery mask.

    mask: bool [T, N_recv, N_send]; sent: int8 [T, N]; alive: bool [T, N].
    One [N, N] @ [N, 3] matmul per trial — MXU-shaped, fp32-exact for
    N < 2^24.
    """
    onehot = jnp.stack(
        [((sent == v) & alive).astype(jnp.float32) for v in (VAL0, VAL1, VALQ)],
        axis=-1)                                            # [T, N, 3]
    counts = jnp.einsum("trs,tsv->trv", mask.astype(jnp.float32), onehot)
    return counts.astype(jnp.int32)


def receiver_counts(cfg: SimConfig, base_key: jax.Array, r: jax.Array,
                    phase: int, sent: jax.Array, alive: jax.Array,
                    ctx: ShardCtx = SINGLE,
                    alive_g: jax.Array | None = None,
                    equiv: jax.Array | None = None,
                    equiv_g: jax.Array | None = None,
                    n_equiv: jax.Array | None = None,
                    dyn=None) -> jax.Array:
    """Dispatch: per-receiver tallied class counts int32 [T, N, 3].

    This is the TPU-native replacement for the whole HTTP message plane
    (SURVEY §5.8): which N-F multiset each receiver tallies, per
    (trial, receiver), deterministically seeded.  ``sent``/``alive`` are this
    shard's local [T_loc, N_loc] blocks; returned counts are per local
    receiver but tallied over the GLOBAL sender population.

    ``equiv`` (bool [T_loc, N_loc] or None) marks live equivocating senders
    (fault_model='equivocate'): their slot in ``sent`` is ignored — each
    (receiver, equivocator) edge carries an independent fair bit per phase
    (uniform/'all' delivery), or a value the count-controlling adversary
    chooses (scheduler='adversarial').  ``equiv_g`` (dense path) and
    ``n_equiv`` (its global count, [T]) are round-constant — callers hoist
    them like alive_g so the psum runs once per round, not per phase.

    ``dyn`` (state.DynParams or None): traced F/quorum for the batched
    dynamic-F sweep.  The quorum flows into the closed-form adversaries
    and the CF samplers as a traced scalar; branch DISPATCH stays keyed
    on the static ``cfg`` (every point sharing a compiled bucket agrees
    on it — sweep.quorum_specialized guarantees that).  Paths whose
    compiled shape specializes on the quorum (dense top-k masks, exact
    shared-CDF tables, pallas kernels) reject dyn.
    """
    T, N = sent.shape
    trial_ids = ctx.trial_ids(T)
    node_ids = ctx.node_ids(N)
    m = cfg.quorum if dyn is None else dyn.quorum

    # Adjacency-structured delivery (benor_tpu/topo): each receiver
    # tallies exactly its topology neighborhood (d graph neighbors +
    # itself) — one O(N*d) gather, never a dense N x N mask.  Requires
    # delivery='all' (config.py enforces it), so no scheduler below ever
    # composes with it; equivocators get per-edge fair bits inside the
    # gather, the dense path's exact semantics at sparse cost.
    if cfg.topology is not None:
        from ..topo.deliver import neighborhood_counts
        return neighborhood_counts(cfg, base_key, r, phase, sent, alive,
                                   ctx, equiv=equiv, alive_g=alive_g,
                                   equiv_g=equiv_g)

    honest = alive if equiv is None else (alive & ~equiv)
    if equiv is not None and n_equiv is None:
        n_equiv = ctx.psum_nodes(
            jnp.sum(equiv & alive, axis=-1, dtype=jnp.int32))    # [T]

    # 'all' delivery: every receiver's tally equals the global histogram —
    # O(T*N), no mask, identical on both paths.  With equivocators, every
    # receiver additionally tallies every live equivocator's edge bit:
    # a Binomial(n_equiv, 1/2) class split per receiver lane.
    # The faultlab planes (benor_tpu/faults, PR 15) modify THIS branch:
    # a partition epoch confines each receiver to its GROUP's histogram
    # ([T, G, 3] masked sums — O(N*G), never N x N), and drop_prob thins
    # the delivered counts — per-edge Bernoulli on the dense path (the
    # exact oracle, via scheduler.omission_delivery_mask) or a
    # closed-form per-class binomial thinning on the histogram path (so
    # N = 1M stays feasible).  drop_prob is a traced DynParams axis; all
    # gates are static, so injection off never traces any of this.
    if cfg.delivery == "all":
        drop_p = None
        if cfg.drop_prob:
            drop_p = jnp.float32(cfg.drop_prob) if dyn is None \
                else dyn.drop_prob
        part = None
        if cfg.partition is not None:
            from ..faults.partitions import parse_partition
            part = parse_partition(cfg.partition)
        if drop_p is not None and cfg.resolved_path == "dense":
            # exact per-edge omission: every (receiver, live sender)
            # edge — self included: the reference's self-broadcast is a
            # localhost fetch like any other (node.ts:72) — survives
            # with probability 1 - p, intersected with the partition
            # epoch's group mask; the dense einsum tallies survivors.
            # equivocate is rejected with drop_prob (config.py), so the
            # honest population is just the live one.
            sent_g = ctx.all_gather_nodes(sent)
            if alive_g is None:
                alive_g = ctx.all_gather_nodes(alive)
            mask = scheduler.omission_delivery_mask(
                cfg, base_key, r, phase, alive_g, drop_p, trial_ids,
                node_ids, part=part)
            return dense_counts(mask, sent_g, alive_g)
        if part is not None:
            counts = partition_counts(cfg, part, sent, honest, node_ids,
                                      r, ctx)
        else:
            hist = class_histogram(sent, honest, ctx)       # [T, 3]
            counts = jnp.broadcast_to(hist[:, None, :], (T, N, 3))
        if drop_p is not None:
            return omission_thin_counts(base_key, r, phase, counts,
                                        drop_p, trial_ids, node_ids)
        if equiv is not None:
            u = rng.grid_uniforms(base_key, r, phase + 32,
                                  trial_ids, node_ids)
            # n_equiv is trial-global, so the split is EXACT via a shared
            # CDF table whenever the static bound n_faulty is tabulable
            # (the normal approx is ~4% biased on extreme counts at small
            # F); above the bound the symmetric normal quantile is exact
            # to far below one count.
            if cfg.n_faulty <= sampling.EXACT_TABLE_MAX:
                b1 = sampling.binomial_half_exact_shared(
                    u, n_equiv, cfg.n_faulty)
            else:
                b1 = sampling.binomial_half(u, n_equiv[:, None])
            b0 = n_equiv[:, None] - b1
            zeros = jnp.zeros_like(b1)
            counts = counts + jnp.stack([b0, b1, zeros], axis=-1)
        return counts

    # Worst-case count-controlling adversary: identical on both paths
    # (scheduler semantics must not flip when path='auto' crosses
    # dense_path_max_n).  Equivocators become the adversary's free pool —
    # it chooses their per-receiver values outright (full Byzantine power).
    if cfg.scheduler == "adversarial":
        hist = class_histogram(sent, honest, ctx)
        counts = adversarial_counts(hist, m, n_free=n_equiv)
        return jnp.broadcast_to(counts[:, None, :], (T, N, 3))

    # Partitioned count-controlling adversary (agreement attack): closed
    # form on BOTH paths, like 'adversarial' above (scheduler semantics
    # must not flip when path='auto' crosses dense_path_max_n).  The
    # counts are realizable as an actual delivery schedule —
    # scheduler.realize_counts_mask builds the explicit per-edge mask and
    # tests/test_targeted.py pins dense_counts(mask) == this closed form.
    if cfg.scheduler == "targeted":
        hist = class_histogram(sent, honest, ctx)
        return targeted_counts(cfg, hist, node_ids, n_free=n_equiv, dyn=dyn)

    if cfg.resolved_path == "dense":
        if dyn is not None:
            raise ValueError(
                "dynamic-F tracing cannot drive the dense delivery mask "
                "(top-k specializes its shape on the quorum); bucket "
                "dense-path configs statically (sweep.quorum_specialized)")
        # Dense path on a node-sharded mesh: receivers stay local, the
        # sender axis is all-gathered. ``alive`` doesn't change within a
        # round, so callers gather it once and pass it for both phases.
        sent_g = ctx.all_gather_nodes(sent)                 # [T, N_glob]
        if alive_g is None:
            alive_g = ctx.all_gather_nodes(alive)
        if equiv is not None and equiv_g is None:
            equiv_g = ctx.all_gather_nodes(equiv)
        honest_g = alive_g if equiv_g is None else (alive_g & ~equiv_g)
        mask = scheduler.quorum_delivery_mask(cfg, base_key, r, phase,
                                              sent_g, alive_g,
                                              trial_ids, node_ids)
        if cfg.use_pallas:
            from .pallas_tally import dense_counts_pallas
            # compile for any accelerator (the axon TPU plugin reports
            # platform 'axon'); interpret only on plain CPU
            counts = dense_counts_pallas(
                mask, sent_g, honest_g,
                interpret=jax.default_backend() == "cpu")
        else:
            counts = dense_counts(mask, sent_g, honest_g)
        if equiv_g is not None:
            # per-edge fair bits for delivered equivocator messages (the
            # arrival race is content-independent, so the mask needs no
            # change — only the counted value does)
            bits = rng.edge_uniforms(base_key, r, phase + 32, trial_ids,
                                     node_ids,
                                     rng.ids(sent_g.shape[-1])) < 0.5
            deliv_b = mask & (equiv_g & alive_g)[:, None, :]
            c1b = jnp.sum(deliv_b & bits, axis=-1, dtype=jnp.int32)
            c0b = jnp.sum(deliv_b & ~bits, axis=-1, dtype=jnp.int32)
            zeros = jnp.zeros_like(c0b)
            counts = counts + jnp.stack([c0b, c1b, zeros], axis=-1)
        return counts

    # histogram path
    hist = class_histogram(sent, honest, ctx)
    if dyn is not None and pallas_stream_active(cfg):
        raise ValueError(
            "dynamic-F tracing cannot drive the fused pallas samplers "
            "(the quorum is baked into the kernel closures); bucket such "
            "configs statically (sweep.quorum_specialized)")
    if equiv is not None:
        if pallas_equiv_active(cfg):
            # fused mixed-population kernel (two threefry blocks -> four
            # uniforms -> CF draws + binomial split in one VMEM pass);
            # same global-id keying contract as cf_counts_pallas
            from .pallas_hist import equiv_counts_pallas
            return equiv_counts_pallas(
                base_key, r, phase, hist, n_equiv, cfg.quorum, N,
                interpret=jax.default_backend() == "cpu",
                node_offset=node_ids[0], trial_offset=trial_ids[0])
        # mixed-population sampler: hypergeometric # of delivered
        # equivocators, honest split of the rest, fair-bit class split
        u_b = rng.grid_uniforms(base_key, r, phase + 32, trial_ids, node_ids)
        u0 = rng.grid_uniforms(base_key, r, phase, trial_ids, node_ids)
        u1 = rng.grid_uniforms(base_key, r, phase + 16, trial_ids, node_ids)
        u_s = rng.grid_uniforms(base_key, r, phase + 48, trial_ids, node_ids)
        return sampling.equivocate_hypergeom_counts(
            u_b, u0, u1, u_s, hist, n_equiv, m)
    if pallas_hist_active(cfg):
        # Fused pallas sampler (the flagship-path kernel): bits + quantile +
        # CF draws in one VMEM pass.  Own stream keyed on base_key (NOT
        # cfg.seed — distinct-key MC replications must stay independent);
        # statistically identical to the grid_uniforms pipeline below,
        # KS-gated by tests/test_pallas_hist.py.  Under a mesh the draws
        # are keyed on this shard's GLOBAL (trial, node) id bases and the
        # psum'd global histogram, so results stay bit-identical across
        # mesh shapes (tests/test_pallas_hist.py::test_sharded_bit_identical).
        from .pallas_hist import cf_counts_pallas
        return cf_counts_pallas(
            base_key, r, phase, hist, cfg.quorum, N,
            interpret=jax.default_backend() == "cpu",
            node_offset=node_ids[0], trial_offset=trial_ids[0])
    u0 = rng.grid_uniforms(base_key, r, phase, trial_ids, node_ids)
    u1 = rng.grid_uniforms(base_key, r, phase + 16, trial_ids, node_ids)
    if cfg.scheduler == "biased":
        if cfg.adversary_strength >= 1.0:
            return biased_priority_counts(u0, hist, m, node_ids)
        if cfg.adversary_strength > 0.0:
            return biased_fractional_counts(
                cfg.adversary_strength, u0, u1, hist, m, node_ids)
        # strength 0: the dense scheduler adds no delay — plain uniform
    return sampling.multivariate_hypergeom_counts(u0, u1, hist, m)


def partition_counts(cfg: SimConfig, part, sent: jax.Array,
                     honest: jax.Array, node_ids: jax.Array, r: jax.Array,
                     ctx: ShardCtx = SINGLE) -> jax.Array:
    """Per-receiver counts under an epoch-structured partition
    (benor_tpu/faults/partitions.py) -> int32 [T, N_local, 3].

    During the epoch (r < heal_round) each receiver tallies its own
    GROUP's class histogram — [T, G, 3] masked sums over global senders
    (one psum under a mesh, like class_histogram), O(N * G) and never a
    dense N x N.  From the heal round on, the whole-network histogram
    (the sum over groups — free).  ``r`` is traced, so one executable
    serves both epochs via a where-select.
    """
    from ..faults.partitions import group_of

    T, n_loc = sent.shape
    G = part.groups
    grp = group_of(node_ids, cfg.n_nodes, G)                # [N_local]
    # one contraction, not a G-way Python unroll: sender-group one-hots
    # x class one-hots -> [T, G, 3] in O(1) traced ops (a large G would
    # otherwise balloon the HLO G-fold)
    g_oh = (grp[:, None] == jnp.arange(G)[None, :]).astype(jnp.int32)
    cls = jnp.stack([((sent == v) & honest).astype(jnp.int32)
                     for v in (VAL0, VAL1, VALQ)], axis=-1)  # [T, N, 3]
    ghist = ctx.psum_nodes(jnp.einsum("tnv,ng->tgv", cls, g_oh))
    whole = jnp.sum(ghist, axis=1)                          # [T, 3]
    per_recv = jnp.take(ghist, grp, axis=1)                 # [T, N_loc, 3]
    partitioned = jnp.asarray(r, jnp.int32) < part.heal_round
    return jnp.where(partitioned, per_recv,
                     jnp.broadcast_to(whole[:, None, :], per_recv.shape))


def omission_thin_counts(base_key: jax.Array, r: jax.Array, phase: int,
                         counts: jax.Array, drop_p: jax.Array,
                         trial_ids: jax.Array,
                         node_ids: jax.Array) -> jax.Array:
    """Per-edge iid omission as closed-form binomial thinning (the
    histogram path of ``SimConfig.drop_prob``) -> int32 [T, N, 3].

    Each delivered message survives independently with probability
    1 - p, so a receiver facing a class-v population of ``c_v`` tallies
    Binomial(c_v, 1 - p) of them — three independent draws per
    (trial, receiver, phase) from dedicated streams (salts phase + 8 /
    + 24 / + 40; disjoint from the sampler/bias/coin/equivocator salt
    families).  ``drop_p`` may be TRACED (the DynParams axis): the
    normal-quantile draw (sampling.binomial_keep) is shape-generic, so a
    whole drop_prob curve shares one bucket executable.  The dense path
    (scheduler.omission_delivery_mask) is the exact per-edge oracle this
    closed form is statistically checked against."""
    keep = 1.0 - jnp.asarray(drop_p, jnp.float32)
    cols = []
    for i, salt in enumerate((8, 24, 40)):
        u = rng.grid_uniforms(base_key, r, phase + salt, trial_ids,
                              node_ids)
        cols.append(sampling.binomial_keep(u, counts[..., i], keep))
    return jnp.stack(cols, axis=-1)


def biased_priority_counts(u0: jax.Array, hist: jax.Array,
                           m: int, node_ids: jax.Array) -> jax.Array:
    """Histogram-level biased scheduler at strength >= 1 (strict priority).

    The dense biased scheduler adds ``adversary_strength`` to the delays of
    edges carrying the value the receiver's parity class is starved of
    (ops/scheduler.py): even receivers' 1-carrying edges, odd receivers'
    0-carrying edges.  At strength >= 1 every favored delay (U[0,1]) sorts
    strictly before every starved delay (U[s, 1+s], s >= 1), so the tallied
    multiset is EXACTLY: all m from the favored classes if they suffice,
    else all favored plus a uniform without-replacement fill from the
    starved class.  Within the favored classes the selection is unbiased, so
    the class split is plain (exact/approx) hypergeometric — reusing
    ops/sampling.py.  KS-tested against the dense path.

    u0: float32 [T, N] per-lane uniforms (the starved fill is deterministic,
    so one draw suffices); hist: int32 [T, 3] global (c0, c1, cq);
    node_ids: global receiver ids [N] (parity decides the starved class).
    Returns int32 [T, N, 3] summing to m.
    """
    ms = sampling.static_m(m)      # None = traced quorum (CF regime only)
    c0, c1, cq = hist[:, 0:1], hist[:, 1:2], hist[:, 2:3]   # [T, 1]
    even = (node_ids % 2 == 0)[None, :]                     # [1, N]
    starved_c = jnp.where(even, c1, c0)                     # [T, N]
    fav_val = jnp.where(even, c0, c1)     # favored value-class count
    fav_total = fav_val + cq
    n_fav = jnp.minimum(fav_total, m)                       # favored taken
    # cap by the starved population: alive >= N-F guarantees the cap is
    # loose today, but a future fault model must not report phantom sends
    n_starved = jnp.minimum(m - n_fav, starved_c)           # starved fill
    # unbiased split of n_fav between the favored value-class and "?"
    h_favval = sampling.hypergeom_normal_approx(
        u0, fav_total, fav_val, n_fav,
        skew_correct=(ms is None or ms > sampling.EXACT_TABLE_MAX))
    # exact regime: replace the approx with the shared-table sampler when
    # parameters are trial-global (they are: fav_total/fav_val depend only
    # on (trial, parity)); two parity classes -> two exact tables.  A
    # traced m skips it — the [T, m+1] table shape needs a static m, and
    # the dynamic-F engine only routes CF-regime quorums here.
    if ms is not None and ms <= sampling.EXACT_TABLE_MAX:
        h_even = sampling.hypergeom_exact_shared(
            u0, (c0 + cq)[:, 0], c0[:, 0], m)   # capped below
        h_odd = sampling.hypergeom_exact_shared(
            u0, (c1 + cq)[:, 0], c1[:, 0], m)
        # the exact tables sample n=m draws; when fav_total < m the actual
        # draw count is fav_total — fall back to the per-lane approx there
        full_fav = fav_total >= m
        h_exact = jnp.where(even, h_even, h_odd)
        h_favval = jnp.where(full_fav, h_exact, h_favval)
    hq = n_fav - h_favval
    h0 = jnp.where(even, h_favval, n_starved)
    h1 = jnp.where(even, n_starved, h_favval)
    return jnp.stack([h0, h1, hq], axis=-1)


def biased_fractional_counts(s: float, u_race: jax.Array, u_split: jax.Array,
                             hist: jax.Array, m: int,
                             node_ids: jax.Array) -> jax.Array:
    """Histogram-level biased scheduler at fractional strength 0 < s < 1.

    Models the dense per-edge delay race (ops/scheduler.py: favored edges
    U[0,1), starved edges U[s, 1+s)) per (trial, receiver) lane with the
    exact two-population uniform-race sampler
    (sampling.uniform_race_favored_count): closed-form piecewise-linear
    mean-field threshold + delta-method fluctuation.

    Limits: s -> 0 recovers the uniform hypergeometric; s -> 1 recovers
    biased_priority_counts (strict priority).  The within-favored split
    (favored value vs "?") stays uniform — delays are iid across favored
    edges — so it is plain hypergeometric, like the strict path.
    MC-aggregate-tested against the dense path (tests/test_sampling.py).

    u_race/u_split: float32 [T, N] independent per-lane uniforms;
    hist: int32 [T, 3] global (c0, c1, cq); returns int32 [T, N, 3].
    """
    c0, c1, cq = hist[:, 0:1], hist[:, 1:2], hist[:, 2:3]   # [T, 1]
    even = (node_ids % 2 == 0)[None, :]                     # [1, N]
    fav_val = jnp.where(even, c0, c1)                       # [T, N]
    starved_c = jnp.where(even, c1, c0)
    n_fav = fav_val + cq
    j = sampling.uniform_race_favored_count(u_race, n_fav, starved_c, m, s)
    k_starved = jnp.minimum(m - j, starved_c)               # starved taken
    # unbiased split of j between the favored value-class and "?"
    h_favval = sampling.hypergeom_normal_approx(u_split, n_fav, fav_val, j)
    hq = j - h_favval
    h0 = jnp.where(even, h_favval, k_starved)
    h1 = jnp.where(even, k_starved, h_favval)
    return jnp.stack([h0, h1, hq], axis=-1)


def targeted_camp_sizes(cfg: SimConfig) -> tuple:
    """(size_per_value_camp, free_static): how many receivers the targeted
    adversary seeds per value camp.  A camp must muster count > F of its
    value at its own receivers; equivocators (free_static of them, each
    able to tell every receiver a different value) substitute for honest
    camp members one-for-one."""
    free_static = cfg.n_faulty if cfg.fault_model == "equivocate" else 0
    return max(cfg.n_faulty + 1 - free_static, 1), free_static


def targeted_camp_sizes_dyn(cfg: SimConfig, dyn) -> jax.Array:
    """Traced counterpart of ``targeted_camp_sizes``'s first element for
    the dynamic-F sweep: the per-value-camp receiver count as an int32
    scalar computed from ``dyn.n_faulty`` (same formula, jnp arithmetic —
    the adversary's camp layout moves with the traced F)."""
    free = dyn.n_faulty if cfg.fault_model == "equivocate" else jnp.int32(0)
    return jnp.maximum(dyn.n_faulty + 1 - free, 1)


def targeted_counts(cfg: SimConfig, hist: jax.Array, node_ids: jax.Array,
                    n_free: jax.Array | None = None,
                    dyn=None) -> jax.Array:
    """Partitioned count-controlling adversary: attack AGREEMENT directly.

    Where ``adversarial_counts`` ties every receiver identically (attacking
    termination), this adversary PARTITIONS the receivers — the true worst
    case of the "first N-F arrivals win" nondeterminism (node.ts:52,88),
    where nothing forces two receivers to tally the same multiset.  Three
    camps by global receiver id (sized by targeted_camp_sizes; the value
    camps sit at the top of the id range, clear of the first_f faulty
    convention):

      camp 0   (s ids)  max-0 multisets: h0 = min(c0 + free, m), then "?",
                        the 1-class last.  In phase 1 they adopt 0; in
                        phase 2 they see count0 > F and decide 0.
      camp 1   (s ids)  the mirror image -> decide 1.  The decide rule
                        checks count0 > F FIRST (node.ts:99), so this camp
                        only decides 1 if its 0-count stays <= F — which is
                        exactly what the manufactured "?" pool buys.
      camp "?" (rest)   max-"?" multisets, remainder split evenly: in
                        phase 1 (no "?" exist yet) that is a perfect tie,
                        so the camp adopts "?" (quirk 4's quorum-counts-"?"
                        is what lets these messages fill quorums); in
                        phase 2 their votes ARE the "?" pool that starves
                        camp 1's zero-count below the bar.

    The resulting thresholds (RESULTS 'safety_violation' study;
    tests/test_targeted.py):
      * crash-model, balanced inputs, even quorum N-F: agreement is
        violated for EVERY 1 <= F < N/2, and at F >= N/2 the decide bar
        m <= F makes decisions impossible (livelock) — the sharpest
        possible 0/1 threshold, pinned at the fault-tolerance boundary.
        (Odd quorums cannot manufacture perfect phase-1 ties, which
        weakens the attack to N <= 3F + 1 — a quirk-born parity effect.)
      * fault_model='equivocate': equivocators substitute for camp
        members AND can send "?", repairing quorum parity — ONE
        equivocator violates agreement at any N.  The reference's
        count > F decide rule has no Byzantine safety margin.
      * F = 0: m = N forces full delivery; the closed form degenerates to
        the global histogram at every receiver — the adversary is
        powerless, exactly like the reference with zero slack.

    hist: int32 [T, 3] global HONEST (c0, c1, cq); node_ids: global
    receiver ids [N_local] of this shard; ``n_free`` (int32 [T] or None) =
    live equivocators, whose per-receiver values the adversary aims at the
    receiver's camp (value camps: the camp value; "?" camp: "?").
    Returns int32 [T, N_local, 3] summing to m whenever the live
    population covers the quorum.  Realizable as an explicit delivery
    schedule: scheduler.realize_counts_mask + tests/test_targeted.py.
    """
    trip = targeted_camp_triples(cfg, hist, n_free=n_free,
                                 dyn=dyn)                   # [T, 3, 3]
    size_v = (targeted_camp_sizes(cfg)[0] if dyn is None
              else targeted_camp_sizes_dyn(cfg, dyn))
    camp1 = node_ids >= cfg.n_nodes - size_v                # [N]
    camp0 = (node_ids >= cfg.n_nodes - 2 * size_v) & ~camp1
    idx = jnp.where(camp1, 1, jnp.where(camp0, 0, 2))       # [N]
    return trip[:, idx, :]


def targeted_camp_triples(cfg: SimConfig, hist: jax.Array,
                          n_free: jax.Array | None = None,
                          dyn=None) -> jax.Array:
    """The targeted adversary's three camp multisets as per-TRIAL scalars:
    int32 [T, 3 camps, 3 classes], camps ordered (0-camp, 1-camp, "?"-camp).

    This is targeted_counts' entire closed form — the per-lane [T, N, 3]
    array is just a camp-id gather of these triples (targeted_counts
    above), and the fused round kernels select the triple in-VMEM by
    global lane id instead of ever materializing per-lane counts
    (ops/pallas_round.py counts_mode='camps').
    """
    m = cfg.quorum if dyn is None else dyn.quorum
    c0, c1, cq = hist[:, 0], hist[:, 1], hist[:, 2]         # [T]
    free = jnp.zeros_like(c0) if n_free is None else n_free

    # value camps: preferred class first (honest + all free), "?" second,
    # the starved class last.  free is exhausted whenever h_pref < m, so
    # no leftover-free case exists.
    def value_camp(want, other):
        pref = jnp.minimum(want + free, m)
        q = jnp.minimum(cq, m - pref)
        oth = jnp.minimum(other, m - pref - q)
        return pref, oth, q

    p0, o0, vq0 = value_camp(c0, c1)
    p1, o1, vq1 = value_camp(c1, c0)

    # "?" camp: every "?" available (honest + free-as-"?"), remainder
    # filled evenly from the value classes.  An even remainder is a
    # perfect tie -> the receiver adopts "?" (phase 1's manufacture step);
    # drop one "?" when that fixes the remainder's parity.
    q_q = jnp.minimum(cq + free, m)
    rem = m - q_q
    drop = ((rem % 2) == 1) & (q_q > 0)
    q_q = q_q - drop
    rem = rem + drop
    tie = rem // 2
    q0 = jnp.minimum(c0, tie)
    q1 = jnp.minimum(c1, tie)
    left = rem - q0 - q1
    e0 = jnp.clip(left, 0, c0 - q0)
    q0 = q0 + e0
    left = left - e0
    e1 = jnp.clip(left, 0, c1 - q1)
    q1 = q1 + e1
    # if the classes could not absorb the parity drop, restore it
    q_q = q_q + jnp.clip(left - e1, 0, drop.astype(jnp.int32))

    camp0 = jnp.stack([p0, o0, vq0], axis=-1)
    camp1 = jnp.stack([o1, p1, vq1], axis=-1)
    campq = jnp.stack([q0, q1, q_q], axis=-1)
    return jnp.stack([camp0, camp1, campq], axis=1)


def adversarial_counts(hist: jax.Array, m: int,
                       n_free: jax.Array | None = None) -> jax.Array:
    """Worst-case count-controlling scheduler: force per-receiver ties.

    The strongest asynchronous adversary doesn't merely *delay* messages —
    it picks, for every receiver, the N-F multiset whose 0/1 counts tie, so
    phase-1 tallies yield "?" and phase-2 never accumulates > F votes for any
    value; undecided nodes fall through to their coins every round.  (A
    shared common coin defeats exactly this adversary in O(1) expected
    rounds — the classic Ben-Or vs Rabin contrast, reproducible with
    ``coin_mode='common'``.)

    ``n_free`` (int32 [T] or None) is the adversary's FREE-VALUE pool:
    live equivocators (fault_model='equivocate') whose delivered value —
    0, 1 or "?" — the adversary chooses per receiver outright.  The
    tie-optimal allocation tops both value classes up toward a common
    level T* = min(m//2, (h0 + h1 + free) // 2); with it the framework
    reproduces the classic N > 3F Byzantine resilience bound: for
    F >= N/3 the adversary ties every tally forever (even against the
    common coin — matching the impossibility), for F < N/3 a unified
    honest class count m - F > F is forced through and decides
    (tests/test_equivocate.py).

    hist: int32 [T, 3] global HONEST (c0, c1, cq); returns int32 [T, 3]
    delivered counts summing to m, balance-first, identical per receiver.
    """
    c0, c1, cq = hist[:, 0], hist[:, 1], hist[:, 2]
    tgt = m // 2
    h0h = jnp.minimum(c0, tgt)            # honest contributions to the tie
    h1h = jnp.minimum(c1, tgt)
    if n_free is not None:
        # water-fill the free pool: lift both classes toward the common
        # level T* (capped by the tie target), leftovers masquerade as "?"
        lvl = jnp.minimum(tgt, (h0h + h1h + n_free) // 2)
        b0 = jnp.clip(lvl - h0h, 0, n_free)
        b1 = jnp.clip(lvl - h1h, 0, n_free - b0)
        cq = cq + (n_free - b0 - b1)
    else:
        b0 = b1 = 0
    h0 = h0h + b0
    h1 = h1h + b1
    hq = jnp.minimum(cq, m - h0 - h1)
    rem = m - h0 - h1 - hq                # forced imbalance, if any
    extra0 = jnp.minimum(rem, c0 - h0h)
    h0, rem = h0 + extra0, rem - extra0
    extra1 = jnp.minimum(rem, c1 - h1h)
    h1 = h1 + extra1
    return jnp.stack([h0, h1, hq], axis=-1)
