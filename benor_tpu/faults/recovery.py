"""Crash-recovery schedules: the ``fault_model='crash_recover'`` plane.

The static fault models ('crash' / 'crash_at_round') only ever SUBTRACT
nodes: a lane that dies stays dead, so every run's live population is
monotone non-increasing and the quorum gate can only stall harder over
time.  Real deployments churn — nodes crash, reboot and REJOIN, with or
without their volatile state ("Simulating BFT Protocol Implementations
at Scale" makes exactly this scenario breadth the point of simulating at
scale).  ``crash_recover`` adds per-node DOWN-INTERVALS:

  * lane i is down for rounds ``crash_round[i] <= r < recover_round[i]``
    (``recover_round <= 0`` means it never rejoins — exactly
    'crash_at_round' semantics, and the lane latches ``killed``);
  * while down the lane neither sends nor tallies: its (x, decided, k)
    freeze, it drops out of the alive count (churn below the quorum
    stalls the whole trial's round, like the reference's receivers
    waiting for fetches that never come), and the auditor's
    ``down_silence`` invariant (benor_tpu/audit.py) machine-checks that
    no decide or coin commit is ever witnessed inside the interval;
  * at ``r == recover_round`` the lane is back: under the ``durable``
    rejoin mode it resumes with the x it crashed with (stable storage);
    under ``amnesia`` an UNDECIDED rejoiner forgets its volatile value
    and restarts from "?" — decisions are always durable (written before
    the decide is announced), so irrevocability holds ACROSS recovery
    and the auditor keeps checking it.

The schedule is a SPEC STRING (``SimConfig.recovery``) so every entry
path — sweep.default_crash_faults, the serve plane's job documents, the
CLI — derives the identical FaultSpec from the config alone:

    at:<crash>:<down>[:amnesia|durable]
        every faulty lane crashes at round <crash> and rejoins <down>
        rounds later (<down> = 0: never — the crash_at_round limit).
    stagger:<crash>:<down>[:amnesia|durable]
        rolling churn: the j-th faulty lane (j = 0..F-1 in id order)
        crashes at round <crash> + j and rejoins <down> rounds later —
        at any instant ~min(down, F) lanes are down, a moving hole in
        the quorum.

Parsing is stdlib-only (like topo/graphs.py) so jax-free tools can
re-derive schedules; the FaultSpec builder imports jax lazily.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

#: The two rejoin modes.  'durable': state survives the crash; 'amnesia':
#: the volatile x restarts at "?" (decisions are durable either way).
REJOIN_MODES = ("durable", "amnesia")


@dataclasses.dataclass(frozen=True)
class RecoverySpec:
    """One parsed recovery schedule."""

    kind: str       # 'at' | 'stagger'
    crash: int      # first crash round (1-based, like message k)
    down: int       # rounds down before rejoin; 0 = never rejoins
    rejoin: str     # 'durable' | 'amnesia'
    spec: str       # the original spec string (bucket keys, reports)

    def validate(self) -> None:
        if self.crash < 1:
            raise ValueError(
                f"recovery spec {self.spec!r}: crash round must be >= 1 "
                "(round indices are 1-based, like the message k)")
        if self.down < 0:
            raise ValueError(
                f"recovery spec {self.spec!r}: down length must be >= 0 "
                "(0 = the lane never rejoins)")

    def rounds(self, n_faulty: int) -> Tuple[list, list]:
        """(crash_rounds, recover_rounds) for the F faulty lanes in id
        order — plain ints, the schedule every harness realizes."""
        if self.kind == "at":
            crash = [self.crash] * n_faulty
        else:                                   # stagger
            crash = [self.crash + j for j in range(n_faulty)]
        recover = [(c + self.down) if self.down > 0 else 0 for c in crash]
        return crash, recover


def parse_recovery(spec: Optional[str]) -> Optional[RecoverySpec]:
    """Spec string -> RecoverySpec; None passes through (no schedule).

    Raises ValueError on malformed specs — the same fail-loudly contract
    as topo/graphs.parse_topology, so SimConfig validation (and the serve
    plane's structured 400s) surface the grammar error verbatim.
    """
    if spec is None:
        return None
    parts = str(spec).split(":")
    kind = parts[0]
    if kind not in ("at", "stagger"):
        raise ValueError(
            f"unknown recovery spec {spec!r}: grammar is "
            "'at:<crash>:<down>[:amnesia|durable]' or "
            "'stagger:<crash>:<down>[:amnesia|durable]'")
    rejoin = "durable"
    body = parts[1:]
    if body and body[-1] in REJOIN_MODES:
        rejoin = body[-1]
        body = body[:-1]
    if len(body) != 2:
        raise ValueError(
            f"recovery spec {spec!r}: expected "
            f"'{kind}:<crash>:<down>[:amnesia|durable]'")
    try:
        crash, down = int(body[0]), int(body[1])
    except ValueError:
        raise ValueError(
            f"recovery spec {spec!r}: <crash> and <down> must be "
            "integers") from None
    out = RecoverySpec(kind=kind, crash=crash, down=down, rejoin=rejoin,
                       spec=str(spec))
    out.validate()
    return out


def rejoin_mode(spec: Optional[str]) -> str:
    """The (static) rejoin mode a config's recovery spec declares —
    'durable' when no spec is set.  The one switch the compiled regimes
    (models/benor.py, ops/pallas_round.py) key the amnesia reset on."""
    parsed = parse_recovery(spec)
    return parsed.rejoin if parsed is not None else "durable"


def crash_recover_faults(cfg):
    """The default fault policy for ``fault_model='crash_recover'``: the
    first F lanes faulty (the canonical mask — lanes are exchangeable
    under the uniform scheduler), with down-intervals realized from
    ``cfg.recovery``.  The single policy sweep.default_crash_faults and
    the serve plane's job inputs share, so "same SimConfig" means the
    same churn schedule on every entry path."""
    import jax.numpy as jnp
    import numpy as np

    from ..state import FaultSpec

    spec = parse_recovery(cfg.recovery)
    if spec is None:
        raise ValueError(
            "fault_model='crash_recover' needs a recovery schedule: set "
            "SimConfig(recovery='at:<crash>:<down>[:amnesia|durable]') "
            "or pass an explicit FaultSpec with recover_round")
    f = cfg.n_faulty
    mask = np.zeros(cfg.n_nodes, bool)
    mask[:f] = True
    crash, recover = spec.rounds(f)
    cr = np.zeros(cfg.n_nodes, np.int32)
    rr = np.zeros(cfg.n_nodes, np.int32)
    cr[:f] = crash
    rr[:f] = recover
    shape = (cfg.trials, cfg.n_nodes)
    return FaultSpec(
        faulty=jnp.broadcast_to(jnp.asarray(mask), shape),
        crash_round=jnp.broadcast_to(jnp.asarray(cr), shape),
        recover_round=jnp.broadcast_to(jnp.asarray(rr), shape))
