"""The faultlab science rows: rounds-to-decide vs drop_prob / churn.

Ben-Or's headline claim is probabilistic termination UNDER ADVERSITY;
these curves stress it along the two dynamic-fault axes PR 15 adds:

  * ``drop_curve`` — rounds-to-decide vs per-edge omission probability.
    ``drop_prob`` is a traced DynParams axis, so the WHOLE curve
    compiles as ONE bucket executable through sweep.run_points_batched
    (the coalescing proof bench's ``faults`` blob pins via the returned
    compile count): as p grows, receivers clear the N - F bar less
    often, stall more rounds, and mean rounds-to-decide climbs until
    the round cap truncates it.
  * ``churn_curve`` — rounds-to-decide vs crash-recovery churn: a
    ``stagger:<crash>:<down>`` schedule per point with growing down
    length.  The recovery spec is STATIC config (it shapes the fault
    masks), so each point is its own bucket — the engine still batches
    the list in one call and the per-point oracle bit-equality holds.

Both run the batched engine end to end, so journal/heartbeat/sweepscope
all apply unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from ..config import SimConfig


def drop_curve(base: SimConfig, drop_probs: Sequence[float],
               verbose: bool = False) -> Tuple[List[Dict], object]:
    """Rounds-to-decide vs omission probability -> (json rows, the
    BatchedCurve).  Every point must arm the omission plane
    (drop_prob > 0): p = 0 is the injection-off config, which buckets
    separately by design (the off path must stay bit-identical to the
    pre-faultlab executable) — callers wanting the baseline run it as
    its own point.

    Runs with ZERO crashes (FaultSpec.none — the coin_comparison
    pattern): crash-from-birth faults pin the live population to the
    quorum N - F exactly, so ANY drop would stall every receiver and
    the curve would measure the stall cliff, not omission.  With all N
    alive the quorum slack F absorbs the thinning, and the delivered
    count crosses the bar at the sharp threshold p ~ F/N."""
    from ..state import FaultSpec
    from ..sweep import run_points_batched

    ps = [float(p) for p in drop_probs]
    if any(p <= 0.0 for p in ps):
        raise ValueError(
            "drop_curve sweeps the ARMED omission plane (drop_prob > 0); "
            "p = 0 is the injection-off config and buckets separately — "
            "run it as its own baseline point")
    cfgs = [base.replace(drop_prob=p) for p in ps]
    T, N = base.trials, base.n_nodes
    cb = run_points_batched(base.replace(drop_prob=ps[0]), cfgs,
                            faults_for=lambda c: FaultSpec.none(T, N),
                            verbose=verbose)
    rows = [{"drop_prob": p, "n_nodes": pt.n_nodes,
             "n_faulty": pt.n_faulty, "trials": pt.trials,
             "mean_k": pt.mean_k, "decided_frac": pt.decided_frac,
             "rounds_executed": pt.rounds_executed}
            for p, pt in zip(ps, cb.points)]
    return rows, cb


def churn_curve(base: SimConfig, down_lengths: Sequence[int],
                crash_round: int = 2,
                verbose: bool = False) -> Tuple[List[Dict], object]:
    """Rounds-to-decide vs churn severity -> (json rows, BatchedCurve).

    Each point runs ``fault_model='crash_recover'`` under a rolling
    ``stagger:<crash_round>:<down>`` schedule; the down length is the
    severity axis (0 rounds down = the static crash_at_round limit is
    EXCLUDED — it never rejoins and measures a different plane)."""
    from ..sweep import run_points_batched

    downs = [int(d) for d in down_lengths]
    if any(d < 1 for d in downs):
        raise ValueError("churn_curve needs down lengths >= 1 (a lane "
                         "that never rejoins is crash_at_round, not "
                         "churn)")
    cfgs = [base.replace(fault_model="crash_recover",
                         recovery=f"stagger:{int(crash_round)}:{d}")
            for d in downs]
    cb = run_points_batched(cfgs[0], cfgs, verbose=verbose)
    rows = [{"down_rounds": d, "recovery": c.recovery,
             "n_nodes": pt.n_nodes, "n_faulty": pt.n_faulty,
             "trials": pt.trials, "mean_k": pt.mean_k,
             "decided_frac": pt.decided_frac,
             "rounds_executed": pt.rounds_executed}
            for d, c, pt in zip(downs, cfgs, cb.points)]
    return rows, cb
