"""Epoch-structured network partitions: the ``SimConfig.partition`` plane.

A partition splits the node id range into G contiguous GROUPS; until the
spec's ``heal_round`` every message crossing a group boundary is lost
(both phases, deterministically), and from ``heal_round`` on the network
is whole again — the classic "partition, then heal" scenario the static
fault models cannot express.  Delivery semantics compose with the rest
of the delivery plane:

  * complete graph (``delivery='all'``): each receiver tallies its own
    GROUP's class histogram during the epoch — [T, G, 3] masked sums, so
    the cost is O(N * G) and never a dense N x N anything (the same
    shape discipline as benor_tpu/topo);
  * adjacency topology (``cfg.topology``): cross-group NEIGHBOR edges go
    silent during the epoch (topo/deliver.py masks the gather), so a
    ring spanning two groups loses exactly its two boundary edges;
  * message omission (``cfg.drop_prob``): the thinning applies to the
    group-confined counts — partitions bound WHO can arrive, omission
    thins HOW MANY do.

A receiver whose group cannot muster the quorum N - F stalls (its state
freezes for the round — the per-lane quorum gate in models/benor.py), so
``partition='halves:h'`` with F < N/2 is a clean liveness attack: every
lane stalls until the heal, then the run converges — rounds-to-decide
shifts by exactly the epoch length.  The auditor learns the matching
invariant: during the epoch no witnessed tally may exceed the receiver's
GROUP size (benor_tpu/audit.py quorum_evidence, the partition-epoch
bound).

Spec grammar (stdlib-importable, like topo/graphs.py, so jax-free tools
— tools/check_metrics_schema.py — re-derive group geometry):

    halves:<heal_round>        two contiguous halves, heal at <heal_round>
    groups:<g>:<heal_round>    g contiguous groups, heal at <heal_round>

``heal_round`` is 1-based like the message k: rounds r < heal_round run
partitioned, rounds r >= heal_round run whole.  Group of node i is
``i * g // n`` — closed-form id arithmetic that works on ints, numpy and
traced jnp arrays alike.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class PartitionSpec:
    """One parsed partition: G contiguous groups until ``heal_round``."""

    groups: int      # number of contiguous groups (>= 2)
    heal_round: int  # first WHOLE round (1-based); rounds before it split
    spec: str        # the original spec string (bucket keys, reports)

    def validate(self, n_nodes: int) -> None:
        if self.groups < 2:
            raise ValueError(
                f"partition spec {self.spec!r}: needs >= 2 groups "
                "(1 group is the whole network — drop the spec instead)")
        if self.groups > n_nodes:
            raise ValueError(
                f"partition spec {self.spec!r}: {self.groups} groups "
                f"cannot all be non-empty at n_nodes={n_nodes}")
        if self.heal_round < 1:
            raise ValueError(
                f"partition spec {self.spec!r}: heal_round must be >= 1 "
                "(round indices are 1-based; heal_round=1 never "
                "partitions anything — drop the spec instead)")

    def group_sizes(self, n_nodes: int) -> List[int]:
        """Per-group node counts under the contiguous ``i * g // n``
        assignment — the audit bound's denominators."""
        g = self.groups
        bounds = [_ceil_div(k * n_nodes, g) for k in range(g + 1)]
        return [bounds[k + 1] - bounds[k] for k in range(g)]


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def parse_partition(spec: Optional[str]) -> Optional[PartitionSpec]:
    """Spec string -> PartitionSpec; None passes through (no partition).

    Raises ValueError on malformed specs (the fail-loudly contract
    SimConfig validation and the serve plane's structured 400s rely on).
    """
    if spec is None:
        return None
    parts = str(spec).split(":")
    kind = parts[0]
    if kind == "halves":
        if len(parts) != 2:
            raise ValueError(
                f"partition spec {spec!r}: expected 'halves:<heal_round>'")
        groups, heal = 2, parts[1]
    elif kind == "groups":
        if len(parts) != 3:
            raise ValueError(
                f"partition spec {spec!r}: expected "
                "'groups:<g>:<heal_round>'")
        groups, heal = parts[1], parts[2]
    else:
        raise ValueError(
            f"unknown partition spec {spec!r}: grammar is "
            "'halves:<heal_round>' or 'groups:<g>:<heal_round>'")
    try:
        groups, heal = int(groups), int(heal)
    except ValueError:
        raise ValueError(
            f"partition spec {spec!r}: <g> and <heal_round> must be "
            "integers") from None
    out = PartitionSpec(groups=groups, heal_round=heal, spec=str(spec))
    if out.groups < 2 or out.heal_round < 1:
        out.validate(n_nodes=out.groups)     # raise the specific message
    return out


def group_of(node_ids, n_nodes: int, groups: int):
    """Group index of each node id under the contiguous assignment —
    ``i * g // n``.  Pure arithmetic: works on Python ints, numpy arrays
    and traced jnp arrays (global ids under a mesh), so the same closed
    form serves the compiled delivery plane and the host-side auditor."""
    return node_ids * groups // n_nodes


def group_size_of(node_id: int, n_nodes: int, spec: PartitionSpec) -> int:
    """Size of the group holding ``node_id`` — the audit-time ceiling on
    any tally witnessed inside the partition epoch."""
    return spec.group_sizes(n_nodes)[int(group_of(node_id, n_nodes,
                                                  spec.groups))]
