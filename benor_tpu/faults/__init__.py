"""faultlab: the dynamic fault-injection plane (PR 15).

Three fault families the static models ('crash' / 'crash_at_round' /
'byzantine' / 'equivocate') cannot express, each a first-class,
sweepable, AUDITED axis of every compiled regime:

  * crash-recovery churn — ``SimConfig(fault_model='crash_recover',
    recovery='stagger:2:3:amnesia')``: per-node down-intervals with
    durable-vs-amnesia rejoin (``recovery.py``; the packed pallas path
    re-derives liveness from the round bounds in-kernel);
  * per-edge message omission — ``SimConfig(drop_prob=p)``: iid drops
    folded into the dense delivery mask / binomial-thinned counts on
    the histogram path, with ``drop_prob`` a traced DynParams axis so a
    whole rounds-vs-p curve is ONE bucket executable (``curves.py``);
  * healing partitions — ``SimConfig(partition='halves:<heal_round>')``:
    epoch-structured group masks composing with topology adjacency,
    never a dense N x N (``partitions.py``).

Injection off is bit-identical in results AND compile counts across all
five regimes (the house rule, pinned by tests/test_faults.py), and
benor_tpu/audit.py machine-checks the matching invariants (down-interval
silence, irrevocability across recovery, partition-epoch tally bounds).
"""

from .partitions import (PartitionSpec, group_of, group_size_of,
                         parse_partition)
from .recovery import (REJOIN_MODES, RecoverySpec, crash_recover_faults,
                       parse_recovery, rejoin_mode)

__all__ = ["PartitionSpec", "group_of", "group_size_of",
           "parse_partition", "REJOIN_MODES", "RecoverySpec",
           "crash_recover_faults", "parse_recovery", "rejoin_mode"]
