"""The faultlab proof document: bench.py's ``faults`` sidecar blob.

One pinned-schema ``kind: faults_manifest`` dict assembling the three
facts the ``faults_ok`` headline rests on — injection-off bit-identity
(results AND compile counts: the off config IS the pre-faultlab config,
so the jit cache must simply hit), the one-bucket omission-curve
coalescing claim (drop_prob rides DynParams), and clean audits across
the new fault families (down_silence + the partition-epoch quorum
bound, benor_tpu/audit.py).  ``tools/check_metrics_schema.py``
registers ``check_faults_manifest`` for this kind in its
MANIFEST_CHECKERS dispatch — the PR 13 manifest-kind-parity lint
(analysis/rules_manifest.py) fails the build if this emission ever
loses its checker — and recomputes the stall threshold, curve ordering
and the ok verdict from the parts.
"""

from __future__ import annotations

from typing import Dict

#: The manifest kind (MANIFEST_CHECKERS key; the manifest-kind-parity
#: lint re-parses this constant).
FAULTS_KIND = "faults_manifest"


def faults_manifest(identity: Dict, curves: Dict, audits: Dict) -> Dict:
    """Assemble the blob from its measured parts.

    ``identity``: {'bit_equal': bool, 'extra_compiles': int} — the
    injection-off rerun vs the plain config; ``curves``: the
    results.faults_curves dict (drop/churn rows + compile counts);
    ``audits``: label -> {'ok', 'checks', 'violations'} per audited
    fault family.  ``ok`` is derived here and re-derived by the checker,
    so a hand-edited verdict cannot survive.
    """
    ok = (bool(identity.get("bit_equal"))
          and identity.get("extra_compiles") == 0
          and len(curves.get("drop_curve", [])) > 0
          and len(curves.get("churn_curve", [])) > 0
          and curves.get("drop_compile_count") == 1
          and all(bool(a.get("ok")) for a in audits.values())
          and len(audits) > 0)
    return {
        "kind": FAULTS_KIND,
        "ok": bool(ok),
        "off_identity": dict(identity),
        **{k: curves[k] for k in ("drop_curve", "drop_compile_count",
                                  "drop_buckets", "churn_curve",
                                  "churn_compile_count")},
        "audits": {k: dict(v) for k, v in audits.items()},
    }
