"""Kernel column-layout rules: prove the declarative layout tables are
internally disjoint, agree across files, and size every out_spec.

This family owns the exact bug surface PR 2 (flight recorder) and PR 3
(witness traces) managed by hand: the fused pallas round emits telemetry
and witness data as EXTRA COLUMNS of a [tiles, T, PARTIAL_COLS] per-tile
partial buffer, and nothing at runtime notices two features landing on
the same column — the numbers are merely silently wrong in one regime.
The tables these rules parse (state.REC_LAYOUT / WIT_LAYOUT,
ops/pallas_round.PROP_PARTIAL_LAYOUT / VOTE_PARTIAL_LAYOUT /
VOTE_RECORD_LAYOUT / WITNESS_*_FIELDS) are the same literals the kernels
derive their indices from, so a layout the checker accepts is the layout
the kernels ship.

Tables are read by PARSING the source (core.literal_assign) — never by
importing it — so the rules also run over fixture trees in tests and
force the tables to stay machine-readable pure literals.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from .core import (Finding, Project, assign_line, dotted_name,
                   literal_assign, rule)

#: Where each table lives, package-root-relative.
STATE_FILE = "state.py"
KERNEL_FILE = "ops/pallas_round.py"
CONFIG_FILE = "config.py"

_STATE_TABLES = ("REC_LAYOUT", "WIT_LAYOUT")
_KERNEL_TABLES = ("PROP_PARTIAL_LAYOUT", "VOTE_PARTIAL_LAYOUT",
                  "VOTE_RECORD_LAYOUT")


def _table(project: Project, rel: str, name: str,
           rule_name: str = "layout-overlap"
           ) -> Tuple[Optional[dict], int, List[Finding]]:
    """(table, line, findings): parse one layout table; a missing or
    non-literal table is itself a finding (deleting the table must not
    silently disable the checker)."""
    src = project.source(rel)
    if src is None:
        return None, 1, []          # file outside this lint root
    table = literal_assign(src, name)
    line = assign_line(src, name)
    if table is None:
        return None, line, [Finding(
            rule_name, rel, line, 0,
            f"machine-readable layout table {name} is missing (or no "
            f"longer a pure literal) — the kernels and the layout "
            f"checker both consume it",
            hint=f"declare {name} as a literal name -> (base, width) "
                 f"dict at module level")]
    if not isinstance(table, dict) or not all(
            isinstance(v, tuple) and len(v) == 2 and
            all(isinstance(x, int) for x in v) for v in table.values()):
        return None, line, [Finding(
            rule_name, rel, line, 0,
            f"layout table {name} must map name -> (base, width) int "
            f"pairs",
            hint="see state.REC_LAYOUT for the shape")]
    return table, line, []


def _by_base(table: dict) -> List[Tuple[str, int, int]]:
    return sorted(((n, b, w) for n, (b, w) in table.items()),
                  key=lambda t: t[1])


def _check_ranges(rel: str, line: int, label: str, entries,
                  start: int,
                  rule_name: str = "layout-overlap") -> List[Finding]:
    """Disjoint + contiguous from ``start`` (positional renderers and
    the kernels' emission order both index columns densely)."""
    findings = []
    expect = start
    for name, base, width in entries:
        if width < 1:
            findings.append(Finding(
                rule_name, rel, line, 0,
                f"{label}[{name!r}] has width {width} < 1"))
            continue
        if base < expect:
            findings.append(Finding(
                rule_name, rel, line, 0,
                f"{label}[{name!r}] at columns [{base}, {base + width}) "
                f"overlaps the previous entry (next free column is "
                f"{expect})",
                hint="re-base the column block; the derived indices "
                     "follow the table automatically"))
        elif base > expect:
            findings.append(Finding(
                rule_name, rel, line, 0,
                f"{label} has a gap before {name!r}: columns "
                f"[{expect}, {base}) are unassigned — positional "
                f"consumers (REC_COLUMNS zips, kernel emission order) "
                f"would mis-align",
                hint="keep the table dense from its start column"))
        expect = max(expect, base + width)
    return findings


@rule("layout-overlap", "layout",
      "layout-table column ranges must be disjoint and dense")
def check_layout_overlap(project: Project) -> List[Finding]:
    findings = []
    for rel, names in ((STATE_FILE, _STATE_TABLES),
                       (KERNEL_FILE, _KERNEL_TABLES)):
        if project.source(rel) is None:
            continue
        tables = {}
        for name in names:
            table, line, errs = _table(project, rel, name)
            findings += errs
            if table is not None:
                tables[name] = (table, line)
        for name, (table, line) in tables.items():
            start = 0
            if name == "VOTE_RECORD_LAYOUT" and \
                    "VOTE_PARTIAL_LAYOUT" in tables:
                # the recorder block bases directly after the vote
                # kernel's base partials — a gap or overlap between the
                # two is the PR-2 hand-assignment bug
                base_tab = tables["VOTE_PARTIAL_LAYOUT"][0]
                start = max(b + w for b, w in base_tab.values())
            findings += _check_ranges(rel, line, name, _by_base(table),
                                      start)
    return findings


@rule("layout-parity", "layout",
      "recorder/witness layouts must agree across state.py and the "
      "kernels")
def check_layout_parity(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    if project.source(STATE_FILE) is None or \
            project.source(KERNEL_FILE) is None:
        return findings
    rec, rec_line, e1 = _table(project, STATE_FILE, "REC_LAYOUT")
    wit, wit_line, e2 = _table(project, STATE_FILE, "WIT_LAYOUT")
    vote, _, e3 = _table(project, KERNEL_FILE, "VOTE_PARTIAL_LAYOUT")
    vrec, vrec_line, e4 = _table(project, KERNEL_FILE,
                                 "VOTE_RECORD_LAYOUT")
    prop, _, e5 = _table(project, KERNEL_FILE, "PROP_PARTIAL_LAYOUT")
    # missing tables are reported by layout-overlap; don't double up
    if any((rec is None, wit is None, vote is None, vrec is None,
            prop is None)):
        return findings

    ksrc = project.source(KERNEL_FILE)
    pf = literal_assign(ksrc, "WITNESS_PROP_FIELDS")
    vf = literal_assign(ksrc, "WITNESS_VOTE_FIELDS")
    pc = literal_assign(ksrc, "PARTIAL_COLS")
    for name, val in (("WITNESS_PROP_FIELDS", pf),
                      ("WITNESS_VOTE_FIELDS", vf),
                      ("PARTIAL_COLS", pc)):
        if val is None:
            findings.append(Finding(
                "layout-parity", KERNEL_FILE,
                assign_line(ksrc, name), 0,
                f"{name} is missing (or not a pure literal)",
                hint="the witness field tuples and the physical column "
                     "width must be machine-readable"))
    if pf is None or vf is None or pc is None:
        return findings

    def extent(*tabs):
        return max(b + w for t in tabs for b, w in t.values())

    # 1. the vote kernel's recorder block is state.REC_LAYOUT, column
    #    for column, in the same order
    rec_cols = [n for n, _, _ in _by_base(rec)]
    vrec_cols = [n for n, _, _ in _by_base(vrec)]
    if rec_cols != vrec_cols:
        findings.append(Finding(
            "layout-parity", KERNEL_FILE, vrec_line, 0,
            f"VOTE_RECORD_LAYOUT columns {vrec_cols} != state.REC_LAYOUT "
            f"columns {rec_cols}: the kernel would emit telemetry rows "
            f"the host renderers mis-label",
            hint="keep both tables name-identical and base-ordered"))
    rec_width = extent(rec)
    vrec_width = extent(vrec, vote) - extent(vote)
    if rec_width != vrec_width:
        findings.append(Finding(
            "layout-parity", STATE_FILE, rec_line, 0,
            f"state.REC_WIDTH ({rec_width}) != the vote kernel's "
            f"recorder block width ({vrec_width}): packed_round would "
            f"assemble rows of the wrong shape",
            hint="add/remove the column in BOTH layout tables"))

    # 2. the witness field tuples cover state.WIT_LAYOUT exactly, minus
    #    the host-set "written" sentinel
    wit_names = set(wit)
    kernel_names = set(pf) | set(vf) | {"written"}
    if len(pf) + len(vf) + 1 != len(set(pf) | set(vf)) + 1 or \
            wit_names != kernel_names:
        missing = sorted(wit_names - kernel_names)
        extra = sorted(kernel_names - wit_names)
        findings.append(Finding(
            "layout-parity", STATE_FILE, wit_line, 0,
            f"WIT_LAYOUT columns and the kernels' witness fields "
            f"disagree (not emitted by any kernel: {missing}; emitted "
            f"but undeclared: {extra})",
            hint="WITNESS_PROP_FIELDS + WITNESS_VOTE_FIELDS + "
                 "{'written'} must equal state.WIT_LAYOUT's names"))
    wit_width = extent(wit)
    if wit_width != len(pf) + len(vf) + 1:
        findings.append(Finding(
            "layout-parity", STATE_FILE, wit_line, 0,
            f"state.WIT_WIDTH ({wit_width}) != kernel witness fields + "
            f"sentinel ({len(pf) + len(vf) + 1})",
            hint="the witness row assembly indexes by WIT_LAYOUT; the "
                 "kernels emit per-field columns — widths must match"))

    # 3. base + per-node witness blocks fit the physical partial width
    #    for the largest watchable node count
    csrc = project.source(CONFIG_FILE)
    max_nodes = literal_assign(csrc, "WITNESS_MAX_NODES") \
        if csrc is not None else None
    if max_nodes is not None:
        prop_need = extent(prop) + len(pf) * max_nodes
        vote_need = extent(vote, vrec) + len(vf) * max_nodes
        for label, need in (("proposal", prop_need), ("vote", vote_need)):
            if need > pc:
                findings.append(Finding(
                    "layout-parity", KERNEL_FILE,
                    assign_line(ksrc, "PARTIAL_COLS"), 0,
                    f"the {label} kernel needs {need} partial columns "
                    f"at WITNESS_MAX_NODES={max_nodes} but PARTIAL_COLS "
                    f"is {pc}: the witness blocks would run off the "
                    f"buffer",
                    hint="shrink config.WITNESS_MAX_NODES or widen "
                         "PARTIAL_COLS (and re-check VMEM cost)"))
    return findings


@rule("pack-layout", "layout",
      "the packed-state bit-field table must be overlap-free, dense and "
      "fit one uint32 word")
def check_pack_layout(project: Project) -> List[Finding]:
    """state.PACK_LAYOUT (PR 8) is the declarative bit-field layout of
    the fused kernels' plane-packed node state — the same silent-
    corruption surface as the partial-column tables: two fields on the
    same plane, a gap the loads mis-index across, or a field running off
    the 32-bit word all keep compiling and merely corrupt one regime's
    numbers.  Prove: (base, width) ranges disjoint + dense from bit 0,
    every width >= 1, and total extent <= the word width
    (state.PACK_NODES_PER_WORD — one bit per node per plane word, so the
    whole layout must fit a 32-plane stack)."""
    findings: List[Finding] = []
    if project.source(STATE_FILE) is None:
        return findings
    table, line, errs = _table(project, STATE_FILE, "PACK_LAYOUT",
                               rule_name="pack-layout")
    findings += errs
    if table is None:
        return findings
    findings += _check_ranges(STATE_FILE, line, "PACK_LAYOUT",
                              _by_base(table), 0,
                              rule_name="pack-layout")
    src = project.source(STATE_FILE)
    word = literal_assign(src, "PACK_NODES_PER_WORD")
    if word is None:
        findings.append(Finding(
            "pack-layout", STATE_FILE,
            assign_line(src, "PACK_NODES_PER_WORD"), 0,
            "PACK_NODES_PER_WORD is missing (or not a pure literal) — "
            "the pack word width must be machine-readable",
            hint="declare it as a literal int next to PACK_LAYOUT"))
        return findings
    extent = max(b + w for b, w in table.values())
    if extent > word:
        findings.append(Finding(
            "pack-layout", STATE_FILE, line, 0,
            f"PACK_LAYOUT spans {extent} bits but the pack word is "
            f"{word} bits wide: the plane stack could not be transposed "
            f"into one word per node and the declared widths lie",
            hint="shrink a field width (the k cap is the usual culprit) "
                 "or re-base the table"))
    return findings


def _netstate_fields(src) -> Optional[List[str]]:
    """NetState's annotated field names, by PARSING state.py (never by
    import — the core.py contract)."""
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef) and node.name == "NetState":
            return [s.target.id for s in node.body
                    if isinstance(s, ast.AnnAssign)
                    and isinstance(s.target, ast.Name)]
    return None


@rule("pack-parity", "layout",
      "PACK_LAYOUT field names must cover NetState's fields plus "
      "PACK_EXTRA_FIELDS exactly")
def check_pack_parity(project: Project) -> List[Finding]:
    """The packed/unpacked parity contract: every NetState leaf must
    have a bit-field in PACK_LAYOUT (or pack/unpack silently drops
    state), and every non-NetState field the kernels pack must be
    declared in PACK_EXTRA_FIELDS (or it rides the stack undocumented).
    Removing any single field from the table breaks the set equality —
    the mutation tests in tests/test_lint.py pin that."""
    findings: List[Finding] = []
    src = project.source(STATE_FILE)
    if src is None:
        return findings
    table, line, errs = _table(project, STATE_FILE, "PACK_LAYOUT")
    if table is None:
        return findings          # pack-layout already reports this
    extra = literal_assign(src, "PACK_EXTRA_FIELDS")
    if extra is None or not isinstance(extra, tuple) or not all(
            isinstance(e, str) for e in extra):
        findings.append(Finding(
            "pack-parity", STATE_FILE,
            assign_line(src, "PACK_EXTRA_FIELDS"), 0,
            "PACK_EXTRA_FIELDS is missing (or not a literal tuple of "
            "strings) — the non-NetState packed fields must be declared",
            hint="declare the extra packed fields as a literal tuple"))
        return findings
    fields = _netstate_fields(src)
    if fields is None:
        findings.append(Finding(
            "pack-parity", STATE_FILE, line, 0,
            "NetState class not found in state.py — the packed/unpacked "
            "parity check has nothing to compare against"))
        return findings
    want = set(fields) | set(extra)
    have = set(table)
    if have != want:
        missing = sorted(want - have)
        undeclared = sorted(have - want)
        findings.append(Finding(
            "pack-parity", STATE_FILE, line, 0,
            f"PACK_LAYOUT fields and NetState + PACK_EXTRA_FIELDS "
            f"disagree (unpacked fields with no bit-field: {missing}; "
            f"packed fields neither NetState nor declared extra: "
            f"{undeclared})",
            hint="add/remove the field in PACK_LAYOUT and, for "
                 "non-NetState fields, PACK_EXTRA_FIELDS together"))
    return findings


@rule("telem-layout", "layout",
      "kernel telemetry columns must derive from the TELEM_COLS table "
      "and fit the PARTIAL_COLS budget")
def check_telem_layout(project: Project) -> List[Finding]:
    """The PR-14 stage-counter block (SimConfig.kernel_telemetry) rides
    the same per-tile partial buffers as the recorder and witness
    blocks — the same silent-corruption surface, policed the same way:

      * TELEM_COLS must exist as a pure-literal name -> (base, width)
        table, overlap-free and dense from offset 0;
      * the kernels' ONE emission site (``_telem_cols``) must key its
        value dict on exactly the table's names — removing a column
        from either side (including the last one, which density alone
        cannot see) breaks the set equality;
      * the worst-case column budget must still fit PARTIAL_COLS on
        both kernels: base partials + recorder block + witness blocks
        at WITNESS_MAX_NODES + the telemetry block;
      * hand-numbered telemetry constants (a module-level ``*TELEM*``
        name bound to an int literal) are a finding — indices derive
        from the table (``_telem_base``/TELEM_WIDTH are computed, not
        hand-counted), or the next rework silently lands two features
        on one column.
    """
    findings: List[Finding] = []
    src = project.source(KERNEL_FILE)
    if src is None:
        return findings
    table, line, errs = _table(project, KERNEL_FILE, "TELEM_COLS",
                               rule_name="telem-layout")
    findings += errs
    if table is None:
        return findings
    findings += _check_ranges(KERNEL_FILE, line, "TELEM_COLS",
                              _by_base(table), 0,
                              rule_name="telem-layout")

    # 1. emission parity: the _telem_cols value-dict keys == the table
    emit_keys = None
    emit_line = line
    for node in ast.walk(src.tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "_telem_cols":
            emit_line = node.lineno
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict) and all(
                        isinstance(k, ast.Constant) and
                        isinstance(k.value, str)
                        for k in sub.keys if k is not None):
                    keys = {k.value for k in sub.keys if k is not None}
                    if emit_keys is None or len(keys) > len(emit_keys):
                        emit_keys = keys
            break
    if emit_keys is None:
        findings.append(Finding(
            "telem-layout", KERNEL_FILE, emit_line, 0,
            "_telem_cols (the one telemetry emission site) is missing "
            "or no longer builds its columns from a name-keyed dict — "
            "the table-to-kernel parity check has nothing to compare",
            hint="keep _telem_cols' values in a dict literal keyed by "
                 "TELEM_COLS names"))
    elif emit_keys != set(table):
        missing = sorted(set(table) - emit_keys)
        extra = sorted(emit_keys - set(table))
        findings.append(Finding(
            "telem-layout", KERNEL_FILE, emit_line, 0,
            f"TELEM_COLS and the _telem_cols emission dict disagree "
            f"(declared but never emitted: {missing}; emitted but "
            f"undeclared: {extra})",
            hint="add/remove the column in BOTH the table and the "
                 "emission dict"))

    # 2. worst-case budget: every kernel's full column stack must fit
    prop, _, _ = _table(project, KERNEL_FILE, "PROP_PARTIAL_LAYOUT")
    vote, _, _ = _table(project, KERNEL_FILE, "VOTE_PARTIAL_LAYOUT")
    vrec, _, _ = _table(project, KERNEL_FILE, "VOTE_RECORD_LAYOUT")
    pf = literal_assign(src, "WITNESS_PROP_FIELDS")
    vf = literal_assign(src, "WITNESS_VOTE_FIELDS")
    pc = literal_assign(src, "PARTIAL_COLS")
    csrc = project.source(CONFIG_FILE)
    max_nodes = literal_assign(csrc, "WITNESS_MAX_NODES") \
        if csrc is not None else None
    if None not in (prop, vote, vrec, pf, vf, pc, max_nodes):
        telem_w = max(b + w for b, w in table.values())

        def extent(*tabs):
            return max(b + w for t in tabs for b, w in t.values())

        prop_need = extent(prop) + len(pf) * max_nodes + telem_w
        vote_need = extent(vote, vrec) + len(vf) * max_nodes + telem_w
        for label, need in (("proposal", prop_need), ("vote", vote_need)):
            if need > pc:
                findings.append(Finding(
                    "telem-layout", KERNEL_FILE, line, 0,
                    f"the {label} kernel needs {need} partial columns "
                    f"with telemetry armed at WITNESS_MAX_NODES="
                    f"{max_nodes} but PARTIAL_COLS is {pc}: the "
                    f"TELEM_COLS block would run off the buffer",
                    hint="shrink the telemetry block (or "
                         "WITNESS_MAX_NODES) — or widen PARTIAL_COLS "
                         "and re-check VMEM cost"))

    # 3. no hand-numbered telemetry column constants
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                "TELEM" in node.targets[0].id and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, int):
            findings.append(Finding(
                "telem-layout", KERNEL_FILE, node.lineno, 0,
                f"hand-numbered telemetry constant "
                f"{node.targets[0].id} = {node.value.value}: telemetry "
                f"column indices must derive from the TELEM_COLS table",
                hint="derive the value from TELEM_COLS (see "
                     "TELEM_WIDTH / _telem_base)"))
    return findings


@rule("layout-outspec", "layout",
      "kernel out_specs must be sized by PARTIAL_COLS, not a literal")
def check_layout_outspec(project: Project) -> List[Finding]:
    """A bare ``128`` in a partial-buffer shape is how the next column
    rework silently diverges from the declared layout: the shape keeps
    compiling while the tables move.  Every partial shape must reference
    the PARTIAL_COLS name."""
    findings = []
    src = project.source(KERNEL_FILE)
    if src is None:
        return findings
    pc = literal_assign(src, "PARTIAL_COLS")
    if pc is None:
        return findings          # layout-parity already reports this

    def scan(sub: ast.AST, where: str):
        for node in ast.walk(sub):
            if isinstance(node, ast.Constant) and node.value == pc:
                findings.append(Finding(
                    "layout-outspec", KERNEL_FILE, node.lineno,
                    node.col_offset,
                    f"bare literal {pc} in {where}: size partial-buffer "
                    f"shapes with PARTIAL_COLS so out_specs follow the "
                    f"declared layout",
                    hint=f"replace {pc} with PARTIAL_COLS"))

    for node in ast.walk(src.tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name in ("_part", "_partial_cols"):
            scan(node, f"{node.name}()")
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name.split(".")[-1] == "ShapeDtypeStruct":
                scan(node, "a pallas out_shape")
    return findings
