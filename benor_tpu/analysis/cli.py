"""benorlint entry points: run_lint(), the report object, and the
``python -m benor_tpu lint`` subcommand body.

Exit contract (CI-gateable, same convention as the ``audit``
subcommand): 0 = clean, 2 = findings.  ``--format json`` emits one
machine-readable report document (schema pinned by
tools/check_metrics_schema.LINT_REPORT_SCHEMA); ``--format text`` emits
one ``path:line:col: [rule] message`` block per finding.

Every run feeds the unified metrics registry (utils/metrics.REGISTRY):
``analysis.files`` / ``analysis.findings`` / ``analysis.suppressed``
counters plus the ``analysis.lint`` timer, so lint cost and outcome land
in the same JSON-lines / Prometheus exports as compile and probe
accounting.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional

from .core import Finding, Project, RULES, run_rules

#: Report schema version (bumped with any key change; the pinned schema
#: lives in tools/check_metrics_schema.py).
REPORT_VERSION = 1


@dataclasses.dataclass
class LintReport:
    """One lint run: findings, per-rule counts, suppression accounting."""

    root: str
    files: int
    rules_run: List[str]
    findings: List[Finding]
    suppressed: Dict[str, int]
    elapsed_s: float

    @property
    def ok(self) -> bool:
        return not self.findings

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "version": REPORT_VERSION,
            "root": self.root,
            "ok": self.ok,
            "files": self.files,
            "rules_run": list(self.rules_run),
            "findings": [f.to_dict() for f in self.findings],
            "counts": self.counts(),
            "suppressed": dict(self.suppressed),
            "suppressed_total": sum(self.suppressed.values()),
            "elapsed_s": round(self.elapsed_s, 3),
        }

    def to_text(self) -> str:
        lines = []
        for f in self.findings:
            lines.append(f"{f.location()}: [{f.rule}] {f.message}")
            if f.hint:
                lines.append(f"    hint: {f.hint}")
        n = len(self.findings)
        sup = sum(self.suppressed.values())
        lines.append(
            f"benorlint: {n} finding{'s' if n != 1 else ''}, {sup} "
            f"suppressed by pragma, {self.files} files, "
            f"{len(self.rules_run)} rules")
        return "\n".join(lines)


def default_root() -> str:
    """The benor_tpu package directory (the lint self-check scope)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_lint(root: Optional[str] = None,
             rules: Optional[List[str]] = None) -> LintReport:
    """Lint the package tree under ``root`` (default: benor_tpu/).

    ``rules`` restricts to a subset of registered rule names (tests use
    this to point one family at a fixture tree)."""
    from ..utils.metrics import REGISTRY

    root = default_root() if root is None else os.path.abspath(root)
    t0 = time.perf_counter()
    project = Project(root)
    findings, suppressed = run_rules(project, names=rules)
    elapsed = time.perf_counter() - t0
    report = LintReport(
        root=root, files=len(project.sources),
        rules_run=sorted(RULES if rules is None else rules),
        findings=findings, suppressed=suppressed, elapsed_s=elapsed)
    REGISTRY.counter("analysis.runs").inc()
    REGISTRY.counter("analysis.files").inc(report.files)
    REGISTRY.counter("analysis.findings").inc(len(findings))
    REGISTRY.counter("analysis.suppressed").inc(
        sum(suppressed.values()))
    REGISTRY.timer("analysis.lint").record(elapsed)
    return report


def main(args) -> int:
    """Body of the ``lint`` CLI subcommand (argparse Namespace with
    ``root``, ``format``, ``out``, ``metrics_out``)."""
    report = run_lint(root=args.root)
    doc = report.to_dict()
    text = (json.dumps(doc, indent=1) if args.format == "json"
            else report.to_text())
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
        print(f"wrote lint report to {args.out}")
    else:
        print(text)
    if getattr(args, "metrics_out", None):
        from ..__main__ import _export_metrics
        _export_metrics(args.metrics_out)
    return 0 if report.ok else 2
