"""Perf-observability rule: every compiled executable must be observable.

ISSUE 5's perfscope subsystem made the AOT pipeline a first-class
observable — but only for executables built through its funnel
(``perfscope/instrument.py``: ``instrumented_jit`` registers the jitted
callable for cost-model introspection, ``aot_compile`` stage-times the
``lower()``/``compile()`` round trip into ``metrics.REGISTRY``).  A raw
``jax.jit`` (or a bare ``jit(...).lower(...).compile()`` chain) added
anywhere else silently re-opens the pre-perfscope blind spot: a compiled
regime whose FLOPs / bytes / peak-HBM never reach a manifest, and whose
regressions the gate cannot see.

``perf-unregistered-jit`` makes that a lint failure.  Two escape
hatches, both visible:

  * ``JIT_REGISTRY`` in perfscope/instrument.py — the pure-literal
    roster of module-level entry points that keep a raw
    ``functools.partial(jax.jit, ...)`` decorator (their donation
    pragmas and tracing seeds hang off that exact spelling).  This rule
    re-parses the tuple (never imports it) and also cross-checks that
    every entry still resolves to a real function, so the roster cannot
    go stale and silently allow-list nothing.
  * the standard ``# benorlint: allow-perf-unregistered-jit`` pragma —
    the sanctioned spelling for throwaway jits in test/fixture trees.

perfscope/instrument.py itself is exempt (it IS the funnel: the one
place ``jax.jit`` and ``.lower().compile()`` are supposed to appear).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .core import Finding, Project, Source, assign_line, dotted_name, rule
from .visitors import _canonical

#: The funnel module, relative to the lint root (benor_tpu/).
_INSTRUMENT_REL = "perfscope/instrument.py"

#: The roster literal the rule re-parses out of the funnel module.
_REGISTRY_NAME = "JIT_REGISTRY"

_HINT = ("route it through perfscope.instrument (instrumented_jit for "
         "entry points, aot_compile for lower/compile chains), add the "
         "entry point to JIT_REGISTRY with its justification, or pragma "
         "throwaway test-tree jits")


def _module_key(rel: str) -> str:
    """`ops/pallas_hist.py` -> `ops.pallas_hist` (the JIT_REGISTRY key
    space: module path relative to the package root, no package name)."""
    parts = rel[:-3].split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _load_registry(project: Project) -> Tuple[Optional[Source], tuple]:
    """(instrument Source, parsed roster) — (None, ()) when the project
    has no funnel module (fixture trees): every raw jit is then
    unregistered by definition."""
    src = project.source(_INSTRUMENT_REL)
    if src is None:
        return None, ()
    from .core import literal_assign
    roster = literal_assign(src, _REGISTRY_NAME)
    if not isinstance(roster, tuple):
        return src, ()
    return src, roster


def _canon_last(project: Project, rel: str, node: ast.AST) -> str:
    """Alias-canonical last component of a dotted ref ('' when the node
    is not a resolvable Name/Attribute chain)."""
    name = dotted_name(node)
    if not name:
        return ""
    idx = project.index
    return _canonical(idx.module_of[rel], idx, name).split(".")[-1]


def _jit_decorator(project: Project, rel: str,
                   dec: ast.AST) -> Optional[ast.AST]:
    """The raw-``jax.jit`` node of a decorator expression, or None.

    Matches the three shipped spellings — ``@jax.jit``,
    ``@jax.jit(...)``, and ``@functools.partial(jax.jit, ...)`` — and
    deliberately NOT ``instrumented_jit`` (that is the fix)."""
    ref = dec.func if isinstance(dec, ast.Call) else dec
    if _canon_last(project, rel, ref) == "jit":
        return dec
    if isinstance(dec, ast.Call) and \
            _canon_last(project, rel, dec.func) == "partial" and dec.args \
            and _canon_last(project, rel, dec.args[0]) == "jit":
        return dec
    return None


def _lower_compile_chain(node: ast.Call) -> bool:
    """``<expr>.lower(...).compile(...)`` — the bare AOT spelling.
    (Requiring the full chain keeps ``str.lower()`` and
    ``Lowered.compile`` on a named temporary out of scope; the repo's
    sanctioned chain lives in aot_compile.)"""
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr == "compile"
            and isinstance(node.func.value, ast.Call)
            and isinstance(node.func.value.func, ast.Attribute)
            and node.func.value.func.attr == "lower")


@rule("perf-unregistered-jit", "perf",
      "compiled executable invisible to perfscope (raw jax.jit / "
      "lower().compile() off the instrumented funnel)")
def check_unregistered_jit(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    inst_src, roster = _load_registry(project)

    # the roster itself must not go stale: an entry whose module is in
    # the tree but whose function is gone allow-lists nothing and hides
    # that it allow-lists nothing
    if inst_src is not None:
        line = assign_line(inst_src, _REGISTRY_NAME)
        for entry in roster:
            mod, _, fn = str(entry).rpartition(".")
            rel = mod.replace(".", "/") + ".py"
            src = project.source(rel)
            if src is None:
                # a roster row for a module that is not in the tree is
                # just as stale as one for a vanished function — a
                # renamed/deleted module must not rot silently
                findings.append(Finding(
                    "perf-unregistered-jit", _INSTRUMENT_REL, line, 0,
                    f"JIT_REGISTRY entry {entry!r} names module {rel} "
                    f"which is not in the tree — a stale roster row "
                    f"allow-lists nothing",
                    hint="update or drop the entry (the roster is the "
                         "reviewed exception list; it must stay real)"))
                continue
            if not any(isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                       and n.name == fn for n in ast.walk(src.tree)):
                findings.append(Finding(
                    "perf-unregistered-jit", _INSTRUMENT_REL, line, 0,
                    f"JIT_REGISTRY entry {entry!r} does not resolve to a "
                    f"function in {rel} — a stale roster row allow-lists "
                    f"nothing",
                    hint="update or drop the entry (the roster is the "
                         "reviewed exception list; it must stay real)"))

    for rel, src in project.sources.items():
        if rel == _INSTRUMENT_REL:
            continue
        mod_key = _module_key(rel)
        in_decorator: Set[int] = set()

        # decorator jits: allowed only through the JIT_REGISTRY roster
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                in_decorator.update(id(sub) for sub in ast.walk(dec))
                jit_node = _jit_decorator(project, rel, dec)
                if jit_node is None:
                    continue
                entry = f"{mod_key}.{node.name}"
                if entry in roster:
                    continue
                findings.append(Finding(
                    "perf-unregistered-jit", rel, dec.lineno,
                    dec.col_offset,
                    f"raw jax.jit on {node.name!r} is invisible to "
                    f"perfscope ({entry!r} is not in "
                    f"perfscope/instrument.py JIT_REGISTRY): its cost "
                    f"model and compile time reach no manifest, so the "
                    f"perf gate cannot see it regress",
                    hint=_HINT))

        # call-site jits + bare lower().compile() chains
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or id(node) in in_decorator:
                continue
            if _canon_last(project, rel, node.func) == "jit":
                findings.append(Finding(
                    "perf-unregistered-jit", rel, node.lineno,
                    node.col_offset,
                    "raw jax.jit(...) call site builds an executable "
                    "perfscope cannot introspect",
                    hint=_HINT))
            elif _lower_compile_chain(node):
                findings.append(Finding(
                    "perf-unregistered-jit", rel, node.lineno,
                    node.col_offset,
                    "bare .lower(...).compile() chain: the AOT round "
                    "trip is untimed and its cost model unread "
                    "(pre-perfscope bench.py's exact blind spot)",
                    hint=_HINT))
    return findings
