"""Cross-module function index + traced-reachability analysis.

The tracer-hygiene rules (rules_tracer.py) need to know which functions
execute UNDER A JAX TRACE — i.e. are reachable from a ``jax.jit`` /
``pallas_call`` / ``shard_map`` / ``lax.while_loop``-family boundary —
because a host sync that is fine in harness code (``int(out[0])`` as a
completion barrier in sweep.run_point) is a bug inside a compiled loop.

This is a deliberately conservative STATIC approximation:

  seeds        functions decorated with ``jax.jit`` (incl. the
               ``functools.partial(jax.jit, ...)`` idiom), and functions
               passed — directly or via ``functools.partial`` — into a
               trace boundary call (jit, pallas_call, shard_map, vmap,
               pmap, and the lax control-flow combinators).
  propagation  a call from a traced function marks the callee traced,
               resolved through each module's import-alias table (plain
               names, one-level ``alias.name`` attributes, and relative
               imports); nested ``def``s of a traced function are traced.
  host escape  functions handed to ``jax.debug.callback`` /
               ``jax.pure_callback`` / ``io_callback`` run on the HOST by
               construction and are never marked, even when the callback
               registration happens inside a traced function.

Unresolvable calls (methods on unknown receivers, dynamic dispatch) are
skipped — the analysis under-approximates rather than guessing, so its
findings stay actionable.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .core import Project, dotted_name

#: Bare callables that open a trace (matched by name alone — the repo
#: imports shard_map under this name, and jit/vmap read unambiguously).
#: perfscope's instrumented spellings are jit-equivalent boundaries: a
#: function handed to instrumented_jit / aot_compile executes under a
#: trace exactly like a jax.jit-decorated one.
_BARE_BOUNDARIES = {"jit", "pallas_call", "shard_map", "vmap", "pmap",
                    "instrumented_jit", "aot_compile"}

#: lax control-flow combinators: matched as ``lax.<name>`` /
#: ``jax.lax.<name>`` (never by bare name — loop bodies are commonly
#: local functions called ``cond``).
_LAX_BOUNDARIES = {"while_loop", "scan", "cond", "fori_loop", "switch",
                   "map", "associative_scan"}

#: Registering a function here hands it to the HOST runtime.
_HOST_SINKS = {"callback", "pure_callback", "io_callback"}


@dataclasses.dataclass
class FuncInfo:
    module: str                  # dotted module, e.g. benor_tpu.ops.rng
    name: str
    rel: str                     # source path relative to the root
    node: ast.AST                # FunctionDef / AsyncFunctionDef
    params: Tuple[str, ...]


class Index:
    """Function defs, import aliases, and the traced set for one Project."""

    def __init__(self) -> None:
        self.funcs: Dict[Tuple[str, str], FuncInfo] = {}
        self.aliases: Dict[str, Dict[str, str]] = {}   # module -> alias map
        self.module_of: Dict[str, str] = {}            # rel path -> module
        self.traced: List[FuncInfo] = []
        self._traced_ids: Set[int] = set()
        self._host_ids: Set[int] = set()

    def is_traced(self, node: ast.AST) -> bool:
        return id(node) in self._traced_ids


def _module_name(root_pkg: str, rel: str) -> str:
    parts = rel[:-3].split("/")                        # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join([root_pkg] + parts) if parts else root_pkg


def _collect_aliases(module: str, tree: ast.Module) -> Dict[str, str]:
    """alias -> dotted target, from every import at any depth."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = module.split(".")
                base = base[:len(base) - node.level]
                src = ".".join(base + ([node.module] if node.module else []))
            else:
                src = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{src}.{a.name}" if src else a.name
    return out


def _params(node) -> Tuple[str, ...]:
    a = node.args
    names = [p.arg for p in
             getattr(a, "posonlyargs", []) + a.args + a.kwonlyargs]
    for extra in (a.vararg, a.kwarg):
        if extra is not None:
            names.append(extra.arg)
    return tuple(names)


def _canonical(source_module: str, idx: "Index", name: str) -> str:
    """Resolve the first component of a dotted name through the module's
    alias table: ``pl.pallas_call`` -> ``jax.experimental.pallas.pallas_call``."""
    head, _, rest = name.partition(".")
    target = idx.aliases.get(source_module, {}).get(head)
    if target is None:
        return name
    return f"{target}.{rest}" if rest else target


def _is_boundary(module: str, idx: "Index", func_node: ast.AST) -> bool:
    name = dotted_name(func_node)
    if name is None:
        return False
    canon = _canonical(module, idx, name)
    last = canon.split(".")[-1]
    if last in _BARE_BOUNDARIES:
        return True
    parts = canon.split(".")
    return (last in _LAX_BOUNDARIES and len(parts) >= 2
            and parts[-2] == "lax")


def _is_partial(module: str, idx: "Index", node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if name is None:
        return False
    return _canonical(module, idx, name).split(".")[-1] == "partial"


def resolve_call(idx: "Index", module: str,
                 func_node: ast.AST) -> Optional[FuncInfo]:
    """FuncInfo for a call target, through the alias table; None when the
    target is not a statically resolvable project function."""
    name = dotted_name(func_node)
    if name is None:
        return None
    if "." not in name:
        alias = idx.aliases.get(module, {}).get(name)
        if alias and "." in alias:
            mod, _, fn = alias.rpartition(".")
            return idx.funcs.get((mod, fn))
        return idx.funcs.get((module, name))
    head, _, rest = name.partition(".")
    if "." in rest:                  # method chains / deep attrs: skip
        return None
    target = idx.aliases.get(module, {}).get(head)
    if target is None:
        return None
    return idx.funcs.get((target, rest))


def _callable_args(call: ast.Call):
    """The argument expressions of a call that may carry function refs."""
    return list(call.args) + [k.value for k in call.keywords]


def build_index(project: Project) -> Index:
    """Build the function index, alias tables and traced set."""
    idx = Index()
    root_pkg = project.root.rstrip("/").split("/")[-1]

    for rel, src in project.sources.items():
        module = _module_name(root_pkg, rel)
        idx.module_of[rel] = module
        idx.aliases[module] = _collect_aliases(module, src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                idx.funcs[(module, node.name)] = FuncInfo(
                    module=module, name=node.name, rel=rel, node=node,
                    params=_params(node))

    def seed_from_ref(module: str, ref: ast.AST, seeds: list) -> None:
        if _is_partial(module, idx, ref):
            args = ref.args
            if args:
                seed_from_ref(module, args[0], seeds)
            return
        if isinstance(ref, (ast.Name, ast.Attribute)):
            info = resolve_call(idx, module, ref)
            if info is not None:
                seeds.append(info)

    seeds: List[FuncInfo] = []
    for rel, src in project.sources.items():
        module = idx.module_of[rel]
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    ref = dec.func if isinstance(dec, ast.Call) else dec
                    name = dotted_name(ref)
                    if name is None:
                        continue
                    canon = _canonical(module, idx, name)
                    if canon.split(".")[-1] in _BARE_BOUNDARIES:
                        seeds.append(idx.funcs[(module, node.name)])
                    elif canon.split(".")[-1] == "partial" and \
                            isinstance(dec, ast.Call) and dec.args:
                        inner = dotted_name(dec.args[0])
                        if inner and _canonical(module, idx, inner) \
                                .split(".")[-1] in _BARE_BOUNDARIES:
                            seeds.append(idx.funcs[(module, node.name)])
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                canon = _canonical(module, idx, name) if name else ""
                if canon.split(".")[-1] in _HOST_SINKS:
                    for ref in _callable_args(node):
                        info = None
                        if isinstance(ref, (ast.Name, ast.Attribute)):
                            info = resolve_call(idx, module, ref)
                        if info is not None:
                            idx._host_ids.add(id(info.node))
                elif _is_boundary(module, idx, node.func):
                    for ref in _callable_args(node):
                        seed_from_ref(module, ref, seeds)

    # worklist propagation: traced -> callees, partial targets, nested defs
    work = list(seeds)
    while work:
        info = work.pop()
        if id(info.node) in idx._traced_ids or \
                id(info.node) in idx._host_ids:
            continue
        idx._traced_ids.add(id(info.node))
        idx.traced.append(info)
        for node in ast.walk(info.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not info.node:
                nested = idx.funcs.get((info.module, node.name))
                if nested is not None and nested.node is node:
                    work.append(nested)
            elif isinstance(node, ast.Call):
                target = resolve_call(idx, info.module, node.func)
                if target is not None:
                    work.append(target)
                if _is_partial(info.module, idx, node) and node.args:
                    first = node.args[0]
                    if isinstance(first, (ast.Name, ast.Attribute)):
                        target = resolve_call(idx, info.module, first)
                        if target is not None:
                            work.append(target)
    idx.traced.sort(key=lambda f: (f.rel, f.node.lineno))
    return idx
