"""Manifest-contract rule: every emitted manifest kind has a checker.

The repo's committed-artifact discipline is that every pinned-schema
document (``kind: perf_manifest`` / ``scaling_manifest`` /
``serve_manifest`` / ``sweep_manifest``) is auto-detected and
cross-field-validated by ``tools/check_metrics_schema.py`` — that is
what makes a hand-edited baseline or a drifted capture fail CI instead
of silently gating vacuously.  Nothing STOPPED a new subsystem from
emitting a fifth ``"<x>_manifest"`` kind with no registered checker:
its documents would flow through the tool's fall-through branch as a
bench record, error confusingly, and the contract would rot.

``manifest-kind-parity`` makes that a lint failure, parsed from BOTH
sides and never imported (the linter's no-import contract):

  * the EMISSION side: every ``"kind": "<x>_manifest"`` dict-literal
    entry and every ``<NAME>_KIND = "<x>_manifest"`` module constant
    anywhere in the package tree — the two spellings the shipped
    manifest builders use (serve/loadgen.py inlines the dict entry;
    perfscope/manifest.py, meshscope/scaling.py and
    sweepscope/manifest.py bind a ``*_KIND`` constant).  Mere
    identifier-shaped strings (``__all__`` rosters of
    ``save_sweep_manifest``-style function names) are not emissions
    and do not count;
  * the REGISTRY side: the pure-literal ``MANIFEST_CHECKERS`` dict in
    ``tools/check_metrics_schema.py`` (the same dispatch ``main`` runs,
    so "registered" means "runnable").  Like perfscope's JIT_REGISTRY,
    the registry is STALENESS-CHECKED: a row whose checker function no
    longer exists in the tool validates nothing and must say so rather
    than rot silently.

The tools file lives OUTSIDE the package root (benor_tpu/'s sibling
``tools/``); a fixture tree without it treats every emitted kind as
unregistered — the same missing-funnel behavior as
``perf-unregistered-jit``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from .core import Finding, Project, rule

#: A whole-string manifest kind: lowercase snake segments ending in
#: ``_manifest`` (matches the value domain of MANIFEST_CHECKERS keys).
_KIND_RE = re.compile(r"\A[a-z0-9]+(?:_[a-z0-9]+)*_manifest\Z")

#: The checker registry's home, relative to the lint root's PARENT
#: (the repo layout: benor_tpu/ and tools/ are siblings).
_TOOLS_REL = os.path.join("tools", "check_metrics_schema.py")

_REGISTRY_NAME = "MANIFEST_CHECKERS"

_HINT = ("register the kind in tools/check_metrics_schema.py "
         "MANIFEST_CHECKERS with a check_<x>_manifest function (schema "
         "file + cross-field pins), like the perf/scaling/serve/sweep "
         "manifests")


def _tools_path(project: Project) -> str:
    return os.path.join(os.path.dirname(project.root), _TOOLS_REL)


def _parse_registry(path: str):
    """(registry dict, assignment line, parsed tool AST) from the tools
    file — ({}, 1, None) when the file or the literal is missing (every
    emitted kind is then unregistered by definition)."""
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return {}, 1, None
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            targets = [node.target.id]
        if _REGISTRY_NAME in targets:
            try:
                value = ast.literal_eval(node.value)
            except (ValueError, TypeError):
                return {}, node.lineno, tree
            if isinstance(value, dict):
                return value, node.lineno, tree
            return {}, node.lineno, tree
    return {}, 1, tree


def _kind_literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and _KIND_RE.match(node.value):
        return node.value
    return None


def _emitted_kinds(project: Project) -> Dict[str, Tuple[str, int, int]]:
    """kind -> first (rel, line, col) where an EMISSION appears, in
    sorted file order (deterministic anchors for dedup + mutation
    tests).  Emissions are ``{"kind": "<x>_manifest", ...}`` dict
    entries and ``<NAME>_KIND = "<x>_manifest"`` module constants (see
    module docstring)."""
    kinds: Dict[str, Tuple[str, int, int]] = {}

    def record(value_node) -> None:
        kind = _kind_literal(value_node)
        if kind is not None and kind not in kinds:
            kinds[kind] = (rel, value_node.lineno,
                           value_node.col_offset)

    for rel in sorted(project.sources):
        src = project.sources[rel]
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and \
                            k.value == "kind":
                        record(v)
            elif isinstance(node, ast.Assign):
                if node.value is not None and any(
                        isinstance(t, ast.Name)
                        and t.id.endswith("KIND")
                        for t in node.targets):
                    record(node.value)
            elif isinstance(node, ast.AnnAssign):
                if node.value is not None and \
                        isinstance(node.target, ast.Name) and \
                        node.target.id.endswith("KIND"):
                    record(node.value)
    return kinds


@rule("manifest-kind-parity", "config",
      "a \"<x>_manifest\" kind emitted without a registered checker in "
      "tools/check_metrics_schema.py (or a stale registry row)")
def check_manifest_kind_parity(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    tools_path = _tools_path(project)
    tools_disp = os.path.relpath(tools_path, project.root)
    registry, reg_line, tool_tree = _parse_registry(tools_path)
    kinds = _emitted_kinds(project)

    for kind in sorted(kinds):
        rel, line, col = kinds[kind]
        if kind not in registry:
            missing = ("tools/check_metrics_schema.py is not in the "
                       "tree" if tool_tree is None else
                       f"{_REGISTRY_NAME} registers no checker for it")
            findings.append(Finding(
                "manifest-kind-parity", rel, line, col,
                f"manifest kind {kind!r} is emitted here but {missing} "
                f"— its documents would dodge schema + cross-field "
                f"validation and the committed-artifact contract rots",
                hint=_HINT))

    # staleness (the JIT_REGISTRY discipline): a registry row whose
    # checker function left the tool validates nothing — and must say
    # so rather than rot silently
    if tool_tree is not None:
        defined = {n.name for n in ast.walk(tool_tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        for kind in sorted(registry):
            fn = registry[kind]
            if not isinstance(fn, str) or fn not in defined:
                findings.append(Finding(
                    "manifest-kind-parity", tools_disp, reg_line, 0,
                    f"{_REGISTRY_NAME} entry {kind!r} -> {fn!r} does "
                    f"not resolve to a function in "
                    f"check_metrics_schema.py — a stale registry row "
                    f"validates nothing",
                    hint="update or drop the row (the registry is the "
                         "tool's live dispatch; it must stay real)"))
    return findings
