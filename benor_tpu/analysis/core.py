"""benorlint core: sources, pragma suppression, findings, rule registry.

Dependency-free (stdlib ``ast`` only): the linter must run in any
environment that can parse the package — including CI images without a
live accelerator — and must never import the modules it inspects (an
import would execute jax backend setup; a PARSE cannot).

The moving parts:

  * ``Source``   — one parsed file: text, AST, and its pragma map.
  * ``Project``  — every ``.py`` file under the package root, plus the
    cross-module function index and traced-reachability set that the
    tracer-hygiene rules consume (built in ``visitors.py``).
  * ``Finding``  — one diagnostic: rule, file:line:col, message, fix hint.
  * ``@rule``    — registry decorator; ``run_rules`` executes every
    registered rule over a Project and applies pragma suppression.

Pragma syntax (the escape hatch for INTENTIONAL rule exceptions):

    # benorlint: allow-<rule> — one-line justification

On a code line it suppresses that rule's findings on that line; on a
comment-only line it covers the rest of its comment block and the first
code line after it (so a multi-line justification can sit directly above
the flagged statement).  Suppressions are counted per rule and reported
— an allow pragma is visible forever, not silent.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

#: ``# benorlint: allow-<rule>[, allow-<rule>...] — justification``
_PRAGMA_RE = re.compile(r"benorlint:\s*(allow-[a-z0-9,\s-]+)")
_ALLOW_RE = re.compile(r"allow-([a-z0-9-]+)")


@dataclasses.dataclass
class Finding:
    """One diagnostic, anchored to a source location."""

    rule: str
    path: str          # repo/package-relative path
    line: int
    col: int
    message: str
    hint: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Source:
    """One parsed python file + its pragma map."""

    def __init__(self, path: str, rel: str, text: str) -> None:
        self.path = path
        self.rel = rel
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.lines = text.splitlines()
        #: line (1-based) -> set of rule names allowed on that line
        self.pragmas: Dict[int, set] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if not m:
                continue
            rules = set(_ALLOW_RE.findall(m.group(1)))
            if not rules:
                continue
            self.pragmas.setdefault(i, set()).update(rules)
            if line.lstrip().startswith("#"):
                # comment-only pragma: cover the rest of the comment
                # block and the first code line after it
                j = i + 1
                while j <= len(self.lines) and (
                        not self.lines[j - 1].strip()
                        or self.lines[j - 1].lstrip().startswith("#")):
                    self.pragmas.setdefault(j, set()).update(rules)
                    j += 1
                self.pragmas.setdefault(j, set()).update(rules)

    def allows(self, rule: str, line: int) -> bool:
        return rule in self.pragmas.get(line, ())


class Project:
    """Every parsed source under one package root, plus the shared
    analyses (function index, traced-reachability) rules consume."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        self.sources: Dict[str, Source] = {}          # rel path -> Source
        #: files that failed to parse, as findings (a broken file must
        #: surface as a diagnostic, not crash the run off the 0/2
        #: exit contract)
        self.errors: List[Finding] = []
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__",))
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, self.root).replace(os.sep, "/")
                with open(full, encoding="utf-8") as fh:
                    text = fh.read()
                try:
                    self.sources[rel] = Source(full, rel, text)
                except SyntaxError as e:
                    self.errors.append(Finding(
                        "parse-error", rel, e.lineno or 1,
                        (e.offset or 1) - 1,
                        f"file does not parse: {e.msg}",
                        hint="benorlint analyzes the AST; fix the "
                             "syntax error first"))
        from .visitors import build_index
        # module/import index + the traced-reachability set (visitors.py)
        self.index = build_index(self)

    def source(self, rel: str) -> Optional[Source]:
        return self.sources.get(rel)


@dataclasses.dataclass
class Rule:
    name: str
    family: str      # 'tracer' | 'layout' | 'config' | 'perf' | 'serve'
    doc: str
    check: Callable[["Project"], List[Finding]]


#: name -> Rule, in registration order.
RULES: "Dict[str, Rule]" = {}


def rule(name: str, family: str, doc: str):
    """Register a rule.  The wrapped function takes a Project and returns
    a list of Findings (pragma suppression is applied by run_rules)."""
    def wrap(fn):
        if name in RULES:
            raise ValueError(f"duplicate rule {name!r}")
        RULES[name] = Rule(name=name, family=family, doc=doc, check=fn)
        return fn
    return wrap


def run_rules(project: Project, names=None
              ) -> Tuple[List[Finding], Dict[str, int]]:
    """Run the (selected) rules -> (active findings, suppressed counts).

    A finding is suppressed when its file carries a matching
    ``# benorlint: allow-<rule>`` pragma on the finding's line (or the
    comment block directly above it).  Findings are deduplicated by
    (rule, location, message) first, and each deduped finding is counted
    once, active or suppressed.  (Distinct messages at one location are
    distinct findings — config-parity anchors one finding per missing
    regime at the field's first sim.py use.)"""
    # rule modules register on import; import them here so a bare
    # ``from .core import run_rules`` is enough to get the full set
    from . import (rules_config, rules_layout,  # noqa: F401
                   rules_manifest, rules_perf, rules_serve,
                   rules_tracer)

    active: List[Finding] = list(project.errors)
    suppressed: Dict[str, int] = {}
    for name, r in RULES.items():
        if names is not None and name not in names:
            continue
        seen = set()
        for f in r.check(project):
            key = (f.rule, f.path, f.line, f.col, f.message)
            if key in seen:
                continue
            seen.add(key)
            src = project.source(f.path)
            if src is not None and src.allows(f.rule, f.line):
                suppressed[f.rule] = suppressed.get(f.rule, 0) + 1
            else:
                active.append(f)
    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return active, suppressed


# --------------------------------------------------------------------------
# Small shared AST helpers (used by every rule family)
# --------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.while_loop`` for an Attribute/Name chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def literal_assign(source: Source, name: str):
    """The literal value of a module-level ``NAME = <literal>`` assignment
    (ast.literal_eval'd), or None when absent / not a pure literal.

    This is how the layout checker reads the declarative column tables:
    by PARSING them, never by importing the modules that own them — which
    also forces the tables to stay machine-readable pure literals."""
    for node in source.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            targets = [node.target.id]
        if name in targets:
            try:
                return ast.literal_eval(node.value)
            except (ValueError, TypeError):
                return None
    return None


def assign_line(source: Source, name: str) -> int:
    """Line of a module-level assignment to ``name`` (1 when absent)."""
    for node in source.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            return node.lineno
        if isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == name:
            return node.lineno
    return 1
