"""Serve-plane rule: no blocking calls on the event loop.

The request plane (benor_tpu/serve/server.py) is one asyncio event loop
serving thousands of concurrent SSE streams; ONE blocking call inside
an ``async def`` handler stalls every client at once — the classic
async-server failure mode, and invisible to tests that drive a handful
of connections.  The device work lives on the batcher thread by design;
handler code must only await.

``serve-blocking-call`` flags, anywhere lexically inside an
``async def`` (nested sync helpers included — they run on the loop when
the handler calls them):

  * ``time.sleep(...)``            — the canonical loop-stall (spell it
                                     ``await asyncio.sleep(...)``)
  * ``<jax-array>.item()``         — a host sync: blocks the loop on
                                     device completion (fetch on the
                                     batcher thread, publish the value)
  * raw socket/HTTP constructions  — ``socket.socket`` /
    ``socket.create_connection`` / ``urllib.request.urlopen`` /
    ``http.client.HTTPConnection`` and ``requests.*`` calls: kernel-
    blocking I/O with no awaitable handle (use asyncio streams)
  * ``subprocess.run`` / ``check_output`` / ``check_call`` / ``call``

The standard ``# benorlint: allow-serve-blocking-call`` pragma is the
escape hatch for a justified exception (none shipped today).
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Project, dotted_name, rule
from .visitors import _canonical

#: Canonical dotted names whose CALL blocks the loop.
_BLOCKING_CALLS = frozenset({
    "time.sleep",
    "socket.socket",
    "socket.create_connection",
    "urllib.request.urlopen",
    "http.client.HTTPConnection",
    "http.client.HTTPSConnection",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
})

#: Any call through the `requests` package (fetch-style HTTP client —
#: the reference's fire-and-forget idiom, and 100% blocking).
_BLOCKING_ROOTS = ("requests",)

_HINT = ("handlers must only await: move device/file/socket work to the "
         "batcher thread (serve/batcher.py) or an asyncio primitive "
         "(asyncio.sleep, asyncio.open_connection, loop.run_in_executor)")


def _blocking_name(project: Project, rel: str, node: ast.Call):
    """Canonical blocked name of a call node, or None."""
    name = dotted_name(node.func)
    if not name:
        return None
    idx = project.index
    canon = _canonical(idx.module_of[rel], idx, name)
    if canon in _BLOCKING_CALLS:
        return canon
    if canon.split(".")[0] in _BLOCKING_ROOTS:
        return canon
    return None


@rule("serve-blocking-call", "serve",
      "blocking host-sync / sleep / raw-socket call inside async "
      "handler code (stalls every client on the event loop)")
def check_serve_blocking(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for rel, src in project.sources.items():
        for fn in ast.walk(src.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in ast.walk(fn):
                if node is not fn and isinstance(node,
                                                 ast.AsyncFunctionDef):
                    continue  # nested async defs get their own walk
                if not isinstance(node, ast.Call):
                    continue
                canon = _blocking_name(project, rel, node)
                if canon is not None:
                    findings.append(Finding(
                        "serve-blocking-call", rel, node.lineno,
                        node.col_offset,
                        f"{canon}(...) inside async {fn.name!r} blocks "
                        f"the event loop: every concurrent SSE client "
                        f"stalls behind this call",
                        hint=_HINT))
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "item" \
                        and not node.args and not node.keywords:
                    findings.append(Finding(
                        "serve-blocking-call", rel, node.lineno,
                        node.col_offset,
                        f".item() inside async {fn.name!r} is a host "
                        f"sync: the event loop blocks on device "
                        f"completion while every other client waits",
                        hint=_HINT))
    return findings
