"""Five-regime config parity: a SimConfig field the driver consumes must
be visible in every compiled regime (or be allowlisted with a reason).

The incident this rule owns: PRs 1-3 each added a SimConfig field
(DynParams' f-axis, ``record``, ``witness_trials``) that had to be
hand-threaded through FIVE separately-compiled regimes — the traced XLA
loop (sim.py), the batched dynamic-F sweep (sweep.py), the fused pallas
round (ops/pallas_round.py), the sharded mesh runner
(parallel/sharded.py) and the multi-host runner (parallel/multihost.py).
A regime that silently ignores a field still runs and still agrees with
itself; only a cross-regime comparison (or a user) notices.  This rule
makes the omission a LINT failure instead: every field ``sim.py`` reads
off ``cfg`` must be referenced in each regime file, or carry an
allowlist entry saying why that regime legitimately never sees it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .core import Finding, Project, rule

SIM_FILE = "sim.py"
CONFIG_FILE = "config.py"

#: The compiled regimes that must keep parity with sim.py's consumption.
REGIME_FILES = ("sweep.py", "ops/pallas_round.py", "parallel/sharded.py",
                "parallel/multihost.py", "parallel/grid.py")

#: (field, regime-file) -> why that regime legitimately never references
#: the field.  Every entry is a REVIEWED delegation argument, not an
#: escape hatch: the reason names the code that covers the regime.
PARITY_ALLOWLIST = {
    ("debug", "sweep.py"):
        "the sweep drives run_consensus/run_consensus_traced, which "
        "apply the debug demotion before any regime dispatch",
    ("debug", "ops/pallas_round.py"):
        "sim.py / parallel/sharded.py demote debug configs to the XLA "
        "loop before the fused round is ever entered",
    ("debug", "parallel/multihost.py"):
        "multihost delegates the whole loop to sharded._compiled, whose "
        "_local_slice handles the debug demotion",
    ("seed", "ops/pallas_round.py"):
        "compiled regimes receive base_key; jax.random.key(cfg.seed) "
        "happens once at the harness boundary (sweep.run_point)",
    ("seed", "parallel/sharded.py"):
        "same as the fused round: the sharded runner takes the derived "
        "base_key, never the raw seed",
    ("seed", "parallel/multihost.py"):
        "same as the sharded runner; every process derives the identical "
        "base_key from cfg.seed at its own harness boundary",
    ("max_rounds", "parallel/multihost.py"):
        "the round loop (and its cap) lives in sharded._local_slice; "
        "multihost only builds global inputs and dispatches to it",
    ("heartbeat_rounds", "ops/pallas_round.py"):
        "the heartbeat (meshscope/heartbeat.py) publishes HOST-side at "
        "slice boundaries, from buffers the slice already returns; the "
        "fused kernels can never see the cadence — run_packed_slice's "
        "callers (sim.run_consensus_slice, sharded._local_slice) own "
        "the boundary, and the sharded/multihost wrappers plus the "
        "sweep engine all reference the field themselves",
    # --- structured delivery planes (benor_tpu/topo, PR 12) -------------
    # The topology/committee dispatch lives INSIDE the shared round
    # kernel: tally.receiver_counts routes to topo/deliver.py and
    # models/benor.py to topo/committees.py, both via ShardCtx gathers/
    # psums keyed on global ids — so the sharded and multihost runners
    # serve structured configs through the identical benor_round body
    # with zero regime-specific code (tests/test_topo.py pins the
    # sharded bit-identity).  The fused pallas kernels implement the
    # complete graph only and structurally never engage (structured
    # planes require delivery='all', which every pallas gate in
    # ops/tally.py rejects; sim.warn_structured_demotes_pallas
    # announces it).  sweep.py references both fields itself
    # (quorum_specialized / sweep_bucket_key).
    ("topology", "ops/pallas_round.py"):
        "the fused kernels implement the complete graph only; "
        "tally.pallas_round_active rejects structured configs before "
        "dispatch and sim.warn_structured_demotes_pallas announces it",
    ("topology", "parallel/sharded.py"):
        "the adjacency gather runs inside the shared round kernel "
        "(tally.receiver_counts -> topo/deliver.py) via "
        "ctx.all_gather_nodes on global ids — the sharded runner "
        "needs no topology-specific code (tests/test_topo.py)",
    ("topology", "parallel/multihost.py"):
        "multihost delegates the whole loop to sharded._local_slice, "
        "which reaches the same kernel-level topo dispatch",
    ("committee_cap", "ops/pallas_round.py"):
        "same structural demotion as topology: committee delivery "
        "requires delivery='all', which every pallas gate rejects",
    ("committee_cap", "parallel/sharded.py"):
        "committee histograms scatter per shard and psum over the node "
        "axis inside the shared round kernel (models/benor.py -> "
        "topo/committees.py); the sharded runner needs no "
        "committee-specific code",
    ("committee_cap", "parallel/multihost.py"):
        "multihost delegates the whole loop to sharded._local_slice, "
        "which reaches the same kernel-level committee dispatch",
    # --- faultlab: the dynamic fault-injection plane (PR 15) -------------
    # sim.injection_plane consumes fault_model/drop_prob/partition/
    # recovery.  crash_recover runs INSIDE the shared round kernel
    # (models/benor.py derives the down mask; ops/pallas_round.py
    # re-derives it in-kernel and reads cfg.fault_model/cfg.recovery
    # itself); omission and partitions live in tally.receiver_counts'
    # delivery='all' branch, reached identically by every regime via
    # benor_round — the sharded/multihost runners need no plane-specific
    # code (tests/test_faults.py pins the sharded bit-identity), and the
    # fused kernels structurally never see the delivery='all' planes
    # (sim.warn_faults_demote_pallas announces the demotion).
    ("fault_model", "parallel/sharded.py"):
        "the crash_recover down mask derives inside the shared round "
        "kernel (models/benor.py) and the packed slice "
        "(pallas_round._load_fields) from the FaultSpec bounds — the "
        "sharded runner passes faults through untouched",
    ("fault_model", "parallel/multihost.py"):
        "multihost delegates the whole loop to sharded._local_slice, "
        "which reaches the same kernel-level fault dispatch",
    ("drop_prob", "ops/pallas_round.py"):
        "omission requires delivery='all', which every pallas gate in "
        "ops/tally.py rejects — the structural demotion "
        "sim.warn_faults_demote_pallas announces; the thinning lives in "
        "tally.omission_thin_counts on the XLA loop",
    ("drop_prob", "parallel/sharded.py"):
        "the binomial thinning runs inside the shared round kernel "
        "(tally.receiver_counts) on psum'd global histograms keyed on "
        "global ids — no sharded-specific code "
        "(tests/test_faults.py pins the mesh bit-identity)",
    ("drop_prob", "parallel/multihost.py"):
        "multihost delegates the whole loop to sharded._local_slice, "
        "which reaches the same kernel-level omission dispatch",
    ("partition", "ops/pallas_round.py"):
        "same structural demotion as drop_prob: partitions require "
        "delivery='all', rejected by every pallas gate and announced "
        "by sim.warn_faults_demote_pallas",
    ("partition", "parallel/sharded.py"):
        "partition group histograms are per-shard masked sums psum'd "
        "over the node axis inside tally.partition_counts (and the "
        "topo gather masks in topo/deliver.py) — no sharded-specific "
        "code",
    ("partition", "parallel/multihost.py"):
        "multihost delegates the whole loop to sharded._local_slice, "
        "which reaches the same kernel-level partition dispatch",
    ("recovery", "parallel/sharded.py"):
        "the recovery schedule is realized into FaultSpec.recover_round "
        "at the harness boundary (sweep.default_crash_faults); the "
        "compiled regimes read the bounds, and the amnesia rejoin mode "
        "is read where it compiles (models/benor.py, ops/pallas_round)",
    ("recovery", "parallel/multihost.py"):
        "same as the sharded runner: the schedule travels as "
        "FaultSpec.recover_round built at the harness boundary",
    # --- gridpipe: the 2D placement plane (parallel/grid.py, PR 16) ------
    # grid.py is a PLACEMENT layer, not a compute regime: it factors the
    # ('trials', 'nodes') mesh, device_puts the pytrees per GRID_RULES
    # and dispatches the unchanged loop to run_consensus (mesh size 1)
    # or run_consensus_sharded — every protocol/fault/observability
    # field is consumed by the delegated regime, which has its own
    # parity row above.  grid.py references exactly the fields that
    # shape PLACEMENT (n_nodes, trials, record, witness*); the rest
    # delegate:
    ("debug", "parallel/grid.py"):
        "grid dispatches to run_consensus / run_consensus_sharded, "
        "which apply the debug demotion themselves",
    ("seed", "parallel/grid.py"):
        "grid places the caller's derived base_key (replicated per "
        "GRID_RULES); jax.random.key(cfg.seed) happens at the harness "
        "boundary like every compiled regime",
    ("max_rounds", "parallel/grid.py"):
        "the round loop and its cap live in the delegated runner "
        "(sim.py / parallel/sharded.py); grid only places inputs",
    ("heartbeat_rounds", "parallel/grid.py"):
        "the heartbeat boundary lives in the delegated runner's slice "
        "loop (sim.run_consensus_slice, sharded._local_slice); "
        "placement happens once, before the first slice",
    ("topology", "parallel/grid.py"):
        "the adjacency gather runs inside the shared round kernel "
        "reached through run_consensus_sharded; topology never "
        "changes array shapes, so placement is indifferent to it",
    ("committee_cap", "parallel/grid.py"):
        "same as topology: committee dispatch is kernel-level in the "
        "delegated regime and shape-invariant for placement",
    ("fault_model", "parallel/grid.py"):
        "FaultSpec arrays are placed by GRID_RULES leaf name (faulty/"
        "crash_round/recover_round); their semantics compile in the "
        "delegated round kernel",
    ("drop_prob", "parallel/grid.py"):
        "omission thinning is kernel-level in the delegated regime; "
        "it reads no additional arrays for grid to place",
    ("partition", "parallel/grid.py"):
        "partition masks derive from global node ids inside the "
        "delegated kernel; nothing partition-specific is placed",
    ("recovery", "parallel/grid.py"):
        "the schedule is realized into FaultSpec.recover_round at the "
        "harness boundary; grid places the realized bounds like any "
        "FaultSpec leaf",
}


def _simconfig_fields(project: Project) -> Set[str]:
    """SimConfig dataclass field names + property names, from the AST of
    config.py (never from an import)."""
    src = project.source(CONFIG_FILE)
    fields: Set[str] = set()
    if src is None:
        return fields
    for node in src.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "SimConfig":
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and \
                        isinstance(item.target, ast.Name):
                    fields.add(item.target.id)
                elif isinstance(item, ast.FunctionDef) and any(
                        isinstance(d, ast.Name) and d.id == "property"
                        for d in item.decorator_list):
                    fields.add(item.name)
    return fields


def _attr_uses(project: Project, rel: str, fields: Set[str],
               receiver: str = None) -> Dict[str, int]:
    """field -> first line where ``<receiver>.<field>`` is read in
    ``rel``; any receiver name when ``receiver`` is None."""
    src = project.source(rel)
    uses: Dict[str, int] = {}
    if src is None:
        return uses
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Attribute) or \
                node.attr not in fields:
            continue
        if receiver is not None and not (
                isinstance(node.value, ast.Name) and
                node.value.id == receiver):
            continue
        if node.attr not in uses or node.lineno < uses[node.attr]:
            uses[node.attr] = node.lineno
    return uses


@rule("config-parity", "config",
      "SimConfig fields consumed in sim.py must reach every regime")
def check_config_parity(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    fields = _simconfig_fields(project)
    if not fields or project.source(SIM_FILE) is None:
        return findings
    consumed = _attr_uses(project, SIM_FILE, fields, receiver="cfg")
    regime_refs = {rel: _attr_uses(project, rel, fields)
                   for rel in REGIME_FILES if project.source(rel)}
    for field in sorted(consumed):
        for rel, refs in regime_refs.items():
            if field in refs:
                continue
            if (field, rel) in PARITY_ALLOWLIST:
                continue
            findings.append(Finding(
                "config-parity", SIM_FILE, consumed[field], 0,
                f"SimConfig.{field} is consumed by the driver (sim.py) "
                f"but never referenced in the {rel} regime — a "
                f"recorder-style feature that silently skips a regime "
                f"still runs and still agrees with itself",
                hint=f"thread the field through {rel}, or add "
                     f"('{field}', '{rel}') to "
                     f"analysis.rules_config.PARITY_ALLOWLIST with the "
                     f"delegation argument"))
    return findings
