"""benorlint — project-native static analysis for the benor_tpu tree.

An AST-based rule framework (visitor core + registry + ``Finding``
objects with file:line anchors and fix hints + ``# benorlint:
allow-<rule>`` pragma suppression) with three rule families, each
grounded in a silent-corruption class THIS repo has already had to
manage by hand.  ``python -m benor_tpu lint`` runs them all (exit 2 on
findings); tests/test_lint.py keeps the shipped tree lint-clean in
tier-1.

Rule families -> the incident each one prevents:

**Tracer hygiene** (rules_tracer.py) — the PR-1 recompile/host-sync
hazard class.  The batched dynamic-F sweep exists because static config
reached compiled code in the wrong places; the flipside is DYNAMIC
values reaching host Python.  ``host-sync`` flags ``.item()`` /
``int()``/``float()`` on tracer params / ``np.asarray`` inside any
function reachable from a jit/pallas_call/shard_map boundary;
``host-rng`` flags ``np.random.*`` (non-reproducible across mesh
shapes — ops/rng.py's fold_in contract); ``traced-branch`` flags Python
``if``/``while`` on jnp expressions; ``dtype-drift`` flags 64-bit
dtypes off state.py's int32 discipline; ``donate-argnums`` flags jit
entrypoints that take donated-size [T, N] buffers undonated;
``rng-fold`` enforces the one-fold_in-chain-per-use key discipline
(never an arithmetic index product); ``broad-except`` flags handlers
that would eat Mosaic lowering failures indistinguishably.

**Kernel column layout** (rules_layout.py) — the PR-2/PR-3 incident.
The flight recorder (PR 2) and the witness traces (PR 3) each appended
hand-numbered partial columns to the fused round kernels' per-tile
reduction buffer (``_RP_* = 5..11``, ``_WITA_BASE = 4`` — bare ints
nothing cross-checked): one off-by-one and two features silently share
a column IN ONE REGIME ONLY.  The constants are now declarative layout
tables (state.REC_LAYOUT / WIT_LAYOUT, ops/pallas_round.py's
PROP/VOTE/RECORD tables + witness field tuples) that kernels and
checker both consume; ``layout-overlap`` proves ranges disjoint and
dense, ``layout-parity`` proves the tables agree across files and fit
PARTIAL_COLS at WITNESS_MAX_NODES, ``layout-outspec`` forbids bare
physical-width literals in out_spec shapes.

**Perf observability** (rules_perf.py) — the ISSUE-5 blind spot.
perfscope made every compiled executable's AOT pipeline and cost model
observable, but only through its funnel (perfscope/instrument.py);
``perf-unregistered-jit`` flags raw ``jax.jit`` / bare
``.lower().compile()`` call sites that would re-open the pre-perfscope
hole (a regime the perf gate cannot see regress), with the pure-literal
``JIT_REGISTRY`` roster as the reviewed exception list — cross-checked
for staleness by the same rule.

**Five-regime config parity** (rules_config.py) — the threading burden
every observability PR paid: a SimConfig field consumed in sim.py had
to be hand-carried through the sweep, fused-round, sharded and
multihost regimes, and a forgotten regime still ran, silently
feature-less.  ``config-parity`` makes the omission a lint failure (or
a reviewed PARITY_ALLOWLIST entry with the delegation argument).

The framework is stdlib-only and reads every table by PARSING source —
linting never imports (or executes) the modules under inspection.
"""

from .cli import LintReport, default_root, run_lint
from .core import Finding, Project, RULES, run_rules, rule

__all__ = ["Finding", "LintReport", "Project", "RULES", "default_root",
           "rule", "run_lint", "run_rules"]
