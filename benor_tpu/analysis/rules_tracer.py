"""Tracer-hygiene rules: host syncs, host RNG, traced branches, dtype
drift, donation, and PRNG-key discipline.

Every rule here is grounded in a hazard this repo actually has to manage
(see analysis/__init__ for the incident map).  The traced-function scope
comes from visitors.build_index: a host sync in harness code is a
completion barrier; the SAME call inside a jit/pallas/shard_map-reachable
function is a per-round device round-trip or a trace error.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from .core import Finding, Project, dotted_name, rule
from .visitors import _canonical

#: jnp-style numpy namespaces whose calls mark a traced (device) value.
_TRACED_NS = ("jnp.", "jax.numpy.", "lax.", "jax.lax.")

#: 64-bit dtype spellings — off the int32 state discipline (state.py):
#: with jax's default x64-disabled config these silently truncate back to
#: 32 bits; with x64 enabled they widen the uint32 plane words and break
#: the state.PACK_LAYOUT bit-plane layout ops/pallas_round.pack_state
#: builds.  Either way: drift.
_WIDE_DTYPES = {"jnp.int64", "jnp.uint64", "jnp.float64",
                "np.int64", "np.uint64", "np.float64",
                "numpy.int64", "numpy.uint64", "numpy.float64"}

#: jax.random samplers (NOT the key combinators fold_in/split/key).
_SAMPLERS = {"uniform", "normal", "bernoulli", "randint", "bits",
             "choice", "permutation", "gamma", "beta", "exponential",
             "categorical"}

#: Parameter names that are donated-size device buffers in this codebase:
#: the [T, N] state pytree and the preallocated telemetry buffers.
_DONATABLE = {"state", "states", "recorder", "witness"}


def _parents(tree: ast.AST) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
    return out


def _canon(project: Project, rel: str, name: str) -> str:
    """Alias-canonical dotted name — ONE resolver (visitors._canonical)
    serves both the reachability analysis and every rule's matching, so
    the two can never disagree about what a name refers to."""
    idx = project.index
    return _canonical(idx.module_of[rel], idx, name)


def _is_np(project: Project, rel: str, name: str) -> bool:
    return _canon(project, rel, name).startswith("numpy.")


def _traced_walk(project: Project):
    """(FuncInfo, node) pairs over every traced function's subtree.

    Each node is yielded ONCE, attributed to its innermost traced
    function (nested defs are visited before their parents, whose walks
    then skip the already-claimed subtree) — so one violation is one
    finding, named after the function that actually contains it."""
    seen = set()
    for info in sorted(project.index.traced,
                       key=lambda f: (f.rel, -f.node.lineno)):
        for node in ast.walk(info.node):
            if id(node) in seen:
                continue
            seen.add(id(node))
            yield info, node


def _guarded_by_isinstance(parents: Dict[int, ast.AST], node: ast.AST,
                           name: str) -> bool:
    """True when an ancestor If/IfExp tests ``isinstance(name, ...)`` —
    the static-vs-traced dispatch idiom (ops/sampling.static_m)."""
    cur = node
    while id(cur) in parents:
        cur = parents[id(cur)]
        if isinstance(cur, (ast.If, ast.IfExp)):
            for sub in ast.walk(cur.test):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name) and \
                        sub.func.id == "isinstance" and sub.args and \
                        isinstance(sub.args[0], ast.Name) and \
                        sub.args[0].id == name:
                    return True
    return False


@rule("host-sync", "tracer",
      "host synchronization inside a traced function")
def check_host_sync(project: Project) -> List[Finding]:
    findings = []
    parent_cache: Dict[str, Dict[int, ast.AST]] = {}
    for info, node in _traced_walk(project):
        if not isinstance(node, ast.Call):
            continue
        # x.item(): the canonical device->host sync
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "item" and not node.args:
            findings.append(Finding(
                "host-sync", info.rel, node.lineno, node.col_offset,
                f".item() inside traced function {info.name!r} forces a "
                f"device->host sync per call",
                hint="keep the value on device (jnp scalar) or fetch it "
                     "once outside the jit boundary"))
            continue
        name = dotted_name(node.func)
        # np.asarray / np.array on a tracer materializes on host
        if name and _is_np(project, info.rel, name) and \
                _canon(project, info.rel, name).split(".")[-1] in \
                ("asarray", "array"):
            findings.append(Finding(
                "host-sync", info.rel, node.lineno, node.col_offset,
                f"np.{name.split('.')[-1]}() inside traced function "
                f"{info.name!r} pulls its operand to the host",
                hint="use jnp.asarray, or pragma when the operand is "
                     "static config-only data that constant-folds"))
            continue
        # int()/float()/bool() on a parameter of the traced function
        if isinstance(node.func, ast.Name) and \
                node.func.id in ("int", "float", "bool") and \
                len(node.args) == 1:
            arg = node.args[0]
            target = arg
            if isinstance(target, ast.Subscript):
                target = target.value
            if isinstance(target, ast.Name) and \
                    target.id in info.params:
                parents = parent_cache.setdefault(
                    info.rel, _parents(project.source(info.rel).tree))
                if _guarded_by_isinstance(parents, node, target.id):
                    continue    # static-vs-traced dispatch idiom
                findings.append(Finding(
                    "host-sync", info.rel, node.lineno, node.col_offset,
                    f"{node.func.id}() on parameter "
                    f"{target.id!r} of traced function {info.name!r} "
                    f"is a concretization sync (TracerConversionError "
                    f"under jit, a blocking fetch otherwise)",
                    hint="thread the value as a traced scalar, or make "
                         "it a static argument"))
    return findings


@rule("host-rng", "tracer",
      "host-side numpy RNG (non-reproducible across mesh shapes)")
def check_host_rng(project: Project) -> List[Finding]:
    findings = []
    for rel, src in project.sources.items():
        for node in ast.walk(src.tree):
            name = dotted_name(node) if isinstance(node, ast.Attribute) \
                else None
            if not name:
                continue
            canon = _canon(project, rel, name)
            if canon.startswith("numpy.random") and \
                    not isinstance(node.ctx, ast.Store):
                findings.append(Finding(
                    "host-rng", rel, node.lineno, node.col_offset,
                    "np.random draws do not key on (seed, round, phase, "
                    "trial, node) and cannot reproduce across mesh "
                    "shapes (ops/rng.py contract)",
                    hint="derive draws from jax.random.fold_in chains, "
                         "or pragma seeded host-side input generation"))
    # one finding per chain: np.random.default_rng yields nested
    # Attribute nodes ("np.random", "np.random.default_rng") that share
    # a start location
    uniq = {}
    for f in findings:
        uniq[(f.path, f.line, f.col)] = f
    return list(uniq.values())


@rule("traced-branch", "tracer",
      "Python control flow on a traced value")
def check_traced_branch(project: Project) -> List[Finding]:
    findings = []
    for info, node in _traced_walk(project):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        offender = None
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Call):
                name = dotted_name(sub.func)
                if name:
                    canon = _canon(project, info.rel, name)
                    if canon.startswith(("jax.numpy.", "jax.lax.")) or \
                            name.startswith(_TRACED_NS):
                        offender = name
                        break
                if isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in ("any", "all") and not sub.args:
                    offender = f".{sub.func.attr}()"
                    break
        if offender is not None:
            kw = "if" if isinstance(node, ast.If) else "while"
            findings.append(Finding(
                "traced-branch", info.rel, node.lineno, node.col_offset,
                f"Python `{kw}` on a traced expression ({offender}) in "
                f"{info.name!r}: under jit this is a ConcretizationError "
                f"(or a silent host sync outside it)",
                hint="use jnp.where / lax.cond / lax.while_loop"))
    return findings


@rule("dtype-drift", "tracer",
      "64-bit dtype off the int32 state discipline")
def check_dtype_drift(project: Project) -> List[Finding]:
    findings = []
    for info, node in _traced_walk(project):
        if not isinstance(node, ast.Attribute):
            continue
        name = dotted_name(node)
        if name in _WIDE_DTYPES:
            findings.append(Finding(
                "dtype-drift", info.rel, node.lineno, node.col_offset,
                f"{name} in traced function {info.name!r}: the state "
                f"discipline is int32 (state.py) — with x64 disabled "
                f"this silently truncates, with it enabled it breaks "
                f"the bit-plane pack layout (state.PACK_LAYOUT / "
                f"ops/pallas_round.pack_state)",
                hint="use an int32/float32 dtype on device; 64-bit "
                     "belongs to host-side summaries only"))
    return findings


@rule("donate-argnums", "tracer",
      "jit entrypoint takes donated-size buffers without donate_argnums")
def check_donate(project: Project) -> List[Finding]:
    findings = []
    idx = project.index
    for rel, src in project.sources.items():
        module = idx.module_of[rel]
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                name = dotted_name(dec.func)
                if not name:
                    continue
                canon = _canon(project, rel, name)
                is_jit = canon.split(".")[-1] == "jit"
                if canon.split(".")[-1] == "partial" and dec.args:
                    inner = dotted_name(dec.args[0])
                    is_jit = bool(inner) and _canon(
                        project, rel, inner).split(".")[-1] == "jit"
                if not is_jit:
                    continue
                kwargs = {k.arg for k in dec.keywords}
                if kwargs & {"donate_argnums", "donate_argnames"}:
                    continue
                info = idx.funcs.get((module, node.name))
                big = [p for p in (info.params if info else ())
                       if p in _DONATABLE]
                if big:
                    findings.append(Finding(
                        "donate-argnums", rel, dec.lineno,
                        dec.col_offset,
                        f"jit entrypoint {node.name!r} takes the "
                        f"donated-size buffer(s) {', '.join(big)} "
                        f"without donate_argnums: input and loop carry "
                        f"stay live together (2x HBM at [T, N] scale)",
                        hint="add donate_argnums/donate_argnames, or "
                             "pragma entrypoints whose operands are "
                             "intentionally re-used by the caller"))
    return findings


@rule("rng-fold", "tracer",
      "PRNG key use off the chained fold_in discipline")
def check_rng_fold(project: Project) -> List[Finding]:
    findings = []
    for rel, src in project.sources.items():
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not name:
                continue
            canon = _canon(project, rel, name)
            if canon.endswith("random.fold_in") and len(node.args) >= 2:
                for sub in ast.walk(node.args[1]):
                    if isinstance(sub, ast.BinOp) and \
                            isinstance(sub.op, ast.Mult):
                        findings.append(Finding(
                            "rng-fold", rel, node.lineno,
                            node.col_offset,
                            "fold_in of an arithmetic index product: "
                            "flat ids like trial*N+node overflow int32 "
                            "at 1M x 1M scale — fold each component in "
                            "its own chained fold_in (ops/rng.py)",
                            hint="fold_in(fold_in(key, trial), node)"))
                        break
    # sampling straight from the run's base_key (no per-round/phase fold)
    for info, node in _traced_walk(project):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        name = dotted_name(node.func)
        if not name:
            continue
        canon = _canon(project, info.rel, name)
        parts = canon.split(".")
        if len(parts) >= 2 and parts[-2] == "random" and \
                parts[-1] in _SAMPLERS:
            key_arg = node.args[0]
            if isinstance(key_arg, ast.Name) and \
                    key_arg.id == "base_key" and \
                    key_arg.id in info.params:
                findings.append(Finding(
                    "rng-fold", info.rel, node.lineno, node.col_offset,
                    f"jax.random.{parts[-1]} drawn directly from the "
                    f"run's base_key in {info.name!r}: every call site "
                    f"shares one stream (ops/rng.py requires exactly "
                    f"one fold_in chain per use)",
                    hint="key on (round, phase, ids) via "
                         "rng.round_key/grid_keys before sampling"))
    return findings


@rule("broad-except", "tracer",
      "broad exception handler (silently eats Mosaic/XLA failures)")
def check_broad_except(project: Project) -> List[Finding]:
    findings = []
    for rel, src in project.sources.items():
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or (
                isinstance(node.type, ast.Name) and
                node.type.id in ("Exception", "BaseException"))
            if broad:
                findings.append(Finding(
                    "broad-except", rel, node.lineno, node.col_offset,
                    "except Exception swallows kernel-lowering and "
                    "backend failures indistinguishably from real "
                    "errors (the demotion-policy bugs of results.py's "
                    "probe history)",
                    hint="catch the specific exception, or pragma "
                         "best-effort boundaries with a justification"))
    return findings
