"""Minimal-repro emission + bit-identical replay (``kind: atlas_repro``).

Every violation or stall the search (or results.py's safety studies)
finds becomes one replayable JSON document: the full frozen SimConfig,
the input/fault POLICY (never raw arrays — both derive from the config
alone, the default_crash_faults discipline), the recorded verdict, and
a canonical digest (atlas/gate.repro_digest, recomputed by the gate and
the manifest checker — an edited repro is detectable offline, stdlib
only).

The emitter SHRINKS before it writes: trials, nodes (with n_faulty
rescaled to preserve F/N — the cliff physics is a ratio) and max_rounds
are halved greedily while the oracle verdict (decided/stalled side +
violation flag) is preserved, so the committed artifact is the smallest
witness of the phenomenon, not a scale-bound snapshot.  Replay
(`replay_repro`, CLI ``python -m benor_tpu replay``) re-runs the exact
config through ``sweep.run_point`` — same seed, same input policy, same
fault mask — and pins the summary bit-identically (Python floats
round-trip through JSON exactly)."""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Optional

from . import gate

#: Record tag of one replayable repro document.  NOT a ``*_manifest``
#: kind: repros are evidence attached to manifests, validated through
#: the digest recompute, not a standalone gated artifact.
REPRO_KIND = "atlas_repro"

#: Shrink floors: below these the phenomenon degenerates into the
#: config validators' territory rather than smaller evidence.
MIN_TRIALS, MIN_NODES, MIN_ROUNDS = 1, 8, 2

#: SimConfig fields that are tuples (JSON round-trips them as lists).
_TUPLE_FIELDS = ("witness_trials", "mesh_shape")


def _cfg_to_doc(cfg) -> Dict:
    d = dataclasses.asdict(cfg)
    for k in _TUPLE_FIELDS:
        if isinstance(d.get(k), tuple):
            d[k] = list(d[k])
    return d


def _cfg_from_doc(doc: Dict):
    from ..config import SimConfig
    d = dict(doc)
    for k in _TUPLE_FIELDS:
        if isinstance(d.get(k), list):
            d[k] = tuple(d[k])
    return SimConfig(**d)


def _inputs_for(cfg, inputs: str):
    from ..sweep import balanced_inputs, random_inputs
    if inputs == "random":
        return random_inputs(cfg.seed, cfg.trials, cfg.n_nodes)
    if inputs == "balanced":
        return balanced_inputs(cfg.trials, cfg.n_nodes)
    if inputs == "ones":
        import numpy as np
        return np.ones((cfg.trials, cfg.n_nodes), np.int8)
    raise ValueError(f"unknown repro input policy {inputs!r} "
                     f"(random | balanced | ones)")


def _faults_for(cfg, faults: str):
    if faults == "none":
        from ..state import FaultSpec
        return FaultSpec.none(cfg.trials, cfg.n_nodes)
    if faults == "default":
        return None               # run_point's first-F-faulty policy
    raise ValueError(f"unknown repro fault policy {faults!r} "
                     f"(none | default)")


def run_verdict(cfg, inputs: str = "random",
                faults: str = "default") -> Dict:
    """One oracle evaluation -> the verdict block a repro records.
    ``verdict`` is the stall/decide side (majority of trials), the
    floats are the exact run_point summaries (bit-identity anchors)."""
    from ..sweep import run_point
    pt = run_point(cfg, initial_values=_inputs_for(cfg, inputs),
                   faults=_faults_for(cfg, faults))
    stall = 1.0 - pt.decided_frac
    return {"verdict": "stalled" if stall >= 0.5 else "decided",
            "rounds_executed": int(pt.rounds_executed),
            "decided_frac": float(pt.decided_frac),
            "mean_k": float(pt.mean_k),
            "disagree_frac": float(pt.disagree_frac),
            "violation": bool(pt.disagree_frac > 0)}


def _preserved(expect: Dict, got: Dict) -> bool:
    """Shrink-acceptance: same stall/decide side + same violation flag
    (the floats legitimately move with scale; the PHENOMENON must not)."""
    return (got["verdict"] == expect["verdict"]
            and got["violation"] == expect["violation"])


def _shrink_candidates(cfg):
    """The next generation of smaller configs, largest reduction first.
    Invalid combinations (a partition that no longer splits, a ring
    degree >= N) are rejected by SimConfig validation and skipped."""
    out = []
    if cfg.trials // 2 >= MIN_TRIALS:
        out.append({"trials": cfg.trials // 2})
    n2 = cfg.n_nodes // 2
    if n2 >= MIN_NODES:
        # preserve the F/N ratio — every cliff in the atlas is a ratio
        out.append({"n_nodes": n2,
                    "n_faulty": max(0, round(cfg.n_faulty * n2
                                             / cfg.n_nodes))})
    if cfg.max_rounds // 2 >= MIN_ROUNDS:
        out.append({"max_rounds": cfg.max_rounds // 2})
    return out


def build_repro(cfg, inputs: str = "random", faults: str = "default",
                label: str = "", shrink: bool = True,
                max_steps: int = 16) -> Dict:
    """Shrink ``cfg`` while its verdict is preserved, then emit the
    replayable document (digest included, verdict re-measured at the
    final size so replay is bit-identical by construction)."""
    expect = run_verdict(cfg, inputs, faults)
    steps = 0
    shrunk_from = {"trials": cfg.trials, "n_nodes": cfg.n_nodes,
                   "max_rounds": cfg.max_rounds}
    while shrink and steps < max_steps:
        for repl in _shrink_candidates(cfg):
            try:
                cand = cfg.replace(**repl)
            except ValueError:
                continue
            got = run_verdict(cand, inputs, faults)
            if _preserved(expect, got):
                cfg, expect, steps = cand, got, steps + 1
                break
        else:
            break
    doc = {"kind": REPRO_KIND, "schema_version": gate.SCHEMA_VERSION,
           "label": str(label), "config": _cfg_to_doc(cfg),
           "inputs": inputs, "faults": faults, "verdict": expect,
           "shrunk_from": shrunk_from, "shrink_steps": steps}
    doc["digest"] = gate.repro_digest(doc)
    return doc


def save_repro(path: str, doc: Dict) -> None:
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)


def load_repro(path: str) -> Dict:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("kind") != REPRO_KIND:
        raise ValueError(
            f"{os.path.basename(path)}: not an atlas_repro document "
            f"(kind={doc.get('kind')!r})")
    return doc


def replay_repro(doc: Dict) -> Dict:
    """Re-execute a repro document and pin it bit-identically.

    ``ok`` requires the digest to recompute (the document is what the
    emitter wrote) AND the fresh summary to equal the recorded one
    exactly — rounds, decided/mean_k/disagree floats, verdict side."""
    digest_ok = gate.repro_digest(doc) == doc.get("digest")
    cfg = _cfg_from_doc(doc["config"])
    fresh = run_verdict(cfg, doc["inputs"], doc["faults"])
    expect = doc["verdict"]
    bit_identical = all(fresh[k] == expect.get(k) for k in fresh)
    return {"ok": bool(digest_ok and bit_identical),
            "digest_ok": digest_ok, "bit_identical": bit_identical,
            "verdict": fresh, "expected": expect}
