"""Cliff-drift gate: compare two atlas manifests, stdlib-only.

The comparison half of the atlas plane, kept import-free of jax/numpy
(and of the rest of the package — ``tools/check_atlas_regression.py``
loads this file BY PATH, so it must be self-contained) so CI can gate a
committed ``ATLAS_BASELINE.json`` without a backend.

What regresses (findings -> exit 2 in the tools gate):

  * a baseline cliff VANISHES — the fresh capture's matching search has
    no cliff on that axis anywhere near it;
  * a baseline cliff MOVES outside its bracket band — the fresh point
    estimate leaves ``[lo - band*width, hi + band*width]`` of the
    committed bracketing interval (band :data:`CLIFF_BAND`; physics
    drift, an evaluator bug, or a decode-rule change all land here);
  * a committed repro STOPS REPRODUCING — the fresh capture replayed
    the cliff's minimal repro and its verdict came back different
    (``repro_reproduced: false``), or a repro document's digest no
    longer matches its canonical payload (tampering / drift);
  * a whole baseline search has no counterpart in the fresh manifest.

What does NOT regress: extra cliffs or searches in the fresh manifest
(discovery is the point), probe-count changes, compile-count changes —
those are schema/cross-field territory
(``check_metrics_schema.check_atlas_manifest``), not drift.

Incomparable (exit 3): platform / device kind / scale mismatch — a CPU
smoke baseline says nothing about TPU cliff locations.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional

#: Manifest schema version — bumped with any shape change; part of the
#: comparability check so an old-shape baseline is incomparable, not
#: misread.
SCHEMA_VERSION = 1

#: Allowed point-estimate drift, in units of the BASELINE bracket
#: width, beyond each bracket end: the fresh estimate must land inside
#: ``[lo - band*width, hi + band*width]``.  1.0 tolerates one full
#: bracket of sampling wobble; a cliff that moved further has changed
#: regime.
CLIFF_BAND = 1.0

#: The repro-digest payload fields, in canonical order.  The digest is
#: sha256 over the sorted-key JSON of exactly these fields — shared
#: verbatim by atlas/repro.py (emission), this gate and
#: check_metrics_schema.check_atlas_manifest (recompute-don't-trust).
REPRO_DIGEST_FIELDS = ("config", "faults", "inputs", "label", "verdict")


def repro_digest(doc: Dict) -> str:
    """The canonical digest of one ``kind: atlas_repro`` document."""
    payload = {k: doc.get(k) for k in REPRO_DIGEST_FIELDS}
    return "sha256:" + hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()


class IncomparableAtlas(Exception):
    """Baseline and manifest describe different machines/scales — the
    gate must refuse (exit 3), not vacuously pass."""


@dataclasses.dataclass
class AtlasFinding:
    """One gate regression: which cliff, what drifted."""

    metric: str
    message: str

    def to_dict(self) -> Dict:
        return {"metric": self.metric, "message": self.message}


def _require(doc: Dict, name: str) -> None:
    if doc.get("kind") != "atlas_manifest":
        raise IncomparableAtlas(
            f"{name} is not an atlas manifest (kind="
            f"{doc.get('kind')!r})")
    if doc.get("schema_version") != SCHEMA_VERSION:
        raise IncomparableAtlas(
            f"{name} schema_version {doc.get('schema_version')!r} != "
            f"{SCHEMA_VERSION} (recapture, do not hand-edit)")


def _search_key(search: Dict) -> str:
    return str(search.get("spec"))


def _nearest_cliff(cliffs: List[Dict], point: float) -> Optional[Dict]:
    best, best_d = None, None
    for c in cliffs:
        try:
            d = abs(float(c["point"]) - point)
        except (KeyError, TypeError, ValueError):
            continue
        if best_d is None or d < best_d:
            best, best_d = c, d
    return best


def compare_atlas(manifest: Dict, baseline: Dict,
                  band: float = CLIFF_BAND) -> List[AtlasFinding]:
    """Findings list (empty = in-band) or IncomparableAtlas."""
    _require(manifest, "manifest")
    _require(baseline, "baseline")
    for field in ("platform", "device_kind"):
        if manifest.get(field) != baseline.get(field):
            raise IncomparableAtlas(
                f"{field} mismatch: manifest "
                f"{manifest.get(field)!r} vs baseline "
                f"{baseline.get(field)!r} — cliff locations are "
                f"machine-conditioned; recapture the baseline instead")
    if manifest.get("scale") != baseline.get("scale"):
        raise IncomparableAtlas(
            f"scale mismatch: manifest {manifest.get('scale')!r} vs "
            f"baseline {baseline.get('scale')!r} — cliffs move with "
            f"(N, trials, rounds); recapture the baseline instead")

    findings: List[AtlasFinding] = []
    fresh = {_search_key(s): s for s in manifest.get("searches", [])}
    for bs in baseline.get("searches", []):
        key = _search_key(bs)
        ms = fresh.get(key)
        if ms is None:
            findings.append(AtlasFinding(
                f"search[{key}]",
                f"baseline search {key!r} has no counterpart in the "
                f"fresh manifest — its cliffs are unverifiable"))
            continue
        mcliffs = ms.get("cliffs", [])
        for bc in bs.get("cliffs", []):
            lo, hi = float(bc["lo"]), float(bc["hi"])
            width = max(hi - lo, 1e-12)
            label = f"cliff[{key} @ {bc.get('point')}]"
            mc = _nearest_cliff(mcliffs, float(bc["point"]))
            in_band = (mc is not None and
                       lo - band * width <= float(mc["point"])
                       <= hi + band * width)
            if mc is None or not in_band:
                where = ("no cliff found at all" if mc is None else
                         f"nearest fresh point estimate {mc['point']} "
                         f"is outside [{lo - band * width:.6g}, "
                         f"{hi + band * width:.6g}]")
                verb = "vanished" if mc is None else "moved"
                findings.append(AtlasFinding(
                    label,
                    f"baseline cliff at {bc['point']} (bracket "
                    f"[{lo}, {hi}]) {verb}: {where}"))
                continue
            # the matched fresh cliff must still reproduce its repro
            if mc.get("repro") is not None:
                if repro_digest(mc["repro"]) != mc["repro"].get("digest"):
                    findings.append(AtlasFinding(
                        label,
                        "fresh cliff's repro digest does not match its "
                        "canonical payload — the repro was edited or "
                        "the emitter drifted"))
                if mc.get("repro_reproduced") is False:
                    findings.append(AtlasFinding(
                        label,
                        "the cliff's minimal repro no longer reproduces "
                        "its recorded verdict — the committed evidence "
                        "is stale"))
            elif bc.get("repro") is not None:
                findings.append(AtlasFinding(
                    label,
                    "baseline cliff carries a repro but the fresh "
                    "capture emitted none — forensics regressed"))
    return findings
