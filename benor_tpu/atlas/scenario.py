"""Declarative scenario axes: the search grammar over the swept knobs.

A :class:`ScenarioAxis` names ONE existing swept knob, a closed value
range and a pinned refinement tolerance; ``apply(cfg, value)`` realizes
a probe as a plain :class:`~benor_tpu.config.SimConfig` — the axis
never invents delivery semantics, it only drives the knobs faultlab /
topo / the committee plane already validate.  The spec grammar is one
colon-separated string (the recovery/partition/topology spec
discipline):

    ``<name>:<lo>:<hi>[:<tol>]``

with ``<name>`` one of:

  ``drop_prob``        per-edge omission probability (traced DynParams
                       axis: a whole generation is ONE dyn bucket)
  ``f``                protocol fault parameter F (DynParams axis: one
                       dyn bucket per generation on delivery='all')
  ``heal_round``       ``partition='halves:<v>'`` heal epoch (static
                       spec: one bucket per distinct probe value)
  ``recovery_down``    ``recovery='at:2:<v>'`` down-interval length
                       under ``fault_model='crash_recover'`` (static)
  ``topology_degree``  ``topology='ring:<v>'`` circulant degree (even;
                       static — tol snaps to 2)
  ``committee_size``   per-round sampled committee size (DynParams axis
                       when the committee plane is armed via
                       ``committee_cap`` on the base config)

Integer axes bisect on the integer lattice (tol >= 1); continuous axes
bisect to the pinned tolerance.  ``faults`` names the fault policy the
evaluator builds per probe: ``'none'`` (all lanes alive — the omission
/ partition regimes, where quorum slack is the physics) or
``'default'`` (run_point's first-F-faulty policy, schedule-aware under
crash_recover).

Import-light by design (config imported lazily in ``apply``): the
stdlib halves of the atlas plane — the gate, the tools checker, the
watch renderer — reason about axis specs without a backend.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

#: knob name -> (integer lattice?, default tolerance, snap step,
#: fault policy).  The single registry the parser, the evaluator and
#: the manifest checker share.
AXIS_KINDS = {
    "drop_prob": {"integer": False, "tol": 0.02, "step": 0.0,
                  "faults": "none"},
    "f": {"integer": True, "tol": 1.0, "step": 1.0, "faults": "default"},
    "heal_round": {"integer": True, "tol": 1.0, "step": 1.0,
                   "faults": "none"},
    "recovery_down": {"integer": True, "tol": 1.0, "step": 1.0,
                      "faults": "default"},
    "topology_degree": {"integer": True, "tol": 2.0, "step": 2.0,
                        "faults": "default"},
    "committee_size": {"integer": True, "tol": 1.0, "step": 1.0,
                       "faults": "default"},
}


@dataclasses.dataclass(frozen=True)
class ScenarioAxis:
    """One search dimension: a knob, a range, a pinned tolerance."""

    name: str
    lo: float
    hi: float
    tol: float
    integer: bool
    step: float     # integer-lattice stride (2 for even-degree rings)
    faults: str     # 'none' | 'default' — the evaluator's fault policy
    spec: str       # the grammar string this axis parsed from

    def snap(self, value: float) -> float:
        """Clamp + project a raw value onto the axis lattice."""
        v = min(max(float(value), self.lo), self.hi)
        if self.step:
            v = self.step * round(v / self.step)
            v = min(max(v, self.lo), self.hi)
        return float(v)

    def grid(self, coarse: int) -> List[float]:
        """``coarse + 1`` evenly spaced snapped values, lo..hi inclusive,
        deduplicated in order (integer lattices collapse close points)."""
        if coarse < 1:
            raise ValueError("coarse grid needs >= 1 interval")
        raw = [self.lo + (self.hi - self.lo) * i / coarse
               for i in range(coarse + 1)]
        out: List[float] = []
        for v in (self.snap(r) for r in raw):
            if not out or v != out[-1]:
                out.append(v)
        return out

    def converged(self, lo: float, hi: float) -> bool:
        """True when a bracket is at the pinned tolerance (a tiny eps
        absorbs float drift from repeated midpoint halving)."""
        return (hi - lo) <= self.tol * (1 + 1e-9)

    def midpoint(self, lo: float, hi: float) -> Optional[float]:
        """The snapped bisection probe inside (lo, hi), or None when the
        bracket is converged / the lattice has no interior point."""
        if self.converged(lo, hi):
            return None
        mid = self.snap((lo + hi) / 2.0)
        if mid <= lo or mid >= hi:
            return None
        return mid

    def apply(self, cfg, value: float):
        """Realize one probe: base config + this axis at ``value``.
        Raises the underlying SimConfig validation error verbatim on an
        incoherent combination (fail-loudly, the spec-grammar contract).
        """
        v = self.snap(value)
        i = int(round(v))
        if self.name == "drop_prob":
            return cfg.replace(drop_prob=v)
        if self.name == "f":
            return cfg.replace(n_faulty=i)
        if self.name == "heal_round":
            return cfg.replace(partition=f"halves:{i}")
        if self.name == "recovery_down":
            return cfg.replace(fault_model="crash_recover",
                               recovery=f"at:2:{i}")
        if self.name == "topology_degree":
            return cfg.replace(topology=f"ring:{i}")
        if self.name == "committee_size":
            if not cfg.committee_cap:
                raise ValueError(
                    "committee_size axis needs a base config with the "
                    "committee plane armed (committee_cap > 0)")
            return cfg.replace(committee_size=i)
        raise ValueError(f"unknown scenario axis {self.name!r}")

    def to_dict(self) -> dict:
        return {"name": self.name, "lo": self.lo, "hi": self.hi,
                "tol": self.tol, "integer": self.integer,
                "spec": self.spec}


def parse_axis(spec: str) -> ScenarioAxis:
    """``'<name>:<lo>:<hi>[:<tol>]'`` -> a validated ScenarioAxis."""
    parts = str(spec).split(":")
    if len(parts) not in (3, 4):
        raise ValueError(
            f"scenario axis spec {spec!r}: grammar is "
            f"'<name>:<lo>:<hi>[:<tol>]' with <name> one of "
            f"{sorted(AXIS_KINDS)}")
    name = parts[0]
    if name not in AXIS_KINDS:
        raise ValueError(
            f"unknown scenario axis {name!r}; known axes: "
            f"{sorted(AXIS_KINDS)}")
    kind = AXIS_KINDS[name]
    try:
        lo, hi = float(parts[1]), float(parts[2])
        tol = float(parts[3]) if len(parts) == 4 else float(kind["tol"])
    except ValueError:
        raise ValueError(
            f"scenario axis spec {spec!r}: <lo>/<hi>/<tol> must be "
            f"numbers") from None
    if not lo < hi:
        raise ValueError(f"scenario axis spec {spec!r}: need lo < hi")
    if tol <= 0:
        raise ValueError(f"scenario axis spec {spec!r}: tol must be > 0")
    if kind["integer"]:
        if lo != int(lo) or hi != int(hi):
            raise ValueError(
                f"scenario axis spec {spec!r}: {name} is an integer "
                f"axis; lo/hi must be integers")
        tol = max(tol, float(kind["tol"]))
    return ScenarioAxis(name=name, lo=lo, hi=hi, tol=tol,
                        integer=bool(kind["integer"]),
                        step=float(kind["step"]),
                        faults=str(kind["faults"]), spec=str(spec))
